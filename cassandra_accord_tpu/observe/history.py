"""Client-visible operation history — the record the independent oracle reads.

Every burn can record what an external client of the system saw, and ONLY
that: for each operation an ``invoke`` event (sim-time, the keys it asked to
read, the values it asked to append) and a terminal event —

- ``ok``           the client received a result: observed per-key version
                   lists + its writes acknowledged (acked and probe-recovered
                   ops both land here: the client learned the outcome),
- ``invalidated``  durably nacked: the writes must NEVER surface,
- ``info``         outcome unknown (lost/truncated): writes MAY have applied,
- ``fail``         the op definitely did not run.

This is exactly the event vocabulary of Jepsen's Elle checker
(invoke / ok / fail / info), deliberately containing ZERO protocol
bookkeeping — no TxnId ordering, no deps, no ballots — so the checker in
``observe/checker.py`` constitutes a second opinion that cannot inherit a
protocol bug.  (It still stores each op's txn id opaquely, solely so anomaly
reports can pull flight-recorder timelines for the implicated txns.)

ZERO OBSERVER EFFECT (the package invariant): the recorder is a passive
sink fed values the harness already computed.  It never touches an RNG, the
scheduler, or the wall clock — proven in-tree by the same-seed trace-diff
test in tests/test_history_checker.py.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

#: terminal-event mapping from the burn harness's resolution kinds
_OUTCOMES = {"ok": "ok", "recovered": "ok", "nacked": "invalidated",
             "lost": "info", "failed": "fail"}


def _as_values(v) -> tuple:
    """Normalize a per-key write to a tuple of appended values (a txn may
    append more than one value to a key — the maelstrom workload does)."""
    if isinstance(v, tuple):
        return v
    if isinstance(v, list):
        return tuple(v)
    return (v,)


class HistoryOp:
    """One client operation: invocation + (eventual) terminal event."""

    __slots__ = ("op_id", "txn_id", "invoke_us", "read_keys", "complete_us",
                 "outcome", "reads", "writes")

    def __init__(self, op_id, txn_id, invoke_us: int,
                 read_keys: Tuple = (), writes: Optional[Dict] = None):
        self.op_id = op_id
        self.txn_id = txn_id
        self.invoke_us = invoke_us
        self.read_keys = tuple(read_keys)
        # intended writes, normalized to key -> (value, ...) append tuples
        self.writes: Dict[object, tuple] = \
            {k: _as_values(v) for k, v in (writes or {}).items()}
        self.complete_us: Optional[int] = None
        self.outcome: Optional[str] = None   # ok|invalidated|info|fail|None
        self.reads: Dict[object, tuple] = {}  # observed per-key version lists

    def to_record(self) -> dict:
        """JSON-safe rendering for anomaly reports / artifacts."""
        return {
            "op_id": self.op_id,
            "txn_id": str(self.txn_id),
            "invoke_us": self.invoke_us,
            "complete_us": self.complete_us,
            "outcome": self.outcome or "open",
            "reads": {str(k): list(v) for k, v in sorted(
                self.reads.items(), key=lambda kv: str(kv[0]))},
            "writes": {str(k): list(v) for k, v in sorted(
                self.writes.items(), key=lambda kv: str(kv[0]))},
        }

    def __repr__(self):
        return (f"HistoryOp({self.op_id}, {self.outcome or 'open'}, "
                f"[{self.invoke_us}..{self.complete_us}], "
                f"r={sorted(map(str, self.reads))}, "
                f"w={sorted(map(str, self.writes))})")


class HistoryRecorder:
    """Accumulates the client-visible history of one burn."""

    def __init__(self):
        self.ops: List[HistoryOp] = []
        self._by_id: Dict[object, HistoryOp] = {}

    def invoke(self, op_id, txn_id, now_us: int, read_keys=(),
               writes: Optional[Dict] = None) -> HistoryOp:
        op = HistoryOp(op_id, txn_id, now_us, read_keys, writes)
        self.ops.append(op)
        self._by_id[op_id] = op
        return op

    def resolve(self, op_id, kind: str, now_us: int,
                reads: Optional[Dict] = None,
                writes: Optional[Dict] = None) -> None:
        """Terminal event for ``op_id``; ``kind`` is the harness resolution
        kind (ok/recovered/nacked/lost/failed)."""
        op = self._by_id.get(op_id)
        if op is None:   # never invoked (harness bug) — don't mask it here
            return
        op.complete_us = now_us
        op.outcome = _OUTCOMES.get(kind, "info")
        if reads:
            op.reads = {k: tuple(v) for k, v in reads.items()}
        if writes:
            # the acked write set can be narrower than intended (it never is
            # in our harness, but the record must reflect what was ACKED)
            op.writes = {k: _as_values(v) for k, v in writes.items()}

    def to_records(self) -> List[dict]:
        return [op.to_record() for op in self.ops]

    def __len__(self):
        return len(self.ops)
