"""Causal provenance: the per-run event DAG behind divergence forensics.

``harness/trace.py`` answers *what* the simulation did — a flat,
byte-comparable list of message-plane events.  This module answers *why*:
every recorded event carries up to two parent edges,

- an **execution parent** (``p1``): the activity — handler, reply callback,
  timer fire, reply timeout — that was running when the event was emitted,
  and for a timer fire, the activity that *armed* it;
- a **message parent** (``p2``): the previous event of the same ``msg_id``
  (a RECV's parent is its send; a reply's parent is the request delivery it
  answers), i.e. wire causality.

Together these form a DAG over a strict superset of the message trace:
handler executions, timer fires, reply callbacks/timeouts, save-status
transitions and crash/restart injections are first-class events too, which
is exactly what makes the forensics *causal* — the origin of a divergence
(a crash that dropped no packet, a timer that fired late) is often invisible
in the byte trace and only exists here.

Zero observer effect: the recorder is a pure side table.  It never touches
RNG, wall clock, or the event loop; the message trace's event tuples are
byte-identical with provenance on vs off (``tests/test_provenance.py``
proves it the PR-3 way, same-seed hostile burn + ``diff_traces``).  Message
events additionally keep their trace sequence number — ``seq_to_pid`` is the
side table keyed by trace seq the rest of the tree joins against.

On top of the DAG:

- :func:`explain_divergence` aligns two same-seed runs' DAGs, names the
  earliest *causally*-divergent event (over the full event superset, not
  merely the first differing trace byte) and walks its ancestor cone back
  to the last shared decision;
- :meth:`ProvenanceRecorder.slice_for` renders a bounded k-hop backward
  slice from a transaction's latest transition — the forensic attachment
  ``AuditViolation``, history-checker anomalies and watchdog stall dumps
  embed.
"""
from __future__ import annotations

import json
from typing import Optional

from ..harness.trace import _brief

# event tuple layout (plain tuples: millions of these exist in long burns)
E_PID, E_KIND, E_US, E_P1, E_P2, E_NAME, E_FRM, E_TO, E_MSG, E_DETAIL = \
    range(10)

# kinds
K_MSG = "msg"              # one message-plane trace event (carries trace seq)
K_HANDLER = "handler"      # Node._process_or_fail executing one request
K_CALLBACK = "callback"    # a reply callback firing (SimMessageSink)
K_TIMEOUT = "timeout"      # a reply timeout firing (SimMessageSink)
K_TIMER = "timer"          # a NodeScheduler timer firing
K_TRANSITION = "transition"  # a save-status transition (_observe_transition)
K_CRASH = "crash"          # nemesis/perturbation fault-in
K_RESTART = "restart"

_RECV_EVENTS = ("RECV", "RECV_RPLY")


def _describe(ev) -> str:
    """One-line human rendering of an event tuple."""
    kind = ev[E_KIND]
    if kind == K_MSG:
        return (f"{ev[E_NAME]} {ev[E_FRM]}->{ev[E_TO]} "
                f"#{ev[E_MSG]} {ev[E_DETAIL]}")
    if kind == K_HANDLER:
        return f"handler {ev[E_NAME]}({ev[E_DETAIL]}) @node{ev[E_TO]}"
    if kind == K_CALLBACK:
        return f"reply-callback #{ev[E_MSG]} @node{ev[E_TO]}"
    if kind == K_TIMEOUT:
        return f"reply-timeout #{ev[E_MSG]} @node{ev[E_TO]}"
    if kind == K_TIMER:
        return f"timer @node{ev[E_TO]}"
    if kind == K_TRANSITION:
        return (f"{ev[E_DETAIL]} -> {ev[E_NAME]} "
                f"@node{ev[E_TO]}/store{ev[E_FRM]}")
    return f"{kind} node{ev[E_TO]}"   # crash / restart


def describe_event(ev) -> dict:
    """JSON-ready rendering of one event (slice/report element)."""
    return {"pid": ev[E_PID], "kind": ev[E_KIND], "sim_us": ev[E_US],
            "parents": [p for p in (ev[E_P1], ev[E_P2]) if p is not None],
            "what": _describe(ev)}


def _content_key(ev):
    """Alignment key: everything positional (pid, parents, msg_id — global
    allocation order) excluded, so two runs' events compare by *what
    happened when*, not by bookkeeping ids."""
    return (ev[E_KIND], ev[E_US], ev[E_NAME], ev[E_FRM], ev[E_TO],
            ev[E_DETAIL])


class ProvenanceRecorder:
    """The per-run causal DAG side table.

    Rides a ``FlightRecorder`` as the ``provenance=`` attachment (like
    ``timeline``/``burnrate``); the cluster and node brackets feed the
    execution-context stack, the message hooks feed the wire chains.
    """

    def __init__(self):
        self.events: list = []           # event tuples, pid == index
        self.seq_to_pid: list = []       # trace seq -> pid (the side table)
        self._ctx: list = []             # execution-context stack of pids
        # only an IMMEDIATELY-following handler/callback bracket may claim a
        # delivery as its cause; any interleaved event clears it
        self._pending_recv: Optional[int] = None
        self._msg_chain: dict = {}       # msg_id -> pid of its latest event
        self._last_txn_event: dict = {}  # str(txn_id) -> pid
        self._last_transition: dict = {} # (node, store, str(txn_id)) -> pid

    # -- recording ------------------------------------------------------------
    def _add(self, kind, now_us, p1, p2, name, frm, to, msg_id, detail) -> int:
        pid = len(self.events)
        self.events.append((pid, kind, now_us, p1, p2, name, frm, to,
                            msg_id, detail))
        return pid

    def current(self) -> Optional[int]:
        """The pid of the innermost running activity (timer-arm capture)."""
        return self._ctx[-1] if self._ctx else None

    def on_message_event(self, event: str, frm: int, to: int, msg_id,
                         message, now_us: int) -> None:
        p1 = self._ctx[-1] if self._ctx else None
        p2 = self._msg_chain.get(msg_id)
        pid = self._add(K_MSG, now_us, p1, p2, event, frm, to, msg_id,
                        _brief(message))
        self.seq_to_pid.append(pid)
        if msg_id is not None:
            self._msg_chain[msg_id] = pid
        self._pending_recv = pid if event in _RECV_EVENTS else None
        txn = getattr(message, "txn_id", None)
        if txn is not None:
            self._last_txn_event[str(txn)] = pid

    def begin_handler(self, node: int, request_type: str, txn_id,
                      now_us: int) -> None:
        p2 = self._pending_recv
        self._pending_recv = None
        p1 = self._ctx[-1] if self._ctx else None
        detail = str(txn_id) if txn_id is not None else ""
        pid = self._add(K_HANDLER, now_us, p1, p2, request_type, None, node,
                        None, detail)
        if txn_id is not None:
            self._last_txn_event[detail] = pid
        self._ctx.append(pid)

    def begin_callback(self, node: int, msg_id, txn_id, now_us: int) -> None:
        p2 = self._pending_recv
        self._pending_recv = None
        if p2 is None:
            p2 = self._msg_chain.get(msg_id)
        p1 = self._ctx[-1] if self._ctx else None
        pid = self._add(K_CALLBACK, now_us, p1, p2, "callback", None, node,
                        msg_id, str(txn_id) if txn_id is not None else "")
        self._ctx.append(pid)

    def begin_timeout(self, node: int, msg_id, txn_id, now_us: int) -> None:
        self._pending_recv = None
        p2 = self._msg_chain.get(msg_id)
        pid = self._add(K_TIMEOUT, now_us, None, p2, "timeout", None, node,
                        msg_id, str(txn_id) if txn_id is not None else "")
        self._ctx.append(pid)

    def begin_timer(self, node: int, armed_by: Optional[int],
                    now_us: int) -> None:
        self._pending_recv = None
        pid = self._add(K_TIMER, now_us, armed_by, None, "timer", None, node,
                        None, "")
        self._ctx.append(pid)

    def end(self) -> None:
        """Close the innermost bracket (handler/callback/timeout/timer)."""
        if self._ctx:
            self._ctx.pop()
        self._pending_recv = None

    def on_transition(self, node: int, store: int, txn_id, status_name: str,
                      now_us: int) -> None:
        p1 = self._ctx[-1] if self._ctx else None
        key = str(txn_id)
        pid = self._add(K_TRANSITION, now_us, p1, None, status_name, store,
                        node, None, key)
        self._last_txn_event[key] = pid
        self._last_transition[(node, store, key)] = pid

    def on_crash(self, node_id: int, now_us: int) -> None:
        p1 = self._ctx[-1] if self._ctx else None
        self._pending_recv = None
        self._add(K_CRASH, now_us, p1, None, K_CRASH, None, node_id, None, "")

    def on_restart(self, node_id: int, now_us: int) -> None:
        p1 = self._ctx[-1] if self._ctx else None
        self._pending_recv = None
        self._add(K_RESTART, now_us, p1, None, K_RESTART, None, node_id,
                  None, "")

    # -- queries --------------------------------------------------------------
    def ancestors(self, pid: int, hops: int = 8) -> list:
        """Pids of the bounded backward cone of ``pid`` (k-hop BFS over both
        parent kinds), sorted ascending; includes ``pid`` itself."""
        seen = {pid}
        frontier = [pid]
        for _ in range(hops):
            nxt = []
            for p in frontier:
                ev = self.events[p]
                for parent in (ev[E_P1], ev[E_P2]):
                    if parent is not None and parent not in seen:
                        seen.add(parent)
                        nxt.append(parent)
            if not nxt:
                break
            frontier = nxt
        return sorted(seen)

    def anchor_for(self, txn_id=None, node=None, store=None) -> Optional[int]:
        """The pid forensics should slice backward from: the txn's latest
        transition at (node, store) if known, else its latest transition
        anywhere, else its latest event of any kind."""
        key = str(txn_id) if txn_id is not None else None
        if key is not None and node is not None and store is not None:
            pid = self._last_transition.get((node, store, key))
            if pid is not None:
                return pid
        if key is not None:
            best = None
            for (_n, _s, k), pid in self._last_transition.items():
                if k == key and (best is None or pid > best):
                    best = pid
            if best is not None:
                return best
            return self._last_txn_event.get(key)
        return len(self.events) - 1 if self.events else None

    def slice_for(self, txn_id=None, node=None, store=None,
                  hops: int = 8) -> Optional[dict]:
        """The bounded k-hop backward causal slice embedded in violation
        reports and stall dumps: the anchor event (the bad transition) plus
        its ancestor cone, each sim-timestamped and rendered."""
        anchor = self.anchor_for(txn_id=txn_id, node=node, store=store)
        if anchor is None:
            return None
        cone = self.ancestors(anchor, hops=hops)
        return {"anchor_pid": anchor, "hops": hops,
                "events": [describe_event(self.events[p]) for p in cone]}

    def tail_summary(self, limit: int = 12) -> dict:
        """The recorder's recent tail (stall dumps when no txn is singled
        out): the last ``limit`` events, rendered."""
        tail = self.events[-limit:]
        return {"events_total": len(self.events),
                "tail": [describe_event(ev) for ev in tail]}

    # -- serialization ("--provenance" artifact / "--explain-vs" input) -------
    def to_doc(self) -> dict:
        return {"version": 1, "events": [list(ev) for ev in self.events]}

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_doc(), f, separators=(",", ":"))

    @staticmethod
    def load(path: str) -> dict:
        with open(path) as f:
            doc = json.load(f)
        if doc.get("version") != 1 or "events" not in doc:
            raise ValueError(f"{path}: not a provenance dump")
        return doc


def _event_list(run) -> list:
    """Accept a ProvenanceRecorder or a loaded dump doc."""
    if isinstance(run, ProvenanceRecorder):
        return run.events
    return run["events"]


def _cone(events: list, pid: int, hops: int) -> list:
    seen = {pid}
    frontier = [pid]
    for _ in range(hops):
        nxt = []
        for p in frontier:
            ev = events[p]
            for parent in (ev[E_P1], ev[E_P2]):
                if parent is not None and parent not in seen:
                    seen.add(parent)
                    nxt.append(parent)
        if not nxt:
            break
        frontier = nxt
    return sorted(seen)


def explain_divergence(a, b, hops: int = 10) -> Optional[dict]:
    """Align two same-seed runs' causal DAGs and explain their divergence.

    Deterministic runs share a byte-identical prefix, so alignment is by
    position over the *full* causal event stream (messages AND handlers,
    timers, transitions, crash/restart injections), comparing content keys
    that exclude bookkeeping ids.  The first index where the keys differ is
    the **causally first** divergent event — it can precede the first
    differing message-trace byte by a long way (an injected crash that
    dropped no packet, a delayed timer) because those causes never appear
    on the wire.

    Returns ``None`` when the runs are causally identical, else a report:

    - ``event_a``/``event_b``: the divergent pair (either side ``None`` if
      that run simply ended);
    - ``first_message_divergence``: the first differing *message* event and
      its trace seq — the byte-level symptom, for contrast;
    - ``cone``: the divergent event's bounded ancestor cone, each member
      marked ``shared`` (still in the common prefix — the causal run-up)
      or ``divergent`` (post-fork consequence);
    - ``origin``: the nearest shared ancestor — the last decision both runs
      agreed on before the trajectories forked;
    - ``text``: the human-readable rendering.
    """
    ea, eb = _event_list(a), _event_list(b)
    n = min(len(ea), len(eb))
    idx = None
    for i in range(n):
        if _content_key(ea[i]) != _content_key(eb[i]):
            idx = i
            break
    if idx is None:
        if len(ea) == len(eb):
            return None
        idx = n   # one run is a strict prefix of the other

    event_a = ea[idx] if idx < len(ea) else None
    event_b = eb[idx] if idx < len(eb) else None

    # first differing MESSAGE event (the byte-plane symptom): positional
    # over each run's msg-kind subsequence, i.e. trace-seq alignment
    ma = [ev for ev in ea if ev[E_KIND] == K_MSG]
    mb = [ev for ev in eb if ev[E_KIND] == K_MSG]
    first_msg = None
    for j in range(min(len(ma), len(mb))):
        if _content_key(ma[j]) != _content_key(mb[j]):
            first_msg = {"seq": j,
                         "event_a": describe_event(ma[j]),
                         "event_b": describe_event(mb[j])}
            break
    if first_msg is None and len(ma) != len(mb):
        j = min(len(ma), len(mb))
        longer = ma if len(ma) > len(mb) else mb
        side = "event_a" if len(ma) > len(mb) else "event_b"
        first_msg = {"seq": j, side: describe_event(longer[j])}

    # the divergent event's ancestor cone, walked in the run that HAS it
    cone_events, cone_run = (eb, "b") if event_b is not None else (ea, "a")
    divergent = event_b if event_b is not None else event_a
    cone = []
    origin = None
    if divergent is not None:
        for p in _cone(cone_events, divergent[E_PID], hops):
            d = describe_event(cone_events[p])
            d["shared"] = p < idx     # prefix events exist in both runs
            if d["shared"] and (origin is None or p > origin["pid"]):
                origin = d
            cone.append(d)

    lines = [f"causal divergence at event {idx}"
             + (f" (sim {divergent[E_US]}us)" if divergent is not None
                else "")]
    lines.append(f"  run a: "
                 + (_describe(event_a) if event_a is not None else "<ended>"))
    lines.append(f"  run b: "
                 + (_describe(event_b) if event_b is not None else "<ended>"))
    if first_msg is not None:
        lines.append(f"first message-trace divergence at seq "
                     f"{first_msg['seq']} (the byte-level symptom):")
        for side in ("event_a", "event_b"):
            if side in first_msg:
                lines.append(f"  run {side[-1]}: {first_msg[side]['what']} "
                             f"(sim {first_msg[side]['sim_us']}us)")
    else:
        lines.append("message traces are byte-identical: the divergence is "
                     "causal-plane only (timer/handler/fault ordering)")
    if origin is not None:
        lines.append(f"origin (last shared decision): {origin['what']} "
                     f"(sim {origin['sim_us']}us, pid {origin['pid']})")
    lines.append(f"ancestor cone of the divergent event (run {cone_run}, "
                 f"<= {hops} hops):")
    for d in cone:
        tag = "shared   " if d["shared"] else "divergent"
        lines.append(f"  [{tag}] pid {d['pid']:>7} sim {d['sim_us']:>12}us "
                     f"{d['what']}")

    return {"index": idx,
            "sim_us": divergent[E_US] if divergent is not None else None,
            "event_a": describe_event(event_a) if event_a is not None else None,
            "event_b": describe_event(event_b) if event_b is not None else None,
            "first_message_divergence": first_msg,
            "origin": origin,
            "cone": cone,
            "text": "\n".join(lines)}


def render_slice(sl: Optional[dict]) -> str:
    """Human rendering of a ``slice_for`` result (KNOWN_ISSUES ledgers,
    stall dumps)."""
    if sl is None:
        return "<no provenance anchor>"
    lines = [f"causal slice (anchor pid {sl['anchor_pid']}, "
             f"<= {sl['hops']} hops):"]
    for d in sl["events"]:
        mark = "*" if d["pid"] == sl["anchor_pid"] else " "
        lines.append(f" {mark} pid {d['pid']:>7} sim {d['sim_us']:>12}us "
                     f"{d['what']}")
    return "\n".join(lines)
