"""Online protocol-invariant auditor: first-divergence detection over the
flight-recorder stream.

``InvariantAuditor`` IS a ``FlightRecorder`` (same hook surface, same
registry/spans/trace planes) that additionally checks, per event, the rule
catalog in ``observe/rules.py``:

1. **SaveStatus edge legality** per (node, store, txn): every observed
   transition must be a ``LEGAL_EDGES`` edge.  Crash/restart re-baselines a
   node's per-store lifecycle state (journal replay legitimately re-observes
   commands at their durable tier, which can sit below the volatile
   pre-crash status).
2. **Commit agreement**: the first decided (``PRE_COMMITTED``-or-later)
   observation of a txn fixes its executeAt cluster-wide — every later
   decided observation on any replica must match, and a decided executeAt
   may never mutate.  The first time two replicas both reach a deps-carrying
   commit tier (COMMITTED / STABLE, compared per tier), their deps restricted
   to the ranges both stores own must be identical; a store's stable deps
   must not mutate while the txn executes.  A decided txn observed
   INVALIDATED anywhere — the exact shape of the PR-2 quarantine-evidence
   bug — violates ``commit.invalidate_conflict``.  Two distinct txns
   deciding the same executeAt violate uniqueness (the hlc+node tiebreak
   contract ``_still_blocks`` relies on).
3. **Per-key / per-txn order**: ballots (``promised`` and
   ``accepted_or_committed``) are monotone per txn per store; normal-path
   applies (the APPLYING -> APPLIED edge) of key-domain writes land in
   strictly increasing executeAt order per key per store (merge paths —
   adoption, replay, heal — are exempt by construction: they never take that
   edge).
4. **Durability / epoch monotonicity**: a store's durability and redundancy
   watermarks never regress (checked lazily on ``durable_gen`` advances);
   a node's topology epoch never regresses within an incarnation; the
   cluster epoch-sync ledger only grows.
5. **Liveness SLO** (flags, never raises): an undecided client txn past
   ``slo_unattended_s`` with no recovery/invalidation attempt attributed, or
   past ``slo_undecided_s`` at all, or decided more than ``slo_unapplied_s``
   ago without any replica reaching APPLIED, opens a flag; the flag closes
   when the condition clears.  ``harness/watchdog.py`` embeds the open flags
   in every stall dump.

On a safety violation the auditor raises ``AuditViolation`` (``strict``) or
records it (``warn``); either way the violation carries the offending txn's
full flight-recorder timeline and a registry snapshot, so a nemesis-found
bug arrives pre-localized to its first bad event.

Zero observer effect: every check reads values the instrumented code already
computed (command fields, store watermarks, sim timestamps) — no RNG, no
wall clock, no scheduling.  ``tests/test_audit.py`` proves it the same way
PR 3 proved the recorder: same-seed hostile burn, ``--audit=strict`` vs off,
byte-identical message traces.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..local.status import Durability, SaveStatus
from ..primitives.timestamp import Domain
from . import rules
from .flight import FlightRecorder


class AuditViolation(Exception):
    """A protocol invariant broke; carries the first bad event's full context.

    ``report()`` renders the plain-data record the burn CLI embeds in
    ``--json`` and the watchdog embeds in stall dumps; ``timeline`` is the
    offending txn's complete flight-recorder span (every per-node/per-store
    SaveStatus transition with sim timestamps) and ``registry`` a metrics
    snapshot taken at the violating event."""

    def __init__(self, rule: str, detail: str, txn_id=None,
                 node: Optional[int] = None, store: Optional[int] = None,
                 now_us: Optional[int] = None, timeline: Optional[dict] = None,
                 registry: Optional[dict] = None,
                 causal_slice: Optional[dict] = None):
        where = " ".join(
            part for part in (
                f"txn {txn_id}" if txn_id is not None else None,
                f"at node {node}" if node is not None else None,
                f"store {store}" if store is not None else None,
                f"sim {now_us}us" if now_us is not None else None)
            if part is not None)
        super().__init__(f"[{rule}] {detail}" + (f" ({where})" if where else ""))
        self.rule = rule
        self.detail = detail
        self.txn_id = txn_id
        self.node = node
        self.store = store
        self.now_us = now_us
        self.timeline = timeline
        self.registry = registry
        # bounded k-hop backward causal slice of the bad event (the ancestor
        # cone from observe/provenance.py), when a recorder was attached
        self.causal_slice = causal_slice

    def report(self, include_registry: bool = False) -> dict:
        out = {
            "rule": self.rule,
            "detail": self.detail,
            "txn_id": None if self.txn_id is None else str(self.txn_id),
            "node": self.node,
            "store": self.store,
            "sim_us": self.now_us,
            "timeline": self.timeline,
        }
        if self.causal_slice is not None:
            out["causal_slice"] = self.causal_slice
        if include_registry:
            out["registry"] = self.registry
        return out


class _TxnAudit:
    """Cross-replica agreement state for one transaction."""

    __slots__ = ("execute_at", "decided_at", "commits", "stables",
                 "invalidated_at", "decided_us", "applied", "attempts")

    def __init__(self):
        self.execute_at = None        # first decided executeAt (+ witness)
        self.decided_at = None        # (node, store) that fixed it
        # per commit tier: {(node, store): (ranges, deps)} — first per store
        self.commits: Dict[Tuple[int, int], Tuple[object, object]] = {}
        self.stables: Dict[Tuple[int, int], Tuple[object, object]] = {}
        self.invalidated_at = None    # (node, store) that invalidated
        self.decided_us = None        # sim time of the first decided event
        self.applied = False          # any replica reached APPLIED
        self.attempts = 0             # recovery/invalidation attempts


class InvariantAuditor(FlightRecorder):
    """A FlightRecorder that halts at the first violated protocol invariant.

    ``mode``: ``"strict"`` raises AuditViolation at the violating event
    (recording it first); ``"warn"`` records only.  SLO flags are always
    recorded, never raised (liveness lag is provisional by nature — a late
    recovery can still settle the txn)."""

    def __init__(self, mode: str = "strict",
                 slo_unattended_s: Optional[float] = None,
                 slo_undecided_s: Optional[float] = None,
                 slo_unapplied_s: Optional[float] = None,
                 message_ring: Optional[int] = None,
                 record_messages: bool = False,
                 timeline=None, burnrate=None, provenance=None):
        assert mode in ("strict", "warn"), f"bad audit mode {mode!r}"
        super().__init__(message_ring=message_ring,
                         record_messages=record_messages,
                         timeline=timeline, burnrate=burnrate,
                         provenance=provenance)
        self.mode = mode
        # single source for the SLO ladder: call sites pass the user value
        # through (None = default), and the decision/apply budgets default to
        # one ladder step above the unattended budget
        if slo_unattended_s is None:
            slo_unattended_s = 10.0
        if slo_undecided_s is None:
            slo_undecided_s = max(6 * slo_unattended_s, 60.0)
        if slo_unapplied_s is None:
            slo_unapplied_s = max(6 * slo_unattended_s, 60.0)
        self.slo_unattended_us = int(slo_unattended_s * 1_000_000)
        self.slo_undecided_us = int(slo_undecided_s * 1_000_000)
        self.slo_unapplied_us = int(slo_unapplied_s * 1_000_000)
        self.cluster = None           # attached by Cluster.__init__ (weakly
                                      # duck-typed: anything with .nodes works)
        self.violations: List[AuditViolation] = []
        self.events_audited = 0
        # (node, store) -> txn -> last status name; re-baselined at crash
        self._last_status: Dict[Tuple[int, int], Dict[object, str]] = {}
        # (node, store) -> txn -> (promised, accepted_or_committed)
        self._ballots: Dict[Tuple[int, int], Dict[object, tuple]] = {}
        # (node, store) -> routing key -> (executeAt, txn) normal-apply watermark
        self._key_applied: Dict[Tuple[int, int], Dict[object, tuple]] = {}
        # (node, store) -> last seen tfk_inversions counter (legal-inversion
        # classification handshake with the per-key execution registers)
        self._tfk_seen: Dict[Tuple[int, int], int] = {}
        # (node, store) -> (durable_gen, majority, universal, shard, local)
        self._watermarks: Dict[Tuple[int, int], tuple] = {}
        # node -> last seen topology epoch (per incarnation)
        self._epochs: Dict[int, int] = {}
        # epoch -> last seen sync-ledger completion count
        self._ledger: Dict[int, int] = {}
        self._txns: Dict[object, _TxnAudit] = {}
        # executeAt -> txn (decided-timestamp uniqueness)
        self._decided_ts: Dict[object, object] = {}
        # nodes between crash and restart-complete: replay re-baselines
        self._replaying: set = set()
        # liveness SLO plane
        self._open_client: Dict[object, dict] = {}   # txn -> client record
        self._slo_flags: Dict[Tuple[str, object], dict] = {}
        self._slo_history: List[dict] = []
        self._next_slo_check_us = None

    # -- lifecycle (cluster crash/restart notifications) ---------------------
    def attach_cluster(self, cluster) -> None:
        self.cluster = cluster

    def on_crash(self, node_id: int) -> None:
        super().on_crash(node_id)
        self._replaying.add(node_id)
        # the process died: volatile lifecycle/ballot state is gone and the
        # journal replay re-observes commands at their durable tier — drop
        # every per-store baseline for the node
        for key in [k for k in self._last_status if k[0] == node_id]:
            self._last_status.pop(key, None)
            self._ballots.pop(key, None)
            self._key_applied.pop(key, None)
            self._watermarks.pop(key, None)
            self._tfk_seen.pop(key, None)
        self._epochs.pop(node_id, None)
        # the node's commit/stable deps records die with its volatile state:
        # a post-restart recovery may legally re-stabilize with a
        # different-but-sufficient cover, which must not be compared against
        # (or immutability-checked against) the pre-crash record
        for audit in self._txns.values():
            for records in (audit.commits, audit.stables):
                for key in [k for k in records if k[0] == node_id]:
                    records.pop(key, None)

    def on_restart(self, node_id: int) -> None:
        super().on_restart(node_id)
        self._replaying.discard(node_id)

    # -- violation plumbing --------------------------------------------------
    def _violate(self, rule: str, detail: str, txn_id=None, node=None,
                 store=None, now_us=None) -> None:
        timeline = None
        span = self.spans.spans.get(txn_id) if txn_id is not None else None
        if span is not None:
            timeline = span.to_dict()
        causal_slice = None
        if self.provenance is not None:
            # the bad event's bounded backward cone — walked NOW, while the
            # recorder still points at the transition that tripped the rule
            causal_slice = self.provenance.slice_for(
                txn_id=txn_id, node=node, store=store)
        violation = AuditViolation(rule, detail, txn_id=txn_id, node=node,
                                   store=store, now_us=now_us,
                                   timeline=timeline,
                                   registry=self.registry.snapshot(),
                                   causal_slice=causal_slice)
        self.violations.append(violation)
        self.registry.counter(f"audit.violation.{rule}").inc()
        if self.mode == "strict":
            raise violation

    # -- the audited hooks ---------------------------------------------------
    def on_submit(self, op_id: int, txn_id, coordinator: int,
                  now_us: int) -> None:
        super().on_submit(op_id, txn_id, coordinator, now_us)
        self._open_client[txn_id] = {"op_id": op_id, "submitted_us": now_us,
                                     "coordinator": coordinator}
        deadline = now_us + min(self.slo_unattended_us, self.slo_undecided_us)
        if self._next_slo_check_us is None or deadline < self._next_slo_check_us:
            self._next_slo_check_us = deadline

    def on_resolve(self, txn_id, kind: str, now_us: int) -> None:
        super().on_resolve(txn_id, kind, now_us)
        self._open_client.pop(txn_id, None)
        for flag_kind in rules.SLO_FLAGS:
            self._close_flag(flag_kind, txn_id, now_us, "resolved")
        self._slo_check(now_us)

    def on_recovery(self, node: int, txn_id, ballot=None, now_us=None) -> None:
        super().on_recovery(node, txn_id, ballot, now_us)
        audit = self._txns.get(txn_id)
        if audit is None:
            audit = self._txns[txn_id] = _TxnAudit()
        audit.attempts += 1
        if now_us is not None:
            self._close_flag(rules.SLO_UNATTENDED, txn_id, now_us,
                             "recovery attempt attributed")

    def on_invalidate(self, node: int, txn_id, now_us=None) -> None:
        super().on_invalidate(node, txn_id, now_us)
        audit = self._txns.get(txn_id)
        if audit is None:
            audit = self._txns[txn_id] = _TxnAudit()
        audit.attempts += 1
        if now_us is not None:
            self._close_flag(rules.SLO_UNATTENDED, txn_id, now_us,
                             "invalidation attempt attributed")

    def on_message_event(self, event: str, frm: int, to: int, msg_id,
                         message, now_us: int) -> None:
        super().on_message_event(event, frm, to, msg_id, message, now_us)
        self._slo_check(now_us)

    def on_reply_timeout(self, node: int, peer: int, txn_id,
                         now_us: int) -> None:
        super().on_reply_timeout(node, peer, txn_id, now_us)
        # a total wedge (all journals stalled: held sends, no message
        # events) still fires reply timeouts — without this pulse the SLO
        # scan would sleep through exactly the stalls it exists to flag
        self._slo_check(now_us)

    def on_transition(self, node: int, store: int, txn_id,
                      status_name: str, now_us: int,
                      command=None, command_store=None) -> None:
        super().on_transition(node, store, txn_id, status_name, now_us,
                              command=command, command_store=command_store)
        self.events_audited += 1
        key = (node, store)
        per_store = self._last_status.setdefault(key, {})
        prev = per_store.get(txn_id)
        if prev is None and node in self._replaying:
            # journal replay re-baselines: the first re-observation of each
            # txn is its durable tier, not an edge
            per_store[txn_id] = status_name
        else:
            frm = prev if prev is not None else "NOT_DEFINED"
            per_store[txn_id] = status_name
            if not rules.is_legal_edge(frm, status_name):
                self._violate(
                    rules.RULE_ILLEGAL_EDGE,
                    f"illegal SaveStatus transition {frm} -> {status_name}",
                    txn_id=txn_id, node=node, store=store, now_us=now_us)
        if command is not None:
            self._audit_ballots(key, txn_id, command, now_us)
            self._audit_agreement(node, store, txn_id, status_name, command,
                                  command_store, now_us)
            if prev == "APPLYING" and status_name == "APPLIED":
                self._audit_key_order(key, txn_id, command, command_store,
                                      now_us)
        if command_store is not None:
            self._audit_watermarks(key, command_store, now_us)
        self._audit_epochs(node, now_us)
        self._slo_check(now_us)

    # -- rule 2: commit agreement --------------------------------------------
    def _audit_agreement(self, node: int, store: int, txn_id, status_name: str,
                         command, command_store, now_us: int) -> None:
        status = SaveStatus[status_name]
        audit = self._txns.get(txn_id)
        if audit is None:
            audit = self._txns[txn_id] = _TxnAudit()
        if status is SaveStatus.INVALIDATED:
            audit.invalidated_at = (node, store)
            if audit.execute_at is not None:
                self._violate(
                    rules.RULE_COMMIT_INVALIDATE_CONFLICT,
                    f"txn invalidated at node {node}/store {store} but "
                    f"decided executeAt={audit.execute_at} was witnessed at "
                    f"node/store {audit.decided_at}",
                    txn_id=txn_id, node=node, store=store, now_us=now_us)
            return
        if status.is_truncated:
            return   # tombstones carry no (reliable) decision payload
        if status is SaveStatus.APPLIED:
            audit.applied = True
            self._close_flag(rules.SLO_UNAPPLIED, txn_id, now_us, "applied")
        if not status.is_decided or command.execute_at is None:
            return
        execute_at = command.execute_at
        # decided: executeAt fixed cluster-wide, forever
        if audit.execute_at is None:
            audit.execute_at = execute_at
            audit.decided_at = (node, store)
            audit.decided_us = now_us
            if txn_id in self._open_client:
                # (re-)arm the SLO scan for the unapplied deadline: the scan
                # may have gone dormant with every pre-decision deadline in
                # the past, and this is the only event that creates a new one
                deadline = now_us + self.slo_unapplied_us
                if self._next_slo_check_us is None \
                        or deadline < self._next_slo_check_us:
                    self._next_slo_check_us = deadline
            other = self._decided_ts.get(execute_at)
            if other is not None and other != txn_id:
                self._violate(
                    rules.RULE_EXECUTE_AT_DUPLICATE,
                    f"distinct txns {other} and {txn_id} both decided "
                    f"executeAt={execute_at}",
                    txn_id=txn_id, node=node, store=store, now_us=now_us)
            self._decided_ts[execute_at] = txn_id
            self._close_flag(rules.SLO_UNATTENDED, txn_id, now_us, "decided")
            self._close_flag(rules.SLO_UNDECIDED, txn_id, now_us, "decided")
        elif execute_at != audit.execute_at:
            rule = rules.RULE_EXECUTE_AT_MUTATED \
                if (node, store) == audit.decided_at \
                else rules.RULE_EXECUTE_AT_MISMATCH
            self._violate(
                rule,
                f"decided executeAt diverged: {audit.execute_at} (first at "
                f"node/store {audit.decided_at}) vs {execute_at} at "
                f"node {node}/store {store}",
                txn_id=txn_id, node=node, store=store, now_us=now_us)
        if audit.invalidated_at is not None:
            self._violate(
                rules.RULE_COMMIT_INVALIDATE_CONFLICT,
                f"txn decided at node {node}/store {store} but was "
                f"invalidated at node/store {audit.invalidated_at}",
                txn_id=txn_id, node=node, store=store, now_us=now_us)
        # cross-replica deps agreement at the COMMITTED tier only: that tier
        # is produced solely by the CommitSlowPath broadcast (one message,
        # one ballot, per-store slices of ONE deps set), where equality on
        # commonly-owned ranges is a true invariant.  The STABLE tier can
        # arrive via Propagate with coverage-gated partial merges and via
        # recovery re-stabilisation — different-but-sufficient covers — so
        # there the auditor checks LOCAL immutability instead.
        if status_name == "COMMITTED":
            self._audit_deps(audit.commits, "COMMITTED", node, store, txn_id,
                             command, command_store, now_us)
        elif status_name == "STABLE" and command.partial_deps is not None:
            audit.stables.setdefault(
                (node, store),
                (command.accepted_or_committed, None,
                 command.partial_deps, command_store))
        elif audit.stables and command.partial_deps is not None:
            # deps immutability while executing: the stable slice this store
            # recorded must still be what the command carries
            rec = audit.stables.get((node, store))
            if rec is not None:
                _ballot, _ranges, deps, _cs = rec
                now_ids = frozenset(command.partial_deps.txn_ids())
                then_ids = frozenset(deps.txn_ids())
                if now_ids != then_ids:
                    self._violate(
                        rules.RULE_DEPS_MUTATED,
                        f"stable deps mutated at node {node}/store {store}: "
                        f"{sorted(then_ids ^ now_ids)} changed",
                        txn_id=txn_id, node=node, store=store, now_us=now_us)

    def _audit_deps(self, records: dict, tier: str, node: int, store: int,
                    txn_id, command, command_store, now_us: int) -> None:
        """Cross-replica deps agreement at a commit tier, modulo ELISION:
        deps are a COVER, not a standalone consensus value — a recovery
        re-coordination at a higher ballot may legitimately compute a
        different (still sufficient) cover, and the data plane elides
        universally-durable and fenced entries.  What MUST agree is the same
        consensus round: two replicas committing at the SAME accepted ballot
        received the same broadcast, so their deps restricted to commonly-
        owned ranges must be identical modulo entries provably SETTLED
        (terminal, durable, or below a redundancy fence) at the store that
        lacks them.  A live differing dep within one ballot means the two
        replicas will execute in different orders — the divergence-class
        violation."""
        if command.partial_deps is None or command_store is None:
            return
        # the commit scope covers (at least) the store's ranges at the txn's
        # epoch — all_ranges() would over-claim ranges adopted LATER, whose
        # deps this commit's slice never carried
        ranges = command_store.ranges_at(txn_id.epoch)
        if not ranges:
            return
        ballot = command.accepted_or_committed
        mine = (ballot, ranges, command.partial_deps, command_store)
        for (other_node, other_store), (other_ballot, other_ranges,
                                        other_deps, other_cs) \
                in records.items():
            if (other_node, other_store) == (node, store):
                continue
            if other_ranges is None or other_ballot != ballot:
                continue   # different consensus rounds: covers may differ
            common = ranges.intersection(other_ranges)
            if not common:
                continue
            mine_sliced = command.partial_deps.slice(common)
            their_sliced = other_deps.slice(common)
            mine_ids = frozenset(mine_sliced.txn_ids())
            their_ids = frozenset(their_sliced.txn_ids())
            if mine_ids == their_ids:
                continue
            # they have it, we lack it: settled HERE?  we have it, they lack
            # it: settled THERE?
            unsettled = [
                dep for dep in their_ids - mine_ids
                if not self._dep_settled(command_store, dep,
                                         their_sliced.participants(dep))
            ] + [
                dep for dep in mine_ids - their_ids
                if not self._dep_settled(other_cs, dep,
                                         mine_sliced.participants(dep))
            ]
            if not unsettled:
                self.registry.counter("audit.deps_elision_diffs").inc()
                continue
            self._violate(
                rules.RULE_DEPS_MISMATCH,
                f"{tier} deps disagree on commonly-owned ranges "
                f"{common!r} with UNSETTLED differing deps "
                f"{sorted(unsettled)}: node {node}/store {store} vs the "
                f"first committer node/store {(other_node, other_store)} "
                f"(full diff: +{sorted(mine_ids - their_ids)} "
                f"-{sorted(their_ids - mine_ids)})",
                txn_id=txn_id, node=node, store=store, now_us=now_us)
        records.setdefault((node, store), mine)

    @staticmethod
    def _dep_settled(command_store, dep_id, participants) -> bool:
        """Is ``dep_id`` provably settled at ``command_store`` — terminal,
        durable at a majority, or below a local-redundancy fence — so that
        eliding it from a deps computation cannot change execution order?"""
        if command_store is None:
            return False
        cmd = command_store.commands.get(dep_id)
        if cmd is not None:
            if cmd.save_status in (SaveStatus.APPLIED, SaveStatus.INVALIDATED) \
                    or cmd.save_status.is_truncated:
                return True
            if cmd.durability >= Durability.MAJORITY:
                return True
        if dep_id in command_store.cold:
            return True   # eviction admits only terminal commands
        if participants is not None:
            keys, rngs = participants
            parts = list(keys) + list(rngs)
            if parts and command_store.redundant_before.is_locally_redundant(
                    dep_id, parts):
                return True
            if parts and command_store.durable_before.min_durability(
                    dep_id, parts) >= Durability.MAJORITY:
                return True
        return False

    # -- rule 3: per-txn ballot + per-key executeAt order ---------------------
    def _audit_ballots(self, key: Tuple[int, int], txn_id, command,
                       now_us: int) -> None:
        per_store = self._ballots.setdefault(key, {})
        prev = per_store.get(txn_id)
        cur = (command.promised, command.accepted_or_committed)
        per_store[txn_id] = cur
        if prev is None:
            return
        if cur[0] < prev[0] or cur[1] < prev[1]:
            which = "promised" if cur[0] < prev[0] else "accepted_or_committed"
            self._violate(
                rules.RULE_BALLOT_REGRESSION,
                f"{which} ballot regressed: {prev} -> {cur}",
                txn_id=txn_id, node=key[0], store=key[1], now_us=now_us)

    def _audit_key_order(self, key: Tuple[int, int], txn_id, command,
                         command_store, now_us: int) -> None:
        """Normal-path applies of key-domain writes must land in executeAt
        order per key per store (merge paths never take APPLYING->APPLIED)
        — UNLESS the inversion is one of the two classified-legal kinds:

        - the late txn is below the key's locally-redundant fence
          (bootstrap / catch-up landing: its deps were elided because the
          snapshot subsumes them, and the data store merges idempotently by
          executeAt — correct under MVCC);
        - the store's own per-key execution registers classified it
          (``tfk_inversions`` advances in ``update_last_execution`` BEFORE
          the APPLIED event fires — the heal/stale-recovery class the burn
          surfaces in its stats and escalates on growth).

        An out-of-order apply that neither a fence nor the tfk plane
        accounts for is a silent execution-frontier break — the violation."""
        if command_store is None:
            return
        counter = command_store.tfk_inversions
        seen = self._tfk_seen.get(key, 0)
        self._tfk_seen[key] = counter
        data_plane_classified = counter > seen
        if not txn_id.is_write or txn_id.domain is not Domain.KEY:
            return
        if command.writes is None or command.execute_at is None:
            return
        owned = command_store.all_ranges()
        watermark = self._key_applied.setdefault(key, {})
        for wkey in command.writes.keys:
            rk = wkey.to_routing() if hasattr(wkey, "to_routing") else wkey
            if not owned.contains(rk):
                continue   # unowned keys are not applied (or registered) here
            prev = watermark.get(rk)
            if prev is not None and command.execute_at <= prev[0]:
                fence = command_store.redundant_before \
                    .locally_redundant_before(rk)
                if fence is not None and txn_id < fence:
                    self.registry.counter("audit.key_inversions_fenced").inc()
                elif data_plane_classified:
                    self.registry.counter("audit.key_inversions_mvcc").inc()
                else:
                    self._violate(
                        rules.RULE_KEY_EXECUTE_AT_ORDER,
                        f"normal-path apply of key {rk!r} out of executeAt "
                        f"order: {command.execute_at} after {prev[0]} "
                        f"(txn {prev[1]}), with no local-redundancy fence "
                        f"above the late txn and no tfk-register "
                        f"classification",
                        txn_id=txn_id, node=key[0], store=key[1],
                        now_us=now_us)
            if prev is None or command.execute_at > prev[0]:
                watermark[rk] = (command.execute_at, txn_id)

    # -- rule 4: durability / epoch monotonicity ------------------------------
    def _audit_watermarks(self, key: Tuple[int, int], command_store,
                          now_us: int) -> None:
        gen = command_store.durable_gen
        prev = self._watermarks.get(key)
        if prev is not None and prev[0] == gen:
            return   # nothing advanced since the last sample
        footprint = command_store.all_ranges()
        majority, universal = \
            command_store.durable_before.max_bounds_over(footprint)
        shard = command_store.redundant_before.max_shard_redundant_over(
            footprint)
        local = command_store.redundant_before.max_locally_redundant_over(
            footprint)
        cur = (gen, majority, universal, shard, local)
        self._watermarks[key] = cur
        if prev is None:
            return
        for name, before, after in (("majority_durable", prev[1], majority),
                                    ("universal_durable", prev[2], universal),
                                    ("shard_redundant", prev[3], shard),
                                    ("locally_redundant", prev[4], local)):
            if before is not None and (after is None or after < before):
                self._violate(
                    rules.RULE_DURABILITY_REGRESSION,
                    f"{name} watermark regressed: {before} -> {after}",
                    node=key[0], store=key[1], now_us=now_us)

    def _audit_epochs(self, node: int, now_us: int) -> None:
        cluster = self.cluster
        if cluster is None:
            return
        node_obj = cluster.nodes.get(node)
        if node_obj is not None:
            epoch = node_obj.topology.current_epoch
            prev = self._epochs.get(node)
            if prev is not None and epoch < prev:
                self._violate(
                    rules.RULE_EPOCH_REGRESSION,
                    f"node topology epoch regressed: {prev} -> {epoch}",
                    node=node, now_us=now_us)
            self._epochs[node] = max(epoch, prev if prev is not None else epoch)
        ledger = getattr(cluster, "sync_ledger", None)
        if ledger:
            for epoch, completed in ledger.items():
                count = len(completed)
                prev_count = self._ledger.get(epoch, 0)
                if count < prev_count:
                    self._violate(
                        rules.RULE_SYNC_LEDGER_REGRESSION,
                        f"epoch {epoch} sync ledger shrank: "
                        f"{prev_count} -> {count}",
                        node=node, now_us=now_us)
                self._ledger[epoch] = max(count, prev_count)

    # -- rule 5: liveness SLO (flags, never raises) ---------------------------
    def _slo_check(self, now_us: int) -> None:
        if self._next_slo_check_us is None or now_us < self._next_slo_check_us:
            return
        next_deadline = None

        def consider(deadline):
            nonlocal next_deadline
            if next_deadline is None or deadline < next_deadline:
                next_deadline = deadline

        for txn_id, rec in list(self._open_client.items()):
            audit = self._txns.get(txn_id)
            attempts = audit.attempts if audit is not None else 0
            decided_us = audit.decided_us if audit is not None else None
            applied = audit.applied if audit is not None else False
            if decided_us is None:
                unattended_at = rec["submitted_us"] + self.slo_unattended_us
                if now_us >= unattended_at:
                    if attempts == 0:
                        self._open_flag(rules.SLO_UNATTENDED, txn_id, rec,
                                        now_us,
                                        f"undecided for "
                                        f"{(now_us - rec['submitted_us']) / 1e6:.1f}"
                                        f"s with no recovery/invalidation "
                                        f"attempt attributed")
                else:
                    consider(unattended_at)
                undecided_at = rec["submitted_us"] + self.slo_undecided_us
                if now_us >= undecided_at:
                    self._open_flag(rules.SLO_UNDECIDED, txn_id, rec, now_us,
                                    f"undecided for "
                                    f"{(now_us - rec['submitted_us']) / 1e6:.1f}s"
                                    f" ({attempts} recovery attempts)")
                else:
                    consider(undecided_at)
            elif not applied:
                unapplied_at = decided_us + self.slo_unapplied_us
                if now_us >= unapplied_at:
                    self._open_flag(rules.SLO_UNAPPLIED, txn_id, rec, now_us,
                                    f"decided "
                                    f"{(now_us - decided_us) / 1e6:.1f}s ago, "
                                    f"no replica reached APPLIED")
                else:
                    consider(unapplied_at)
        self._next_slo_check_us = next_deadline

    def _open_flag(self, kind: str, txn_id, rec: dict, now_us: int,
                   detail: str) -> None:
        key = (kind, txn_id)
        if key in self._slo_flags:
            return
        flag = {"kind": kind, "txn_id": str(txn_id), "op_id": rec["op_id"],
                "coordinator": rec["coordinator"],
                "submitted_us": rec["submitted_us"], "flagged_us": now_us,
                "detail": detail, "closed_us": None, "closed_because": None}
        self._slo_flags[key] = flag
        self._slo_history.append(flag)
        self.registry.counter(f"audit.{kind}").inc()
        if self.burnrate is not None:
            # a flag opening is one bad event on the liveness-SLO burn-rate
            # monitors (observe/burnrate.py) — the early-warning plane
            self.burnrate.on_flag_opened(kind, now_us)

    def _close_flag(self, kind: str, txn_id, now_us: int, why: str) -> None:
        flag = self._slo_flags.pop((kind, txn_id), None)
        if flag is not None:
            flag["closed_us"] = now_us
            flag["closed_because"] = why
            if self.burnrate is not None:
                self.burnrate.on_flag_closed(kind, now_us)

    # -- reporting ------------------------------------------------------------
    def open_slo_flags(self) -> List[dict]:
        return [dict(f) for f in self._slo_flags.values()]

    def slo_flag_history(self) -> List[dict]:
        return [dict(f) for f in self._slo_history]

    def verdict(self) -> dict:
        """Per-run audit summary (the burn CLI's --json per-seed verdict)."""
        out = {
            "mode": self.mode,
            "events_audited": self.events_audited,
            "violations": len(self.violations),
            "first_violation": self.violations[0].report()
            if self.violations else None,
            "rules_violated": sorted({v.rule for v in self.violations}),
            "slo_flags_raised": len(self._slo_history),
            "slo_flags_open": len(self._slo_flags),
            "open_slo_flags": self.open_slo_flags()[:16],
        }
        if self.burnrate is not None:
            # the burn-rate monitors' slo.burn events land in the SAME warn
            # stream as the flags: a soak's --json verdict carries the
            # early-warning trajectory, not just the end-state flags
            out.update(self.burnrate.report())
        return out

    def audit_report(self) -> str:
        """One-paragraph text report for the watchdog's stall dump."""
        import json
        return json.dumps(self.verdict(), sort_keys=True, default=str)
