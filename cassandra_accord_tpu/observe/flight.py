"""The flight recorder: every observability hook the instrumented tree calls.

One ``FlightRecorder`` per run bundles the metrics registry, the txn span
recorder, and a (optionally ring-bounded) message event buffer, and exposes
the ``on_*`` hooks wired through ``harness/cluster.py`` (message routing,
reply timeouts/backoff), ``coordinate/`` (path classification, recovery
attribution), ``local/commands.py`` (status transitions) and
``local/progress_log.py`` (investigation launches).

All hooks obey the zero-observer-effect contract (see ``observe/__init__``):
they consume values the caller already computed and never touch RNG, wall
clock, or the event loop.
"""
from __future__ import annotations

from typing import Optional

from ..harness.trace import Trace
from . import device as device_metrics
from . import schema
from .registry import MetricsRegistry
from .spans import TxnSpanRecorder

# link-action / routing events the cluster reports for an OUTBOUND packet
# (the reply family is prefixed RPLY_); RECV/RECV_RPLY are deliveries
_SEND_EVENTS = ("DELIVER", "DROP", "FAILURE", "DELIVER_WITH_FAILURE", "DOWN")


def _message_metric(message) -> str:
    """Schema metric name for a message instance; total (never raises)."""
    try:
        return schema.metric_for_message(message.type.name)
    except Exception:  # noqa: BLE001 — unregistered/legacy message classes
        return f"msg.unregistered.{type(message).__name__}"


class FlightRecorder:
    """Metrics + spans + message events for one deterministic run."""

    def __init__(self, message_ring: Optional[int] = None,
                 record_messages: bool = True,
                 timeline=None, burnrate=None, provenance=None):
        self.registry = MetricsRegistry()
        self.spans = TxnSpanRecorder()
        self.record_messages = record_messages
        # causal provenance side table (observe/provenance.py): the per-run
        # event DAG divergence forensics and violation slicing walk.  Pure
        # bookkeeping on already-computed values — same zero-observer-effect
        # contract as every other attachment here.
        self.provenance = provenance
        # sim-time windowed telemetry (observe/timeline.py): counters become
        # per-window rates, gauges samples, latencies per-window percentiles.
        # Same zero-observer-effect contract as every other plane here.
        self.timeline = timeline
        # multi-window SLO burn-rate monitors (observe/burnrate.py): mid-run
        # early warning fed from the same hooks
        self.burnrate = burnrate
        if burnrate is not None:
            burnrate.bind(self)
        # in-flight client ops (submit minus resolve), sampled onto the
        # timeline — the commits/s-vs-in-flight curve ROADMAP item 2 reads
        self._in_flight = 0
        # the message timeline IS a Trace (same event tuples, same optional
        # ring bound) — one ring-buffer implementation, not two
        self._message_trace = Trace(keep_last=message_ring)
        # sim-timestamped recovery/invalidation attempts (Chrome-trace
        # counter tracks sample these into per-bucket "C" events)
        self._recovery_times: list = []
        self._invalidate_times: list = []
        # consult-service (ts, queue_depth, batch_rows) samples, pulled from
        # every engaged DeviceConsultService at collect_cluster time — the
        # export renders them as a dedicated counter track (pid 0, tid 1)
        self._service_samples: list = []

    @property
    def messages(self):
        return self._message_trace.events

    @property
    def dropped_messages(self) -> int:
        return self._message_trace.dropped

    # -- message plane (cluster.route / route_reply / _deliver) --------------
    def on_message_event(self, event: str, frm: int, to: int, msg_id,
                         message, now_us: int) -> None:
        reg = self.registry
        tl = self.timeline
        if event in _SEND_EVENTS:
            name = _message_metric(message)
            reg.counter(name).inc()
            reg.counter(name, node=frm).inc()
            reg.counter(f"link.{event.lower()}").inc()
            if tl is not None:
                tl.count(name, now_us)
                tl.count(f"link.{event.lower()}", now_us)
        elif event.startswith("RPLY_"):
            name = _message_metric(message)
            reg.counter(name).inc()
            reg.counter(name, node=frm).inc()
            reg.counter(f"link.reply_{event[5:].lower()}").inc()
            if tl is not None:
                tl.count(name, now_us)
                tl.count(f"link.reply_{event[5:].lower()}", now_us)
        else:   # RECV / RECV_RPLY: the delivery, counted at the receiver
            reg.counter("msg.received", node=to).inc()
            if tl is not None:
                tl.count("msg.received", now_us, node=to)
        if self.record_messages:
            self._message_trace.hook(event, frm, to, msg_id, message, now_us)
        if self.provenance is not None:
            self.provenance.on_message_event(event, frm, to, msg_id, message,
                                             now_us)
        if self.burnrate is not None:
            # clock pulse: a total wedge produces no resolutions, but probes
            # and timeouts keep the message plane (and so the monitors) live
            self.burnrate.on_pulse(now_us)

    def on_reply_timeout(self, node: int, peer: int, txn_id,
                         now_us: int) -> None:
        self.registry.counter("net.reply_timeouts").inc()
        self.registry.counter("net.reply_timeouts", node=node).inc()
        self.spans.on_timeout(txn_id)
        if self.timeline is not None:
            self.timeline.count("net.reply_timeouts", now_us)
        if self.burnrate is not None:
            # timeouts keep firing through a total wedge (held sends emit no
            # message events) — they are the monitor's clock there
            self.burnrate.on_pulse(now_us)

    def on_backoff(self, node: int, txn_id, attempt: int) -> None:
        self.registry.counter("net.backoff_rearms").inc()
        self.registry.counter("net.backoff_rearms", node=node).inc()
        self.spans.on_backoff(txn_id)

    # -- client envelope (harness/burn.py) -----------------------------------
    def on_submit(self, op_id: int, txn_id, coordinator: int,
                  now_us: int) -> None:
        self.spans.on_submit(op_id, txn_id, coordinator, now_us)
        self.registry.counter(schema.SUBMITTED_METRIC).inc()
        self.registry.counter(schema.SUBMITTED_METRIC, node=coordinator).inc()
        self._in_flight += 1
        if self.timeline is not None:
            self.timeline.count(schema.SUBMITTED_METRIC, now_us)
            self.timeline.count(schema.SUBMITTED_METRIC, now_us,
                                node=coordinator)
            self.timeline.sample(schema.TIMELINE_IN_FLIGHT_METRIC,
                                 self._in_flight, now_us)

    def on_resolve(self, txn_id, kind: str, now_us: int) -> None:
        outcome = self.spans.on_resolve(txn_id, kind, now_us)
        self.registry.counter(schema.OUTCOME_METRICS[outcome]).inc()
        span = self.spans.spans[txn_id]
        latency_us = None
        if span.submitted_us is not None:
            latency_us = now_us - span.submitted_us
            self.registry.histogram(schema.LATENCY_METRIC).record(latency_us)
        self._in_flight -= 1
        if self.timeline is not None:
            self.timeline.count(schema.OUTCOME_METRICS[outcome], now_us)
            self.timeline.sample(schema.TIMELINE_IN_FLIGHT_METRIC,
                                 self._in_flight, now_us)
            if latency_us is not None:
                self.timeline.value(schema.LATENCY_METRIC, latency_us, now_us)
        if self.burnrate is not None:
            self.burnrate.on_resolution(outcome, latency_us, now_us)

    # -- coordination classification (coordinate/) ---------------------------
    def on_path(self, txn_id, path: str,
                fast_path_votes=None) -> None:
        self.spans.on_path(txn_id, path)
        self.registry.counter(f"txn.path.{path}").inc()
        if fast_path_votes is not None:
            accepts, rejects = fast_path_votes
            self.registry.counter("txn.fastpath.votes_accept").inc(accepts)
            self.registry.counter("txn.fastpath.votes_reject").inc(rejects)

    def on_recovery(self, node: int, txn_id, ballot=None, now_us=None) -> None:
        self.spans.on_recovery(txn_id)
        self.registry.counter("recovery.attempts").inc()
        self.registry.counter("recovery.attempts", node=node).inc()
        if now_us is not None:
            # sim-timestamped attribution: the Chrome-trace export's
            # recovery counter track samples these
            self._recovery_times.append(now_us)
            if self.timeline is not None:
                self.timeline.count("recovery.attempts", now_us)

    def on_invalidate(self, node: int, txn_id, now_us=None) -> None:
        self.spans.on_invalidate_attempt(txn_id)
        self.registry.counter("recovery.invalidate_attempts").inc()
        self.registry.counter("recovery.invalidate_attempts", node=node).inc()
        if now_us is not None:
            self._invalidate_times.append(now_us)
            if self.timeline is not None:
                self.timeline.count("recovery.invalidate_attempts", now_us)

    # -- replica-side lifecycle (local/commands.py) --------------------------
    def on_transition(self, node: int, store: int, txn_id,
                      status_name: str, now_us: int,
                      command=None, command_store=None) -> None:
        """``command``/``command_store`` are the live objects the transition
        just mutated — passed so the InvariantAuditor subclass can read
        decision state (executeAt, deps, ballots, watermarks) passively;
        the recorder itself only uses the scalar fields."""
        self.spans.on_transition(node, store, txn_id, status_name, now_us)
        if self.provenance is not None:
            self.provenance.on_transition(node, store, txn_id, status_name,
                                          now_us)
        name = schema.metric_for_save_status(status_name)
        self.registry.counter(name).inc()
        self.registry.counter(name, node=node, store=store).inc()
        if self.timeline is not None:
            self.timeline.count(name, now_us)
            self.timeline.count(name, now_us, node=node, store=store)

    # -- node lifecycle (harness/cluster.py crash/restart) -------------------
    def on_crash(self, node_id: int) -> None:
        self.registry.counter("lifecycle.node_crashes").inc()
        self.registry.counter("lifecycle.node_crashes", node=node_id).inc()

    def on_restart(self, node_id: int) -> None:
        self.registry.counter("lifecycle.node_restarts").inc()
        self.registry.counter("lifecycle.node_restarts", node=node_id).inc()

    # -- progress-log liveness machinery (local/progress_log.py) -------------
    def on_progress(self, kind: str, node: int,
                    store: Optional[int] = None) -> None:
        self.registry.counter(f"progress.{kind}").inc()
        self.registry.counter(f"progress.{kind}", node=node, store=store).inc()

    # -- pull collection (end of run / watchdog dump) ------------------------
    def collect_cluster(self, cluster) -> None:
        """Pull-collect cluster/stores state as gauges: simulator stats
        (message counts, fault injections), per-store size/diagnostic
        counters, and the device-resolver counters."""
        reg = self.registry
        for key, value in cluster.stats.items():
            reg.gauge(f"sim.{key}").set(value)
        sg = schema.STORE_GAUGE_METRICS   # names live in the unit-linted schema
        for node in cluster.nodes.values():
            for cs in node.command_stores.all_stores():
                reg.gauge(sg["commands"], node=node.id,
                          store=cs.id).set(len(cs.commands))
                reg.gauge(sg["cold"], node=node.id,
                          store=cs.id).set(len(cs.cold))
                reg.gauge(sg["exec_deferred"], node=node.id,
                          store=cs.id).set(len(cs.exec_deferred))
                reg.gauge(sg["cache_miss_loads"], node=node.id,
                          store=cs.id).set(cs.cache_miss_loads)
                reg.gauge(sg["tfk_inversions"], node=node.id,
                          store=cs.id).set(cs.tfk_inversions)
        device_metrics.collect_into(reg, cluster)
        samples: list = []
        for _node_id, _store_id, svc in device_metrics.cluster_services(cluster):
            samples.extend(svc.samples)
        samples.sort()
        self._service_samples = samples

    # -- rendering -----------------------------------------------------------
    def metrics_snapshot(self, cluster=None) -> dict:
        if cluster is not None:
            self.collect_cluster(cluster)
        return self.registry.snapshot()

    def registry_json(self, cluster=None) -> str:
        if cluster is not None:
            self.collect_cluster(cluster)
        return self.registry.to_json()

    def write_timeline(self, path: str) -> None:
        """Write the windowed-telemetry JSONL artifact (burn CLI
        ``--timeline-out``); requires a timeline attached at construction."""
        from .timeline import write_timeline_jsonl
        write_timeline_jsonl(path, self)

    def chrome_trace(self, profiler=None) -> dict:
        from .export import chrome_trace
        return chrome_trace(self, profiler=profiler)

    def write_trace(self, path: str, profiler=None) -> None:
        from .export import write_chrome_trace
        write_chrome_trace(path, self, profiler=profiler)

    def latency_budget(self, top_k: int = 6) -> dict:
        """Plane-1 critical-path latency budget over the recorded spans
        (observe/critical_path.py) — post-hoc analysis, no runtime cost."""
        from .critical_path import latency_budget
        return latency_budget(self, top_k=top_k)
