"""Transaction lifecycle spans.

One ``TxnSpan`` per transaction records, with sim-timestamps:

- the client submit / resolve envelope (coordinator node, op id, outcome),
- fast/slow-path classification from the PreAccept round's tracker votes,
- recovery and invalidation attribution (how many recovery attempts touched
  this txn; whether an invalidation round was launched against it),
- reply timeout and backoff re-arm counts attributed to the txn's messages,
- every per-(node, store) ``SaveStatus`` transition — the
  PreAccept→Accept→Commit→Stable→Apply timeline the Chrome-trace export
  renders one track per node/store.

Span identity is the transaction's own ``TxnId`` — already unique and
deterministic — so recording allocates nothing from any shared sequence
(the zero-observer-effect contract).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

# resolve-kind (harness/burn.py) -> final outcome class.  "ok" resolutions
# split fast/slow by the recorded coordination path.
_KIND_OUTCOME = {"recovered": "recovered", "nacked": "invalidated",
                 "lost": "lost", "failed": "failed"}


class TxnSpan:
    __slots__ = ("txn_id", "op_id", "coordinator", "submitted_us",
                 "resolved_us", "path", "outcome", "recoveries",
                 "invalidate_attempts", "timeouts", "backoffs", "transitions")

    def __init__(self, txn_id):
        self.txn_id = txn_id
        self.op_id: Optional[int] = None
        self.coordinator: Optional[int] = None
        self.submitted_us: Optional[int] = None
        self.resolved_us: Optional[int] = None
        self.path: Optional[str] = None          # "fast" | "slow"
        self.outcome: Optional[str] = None       # schema.OUTCOMES
        self.recoveries = 0
        self.invalidate_attempts = 0
        self.timeouts = 0
        self.backoffs = 0
        # (node, store) -> [(save_status_name, sim_micros), ...]
        self.transitions: Dict[Tuple[int, int], List[Tuple[str, int]]] = {}

    @property
    def is_client_op(self) -> bool:
        return self.submitted_us is not None

    def to_dict(self) -> dict:
        """Stable plain-data rendering (the span schema tests pin this)."""
        return {
            "txn_id": str(self.txn_id),
            "op_id": self.op_id,
            "coordinator": self.coordinator,
            "submitted_us": self.submitted_us,
            "resolved_us": self.resolved_us,
            "path": self.path,
            "outcome": self.outcome,
            "recoveries": self.recoveries,
            "invalidate_attempts": self.invalidate_attempts,
            "timeouts": self.timeouts,
            "backoffs": self.backoffs,
            "transitions": {f"{n}/{s}": list(ts)
                            for (n, s), ts in sorted(self.transitions.items())},
        }


class TxnSpanRecorder:
    """All spans of one run, keyed by TxnId.  System transactions (sync
    points, durability rounds) get transition-only spans; client ops get the
    full submit/resolve envelope from the burn harness."""

    __slots__ = ("spans",)

    def __init__(self):
        self.spans: Dict[object, TxnSpan] = {}

    def _span(self, txn_id) -> TxnSpan:
        span = self.spans.get(txn_id)
        if span is None:
            span = TxnSpan(txn_id)
            self.spans[txn_id] = span
        return span

    # -- client envelope (harness/burn.py) -----------------------------------
    def on_submit(self, op_id: int, txn_id, coordinator: int,
                  now_us: int) -> None:
        span = self._span(txn_id)
        span.op_id = op_id
        span.coordinator = coordinator
        span.submitted_us = now_us

    def on_resolve(self, txn_id, kind: str, now_us: int) -> str:
        """Record the final resolution; returns the outcome class."""
        span = self._span(txn_id)
        span.resolved_us = now_us
        outcome = _KIND_OUTCOME.get(kind)
        if outcome is None:                      # kind == "ok"
            outcome = span.path or "slow"
        span.outcome = outcome
        return outcome

    # -- coordination classification (coordinate/) ---------------------------
    def on_path(self, txn_id, path: str) -> None:
        span = self._span(txn_id)
        if span.path is None:        # first classification wins (recovery
            span.path = path         # re-proposals don't reclassify)

    def on_recovery(self, txn_id) -> None:
        self._span(txn_id).recoveries += 1

    def on_invalidate_attempt(self, txn_id) -> None:
        self._span(txn_id).invalidate_attempts += 1

    # -- message-plane attribution (harness/cluster.py sinks) ----------------
    def on_timeout(self, txn_id) -> None:
        if txn_id is not None:
            self._span(txn_id).timeouts += 1

    def on_backoff(self, txn_id) -> None:
        if txn_id is not None:
            self._span(txn_id).backoffs += 1

    # -- replica-side lifecycle (local/commands.py) --------------------------
    def on_transition(self, node: int, store: int, txn_id,
                      status_name: str, now_us: int) -> None:
        self._span(txn_id).transitions.setdefault((node, store), []) \
            .append((status_name, now_us))

    # -- rendering -----------------------------------------------------------
    def client_spans(self) -> List[TxnSpan]:
        return [s for s in self.spans.values() if s.is_client_op]

    def to_list(self) -> List[dict]:
        return [span.to_dict() for _txn_id, span in
                sorted(self.spans.items(), key=lambda kv: str(kv[0]))]
