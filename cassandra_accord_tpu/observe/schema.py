"""The canonical metric-name schema — the registry completeness contract.

Every wire ``MessageType`` and every ``Status``/``SaveStatus`` member has an
EXPLICIT entry here.  The dicts are written out (not derived from the enums)
on purpose: ``tests/test_observe.py`` asserts exact two-way agreement with
the enums, so a NEW message type or status phase cannot ship unobserved —
adding the enum member without a metric name fails tier-1, and a stale entry
for a removed/renamed member fails it too.
"""
from __future__ import annotations

# -- message plane (messages/base.py MessageType) ----------------------------

MESSAGE_METRICS = {
    "SIMPLE_RSP": "msg.simple_rsp",
    "FAILURE_RSP": "msg.failure_rsp",
    "PRE_ACCEPT_REQ": "msg.pre_accept_req",
    "PRE_ACCEPT_RSP": "msg.pre_accept_rsp",
    "ACCEPT_REQ": "msg.accept_req",
    "ACCEPT_RSP": "msg.accept_rsp",
    "ACCEPT_INVALIDATE_REQ": "msg.accept_invalidate_req",
    "GET_DEPS_REQ": "msg.get_deps_req",
    "GET_DEPS_RSP": "msg.get_deps_rsp",
    "GET_EPHEMERAL_READ_DEPS_REQ": "msg.get_ephemeral_read_deps_req",
    "GET_EPHEMERAL_READ_DEPS_RSP": "msg.get_ephemeral_read_deps_rsp",
    "GET_MAX_CONFLICT_REQ": "msg.get_max_conflict_req",
    "GET_MAX_CONFLICT_RSP": "msg.get_max_conflict_rsp",
    "COMMIT_SLOW_PATH_REQ": "msg.commit_slow_path_req",
    "COMMIT_MAXIMAL_REQ": "msg.commit_maximal_req",
    "STABLE_FAST_PATH_REQ": "msg.stable_fast_path_req",
    "STABLE_SLOW_PATH_REQ": "msg.stable_slow_path_req",
    "STABLE_MAXIMAL_REQ": "msg.stable_maximal_req",
    "COMMIT_INVALIDATE_REQ": "msg.commit_invalidate_req",
    "APPLY_MINIMAL_REQ": "msg.apply_minimal_req",
    "APPLY_MAXIMAL_REQ": "msg.apply_maximal_req",
    "APPLY_RSP": "msg.apply_rsp",
    "READ_REQ": "msg.read_req",
    "READ_EPHEMERAL_REQ": "msg.read_ephemeral_req",
    "READ_RSP": "msg.read_rsp",
    "BEGIN_RECOVER_REQ": "msg.begin_recover_req",
    "BEGIN_RECOVER_RSP": "msg.begin_recover_rsp",
    "BEGIN_INVALIDATE_REQ": "msg.begin_invalidate_req",
    "BEGIN_INVALIDATE_RSP": "msg.begin_invalidate_rsp",
    "WAIT_ON_COMMIT_REQ": "msg.wait_on_commit_req",
    "WAIT_ON_COMMIT_RSP": "msg.wait_on_commit_rsp",
    "WAIT_UNTIL_APPLIED_REQ": "msg.wait_until_applied_req",
    "APPLY_THEN_WAIT_UNTIL_APPLIED_REQ":
        "msg.apply_then_wait_until_applied_req",
    "RECOVER_AWAIT_REQ": "msg.recover_await_req",
    "CHECK_STATUS_REQ": "msg.check_status_req",
    "CHECK_STATUS_RSP": "msg.check_status_rsp",
    "FETCH_DATA_REQ": "msg.fetch_data_req",
    "FETCH_DATA_RSP": "msg.fetch_data_rsp",
    "SET_SHARD_DURABLE_REQ": "msg.set_shard_durable_req",
    "SET_GLOBALLY_DURABLE_REQ": "msg.set_globally_durable_req",
    "QUERY_DURABLE_BEFORE_REQ": "msg.query_durable_before_req",
    "QUERY_DURABLE_BEFORE_RSP": "msg.query_durable_before_rsp",
    "INFORM_OF_TXN_REQ": "msg.inform_of_txn_req",
    "FIND_ROUTE_REQ": "msg.find_route_req",
    "FIND_ROUTE_RSP": "msg.find_route_rsp",
    "INFORM_DURABLE_REQ": "msg.inform_durable_req",
    "INFORM_HOME_DURABLE_REQ": "msg.inform_home_durable_req",
    "PROPAGATE_PRE_ACCEPT_MSG": "msg.propagate_pre_accept_msg",
    "PROPAGATE_STABLE_MSG": "msg.propagate_stable_msg",
    "PROPAGATE_APPLY_MSG": "msg.propagate_apply_msg",
    "PROPAGATE_OTHER_MSG": "msg.propagate_other_msg",
}

# -- txn status lattice (local/status.py) ------------------------------------

STATUS_METRICS = {
    "NOT_DEFINED": "txn.status.not_defined",
    "PRE_ACCEPTED": "txn.status.pre_accepted",
    "ACCEPTED_INVALIDATE": "txn.status.accepted_invalidate",
    "ACCEPTED": "txn.status.accepted",
    "PRE_COMMITTED": "txn.status.pre_committed",
    "COMMITTED": "txn.status.committed",
    "STABLE": "txn.status.stable",
    "PRE_APPLIED": "txn.status.pre_applied",
    "APPLIED": "txn.status.applied",
    "TRUNCATED": "txn.status.truncated",
    "INVALIDATED": "txn.status.invalidated",
}

SAVE_STATUS_METRICS = {
    "NOT_DEFINED": "txn.save_status.not_defined",
    "PRE_ACCEPTED": "txn.save_status.pre_accepted",
    "ACCEPTED_INVALIDATE": "txn.save_status.accepted_invalidate",
    "ACCEPTED": "txn.save_status.accepted",
    "PRE_COMMITTED": "txn.save_status.pre_committed",
    "COMMITTED": "txn.save_status.committed",
    "STABLE": "txn.save_status.stable",
    "READY_TO_EXECUTE": "txn.save_status.ready_to_execute",
    "PRE_APPLIED": "txn.save_status.pre_applied",
    "APPLYING": "txn.save_status.applying",
    "APPLIED": "txn.save_status.applied",
    "TRUNCATED_APPLY": "txn.save_status.truncated_apply",
    "ERASED": "txn.save_status.erased",
    "INVALIDATED": "txn.save_status.invalidated",
}

# -- coordinator-side resolution classes (harness/burn.py resolve kinds) -----
# Every submitted op resolves as exactly ONE of these; the flight recorder's
# span accounting asserts sum(outcomes) == submitted (tier-1).

OUTCOMES = ("fast", "slow", "recovered", "invalidated", "lost", "failed")
OUTCOME_METRICS = {o: f"txn.resolved.{o}" for o in OUTCOMES}
# the outcome classes that ARE commits — the critical-path extractor's
# admission set and the windowed commits/s curve sum over the same tuple
COMMIT_OUTCOMES = ("fast", "slow", "recovered")

SUBMITTED_METRIC = "txn.submitted"
LATENCY_METRIC = "txn.latency_us"

# -- device data plane (impl/tpu_resolver.py counters) -----------------------

RESOLVER_COUNTERS = ("prefetch_hits", "prefetch_patched", "prefetch_misses",
                     "walk_consults", "host_consults", "native_consults",
                     "device_consults", "service_submitted", "service_batches")
RESOLVER_METRICS = {c: f"resolver.{c}" for c in RESOLVER_COUNTERS}

# -- persistent batched device consult service (device_service/) -------------
# per-store gauges collected from DeviceConsultService.stats(); the
# batch-size distribution additionally lands in a sim-registry histogram and
# the queue-depth/batch-rows samples become Chrome-trace counter tracks
SERVICE_STAT_METRICS = {
    "submitted": "service.submitted",
    "answered": "service.answered",
    "oneshot_rows": "service.oneshot_rows",
    "batches": "service.batches",
    "dropped_windows": "service.dropped_windows",
    # NOTE: dispatch_mean_s/dispatch_max_s (wall-clock) stay OUT of the
    # registry on purpose — snapshots are diffed across same-seed runs and
    # must not carry always-differing wall-clock floats; the bench and the
    # replay harness read them from DeviceConsultService.stats() directly
    "mean_batch_rows": "service.mean_batch_rows",
    "window_occupancy": "service.window_occupancy",
    "jit_shapes": "service.jit_shapes",
    "index_full_uploads": "service.index_full_uploads",
    "index_incremental_refreshes": "service.index_incremental_refreshes",
    "index_rows_uploaded": "service.index_rows_uploaded",
    "samples_dropped": "service.samples_dropped",
}
SERVICE_BATCH_SIZE_METRIC = "service.batch_size"


# -- store-scope pull-collected gauges (FlightRecorder.collect_cluster) ------

STORE_GAUGE_METRICS = {
    "commands": "store.commands",
    "cold": "store.cold",
    "exec_deferred": "store.exec_deferred",
    "cache_miss_loads": "store.cache_miss_loads",
    "tfk_inversions": "store.tfk_inversions",
}

# -- timeline-only series (observe/timeline.py; never in the registry) -------
# the windowed in-flight gauge is maintained by the flight recorder's own
# submit/resolve envelope (submitted - resolved), sampled into the timeline

TIMELINE_IN_FLIGHT_METRIC = "txn.in_flight"

# -- timeline policy declarations ---------------------------------------------
# Every metric the schema registers declares how the sim-time timeline
# (observe/timeline.py) treats it — its TIMELINE POLICY:
#
#   ``rate``       event-stream counter: per-window increment count + rate/s
#   ``sample``     gauge: last value observed inside each window
#   ``percentile`` value stream: per-window exact p50/p95/p99 (nearest-rank)
#   ``excluded``   no per-event stream exists (end-of-run pull-collected
#                  gauges) or the series is deliberately not windowed
#
# Two-way linted (tests/test_observe.py) against the metric tables above,
# exactly like METRIC_UNITS: a new schema metric without a policy fails
# tier-1, and so does a stale policy entry for a removed metric.  The
# Timeline enforces the declaration at feed time — feeding an ``excluded``
# metric, or feeding with the wrong verb, raises.

TIMELINE_POLICY_VALUES = ("rate", "sample", "percentile", "excluded")

TIMELINE_POLICIES = {
    SUBMITTED_METRIC: "rate",
    LATENCY_METRIC: "percentile",
    TIMELINE_IN_FLIGHT_METRIC: "sample",
    SERVICE_BATCH_SIZE_METRIC: "percentile",
    **{name: "rate" for name in OUTCOME_METRICS.values()},
    # pull-collected end-of-run gauges: there is no per-event stream to
    # window (the consult-service QUEUE trajectory is windowed separately
    # from its deterministic (ts, depth, rows) samples — timeline.py
    # service_window_records)
    **{name: "excluded" for name in RESOLVER_METRICS.values()},
    **{name: "excluded" for name in SERVICE_STAT_METRICS.values()},
    **{name: "excluded" for name in STORE_GAUGE_METRICS.values()},
}

# dynamic metric families resolve by prefix (same pattern as
# METRIC_UNIT_PREFIXES); explicit entries take precedence
TIMELINE_POLICY_PREFIXES = {
    "msg.": "rate",              # MESSAGE_METRICS + msg.received/unregistered
    "link.": "rate",
    "net.": "rate",
    "txn.status.": "rate",
    "txn.save_status.": "rate",
    "txn.path.": "rate",
    "txn.fastpath.": "rate",
    "recovery.": "rate",
    "progress.": "rate",
    "lifecycle.": "rate",
    "slo.": "rate",              # burn-rate monitor firings (observe/burnrate)
    "overload.": "rate",         # admission nacks/sheds + retry-budget
                                 # denials (local/overload.py, harness/burn)
    "audit.": "excluded",        # violation counters: forensic, not windowed
    "sim.": "excluded",          # pull-collected cluster.stats mirror
}


def timeline_policy_for(metric_name: str) -> str:
    """Declared timeline policy for a metric; KeyError (with the fix) for an
    undeclared one — the lint test turns that into a tier-1 failure."""
    policy = TIMELINE_POLICIES.get(metric_name)
    if policy is not None:
        return policy
    for prefix, policy in TIMELINE_POLICY_PREFIXES.items():
        if metric_name.startswith(prefix):
            return policy
    raise KeyError(
        f"metric {metric_name!r} declares no timeline policy: add it to "
        f"observe/schema.py TIMELINE_POLICIES "
        f"(rate | sample | percentile | excluded)")


# -- unit / time-plane declarations -------------------------------------------
# Every HISTOGRAM and GAUGE metric declares its unit, which doubles as its
# time-plane declaration: ``sim_s`` values are simulated time (deterministic,
# diffable across same-seed runs), ``wall_s`` is host time (NEVER allowed in
# the registry — snapshots are diffed across same-seed runs; the wall plane
# lives in observe/profiler.py reports), ``bytes`` / ``count`` are plane-free
# magnitudes.  Two-way linted (tests/test_observe.py) against the metric
# tables above, exactly like the MessageType / SaveStatus completeness
# checks: a new gauge/histogram without a unit fails tier-1, and so does a
# stale unit entry for a removed metric.  ``sim.*`` gauges mirror dynamic
# simulator-stat keys (message-class counts, fault injections) and are
# covered by the prefix table.

UNITS = ("sim_s", "wall_s", "bytes", "count")

METRIC_UNITS = {
    LATENCY_METRIC: "sim_s",
    SERVICE_BATCH_SIZE_METRIC: "count",
    **{name: "count" for name in RESOLVER_METRICS.values()},
    **{name: "count" for name in SERVICE_STAT_METRICS.values()},
    **{name: "count" for name in STORE_GAUGE_METRICS.values()},
}

METRIC_UNIT_PREFIXES = {
    "sim.": "count",        # pull-collected cluster.stats mirror (dynamic)
}


def unit_for(metric_name: str) -> str:
    """Declared unit/time-plane for a gauge or histogram metric; KeyError
    (with the fix) for an undeclared one — the lint test turns that into a
    tier-1 failure."""
    unit = METRIC_UNITS.get(metric_name)
    if unit is not None:
        return unit
    for prefix, unit in METRIC_UNIT_PREFIXES.items():
        if metric_name.startswith(prefix):
            return unit
    raise KeyError(
        f"metric {metric_name!r} declares no unit/time plane: add it to "
        f"observe/schema.py METRIC_UNITS (sim_s | wall_s | bytes | count)")


def metric_for_message(type_name: str) -> str:
    """Registry name for a MessageType member; KeyError (with the fix) for an
    unregistered one — the lint test turns that into a tier-1 failure."""
    try:
        return MESSAGE_METRICS[type_name]
    except KeyError:
        raise KeyError(
            f"MessageType.{type_name} has no metric name: add it to "
            f"observe/schema.py MESSAGE_METRICS") from None


def metric_for_save_status(status_name: str) -> str:
    try:
        return SAVE_STATUS_METRICS[status_name]
    except KeyError:
        raise KeyError(
            f"SaveStatus.{status_name} has no metric name: add it to "
            f"observe/schema.py SAVE_STATUS_METRICS") from None
