"""Multi-window SLO burn-rate monitors: mid-run early warning for soaks.

The auditor's liveness-SLO plane and the watchdog both answer AFTER the fact
— a flag names a stuck txn once its budget lapses, the watchdog kills the
burn once NOTHING has resolved for minutes.  Large-cluster soak burns need
the signal in between: *the error budget is burning fast enough that this
run is headed for a wedge*, minutes before the watchdog's exit.

This is the classic SRE multi-window burn-rate construction, transplanted
onto SIMULATED time so it stays deterministic:

- an SLO defines which events are BAD (a commit slower than the latency SLO,
  an auditor liveness flag opening) against a stream of GOOD events (commits
  inside the SLO);
- the **burn rate** over a window is ``bad_fraction / error_budget`` — 1.0
  means the budget burns exactly at its sustainable rate, 10 means ten times
  too fast;
- a monitor fires only when BOTH a short window and a long window exceed the
  threshold (the standard two-window guard: the long window proves it is not
  a blip, the short window proves it is still happening), with a minimum
  bad-event count so a single unlucky txn cannot page.

Every fired episode is a deterministic ``slo.burn`` event (sim-timestamped,
opened/cleared like the auditor's flags): it lands in the monitor's event
list, in the registry as an ``slo.burn.<name>`` counter, on the timeline
(when one is attached) as a windowed rate, in the auditor's ``verdict()``
(the burn CLI's ``--json`` warn stream), and in the watchdog's stall dump.
``tests/test_burnrate.py`` proves the acceptance shape: on an injected
journal-stall wedge the monitor fires strictly earlier (sim time) than the
watchdog's stall exit, and it stays silent across the clean matrix.

Zero observer effect: the monitor consumes sim-timestamps and outcomes the
recorder hooks already carry — no RNG, no wall clock, no scheduling.
"""
from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Optional, Tuple


class SloSpec:
    """One SLO and its burn-rate alerting policy.

    ``kind``: ``"latency"`` — resolutions are the event stream, bad when the
    commit latency exceeds ``latency_slo_us`` (or the op failed outright);
    ``"liveness"`` — bad pressure is the count of CURRENTLY-OPEN auditor
    SLO flags (a flag opens once but a wedge holds it open — the open set,
    not the opening edge, is the sustained signal), judged against the
    windowed resolution stream as the good events: a wedge starves the good
    stream while flags stay open, driving the bad fraction to 1.0 in the
    short window first and in the long window once the pre-wedge
    resolutions age out."""

    __slots__ = ("name", "kind", "budget", "short_us", "long_us",
                 "burn_threshold", "min_bad", "latency_slo_us")

    def __init__(self, name: str, kind: str, budget: float,
                 short_s: float = 5.0, long_s: float = 30.0,
                 burn_threshold: float = 10.0, min_bad: int = 3,
                 latency_slo_us: int = 5_000_000):
        assert kind in ("latency", "liveness"), kind
        assert 0.0 < budget < 1.0, "budget is an error fraction"
        assert short_s < long_s, "the short window must be shorter"
        self.name = name
        self.kind = kind
        self.budget = budget
        self.short_us = int(short_s * 1_000_000)
        self.long_us = int(long_s * 1_000_000)
        self.burn_threshold = burn_threshold
        self.min_bad = min_bad
        self.latency_slo_us = latency_slo_us


# Defaults tuned for burn-harness scale (sim-seconds, tens-to-hundreds of
# ops): the latency SLO allows 5 sim-seconds per commit with a 5% budget —
# benign runs sit orders of magnitude below it — and the liveness SLO burns
# on auditor flag openings against a 2% budget.  A threshold of 10 with both
# windows agreeing means the budget is burning >= 10x too fast NOW and has
# been for a full long window.
DEFAULT_SLOS = (
    SloSpec("commit_latency", "latency", budget=0.05,
            short_s=5.0, long_s=30.0, burn_threshold=10.0, min_bad=5,
            latency_slo_us=5_000_000),
    SloSpec("liveness", "liveness", budget=0.02,
            short_s=5.0, long_s=30.0, burn_threshold=10.0, min_bad=3),
)


class BurnRateMonitor:
    """Deterministic multi-window burn-rate evaluation over recorder hooks.

    Attach via ``FlightRecorder(burnrate=BurnRateMonitor())`` (or the burn
    CLI's ``--burnrate``); the recorder feeds resolutions, the auditor feeds
    flag openings, and every message event pulses the sim clock so the
    monitor can evaluate between resolutions (a total wedge produces no
    resolutions at all — the probes and timeouts still pulse)."""

    def __init__(self, specs: Tuple[SloSpec, ...] = DEFAULT_SLOS):
        self.specs = tuple(specs)
        # per spec: deque of (sim_us, is_bad) pruned to the long window
        self._events: Dict[str, Deque[Tuple[int, bool]]] = {
            s.name: deque() for s in self.specs}
        self.events: List[dict] = []          # fired slo.burn episodes
        self._open: Dict[str, dict] = {}      # name -> currently-burning event
        self._open_flags: Dict[str, int] = {}  # auditor flag kind -> open count
        self._next_check_us: Optional[int] = None
        self._recorder = None                 # bound by FlightRecorder

    # -- wiring ---------------------------------------------------------------
    def bind(self, recorder) -> None:
        self._recorder = recorder

    # -- feeding (recorder/auditor hooks) -------------------------------------
    def on_resolution(self, outcome: str, latency_us: Optional[int],
                      now_us: int) -> None:
        for spec in self.specs:
            if spec.kind == "latency":
                bad = outcome == "failed" or (
                    latency_us is not None and latency_us > spec.latency_slo_us)
                self._events[spec.name].append((now_us, bad))
            else:   # liveness: resolutions are the GOOD stream
                self._events[spec.name].append((now_us, False))
        # Resolutions are the hot path (hundreds/sim-s at drain): the event
        # is recorded above regardless, so evaluation can ride the same
        # cadence guard as on_pulse instead of rescanning the windows on
        # every commit.  Flag edges stay immediate — they are rare and an
        # open/close can change the verdict by itself.
        if self._next_check_us is not None and now_us < self._next_check_us:
            return
        self._check(now_us)

    def on_flag_opened(self, flag_kind: str, now_us: int) -> None:
        """An auditor liveness-SLO flag opened (slo.unattended / undecided /
        unapplied): the open-flag pressure the liveness SLOs burn on."""
        self._open_flags[flag_kind] = self._open_flags.get(flag_kind, 0) + 1
        self._check(now_us)

    def on_flag_closed(self, flag_kind: str, now_us: int) -> None:
        """The flag's condition cleared (decided / applied / resolved)."""
        count = self._open_flags.get(flag_kind, 0)
        if count > 1:
            self._open_flags[flag_kind] = count - 1
        else:
            self._open_flags.pop(flag_kind, None)
        self._check(now_us)

    def on_pulse(self, now_us: int) -> None:
        """Clock pulse from the message plane: evaluate if the check cadence
        elapsed (cheap guard — one integer compare on the hot path)."""
        if self._next_check_us is not None and now_us < self._next_check_us:
            return
        self._check(now_us)

    # -- evaluation -----------------------------------------------------------
    def _rates(self, spec: SloSpec, now_us: int) -> Tuple[float, float, int]:
        """(short_burn_rate, long_burn_rate, bad_count) for one spec.

        ``latency``: both counts come from the windowed event stream.
        ``liveness``: bad is the INSTANTANEOUS open-flag count (state, not
        an edge — it applies to both windows), good the windowed
        resolutions."""
        events = self._events[spec.name]
        long_lo = now_us - spec.long_us
        while events and events[0][0] < long_lo:
            events.popleft()
        short_lo = now_us - spec.short_us
        good_l = bad_l = good_s = bad_s = 0
        for ts, is_bad in events:
            if is_bad:
                bad_l += 1
                if ts >= short_lo:
                    bad_s += 1
            else:
                good_l += 1
                if ts >= short_lo:
                    good_s += 1
        if spec.kind == "liveness":
            open_flags = sum(self._open_flags.values())
            bad_s = bad_l = open_flags

        def burn(bad, good):
            total = bad + good
            if not total:
                return 0.0
            return (bad / total) / spec.budget
        return burn(bad_s, good_s), burn(bad_l, good_l), bad_s

    def _check(self, now_us: int) -> None:
        min_short = min(s.short_us for s in self.specs)
        self._next_check_us = now_us + max(min_short // 4, 1)
        for spec in self.specs:
            short, long_, bad_s = self._rates(spec, now_us)
            burning = (short >= spec.burn_threshold
                       and long_ >= spec.burn_threshold
                       and bad_s >= spec.min_bad)
            open_ev = self._open.get(spec.name)
            if burning and open_ev is None:
                event = {"kind": "slo.burn", "slo": spec.name,
                         "sim_us": now_us,
                         "short_burn_rate": round(short, 2),
                         "long_burn_rate": round(long_, 2),
                         "short_window_s": spec.short_us / 1e6,
                         "long_window_s": spec.long_us / 1e6,
                         "burn_threshold": spec.burn_threshold,
                         "cleared_us": None}
                self._open[spec.name] = event
                self.events.append(event)
                self._emit(spec, now_us)
            elif not burning and open_ev is not None:
                open_ev["cleared_us"] = now_us
                del self._open[spec.name]

    def _emit(self, spec: SloSpec, now_us: int) -> None:
        """Fan the firing out to the recorder's other planes (registry
        counter, timeline rate) — all deterministic bookkeeping."""
        rec = self._recorder
        if rec is None:
            return
        rec.registry.counter(f"slo.burn.{spec.name}").inc()
        timeline = getattr(rec, "timeline", None)
        if timeline is not None:
            timeline.count(f"slo.burn.{spec.name}", now_us)

    # -- reporting ------------------------------------------------------------
    def open_burns(self) -> List[dict]:
        return [dict(e) for e in self._open.values()]

    def report(self) -> dict:
        """Plane summary for verdicts / stall dumps."""
        return {
            "slo_burn_events": len(self.events),
            "open_slo_burns": sorted(self._open),
            "first_slo_burn": dict(self.events[0]) if self.events else None,
            "last_slo_burn": dict(self.events[-1]) if self.events else None,
        }
