"""Sim-time windowed telemetry: the TRAJECTORY plane of the observability
stack.

Every existing plane (registry snapshots, spans, critical-path budgets, the
auditor verdict) answers questions as WHOLE-RUN aggregates.  Scale questions
are trajectory questions — does ``commits_per_sec`` climb with concurrency or
flatline, does a 15-node elastic soak degrade minutes before the watchdog
fires, does device-service batch occupancy actually fill the windows that
amortize dispatch — so this module derives, from the very same flight-recorder
hook stream, fixed-width SIM-TIME windows in which

- **counters become per-window rates** (count + count/window-seconds),
- **gauges become samples** (last value observed inside the window),
- **value streams become per-window exact percentiles** (nearest-rank
  p50/p95/p99 over the raw values recorded in the window — EXACT, unlike the
  registry histogram's conservative bucket bounds, because a window holds few
  enough values to keep raw).

Windows are scoped exactly like the registry (``cluster`` / ``node/<id>`` /
``store/<node>/<store>``) and ring-bounded (``keep_windows``): a soak keeps
the recent trajectory — the windows INTO a stall — while memory stays flat.

Every metric's treatment is DECLARED in ``observe/schema.py``
(``TIMELINE_POLICIES``: ``rate | sample | percentile | excluded``), two-way
linted like ``METRIC_UNITS``, and enforced at feed time: feeding an
``excluded`` metric, or feeding with the wrong verb, raises.

Zero observer effect, by construction: the ``Timeline`` is plain host-side
bookkeeping fed sim-timestamps the instrumented code already computed — no
RNG, no wall clock, no scheduling.  ``tests/test_timeline.py`` proves it the
same way PR 3 proved the recorder: same-seed hostile burn, timelines on vs
off, byte-identical message traces.

Export surfaces: ``write_timeline_jsonl`` (the burn CLI's ``--timeline-out``
artifact — one JSON line per window plus consult-service trajectory windows
derived from the service's deterministic samples), Perfetto per-window
counter tracks (``observe/export.timeline_counter_events``), and the
watchdog's stall dump, which embeds the last-N windows — the trajectory into
the stall, not just the final snapshot.
"""
from __future__ import annotations

import json
import math
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import schema
from .registry import MetricsRegistry

DEFAULT_WINDOW_US = 1_000_000          # 1 sim-second
DEFAULT_KEEP_WINDOWS = 512             # ring bound (soaks keep the tail)
DEFAULT_VALUES_PER_WINDOW = 4096       # raw-value cap per (scope, metric)

# the outcome classes whose per-window rates sum to "commits per second"
COMMIT_OUTCOMES = schema.COMMIT_OUTCOMES


def exact_percentile(sorted_values: List[int], q: float) -> Optional[int]:
    """Nearest-rank percentile over an ALREADY-SORTED list (exact, unlike
    ``Histogram.snapshot_percentile``'s bucket upper bound): the smallest
    value with at least ``ceil(q * n)`` values at or below it."""
    n = len(sorted_values)
    if not n:
        return None
    rank = max(1, math.ceil(q * n))
    return sorted_values[min(rank, n) - 1]


class Timeline:
    """Fixed-width sim-time windows over the flight-recorder event stream."""

    __slots__ = ("window_us", "keep_windows", "values_per_window",
                 "_finalized", "dropped_windows", "_open_idx", "_counts",
                 "_samples", "_values", "_value_overflow", "_policy_memo")

    def __init__(self, window_us: int = DEFAULT_WINDOW_US,
                 keep_windows: int = DEFAULT_KEEP_WINDOWS,
                 values_per_window: int = DEFAULT_VALUES_PER_WINDOW):
        assert window_us > 0, "window width must be positive sim-micros"
        self.window_us = int(window_us)
        self.keep_windows = keep_windows
        self.values_per_window = values_per_window
        self._finalized: deque = deque()
        self.dropped_windows = 0
        self._open_idx: Optional[int] = None
        # open-window accumulators, keyed (scope, metric)
        self._counts: Dict[Tuple[str, str], int] = {}
        self._samples: Dict[Tuple[str, str], object] = {}
        self._values: Dict[Tuple[str, str], List[int]] = {}
        self._value_overflow: Dict[Tuple[str, str], int] = {}
        # metric -> policy, memoized (the schema lookup walks a prefix table)
        self._policy_memo: Dict[str, str] = {}

    # -- policy enforcement --------------------------------------------------
    def _policy(self, name: str) -> str:
        policy = self._policy_memo.get(name)
        if policy is None:
            policy = schema.timeline_policy_for(name)
            self._policy_memo[name] = policy
        return policy

    def _check(self, name: str, verb: str) -> None:
        policy = self._policy(name)
        if policy != verb:
            raise ValueError(
                f"metric {name!r} declares timeline policy {policy!r} but was "
                f"fed as {verb!r} (observe/schema.py TIMELINE_POLICIES is the "
                f"contract)")

    # -- feeding (called from FlightRecorder hooks) --------------------------
    def count(self, name: str, now_us: int, n: int = 1,
              node: Optional[int] = None, store: Optional[int] = None) -> None:
        self._check(name, "rate")
        self._roll(now_us)
        key = (MetricsRegistry.scope(node, store), name)
        self._counts[key] = self._counts.get(key, 0) + n

    def sample(self, name: str, value, now_us: int,
               node: Optional[int] = None, store: Optional[int] = None) -> None:
        self._check(name, "sample")
        self._roll(now_us)
        self._samples[(MetricsRegistry.scope(node, store), name)] = value

    def value(self, name: str, v: int, now_us: int,
              node: Optional[int] = None, store: Optional[int] = None) -> None:
        self._check(name, "percentile")
        self._roll(now_us)
        key = (MetricsRegistry.scope(node, store), name)
        values = self._values.get(key)
        if values is None:
            values = self._values[key] = []
        if len(values) >= self.values_per_window:
            self._value_overflow[key] = self._value_overflow.get(key, 0) + 1
            return
        values.append(v)

    # -- windowing -----------------------------------------------------------
    def _roll(self, now_us: int) -> None:
        idx = now_us // self.window_us
        if self._open_idx is None:
            self._open_idx = idx
            return
        if idx == self._open_idx:
            return
        # sim time is globally monotone; a lower index would mean a hook fed
        # a stale timestamp — fold it into the open window rather than
        # corrupting the ring with out-of-order records
        if idx < self._open_idx:
            return
        self._finalize_open()
        self._open_idx = idx   # gaps stay gaps: indices are explicit in the
        #                        records, so quiet sim-seconds cost nothing

    def _render_open(self) -> Optional[dict]:
        if self._open_idx is None:
            return None
        idx = self._open_idx
        window_s = self.window_us / 1e6
        scopes: Dict[str, dict] = {}
        for (scope, name), n in sorted(self._counts.items()):
            s = scopes.setdefault(scope, {})
            s.setdefault("counts", {})[name] = n
            s.setdefault("rates_per_s", {})[name] = round(n / window_s, 3)
        for (scope, name), v in sorted(self._samples.items()):
            scopes.setdefault(scope, {}).setdefault("samples", {})[name] = v
        for (scope, name), values in sorted(self._values.items()):
            vals = sorted(values)
            overflow = self._value_overflow.get((scope, name), 0)
            entry = {"count": len(vals) + overflow,
                     "p50": exact_percentile(vals, 0.50),
                     "p95": exact_percentile(vals, 0.95),
                     "p99": exact_percentile(vals, 0.99),
                     "min": vals[0] if vals else None,
                     "max": vals[-1] if vals else None}
            if overflow:
                entry["values_dropped"] = overflow
            scopes.setdefault(scope, {}).setdefault("percentiles", {})[name] \
                = entry
        return {"window": int(idx),
                "start_us": int(idx * self.window_us),
                "end_us": int((idx + 1) * self.window_us),
                "scopes": scopes}

    def _finalize_open(self) -> None:
        rec = self._render_open()
        if rec is None:
            return
        self._finalized.append(rec)
        if len(self._finalized) > self.keep_windows:
            self._finalized.popleft()
            self.dropped_windows += 1
        self._counts.clear()
        self._samples.clear()
        self._values.clear()
        self._value_overflow.clear()

    # -- reading -------------------------------------------------------------
    def records(self, include_open: bool = True) -> List[dict]:
        """Finalized window records, oldest first; ``include_open`` renders
        the currently-open window too (without mutating state — safe from a
        mid-run watchdog dump)."""
        out = list(self._finalized)
        if include_open:
            rec = self._render_open()
            if rec is not None:
                out.append(rec)
        return out

    def series(self, name: str, scope: str = "cluster",
               field: str = "rates_per_s") -> List[Tuple[int, object]]:
        """One metric's windowed series as [(window_index, value)] — the
        plotting/test accessor."""
        out = []
        for rec in self.records():
            value = rec["scopes"].get(scope, {}).get(field, {}).get(name)
            if value is not None:
                out.append((rec["window"], value))
        return out


def commits_per_sec_series(records: List[dict]) -> List[Tuple[int, float]]:
    """The windowed commits/s curve: the sum of per-window resolution rates
    over the commit outcome classes (fast + slow + recovered)."""
    names = [schema.OUTCOME_METRICS[o] for o in COMMIT_OUTCOMES]
    out = []
    for rec in records:
        rates = rec["scopes"].get("cluster", {}).get("rates_per_s", {})
        vals = [rates[n] for n in names if n in rates]
        if vals:
            out.append((rec["window"], round(sum(vals), 3)))
    return out


def service_window_records(recorder, window_us: int) -> List[dict]:
    """Consult-service trajectory windows derived POST-HOC from the
    deterministic (sim_ts, queue_depth, batch_rows) samples the recorder
    pull-collected out of every engaged DeviceConsultService — the
    queue-depth / batch-occupancy over-time series ROADMAP item 1's window
    tuning loop reads.  No runtime ingestion: samples are bucketed at export
    time, so the zero-observer-effect contract is untouched."""
    samples = getattr(recorder, "_service_samples", None)
    if not samples:
        return []
    by_window: Dict[int, List[Tuple[int, int]]] = {}
    for ts, depth, rows in samples:
        by_window.setdefault(ts // window_us, []).append((depth, rows))
    out = []
    for idx in sorted(by_window):
        entries = by_window[idx]
        depths = [d for d, _ in entries]
        rows = [r for _, r in entries]
        out.append({"kind": "service_window", "window": int(idx),
                    "start_us": int(idx * window_us),
                    "end_us": int((idx + 1) * window_us),
                    "dispatches": len(entries),
                    "queue_depth_max": max(depths),
                    "batch_rows_max": max(rows),
                    "batch_rows_mean": round(sum(rows) / len(rows), 2)})
    return out


def write_timeline_jsonl(path: str, recorder) -> None:
    """The ``--timeline-out`` artifact: a header line, one JSON line per
    telemetry window, then the consult-service trajectory windows.  JSONL so
    soak-length series stream through ``jq`` without loading whole."""
    timeline = getattr(recorder, "timeline", None)
    if timeline is None:
        raise ValueError("recorder has no timeline attached "
                         "(FlightRecorder(timeline=Timeline(...)))")
    records = timeline.records(include_open=True)
    with open(path, "w") as f:
        header = {"kind": "header", "schema": "accord-timeline/1",
                  "window_us": timeline.window_us,
                  "windows": len(records),
                  "windows_dropped": timeline.dropped_windows}
        f.write(json.dumps(header, sort_keys=True) + "\n")
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
        for rec in service_window_records(recorder, timeline.window_us):
            f.write(json.dumps(rec, sort_keys=True) + "\n")
