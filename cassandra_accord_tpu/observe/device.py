"""Device data-plane metrics: one source for resolver counters and kernel
roofline accounting.

The TPU deps resolver's ad-hoc counters (consult tier choices, prefetch
hit/miss/patch) and the kernel-level roofline numbers (join FLOPs, index
bytes, MFU vs peak) previously lived in two places — burn-result stats and
``bench.py`` JSON tails — with the formulas duplicated.  Both now report
through here.
"""
from __future__ import annotations

from typing import Dict, Optional

from .schema import (RESOLVER_COUNTERS, RESOLVER_METRICS,
                     SERVICE_BATCH_SIZE_METRIC, SERVICE_STAT_METRICS)

# one v5p-class chip's bf16 matmul peak, the MFU denominator bench.py reports
PEAK_BF16_TFLOPS = 275.0


def resolver_counters(resolver) -> Optional[Dict[str, int]]:
    """The standard counter dict for one store's resolver (unwrapping the
    verify resolver to its device half), or None when the store runs a plain
    host resolver with no telemetry."""
    r = getattr(resolver, "tpu", resolver)
    if not hasattr(r, RESOLVER_COUNTERS[0]):
        return None
    return {name: getattr(r, name) for name in RESOLVER_COUNTERS}


def cluster_resolver_totals(cluster) -> Dict[str, int]:
    """Sum of every store's resolver counters (the burn-result telemetry
    block).  Zero-filled keys when no telemetry-bearing resolver exists so
    callers can test ``any(tel.values())``."""
    totals = {name: 0 for name in RESOLVER_COUNTERS}
    for node in cluster.nodes.values():
        for store in node.command_stores.all_stores():
            counters = resolver_counters(store.resolver)
            if counters is not None:
                for name, value in counters.items():
                    totals[name] += value
    return totals


def service_of(resolver):
    """The store's DeviceConsultService, if one was ever engaged (unwraps
    the verify resolver); None otherwise."""
    r = getattr(resolver, "tpu", resolver)
    return getattr(r, "_service_obj", None)


def cluster_services(cluster):
    """Every engaged per-store consult service in the cluster."""
    out = []
    for node in cluster.nodes.values():
        for store in node.command_stores.all_stores():
            svc = service_of(store.resolver)
            if svc is not None:
                out.append((node.id, store.id, svc))
    return out


def collect_into(registry, cluster) -> None:
    """Pull-collect per-store resolver counters (and cluster totals) into a
    MetricsRegistry as gauges under the schema's ``resolver.*`` names, plus
    the consult-service stats under ``service.*`` (queue/batching behavior:
    batch-size histogram, window occupancy, dispatch latency, refresh
    traffic)."""
    totals = {name: 0 for name in RESOLVER_COUNTERS}
    seen = False
    for node in cluster.nodes.values():
        for store in node.command_stores.all_stores():
            counters = resolver_counters(store.resolver)
            if counters is None:
                continue
            seen = True
            for name, value in counters.items():
                registry.gauge(RESOLVER_METRICS[name], node=node.id,
                               store=store.id).set(value)
                totals[name] += value
    if seen:
        for name, value in totals.items():
            registry.gauge(RESOLVER_METRICS[name]).set(value)
    for node_id, store_id, svc in cluster_services(cluster):
        stats = svc.stats()
        for name, metric in SERVICE_STAT_METRICS.items():
            registry.gauge(metric, node=node_id, store=store_id) \
                .set(stats[name])
        # batch sizes are bounded by the window row cap (default 256): pow2
        # bounds, NOT the sim-time latency defaults (everything would land
        # in the first 1000us bucket)
        hist = registry.histogram(SERVICE_BATCH_SIZE_METRIC, node=node_id,
                                  store=store_id,
                                  bounds=(1, 2, 4, 8, 16, 32, 64, 128, 256))
        # record only the DELTA since this service was last collected:
        # collect_cluster runs again on the failure path (and on any later
        # metrics_snapshot), and Histogram.record is additive
        reported = getattr(svc, "_hist_reported", None)
        if reported is None:
            reported = svc._hist_reported = {}
        for rows, count in svc.batch_size_hist.items():
            delta = count - reported.get(rows, 0)
            if delta > 0:
                hist.record_many(rows, delta)
            reported[rows] = count


# -- kernel roofline accounting (bench.py) -----------------------------------

def consult_join_flops(b: int, k: int, t: int) -> float:
    """Matmul FLOPs of one fused consult launch: a [B,K]x[K,T] join."""
    return 2.0 * b * k * t


def index_bytes_int8(t: int, k: int) -> int:
    """Resident bytes of the int8 incidence index (key_inc + live mirror)."""
    return 2 * t * k


def kernel_consult_metrics(t: int, k: int, b: int,
                           device_qps: float) -> Dict[str, float]:
    """Roofline block for one consult-kernel measurement: achieved join
    TFLOP/s and MFU against the chip's bf16 peak."""
    tflops = device_qps / b * consult_join_flops(b, k, t) / 1e12
    return {"index_bytes_int8": index_bytes_int8(t, k),
            "device_join_tflops": round(tflops, 4),
            "consult_mfu_vs_275tflops": round(tflops / PEAK_BF16_TFLOPS, 5)}


def launch_mfu(t: int, k: int, rows: int, seconds: float) -> Dict[str, float]:
    """Honest MFU of one measured consult launch (the wall profiler's
    per-launch plane): achieved join TFLOP/s of a [rows,K]x[K,T] join over
    its measured wall seconds, against the bf16 peak — same denominator as
    ``kernel_consult_metrics``, one formula source for bench and profiler."""
    tflops = consult_join_flops(max(rows, 1), k, t) / max(seconds, 1e-9) / 1e12
    return {"launch_join_tflops": round(tflops, 5),
            "launch_mfu_vs_275tflops": round(tflops / PEAK_BF16_TFLOPS, 7)}
