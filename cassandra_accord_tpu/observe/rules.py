"""The protocol-invariant rule catalog: the auditor's legal-edge table and
rule names.

Like ``observe/schema.py``, the tables here are written out EXPLICITLY (not
derived from the enums or the transition code) on purpose: the tier-1 lint
(``tests/test_audit.py``) asserts exact two-way agreement with the
``SaveStatus`` enum — every member must appear as a source and as a target of
at least one legal edge — so a new phase cannot ship unaudited, and a stale
entry for a removed member fails tier-1 too.

Edge provenance (each edge names the ``local/commands.py`` path that takes
it; the auditor flags anything else as ``RULE_ILLEGAL_EDGE``):

- ``preaccept``: NOT_DEFINED -> PRE_ACCEPTED.
- ``accept``: {NOT_DEFINED, PRE_ACCEPTED, ACCEPTED_INVALIDATE, ACCEPTED} ->
  ACCEPTED (the self-edge is a higher-ballot re-accept; the
  ACCEPTED_INVALIDATE source is a later-ballot Accept superseding an
  invalidation vote).
- ``accept_invalidate``: {NOT_DEFINED, PRE_ACCEPTED} -> ACCEPTED_INVALIDATE
  (guarded ``save_status < ACCEPTED_INVALIDATE``, so never from ACCEPTED+).
- ``precommit``: anything undecided -> PRE_COMMITTED.
- ``commit``: anything below the target tier (and not truncated/invalidated)
  -> COMMITTED / STABLE.
- ``maybe_execute``: STABLE -> READY_TO_EXECUTE; PRE_APPLIED -> APPLYING;
  ``_apply_writes`` then APPLYING -> APPLIED.
- ``apply_``: anything below PRE_APPLIED (not truncated/invalidated) ->
  PRE_APPLIED.
- ``commit_invalidate``: only NEVER-pre-committed states -> INVALIDATED (a
  decided txn arriving here is the agent-escalated "committed AND
  invalidated" impossibility, and is additionally caught cross-replica by
  ``RULE_COMMIT_INVALIDATE_CONFLICT``).
- ``truncate`` / ``adopt_truncated_outcome``: any pre-PRE_APPLIED state (the
  adoption guard) or APPLIED (GC) -> TRUNCATED_APPLY; the ERASE tier ->
  ERASED (GC of universally-durable applied txns; the never-committed
  below-fence erase; ``install_quarantine_tombstone``'s fresh tombstone).
- terminal self-edges (APPLIED, TRUNCATED_APPLY, ERASED, INVALIDATED,
  NOT_DEFINED): duplicate re-observations — journal replay re-reports a
  rebuilt command's tier, and repeated truncation refreshes a tombstone.
  They carry no state change and are explicitly legal.

Journal-replay semantics: a restart rebuilds a store from its durable tier,
which may sit ANYWHERE at or below the pre-crash status (the crash loses the
volatile tail).  The auditor therefore re-baselines a node's per-store
lifecycle state at ``crash`` and treats the replay window's first
re-observation of each txn as its new baseline rather than an edge.
"""
from __future__ import annotations

from ..local.status import SaveStatus

# -- rule names (the catalog; README "Auditing" documents each) --------------

RULE_ILLEGAL_EDGE = "save_status.illegal_edge"
RULE_EXECUTE_AT_MISMATCH = "commit.execute_at_mismatch"
RULE_EXECUTE_AT_MUTATED = "commit.execute_at_mutated"
RULE_DEPS_MISMATCH = "commit.deps_mismatch"
RULE_DEPS_MUTATED = "stable.deps_mutated"
RULE_COMMIT_INVALIDATE_CONFLICT = "commit.invalidate_conflict"
RULE_EXECUTE_AT_DUPLICATE = "commit.execute_at_not_unique"
RULE_BALLOT_REGRESSION = "ballot.regression"
RULE_KEY_EXECUTE_AT_ORDER = "key.execute_at_order"
RULE_DURABILITY_REGRESSION = "durability.watermark_regression"
RULE_EPOCH_REGRESSION = "epoch.regression"
RULE_SYNC_LEDGER_REGRESSION = "epoch.sync_ledger_regression"

SAFETY_RULES = (
    RULE_ILLEGAL_EDGE,
    RULE_EXECUTE_AT_MISMATCH,
    RULE_EXECUTE_AT_MUTATED,
    RULE_DEPS_MISMATCH,
    RULE_DEPS_MUTATED,
    RULE_COMMIT_INVALIDATE_CONFLICT,
    RULE_EXECUTE_AT_DUPLICATE,
    RULE_BALLOT_REGRESSION,
    RULE_KEY_EXECUTE_AT_ORDER,
    RULE_DURABILITY_REGRESSION,
    RULE_EPOCH_REGRESSION,
    RULE_SYNC_LEDGER_REGRESSION,
)

# liveness SLO flag classes (flags, never raises — see observe/audit.py)
SLO_UNATTENDED = "slo.unattended"    # undecided past budget, no attempt attributed
SLO_UNDECIDED = "slo.undecided"      # undecided past the (larger) decision budget
SLO_UNAPPLIED = "slo.unapplied"      # decided long ago, never applied anywhere

SLO_FLAGS = (SLO_UNATTENDED, SLO_UNDECIDED, SLO_UNAPPLIED)

# -- the legal-edge table (source name -> frozenset of target names) ---------

LEGAL_EDGES = {
    "NOT_DEFINED": frozenset({
        "NOT_DEFINED",              # replay re-observation of a journal stub
        "PRE_ACCEPTED", "ACCEPTED_INVALIDATE", "ACCEPTED", "PRE_COMMITTED",
        "COMMITTED", "STABLE", "PRE_APPLIED", "INVALIDATED",
        "TRUNCATED_APPLY", "ERASED",
    }),
    "PRE_ACCEPTED": frozenset({
        "ACCEPTED_INVALIDATE", "ACCEPTED", "PRE_COMMITTED", "COMMITTED",
        "STABLE", "PRE_APPLIED", "INVALIDATED", "TRUNCATED_APPLY", "ERASED",
    }),
    "ACCEPTED_INVALIDATE": frozenset({
        "ACCEPTED", "PRE_COMMITTED", "COMMITTED", "STABLE", "PRE_APPLIED",
        "INVALIDATED", "TRUNCATED_APPLY", "ERASED",
    }),
    "ACCEPTED": frozenset({
        "ACCEPTED",                 # higher-ballot re-accept
        "PRE_COMMITTED", "COMMITTED", "STABLE", "PRE_APPLIED", "INVALIDATED",
        "TRUNCATED_APPLY", "ERASED",
    }),
    "PRE_COMMITTED": frozenset({
        "COMMITTED", "STABLE", "PRE_APPLIED", "TRUNCATED_APPLY", "ERASED",
    }),
    "COMMITTED": frozenset({
        "STABLE", "PRE_APPLIED", "TRUNCATED_APPLY", "ERASED",
    }),
    "STABLE": frozenset({
        "READY_TO_EXECUTE", "PRE_APPLIED", "TRUNCATED_APPLY", "ERASED",
    }),
    "READY_TO_EXECUTE": frozenset({
        "PRE_APPLIED", "TRUNCATED_APPLY", "ERASED",
    }),
    "PRE_APPLIED": frozenset({
        "APPLYING", "TRUNCATED_APPLY", "ERASED",
    }),
    "APPLYING": frozenset({
        "APPLIED", "TRUNCATED_APPLY", "ERASED",
    }),
    "APPLIED": frozenset({
        "APPLIED",                  # replay re-observation
        "TRUNCATED_APPLY", "ERASED",
    }),
    "TRUNCATED_APPLY": frozenset({
        "TRUNCATED_APPLY",          # tombstone refresh / replay re-observation
        "ERASED",
    }),
    "ERASED": frozenset({
        "ERASED",                   # tombstone refresh / replay re-observation
    }),
    "INVALIDATED": frozenset({
        "INVALIDATED",              # replay re-observation
    }),
}


def is_legal_edge(frm: str, to: str) -> bool:
    targets = LEGAL_EDGES.get(frm)
    return targets is not None and to in targets


def lint_legal_edges() -> list:
    """Two-way completeness check of the edge table against the SaveStatus
    enum (the CI-lint satellite; tests turn a nonempty return into a tier-1
    failure).  Every member must be a source (have at least one outgoing
    edge) and a target (appear in at least one edge's target set); every
    name in the table must be a real member."""
    problems = []
    members = {s.name for s in SaveStatus}
    missing_source = sorted(members - set(LEGAL_EDGES))
    if missing_source:
        problems.append(f"SaveStatus members with no source row in "
                        f"LEGAL_EDGES: {missing_source}")
    stale = sorted(set(LEGAL_EDGES) - members)
    if stale:
        problems.append(f"LEGAL_EDGES rows for nonexistent SaveStatus "
                        f"members: {stale}")
    all_targets = set()
    for src, targets in LEGAL_EDGES.items():
        if not targets:
            problems.append(f"LEGAL_EDGES[{src}] has no outgoing edges "
                            f"(every member must be a source)")
        bad = sorted(set(targets) - members)
        if bad:
            problems.append(f"LEGAL_EDGES[{src}] targets nonexistent "
                            f"members: {bad}")
        all_targets.update(targets)
    missing_target = sorted(members - all_targets)
    if missing_target:
        problems.append(f"SaveStatus members never a target of any legal "
                        f"edge: {missing_target}")
    return problems
