"""Plane 2 of the performance-observability layer: the WALL-CLOCK profiler.

Explicitly OUTSIDE the determinism contract: everything here measures host
time (``time.perf_counter``), which differs run to run and machine to
machine.  What it must never do is perturb the simulation — the profiler
reads wall clocks and appends to its own buffers, but touches no RNG, no
sim scheduling, and no message path, so a same-seed burn with the profiler
on vs off still yields a byte-identical recorder trace
(``tests/test_profiler.py::test_profiler_zero_observer_effect`` proves it
in-tree).  Wall-clock numbers also stay OUT of the deterministic metrics
registry: snapshots are diffed across same-seed runs and must not carry
always-differing floats.

Three measurement planes:

1. **Per-message-type handler CPU** (``local/node.py`` wraps
   ``request.process``): where the single-threaded event loop's compute
   goes, by wire message type — the 43-commits/s wall is a CPU budget and
   this names its line items.
2. **Event-loop occupancy + queue depth** (``harness/cluster.py`` run
   loops): busy fraction of the loop's wall time, per-task cost, and the
   pending-queue depth distribution — distinguishes "the loop is saturated"
   from "the loop is idle waiting on sim time".
3. **Device-service launch breakdown** (``device_service/service.py`` +
   ``impl/tpu_resolver.py``): per-launch dispatch RTT, host↔device
   transfer bytes, compile events (observed as new jit shape signatures),
   and per-launch kernel wall-ms feeding the honest-MFU formulas in
   ``observe/device.py``.

Handler slices are kept (bounded) with wall timestamps so the Perfetto
export can render wall-clock tracks and flow-link a txn's sim spans to the
host handler slices that served it (``observe/export.py``).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from .critical_path import _percentile
from .device import launch_mfu

# handler slices kept for the Perfetto wall tracks (ring-bounded: a hostile
# seed emits hundreds of thousands of handler invocations)
DEFAULT_SLICE_CAP = 20_000
_QUEUE_SAMPLE_EVERY = 64          # queue-depth sample cadence (tasks)
_QUEUE_SAMPLE_CAP = 65_536


class _HandlerStat:
    __slots__ = ("count", "total_s", "max_s")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def add(self, dt: float) -> None:
        self.count += 1
        self.total_s += dt
        self.max_s = max(self.max_s, dt)


class WallProfiler:
    """One profiler per run; attach via ``run_burn(profiler=...)`` (or
    ``Cluster(profiler=...)``)."""

    def __init__(self, slice_cap: int = DEFAULT_SLICE_CAP):
        self.t0 = time.perf_counter()
        # -- plane 1: per-message-type handler CPU ---------------------------
        self.handlers: Dict[str, _HandlerStat] = {}
        # (type_name, node, txn_id_str|None, wall_t0_us, dur_us, sim_us)
        self.slices: List[tuple] = []
        self.slices_dropped = 0
        self._slice_cap = slice_cap
        # -- plane 2: event-loop occupancy + queue depth ---------------------
        self.tasks = 0
        self.busy_s = 0.0
        self.max_task_s = 0.0
        self.queue_depths: List[int] = []
        # -- plane 3: device-service launches --------------------------------
        self.launches = 0
        self.launch_wall_s = 0.0
        self.launch_max_s = 0.0
        self.launch_rows = 0
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.compile_events = 0
        self.launch_ms: List[float] = []        # per-launch kernel wall ms
        self._launch_cap = 8192
        self.consult_wall_s = 0.0               # resolver _consult total
        self._launch_shape = None               # (t, k) of the last launch

    # -- handler timing (Node._process_or_fail) ------------------------------
    def now(self) -> float:
        return time.perf_counter()

    def on_handler(self, node_id: int, type_name: str, txn_id,
                   t_start: float, sim_us: int) -> None:
        dt = time.perf_counter() - t_start
        stat = self.handlers.get(type_name)
        if stat is None:
            stat = self.handlers[type_name] = _HandlerStat()
        stat.add(dt)
        if len(self.slices) < self._slice_cap:
            wall_us = int((t_start - self.t0) * 1e6)
            self.slices.append((type_name, node_id,
                                str(txn_id) if txn_id is not None else None,
                                wall_us, max(int(dt * 1e6), 1), sim_us))
        else:
            self.slices_dropped += 1

    # -- event-loop sampling (Cluster.run_until / run_until_idle) ------------
    def on_task(self, dt_s: float, queue_depth: int) -> None:
        self.tasks += 1
        self.busy_s += dt_s
        if dt_s > self.max_task_s:
            self.max_task_s = dt_s
        if self.tasks % _QUEUE_SAMPLE_EVERY == 0 \
                and len(self.queue_depths) < _QUEUE_SAMPLE_CAP:
            self.queue_depths.append(queue_depth)

    # -- device-service launches (DeviceConsultService._dispatch) ------------
    def on_device_launch(self, rows: int, seconds: float, h2d_bytes: int,
                         d2h_bytes: int, compiled: bool,
                         shape: Optional[tuple] = None) -> None:
        self.launches += 1
        self.launch_wall_s += seconds
        self.launch_max_s = max(self.launch_max_s, seconds)
        self.launch_rows += rows
        self.h2d_bytes += h2d_bytes
        self.d2h_bytes += d2h_bytes
        if compiled:
            self.compile_events += 1
        if len(self.launch_ms) < self._launch_cap:
            self.launch_ms.append(seconds * 1e3)
        if shape is not None:
            self._launch_shape = shape

    # -- reporting ------------------------------------------------------------
    # exact nearest-rank percentile, shared with the plane-1 budget so both
    # planes of one report agree on quantile semantics
    _pct = staticmethod(_percentile)

    def collect_cluster(self, cluster) -> None:
        """Pull the resolver-side wall counters the run accumulated (the
        resolver's ``consult_wall_s`` — total wall time inside tier
        dispatch, whichever tier answered)."""
        total = 0.0
        for node in cluster.nodes.values():
            for store in node.command_stores.all_stores():
                r = getattr(store.resolver, "tpu", store.resolver)
                total += getattr(r, "consult_wall_s", 0.0)
        self.consult_wall_s = total

    def report(self, top_k: int = 12) -> dict:
        """Plain-data wall-clock report (JSON-serializable)."""
        wall_s = time.perf_counter() - self.t0
        handlers = {}
        ranked = sorted(self.handlers.items(),
                        key=lambda kv: (-kv[1].total_s, kv[0]))
        for name, st in ranked[:top_k]:
            handlers[name] = {
                "count": st.count,
                "total_s": round(st.total_s, 4),
                "mean_us": round(1e6 * st.total_s / st.count, 1),
                "max_us": round(1e6 * st.max_s, 1),
            }
        other = ranked[top_k:]
        if other:
            handlers["(other)"] = {
                "count": sum(st.count for _n, st in other),
                "total_s": round(sum(st.total_s for _n, st in other), 4),
                "mean_us": None, "max_us": None,
            }
        depths = sorted(self.queue_depths)
        kernel_ms = sorted(self.launch_ms)
        device = {
            "launches": self.launches,
            "launch_rows": self.launch_rows,
            "dispatch_wall_s": round(self.launch_wall_s, 4),
            "dispatch_mean_ms": round(1e3 * self.launch_wall_s
                                      / self.launches, 3)
            if self.launches else None,
            "dispatch_max_ms": round(1e3 * self.launch_max_s, 3),
            "kernel_ms_p50": round(self._pct(kernel_ms, 0.50), 3)
            if kernel_ms else None,
            "kernel_ms_p95": round(self._pct(kernel_ms, 0.95), 3)
            if kernel_ms else None,
            "h2d_bytes": self.h2d_bytes,
            "d2h_bytes": self.d2h_bytes,
            "compile_events": self.compile_events,
            "consult_wall_s": round(self.consult_wall_s, 4),
        }
        if self.launches and self._launch_shape is not None:
            # honest MFU over the measured launches: the mean launch's
            # achieved join FLOP/s against the chip's bf16 peak
            # (observe/device.launch_mfu — same denominator bench.py reports)
            t, k = self._launch_shape
            device.update(launch_mfu(
                t, k, int(self.launch_rows / self.launches) or 1,
                self.launch_wall_s / self.launches))
        return {
            "time_plane": "wall_s",
            "wall_s": round(wall_s, 3),
            "handlers": handlers,
            "handler_total_s": round(sum(st.total_s
                                         for st in self.handlers.values()), 4),
            "handler_slices": len(self.slices),
            "handler_slices_dropped": self.slices_dropped,
            "scheduler": {
                "tasks": self.tasks,
                "busy_s": round(self.busy_s, 4),
                "occupancy": round(self.busy_s / wall_s, 4) if wall_s else None,
                "mean_task_us": round(1e6 * self.busy_s / self.tasks, 2)
                if self.tasks else None,
                "max_task_ms": round(1e3 * self.max_task_s, 3),
                "queue_depth": {
                    "samples": len(depths),
                    "p50": self._pct(depths, 0.50),
                    "p95": self._pct(depths, 0.95),
                    "max": depths[-1] if depths else None,
                },
            },
            "device": device,
        }


def format_wall_profile(report: dict, label: str = "") -> str:
    """Compact human rendering of ``WallProfiler.report()`` (burn CLI)."""
    sch = report["scheduler"]
    lines = [f"wall profile{': ' + label if label else ''} — "
             f"{report['wall_s']:.2f}s wall, {sch['tasks']} tasks, "
             f"occupancy {100.0 * (sch['occupancy'] or 0.0):.0f}%, "
             f"handler CPU {report['handler_total_s']:.2f}s"]
    lines.append(f"  {'handler':<34}{'count':>8}{'total_s':>9}{'mean_us':>9}")
    for name, row in report["handlers"].items():
        mean = f"{row['mean_us']:>9.1f}" if row["mean_us"] is not None \
            else f"{'':>9}"
        lines.append(f"  {name:<34}{row['count']:>8}{row['total_s']:>9.3f}"
                     f"{mean}")
    dev = report["device"]
    if dev["launches"]:
        lines.append(
            f"  device: {dev['launches']} launches, "
            f"{dev['dispatch_mean_ms']:.2f}ms mean RTT "
            f"(max {dev['dispatch_max_ms']:.2f}), "
            f"{dev['compile_events']} compiles, "
            f"h2d {dev['h2d_bytes']} B, d2h {dev['d2h_bytes']} B, "
            f"MFU {dev.get('launch_mfu_vs_275tflops', 0)}")
    elif dev["consult_wall_s"]:
        lines.append(f"  consult wall (host tiers): "
                     f"{dev['consult_wall_s']:.3f}s, no device launches")
    q = sch["queue_depth"]
    if q["samples"]:
        lines.append(f"  queue depth: p50 {q['p50']}, p95 {q['p95']}, "
                     f"max {q['max']} ({q['samples']} samples)")
    return "\n".join(lines)
