"""Injectable deterministic randomness.

Capability parity with ``accord.utils.RandomSource`` (RandomSource.java:1-410): a
seedable, forkable RNG handed to every component that needs randomness so a single seed
fully determines a simulation run.  Backed by Python's Mersenne Twister (stable across
platforms/versions for the methods used here).
"""
from __future__ import annotations

import random as _pyrandom
from typing import Optional, Sequence, TypeVar

T = TypeVar("T")


class RandomSource:
    __slots__ = ("_rng", "_seed")

    def __init__(self, seed: int):
        self._seed = seed
        self._rng = _pyrandom.Random(seed)

    @property
    def seed(self) -> int:
        return self._seed

    def fork(self) -> "RandomSource":
        """A new independent source deterministically derived from this one."""
        return RandomSource(self._rng.getrandbits(63))

    # -- scalars ------------------------------------------------------------
    def next_int(self, bound_or_min: int, bound: Optional[int] = None) -> int:
        """next_int(n) -> [0, n); next_int(lo, hi) -> [lo, hi)."""
        if bound is None:
            lo, hi = 0, bound_or_min
        else:
            lo, hi = bound_or_min, bound
        if hi <= lo:
            raise ValueError(f"empty range [{lo}, {hi})")
        return self._rng.randrange(lo, hi)

    def next_long(self, bound: Optional[int] = None) -> int:
        if bound is None:
            return self._rng.getrandbits(63)
        return self._rng.randrange(bound)

    def next_float(self) -> float:
        return self._rng.random()

    def next_boolean(self) -> bool:
        return self._rng.getrandbits(1) == 1

    def decide(self, probability: float) -> bool:
        """True with the given probability."""
        return self._rng.random() < probability

    def next_gaussian(self) -> float:
        return self._rng.gauss(0.0, 1.0)

    # -- biased ints (reference: RandomSource.nextBiasedInt) ----------------
    def next_biased_int(self, lo: int, median: int, hi: int) -> int:
        """Uniform-ish in [lo, hi) but with 50% of mass below ``median``."""
        if not (lo <= median < hi):
            raise ValueError(f"need lo <= median < hi, got {lo},{median},{hi}")
        if self._rng.getrandbits(1) and median > lo:
            return self._rng.randrange(lo, median)
        return self._rng.randrange(median, hi)

    # -- collections --------------------------------------------------------
    def pick(self, items: Sequence[T]) -> T:
        return items[self._rng.randrange(len(items))]

    def pick_weighted(self, items: Sequence[T], weights: Sequence[float]) -> T:
        return self._rng.choices(items, weights=weights, k=1)[0]

    def shuffle(self, items: list) -> list:
        self._rng.shuffle(items)
        return items

    def sample(self, items: Sequence[T], k: int) -> list:
        return self._rng.sample(list(items), k)

    # -- distributions ------------------------------------------------------
    def next_zipf(self, n: int, theta: float = 0.99) -> int:
        """Zipfian in [0, n) via inverse-CDF on the truncated zeta distribution.
        Used by workload generators (reference: Gens zipf distributions)."""
        # simple rejection-free approximation: harmonic inverse
        u = self._rng.random()
        # precompute-free: accumulate until we pass u * H_n
        # (n is small in workloads: tens of keys)
        h = 0.0
        terms = [1.0 / ((i + 1) ** theta) for i in range(n)]
        total = sum(terms)
        target = u * total
        for i, t in enumerate(terms):
            h += t
            if h >= target:
                return i
        return n - 1
