"""Invariant / assertion layer with tiered paranoia levels.

Capability parity with the reference's ``accord.utils.Invariants``
(accord-core/src/main/java/accord/utils/Invariants.java:29-390): checks are grouped by
asymptotic cost so expensive validation (linear/superlinear scans of internal state) can
be switched on in the simulation harness and off in production.  Levels come from the
environment (``ACCORD_PARANOIA``) or are set programmatically by the test harness.
"""
from __future__ import annotations

import enum
import os


class Paranoia(enum.IntEnum):
    NONE = 0
    CONSTANT = 1
    LINEAR = 2
    SUPERLINEAR = 3


def _from_env() -> Paranoia:
    raw = os.environ.get("ACCORD_PARANOIA", "constant").upper()
    try:
        return Paranoia[raw]
    except KeyError:
        return Paranoia.CONSTANT


class InvariantViolation(AssertionError):
    pass


class Invariants:
    """Static holder for the process-wide paranoia level plus check helpers."""

    paranoia: Paranoia = _from_env()

    # -- level queries ------------------------------------------------------
    @classmethod
    def is_paranoid(cls) -> bool:
        return cls.paranoia >= Paranoia.CONSTANT

    @classmethod
    def test_paranoia(cls, level: Paranoia) -> bool:
        return cls.paranoia >= level

    @classmethod
    def debug(cls) -> bool:
        return cls.paranoia >= Paranoia.LINEAR

    @classmethod
    def set_paranoia(cls, level: Paranoia) -> None:
        cls.paranoia = level

    # -- checks -------------------------------------------------------------
    @staticmethod
    def check_state(condition: bool, msg: str = "invariant violated", *args) -> None:
        if not condition:
            raise InvariantViolation(msg % args if args else msg)

    @staticmethod
    def check_argument(condition: bool, msg: str = "illegal argument", *args) -> None:
        if not condition:
            raise ValueError(msg % args if args else msg)

    @staticmethod
    def non_null(obj, msg: str = "unexpected null"):
        if obj is None:
            raise InvariantViolation(msg)
        return obj

    @staticmethod
    def illegal_state(msg: str = "illegal state"):
        raise InvariantViolation(msg)

    @classmethod
    def paranoid(cls, condition_fn, msg: str = "paranoid invariant violated",
                 level: Paranoia = Paranoia.LINEAR) -> None:
        """Run ``condition_fn`` (a thunk, so the check itself is free when off) only if
        the configured paranoia level is >= ``level``."""
        if cls.paranoia >= level and not condition_fn():
            raise InvariantViolation(msg)


check_state = Invariants.check_state
check_argument = Invariants.check_argument
non_null = Invariants.non_null
illegal_state = Invariants.illegal_state
