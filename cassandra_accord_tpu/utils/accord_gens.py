"""Domain generators for property tests (accord.utils.AccordGens /
Gens.java:1-1073): txn ids, keys, ranges, deps, per-key indexes — each with
meaningful shrinking toward simpler instances."""
from __future__ import annotations

from typing import List, Tuple

from ..primitives.deps import Deps, DepsBuilder, KeyDeps, RangeDeps
from ..primitives.keys import IntKey, Range, Ranges, RoutingKeys
from ..primitives.timestamp import Ballot, Domain, Timestamp, TxnId, TxnKind
from . import property as prop

KEY_SPACE = 1000


def int_keys(lo: int = 0, hi: int = KEY_SPACE - 1) -> prop.Gen:
    return prop.ints(lo, hi).map(IntKey, "int_keys")


def routing_keys(lo: int = 0, hi: int = KEY_SPACE - 1) -> prop.Gen:
    return prop.ints(lo, hi).map(lambda v: IntKey(v).to_routing(),
                                 "routing_keys")


def txn_kinds(globally_visible_only: bool = True) -> prop.Gen:
    opts = [TxnKind.WRITE, TxnKind.READ]
    if not globally_visible_only:
        opts += [TxnKind.SYNC_POINT, TxnKind.EXCLUSIVE_SYNC_POINT]
    return prop.pick(opts)


def txn_ids(max_epoch: int = 3, max_hlc: int = 10_000,
            max_node: int = 8) -> prop.Gen:
    """Shrinks toward (epoch 1, hlc 0, node 1, WRITE)."""
    base = prop.tuples(prop.ints(1, max_epoch), prop.ints(0, max_hlc),
                       prop.ints(1, max_node), txn_kinds())

    def build(t):
        epoch, hlc, node, kind = t
        return TxnId(epoch, hlc, node, kind, Domain.KEY)

    def sample(rng):
        return build(base(rng))

    def shrink(v: TxnId):
        for cand in base.shrink((v.epoch, v.hlc, v.node, v.kind)):
            yield build(cand)
    return prop.Gen(sample, shrink, "txn_ids")


def timestamps(max_epoch: int = 3, max_hlc: int = 10_000,
               max_node: int = 8) -> prop.Gen:
    base = prop.tuples(prop.ints(1, max_epoch), prop.ints(0, max_hlc),
                       prop.ints(0, max_node))

    def build(t):
        return Timestamp(t[0], t[1], t[2])

    def sample(rng):
        return build(base(rng))

    def shrink(v: Timestamp):
        for cand in base.shrink((v.epoch, v.hlc, v.node)):
            yield build(cand)
    return prop.Gen(sample, shrink, "timestamps")


def ranges(max_ranges: int = 4, space: int = KEY_SPACE) -> prop.Gen:
    """Non-empty, sorted, non-overlapping half-open ranges; shrinks by
    dropping ranges."""
    bounds = prop.lists(prop.ints(0, space - 1), min_size=2,
                        max_size=2 * max_ranges)

    def build(bs: List[int]) -> Ranges:
        bs = sorted(set(bs))
        out = [Range(IntKey(bs[i]), IntKey(bs[i + 1]))
               for i in range(0, len(bs) - 1, 2)]
        return Ranges.of(*out)

    def sample(rng):
        return build(bounds(rng))

    def shrink(v: Ranges):
        rs = list(v)
        for i in range(len(rs)):
            if len(rs) > 1:
                yield Ranges.of(*(rs[:i] + rs[i + 1:]))
    return prop.Gen(sample, shrink, "ranges")


def key_deps_pairs(max_pairs: int = 24) -> prop.Gen:
    """The raw material of a KeyDeps: (routing key, txn id) incidences
    (KeyDepsTest.java builds from exactly this shape)."""
    return prop.lists(prop.tuples(routing_keys(), txn_ids()),
                      max_size=max_pairs)


def key_deps_from(pairs: List[Tuple]) -> KeyDeps:
    b = DepsBuilder()
    for rk, tid in pairs:
        b.add(rk, tid)
    return b.build().key_deps


def range_deps_pairs(max_pairs: int = 16) -> prop.Gen:
    def rng_gen():
        return prop.tuples(prop.ints(0, KEY_SPACE - 2), prop.ints(1, 50))
    base = prop.lists(prop.tuples(rng_gen(), txn_ids(
        )), max_size=max_pairs)

    def sample(rng):
        return base(rng)
    return prop.Gen(sample, base.shrink, "range_deps_pairs")


def range_deps_from(pairs) -> RangeDeps:
    b = DepsBuilder()
    for (start, width), tid in pairs:
        b.add(Range(IntKey(start), IntKey(min(KEY_SPACE, start + width))), tid)
    return b.build().range_deps
