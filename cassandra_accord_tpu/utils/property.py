"""Seeded property testing with shrinking.

Capability parity with ``accord.utils.Property`` / ``Gens``
(Property.java:1-917, Gens.java:1-1073): the reference's deps/cfk/topology
suites are property-based — thousands of generated cases per invariant, with
failing cases shrunk to a minimal reproducer and reported with their seed.

Usage::

    @for_all(gens.lists(gens.ints(0, 100), max_size=20), tries=2000)
    def test_sorted_idempotent(xs):
        assert sorted(sorted(xs)) == sorted(xs)

A failing case is shrunk greedily (each argument in turn, re-running the
property on every candidate) and re-raised with the minimal arguments and
the reproducing seed in the message.
"""
from __future__ import annotations

import functools
import itertools
from typing import Any, Callable, Generic, Iterable, List, Optional, Sequence, TypeVar

from .random import RandomSource

T = TypeVar("T")


class Gen(Generic[T]):
    """A seeded generator + shrinker for values of one domain."""

    def __init__(self, sample: Callable[[RandomSource], T],
                 shrink: Optional[Callable[[T], Iterable[T]]] = None,
                 describe: str = "gen"):
        self._sample = sample
        self._shrink = shrink or (lambda v: ())
        self.describe = describe

    def __call__(self, rng: RandomSource) -> T:
        return self._sample(rng)

    def shrink(self, value: T) -> Iterable[T]:
        """Candidate SIMPLER values (each must itself be generatable)."""
        return self._shrink(value)

    def map(self, fn: Callable[[T], Any], describe: str = "mapped") -> "Gen":
        """Derived generator; shrinking happens in the SOURCE domain via
        ``flat`` tracking is not attempted — mapped gens shrink by mapping
        the source's shrinks."""
        src = self

        def sample(rng):
            return fn(src(rng))

        return Gen(sample, describe=describe)


# ---------------------------------------------------------------------------
# combinators (Gens.java)
# ---------------------------------------------------------------------------

def constant(value) -> Gen:
    return Gen(lambda rng: value, describe=f"constant({value!r})")


def ints(lo: int, hi: int) -> Gen:
    """Uniform int in [lo, hi]; shrinks toward lo."""
    def shrink(v):
        seen = set()
        # toward lo by halving the distance
        cur = v
        while cur != lo:
            cur = lo + (cur - lo) // 2
            if cur not in seen:
                seen.add(cur)
                yield cur
    return Gen(lambda rng: lo + rng.next_int(hi - lo + 1), shrink,
               describe=f"ints({lo},{hi})")


def booleans() -> Gen:
    return Gen(lambda rng: rng.next_boolean(),
               lambda v: (False,) if v else (), "booleans()")


def pick(options: Sequence) -> Gen:
    """Uniform choice; shrinks toward earlier options (order = simplicity)."""
    def shrink(v):
        i = options.index(v)
        for j in (0, i // 2):
            if j < i:
                yield options[j]
    return Gen(lambda rng: options[rng.next_int(len(options))], shrink,
               describe=f"pick({len(options)} options)")


def lists(elem: Gen, min_size: int = 0, max_size: int = 16) -> Gen:
    """List of ``elem``; shrinks by dropping chunks, then shrinking elements."""
    def sample(rng):
        n = min_size + rng.next_int(max_size - min_size + 1)
        return [elem(rng) for _ in range(n)]

    def shrink(v):
        n = len(v)
        # drop halves / single elements
        step = max(1, n // 2)
        while step >= 1:
            for i in range(0, n, step):
                cand = v[:i] + v[i + step:]
                if len(cand) >= min_size:
                    yield cand
            if step == 1:
                break
            step //= 2
        # shrink individual elements
        for i, x in enumerate(v):
            for sx in itertools.islice(elem.shrink(x), 4):
                yield v[:i] + [sx] + v[i + 1:]
    return Gen(sample, shrink, f"lists({elem.describe})")


def tuples(*gens: Gen) -> Gen:
    def sample(rng):
        return tuple(g(rng) for g in gens)

    def shrink(v):
        for i, g in enumerate(gens):
            for sx in itertools.islice(g.shrink(v[i]), 6):
                yield v[:i] + (sx,) + v[i + 1:]
    return Gen(sample, shrink, f"tuples({', '.join(g.describe for g in gens)})")


# ---------------------------------------------------------------------------
# the runner (Property.qt / forAll)
# ---------------------------------------------------------------------------

class PropertyFailure(AssertionError):
    def __init__(self, seed: int, case_no: int, args, original: BaseException,
                 shrunk_args=None, shrinks: int = 0):
        self.seed = seed
        self.case_no = case_no
        self.args = args
        self.shrunk_args = shrunk_args
        self.original = original
        msg = (f"property failed (seed={seed}, case={case_no}): {original!r}\n"
               f"  args:   {args!r}")
        if shrunk_args is not None and shrinks:
            msg += f"\n  shrunk ({shrinks} steps): {shrunk_args!r}"
        super().__init__(msg)


def for_all(*gens: Gen, tries: int = 1000, seed: int = 0xACC0,
            max_shrinks: int = 400):
    """Decorator: run the property over ``tries`` seeded cases; shrink and
    re-raise on failure.  The decorated function becomes a zero-arg callable
    (pytest-compatible)."""

    def decorate(fn: Callable) -> Callable:
        # NOTE: no functools.wraps — copying the wrapped signature would make
        # test runners treat the generated arguments as fixtures
        def run():
            rng = RandomSource(seed)
            for case_no in range(tries):
                case_rng = rng.fork()
                args = tuple(g(case_rng) for g in gens)
                try:
                    fn(*args)
                except BaseException as e:  # noqa: BLE001
                    shrunk, steps = _shrink(fn, gens, args, max_shrinks)
                    raise PropertyFailure(seed, case_no, args, e, shrunk,
                                          steps) from e
        run.property_tries = tries
        run.__name__ = getattr(fn, "__name__", "property")
        run.__doc__ = fn.__doc__
        return run
    return decorate


def _shrink(fn, gens, args, max_shrinks: int):
    """Greedy per-argument shrinking: accept any candidate that still fails."""
    cur = tuple(args)
    steps = 0
    budget = max_shrinks
    improved = True
    while improved and budget > 0:
        improved = False
        for i, g in enumerate(gens):
            for cand in g.shrink(cur[i]):
                if budget <= 0:
                    break
                budget -= 1
                trial = cur[:i] + (cand,) + cur[i + 1:]
                try:
                    fn(*trial)
                except BaseException:  # noqa: BLE001 — still failing: simpler!
                    cur = trial
                    steps += 1
                    improved = True
                    break
    return cur, steps
