"""Single-threaded async primitives.

Capability parity with ``accord.utils.async`` (AsyncChains.java:48-876,
AsyncResults.java): composable callback futures used throughout the protocol.  Unlike
the Java reference there are no real threads to coordinate here — every callback runs
inline or on an injected executor (in the simulation harness, the deterministic event
loop; in production, a shard's task queue) — so this is deliberately a small, allocation
-light implementation rather than a concurrency library.

Semantics preserved from the reference:
- an ``AsyncChain`` is single-consumption: ``begin(callback)`` may be invoked once;
- ``map``/``flat_map``/``recover`` build derived chains lazily;
- an ``AsyncResult`` is a settable, multi-listener terminal result; ``Settable``
  mirrors ``AsyncResults.SettableResult``.
"""
from __future__ import annotations

import traceback
from typing import Any, Callable, Generic, List, Optional, Tuple, TypeVar

from .invariants import InvariantViolation

T = TypeVar("T")
U = TypeVar("U")

Callback = Callable[[Optional[T], Optional[BaseException]], None]


class AsyncChain(Generic[T]):
    """Lazy, single-consumption async value. Subclasses implement ``_start``."""

    def __init__(self):
        self._begun = False

    # -- core ---------------------------------------------------------------
    def begin(self, callback: Callback) -> None:
        if self._begun:
            raise RuntimeError("AsyncChain already begun")
        self._begun = True
        self._start(callback)

    def _start(self, callback: Callback) -> None:
        raise NotImplementedError

    # -- combinators --------------------------------------------------------
    def map(self, fn: Callable[[T], U]) -> "AsyncChain[U]":
        return _Mapped(self, fn)

    def flat_map(self, fn: Callable[[T], "AsyncChain[U]"]) -> "AsyncChain[U]":
        return _FlatMapped(self, fn)

    def recover(self, fn: Callable[[BaseException], Optional[T]]) -> "AsyncChain[T]":
        return _Recovered(self, fn)

    def add_callback(self, fn: Callable[[], None]) -> "AsyncChain[T]":
        """Run ``fn`` on success, pass failures through."""
        def wrap(v):
            fn()
            return v
        return _Mapped(self, wrap)

    def begin_result(self) -> "AsyncResult[T]":
        """Begin this chain, exposing completion as a multi-listener AsyncResult."""
        result: Settable[T] = Settable()
        self.begin(lambda v, f: result.set_failure(f) if f is not None else result.set_success(v))
        return result


class _Mapped(AsyncChain[U]):
    def __init__(self, parent: AsyncChain[T], fn: Callable[[T], U]):
        super().__init__()
        self._parent, self._fn = parent, fn

    def _start(self, callback: Callback) -> None:
        def on_done(value, failure):
            if failure is not None:
                callback(None, failure)
                return
            try:
                mapped = self._fn(value)
            except BaseException as e:  # noqa: BLE001 — propagate to the chain consumer
                callback(None, e)
                return
            callback(mapped, None)
        self._parent.begin(on_done)


class _FlatMapped(AsyncChain[U]):
    def __init__(self, parent: AsyncChain[T], fn: Callable[[T], AsyncChain[U]]):
        super().__init__()
        self._parent, self._fn = parent, fn

    def _start(self, callback: Callback) -> None:
        def on_done(value, failure):
            if failure is not None:
                callback(None, failure)
                return
            try:
                nxt = self._fn(value)
            except BaseException as e:  # noqa: BLE001
                callback(None, e)
                return
            nxt.begin(callback)
        self._parent.begin(on_done)


class _Recovered(AsyncChain[T]):
    def __init__(self, parent: AsyncChain[T], fn: Callable[[BaseException], Optional[T]]):
        super().__init__()
        self._parent, self._fn = parent, fn

    def _start(self, callback: Callback) -> None:
        def on_done(value, failure):
            if failure is None:
                callback(value, None)
                return
            try:
                recovered = self._fn(failure)
            except BaseException as e:  # noqa: BLE001
                callback(None, e)
                return
            callback(recovered, None)
        self._parent.begin(on_done)


class _Immediate(AsyncChain[T]):
    def __init__(self, value=None, failure: Optional[BaseException] = None):
        super().__init__()
        self._value, self._failure = value, failure

    def _start(self, callback: Callback) -> None:
        callback(self._value, self._failure)


class _Deferred(AsyncChain[T]):
    """Chain produced from a function invoked at begin() time (possibly via executor)."""

    def __init__(self, fn: Callable[[], T], executor=None):
        super().__init__()
        self._fn, self._executor = fn, executor

    def _start(self, callback: Callback) -> None:
        def run():
            try:
                v = self._fn()
            except InvariantViolation:
                # paranoia-check failures must FAIL the run loudly, not be
                # converted into a failure reply the protocol will retry —
                # a broken invariant inside a message handler otherwise
                # becomes an infinite recovery livelock (the round-5 deps
                # parity violation burned exactly this way)
                raise
            except BaseException as e:  # noqa: BLE001
                callback(None, e)
                return
            callback(v, None)
        if self._executor is None:
            run()
        else:
            self._executor.execute(run)


class AsyncResult(Generic[T]):
    """A completed-or-pending result supporting many listeners (reference:
    AsyncResults). Also usable as an AsyncChain via ``to_chain``/``map``."""

    __slots__ = ("_done", "_value", "_failure", "_listeners")

    def __init__(self):
        self._done = False
        self._value: Optional[T] = None
        self._failure: Optional[BaseException] = None
        self._listeners: List[Callback] = []

    # -- inspection ---------------------------------------------------------
    def is_done(self) -> bool:
        return self._done

    def is_success(self) -> bool:
        return self._done and self._failure is None

    def is_failure(self) -> bool:
        return self._done and self._failure is not None

    @property
    def value(self) -> Optional[T]:
        if not self._done:
            raise RuntimeError("result not done")
        if self._failure is not None:
            raise self._failure
        return self._value

    @property
    def failure(self) -> Optional[BaseException]:
        return self._failure

    # -- listeners ----------------------------------------------------------
    def add_listener(self, callback: Callback) -> None:
        if self._done:
            callback(self._value, self._failure)
        else:
            self._listeners.append(callback)

    def add_success_listener(self, fn: Callable[[T], None]) -> None:
        self.add_listener(lambda v, f: fn(v) if f is None else None)

    # -- chain view ---------------------------------------------------------
    def to_chain(self) -> AsyncChain[T]:
        outer = self

        class _C(AsyncChain):
            def _start(self, callback: Callback) -> None:
                outer.add_listener(callback)

        return _C()

    def map(self, fn: Callable[[T], U]) -> AsyncChain[U]:
        return self.to_chain().map(fn)

    def flat_map(self, fn: Callable[[T], AsyncChain[U]]) -> AsyncChain[U]:
        return self.to_chain().flat_map(fn)

    # -- completion (internal; Settable exposes publicly) -------------------
    def _complete(self, value, failure) -> bool:
        if self._done:
            return False
        self._done = True
        self._value, self._failure = value, failure
        listeners, self._listeners = self._listeners, []
        for cb in listeners:
            cb(value, failure)
        return True


class Settable(AsyncResult[T]):
    """Externally-completable AsyncResult (reference: AsyncResults.SettableResult)."""

    __slots__ = ()

    def set_success(self, value: T = None) -> bool:
        return self._complete(value, None)

    def set_failure(self, failure: BaseException) -> bool:
        return self._complete(None, failure)

    def try_success(self, value: T = None) -> bool:
        return self.set_success(value)


# -- factory helpers --------------------------------------------------------

def settable() -> Settable:
    return Settable()


def done(value: T = None) -> AsyncChain[T]:
    return _Immediate(value=value)


def failure(exc: BaseException) -> AsyncChain:
    return _Immediate(failure=exc)


def of_callable(fn: Callable[[], T], executor=None) -> AsyncChain[T]:
    return _Deferred(fn, executor)


def success_result(value: T = None) -> AsyncResult[T]:
    r: Settable[T] = Settable()
    r.set_success(value)
    return r


def all_of(chains: List[AsyncChain]) -> AsyncChain[list]:
    """Completes with the list of all results, or the first failure (reference:
    AsyncChains.all / reduce)."""

    class _All(AsyncChain):
        def _start(self, callback: Callback) -> None:
            n = len(chains)
            if n == 0:
                callback([], None)
                return
            results = [None] * n
            state = {"remaining": n, "failed": False}

            def make(i):
                def on_done(value, fail):
                    if state["failed"]:
                        return
                    if fail is not None:
                        state["failed"] = True
                        callback(None, fail)
                        return
                    results[i] = value
                    state["remaining"] -= 1
                    if state["remaining"] == 0:
                        callback(results, None)
                return on_done

            for i, c in enumerate(chains):
                c.begin(make(i))

    return _All()
