from .invariants import Invariants, Paranoia
from .random import RandomSource
from .async_ import AsyncResult, AsyncChain, settable, done, failure
from .interval_map import ReducingIntervalMap

__all__ = [
    "Invariants",
    "Paranoia",
    "RandomSource",
    "AsyncResult",
    "AsyncChain",
    "settable",
    "done",
    "failure",
    "ReducingIntervalMap",
]
