"""Mergeable piecewise-constant interval maps.

Capability parity with ``accord.utils.ReducingIntervalMap``/``ReducingRangeMap``
(ReducingIntervalMap.java:1-594, ReducingRangeMap.java:1-443): a value per half-open
interval of the routing-key space, with pointwise merge (via a user reduce function),
lookup, and folds over Keys/Ranges.  Base structure of ``RedundantBefore``,
``DurableBefore`` and ``MaxConflicts`` in ``local``.

Representation: ``bounds = [b0, b1, ..., bn-1]`` strictly increasing routing keys and
``values = [v0, v1, ..., vn]`` with ``len(values) == len(bounds)+1``; value ``v_i``
applies to keys in ``[b_{i-1}, b_i)`` (v0 below b0, vn at/above bn-1).  None values mean
"absent" and merge as the identity.
"""
from __future__ import annotations

from bisect import bisect_right
from typing import Any, Callable, Generic, List, Optional, Sequence, Tuple, TypeVar

V = TypeVar("V")


class ReducingIntervalMap(Generic[V]):
    __slots__ = ("bounds", "values")

    def __init__(self, bounds: Sequence = (), values: Sequence = (None,)):
        if len(values) != len(bounds) + 1:
            raise ValueError("values must have len(bounds)+1 entries")
        self.bounds: Tuple = tuple(bounds)
        self.values: Tuple = tuple(values)

    # -- construction -------------------------------------------------------
    @staticmethod
    def constant(value: Optional[V]) -> "ReducingIntervalMap[V]":
        return ReducingIntervalMap((), (value,))

    @staticmethod
    def of_range(start, end, value: V, outer: Optional[V] = None) -> "ReducingIntervalMap[V]":
        """value on [start, end), ``outer`` elsewhere."""
        if not start < end:
            raise ValueError(f"empty range [{start}, {end})")
        return ReducingIntervalMap((start, end), (outer, value, outer))

    @staticmethod
    def of_ranges(ranges, value: V, outer: Optional[V] = None) -> "ReducingIntervalMap[V]":
        """value on each half-open (start, end) pair in ``ranges`` (non-overlapping,
        sorted), ``outer`` elsewhere."""
        bounds, values = [], [outer]
        for start, end in ranges:
            if bounds and bounds[-1] == start:
                bounds.append(end)
                values[-1] = value
                values.append(outer)
            else:
                bounds.append(start)
                values.append(value)
                bounds.append(end)
                values.append(outer)
        return ReducingIntervalMap(bounds, values)

    # -- lookup -------------------------------------------------------------
    def get(self, key) -> Optional[V]:
        i = bisect_right(self.bounds, key)
        return self.values[i]

    def map_values(self, fn: Callable[[V], Any]) -> "ReducingIntervalMap":
        """New map with ``fn`` applied to every non-None value."""
        return ReducingIntervalMap(
            self.bounds, tuple(None if v is None else fn(v) for v in self.values))

    def values_over(self, start, end) -> List[Optional[V]]:
        """Every distinct value the map takes over [start, end)."""
        i = bisect_right(self.bounds, start)
        out = [self.values[i]]
        while i < len(self.bounds) and self.bounds[i] < end:
            out.append(self.values[i + 1])
            i += 1
        return out

    def items_over(self, start, end) -> List[Tuple[Any, Any, Optional[V]]]:
        """(lo, hi, value) per map interval intersecting [start, end), clipped
        to the query bounds — callers that attribute a value to "its" interval
        must use THIS, not values_over, or they smear the value across the whole
        query range."""
        out: List[Tuple[Any, Any, Optional[V]]] = []
        i = bisect_right(self.bounds, start)
        lo = start
        while True:
            hi = self.bounds[i] if i < len(self.bounds) else None
            seg_end = end if hi is None or hi > end else hi
            out.append((lo, seg_end, self.values[i]))
            if hi is None or hi >= end:
                break
            lo = hi
            i += 1
        return out

    def is_empty(self) -> bool:
        return all(v is None for v in self.values)

    # -- merge --------------------------------------------------------------
    def merge(self, other: "ReducingIntervalMap[V]",
              reduce: Callable[[V, V], V],
              strict: bool = False) -> "ReducingIntervalMap[V]":
        """Pointwise merge; where both maps have a value, combine with ``reduce``.
        Default: None (absent) merges as the identity — the other side wins.
        ``strict``: None annihilates — an interval absent from EITHER map is
        absent from the result (for min-style agreement merges)."""
        if strict:
            def combine(a, b):
                if a is None or b is None:
                    return None
                return reduce(a, b)
        else:
            def combine(a, b):
                if a is None:
                    return b
                if b is None:
                    return a
                return reduce(a, b)

        bounds: List = sorted(set(self.bounds) | set(other.bounds))
        values: List = []
        # value for interval below bounds[0], between each pair, and above the last
        probes = []
        if not bounds:
            return ReducingIntervalMap((), (combine(self.values[0], other.values[0]),))
        # representative probe per interval: for interval i ending at bounds[i] use the
        # bound itself is exclusive, so probe must be < bounds[i]; use bisect on bound
        for i in range(len(bounds) + 1):
            if i == 0:
                lo_bound = None
            else:
                lo_bound = bounds[i - 1]
            # interval is [lo_bound, bounds[i]) — any key >= lo_bound and < next bound;
            # we can evaluate each source map by index arithmetic instead of probing.
            a = self._value_for_interval(lo_bound)
            b = other._value_for_interval(lo_bound)
            values.append(combine(a, b))
        # compact equal-adjacent intervals
        cb: List = []
        cv: List = [values[0]]
        for i, b in enumerate(bounds):
            if values[i + 1] == cv[-1]:
                continue
            cb.append(b)
            cv.append(values[i + 1])
        return ReducingIntervalMap(cb, cv)

    def _value_for_interval(self, lo_bound) -> Optional[V]:
        """Value applying to keys in the interval starting at ``lo_bound`` (None = -inf)."""
        if lo_bound is None:
            return self.values[0]
        i = bisect_right(self.bounds, lo_bound)
        return self.values[i]

    # -- folds --------------------------------------------------------------
    def foldl_keys(self, keys, fn: Callable[[V, Any, Any], Any], accumulate):
        """fold fn(value, key, acc) over keys that land on non-None values."""
        acc = accumulate
        for k in keys:
            v = self.get(k)
            if v is not None:
                acc = fn(v, k, acc)
        return acc

    def foldl_intervals(self, fn: Callable[[Optional[V], Any, Any, Any], Any], accumulate):
        """fold fn(value, start, end, acc) over every interval (start/end may be None
        at the extremes)."""
        acc = accumulate
        prev = None
        for i, v in enumerate(self.values):
            end = self.bounds[i] if i < len(self.bounds) else None
            acc = fn(v, prev, end, acc)
            prev = end
        return acc

    def __eq__(self, other):
        return (isinstance(other, ReducingIntervalMap)
                and self.bounds == other.bounds and self.values == other.values)

    def __repr__(self):
        parts = []
        prev = "-inf"
        for i, v in enumerate(self.values):
            end = self.bounds[i] if i < len(self.bounds) else "+inf"
            if v is not None:
                parts.append(f"[{prev},{end})={v!r}")
            prev = end
        return "IntervalMap{" + ", ".join(parts) + "}"
