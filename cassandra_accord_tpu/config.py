"""LocalConfig — one injected configuration object for every tunable.

Capability parity with ``accord.config.LocalConfig``
(config/LocalConfig.java: progress-log schedule delay, epoch-fetch
timeout/watchdog — extended here with this build's read-retry and
accelerator data-plane knobs, which previously lived as ``ACCORD_*``
environment reads scattered through the tree, VERDICT r04 item 10).

``LocalConfig.from_env()`` reads the environment ONCE at construction (so
tests that monkeypatch env before building a Node/resolver keep working),
and every component takes the object — env vars are the default source, the
object is the override surface (the reference's MutableLocalConfig role)."""
from __future__ import annotations

import os
from dataclasses import dataclass, replace
from typing import Optional


@dataclass
class LocalConfig:
    # -- reference knobs (config/LocalConfig.java) ---------------------------
    progress_log_poll_s: float = 0.5        # getProgressLogScheduleDelay
    epoch_fetch_initial_timeout_s: float = 10.0   # epochFetchInitialTimeout
    epoch_fetch_watchdog_interval_s: float = 10.0  # epochFetchWatchdogInterval

    # -- epoch fetch watchdog (Node._arm_epoch_watchdog) ---------------------
    epoch_fetch_retry_s: float = 1.0
    epoch_fetch_attempts: int = 30

    # -- coordination timing -------------------------------------------------
    read_retry_delay_s: float = 0.15        # transient-nack read re-round beat
    max_read_rounds: int = 3                # bounded re-rounds before Exhausted
    slow_read_threshold_s: float = 0.6      # speculative second read beat
    investigation_stagger_s: float = 0.5    # progress-log launch stagger window

    # -- deps-resolver data plane (impl/resolver.py, impl/tpu_resolver.py) ---
    resolver_kind: str = "cpu"              # cpu | tpu | verify
    tpu_txn_slots: int = 64
    tpu_key_slots: int = 64
    tpu_tier: str = "auto"                  # auto | host | device | walk
    tpu_walk_max: int = 384                 # index size below which walk always
    tpu_walk_width: int = 8                 # narrow-query walk routing width
    tpu_f32_max: int = 16384                # persistent f32 mirror bound
    tpu_host_engine: str = "auto"           # auto | numpy | native
    tpu_dispatch_elems: Optional[float] = None  # device-tier threshold override

    @classmethod
    def from_env(cls, **overrides) -> "LocalConfig":
        env = os.environ
        de = env.get("ACCORD_TPU_DISPATCH_ELEMS")
        cfg = cls(
            resolver_kind=env.get("ACCORD_RESOLVER", "cpu").lower(),
            tpu_txn_slots=int(env.get("ACCORD_TPU_TXN_SLOTS", "64")),
            tpu_key_slots=int(env.get("ACCORD_TPU_KEY_SLOTS", "64")),
            tpu_tier=env.get("ACCORD_TPU_TIER", "auto"),
            tpu_walk_max=int(env.get("ACCORD_TPU_WALK_MAX", "384")),
            tpu_walk_width=int(env.get("ACCORD_TPU_WALK_WIDTH", "8")),
            tpu_f32_max=int(env.get("ACCORD_TPU_F32_MAX", "16384")),
            tpu_host_engine=env.get("ACCORD_TPU_HOST_TIER", "auto"),
            tpu_dispatch_elems=float(de) if de is not None else None,
        )
        return replace(cfg, **overrides) if overrides else cfg
