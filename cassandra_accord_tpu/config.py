"""LocalConfig — one injected configuration object for every tunable.

Capability parity with ``accord.config.LocalConfig``
(config/LocalConfig.java: progress-log schedule delay, epoch-fetch
timeout/watchdog — extended here with this build's read-retry and
accelerator data-plane knobs, which previously lived as ``ACCORD_*``
environment reads scattered through the tree, VERDICT r04 item 10).

``LocalConfig.from_env()`` reads the environment ONCE at construction (so
tests that monkeypatch env before building a Node/resolver keep working),
and every component takes the object — env vars are the default source, the
object is the override surface (the reference's MutableLocalConfig role)."""
from __future__ import annotations

import os
from dataclasses import dataclass, replace
from typing import Optional


@dataclass
class LocalConfig:
    # -- reference knobs (config/LocalConfig.java) ---------------------------
    progress_log_poll_s: float = 0.5        # getProgressLogScheduleDelay
    epoch_fetch_initial_timeout_s: float = 10.0   # epochFetchInitialTimeout
    epoch_fetch_watchdog_interval_s: float = 10.0  # epochFetchWatchdogInterval

    # -- epoch fetch watchdog (Node._arm_epoch_watchdog) ---------------------
    epoch_fetch_retry_s: float = 1.0
    epoch_fetch_attempts: int = 30

    # -- coordination timing -------------------------------------------------
    read_retry_delay_s: float = 0.15        # transient-nack read re-round beat
    max_read_rounds: int = 3                # bounded re-rounds before Exhausted
    # speculative second read beat: sized just under the reply timeout (2s) —
    # aggressive speculation (0.6s was tried) duplicates reads under chaos
    # and flipped passing hostile seeds into the livelock class; at 1.5s it
    # fires only where it saves a whole timeout round (measured: hostile
    # seed 5 passes in 6.6s with 1.5s, 16.1s without speculation, stalls
    # at 0.6s)
    slow_read_threshold_s: float = 1.5
    investigation_stagger_s: float = 0.5    # progress-log launch stagger window

    # -- re-fencing cooperation (the seed-6 wedge) ---------------------------
    # a txn decided (stable-or-later) this many sim-seconds ago with no local
    # apply counts as "unapplied pressure" (the slo.unapplied condition); the
    # bootstrap retry ladder and staleness catch-up escalation stretch their
    # cadence by the pressure count, capped, so re-fencing never outruns
    # in-flight partial-read coverage assembly
    refence_pressure_age_s: float = 10.0
    refence_backoff_max_s: float = 30.0

    # -- elastic membership (harness/nemesis.py MembershipNemesis) -----------
    # mean sim-time between join/decommission attempts (jittered, de-aligned
    # from the other nemesis cadences); member-count bounds are derived from
    # the initial cluster size unless set
    membership_interval_s: float = 25.0
    membership_min_members: Optional[int] = None
    membership_max_members: Optional[int] = None

    # -- crash-restart nemesis (harness/nemesis.py) --------------------------
    # mean sim-time between crash attempts; each tick is jittered so crashes
    # never align with the chaos re-roll cadence
    restart_interval_s: float = 20.0
    restart_downtime_min_s: float = 2.0     # min sim-time a node stays down
    restart_downtime_max_s: float = 12.0    # max sim-time a node stays down
    restart_max_down: int = 1               # max concurrently-crashed nodes
    # never crash a node if doing so would leave ANY shard it replicates
    # without a live slow-path quorum (liveness floor; turning this off makes
    # stalls expected and is only for targeted experiments)
    restart_keep_quorum: bool = True

    # -- stall watchdog (harness/watchdog.py) --------------------------------
    stall_watchdog_interval_s: float = 5.0  # sim-time between progress checks
    stall_watchdog_after_s: float = 120.0   # sim-time with no resolved op => dump

    # -- gray-failure nemeses (harness/nemesis.py) ---------------------------
    # stop-the-world process pauses: scheduler, sinks and store executors
    # freeze; every due timer/delivery late-fires in order at resume.  The
    # cadences are deliberately de-aligned from restart_interval_s (20) and
    # the 5s chaos re-roll so fault classes overlap at seeded, shifting phases
    pause_interval_s: float = 15.0          # mean sim-time between pause attempts
    pause_min_s: float = 0.5                # min stop-the-world duration
    pause_max_s: float = 4.0                # max (> reply timeout: peers MUST
                                            # observe the node as slow-not-dead)
    pause_max_paused: int = 1               # max concurrently-paused nodes
    pause_keep_quorum: bool = True          # count paused as unavailable for
                                            # the crash/pause quorum floor
    # journal-append stalls: durability (and therefore every outbound reply —
    # fsync-before-reply) lags execution; a crash mid-stall loses the whole
    # unsynced tail
    disk_stall_interval_s: float = 17.0     # mean sim-time between stall attempts
    disk_stall_min_s: float = 1.0
    disk_stall_max_s: float = 6.0

    # -- journal integrity (harness/journal.py) ------------------------------
    # crash-time damage injection (restart nemesis): probability a crash tears
    # the tail record (partial write) / bit-flips a random record
    journal_torn_tail_chance: float = 0.25
    journal_corrupt_chance: float = 0.15
    # what restart replay does with a checksum-failed MID-LOG record (a torn
    # TAIL always silently truncates to the last whole record, like any WAL):
    # "quarantine" drops the damaged txn's records and re-enters the bootstrap
    # catch-up ladder over its footprint; "halt" raises JournalCorruption loud
    journal_corruption_policy: str = "quarantine"

    # -- adaptive reply timeout/backoff (harness/cluster.py sink) ------------
    # the first timeout is reply_timeout_s; every non-final-reply re-arm grows
    # by reply_backoff_factor (capped, with deterministic hash jitter so
    # re-arms across nodes never phase-lock), and after reply_rearm_budget
    # re-arms the last armed timer stands un-re-armed (bounded patience)
    reply_backoff_factor: float = 2.0
    reply_backoff_max_s: float = 30.0
    reply_backoff_jitter: float = 0.25      # fraction of the timeout, [0, j)
    reply_rearm_budget: int = 8

    # -- slow-replica tracking (read-speculation routing) --------------------
    # a peer is "slow" while its reply-latency EWMA exceeds the threshold or
    # within the penalty window after a reply timeout; coordinators route
    # per-shard data reads around slow peers instead of burning timeout rounds
    slow_peer_ewma_alpha: float = 0.3
    slow_peer_latency_threshold_s: float = 1.0
    slow_peer_penalty_s: float = 5.0

    # -- overload robustness (local/overload.py) ------------------------------
    # admission control: a node sheds NEW work (replica-side PreAccepts via a
    # fast Overloaded nack; harness clients check before dispatching) while
    # its composite load signal — outstanding RPC callbacks + unapplied
    # execution pressure — sits above the high watermark, until it drains
    # below the low watermark (hysteresis).  Default OFF: with the default
    # config every trajectory is byte-identical to the pre-overload tree.
    admission_enabled: bool = False
    admission_hi: int = 48                  # shed at/above this composite load
    admission_lo: int = 32                  # readmit at/below this (hysteresis)
    admission_pressure_age_s: float = 5.0   # unapplied-pressure age horizon
    # coordinator routing: after an Overloaded nack (or a piggybacked load
    # bit) the peer counts as slow for this window — reads route around it
    overload_penalty_s: float = 2.0
    # replies piggyback the replica's current overload bit so coordinators
    # learn of pressure without waiting for a shed (only consulted when
    # admission is enabled)
    backpressure_piggyback: bool = True
    # retry budgets: deterministic token buckets (hash-jittered refill, zero
    # RNG-stream consumption) gate the unbounded retry surfaces — progress-log
    # investigation/blocked-fetch launches and the bootstrap re-fencing
    # ladder.  A denied launch defers to the next poll/rung instead of
    # joining a herd.  Default OFF.
    # defaults sized to bind only on storms: a store's normal recovery drain
    # runs tens of investigations per sim-second — a budget tighter than that
    # throttles the HEAL rate and manufactures the very goodput collapse it
    # exists to prevent (measured on the round-14 ramp oracle: rate 4/s
    # stretched the post-overload drain tail 2-3x)
    retry_budget_enabled: bool = False
    retry_budget_rate_s: float = 32.0       # tokens per sim-second
    retry_budget_burst: float = 64.0        # bucket capacity
    retry_budget_jitter: float = 0.25       # refill-rate jitter fraction

    # -- columnar protocol engine (protocol_batch/) ---------------------------
    # struct-of-arrays txn batches over command-store hot state + vectorized
    # release/frontier/progress scans.  "off" keeps every legacy code path
    # untouched; "on"/"auto" enable the engine — which by the exact-skip
    # contract NEVER changes a protocol decision (same-seed burns on-vs-off
    # are byte-identical; the knob buys wall-clock, never trajectory)
    columnar: str = "auto"                  # auto | on | off

    # -- deps-resolver data plane (impl/resolver.py, impl/tpu_resolver.py) ---
    resolver_kind: str = "cpu"              # cpu | tpu | verify
    tpu_txn_slots: int = 64
    tpu_key_slots: int = 64
    tpu_tier: str = "auto"                  # auto | host | device | walk
    tpu_walk_max: int = 384                 # index size below which walk always
    tpu_walk_width: int = 8                 # narrow-query walk routing width
    tpu_f32_max: int = 16384                # persistent f32 mirror bound
    tpu_host_engine: str = "auto"           # auto | numpy | native
    tpu_dispatch_elems: Optional[float] = None  # device-tier threshold override

    # -- persistent batched device consult service (device_service/) ---------
    # "auto"/"on": the resolver's device tier routes through the persistent
    # service (incremental double-buffered index refresh, ragged batching
    # window, futures API); "off": legacy one-shot dispatch (whole-index
    # re-upload per consult — the r05 replay wedge; kept as a bench baseline)
    tpu_service: str = "auto"               # auto | on | off
    # jax = the fused kernel wherever jax placed the buffers (TPU or the CPU
    # backend — both count as the kernel tier); host = deterministic numpy
    # fallback (bit-identical answers, dispatched eagerly per window);
    # auto = jax whenever a usable jax runtime exists, host otherwise
    tpu_service_backend: str = "auto"       # auto | jax | host
    tpu_service_max_window: int = 256       # row-bucket cap per dispatch
    tpu_service_refresh_full_frac: float = 0.25  # dirty fraction -> full upload

    _ENV_FIELDS = (
        ("ACCORD_RESTART_INTERVAL", "restart_interval_s", float),
        ("ACCORD_RESTART_DOWNTIME_MIN", "restart_downtime_min_s", float),
        ("ACCORD_RESTART_DOWNTIME_MAX", "restart_downtime_max_s", float),
        ("ACCORD_RESTART_MAX_DOWN", "restart_max_down", int),
        ("ACCORD_STALL_WATCHDOG_AFTER", "stall_watchdog_after_s", float),
        ("ACCORD_PAUSE_INTERVAL", "pause_interval_s", float),
        ("ACCORD_PAUSE_MAX", "pause_max_s", float),
        ("ACCORD_DISK_STALL_INTERVAL", "disk_stall_interval_s", float),
        ("ACCORD_MEMBERSHIP_INTERVAL", "membership_interval_s", float),
        ("ACCORD_JOURNAL_CORRUPTION", "journal_corruption_policy",
         lambda v: v.lower()),
        ("ACCORD_JOURNAL_TORN_TAIL_CHANCE", "journal_torn_tail_chance", float),
        ("ACCORD_JOURNAL_CORRUPT_CHANCE", "journal_corrupt_chance", float),
        ("ACCORD_ADMISSION", "admission_enabled",
         lambda v: v.lower() not in ("", "0", "off", "false")),
        ("ACCORD_ADMISSION_HI", "admission_hi", int),
        ("ACCORD_ADMISSION_LO", "admission_lo", int),
        ("ACCORD_OVERLOAD_PENALTY", "overload_penalty_s", float),
        ("ACCORD_RETRY_BUDGET", "retry_budget_enabled",
         lambda v: v.lower() not in ("", "0", "off", "false")),
        ("ACCORD_RETRY_BUDGET_RATE", "retry_budget_rate_s", float),
        ("ACCORD_RETRY_BUDGET_BURST", "retry_budget_burst", float),
        ("ACCORD_REPLY_BACKOFF_MAX", "reply_backoff_max_s", float),
        ("ACCORD_REPLY_REARM_BUDGET", "reply_rearm_budget", int),
        ("ACCORD_COLUMNAR", "columnar", lambda v: v.lower()),
        ("ACCORD_RESOLVER", "resolver_kind", lambda v: v.lower()),
        ("ACCORD_TPU_TXN_SLOTS", "tpu_txn_slots", int),
        ("ACCORD_TPU_KEY_SLOTS", "tpu_key_slots", int),
        ("ACCORD_TPU_TIER", "tpu_tier", str),
        ("ACCORD_TPU_WALK_MAX", "tpu_walk_max", int),
        ("ACCORD_TPU_WALK_WIDTH", "tpu_walk_width", int),
        ("ACCORD_TPU_F32_MAX", "tpu_f32_max", int),
        ("ACCORD_TPU_HOST_TIER", "tpu_host_engine", str),
        ("ACCORD_TPU_DISPATCH_ELEMS", "tpu_dispatch_elems", float),
        ("ACCORD_TPU_SERVICE", "tpu_service", lambda v: v.lower()),
        ("ACCORD_TPU_SERVICE_BACKEND", "tpu_service_backend",
         lambda v: v.lower()),
        ("ACCORD_TPU_SERVICE_MAX_WINDOW", "tpu_service_max_window", int),
    )

    @classmethod
    def from_env(cls, **overrides) -> "LocalConfig":
        # kwargs ONLY for env vars actually set: the dataclass field defaults
        # stay the single source of truth
        kw = {}
        for var, field, conv in cls._ENV_FIELDS:
            raw = os.environ.get(var)
            if raw is not None:
                kw[field] = conv(raw)
        kw.update(overrides)
        return cls(**kw)
