"""Native (C++) host-tier runtime components, loaded via ctypes.

The reference keeps its hot host-side structures in primitive-array Java
(KeyDeps CSR maps, SortedArrays — SURVEY §2.8); our equivalents are numpy +
device kernels, with this package providing the NATIVE host rung of the
consult cost ladder: ``consult.cpp`` compiled on first use with the
toolchain's g++ into ``_consult.so`` and called through ctypes (no pybind11
in the image; the ctypes boundary passes raw numpy buffers, zero-copy).

Build is lazy, cached by source mtime, and failure-tolerant: environments
without a compiler simply fall back to the numpy tier
(``available()`` -> False).  Force a rebuild by deleting ``_consult.so``.
"""
from __future__ import annotations

import ctypes
import os
import platform
import subprocess
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "consult.cpp")
# -march=native output is host-specific: tag the cache by machine so a shared
# checkout across heterogeneous hosts never dlopens another arch's build
_LIB = os.path.join(_DIR, f"_consult_{platform.machine()}.so")

_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    # compile to a private temp path and rename into place: rename is atomic
    # on the same filesystem, so concurrent builders (parallel pytest, burns)
    # never dlopen a partially-written .so
    tmp = f"{_LIB}.tmp.{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O3", "-march=native", "-shared", "-fPIC",
             "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    if not os.path.exists(_LIB) \
            or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
        if not _build():
            _load_failed = True
            return None
    try:
        lib = ctypes.CDLL(_LIB)
    except OSError:
        _load_failed = True
        return None
    f32p = np.ctypeslib.ndpointer(dtype=np.float32, flags="C_CONTIGUOUS")
    i8p = np.ctypeslib.ndpointer(dtype=np.int8, flags="C_CONTIGUOUS")
    u8p = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS")
    c = lib.consult_batch
    c.restype = ctypes.c_int
    c.argtypes = [f32p, f32p, i32p, i32p, i8p, i8p, u8p,
                  ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                  i32p, ctypes.c_int32, i32p, i8p, ctypes.c_int32,
                  u8p, ctypes.c_int32, ctypes.c_int8,
                  ctypes.c_uint8, ctypes.c_uint8,
                  ctypes.c_void_p, ctypes.c_void_p]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


_witness_cache: Optional[np.ndarray] = None


def _witnesses() -> np.ndarray:
    global _witness_cache
    if _witness_cache is None:
        from ..primitives.timestamp import TxnKind
        n = len(TxnKind)
        w = np.zeros((n, n), dtype=np.uint8)
        for a in TxnKind:
            for b in TxnKind:
                w[a, b] = 1 if a.witnesses(b) else 0
        _witness_cache = np.ascontiguousarray(w)
    return _witness_cache


def consult_batch(h: dict, qcols_list, before: np.ndarray, kind: np.ndarray,
                  invalidated_code: int, want_deps: bool = True,
                  want_max: bool = True
                  ) -> Tuple[Optional[np.ndarray], Optional[np.ndarray]]:
    """Run the native consult over the resolver's canonical host mirror
    ``h`` (key_inc/live_inc [T,K] int8, ts/txn_id [T,5] int32, kind/status
    [T] int8, active [T] bool).  ``qcols_list``: per-query lists of key-slot
    columns.  Returns (deps [B,T] bool | None, max_lanes [B,5] int64 | None).
    """
    lib = _load()
    assert lib is not None, "native consult unavailable"
    T, K = h["key_inc"].shape
    lanes = h["ts"].shape[1]
    assert lanes <= 8, f"native consult supports <=8 lanes, got {lanes}"
    B = len(qcols_list)
    max_q = max((len(c) for c in qcols_list), default=1) or 1
    qcols = np.full((B, max_q), -1, dtype=np.int32)
    for i, cols in enumerate(qcols_list):
        qcols[i, :len(cols)] = cols
    out_deps = np.zeros((B, T), dtype=np.uint8) if want_deps else None
    out_max = np.zeros((B, lanes), dtype=np.int64) if want_max else None
    active = np.ascontiguousarray(h["active"].astype(np.uint8))
    wit = _witnesses()
    # the TRANSPOSED f32 incidence mirrors the resolver already maintains
    # for its numpy tier ([K, T], 0.0/1.0); build per call only when the
    # index is above the resolver's f32-mirror bound (rare — the cost model
    # routes that scale to the device tier)
    live_T = h.get("live_f32")
    key_T = h.get("key_inc_f32")
    if live_T is None or key_T is None:
        live_T = np.ascontiguousarray(h["live_inc"].T.astype(np.float32))
        key_T = np.ascontiguousarray(h["key_inc"].T.astype(np.float32))
    rc = lib.consult_batch(
        np.ascontiguousarray(live_T),
        np.ascontiguousarray(key_T),
        np.ascontiguousarray(h["ts"]),
        np.ascontiguousarray(h["txn_id"]),
        np.ascontiguousarray(h["kind"]),
        np.ascontiguousarray(h["status"]),
        active, T, K, lanes,
        qcols, max_q,
        np.ascontiguousarray(before.astype(np.int32)),
        np.ascontiguousarray(kind.astype(np.int8)), B,
        wit, wit.shape[0], invalidated_code,
        1 if want_deps else 0, 1 if want_max else 0,
        out_deps.ctypes.data_as(ctypes.c_void_p) if want_deps else None,
        out_max.ctypes.data_as(ctypes.c_void_p) if want_max else None)
    if rc != 0:
        # a silent all-zero result would read as "no dependencies" — fail loud
        raise RuntimeError(f"native consult_batch failed (rc={rc})")
    return (out_deps.astype(bool) if want_deps else None, out_max)
