// Native host-tier consult engine.
//
// The C++ analog of TpuDepsResolver._consult_host (impl/tpu_resolver.py) and
// ops.deps_kernels.consult: answers a batch of PreAccept-class deps queries
// (SafeCommandStore.mapReduceActive, SafeCommandStore.java:292;
// cfk/CommandsForKey.java:925) plus the timestamp-proposal max
// (MaxConflicts.java:32) against the store's conflict index.
//
// Where the numpy host tier runs dense [B,K]x[K,T] BLAS matmuls (O(B*T*K)
// with f32 temporaries), this engine works over the TRANSPOSED f32 mirrors ([K,T]) in
// two phases per query: (1) the share bitmaps as an OR over the query's OWN
// key rows — contiguous streaming loads, protocol queries touch 1-3 keys —
// then (2) witness/status/timestamp checks only where the bitmap hits.
// O(B*T*k_q) sequential traffic, no temporaries, no cache thrash.  It is
// the host-side rung of the consult cost ladder between the scalar cfk walk
// and the MXU device tier.
//
// Semantics mirrored bit-for-bit (parity-tested from tests/test_native.py):
//   deps   = share_live & lex_less(txn_id, before) & witnesses[qk][k]
//            & active & (status != INVALIDATED)        over the LIVE incidence
//   max    = lane-lex max of max(ts, txn_id) where share_full & active
//            over the FULL incidence (elision never applies to MaxConflicts)
//
// Built with plain g++ (no pybind11 in the image); loaded via ctypes
// (native/__init__.py), with the numpy tier as fallback when no compiler.

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// Lexicographic a < b over `lanes` int32 lanes (all values non-negative).
static inline bool ts_less(const int32_t* a, const int32_t* b, int lanes) {
    for (int i = 0; i < lanes; ++i) {
        if (a[i] != b[i]) return a[i] < b[i];
    }
    return false;
}

// live_T / key_T: TRANSPOSED incidence, [K*T] row-major (key-major), f32 —
// the resolver's existing host-tier mirrors (0.0/1.0 values), consumed
// as-is so the native tier adds no index bookkeeping of its own.
// out_deps: [B*T] uint8 (may be null when want_deps == 0)
// out_max:  [B*lanes] int64 (may be null when want_max == 0)
// Returns 0 on success, nonzero on bad arguments / allocation failure —
// the caller must NOT read the output buffers then (a silent return would
// read as "no dependencies", a correctness failure, not a crash).
int consult_batch(const float* live_T,        // [K*T]
                   const float* key_T,        // [K*T]
                   const int32_t* ts,         // [T*lanes]
                   const int32_t* txn_id,     // [T*lanes]
                   const int8_t* kind,        // [T]
                   const int8_t* status,      // [T]
                   const uint8_t* active,     // [T]
                   int32_t T, int32_t K, int32_t lanes,
                   const int32_t* qcols,      // [B*max_q] key rows, -1 pad
                   int32_t max_q,
                   const int32_t* before,     // [B*lanes]
                   const int8_t* qkind,       // [B]
                   int32_t B,
                   const uint8_t* witnesses,  // [NK*NK] row-major
                   int32_t NK,
                   int8_t invalidated_code,
                   uint8_t want_deps,
                   uint8_t want_max,
                   uint8_t* out_deps,
                   int64_t* out_max) {
    if (lanes > 8 || lanes <= 0 || T <= 0) return 1;  // best[8] bound below
    int8_t* share_full = static_cast<int8_t*>(std::malloc(2 * (size_t)T));
    if (share_full == nullptr) return 2;
    int8_t* share_live = share_full + T;
    for (int32_t b = 0; b < B; ++b) {
        const int32_t* cols = qcols + (int64_t)b * max_q;
        int32_t ncols = 0;
        while (ncols < max_q && cols[ncols] >= 0) ++ncols;
        // phase 1: share bitmaps by streaming OR over the query's key rows
        std::memset(share_full, 0, 2 * (size_t)T);
        for (int32_t c = 0; c < ncols; ++c) {
            const float* kr = key_T + (int64_t)cols[c] * T;
            const float* lr = live_T + (int64_t)cols[c] * T;
            for (int32_t t = 0; t < T; ++t) {
                share_full[t] |= kr[t] != 0.0f;
                share_live[t] |= lr[t] != 0.0f;
            }
        }
        // phase 2: predicate checks only where the bitmaps hit
        const int32_t* bound = before + (int64_t)b * lanes;
        const uint8_t* wit_row =
            witnesses + (int64_t)(uint8_t)qkind[b] * NK;
        uint8_t* drow = want_deps ? out_deps + (int64_t)b * T : nullptr;
        if (want_deps) std::memset(drow, 0, T);
        int64_t best[8] = {0, 0, 0, 0, 0, 0, 0, 0};
        bool any = false;
        for (int32_t t = 0; t < T; ++t) {
            if (!(share_full[t] | share_live[t]) || !active[t]) continue;
            if (want_deps && share_live[t]
                    && status[t] != invalidated_code
                    && wit_row[(uint8_t)kind[t]]
                    && ts_less(txn_id + (int64_t)t * lanes, bound, lanes)) {
                drow[t] = 1;
            }
            if (want_max && share_full[t]) {
                const int32_t* slot_ts = ts + (int64_t)t * lanes;
                const int32_t* slot_id = txn_id + (int64_t)t * lanes;
                const int32_t* cand =
                    ts_less(slot_ts, slot_id, lanes) ? slot_id : slot_ts;
                bool bigger = !any;
                if (any) {
                    for (int i = 0; i < lanes; ++i) {
                        if ((int64_t)cand[i] != best[i]) {
                            bigger = (int64_t)cand[i] > best[i];
                            break;
                        }
                    }
                }
                if (bigger) {
                    for (int i = 0; i < lanes; ++i) best[i] = cand[i];
                    any = true;
                }
            }
        }
        if (want_max) {
            int64_t* mrow = out_max + (int64_t)b * lanes;
            for (int i = 0; i < lanes; ++i) mrow[i] = best[i];
        }
    }
    std::free(share_full);
    return 0;
}

}  // extern "C"
