"""Per-node epoch ledger with epoch-sync quorum tracking.

Capability parity with ``accord.topology.TopologyManager`` (TopologyManager.java:78-795):
tracks every topology epoch this node has learned, which remote nodes have finished
syncing each epoch (a quorum per shard makes the epoch "synced"), epoch
closure/redundancy marks, and selects the Topologies a coordination round must contact
for a route over [txnId.epoch, executeAt.epoch] — extended downward over unsynced
epochs (``with_unsynced_epochs``) so no dependency can be missed during topology
change.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set

from ..primitives.keys import Ranges
from ..utils import async_ as au
from ..utils.invariants import check_argument, check_state
from .topology import Topologies, Topology


class EpochReady:
    """Four stages of epoch adoption (ConfigurationService.java:65): metadata known,
    coordination possible, data bootstrapped, reads allowed."""

    __slots__ = ("epoch", "metadata", "coordination", "data", "reads")

    def __init__(self, epoch: int,
                 metadata: au.AsyncResult = None, coordination: au.AsyncResult = None,
                 data: au.AsyncResult = None, reads: au.AsyncResult = None):
        self.epoch = epoch
        self.metadata = metadata or au.success_result()
        self.coordination = coordination or au.success_result()
        self.data = data or au.success_result()
        self.reads = reads or au.success_result()

    @staticmethod
    def done(epoch: int) -> "EpochReady":
        return EpochReady(epoch)


class _EpochState:
    __slots__ = ("topology", "synced_nodes", "sync_complete", "closed", "redundant",
                 "ready")

    def __init__(self, topology: Topology):
        self.topology = topology
        self.synced_nodes: Set[int] = set()
        self.sync_complete = False
        self.closed: Ranges = Ranges.EMPTY
        self.redundant: Ranges = Ranges.EMPTY
        self.ready: Optional[EpochReady] = None

    def recompute_sync(self) -> None:
        if self.sync_complete:
            return
        for shard in self.topology.shards:
            acks = sum(1 for n in shard.nodes if n in self.synced_nodes)
            if acks < shard.slow_path_quorum_size:
                return
        self.sync_complete = True


class TopologyManager:
    def __init__(self, node_id: int, sorter=None):
        self.node_id = node_id
        self.sorter = sorter
        self._epochs: List[_EpochState] = []   # index 0 = min_epoch
        self._min_epoch = 0
        self._awaiting: Dict[int, List[au.Settable]] = {}
        # sync-complete reports that arrived before we learned the epoch
        self._pending_sync: Dict[int, Set[int]] = {}

    # -- queries ------------------------------------------------------------
    @property
    def min_epoch(self) -> int:
        return self._min_epoch

    @property
    def current_epoch(self) -> int:
        return self._min_epoch + len(self._epochs) - 1 if self._epochs else 0

    def current(self) -> Topology:
        check_state(bool(self._epochs), "no topology known yet")
        return self._epochs[-1].topology

    def has_epoch(self, epoch: int) -> bool:
        return self._min_epoch <= epoch <= self.current_epoch and bool(self._epochs)

    def topology_for_epoch(self, epoch: int) -> Topology:
        check_argument(self.has_epoch(epoch), "unknown epoch %s", epoch)
        return self._epochs[epoch - self._min_epoch].topology

    def is_sync_complete(self, epoch: int) -> bool:
        return self.has_epoch(epoch) and self._epochs[epoch - self._min_epoch].sync_complete

    # -- updates ------------------------------------------------------------
    def on_topology_update(self, topology: Topology,
                           ready_factory: Optional[Callable[[Topology], EpochReady]] = None
                           ) -> EpochReady:
        if self._epochs:
            check_argument(topology.epoch == self.current_epoch + 1,
                           "expected epoch %s, got %s", self.current_epoch + 1, topology.epoch)
        else:
            self._min_epoch = topology.epoch
        state = _EpochState(topology)
        self._epochs.append(state)
        # first epoch has nothing to sync from; mark prior-epoch-less epochs synced
        if len(self._epochs) == 1:
            state.sync_complete = True
        # apply any sync reports that raced ahead of the topology
        for n in self._pending_sync.pop(topology.epoch, ()):  # type: ignore[arg-type]
            state.synced_nodes.add(n)
        state.recompute_sync()
        state.ready = ready_factory(topology) if ready_factory else EpochReady.done(topology.epoch)
        for waiter in self._awaiting.pop(topology.epoch, []):
            waiter.set_success(topology)
        return state.ready

    def on_remote_sync_complete(self, node: int, epoch: int) -> None:
        """``node`` reports it has finished syncing ``epoch``."""
        if not self.has_epoch(epoch):
            if epoch <= self.current_epoch:
                return  # epoch already truncated — stale report
            self._pending_sync.setdefault(epoch, set()).add(node)
            return
        state = self._epochs[epoch - self._min_epoch]
        state.synced_nodes.add(node)
        state.recompute_sync()

    def reload_prior_epoch(self, topology: Topology,
                           synced_nodes: Optional[Set[int]] = None) -> None:
        """Restart path (crash-restart nemesis): re-install a durably-known
        epoch OLDER than the boot epoch.  Topology metadata is durable state
        on a real node — a restarted node must still answer
        ``precise_epochs`` for transactions that started in epochs its
        in-memory manager was rebuilt after.  Prepends strictly-consecutive
        epochs below ``min_epoch``; closure/redundancy marks are volatile and
        conservatively reset (they re-accumulate from durability rounds)."""
        check_state(bool(self._epochs), "boot epoch must be installed first")
        check_argument(topology.epoch == self._min_epoch - 1,
                       "prior-epoch reload must be consecutive: expected %s, got %s",
                       self._min_epoch - 1, topology.epoch)
        state = _EpochState(topology)
        state.synced_nodes = set(synced_nodes or ())
        # the first epoch overall has no predecessor to sync from
        state.sync_complete = topology.epoch == 1
        state.recompute_sync()
        state.ready = EpochReady.done(topology.epoch)
        self._epochs.insert(0, state)
        self._min_epoch = topology.epoch

    def truncate_until(self, epoch: int) -> None:
        """Drop epochs strictly below ``epoch`` (topology GC)."""
        if epoch <= self._min_epoch:
            return
        drop = min(epoch - self._min_epoch, len(self._epochs) - 1)
        if drop > 0:
            self._epochs = self._epochs[drop:]
            self._min_epoch += drop
        for stale in [e for e in self._pending_sync if e < self._min_epoch]:
            del self._pending_sync[stale]
        for stale in [e for e in self._awaiting if e < self._min_epoch]:
            del self._awaiting[stale]

    def on_epoch_closed(self, ranges: Ranges, epoch: int) -> None:
        if self.has_epoch(epoch):
            st = self._epochs[epoch - self._min_epoch]
            st.closed = st.closed.union(ranges)

    def on_epoch_redundant(self, ranges: Ranges, epoch: int) -> None:
        if self.has_epoch(epoch):
            st = self._epochs[epoch - self._min_epoch]
            st.redundant = st.redundant.union(ranges)

    # -- awaiting -----------------------------------------------------------
    def await_epoch(self, epoch: int) -> au.AsyncResult:
        if self.has_epoch(epoch):
            return au.success_result(self.topology_for_epoch(epoch))
        s = au.settable()
        self._awaiting.setdefault(epoch, []).append(s)
        return s

    def fail_epoch_waiters(self, epoch: int, failure: BaseException) -> None:
        """The epoch-fetch watchdog gave up (configuration service
        unreachable): fail every waiter so gated work errors out instead of
        stalling forever (TopologyManager.java epoch-fetch watchdog)."""
        for s in self._awaiting.pop(epoch, []):
            if not s.is_done():
                s.set_failure(failure)

    # -- coordination selection (TopologyManager.java:513+) ------------------
    def precise_epochs(self, unseekables, min_epoch: int, max_epoch: int) -> Topologies:
        """Topologies over [min_epoch, max_epoch], each trimmed to the shards
        intersecting ``unseekables`` (a Route/RoutingKeys/Ranges, or None for all)."""
        check_argument(self.has_epoch(min_epoch) and self.has_epoch(max_epoch),
                       "epochs [%s,%s] not all known", min_epoch, max_epoch)
        return Topologies([self.topology_for_epoch(e).trim(unseekables)
                           for e in range(min_epoch, max_epoch + 1)])

    def with_unsynced_epochs(self, unseekables, min_epoch: int, max_epoch: int) -> Topologies:
        """Like precise_epochs but extended down over epochs that are not both
        sync-complete AND CLOSED over the footprint.  Sync alone is not enough:
        an epoch may be synced while old-epoch transactions are still in flight
        on its replicas — a dependency round that skips them can miss a
        committed-at-old-executeAt txn entirely (the bootstrap-fence
        completeness hole).  Only an applied exclusive sync point closes an
        epoch's ranges to new proposals (TopologyManager epoch closure,
        TopologyManager.java:78-795)."""
        lo = min_epoch
        while lo > self._min_epoch and not (
                self.is_sync_complete(lo - 1)
                and self._closed_over(lo - 1, unseekables)):
            lo -= 1
        return self.precise_epochs(unseekables, lo, max_epoch)

    def _closed_over(self, epoch: int, unseekables) -> bool:
        """Is every part of ``unseekables`` marked closed at ``epoch``?"""
        if not self.has_epoch(epoch):
            return True
        st = self._epochs[epoch - self._min_epoch]
        from ..primitives.route import Route
        parts = unseekables.participants() if isinstance(unseekables, Route) \
            else unseekables
        if parts is None:
            return False
        return st.closed.contains_all(parts)

    def with_open_epochs(self, unseekables, min_epoch: int, max_epoch: int) -> Topologies:
        return self.with_unsynced_epochs(unseekables, min_epoch, max_epoch)
