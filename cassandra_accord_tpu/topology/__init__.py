from .topology import Shard, Topologies, Topology
from .manager import EpochReady, TopologyManager

__all__ = ["Shard", "Topology", "Topologies", "TopologyManager", "EpochReady"]
