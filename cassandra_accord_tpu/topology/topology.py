"""Epoch-versioned shard maps and quorum math.

Capability parity with ``accord.topology.Shard/Topology/Topologies``
(Shard.java:38-90, Topology.java:61-272, Topologies.java:1-485):

- ``Shard``: a key range + its replica list + the fast-path electorate + joining set,
  with the Accord quorum sizes: f = (n-1)//2 tolerated failures, slow-path quorum
  n - f (simple majority), fast-path quorum (f+e)//2 + 1 over an electorate of size e,
  recovery fast-path size (f+1)//2.
- ``Topology``: one epoch's sorted, non-overlapping shard array with per-node subset
  views and selection/trim operations.
- ``Topologies``: a multi-epoch stack spanning [txnId.epoch, executeAt.epoch] used to
  address coordination messages across concurrent epochs.
"""
from __future__ import annotations

from bisect import bisect_right
from typing import Dict, FrozenSet, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

from ..primitives.keys import Range, Ranges, RoutingKey
from ..primitives.route import Route
from ..utils.invariants import check_argument, check_state


def max_tolerated_failures(replicas: int) -> int:
    return (replicas - 1) // 2


def slow_path_quorum_size(replicas: int) -> int:
    return replicas - max_tolerated_failures(replicas)


def fast_path_quorum_size(replicas: int, electorate: int, f: int) -> int:
    check_argument(electorate >= replicas - f, "electorate too small")
    return (f + electorate) // 2 + 1


class Shard:
    __slots__ = ("range", "nodes", "fast_path_electorate", "joining",
                 "max_failures", "recovery_fast_path_size",
                 "fast_path_quorum_size", "slow_path_quorum_size")

    def __init__(self, range_: Range, nodes: Sequence[int],
                 fast_path_electorate: Optional[Iterable[int]] = None,
                 joining: Optional[Iterable[int]] = None):
        self.range = range_
        self.nodes: Tuple[int, ...] = tuple(sorted(nodes))
        electorate = frozenset(fast_path_electorate) if fast_path_electorate is not None \
            else frozenset(self.nodes)
        self.fast_path_electorate: FrozenSet[int] = electorate
        self.joining: FrozenSet[int] = frozenset(joining or ())
        check_argument(self.joining.issubset(self.nodes),
                       "joining nodes must also be present in nodes")
        n = len(self.nodes)
        f = max_tolerated_failures(n)
        self.max_failures = f
        self.recovery_fast_path_size = (f + 1) // 2
        self.slow_path_quorum_size = slow_path_quorum_size(n)
        self.fast_path_quorum_size = fast_path_quorum_size(n, len(electorate), f)

    def rf(self) -> int:
        return len(self.nodes)

    def contains(self, key: RoutingKey) -> bool:
        return self.range.contains(key)

    def contains_node(self, node: int) -> bool:
        return node in self.nodes

    def rejects_fast_path(self, reject_count: int) -> bool:
        """Enough electorate rejections that fast path can no longer be reached."""
        return reject_count > len(self.fast_path_electorate) - self.fast_path_quorum_size

    def __eq__(self, other) -> bool:
        return (isinstance(other, Shard) and self.range == other.range
                and self.nodes == other.nodes
                and self.fast_path_electorate == other.fast_path_electorate
                and self.joining == other.joining)

    def __hash__(self):
        return hash((self.range, self.nodes))

    def __repr__(self) -> str:
        return f"Shard({self.range!r}, n={list(self.nodes)}, fp={sorted(self.fast_path_electorate)})"


class Topology:
    """One epoch's shard map. Shards sorted by range start; ranges non-overlapping."""

    __slots__ = ("epoch", "shards", "_starts", "_node_ids")

    def __init__(self, epoch: int, shards: Sequence[Shard]):
        self.epoch = epoch
        self.shards: Tuple[Shard, ...] = tuple(sorted(shards, key=lambda s: s.range))
        for a, b in zip(self.shards, self.shards[1:]):
            check_argument(not a.range.intersects(b.range),
                           "shard ranges overlap: %s %s", a.range, b.range)
        self._starts = [s.range.start for s in self.shards]
        ids: Set[int] = set()
        for s in self.shards:
            ids.update(s.nodes)
        self._node_ids = frozenset(ids)

    EMPTY: "Topology"

    @property
    def size(self) -> int:
        return len(self.shards)

    def nodes(self) -> FrozenSet[int]:
        return self._node_ids

    def contains_node(self, node: int) -> bool:
        return node in self._node_ids

    def ranges(self) -> Ranges:
        return Ranges.of(*[s.range for s in self.shards])

    # -- lookup -------------------------------------------------------------
    def for_key(self, key: RoutingKey) -> Optional[Shard]:
        i = bisect_right(self._starts, key) - 1
        if i >= 0 and self.shards[i].range.contains(key):
            return self.shards[i]
        return None

    def for_key_required(self, key: RoutingKey) -> Shard:
        s = self.for_key(key)
        check_state(s is not None, "no shard for key %s in epoch %s" % (key, self.epoch))
        return s

    def for_selection(self, unseekables) -> List[Shard]:
        """Shards intersecting a RoutingKeys/Ranges/Route selection."""
        if isinstance(unseekables, Route):
            unseekables = unseekables.participants()
        out: List[Shard] = []
        if isinstance(unseekables, Ranges):
            for s in self.shards:
                if unseekables.intersects(s.range):
                    out.append(s)
        else:
            for s in self.shards:
                if any(s.range.contains(k) for k in unseekables):
                    out.append(s)
        return out

    def for_node(self, node: int) -> "Topology":
        return Topology(self.epoch, [s for s in self.shards if s.contains_node(node)])

    def trim(self, unseekables) -> "Topology":
        """Subset topology containing only shards intersecting the selection
        (Topology.forSelection/trim semantics)."""
        if unseekables is None:
            return self
        return Topology(self.epoch, self.for_selection(unseekables))

    def ranges_for_node(self, node: int) -> Ranges:
        return Ranges.of(*[s.range for s in self.shards if s.contains_node(node)])

    def nodes_for(self, unseekables) -> List[int]:
        ids: Set[int] = set()
        for s in self.for_selection(unseekables):
            ids.update(s.nodes)
        return sorted(ids)

    def __eq__(self, other) -> bool:
        return isinstance(other, Topology) and self.epoch == other.epoch and self.shards == other.shards

    def __hash__(self):
        return hash((self.epoch, self.shards))

    def __repr__(self) -> str:
        return f"Topology(e{self.epoch}, {list(self.shards)!r})"


Topology.EMPTY = Topology(0, [])


class Topologies:
    """Multi-epoch stack, newest first (Topologies.java semantics)."""

    __slots__ = ("topologies",)

    def __init__(self, topologies: Sequence[Topology]):
        check_argument(len(topologies) > 0, "empty Topologies")
        ts = sorted(topologies, key=lambda t: -t.epoch)
        for a, b in zip(ts, ts[1:]):
            check_argument(a.epoch == b.epoch + 1, "non-contiguous epochs")
        self.topologies: Tuple[Topology, ...] = tuple(ts)

    @property
    def current_epoch(self) -> int:
        return self.topologies[0].epoch

    @property
    def oldest_epoch(self) -> int:
        return self.topologies[-1].epoch

    def current(self) -> Topology:
        return self.topologies[0]

    def for_epoch(self, epoch: int) -> Topology:
        i = self.current_epoch - epoch
        check_argument(0 <= i < len(self.topologies), "epoch %s not in %s", epoch, self)
        return self.topologies[i]

    def contains_epoch(self, epoch: int) -> bool:
        return self.oldest_epoch <= epoch <= self.current_epoch

    def for_epochs(self, min_epoch: int, max_epoch: int) -> "Topologies":
        return Topologies([t for t in self.topologies if min_epoch <= t.epoch <= max_epoch])

    def size(self) -> int:
        return len(self.topologies)

    def nodes(self) -> FrozenSet[int]:
        ids: Set[int] = set()
        for t in self.topologies:
            ids.update(t.nodes())
        return frozenset(ids)

    def nodes_for(self, unseekables) -> List[int]:
        ids: Set[int] = set()
        for t in self.topologies:
            ids.update(t.nodes_for(unseekables))
        return sorted(ids)

    def __iter__(self) -> Iterator[Topology]:
        return iter(self.topologies)

    def __repr__(self) -> str:
        return f"Topologies({[t.epoch for t in self.topologies]})"
