"""The crash-restart nemesis: kill nodes mid-burn, rebuild them from journal
replay.

Capability parity with the reference burn's node-restart axis (BurnTest's
journal-backed restarts: a node's in-memory state is discarded and
reconstructed from its journal, then the protocol heals what the journal
predates).  At seeded, jittered points in a burn a victim is crashed via
``Cluster.crash`` — volatile stores, caches, device mirrors, callbacks and
timers destroyed, in-flight messages to it dropped — and restarted after a
seeded downtime via ``Cluster.restart`` (journal replay + topology re-join +
bootstrap catch-up).

Safety rails (LocalConfig knobs): at most ``restart_max_down`` nodes down at
once, and a victim is only eligible if every shard it replicates keeps a live
slow-path quorum (``restart_keep_quorum``) — without that floor, stalls are
expected rather than bugs.
"""
from __future__ import annotations

from typing import Callable, Optional

from ..utils.random import RandomSource
from .cluster import Cluster


class RestartNemesis:
    """One per burn; schedule driven by the cluster's deterministic queue."""

    def __init__(self, cluster: Cluster, rng: RandomSource,
                 interval_s: float = 20.0,
                 downtime_min_s: float = 2.0, downtime_max_s: float = 12.0,
                 max_down: int = 1, keep_quorum: bool = True,
                 on_crash: Optional[Callable[[int], None]] = None,
                 on_restart: Optional[Callable[[object], None]] = None):
        self.cluster = cluster
        self.rng = rng
        self.interval_s = interval_s
        self.downtime_min_s = downtime_min_s
        self.downtime_max_s = max(downtime_max_s, downtime_min_s)
        self.max_down = max_down
        self.keep_quorum = keep_quorum
        self.on_crash = on_crash
        self.on_restart = on_restart
        self.stopped = False
        self._task = None

    def attach(self) -> None:
        """Register the jittered crash cadence (never aligned with the chaos
        re-roll interval: each gap is resampled in [0.5, 1.5) x interval)."""
        rng = self.rng

        def gap():
            return self.interval_s * (0.5 + rng.next_float())

        self._task = self.cluster.scheduler.recurring(gap, self._tick)

    # -- the schedule --------------------------------------------------------
    def _tick(self) -> None:
        if self.stopped or len(self.cluster.down) >= self.max_down:
            return
        victim = self._pick_victim()
        if victim is None:
            return
        self.cluster.crash(victim)
        if self.on_crash is not None:
            self.on_crash(victim)
        downtime = self.downtime_min_s + self.rng.next_float() * (
            self.downtime_max_s - self.downtime_min_s)
        self.cluster.scheduler.once(downtime, lambda: self._restart(victim))

    def _pick_victim(self) -> Optional[int]:
        candidates = []
        for node_id in sorted(self.cluster.nodes):
            if node_id in self.cluster.down:
                continue
            if self.keep_quorum and not self._quorum_safe(node_id):
                continue
            candidates.append(node_id)
        return self.rng.pick(candidates) if candidates else None

    def _quorum_safe(self, node_id: int) -> bool:
        """Would crashing ``node_id`` leave every shard it replicates — in
        EVERY installed epoch, not only the latest — with a live slow-path
        quorum?  Old epochs matter: a txn coordinated or recovered against a
        pre-churn shard still needs that shard's quorum until the epoch
        retires, so checking only ``topologies[-1]`` would let
        ``restart_max_down >= 2`` crash two members of an old shard and
        produce an *expected* stall the watchdog then reports as a bug.
        (Conservative: epochs whose txns have all settled are still counted.)"""
        would_down = self.cluster.down | {node_id}
        for topology in self.cluster.topologies:
            for shard in topology.shards:
                if node_id in shard.nodes:
                    live = sum(1 for n in shard.nodes if n not in would_down)
                    if live < shard.slow_path_quorum_size:
                        return False
        return True

    def _restart(self, node_id: int) -> None:
        if node_id not in self.cluster.down:
            return   # already restored (stop_and_restore raced the timer)
        node = self.cluster.restart(node_id)
        if self.on_restart is not None:
            self.on_restart(node)

    # -- quiesce -------------------------------------------------------------
    def stop_and_restore(self) -> None:
        """Stop crashing and bring every down node back (burn quiesce: the
        final agreement checks need the full replica set live and caught up)."""
        self.stopped = True
        if self._task is not None:
            self._task.cancel()
        for node_id in sorted(self.cluster.down):
            self._restart(node_id)
