"""The gray-failure nemeses: kill, pause and disk-stall nodes mid-burn.

Capability parity with the reference burn's node-restart axis (BurnTest's
journal-backed restarts) plus the in-between regimes its
``SimulatedDelayedExecutorService`` and journal machinery exercise — the
failures that are NOT fail-stop:

- ``RestartNemesis``: seeded kills + journal-replay rebuilds
  (``Cluster.crash``/``restart``), now with crash-time journal damage
  injection — torn tail records and bit flips the restart replay must
  detect (checksums) and absorb (truncate / quarantine-and-bootstrap).
- ``PauseNemesis``: stop-the-world process pauses (GC pause, VM migration,
  SIGSTOP): the victim's scheduler, sinks, executors and timers freeze, then
  resume with every frozen timer late-firing — peers observe silence from a
  node that is slow, NOT dead, violating every timeout assumption.
- ``DiskStallNemesis``: journal-append stalls (fsync latency spikes):
  durability — and with it every outbound packet, fsync-before-reply —
  lags execution; a crash mid-stall loses the whole unsynced tail.

Safety rails (LocalConfig knobs): at most ``restart_max_down`` nodes down
(and ``pause_max_paused`` paused) at once, and a victim is only eligible if
every shard it replicates keeps a live slow-path quorum counting every
MUTED node — down, paused, or journal-stalled — as unavailable (see
``muted_nodes``); without that shared floor, stalls are expected rather
than bugs.  The default cadences (20s / 15s / 17s) are deliberately
de-aligned AND sized so the three axes COMBINED inject roughly the fault
rate the single-axis restart matrix ran at: fault rate has to stay below
the bootstrap/recovery heal rate, or the burn degenerates into a
perpetually-bootstrapping cluster and the watchdog reports the (expected)
unavailability as a stall.
"""
from __future__ import annotations

from typing import Callable, Optional

from ..topology.topology import Topology
from ..utils.random import RandomSource
from .cluster import Cluster


def muted_nodes(cluster: Cluster) -> set:
    """Every node currently unable to answer its peers: down, stop-the-world
    paused, or journal-stalled (fsync-before-reply holds its packets).  The
    quorum floor of EVERY nemesis counts all three — the fault axes are
    independent, and without a shared floor their overlap mutes whole
    quorums, producing *expected* stalls the watchdog reports as bugs."""
    muted = cluster.down | cluster.paused
    if cluster.journal is not None:
        muted |= {n for n in cluster.nodes if cluster.journal.is_stalled(n)}
    return muted


def quorum_safe(cluster: Cluster, node_id: int, unavailable) -> bool:
    """Would making ``node_id`` unavailable leave every shard it replicates —
    in EVERY installed epoch, not only the latest — with a live slow-path
    quorum?  Old epochs matter: a txn coordinated or recovered against a
    pre-churn shard still needs that shard's quorum until the epoch retires,
    so checking only ``topologies[-1]`` would let two fault axes take out two
    members of an old shard and produce an *expected* stall the watchdog then
    reports as a bug.  (Conservative: epochs whose txns have all settled are
    still counted.)"""
    would_down = set(unavailable) | {node_id}
    for topology in cluster.topologies:
        for shard in topology.shards:
            if node_id in shard.nodes:
                live = sum(1 for n in shard.nodes if n not in would_down)
                if live < shard.slow_path_quorum_size:
                    return False
    return True


class RestartNemesis:
    """One per burn; schedule driven by the cluster's deterministic queue."""

    def __init__(self, cluster: Cluster, rng: RandomSource,
                 interval_s: float = 20.0,
                 downtime_min_s: float = 2.0, downtime_max_s: float = 12.0,
                 max_down: int = 1, keep_quorum: bool = True,
                 torn_tail_chance: float = 0.0,
                 corrupt_chance: float = 0.0,
                 on_crash: Optional[Callable[[int], None]] = None,
                 on_restart: Optional[Callable[[object], None]] = None):
        self.cluster = cluster
        self.rng = rng
        self.interval_s = interval_s
        self.downtime_min_s = downtime_min_s
        self.downtime_max_s = max(downtime_max_s, downtime_min_s)
        self.max_down = max_down
        self.keep_quorum = keep_quorum
        # crash-time journal damage: probability the crash tears the tail
        # record (partial append) / bit-flips a random record (bit rot)
        self.torn_tail_chance = torn_tail_chance
        self.corrupt_chance = corrupt_chance
        self.on_crash = on_crash
        self.on_restart = on_restart
        self.stopped = False
        self._task = None

    def attach(self) -> None:
        """Register the jittered crash cadence (never aligned with the chaos
        re-roll interval: each gap is resampled in [0.5, 1.5) x interval)."""
        rng = self.rng

        def gap():
            return self.interval_s * (0.5 + rng.next_float())

        self._task = self.cluster.scheduler.recurring(gap, self._tick)

    # -- the schedule --------------------------------------------------------
    def _tick(self) -> None:
        if self.stopped or len(self.cluster.down) >= self.max_down:
            return
        victim = self._pick_victim()
        if victim is None:
            return
        self.cluster.crash(victim)
        self._inject_journal_damage(victim)
        if self.on_crash is not None:
            self.on_crash(victim)
        downtime = self.downtime_min_s + self.rng.next_float() * (
            self.downtime_max_s - self.downtime_min_s)
        self.cluster.scheduler.once(downtime, lambda: self._restart(victim))

    def _inject_journal_damage(self, victim: int) -> None:
        """Seeded post-crash damage to the victim's durable log — what the
        restart replay's checksum verification must catch."""
        journal = self.cluster.journal
        if journal is None:
            return
        if self.torn_tail_chance and self.rng.next_float() < self.torn_tail_chance:
            # age gate = the minimum link latency: a record older than that
            # may have been ACKED to a peer (fsync-before-reply) and tearing
            # it would roll back a promise the protocol assumes stable —
            # injection unsoundness, not a fault model
            torn = journal.tear_tail_record(
                victim, self.rng,
                max_age_us=self.cluster.link.min_latency_us)
            if torn:
                self.cluster.stats["journal_injected_tears"] = \
                    self.cluster.stats.get("journal_injected_tears", 0) + torn
        if self.corrupt_chance and self.rng.next_float() < self.corrupt_chance:
            if journal.corrupt_random_record(victim, self.rng) is not None:
                self.cluster.stats["journal_injected_bitflips"] = \
                    self.cluster.stats.get("journal_injected_bitflips", 0) + 1

    def _pick_victim(self) -> Optional[int]:
        candidates = []
        unavailable = muted_nodes(self.cluster)
        for node_id in sorted(self.cluster.nodes):
            if node_id in self.cluster.down:
                continue
            if self.keep_quorum and not quorum_safe(self.cluster, node_id,
                                                    unavailable):
                continue
            candidates.append(node_id)
        return self.rng.pick(candidates) if candidates else None

    def _restart(self, node_id: int) -> None:
        if node_id not in self.cluster.down:
            return   # already restored (stop_and_restore raced the timer)
        node = self.cluster.restart(node_id)
        if self.on_restart is not None:
            self.on_restart(node)

    # -- quiesce -------------------------------------------------------------
    def stop_and_restore(self) -> None:
        """Stop crashing and bring every down node back (burn quiesce: the
        final agreement checks need the full replica set live and caught up)."""
        self.stopped = True
        if self._task is not None:
            self._task.cancel()
        for node_id in sorted(self.cluster.down):
            self._restart(node_id)


class MembershipNemesis:
    """Elastic membership under load: seeded join (``Cluster.add_node`` + a
    join epoch through the randomizer's elastic mutations) and decommission
    (``Cluster.decommission`` — hand-off + removal from every shard in one
    epoch) cycles, holding the member count inside
    [``min_members``, ``max_members``].

    Floors shared with every other nemesis: joins/leaves respect the
    randomizer's clean-readable-quorum-per-range check (a newcomer counts
    unavailable until its bootstrap fetch lands), leaves additionally
    require every affected shard to keep a live slow-path quorum counting
    MUTED nodes (down / paused / journal-stalled) unavailable, and the whole
    schedule is gated on outstanding bootstraps like topology churn — a
    membership change is a bootstrap storm by construction, and stacking
    them outruns the heal rate into expected (reported-as-stall)
    unavailability."""

    def __init__(self, cluster: Cluster, rng: RandomSource,
                 randomizer, interval_s: float = 25.0,
                 min_members: Optional[int] = None,
                 max_members: Optional[int] = None,
                 spawn_pool: Optional[list] = None,
                 on_join: Optional[Callable[[int], None]] = None,
                 on_leave: Optional[Callable[[int], None]] = None):
        self.cluster = cluster
        self.rng = rng
        self.randomizer = randomizer
        self.interval_s = interval_s
        initial = len(cluster.topologies[-1].nodes())
        self.min_members = min_members if min_members is not None \
            else max(3, initial - 1)
        self.max_members = max_members if max_members is not None \
            else initial + max(2, initial // 2)
        if spawn_pool:
            self.randomizer.spawn_pool = sorted(
                set(self.randomizer.spawn_pool) | set(spawn_pool))
        # both membership planes honor the same bounds: the churn-mix
        # join/leave actions otherwise bypass membership_{min,max}_members
        self.randomizer.min_members = self.min_members
        self.randomizer.max_members = self.max_members
        self.on_join = on_join
        self.on_leave = on_leave
        self.joins = 0
        self.leaves = 0
        self.stopped = False
        self._task = None

    def attach(self) -> None:
        rng = self.rng

        def gap():
            return self.interval_s * (0.5 + rng.next_float())

        self._task = self.cluster.scheduler.recurring(gap, self._tick)

    def _tick(self) -> None:
        cluster = self.cluster
        if self.stopped:
            return
        # same bootstrap gate as topology churn: a membership change while
        # many ranges are mid-bootstrap stacks fetch load the cluster is
        # already struggling to drain
        pending = {rng for node in cluster.nodes.values()
                   for cs in node.command_stores.all_stores()
                   for rng in (cs.pending_bootstrap or ())}
        if len(pending) > 3:
            return
        current = cluster.topologies[-1]
        members = sorted(current.nodes())
        want_join = len(members) <= self.min_members or (
            len(members) < self.max_members and self.rng.next_boolean())
        shards = list(current.shards)
        if want_join:
            new_shards = self.randomizer._join(shards, current)
            if new_shards is None:
                return
            topo = Topology(current.epoch + 1, new_shards)
            cluster.update_topology(topo)
            self.joins += 1
            joined = sorted(topo.nodes() - current.nodes())
            if self.on_join is not None and joined:
                self.on_join(joined[0])
        else:
            new_shards = self.randomizer._leave(shards, current)
            if new_shards is None:
                return
            topo = Topology(current.epoch + 1, new_shards)
            cluster.update_topology(topo)
            self.leaves += 1
            left = sorted(current.nodes() - topo.nodes())
            if self.on_leave is not None and left:
                self.on_leave(left[0])

    def stop(self) -> None:
        """Stop scheduling membership changes (burn quiesce).  Drained nodes
        stay live — the final agreement checks judge the LAST topology's
        replica sets, and prior epochs still need their members."""
        self.stopped = True
        if self._task is not None:
            self._task.cancel()


class PauseNemesis:
    """Stop-the-world process pauses at seeded, jittered points: the victim's
    scheduler, sinks, store executors and timers freeze (``Cluster.pause``);
    at resume every frozen timer and buffered delivery late-fires in order —
    the post-GC-pause timer storm.  Peers observe only silence: the node is
    slow, NOT dead, which is exactly the regime flat timeouts misclassify."""

    def __init__(self, cluster: Cluster, rng: RandomSource,
                 interval_s: float = 15.0,
                 pause_min_s: float = 0.5, pause_max_s: float = 4.0,
                 max_paused: int = 1, keep_quorum: bool = True,
                 on_pause: Optional[Callable[[int], None]] = None,
                 on_resume: Optional[Callable[[int], None]] = None):
        self.cluster = cluster
        self.rng = rng
        self.interval_s = interval_s
        self.pause_min_s = pause_min_s
        self.pause_max_s = max(pause_max_s, pause_min_s)
        self.max_paused = max_paused
        self.keep_quorum = keep_quorum
        self.on_pause = on_pause
        self.on_resume = on_resume
        self.stopped = False
        self._task = None

    def attach(self) -> None:
        rng = self.rng

        def gap():
            return self.interval_s * (0.5 + rng.next_float())

        self._task = self.cluster.scheduler.recurring(gap, self._tick)

    def _tick(self) -> None:
        cluster = self.cluster
        if self.stopped or len(cluster.paused) >= self.max_paused:
            return
        unavailable = muted_nodes(cluster)
        candidates = []
        for node_id in sorted(cluster.nodes):
            if node_id in unavailable:
                continue
            if self.keep_quorum and not quorum_safe(cluster, node_id,
                                                    unavailable):
                continue
            candidates.append(node_id)
        if not candidates:
            return
        victim = self.rng.pick(candidates)
        token = cluster.pause(victim)
        if self.on_pause is not None:
            self.on_pause(victim)
        duration = self.pause_min_s + self.rng.next_float() * (
            self.pause_max_s - self.pause_min_s)
        cluster.scheduler.once(duration, lambda: self._resume(victim, token))

    def _resume(self, node_id: int, token: int) -> None:
        # token-guarded: if the node crashed (clearing the pause) and was
        # paused AGAIN since, this stale timer must not cut the new pause short
        if node_id in self.cluster.paused:
            self.cluster.resume(node_id, token)
            if node_id not in self.cluster.paused and self.on_resume is not None:
                self.on_resume(node_id)

    def stop_and_restore(self) -> None:
        """Resume every paused node (burn quiesce)."""
        self.stopped = True
        if self._task is not None:
            self._task.cancel()
        for node_id in sorted(self.cluster.paused):
            self.cluster.resume(node_id)


class LoadSpikeNemesis:
    """Deterministic offered-load schedule for open-loop burns: a list of
    ``(start_s, rate_mult)`` phases, each armed as ONE absolute sim-time
    timer that sets the workload's ``rate_mult``.  Unlike the gray-failure
    nemeses this one is fully deterministic — no RNG, no jitter — because
    the overload oracle compares goodput ACROSS multipliers, and a jittered
    phase boundary would smear the measurement windows.

    ``phase_of(now_s)`` reports which phase a given sim-instant falls in, so
    the burn can bucket per-op outcomes by phase (the burst-recovery check
    needs pre/burst/post goodput separately)."""

    def __init__(self, cluster: Cluster, workload, phases):
        # phases: iterable of (start_s, rate_mult), start_s ascending
        self.cluster = cluster
        self.workload = workload
        self.phases = sorted((float(s), float(m)) for s, m in phases)
        assert all(m > 0.0 for _, m in self.phases), \
            "rate multipliers must be positive"
        self.transitions = 0
        self.stopped = False
        self._tasks = []

    def attach(self) -> None:
        now_s = self.cluster.queue.now_micros / 1e6
        for start_s, mult in self.phases:
            delay = start_s - now_s
            if delay <= 0.0:
                self._enter(mult)
                continue
            self._tasks.append(self.cluster.scheduler.once(
                delay, lambda m=mult: self._enter(m)))

    def _enter(self, mult: float) -> None:
        if self.stopped:
            return
        self.workload.rate_mult = mult
        self.transitions += 1
        self.cluster.stats["load_phase_transitions"] = \
            self.cluster.stats.get("load_phase_transitions", 0) + 1

    def phase_of(self, now_s: float) -> int:
        """Index of the phase containing ``now_s`` (-1 before the first)."""
        idx = -1
        for i, (start_s, _mult) in enumerate(self.phases):
            if now_s >= start_s:
                idx = i
        return idx

    def stop(self) -> None:
        """Freeze the schedule (burn quiesce): pending phase timers no-op."""
        self.stopped = True
        for task in self._tasks:
            cancel = getattr(task, "cancel", None)
            if cancel is not None:
                cancel()


class DiskStallNemesis:
    """Journal-append stalls at seeded, jittered points
    (``Cluster.stall_journal``): the victim keeps executing but nothing it
    writes becomes durable — and nothing it SENDS leaves the box
    (fsync-before-reply) — until the stall ends.  A crash landing inside the
    stall window (the restart nemesis runs independently) loses the whole
    unsynced journal tail, strictly more than ``drop_tail`` ever simulated,
    and the held packets with it — so peers never witnessed the lost state."""

    def __init__(self, cluster: Cluster, rng: RandomSource,
                 interval_s: float = 17.0,
                 stall_min_s: float = 1.0, stall_max_s: float = 6.0,
                 keep_quorum: bool = True,
                 on_stall: Optional[Callable[[int], None]] = None):
        assert cluster.journal is not None, \
            "disk stalls require the journal (the stalled device)"
        self.cluster = cluster
        self.rng = rng
        self.interval_s = interval_s
        self.stall_min_s = stall_min_s
        self.stall_max_s = max(stall_max_s, stall_min_s)
        # a stalled journal MUTES the node (fsync-before-reply): it needs the
        # same quorum floor as crashes and pauses, or overlapping fault axes
        # mute whole quorums (measured: seed 1 x 250 ops with all three axes
        # re-created the seed-6 bootstrap-refencing stall)
        self.keep_quorum = keep_quorum
        self.on_stall = on_stall
        self.stopped = False
        self._task = None

    def attach(self) -> None:
        rng = self.rng

        def gap():
            return self.interval_s * (0.5 + rng.next_float())

        self._task = self.cluster.scheduler.recurring(gap, self._tick)

    def _tick(self) -> None:
        cluster = self.cluster
        if self.stopped:
            return
        unavailable = muted_nodes(cluster)
        candidates = [n for n in sorted(cluster.nodes)
                      if n not in unavailable
                      and (not self.keep_quorum
                           or quorum_safe(cluster, n, unavailable))]
        if not candidates:
            return
        victim = self.rng.pick(candidates)
        token = cluster.stall_journal(victim)
        if self.on_stall is not None:
            self.on_stall(victim)
        duration = self.stall_min_s + self.rng.next_float() * (
            self.stall_max_s - self.stall_min_s)
        cluster.scheduler.once(duration,
                               lambda: cluster.unstall_journal(victim, token))

    def stop_and_restore(self) -> None:
        """Unstall every journal (burn quiesce: everything becomes durable
        and the held packets drain)."""
        self.stopped = True
        if self._task is not None:
            self._task.cancel()
        for node_id in sorted(self.cluster.nodes):
            if self.cluster.journal.is_stalled(node_id):
                self.cluster.unstall_journal(node_id)
