"""Deterministic simulated cluster: one seed => one fully reproducible multi-node run.

Capability parity with ``accord.impl.basic.Cluster`` + ``NodeSink`` +
``RandomDelayQueue`` (Cluster.java:121-903, NodeSink.java:45, RandomDelayQueue):
a single-threaded event loop over a priority queue of (virtual-micros, seq, task);
all network sends, scheduler callbacks and store tasks go through the queue; per-link
behavior (latency, drop, failure) is pluggable for fault injection.  Simulated time
advances to each task's deadline — wall-clock independence is what makes every run
replayable from its seed.
"""
from __future__ import annotations

import heapq
from typing import Callable, Dict, List, Optional, Tuple

from ..api.interfaces import Agent, ConfigurationService, DataStore, EventsListener, MessageSink, Scheduler
from ..impl.list_store import ListStore
from ..local.node import Node
from ..messages.base import Callback, FailureReply, Reply, Request
from ..primitives.timestamp import Timestamp
from ..topology.topology import Topology
from ..utils import async_ as au
from ..utils.random import RandomSource
from ..coordinate.errors import Timeout


class PendingQueue:
    """Priority queue keyed by virtual micros; seq breaks ties deterministically.

    Recurring tasks (periodic progress-log polls, durability cycles) are marked so
    ``run_until_idle`` can stop when only recurring work remains — the reference's
    ``processPending`` drains "until only recurring tasks remain"
    (Cluster.java:215-228)."""

    def __init__(self):
        self._heap: List[Tuple[int, int, Callable]] = []
        self._seq = 0
        self.now_micros = 0
        self._live_nonrecurring = 0

    def add(self, at_micros: int, task: Callable[[], None],
            recurring: bool = False) -> "PendingQueue._Entry":
        entry = PendingQueue._Entry(max(at_micros, self.now_micros), self._seq, task,
                                    recurring, self)
        self._seq += 1
        if not recurring:
            self._live_nonrecurring += 1
        heapq.heappush(self._heap, entry)
        return entry

    def add_after(self, delay_micros: int, task: Callable[[], None],
                  recurring: bool = False):
        return self.add(self.now_micros + delay_micros, task, recurring)

    def pop(self) -> Optional[Callable]:
        while self._heap:
            entry = heapq.heappop(self._heap)
            if entry.cancelled:
                continue
            entry.popped = True
            if not entry.recurring:
                self._live_nonrecurring -= 1
            self.now_micros = max(self.now_micros, entry.at)
            return entry.task
        return None

    def has_nonrecurring(self) -> bool:
        return self._live_nonrecurring > 0

    def __len__(self):
        return sum(1 for e in self._heap if not e.cancelled)

    class _Entry:
        __slots__ = ("at", "seq", "task", "cancelled", "recurring", "popped",
                     "_queue")

        def __init__(self, at: int, seq: int, task: Callable, recurring: bool = False,
                     queue: "PendingQueue" = None):
            self.at = at
            self.seq = seq
            self.task = task
            self.cancelled = False
            self.recurring = recurring
            # set when pop() hands the task out: cancel() after that must NOT
            # decrement the live counter again — cancelling an already-run
            # one-shot (e.g. CoordinateDurabilityScheduling.stop() sweeping
            # its fired entries) double-decremented _live_nonrecurring, the
            # queue then claimed idle while real timeouts still pended,
            # run_until_idle exited early, hung bootstrap fences never timed
            # out, and pending_bootstrap never cleared (seed-7 replica
            # divergence at the final-agreement check)
            self.popped = False
            self._queue = queue

        def cancel(self):
            if not self.cancelled and not self.popped:
                self.cancelled = True
                if not self.recurring and self._queue is not None:
                    self._queue._live_nonrecurring -= 1

        def __lt__(self, other):
            return (self.at, self.seq) < (other.at, other.seq)


class SimScheduler(Scheduler):
    def __init__(self, queue: PendingQueue):
        self.queue = queue

    def once(self, delay_s: float, run: Callable[[], None]):
        entry = self.queue.add_after(int(delay_s * 1_000_000), run)

        class _S(Scheduler.Scheduled):
            def cancel(self_inner):
                entry.cancel()
        return _S()

    def recurring(self, interval_s, run: Callable[[], None]):
        """``interval_s`` may be a float or a zero-arg callable resampled every
        cycle (jittered cadences — breaks cross-node poll alignment that would
        otherwise make concurrent recovery attempts perpetually preempt each
        other; the reference randomizes its progress-log requeue delays)."""
        state = {"cancelled": False, "entry": None}
        next_us = (lambda: int(interval_s() * 1_000_000)) if callable(interval_s) \
            else (lambda: int(interval_s * 1_000_000))

        def fire():
            if state["cancelled"]:
                return
            run()
            state["entry"] = self.queue.add_after(next_us(), fire, recurring=True)

        state["entry"] = self.queue.add_after(next_us(), fire, recurring=True)

        class _S(Scheduler.Scheduled):
            def cancel(self_inner):
                state["cancelled"] = True
                if state["entry"] is not None:
                    state["entry"].cancel()
        return _S()


class LinkConfig:
    """Per-link delivery behavior (NodeSink.Action): deliver with latency, drop,
    or deliver-then-report-failure."""

    DELIVER = "deliver"
    DROP = "drop"
    FAILURE = "failure"                  # drop AND report failure to the sender
    DELIVER_WITH_FAILURE = "deliver_with_failure"  # deliver AND report failure

    def __init__(self, rng: RandomSource, min_latency_us: int = 500,
                 max_latency_us: int = 20_000):
        self.rng = rng
        self.min_latency_us = min_latency_us
        self.max_latency_us = max_latency_us

    def action(self, from_node: int, to_node: int, message=None) -> str:
        return LinkConfig.DELIVER

    def latency_us(self, from_node: int, to_node: int) -> int:
        return self.rng.next_int(self.min_latency_us, self.max_latency_us)


class SimMessageSink(MessageSink):
    """Routes messages through the cluster queue with link behavior + reply
    correlation + caller-side timeouts (SafeCallback semantics)."""

    def __init__(self, node_id: int, cluster: "Cluster"):
        self.node_id = node_id
        self.cluster = cluster
        self._next_msg_id = 0
        # msg_id -> (callback, timeout_entry, to_node)
        self.callbacks: Dict[int, Tuple[Callback, object, int]] = {}

    # -- outbound -----------------------------------------------------------
    def send(self, to: int, request: Request) -> None:
        self._send(to, request, None)

    def send_with_callback(self, to: int, request: Request, callback: Callback) -> None:
        self._send(to, request, callback)

    def _send(self, to: int, request: Request, callback: Optional[Callback]) -> None:
        msg_id = self._next_msg_id
        self._next_msg_id += 1
        cluster = self.cluster
        if callback is not None:
            timeout_us = int(cluster.reply_timeout_s * 1_000_000)
            entry = cluster.queue.add_after(timeout_us, lambda: self._timeout(msg_id))
            self.callbacks[msg_id] = (callback, entry, to)
        cluster.route(self.node_id, to, request, msg_id, callback is not None)

    def reply(self, to: int, reply_context, reply: Reply) -> None:
        from ..messages.base import LOCAL_NO_REPLY
        if reply_context is LOCAL_NO_REPLY:
            return   # self-delivered local request: nothing to answer
        self.cluster.route_reply(self.node_id, to, reply_context, reply)

    # -- inbound correlation -------------------------------------------------
    def deliver_reply(self, from_node: int, msg_id: int, reply: Reply) -> None:
        entry = self.callbacks.get(msg_id)
        if entry is None:
            return
        callback, timeout_entry, to = entry
        timeout_entry.cancel()
        if reply.is_final:
            del self.callbacks[msg_id]
        else:
            # non-final reply (e.g. StableAck before a long dependency wait):
            # keep the callback registered and re-arm the timeout so a lost final
            # reply still triggers the failure/retry path
            timeout_us = int(self.cluster.reply_timeout_s * 1_000_000)
            new_entry = self.cluster.queue.add_after(timeout_us, lambda: self._timeout(msg_id))
            self.callbacks[msg_id] = (callback, new_entry, to)
        try:
            if isinstance(reply, FailureReply):
                callback.on_failure(from_node, reply.failure)
            else:
                callback.on_success(from_node, reply)
        except BaseException as e:  # noqa: BLE001
            callback.on_callback_failure(from_node, e)

    def report_failure(self, msg_id: int, to_node: int, failure: BaseException) -> None:
        entry = self.callbacks.pop(msg_id, None)
        if entry is None:
            return
        callback, timeout_entry, _ = entry
        timeout_entry.cancel()
        try:
            callback.on_failure(to_node, failure)
        except BaseException as e:  # noqa: BLE001
            callback.on_callback_failure(to_node, e)

    def _timeout(self, msg_id: int) -> None:
        entry = self.callbacks.pop(msg_id, None)
        if entry is None:
            return
        callback, _timeout_entry, to = entry
        try:
            callback.on_failure(to, Timeout(None, f"no reply from {to}"))
        except BaseException as e:  # noqa: BLE001
            callback.on_callback_failure(to, e)


class ReplyContext:
    __slots__ = ("reply_to", "msg_id")

    def __init__(self, reply_to: int, msg_id: int):
        self.reply_to = reply_to
        self.msg_id = msg_id


class SimConfigService(ConfigurationService):
    """Static/global epoch feed shared by all nodes (BurnTestConfigurationService
    simplified): the cluster appends topologies; every node learns them through the
    queue."""

    def __init__(self, cluster: "Cluster", node_id: int):
        self.cluster = cluster
        self.node_id = node_id
        self.listeners: List[ConfigurationService.Listener] = []

    def register_listener(self, listener) -> None:
        self.listeners.append(listener)

    def current_topology(self) -> Topology:
        return self.cluster.topologies[-1]

    def get_topology_for_epoch(self, epoch: int) -> Optional[Topology]:
        for t in self.cluster.topologies:
            if t.epoch == epoch:
                return t
        return None

    def fetch_topology_for_epoch(self, epoch: int) -> None:
        if self.get_topology_for_epoch(epoch) is not None:
            self.cluster.queue.add_after(0, self.deliver_pending)

    def deliver_pending(self) -> None:
        """Deliver every not-yet-delivered epoch, in order (TopologyManager
        requires consecutive epochs)."""
        node = self.cluster.nodes[self.node_id]
        while True:
            current = node.topology.current_epoch
            nxt = self.get_topology_for_epoch(current + 1) if current > 0 \
                else self.cluster.topologies[0]
            if nxt is None or (current > 0 and nxt.epoch <= current):
                return
            self.notify(nxt)
            if node.topology.current_epoch == current:
                return  # listener refused (shouldn't happen); avoid spinning

    def notify(self, topology: Topology) -> None:
        for listener in self.listeners:
            listener.on_topology_update(topology, start_sync=True)

    def acknowledge_epoch(self, ready, start_sync: bool) -> None:
        # report sync completion to all peers once the epoch is locally ready
        epoch = ready.epoch
        me = self.node_id

        def broadcast():
            for other in self.cluster.nodes.values():
                other.on_remote_sync_complete(me, epoch)
        ready.reads.add_listener(lambda v, f: broadcast())


class DelayedAgentExecutor:
    """Store executor adding a random queue delay to every task, simulating
    storage/executor latency and forcing interleavings
    (DelayedCommandStores.DelayedCommandStore, DelayedCommandStores.java:138-195)."""

    def __init__(self, agent: Agent, queue: PendingQueue, rng: RandomSource,
                 max_delay_us: int = 1_000):
        self.agent = agent
        self.queue = queue
        self.rng = rng
        self.max_delay_us = max_delay_us

    def execute(self, task: Callable[[], None]) -> None:
        def run():
            try:
                task()
            except BaseException as e:  # noqa: BLE001
                self.agent.on_uncaught_exception(e)

        self.queue.add_after(self.rng.next_int(self.max_delay_us + 1), run)

    def submit(self, task: Callable[[], object]):
        from ..utils import async_ as au
        return au.of_callable(task, executor=self)


class SimAgent(Agent):
    def __init__(self, cluster: "Cluster"):
        self.cluster = cluster

    def on_uncaught_exception(self, failure: BaseException) -> None:
        self.cluster.failures.append(failure)
        raise failure

    def on_handled_exception(self, failure: BaseException) -> None:
        pass

    def pre_accept_timeout(self) -> float:
        return 1.0


class Cluster:
    """In-process multi-node Accord cluster on simulated time."""

    def __init__(self, topology: Topology, seed: int = 1, num_shards: int = 1,
                 link_config: Optional[LinkConfig] = None,
                 reply_timeout_s: float = 2.0,
                 progress_log: bool = False,
                 progress_poll_s: float = 0.5,
                 extra_nodes: Optional[List[int]] = None,
                 delayed_stores: bool = False,
                 clock_drift: bool = False,
                 journal: bool = False,
                 resolver: Optional[str] = None,
                 batch_window_us: int = 0,
                 node_config=None):
        self.rng = RandomSource(seed)
        self.queue = PendingQueue()
        self.scheduler = SimScheduler(self.queue)
        self.topologies: List[Topology] = [topology]
        # message trace hook: fn(event, from, to, msg_id, message, now_micros)
        # where event is the link action taken or "REPLY"/"REPLY_<action>"
        # (the reference's accord.impl.basic.Trace logger, Cluster.java:237-264)
        self.tracer: Optional[Callable] = None
        # controllable-delivery hook (MockCluster/Network capability,
        # impl/mock/MockCluster.java): fn(from, to, request, msg_id,
        # has_callback) -> True to swallow (the hook owns delivery/reply)
        self.request_filter: Optional[Callable] = None
        self.link = link_config or LinkConfig(self.rng.fork())
        self.reply_timeout_s = reply_timeout_s
        # request-delivery coalescing: requests arriving at a node within
        # ``batch_window_us`` sim-time are processed as one batch, letting the
        # device resolver answer the whole window's deps queries in ONE fused
        # launch (TpuDepsResolver.prefetch).  0 = deliver individually.  This
        # models a real TPU-serving node's request coalescing; it only shifts
        # delivery times by <= the window, which is legal network behavior.
        self.batch_window_us = batch_window_us
        self._inboxes: Dict[int, List] = {}
        self._inbox_drain_at: Dict[int, Optional[int]] = {}
        self._inbox_seq = 0
        self.failures: List[BaseException] = []
        self.stats: Dict[str, int] = {}
        self.nodes: Dict[int, Node] = {}
        self.sinks: Dict[int, SimMessageSink] = {}
        self.stores: Dict[int, ListStore] = {}
        self.journal = None
        plf = None
        if progress_log:
            from ..impl.progress_log import progress_log_factory
            plf = progress_log_factory(progress_poll_s)
        agent = SimAgent(self)
        # per-node clock drift (FrequentLargeRange nowSupplier, BurnTest:329-339)
        self.clock_offsets: Dict[int, int] = {}
        for node_id in sorted(set(topology.nodes()) | set(extra_nodes or ())):
            sink = SimMessageSink(node_id, self)
            store = ListStore(node_id)
            self.sinks[node_id] = sink
            self.stores[node_id] = store
            executor_factory = None
            if delayed_stores:
                exec_rng = self.rng.fork()
                executor_factory = (lambda rng: (lambda i: DelayedAgentExecutor(
                    agent, self.queue, rng.fork())))(exec_rng)
            self.nodes[node_id] = Node(
                node_id, sink, SimConfigService(self, node_id), agent,
                self.scheduler, store, self.rng.fork(),
                now_micros=(lambda nid: (lambda: self.queue.now_micros
                                         + self.clock_offsets.get(nid, 0)))(node_id),
                num_shards=num_shards,
                executor_factory=executor_factory,
                progress_log_factory=plf,
                resolver=resolver,
                config=node_config)
            if clock_drift:
                self._start_drift(node_id)
        if journal:
            from .journal import Journal
            self.journal = Journal()
            for node in self.nodes.values():
                for store in node.command_stores.all_stores():
                    self.journal.attach(store)
        # chaos link configs re-randomize themselves off the cluster queue
        if hasattr(self.link, "attach"):
            self.link.attach(self)

    def _start_drift(self, node_id: int) -> None:
        """Random-walk clock drift: small 50µs-5ms jumps, occasional 1-10ms
        large jumps (BurnTest.java:329-339 FrequentLargeRange)."""
        rng = self.rng.fork()

        def jump():
            if rng.next_float() < 0.1:
                delta = rng.next_int(1_000, 10_000)
            else:
                delta = rng.next_int(50, 5_000)
            # drift forward or back, but never behind real sim time
            off = self.clock_offsets.get(node_id, 0)
            off += delta if rng.next_boolean() else -delta
            self.clock_offsets[node_id] = max(0, off)

        self.scheduler.recurring(0.05, jump)

    # -- topology change -----------------------------------------------------
    def update_topology(self, new_topology: Topology) -> None:
        """Advance the cluster to a new epoch: every node learns it after a
        random delay (epoch propagation skew), in epoch order."""
        assert new_topology.epoch == self.topologies[-1].epoch + 1, \
            f"epoch must advance by 1: {self.topologies[-1].epoch} -> {new_topology.epoch}"
        self.topologies.append(new_topology)
        for node_id in sorted(self.nodes):
            delay = self.rng.next_int(200, 5000)
            svc = self.nodes[node_id].config_service
            self.queue.add_after(delay, svc.deliver_pending)

    # -- message routing ----------------------------------------------------
    def route(self, from_node: int, to_node: int, request: Request, msg_id: int,
              has_callback: bool) -> None:
        self._count(f"{type(request).__name__}")
        if self.request_filter is not None and \
                self.request_filter(from_node, to_node, request, msg_id,
                                    has_callback):
            return
        action = self.link.action(from_node, to_node, request) if from_node != to_node \
            else LinkConfig.DELIVER
        if self.tracer is not None:
            self.tracer(action.upper(), from_node, to_node, msg_id, request,
                        self.queue.now_micros)
        if action in (LinkConfig.DROP, LinkConfig.FAILURE):
            if action == LinkConfig.FAILURE and has_callback:
                self.queue.add_after(
                    self.link.latency_us(from_node, to_node),
                    lambda: self.sinks[from_node].report_failure(
                        msg_id, to_node, ConnectionError(f"link {from_node}->{to_node}")))
            return
        latency = 0 if from_node == to_node else self.link.latency_us(from_node, to_node)
        ctx = ReplyContext(from_node, msg_id)
        if self.batch_window_us > 0:
            self._inbox_deliver(to_node, request, from_node, ctx, latency)
        else:
            self.queue.add_after(latency, lambda: self._deliver(
                to_node, request, from_node, ctx))
        if action == LinkConfig.DELIVER_WITH_FAILURE and has_callback:
            self.queue.add_after(
                self.link.latency_us(from_node, to_node),
                lambda: self.sinks[from_node].report_failure(
                    msg_id, to_node, ConnectionError(f"link {from_node}->{to_node}")))

    def _deliver(self, to_node: int, request: Request, from_node: int,
                 ctx: "ReplyContext") -> None:
        if self.tracer is not None:
            self.tracer("RECV", from_node, to_node, ctx.msg_id, request,
                        self.queue.now_micros)
        self.nodes[to_node].receive(request, from_node, ctx)

    def route_reply(self, from_node: int, to_node: int, reply_context: ReplyContext,
                    reply: Reply) -> None:
        self._count(f"{type(reply).__name__}")
        action = self.link.action(from_node, to_node, reply) if from_node != to_node \
            else LinkConfig.DELIVER
        if self.tracer is not None:
            self.tracer(f"RPLY_{action.upper()}", from_node, to_node,
                        reply_context.msg_id, reply, self.queue.now_micros)
        if action in (LinkConfig.DROP, LinkConfig.FAILURE):
            return
        latency = 0 if from_node == to_node else self.link.latency_us(from_node, to_node)

        def deliver():
            if self.tracer is not None:
                self.tracer("RECV_RPLY", from_node, to_node,
                            reply_context.msg_id, reply, self.queue.now_micros)
            self.sinks[to_node].deliver_reply(from_node, reply_context.msg_id,
                                              reply)
        self.queue.add_after(latency, deliver)

    def _count(self, key: str) -> None:
        self.stats[key] = self.stats.get(key, 0) + 1

    # -- request-delivery coalescing (batch_window_us) ------------------------
    def _inbox_deliver(self, to_node: int, request: Request, from_node: int,
                       ctx: "ReplyContext", latency: int) -> None:
        arrival = self.queue.now_micros + latency
        self._inboxes.setdefault(to_node, []).append(
            (arrival, self._inbox_seq, request, from_node, ctx))
        self._inbox_seq += 1
        due = arrival + self.batch_window_us
        scheduled = self._inbox_drain_at.get(to_node)
        # also RE-schedule when this arrival precedes the pending drain: a
        # fast link's message must never wait out a slow link's window (no
        # message is held longer than its own arrival + window; the stale
        # later drain fires harmlessly on whatever remains)
        if scheduled is None or due < scheduled:
            self._inbox_drain_at[to_node] = due
            self.queue.add_after(due - self.queue.now_micros,
                                 lambda: self._drain_inbox(to_node))

    def _drain_inbox(self, to_node: int) -> None:
        """Process every request that has arrived at ``to_node`` by now, as one
        batch: prefetch the batch's declared deps queries per store (one fused
        device launch each), then run the handlers sequentially in arrival
        order — exact sequential semantics, batched device traffic."""
        box = self._inboxes.get(to_node, [])
        now = self.queue.now_micros
        ready = sorted(e for e in box if e[0] <= now)
        rest = [e for e in box if e[0] > now]
        self._inboxes[to_node] = rest
        self._inbox_drain_at[to_node] = None
        if rest:
            due = min(e[0] for e in rest) + self.batch_window_us
            self._inbox_drain_at[to_node] = due
            self.queue.add_after(due - now, lambda: self._drain_inbox(to_node))
        if not ready:
            return
        node = self.nodes.get(to_node)
        if node is None:
            return
        # even a batch of one PreAccept gains: its deps + max-conflict consults
        # fuse into a single launch instead of two
        per_store: Dict[object, List] = {}
        with_specs = []
        for entry in ready:
            specs = entry[2].prefetch_specs(node)
            with_specs.append((entry, bool(specs)))
            for store, spec in specs or ():
                per_store.setdefault(store, []).append(spec)
        # deps-query-bearing requests drain FIRST: a Commit/Apply processed
        # mid-window moves the covering bounds and invalidates the window's
        # prefetched answers, so serve the queries before advancing state.
        # Reordering within the window is legal network behavior (it is
        # indistinguishable from jitter below the coalescing latency), and
        # the (priority, arrival, seq) key keeps it deterministic.
        with_specs.sort(key=lambda p: (not p[1], p[0][0], p[0][1]))
        for store, specs in per_store.items():
            store.resolver.prefetch(specs)
        try:
            for (_at, _seq, request, frm, ctx), _h in with_specs:
                if self.tracer is not None:
                    self.tracer("RECV", frm, to_node, ctx.msg_id, request,
                                self.queue.now_micros)
                node.receive(request, frm, ctx)
        finally:
            for store in per_store:
                store.resolver.end_batch()

    # -- execution ----------------------------------------------------------
    def run_until_idle(self, max_tasks: int = 1_000_000) -> int:
        """Drain the queue until only recurring tasks remain; returns tasks
        executed. Raises any node failure."""
        n = 0
        while n < max_tasks and self.queue.has_nonrecurring():
            task = self.queue.pop()
            if task is None:
                break
            task()
            n += 1
            if self.failures:
                raise self.failures[0]
        return n

    def run_until(self, predicate: Callable[[], bool], max_tasks: int = 1_000_000) -> bool:
        n = 0
        while n < max_tasks:
            if predicate():
                return True
            task = self.queue.pop()
            if task is None:
                return predicate()
            task()
            n += 1
            if self.failures:
                raise self.failures[0]
        return predicate()

    def run_for(self, sim_seconds: float, max_tasks: int = 1_000_000) -> None:
        """Advance simulated time by ``sim_seconds``, executing everything due."""
        deadline = self.queue.now_micros + int(sim_seconds * 1_000_000)
        self.run_until(lambda: self.queue.now_micros >= deadline, max_tasks)

    @property
    def now_micros(self) -> int:
        return self.queue.now_micros
