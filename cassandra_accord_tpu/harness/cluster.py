"""Deterministic simulated cluster: one seed => one fully reproducible multi-node run.

Capability parity with ``accord.impl.basic.Cluster`` + ``NodeSink`` +
``RandomDelayQueue`` (Cluster.java:121-903, NodeSink.java:45, RandomDelayQueue):
a single-threaded event loop over a priority queue of (virtual-micros, seq, task);
all network sends, scheduler callbacks and store tasks go through the queue; per-link
behavior (latency, drop, failure) is pluggable for fault injection.  Simulated time
advances to each task's deadline — wall-clock independence is what makes every run
replayable from its seed.
"""
from __future__ import annotations

import heapq
from typing import Callable, Dict, List, Optional, Tuple

from ..api.interfaces import Agent, ConfigurationService, DataStore, EventsListener, MessageSink, Scheduler
from ..impl.list_store import ListStore
from ..local.node import Node
from ..messages.base import Callback, FailureReply, Reply, Request
from ..primitives.timestamp import Timestamp
from ..topology.topology import Shard, Topology
from ..utils import async_ as au
from ..utils.random import RandomSource
from ..coordinate.errors import Overloaded, Timeout


class PendingQueue:
    """Priority queue keyed by virtual micros; seq breaks ties deterministically.

    Recurring tasks (periodic progress-log polls, durability cycles) are marked so
    ``run_until_idle`` can stop when only recurring work remains — the reference's
    ``processPending`` drains "until only recurring tasks remain"
    (Cluster.java:215-228)."""

    def __init__(self):
        self._heap: List[Tuple[int, int, Callable]] = []
        self._seq = 0
        self.now_micros = 0
        self._live_nonrecurring = 0

    def add(self, at_micros: int, task: Callable[[], None],
            recurring: bool = False) -> "PendingQueue._Entry":
        entry = PendingQueue._Entry(max(at_micros, self.now_micros), self._seq, task,
                                    recurring, self)
        self._seq += 1
        if not recurring:
            self._live_nonrecurring += 1
        heapq.heappush(self._heap, entry)
        return entry

    def add_after(self, delay_micros: int, task: Callable[[], None],
                  recurring: bool = False):
        return self.add(self.now_micros + delay_micros, task, recurring)

    def pop(self) -> Optional[Callable]:
        while self._heap:
            entry = heapq.heappop(self._heap)
            if entry.cancelled:
                continue
            entry.popped = True
            if not entry.recurring:
                self._live_nonrecurring -= 1
                assert self._live_nonrecurring >= 0, \
                    "PendingQueue idle accounting went negative (double decrement)"
            self.now_micros = max(self.now_micros, entry.at)
            return entry.task
        return None

    def has_nonrecurring(self) -> bool:
        return self._live_nonrecurring > 0

    def __len__(self):
        return sum(1 for e in self._heap if not e.cancelled)

    class _Entry:
        __slots__ = ("at", "seq", "task", "cancelled", "recurring", "popped",
                     "_queue")

        def __init__(self, at: int, seq: int, task: Callable, recurring: bool = False,
                     queue: "PendingQueue" = None):
            self.at = at
            self.seq = seq
            self.task = task
            self.cancelled = False
            self.recurring = recurring
            # set when pop() hands the task out: cancel() after that must NOT
            # decrement the live counter again — cancelling an already-run
            # one-shot (e.g. CoordinateDurabilityScheduling.stop() sweeping
            # its fired entries) double-decremented _live_nonrecurring, the
            # queue then claimed idle while real timeouts still pended,
            # run_until_idle exited early, hung bootstrap fences never timed
            # out, and pending_bootstrap never cleared (seed-7 replica
            # divergence at the final-agreement check)
            self.popped = False
            self._queue = queue

        def cancel(self):
            if not self.cancelled and not self.popped:
                self.cancelled = True
                if not self.recurring and self._queue is not None:
                    self._queue._live_nonrecurring -= 1
                    assert self._queue._live_nonrecurring >= 0, \
                        "PendingQueue idle accounting went negative (double decrement)"

        def __lt__(self, other):
            return (self.at, self.seq) < (other.at, other.seq)


def backoff_timeout_us(base_s: float, attempt: int, factor: float, max_s: float,
                       jitter_frac: float, salt: int) -> int:
    """Exponential reply-timeout backoff with DETERMINISTIC jitter: the jitter
    comes from a golden-ratio hash of (salt=msg_id, attempt), not from any
    rng — no seeded stream is consumed, so every trajectory stays replayable
    while re-arms across nodes never phase-lock."""
    t = min(base_s * (factor ** attempt), max_s)
    h = (salt * 0x9E3779B97F4A7C15 + (attempt + 1) * 0xD1B54A32D192ED03) \
        & 0xFFFFFFFFFFFFFFFF
    t *= 1.0 + jitter_frac * ((h >> 40) / float(1 << 24))
    return int(t * 1_000_000)


class SlowReplicaTracker:
    """Per-node gray-failure detector: reply-latency EWMA plus a penalty
    window after each reply timeout.  Coordinators consult ``slow_peers`` to
    route per-shard data reads around paused-but-alive peers instead of
    burning whole reply-timeout rounds on them (ReadTracker.java's slow
    ladder, fed by observed behavior instead of a static preference)."""

    __slots__ = ("cluster", "alpha", "threshold_us", "penalty_us", "ewma",
                 "slow_until")

    def __init__(self, cluster: "Cluster", alpha: float, threshold_s: float,
                 penalty_s: float):
        self.cluster = cluster
        self.alpha = alpha
        self.threshold_us = threshold_s * 1_000_000
        self.penalty_us = int(penalty_s * 1_000_000)
        self.ewma: Dict[int, float] = {}
        self.slow_until: Dict[int, int] = {}

    def record_reply(self, peer: int, latency_us: int) -> None:
        prev = self.ewma.get(peer)
        self.ewma[peer] = latency_us if prev is None \
            else prev + self.alpha * (latency_us - prev)

    def record_timeout(self, peer: int) -> None:
        self.slow_until[peer] = self.cluster.queue.now_micros + self.penalty_us

    def record_overloaded(self, peer: int) -> None:
        """An Overloaded nack (or a piggybacked load bit) from ``peer``: treat
        it like a slow peer for the overload penalty window, so coordinators
        route reads around it instead of feeding the hot node more work.
        Never shortens an existing penalty (a timeout's window stands)."""
        until = self.cluster.queue.now_micros + self.cluster.overload_penalty_us
        if until > self.slow_until.get(peer, -1):
            self.slow_until[peer] = until

    def is_slow(self, peer: int) -> bool:
        if self.ewma.get(peer, 0.0) > self.threshold_us:
            return True
        return self.cluster.queue.now_micros < self.slow_until.get(peer, -1)

    def slow_peers(self) -> frozenset:
        return frozenset(p for p in set(self.ewma) | set(self.slow_until)
                         if self.is_slow(p))


class SimScheduler(Scheduler):
    def __init__(self, queue: PendingQueue):
        self.queue = queue

    def once(self, delay_s: float, run: Callable[[], None]):
        entry = self.queue.add_after(int(delay_s * 1_000_000), run)

        class _S(Scheduler.Scheduled):
            def cancel(self_inner):
                entry.cancel()
        return _S()

    def recurring(self, interval_s, run: Callable[[], None]):
        """``interval_s`` may be a float or a zero-arg callable resampled every
        cycle (jittered cadences — breaks cross-node poll alignment that would
        otherwise make concurrent recovery attempts perpetually preempt each
        other; the reference randomizes its progress-log requeue delays)."""
        state = {"cancelled": False, "entry": None}
        next_us = (lambda: int(interval_s() * 1_000_000)) if callable(interval_s) \
            else (lambda: int(interval_s * 1_000_000))

        def fire():
            if state["cancelled"]:
                return
            run()
            state["entry"] = self.queue.add_after(next_us(), fire, recurring=True)

        state["entry"] = self.queue.add_after(next_us(), fire, recurring=True)

        class _S(Scheduler.Scheduled):
            def cancel(self_inner):
                state["cancelled"] = True
                if state["entry"] is not None:
                    state["entry"].cancel()
        return _S()


class NodeScheduler(Scheduler):
    """Per-node-incarnation scheduler facade over the cluster queue.

    Every task is gated on the node's incarnation still being live — a crashed
    node's timers, progress-log polls, epoch watchdogs and read-speculation
    beats must never fire against torn-down state.  Live one-shot entries are
    tracked so ``Cluster.crash`` can cancel them outright (keeping the queue's
    idle accounting exact: a wrapped no-op would otherwise pin
    ``has_nonrecurring`` until the dead timer's deadline).  Recurring tasks
    stop re-arming at the first post-crash fire."""

    def __init__(self, cluster: "Cluster", node_id: int, incarnation: int):
        self.cluster = cluster
        self.node_id = node_id
        self.incarnation = incarnation
        self._sim = SimScheduler(cluster.queue)
        self._entries: set = set()

    def is_live(self) -> bool:
        return (self.cluster.incarnations.get(self.node_id, 0) == self.incarnation
                and self.node_id not in self.cluster.down)

    def teardown(self) -> None:
        """Cancel every live one-shot this node scheduled (crash path)."""
        for entry in list(self._entries):
            entry.cancel()
        self._entries.clear()

    def once(self, delay_s: float, run: Callable[[], None]):
        holder = {"cancelled": False}
        prov = self.cluster._prov
        # causal provenance: the timer's parent is the activity ARMING it;
        # at fire time the bracket makes its sends/transitions children
        armed_by = prov.current() if prov is not None else None

        def guarded():
            # stop-the-world pause: the timer is DUE but the process is not
            # scheduling — park it; it late-fires (in order) at resume.  The
            # cancelled flag must be re-checked then: cancel() after the park
            # can no longer reach the popped queue entry
            if self.cluster._gate(self.node_id, guarded):
                return
            entry = holder.get("e")
            if entry is not None:
                self._entries.discard(entry)
            if not holder["cancelled"] and self.is_live():
                if prov is not None:
                    prov.begin_timer(self.node_id, armed_by,
                                     self.cluster.queue.now_micros)
                    try:
                        run()
                    finally:
                        prov.end()
                else:
                    run()

        entry = self.cluster.queue.add_after(int(delay_s * 1_000_000), guarded)
        holder["e"] = entry
        self._entries.add(entry)
        entries = self._entries

        class _S(Scheduler.Scheduled):
            def cancel(self_inner):
                holder["cancelled"] = True
                entries.discard(entry)
                entry.cancel()
        return _S()

    def recurring(self, interval_s, run: Callable[[], None]):
        """SimScheduler's resample/fire/re-arm machinery, plus the incarnation
        gate: a dead node's cadence no-ops and cancels itself at its first
        post-crash fire (one orphan re-arm, then the queue forgets it).
        While the node is PAUSED, fires coalesce: at most one parked instance
        late-fires at resume (a frozen process's periodic timer doesn't burst
        one fire per missed period)."""
        holder = {"parked": False}
        prov = self.cluster._prov
        armed_by = prov.current() if prov is not None else None

        def late_fire():
            holder["parked"] = False
            guarded()

        def guarded():
            if self.node_id in self.cluster.paused:
                if not holder["parked"]:
                    holder["parked"] = True
                    self.cluster._gate(self.node_id, late_fire)
                return
            if self.is_live():
                if prov is not None:
                    prov.begin_timer(self.node_id, armed_by,
                                     self.cluster.queue.now_micros)
                    try:
                        run()
                    finally:
                        prov.end()
                else:
                    run()
            elif holder.get("s") is not None:
                holder["s"].cancel()

        holder["s"] = self._sim.recurring(interval_s, guarded)
        return holder["s"]


class LinkConfig:
    """Per-link delivery behavior (NodeSink.Action): deliver with latency, drop,
    or deliver-then-report-failure."""

    DELIVER = "deliver"
    DROP = "drop"
    FAILURE = "failure"                  # drop AND report failure to the sender
    DELIVER_WITH_FAILURE = "deliver_with_failure"  # deliver AND report failure

    def __init__(self, rng: RandomSource, min_latency_us: int = 500,
                 max_latency_us: int = 20_000):
        self.rng = rng
        self.min_latency_us = min_latency_us
        self.max_latency_us = max_latency_us

    def action(self, from_node: int, to_node: int, message=None) -> str:
        return LinkConfig.DELIVER

    def latency_us(self, from_node: int, to_node: int) -> int:
        return self.rng.next_int(self.min_latency_us, self.max_latency_us)


class SimMessageSink(MessageSink):
    """Routes messages through the cluster queue with link behavior + reply
    correlation + caller-side timeouts (SafeCallback semantics)."""

    def __init__(self, node_id: int, cluster: "Cluster"):
        self.node_id = node_id
        self.cluster = cluster
        # msg_id -> (callback, timeout_entry, to_node, rearm_attempt, sent_at,
        #            txn_id) — txn_id attributes timeout/backoff observability
        # to the transaction's flight-recorder span (None for txn-less rounds)
        self.callbacks: Dict[int, Tuple[Callback, object, int, int, int,
                                        object]] = {}
        # gray-failure detector feeding read-speculation routing
        alpha, threshold_s, penalty_s = cluster.slow_peer_params
        self.slow_replicas = SlowReplicaTracker(cluster, alpha, threshold_s,
                                                penalty_s)

    def is_live(self) -> bool:
        """A sink belonging to a crashed (or replaced-by-restart) incarnation
        must neither send nor arm timeouts."""
        return (self.cluster.sinks.get(self.node_id) is self
                and self.node_id not in self.cluster.down)

    def teardown(self) -> None:
        """Crash path: drop every registered callback and cancel its timeout
        entry (exact idle accounting — the timers must not pin the queue)."""
        for _callback, timeout_entry, _to, _attempt, _sent, _tid in \
                self.callbacks.values():
            timeout_entry.cancel()
        self.callbacks.clear()

    def _arm_timeout(self, msg_id: int, attempt: int):
        """Arm (or re-arm) the reply timeout for ``msg_id``.  attempt 0 is the
        flat base timeout; every non-final-reply re-arm backs off
        exponentially with deterministic jitter (adaptive patience: a node
        that keeps proving liveness earns longer — but bounded — waits)."""
        cluster = self.cluster
        timeout_us = backoff_timeout_us(
            cluster.reply_timeout_s, attempt, cluster.reply_backoff_factor,
            cluster.reply_backoff_max_s, cluster.reply_backoff_jitter, msg_id)
        return cluster.queue.add_after(timeout_us, lambda: self._timeout(msg_id))

    # -- outbound -----------------------------------------------------------
    def send(self, to: int, request: Request) -> None:
        self._send(to, request, None)

    def send_with_callback(self, to: int, request: Request, callback: Callback) -> None:
        self._send(to, request, callback)

    def _send(self, to: int, request: Request, callback: Optional[Callback]) -> None:
        if not self.is_live():
            return   # a dead incarnation cannot put packets on the wire
        # cluster-global msg ids: ids stay unique across a node's crash-restart
        # boundary, so a stale reply can never correlate with a NEW callback
        msg_id = self.cluster.alloc_msg_id()
        cluster = self.cluster
        if callback is not None:
            entry = self._arm_timeout(msg_id, 0)
            self.callbacks[msg_id] = (callback, entry, to, 0,
                                      cluster.queue.now_micros,
                                      getattr(request, "txn_id", None))

        def emit():
            cluster.route(self.node_id, to, request, msg_id,
                          callback is not None)
        # journal-append stall = fsync-before-reply: a node whose durable
        # write path is stalled cannot put NEW packets on the wire (its own
        # timers above still run — the process believes it sent).  Held
        # packets drain at unstall; a crash mid-stall loses them with the
        # unsynced journal tail, so no peer ever observed non-durable state
        if to != self.node_id and cluster.journal is not None \
                and cluster.journal.is_stalled(self.node_id):
            cluster.hold_send(self.node_id, emit)
        else:
            emit()

    def reply(self, to: int, reply_context, reply: Reply) -> None:
        from ..messages.base import LOCAL_NO_REPLY
        if reply_context is LOCAL_NO_REPLY:
            return   # self-delivered local request: nothing to answer
        if not self.is_live():
            return   # dead incarnation: replies die with the process
        cluster = self.cluster
        # backpressure piggyback: stamp the reply's wire journey with this
        # replica's CURRENT overload bit (send-time state — deterministic),
        # so coordinators learn of pressure from every reply, not only from
        # the sheds.  Reply objects stay untouched (no schema change); the
        # bit rides the routing call.
        hot = False
        if cluster.backpressure_piggyback:
            node = cluster.nodes.get(self.node_id)
            adm = getattr(node, "admission", None)
            hot = adm is not None and adm.overloaded()

        def emit():
            cluster.route_reply(self.node_id, to, reply_context, reply,
                                overloaded=hot)
        if to != self.node_id and cluster.journal is not None \
                and cluster.journal.is_stalled(self.node_id):
            cluster.hold_send(self.node_id, emit)
        else:
            emit()

    # -- inbound correlation -------------------------------------------------
    def deliver_reply(self, from_node: int, msg_id: int, reply: Reply,
                      overloaded: bool = False) -> None:
        entry = self.callbacks.get(msg_id)
        if entry is None:
            return
        callback, timeout_entry, to, attempt, sent_at, tid = entry
        now = self.cluster.queue.now_micros
        # per-LEG latency (send→first reply, reply→reply): measuring from the
        # original send would fold a txn's whole dependency wait into the
        # peer's "latency" and mark healthy-but-working replicas slow
        self.slow_replicas.record_reply(from_node, now - sent_at)
        if overloaded or (isinstance(reply, FailureReply)
                          and isinstance(reply.failure, Overloaded)):
            # an explicit admission nack, or the piggybacked load bit:
            # route around this peer like a slow one for the penalty window
            self.slow_replicas.record_overloaded(from_node)
        if reply.is_final:
            timeout_entry.cancel()
            del self.callbacks[msg_id]
        elif attempt + 1 < self.cluster.reply_rearm_budget:
            # non-final reply (e.g. StableAck before a long dependency wait):
            # keep the callback registered and re-arm the timeout — backed
            # off, so a long-but-live dependency wait isn't hammered — and a
            # lost final reply still triggers the failure/retry path
            timeout_entry.cancel()
            new_entry = self._arm_timeout(msg_id, attempt + 1)
            self.callbacks[msg_id] = (callback, new_entry, to, attempt + 1,
                                      now, tid)
            if self.cluster.observer is not None:
                self.cluster.observer.on_backoff(self.node_id, tid,
                                                 attempt + 1)
        else:
            # re-arm budget exhausted — deliver the reply below but leave the
            # LAST armed timer standing; when it fires, the normal timeout
            # path reports failure and the coordinator's retry machinery
            # takes over from fresher information (bounded patience, never a
            # hang)
            self.callbacks[msg_id] = (callback, timeout_entry, to, attempt,
                                      now, tid)
        prov = self.cluster._prov
        if prov is not None:
            # causal bracket: sends the callback makes are children of this
            # reply delivery (which chains back to the original request)
            prov.begin_callback(self.node_id, msg_id, tid, now)
        try:
            if isinstance(reply, FailureReply):
                callback.on_failure(from_node, reply.failure)
            else:
                callback.on_success(from_node, reply)
        except BaseException as e:  # noqa: BLE001
            callback.on_callback_failure(from_node, e)
        finally:
            if prov is not None:
                prov.end()

    def report_failure(self, msg_id: int, to_node: int, failure: BaseException) -> None:
        if self.cluster._gate(self.node_id, lambda: self.report_failure(
                msg_id, to_node, failure)):
            return   # paused process: the failure surfaces at resume
        entry = self.callbacks.pop(msg_id, None)
        if entry is None:
            return
        callback, timeout_entry, _, _attempt, _sent, _tid = entry
        timeout_entry.cancel()
        try:
            callback.on_failure(to_node, failure)
        except BaseException as e:  # noqa: BLE001
            callback.on_callback_failure(to_node, e)

    def _timeout(self, msg_id: int) -> None:
        # a PAUSED process's timers are frozen: the timeout parks and
        # late-fires at resume (where the reply may by then have raced it in
        # — the park list preserves order, so the reply wins if it arrived
        # first, exactly like a real post-pause timer storm)
        if self.cluster._gate(self.node_id, lambda: self._timeout(msg_id)):
            return
        entry = self.callbacks.pop(msg_id, None)
        if entry is None:
            return
        callback, _timeout_entry, to, _attempt, _sent, tid = entry
        self.slow_replicas.record_timeout(to)
        if self.cluster.observer is not None:
            self.cluster.observer.on_reply_timeout(
                self.node_id, to, tid, self.cluster.queue.now_micros)
        prov = self.cluster._prov
        if prov is not None:
            # causal bracket: retries/failure handling this timeout launches
            # chain back (via msg_id) to the send that went unanswered
            prov.begin_timeout(self.node_id, msg_id, tid,
                               self.cluster.queue.now_micros)
        try:
            callback.on_failure(to, Timeout(None, f"no reply from {to}"))
        except BaseException as e:  # noqa: BLE001
            callback.on_callback_failure(to, e)
        finally:
            if prov is not None:
                prov.end()


class ReplyContext:
    __slots__ = ("reply_to", "msg_id")

    def __init__(self, reply_to: int, msg_id: int):
        self.reply_to = reply_to
        self.msg_id = msg_id


class SimConfigService(ConfigurationService):
    """Static/global epoch feed shared by all nodes (BurnTestConfigurationService
    simplified): the cluster appends topologies; every node learns them through the
    queue."""

    def __init__(self, cluster: "Cluster", node_id: int):
        self.cluster = cluster
        self.node_id = node_id
        self.listeners: List[ConfigurationService.Listener] = []
        # restart support: while set, current_topology() reports the epoch the
        # node had durably reached at crash — the restarted Node initialises
        # there and re-learns every later epoch through deliver_pending, so
        # ranges adopted while it was down go through the normal bootstrap
        # diff instead of being silently treated as first-epoch fresh space
        self.boot_cap: Optional[int] = None

    def register_listener(self, listener) -> None:
        self.listeners.append(listener)

    def current_topology(self) -> Topology:
        if self.boot_cap is not None:
            capped = self.get_topology_for_epoch(self.boot_cap)
            if capped is not None:
                return capped
        return self.cluster.topologies[-1]

    def get_topology_for_epoch(self, epoch: int) -> Optional[Topology]:
        for t in self.cluster.topologies:
            if t.epoch == epoch:
                return t
        return None

    def fetch_topology_for_epoch(self, epoch: int) -> None:
        if self.get_topology_for_epoch(epoch) is not None:
            self.cluster.queue.add_after(0, self.deliver_pending)

    def deliver_pending(self) -> None:
        """Deliver every not-yet-delivered epoch, in order (TopologyManager
        requires consecutive epochs)."""
        if self.cluster._gate(self.node_id, self.deliver_pending):
            return   # paused process: epoch learning resumes with it
        node = self.cluster.nodes.get(self.node_id)
        if node is None or node.config_service is not self:
            return   # node crashed (or this service belongs to a dead incarnation)
        while True:
            current = node.topology.current_epoch
            nxt = self.get_topology_for_epoch(current + 1) if current > 0 \
                else self.cluster.topologies[0]
            if nxt is None or (current > 0 and nxt.epoch <= current):
                return
            self.notify(nxt)
            if node.topology.current_epoch == current:
                return  # listener refused (shouldn't happen); avoid spinning

    def notify(self, topology: Topology) -> None:
        for listener in self.listeners:
            listener.on_topology_update(topology, start_sync=True)

    def acknowledge_epoch(self, ready, start_sync: bool) -> None:
        # report sync completion to all peers once the epoch is locally ready
        epoch = ready.epoch
        me = self.node_id

        def broadcast():
            cluster = self.cluster
            # ledger: a node restarting later re-learns completions it missed
            # while down (gossip-on-rejoin; the live broadcast below only
            # reaches nodes that are up right now)
            cluster.sync_ledger.setdefault(epoch, set()).add(me)
            for other in cluster.nodes.values():
                other.on_remote_sync_complete(me, epoch)
        ready.reads.add_listener(lambda v, f: broadcast())


class DelayedAgentExecutor:
    """Store executor adding a random queue delay to every task, simulating
    storage/executor latency and forcing interleavings
    (DelayedCommandStores.DelayedCommandStore, DelayedCommandStores.java:138-195)."""

    def __init__(self, agent: Agent, queue: PendingQueue, rng: RandomSource,
                 max_delay_us: int = 1_000, is_live: Optional[Callable[[], bool]] = None,
                 pause_gate: Optional[Callable[[Callable], bool]] = None):
        self.agent = agent
        self.queue = queue
        self.rng = rng
        self.max_delay_us = max_delay_us
        # crash gate: a queued store task belonging to a crashed node
        # incarnation must not run against the torn-down store
        self.is_live = is_live
        # pause gate: a queued store task of a PAUSED node parks and
        # late-fires at resume (Cluster._gate)
        self.pause_gate = pause_gate

    def execute(self, task: Callable[[], None]) -> None:
        def run():
            if self.pause_gate is not None and self.pause_gate(run):
                return
            if self.is_live is not None and not self.is_live():
                return
            try:
                task()
            except BaseException as e:  # noqa: BLE001
                self.agent.on_uncaught_exception(e)

        self.queue.add_after(self.rng.next_int(self.max_delay_us + 1), run)

    def submit(self, task: Callable[[], object]):
        from ..utils import async_ as au
        return au.of_callable(task, executor=self)


class SimAgent(Agent):
    def __init__(self, cluster: "Cluster"):
        self.cluster = cluster

    def on_uncaught_exception(self, failure: BaseException) -> None:
        self.cluster.failures.append(failure)
        raise failure

    def on_handled_exception(self, failure: BaseException) -> None:
        pass

    def pre_accept_timeout(self) -> float:
        return 1.0


class Cluster:
    """In-process multi-node Accord cluster on simulated time."""

    def __init__(self, topology: Topology, seed: int = 1, num_shards: int = 1,
                 link_config: Optional[LinkConfig] = None,
                 reply_timeout_s: float = 2.0,
                 progress_log: bool = False,
                 progress_poll_s: float = 0.5,
                 extra_nodes: Optional[List[int]] = None,
                 delayed_stores: bool = False,
                 clock_drift: bool = False,
                 journal: bool = False,
                 resolver: Optional[str] = None,
                 batch_window_us: int = 0,
                 node_config=None,
                 observer=None,
                 profiler=None):
        self.rng = RandomSource(seed)
        self.queue = PendingQueue()
        self.scheduler = SimScheduler(self.queue)
        self.topologies: List[Topology] = [topology]
        # message trace hook: fn(event, from, to, msg_id, message, now_micros)
        # where event is the link action taken or "REPLY"/"REPLY_<action>"
        # (the reference's accord.impl.basic.Trace logger, Cluster.java:237-264)
        self.tracer: Optional[Callable] = None
        # flight recorder (observe.FlightRecorder): passive metrics/span hooks
        # fed from the same sites as the tracer plus the lifecycle planes;
        # MUST have zero observer effect (no RNG, no wall clock, no scheduling)
        self.observer = observer
        # causal provenance recorder (observe/provenance.py), riding the
        # observer: the execution-context brackets below (reply callbacks,
        # timeouts, node timers, crash/restart) feed it directly — pure
        # bookkeeping, same zero-observer-effect contract as the observer
        self._prov = getattr(observer, "provenance", None) \
            if observer is not None else None
        # wall-clock profiler (observe.WallProfiler): times handler CPU and
        # event-loop occupancy.  Reads wall clocks ONLY — it must never
        # touch RNG, sim scheduling, or the message path, so the recorder
        # trace stays byte-identical with it on vs off (tests/test_profiler)
        self.profiler = profiler
        if observer is not None and hasattr(observer, "attach_cluster"):
            # the InvariantAuditor reads cluster state (node epochs, the
            # epoch-sync ledger) passively for its monotonicity rules
            observer.attach_cluster(self)
        # controllable-delivery hook (MockCluster/Network capability,
        # impl/mock/MockCluster.java): fn(from, to, request, msg_id,
        # has_callback) -> True to swallow (the hook owns delivery/reply)
        self.request_filter: Optional[Callable] = None
        self.link = link_config or LinkConfig(self.rng.fork())
        self.reply_timeout_s = reply_timeout_s
        # request-delivery coalescing: requests arriving at a node within
        # ``batch_window_us`` sim-time are processed as one batch, letting the
        # device resolver answer the whole window's deps queries in ONE fused
        # launch (TpuDepsResolver.prefetch).  0 = deliver individually.  This
        # models a real TPU-serving node's request coalescing; it only shifts
        # delivery times by <= the window, which is legal network behavior.
        self.batch_window_us = batch_window_us
        self._inboxes: Dict[int, List] = {}
        self._inbox_drain_at: Dict[int, Optional[int]] = {}
        self._inbox_seq = 0
        self._next_msg_id = 0
        self.failures: List[BaseException] = []
        self.stats: Dict[str, int] = {}
        self.nodes: Dict[int, Node] = {}
        self.sinks: Dict[int, SimMessageSink] = {}
        self.stores: Dict[int, ListStore] = {}
        self.journal = None
        # crash-restart lifecycle: currently-down node ids, per-node
        # incarnation counters (bumped at crash, so every queued delivery /
        # timer belonging to the dead incarnation is invalidated), durable
        # restart metadata captured at crash, and the epoch-sync ledger a
        # restarted node replays on rejoin
        self.down: set = set()
        self.incarnations: Dict[int, int] = {}
        self._crash_info: Dict[int, dict] = {}
        # gray-failure lifecycle: stop-the-world paused node ids, their parked
        # (popped-but-frozen) tasks that late-fire in order at resume, a
        # per-node pause generation (a stale resume timer must not end a
        # NEWER pause), and outbound packets held by a journal-append stall
        # (fsync-before-reply: a stalled disk mutes the node's sends)
        self.paused: set = set()
        self._parked: Dict[int, List[Callable]] = {}
        self._pause_epochs: Dict[int, int] = {}
        self._held_sends: Dict[int, List[Callable]] = {}
        self._stall_epochs: Dict[int, int] = {}
        # adaptive-timeout + gray-failure knobs (LocalConfig; env-overridable)
        from ..config import LocalConfig
        _cfg = node_config if node_config is not None else LocalConfig.from_env()
        self.reply_backoff_factor = _cfg.reply_backoff_factor
        self.reply_backoff_max_s = _cfg.reply_backoff_max_s
        self.reply_backoff_jitter = _cfg.reply_backoff_jitter
        self.reply_rearm_budget = _cfg.reply_rearm_budget
        self.slow_peer_params = (_cfg.slow_peer_ewma_alpha,
                                 _cfg.slow_peer_latency_threshold_s,
                                 _cfg.slow_peer_penalty_s)
        self.journal_corruption_policy = _cfg.journal_corruption_policy
        # overload plane (local/overload.py): how long an Overloaded nack (or
        # a piggybacked load bit) marks the peer slow, and whether replies
        # carry the bit at all — piggyback only matters when admission is on
        # (off by default: the reply path stays bit-for-bit untouched)
        self.overload_penalty_us = int(_cfg.overload_penalty_s * 1_000_000)
        self.backpressure_piggyback = (_cfg.backpressure_piggyback
                                       and _cfg.admission_enabled)
        # catch-up ranges a restart has accepted but not yet handed to
        # Bootstrap (the +1us relaunch task): a second crash inside that
        # window must re-inherit them, not forget the data holes
        self._pending_catchup: Dict[int, object] = {}
        self.sync_ledger: Dict[int, set] = {}
        # fired with the freshly-rebuilt Node after every restart (the burn
        # re-applies per-node wiring: durability scheduling, store flags)
        self.on_restart_hooks: List[Callable] = []
        self._plf = None
        if progress_log:
            from ..impl.progress_log import progress_log_factory
            self._plf = progress_log_factory(progress_poll_s)
        self.agent = SimAgent(self)
        self._num_shards = num_shards
        self._delayed_stores = delayed_stores
        self._clock_drift = clock_drift
        self._resolver = resolver
        self._node_config = node_config
        # elastic-membership lifecycle: nodes drained out of every shard by
        # ``decommission`` (still live, serving prior epochs until they
        # retire) and hooks fired with each freshly-added Node (the burn
        # re-applies per-node wiring, like on_restart_hooks)
        self.decommissioned: set = set()
        self.on_add_hooks: List[Callable] = []
        # per-node clock drift (FrequentLargeRange nowSupplier, BurnTest:329-339)
        self.clock_offsets: Dict[int, int] = {}
        for node_id in sorted(set(topology.nodes()) | set(extra_nodes or ())):
            self.stores[node_id] = ListStore(node_id)
            self.nodes[node_id] = self._make_node(node_id)
            if clock_drift:
                self._start_drift(node_id)
        if journal:
            from .journal import Journal
            self.journal = Journal()
            # append-time clock: the torn-write injector's acked-record
            # soundness gate needs to know how old the tail append is
            self.journal.now_us = lambda: self.queue.now_micros
            for node in self.nodes.values():
                for store in node.command_stores.all_stores():
                    self.journal.attach(store)
        # chaos link configs re-randomize themselves off the cluster queue
        if hasattr(self.link, "attach"):
            self.link.attach(self)

    def alloc_msg_id(self) -> int:
        self._next_msg_id += 1
        return self._next_msg_id

    def _trace(self, event: str, frm: int, to: int, msg_id, message) -> None:
        """Report one message-plane event to the trace hook and the flight
        recorder (both passive; the sim's behavior must not depend on them)."""
        if self.tracer is not None:
            self.tracer(event, frm, to, msg_id, message, self.queue.now_micros)
        if self.observer is not None:
            self.observer.on_message_event(event, frm, to, msg_id, message,
                                           self.queue.now_micros)

    def _make_node(self, node_id: int, boot_epoch: Optional[int] = None) -> Node:
        """Construct one Node (initial boot or restart).  ``boot_epoch`` caps
        the topology the node initialises with (the epoch it had durably
        reached at crash); later epochs stream in via deliver_pending."""
        incarnation = self.incarnations.get(node_id, 0)
        sink = SimMessageSink(node_id, self)
        self.sinks[node_id] = sink
        store = self.stores[node_id]
        svc = SimConfigService(self, node_id)
        scheduler = NodeScheduler(self, node_id, incarnation)
        executor_factory = None
        if self._delayed_stores:
            exec_rng = self.rng.fork()
            is_live = scheduler.is_live
            pause_gate = (lambda nid: (lambda task: self._gate(nid, task)))(node_id)
            executor_factory = (lambda rng: (lambda i: DelayedAgentExecutor(
                self.agent, self.queue, rng.fork(), is_live=is_live,
                pause_gate=pause_gate)))(exec_rng)
        svc.boot_cap = boot_epoch
        try:
            node = Node(
                node_id, sink, svc, self.agent,
                scheduler, store, self.rng.fork(),
                now_micros=(lambda nid: (lambda: self.queue.now_micros
                                         + self.clock_offsets.get(nid, 0)))(node_id),
                num_shards=self._num_shards,
                executor_factory=executor_factory,
                progress_log_factory=self._plf,
                resolver=self._resolver,
                config=self._node_config)
        finally:
            svc.boot_cap = None
        # flight-recorder wiring (survives restarts: every rebuilt incarnation
        # reports into the same run-wide recorder); the wall profiler rides
        # the same lifecycle
        node.observer = self.observer
        node.profiler = self.profiler
        return node

    # -- pause lifecycle (the pause nemesis substrate) ------------------------
    def _gate(self, node_id: int, task: Callable[[], None]) -> bool:
        """Park ``task`` if ``node_id`` is stop-the-world paused.  Returns
        True when parked (the caller must NOT run); parked tasks late-fire in
        park order at ``resume``.  Idle-accounting note: a parked task was
        already popped (counter decremented) and resume re-adds it as a fresh
        entry (counter incremented) — the queue's live accounting stays exact
        across the pause, the PR-1 cancel() bug class's pause analog."""
        if node_id in self.paused:
            self._parked.setdefault(node_id, []).append(task)
            return True
        return False

    def pause(self, node_id: int) -> int:
        """Stop the node's world: scheduler, sinks, store executors and
        timers freeze (tasks park as they come due); inbound messages queue.
        Peers observe only silence — the node is slow, not dead.  Returns a
        pause generation token for ``resume``."""
        assert node_id in self.nodes and node_id not in self.down, \
            f"node {node_id} is not live"
        assert node_id not in self.paused, f"node {node_id} is already paused"
        self.paused.add(node_id)
        epoch = self._pause_epochs.get(node_id, 0) + 1
        self._pause_epochs[node_id] = epoch
        self._count("node_pauses")
        return epoch

    def resume(self, node_id: int, token: Optional[int] = None) -> None:
        """End a pause: every parked task re-enqueues at NOW, in park order —
        all frozen timers late-fire, violating every timeout assumption at
        once (the post-GC-pause timer storm).  ``token`` guards a stale
        resume timer against ending a newer pause."""
        if node_id not in self.paused:
            return
        if token is not None and self._pause_epochs.get(node_id) != token:
            return
        self.paused.discard(node_id)
        for task in self._parked.pop(node_id, []):
            self.queue.add_after(0, task)
        self._count("node_resumes")

    # -- journal-append stalls (the disk-stall nemesis substrate) -------------
    def hold_send(self, node_id: int, emit: Callable[[], None]) -> None:
        """Buffer an outbound packet of a journal-stalled node (the send path
        blocks on fsync).  Drains at ``unstall_journal``; dies with the
        process at ``crash`` — alongside the unsynced journal tail, so no
        peer ever observed state the crash un-persisted."""
        self._held_sends.setdefault(node_id, []).append(emit)

    def stall_journal(self, node_id: int) -> int:
        """Start a journal-append stall: durability (and every outbound
        packet — fsync-before-reply) lags execution until unstall.  Returns a
        stall generation token."""
        assert self.journal is not None, "disk stalls require the journal"
        assert node_id in self.nodes and node_id not in self.down, \
            f"node {node_id} is not live"
        self.journal.stall(node_id)
        epoch = self._stall_epochs.get(node_id, 0) + 1
        self._stall_epochs[node_id] = epoch
        self._count("journal_stalls")
        return epoch

    def unstall_journal(self, node_id: int, token: Optional[int] = None) -> None:
        """The append path caught up: buffered records become durable and the
        held outbound packets hit the wire (in order)."""
        if self.journal is None or not self.journal.is_stalled(node_id):
            return
        if token is not None and self._stall_epochs.get(node_id) != token:
            return
        self.journal.unstall(node_id)
        for emit in self._held_sends.pop(node_id, []):
            self.queue.add_after(0, emit)

    # -- crash-restart lifecycle (the crash-restart nemesis substrate) --------
    def crash(self, node_id: int) -> None:
        """Kill a node mid-flight: its in-memory command stores, per-key
        indexes, device mirrors, message callbacks and timers are destroyed
        and messages in flight to it are dropped.  The durable stores — the
        journal and the data files (ListStore) — survive for ``restart``."""
        assert node_id in self.nodes and node_id not in self.down, \
            f"node {node_id} is not live"
        assert self.journal is not None, \
            "crash-restart requires the journal (the restart store of record)"
        assert self._num_shards == 1, \
            "restart replay keys journal logs by store id; multi-store range " \
            "assignment is not stable across a restart boundary"
        node = self.nodes.pop(node_id)
        self.down.add(node_id)
        # a paused process dies parked: its frozen timers/deliveries die with
        # it (they were already popped, so accounting stays exact)
        self.paused.discard(node_id)
        self._parked.pop(node_id, None)
        # crash during a journal-append stall: the unsynced tail is gone, and
        # so are the outbound packets fsync was holding — no peer ever saw
        # the state those records carried
        self._held_sends.pop(node_id, None)
        lost = self.journal.lose_unsynced(node_id)
        if lost:
            self.stats["journal_unsynced_lost"] = \
                self.stats.get("journal_unsynced_lost", 0) + lost
        # invalidate every queued delivery/timer addressed to this incarnation
        self.incarnations[node_id] = self.incarnations.get(node_id, 0) + 1
        # durable restart metadata (real nodes persist bootstrap progress
        # markers: losing them would let a half-bootstrapped replica serve
        # reads over ranges it never fetched).  Data-store stale marks are
        # held by VOLATILE heal machinery that dies with the process, so the
        # marked ranges re-enter the catch-up ladder at restart instead.
        data = self.stores[node_id]
        pending = data.stale_ranges
        for cs in node.command_stores.all_stores():
            pending = pending.union(cs.pending_bootstrap)
        # debt the previous restart never got to hand to Bootstrap (crashed
        # again before its relaunch task fired): still owed after this crash
        leftover = self._pending_catchup.pop(node_id, None)
        if leftover is not None:
            pending = pending.union(leftover)
        self._crash_info[node_id] = {
            "epoch": node.topology.current_epoch,
            "pending": pending,
        }
        data._stale_marks.clear()
        # tear down volatile machinery without corrupting idle accounting:
        # progress-log polls, node timers, reply callbacks + their timeouts
        for cs in node.command_stores.all_stores():
            close = getattr(cs.progress_log, "close", None)
            if close is not None:
                close()
        if isinstance(node.scheduler, NodeScheduler):
            node.scheduler.teardown()
        self.sinks[node_id].teardown()
        # purge the request-coalescing inbox (those messages were in RAM)
        self._inboxes.pop(node_id, None)
        self._inbox_drain_at.pop(node_id, None)
        if self._prov is not None:
            # fault-ins are first-class causal events: an injected crash is
            # often the true origin of a divergence yet emits no trace byte
            self._prov.on_crash(node_id, self.queue.now_micros)
        if self.observer is not None:
            # the auditor re-baselines the node's lifecycle state here: the
            # journal replay at restart legitimately re-observes commands at
            # their durable tier, below whatever the volatile state reached
            self.observer.on_crash(node_id)
        self._count("node_crashes")

    def restart(self, node_id: int, lose_tail: int = 0) -> Node:
        """Bring a crashed node back: reconstruct every command store from its
        journal (volatile execution state is lost — commands resume from
        their durable tier, STABLE / PRE_APPLIED), re-register with the
        topology service, replay the epoch-sync ledger, and re-enter the
        bootstrap catch-up ladder for ranges whose fetch the crash killed.
        ``lose_tail`` optionally drops the last N journal records per store
        first (unsynced-tail loss experiments; NOT sound for promises)."""
        assert node_id in self.down, f"node {node_id} is not down"
        info = self._crash_info.pop(node_id)
        if lose_tail:
            for sid in range(self._num_shards):
                self.journal.drop_tail(node_id, sid, lose_tail)
        self.down.discard(node_id)
        node = self._make_node(node_id, boot_epoch=info["epoch"])
        self.nodes[node_id] = node
        # topology metadata is durable on a real node: re-install every epoch
        # below the boot epoch BEFORE journal replay — replay's waiting_on
        # re-derivation judges each dep's participation against the ranges the
        # store owned AT THE DEP'S EPOCH (ranges_at), and an unknown old epoch
        # reads as "never owned", silently dropping the dep from the execution
        # frontier (seed-0 replica divergence: a later write applied over an
        # unapplied earlier one).  Also keeps precise_epochs answerable for
        # old transactions (client probes, recovery).
        for topo in sorted(self.topologies, key=lambda t: t.epoch, reverse=True):
            if topo.epoch < node.topology.min_epoch:
                node.topology.reload_prior_epoch(
                    topo, self.sync_ledger.get(topo.epoch))
                node.command_stores.update_topology(topo)
        from ..local import commands as C
        from ..local.command_store import CommandStore, SafeCommandStore
        from ..primitives.keys import Ranges as _Ranges
        quarantine = _Ranges.EMPTY
        for cs in node.command_stores.all_stores():
            self.journal.attach(cs)
            # verified replay: every record re-checked against its CRC32; a
            # torn tail truncates to the last whole record; mid-log
            # corruption halts loudly or quarantines per the configured
            # policy (LocalConfig.journal_corruption_policy)
            replay = self.journal.restart_replay(
                node_id, cs.id, policy=self.journal_corruption_policy)
            if replay.torn_tail_dropped:
                self.stats["journal_torn_records"] = \
                    self.stats.get("journal_torn_records", 0) \
                    + replay.torn_tail_dropped
            damaged = dict(replay.quarantined)

            def on_damaged(txn_id, command, problem, cs=cs, damaged=damaged):
                # a record that PASSED checksum but decoded to inconsistent
                # state (replay-side damage): quarantine it like a corrupt
                # record — drop its journal entries, bootstrap its footprint
                self.journal.erase_key(node_id, cs.id, txn_id)
                damaged[txn_id] = command.route

            # synchronous replay (process start blocks on journal replay),
            # under the store's logical-thread discipline
            prev, CommandStore._current = CommandStore._current, cs
            try:
                safe = SafeCommandStore(cs)
                C.replay_journal(safe, replay.commands, on_damaged=on_damaged)
                for txn_id in replay.quarantined:
                    # knowledge LOST, not absent: the tombstone answers
                    # "truncated/unknowable" — a quarantined replica that
                    # answers "never witnessed" hands recovery/inference a
                    # false proof (an applied txn was invalidated with it)
                    C.install_quarantine_tombstone(safe, txn_id)
            finally:
                CommandStore._current = prev
            if damaged:
                self.stats["journal_quarantined_txns"] = \
                    self.stats.get("journal_quarantined_txns", 0) + len(damaged)
                for txn_id, route in damaged.items():
                    if route is None:
                        # no surviving record names a route: route is set at
                        # the FIRST transition (preaccept), so a route-less
                        # txn never progressed past a stub — no writes can
                        # have landed, the tombstone alone suffices.  (A
                        # whole-store fallback here bootstrapped [k0,k1000)
                        # mid-churn and recreated the seed-6 refencing stall.)
                        continue
                    parts = route.participants()
                    if not isinstance(parts, _Ranges):
                        parts = parts.to_ranges()
                    quarantine = quarantine.union(parts)
            resume = getattr(cs.progress_log, "resume_after_restart", None)
            if resume is not None:
                resume()
        # stream the epochs the node missed while down (adoption diffs fire
        # normal bootstraps), then replay sync completions peers broadcast
        self.queue.add_after(0, node.config_service.deliver_pending)
        for epoch in sorted(self.sync_ledger):
            for n in sorted(self.sync_ledger[epoch]):
                if n != node_id:
                    node.on_remote_sync_complete(n, epoch)
        pending = info["pending"]
        if len(quarantine):
            # quarantined footprints re-enter the bootstrap catch-up ladder:
            # the replica treats the affected ranges as never-fetched and
            # streams them fresh from peers (quarantine-and-bootstrap)
            pending = pending.union(quarantine)
        if pending:
            self._pending_catchup[node_id] = pending

            def relaunch():
                from ..local.bootstrap import Bootstrap
                cur = self.nodes.get(node_id)
                if cur is not node:
                    return   # crashed again: crash() re-inherited the debt
                self._pending_catchup.pop(node_id, None)
                for cs in node.command_stores.all_stores():
                    mine = pending.intersection(cs.all_ranges()) \
                        .without(cs.pending_bootstrap)
                    if mine:
                        Bootstrap(node, cs, mine, node.epoch(),
                                  catch_up=True).start()
            # after deliver_pending so ownership reflects the live topology
            self.queue.add_after(1, relaunch)
        for hook in list(self.on_restart_hooks):
            hook(node)
        if self._prov is not None:
            self._prov.on_restart(node_id, self.queue.now_micros)
        if self.observer is not None:
            # replay is complete: the auditor resumes normal edge checking
            # for this node (post-restart traffic takes live paths again)
            self.observer.on_restart(node_id)
        self._count("node_restarts")
        return node

    def _start_drift(self, node_id: int) -> None:
        """Random-walk clock drift: small 50µs-5ms jumps, occasional 1-10ms
        large jumps (BurnTest.java:329-339 FrequentLargeRange)."""
        rng = self.rng.fork()

        def jump():
            if rng.next_float() < 0.1:
                delta = rng.next_int(1_000, 10_000)
            else:
                delta = rng.next_int(50, 5_000)
            # drift forward or back, but never behind real sim time
            off = self.clock_offsets.get(node_id, 0)
            off += delta if rng.next_boolean() else -delta
            self.clock_offsets[node_id] = max(0, off)

        self.scheduler.recurring(0.05, jump)

    # -- elastic membership (join / decommission) -----------------------------
    def add_node(self, node_id: int) -> Node:
        """Spin up a brand-new process mid-run: fresh (empty) data store,
        fresh Node initialised at the CURRENT epoch.  The node owns nothing
        until a topology change gives it shards — its adoption diff then
        runs the normal bootstrap ladder (fence sync point + data fetch)
        against the live peers, exactly like any freshly-adopted range.
        Joining is therefore ``add_node`` + a join epoch (TopologyRandomizer
        ``join`` / MembershipNemesis), never a special data path."""
        assert node_id not in self.nodes and node_id not in self.down, \
            f"node {node_id} already exists"
        self.stores[node_id] = ListStore(node_id)
        node = self._make_node(node_id)
        self.nodes[node_id] = node
        if self._clock_drift:
            self._start_drift(node_id)
        if self.journal is not None:
            for store in node.command_stores.all_stores():
                self.journal.attach(store)
        self.decommissioned.discard(node_id)
        for hook in list(self.on_add_hooks):
            hook(node)
        self._count("node_joins")
        return node

    def decommission(self, node_id: int,
                     choose_replacement: Optional[Callable] = None) -> Optional[Topology]:
        """Remove ``node_id`` from EVERY shard of the latest topology in one
        new epoch (the hand-off): each vacated slot is filled by a live
        member (``choose_replacement(shard, candidates) -> node`` overrides
        the default least-loaded pick).  NOTE: this manual API applies NO
        clean-readable-quorum floor — the seeded schedules
        (TopologyRandomizer._leave / MembershipNemesis) layer that check on
        top; a direct caller draining a node whose shards are already
        mid-bootstrap elsewhere is asking for expected unavailability.
        The process stays LIVE — it keeps
        serving prior-epoch reads, recovery evidence and bootstrap fetches
        until those epochs retire; the new replicas bootstrap their adopted
        ranges from it and its peers through the normal ladder.  Returns the
        new topology, or None when some shard has no replacement candidate
        (every live node already replicates it)."""
        current = self.topologies[-1]
        if not current.contains_node(node_id):
            self.decommissioned.add(node_id)
            return None   # already out of every shard: just mark drained
        new_shards = self.plan_handoff(
            list(current.shards), node_id,
            candidate_pool=[n for n in sorted(self.nodes)
                            if n != node_id and n not in self.down
                            and n not in self.decommissioned],
            choose_replacement=choose_replacement)
        if new_shards is None:
            return None
        topology = Topology(current.epoch + 1, new_shards)
        self.update_topology(topology)
        self.decommissioned.add(node_id)
        self._count("node_decommissions")
        return topology

    def plan_handoff(self, shards: List[Shard], leaver: int,
                     candidate_pool: List[int],
                     choose_replacement: Optional[Callable] = None,
                     shard_ok: Optional[Callable] = None) -> Optional[List[Shard]]:
        """The shared hand-off planner behind ``decommission`` and the
        randomizer's ``leave`` mutation: replace ``leaver`` in every shard
        with a candidate (``choose_replacement(shard, candidates)``
        overrides the default least-loaded pick), optionally vetoing each
        substituted shard via ``shard_ok(new_shard, pick)`` (the
        randomizer's clean-readable-quorum floor).  Returns the full new
        shard list, or None when any shard has no acceptable candidate —
        the plan is all-or-nothing."""
        load: Dict[int, int] = {}
        for shard in shards:
            for n in shard.nodes:
                load[n] = load.get(n, 0) + 1
        out: List[Shard] = []
        for shard in shards:
            if leaver not in shard.nodes:
                out.append(shard)
                continue
            candidates = [n for n in candidate_pool if n not in shard.nodes]
            if not candidates:
                return None
            if choose_replacement is not None:
                pick = choose_replacement(shard, candidates)
            else:
                pick = min(candidates, key=lambda n: (load.get(n, 0), n))
            new_shard = Shard(shard.range,
                              [pick if n == leaver else n for n in shard.nodes])
            if shard_ok is not None and not shard_ok(new_shard, pick):
                return None
            load[pick] = load.get(pick, 0) + 1
            out.append(new_shard)
        return out

    # -- topology change -----------------------------------------------------
    def update_topology(self, new_topology: Topology) -> None:
        """Advance the cluster to a new epoch: every node learns it after a
        random delay (epoch propagation skew), in epoch order."""
        assert new_topology.epoch == self.topologies[-1].epoch + 1, \
            f"epoch must advance by 1: {self.topologies[-1].epoch} -> {new_topology.epoch}"
        self.topologies.append(new_topology)
        for node_id in sorted(self.nodes):
            delay = self.rng.next_int(200, 5000)
            svc = self.nodes[node_id].config_service
            self.queue.add_after(delay, svc.deliver_pending)

    # -- message routing ----------------------------------------------------
    def route(self, from_node: int, to_node: int, request: Request, msg_id: int,
              has_callback: bool) -> None:
        self._count(f"{type(request).__name__}")
        if self.request_filter is not None and \
                self.request_filter(from_node, to_node, request, msg_id,
                                    has_callback):
            return
        if to_node in self.down:
            # connection refused: the sender observes it as a link failure
            self._trace("DOWN", from_node, to_node, msg_id, request)
            if has_callback:
                self.queue.add_after(
                    self.link.latency_us(from_node, to_node),
                    lambda: self.sinks[from_node].report_failure(
                        msg_id, to_node,
                        ConnectionError(f"node {to_node} is down")))
            return
        action = self.link.action(from_node, to_node, request) if from_node != to_node \
            else LinkConfig.DELIVER
        self._trace(action.upper(), from_node, to_node, msg_id, request)
        if action in (LinkConfig.DROP, LinkConfig.FAILURE):
            if action == LinkConfig.FAILURE and has_callback:
                self.queue.add_after(
                    self.link.latency_us(from_node, to_node),
                    lambda: self.sinks[from_node].report_failure(
                        msg_id, to_node, ConnectionError(f"link {from_node}->{to_node}")))
            return
        latency = 0 if from_node == to_node else self.link.latency_us(from_node, to_node)
        ctx = ReplyContext(from_node, msg_id)
        if self.batch_window_us > 0:
            self._inbox_deliver(to_node, request, from_node, ctx, latency)
        else:
            inc = self.incarnations.get(to_node, 0)
            self.queue.add_after(latency, lambda: self._deliver(
                to_node, request, from_node, ctx, inc))
        if action == LinkConfig.DELIVER_WITH_FAILURE and has_callback:
            self.queue.add_after(
                self.link.latency_us(from_node, to_node),
                lambda: self.sinks[from_node].report_failure(
                    msg_id, to_node, ConnectionError(f"link {from_node}->{to_node}")))

    def _deliver(self, to_node: int, request: Request, from_node: int,
                 ctx: "ReplyContext", incarnation: Optional[int] = None) -> None:
        if to_node in self.down or (
                incarnation is not None
                and incarnation != self.incarnations.get(to_node, 0)):
            return   # the TCP connection died with the target's process
        if self._gate(to_node, lambda: self._deliver(
                to_node, request, from_node, ctx, incarnation)):
            return   # paused process: the packet queues in its socket buffer
        node = self.nodes.get(to_node)
        if node is None:
            return
        self._trace("RECV", from_node, to_node, ctx.msg_id, request)
        node.receive(request, from_node, ctx)

    def route_reply(self, from_node: int, to_node: int, reply_context: ReplyContext,
                    reply: Reply, overloaded: bool = False) -> None:
        self._count(f"{type(reply).__name__}")
        action = self.link.action(from_node, to_node, reply) if from_node != to_node \
            else LinkConfig.DELIVER
        self._trace(f"RPLY_{action.upper()}", from_node, to_node,
                    reply_context.msg_id, reply)
        if action in (LinkConfig.DROP, LinkConfig.FAILURE):
            return
        if to_node in self.down:
            return   # replies to a down node vanish with its connections
        latency = 0 if from_node == to_node else self.link.latency_us(from_node, to_node)
        inc = self.incarnations.get(to_node, 0)

        def deliver():
            if to_node in self.down or inc != self.incarnations.get(to_node, 0):
                return  # the recipient crashed while the reply was in flight
            if self._gate(to_node, deliver):
                return  # paused recipient: the reply queues until resume
            self._trace("RECV_RPLY", from_node, to_node,
                        reply_context.msg_id, reply)
            self.sinks[to_node].deliver_reply(from_node, reply_context.msg_id,
                                              reply, overloaded=overloaded)
        self.queue.add_after(latency, deliver)

    def _count(self, key: str) -> None:
        self.stats[key] = self.stats.get(key, 0) + 1

    # -- request-delivery coalescing (batch_window_us) ------------------------
    def _inbox_deliver(self, to_node: int, request: Request, from_node: int,
                       ctx: "ReplyContext", latency: int) -> None:
        arrival = self.queue.now_micros + latency
        self._inboxes.setdefault(to_node, []).append(
            (arrival, self._inbox_seq, request, from_node, ctx))
        self._inbox_seq += 1
        due = arrival + self.batch_window_us
        scheduled = self._inbox_drain_at.get(to_node)
        # also RE-schedule when this arrival precedes the pending drain: a
        # fast link's message must never wait out a slow link's window (no
        # message is held longer than its own arrival + window; the stale
        # later drain fires harmlessly on whatever remains)
        if scheduled is None or due < scheduled:
            self._inbox_drain_at[to_node] = due
            self.queue.add_after(due - self.queue.now_micros,
                                 lambda: self._drain_inbox(to_node))

    def _drain_inbox(self, to_node: int) -> None:
        """Process every request that has arrived at ``to_node`` by now, as one
        batch: prefetch the batch's declared deps queries per store (one fused
        device launch each), then run the handlers sequentially in arrival
        order — exact sequential semantics, batched device traffic."""
        if self._gate(to_node, lambda: self._drain_inbox(to_node)):
            return   # paused process: the batch drains at resume
        box = self._inboxes.get(to_node, [])
        now = self.queue.now_micros
        ready = sorted(e for e in box if e[0] <= now)
        rest = [e for e in box if e[0] > now]
        self._inboxes[to_node] = rest
        self._inbox_drain_at[to_node] = None
        if rest:
            due = min(e[0] for e in rest) + self.batch_window_us
            self._inbox_drain_at[to_node] = due
            self.queue.add_after(due - now, lambda: self._drain_inbox(to_node))
        if not ready:
            return
        node = self.nodes.get(to_node)
        if node is None:
            return
        # even a batch of one PreAccept gains: its deps + max-conflict consults
        # fuse into a single launch instead of two
        per_store: Dict[object, List] = {}
        with_specs = []
        for entry in ready:
            specs = entry[2].prefetch_specs(node)
            with_specs.append((entry, bool(specs)))
            for store, spec in specs or ():
                per_store.setdefault(store, []).append(spec)
        # deps-query-bearing requests drain FIRST: a Commit/Apply processed
        # mid-window moves the covering bounds and invalidates the window's
        # prefetched answers, so serve the queries before advancing state.
        # Reordering within the window is legal network behavior (it is
        # indistinguishable from jitter below the coalescing latency), and
        # the (priority, arrival, seq) key keeps it deterministic.
        with_specs.sort(key=lambda p: (not p[1], p[0][0], p[0][1]))
        for store, specs in per_store.items():
            engine = getattr(store, "batch_engine", None)
            if engine is not None:
                # columnar ingress accounting: the delivery window IS the
                # per-tick batch the engine's ConsultBatch bridge packs
                # (protocol_batch/engine.consult_ingress); counted here so
                # the ramp bench can report rows-per-window amortization
                engine.stats["ingress_windows"] += 1
                engine.stats["ingress_rows"] += len(specs)
            store.resolver.prefetch(specs)
        try:
            for (_at, _seq, request, frm, ctx), _h in with_specs:
                self._trace("RECV", frm, to_node, ctx.msg_id, request)
                node.receive(request, frm, ctx)
        finally:
            for store in per_store:
                store.resolver.end_batch()

    # -- execution ----------------------------------------------------------
    def run_until_idle(self, max_tasks: int = 1_000_000) -> int:
        """Drain the queue until only recurring tasks remain; returns tasks
        executed. Raises any node failure."""
        n = 0
        profiler = self.profiler
        while n < max_tasks and self.queue.has_nonrecurring():
            task = self.queue.pop()
            if task is None:
                break
            if profiler is not None:
                t0 = profiler.now()
                task()
                profiler.on_task(profiler.now() - t0, len(self.queue._heap))
            else:
                task()
            n += 1
            if self.failures:
                raise self.failures[0]
        return n

    def run_until(self, predicate: Callable[[], bool], max_tasks: int = 1_000_000) -> bool:
        n = 0
        profiler = self.profiler
        while n < max_tasks:
            if predicate():
                return True
            task = self.queue.pop()
            if task is None:
                return predicate()
            if profiler is not None:
                # event-loop occupancy plane: per-task wall cost + pending-
                # queue depth (len of the raw heap is O(1); the cancelled-
                # entry overcount is fine for a depth distribution)
                t0 = profiler.now()
                task()
                profiler.on_task(profiler.now() - t0, len(self.queue._heap))
            else:
                task()
            n += 1
            if self.failures:
                raise self.failures[0]
        return predicate()

    def run_for(self, sim_seconds: float, max_tasks: int = 1_000_000) -> None:
        """Advance simulated time by ``sim_seconds``, executing everything due."""
        deadline = self.queue.now_micros + int(sim_seconds * 1_000_000)
        self.run_until(lambda: self.queue.now_micros >= deadline, max_tasks)

    @property
    def now_micros(self) -> int:
        return self.queue.now_micros
