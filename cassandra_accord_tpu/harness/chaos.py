"""Hostile-network fault injection: periodically re-randomized per-link behavior.

Capability parity with the reference burn's link chaos
(``accord.impl.basic.Cluster`` — overrideLinks/partition/linkOverrideSupplier,
Cluster.java:455-459,615-760; ``NodeSink.Action``, NodeSink.java:45): every
``interval_s`` of sim-time the whole link table is re-rolled:

- with a per-run biased probability, a **network partition** cuts a random
  minority (up to ``(rf+1)//2 - 1`` nodes, so every shard keeps a live quorum
  on the majority side) off from the rest — messages crossing the boundary DROP;
- on top, a random **override kind** is applied: NONE, PAIRED_UNIDIRECTIONAL
  (each node paired with one other, one direction overridden), RANDOM_UNIDIRECTIONAL
  or RANDOM_BIDIRECTIONAL (a random set of links overridden).  Overridden links
  get a per-message weighted action distribution over
  {DELIVER, DROP, DELIVER_WITH_FAILURE, FAILURE} and/or inflated latencies.

The schedule itself is driven by the cluster's deterministic queue, so the whole
fault pattern replays from the seed.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..utils.random import RandomSource
from .cluster import Cluster, LinkConfig

_ACTIONS = (LinkConfig.DELIVER, LinkConfig.DROP,
            LinkConfig.DELIVER_WITH_FAILURE, LinkConfig.FAILURE)


class _LinkOverride:
    """One overridden link: per-message weighted action pick and/or a latency
    override (linkOverrideSupplier, Cluster.java:692-711)."""

    __slots__ = ("rng", "weights", "latency_range")

    def __init__(self, rng: RandomSource, weights: Optional[List[float]],
                 latency_range: Optional[Tuple[int, int]]):
        self.rng = rng
        self.weights = weights                    # None => keep default action
        self.latency_range = latency_range        # None => keep default latency

    def action(self) -> Optional[str]:
        if self.weights is None:
            return None
        r = self.rng.next_float() * sum(self.weights)
        acc = 0.0
        for w, a in zip(self.weights, _ACTIONS):
            acc += w
            if r < acc:
                return a
        return _ACTIONS[-1]

    def latency_us(self) -> Optional[int]:
        if self.latency_range is None:
            return None
        lo, hi = self.latency_range
        return self.rng.next_int(lo, hi)


class RandomizedLinkConfig(LinkConfig):
    """LinkConfig whose behavior is re-rolled every ``interval_s`` sim-seconds.

    ``heal()`` permanently restores a benign network (used by the burn once all
    ops have resolved, mirroring the reference's noMoreWorkSignal cancelling the
    chaos task)."""

    KINDS = ("none", "paired_uni", "random_uni", "random_bidi")

    def __init__(self, rng: RandomSource, rf: int, interval_s: float = 5.0,
                 min_latency_us: int = 500, max_latency_us: int = 20_000):
        super().__init__(rng, min_latency_us, max_latency_us)
        self.rf = rf
        self.interval_s = interval_s
        # per-run biased partition coin (Cluster.java:719 biasedUniformBools)
        self.partition_chance = rng.next_float()
        self.partitioned: frozenset = frozenset()
        # ASYMMETRIC partitions (reference Cluster.java overrideLinks
        # supports per-link asymmetry), behind the same per-run biased coin:
        # - one-way cut: the minority side's links fail in ONE direction
        #   only (it can hear but not be heard, or speak but not be heard
        #   back — "deaf"/"mute" halves of a failing NIC);
        # - bridge partial partition: two sides cannot reach each other
        #   directly, but a bridge node talks to both (a half-healed
        #   spanning link) — no side is fully cut off yet no quorum sees
        #   the full membership.
        self.asym_chance = rng.next_float()
        self.partition_mode = "sym"      # sym | oneway_out | oneway_in | bridge
        self.bridge: frozenset = frozenset()   # bridge node(s) for "bridge"
        self.overrides: Dict[Tuple[int, int], _LinkOverride] = {}
        self.healed = False
        self._nodes: List[int] = []
        self._task = None

    # -- wiring ---------------------------------------------------------------
    def attach(self, cluster: Cluster) -> None:
        """Register the re-randomization task on the cluster queue (the chaos
        recurring task, Cluster.java:455-459), retaining the handle so
        ``heal`` can CANCEL it — the ``healed`` no-op guard alone left the
        reroll firing (and drawing rng) forever after quiesce."""
        self._cluster = cluster
        self._nodes = sorted(cluster.nodes)

        def reroll():
            if not self.healed:
                # refresh the node set each re-roll: elastic membership
                # spawns processes mid-burn, and a snapshot taken at attach
                # would leave every joiner permanently exempt from
                # partitions and link faults (membership would not be a
                # fault axis for the very nodes it adds).  Down nodes stay
                # in the pool (they restart; and for non-elastic runs
                # nodes|down is constant, so trajectories are unchanged)
                self._nodes = sorted(set(cluster.nodes) | cluster.down)
                self.randomize()

        self._task = cluster.scheduler.recurring(self.interval_s, reroll)
        self.randomize()

    def heal(self) -> None:
        self.healed = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self.partitioned = frozenset()
        self.overrides = {}

    # -- the re-roll ----------------------------------------------------------
    def randomize(self) -> None:
        rng = self.rng
        # partition: minority side cut off (Cluster.java:615-622), with the
        # asymmetric variants behind their own per-run biased coin
        self.partitioned = frozenset()
        self.partition_mode = "sym"
        self.bridge = frozenset()
        if self._nodes and rng.next_float() < self.partition_chance:
            size = rng.next_int((self.rf + 1) // 2)
            if size > 0:
                picks = list(self._nodes)
                rng.shuffle(picks)
                self.partitioned = frozenset(picks[:size])
                if rng.next_float() < self.asym_chance:
                    self.partition_mode = rng.pick(
                        ["oneway_out", "oneway_in", "bridge"])
                    if self.partition_mode == "bridge":
                        rest = [n for n in picks[size:]]
                        if rest:
                            self.bridge = frozenset(rest[:1])
                        else:
                            self.partition_mode = "sym"
        # link overrides (Cluster.java:714-741)
        self.overrides = {}
        kind = rng.pick(list(self.KINDS))
        if kind == "none" or len(self._nodes) < 2:
            return
        if kind == "paired_uni":
            picks = list(self._nodes)
            rng.shuffle(picks)
            for i in range(0, len(picks) - 1, 2):
                self.overrides[(picks[i], picks[i + 1])] = self._make_override()
        else:
            bidi = kind == "random_bidi"
            n = len(self._nodes)
            count = rng.next_int(1, max(2, n if (bidi or rng.next_boolean())
                                        else max(2, (n * n) // 2)))
            for _ in range(count):
                a = rng.pick(self._nodes)
                b = rng.pick(self._nodes)
                self.overrides[(a, b)] = self._make_override()
                if bidi:
                    self.overrides[(b, a)] = self._make_override()

    def _make_override(self) -> _LinkOverride:
        rng = self.rng
        # OverrideLinkKind: ACTION / LATENCY / BOTH (Cluster.java:690-711)
        which = rng.pick(["action", "latency", "both"])
        weights = None
        latency_range = None
        if which in ("action", "both"):
            weights = [rng.next_float() for _ in _ACTIONS]
            weights[0] += 1.0   # keep DELIVER likeliest so the run stays live
        if which in ("latency", "both"):
            lo = rng.next_int(1_000, 300_000)
            hi = lo + rng.next_int(1_000, 1_700_000)
            latency_range = (lo, hi)
        return _LinkOverride(rng.fork(), weights, latency_range)

    def _partition_drops(self, from_node: int, to_node: int) -> bool:
        """Does the current partition cut this directed link?

        - ``sym``: any link crossing the minority boundary drops (both
          directions — the classic clean partition);
        - ``oneway_out``: only packets FROM the minority drop (it hears the
          world but cannot be heard — mute);
        - ``oneway_in``: only packets TO the minority drop (it speaks but
          hears nothing back — deaf);
        - ``bridge``: links crossing the boundary drop UNLESS either
          endpoint is the bridge node, which talks to both sides."""
        crossing = (from_node in self.partitioned) != (to_node in self.partitioned)
        if not crossing:
            return False
        mode = self.partition_mode
        if mode == "oneway_out":
            return from_node in self.partitioned
        if mode == "oneway_in":
            return to_node in self.partitioned
        if mode == "bridge":
            return from_node not in self.bridge and to_node not in self.bridge
        return True

    # -- LinkConfig interface -------------------------------------------------
    def action(self, from_node: int, to_node: int, message=None) -> str:
        if self.healed:
            return LinkConfig.DELIVER
        if self._partition_drops(from_node, to_node):
            return LinkConfig.DROP
        override = self.overrides.get((from_node, to_node))
        if override is not None:
            act = override.action()
            if act is not None:
                return act
        return LinkConfig.DELIVER

    def latency_us(self, from_node: int, to_node: int) -> int:
        if not self.healed:
            override = self.overrides.get((from_node, to_node))
            if override is not None:
                lat = override.latency_us()
                if lat is not None:
                    return lat
        return super().latency_us(from_node, to_node)
