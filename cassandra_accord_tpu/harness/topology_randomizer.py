"""Random topology mutations for the burn test.

Capability parity with ``accord.burn.TopologyRandomizer`` (TopologyRandomizer.java:1-524):
periodically mutate the cluster topology — move a replica between nodes, split a
shard's range, merge adjacent shards — driving live epoch adoption, bootstrap
(data fetch + exclusive sync point fencing) and epoch-sync machinery under load.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, List, Optional

from ..primitives.keys import Range
from ..topology.topology import Shard, Topology
from ..utils.random import RandomSource

if TYPE_CHECKING:
    from .cluster import Cluster


class TopologyRandomizer:
    def __init__(self, cluster: "Cluster", rng: RandomSource,
                 candidate_nodes: Optional[List[int]] = None):
        self.cluster = cluster
        self.rng = rng
        self.candidates = sorted(candidate_nodes or cluster.nodes)

    def maybe_update_topology(self) -> Optional[Topology]:
        """Apply one random mutation; returns the new topology (or None if the
        chosen mutation was not applicable).

        Gated on outstanding bootstraps, matching the reference
        (TopologyRandomizer.java:434 ``pendingTopologies() > 5 -> skip``):
        un-gated churn outruns bootstrap completion and drives the cluster
        into a pending-bootstrap blanket — every replica's copy of most keys
        pending, reads unable to assemble coverage from any union, and the
        bootstrap fences those reads gate stuck behind them.  The reference
        never exercises that regime; neither should the harness."""
        # distinct pending ranges cluster-wide ~ topologies in flight (one
        # mutation bootstraps 1-2 distinct ranges across its replicas) —
        # counting per-store pieces over-gates by ~replication factor
        pending = {rng for node in self.cluster.nodes.values()
                   for cs in node.command_stores.all_stores()
                   for rng in (cs.pending_bootstrap or ())}
        if len(pending) > 5:
            return None
        current = self.cluster.topologies[-1]
        mutation = self.rng.pick(["move", "move", "split", "merge"])
        shards = list(current.shards)
        if mutation == "move":
            new_shards = self._move(shards)
        elif mutation == "split":
            new_shards = self._split(shards)
        else:
            new_shards = self._merge(shards)
        if new_shards is None:
            return None
        topology = Topology(current.epoch + 1, new_shards)
        self.cluster.update_topology(topology)
        return topology

    # -- mutations -----------------------------------------------------------
    def _move(self, shards: List[Shard]) -> Optional[List[Shard]]:
        """Replace one replica of one shard with a node not currently in it."""
        idx = self.rng.next_int(len(shards))
        shard = shards[idx]
        outside = [n for n in self.candidates if n not in shard.nodes]
        if not outside:
            return None
        newcomer = self.rng.pick(outside)
        leaver = self.rng.pick(list(shard.nodes))
        replicas = [newcomer if n == leaver else n for n in shard.nodes]
        shards[idx] = Shard(shard.range, replicas)
        return shards

    def _split(self, shards: List[Shard]) -> Optional[List[Shard]]:
        """Split one shard's range at an interior point."""
        idx = self.rng.next_int(len(shards))
        shard = shards[idx]
        start, end = shard.range.start, shard.range.end
        sv, ev = getattr(start, "value", None), getattr(end, "value", None)
        if not isinstance(sv, int) or not isinstance(ev, int) or ev - sv < 2:
            return None
        mid = sv + 1 + self.rng.next_int(ev - sv - 1)
        cls = type(start)
        prefix = getattr(start, "prefix", 0)
        mid_key = cls(mid, prefix)
        shards[idx: idx + 1] = [Shard(Range(start, mid_key), list(shard.nodes)),
                                Shard(Range(mid_key, end), list(shard.nodes))]
        return shards

    def _merge(self, shards: List[Shard]) -> Optional[List[Shard]]:
        """Merge two adjacent shards (the survivors' replicas bootstrap the
        merged range)."""
        if len(shards) < 2:
            return None
        idx = self.rng.next_int(len(shards) - 1)
        a, b = shards[idx], shards[idx + 1]
        if a.range.end != b.range.start or a.rf != b.rf:
            return None
        shards[idx: idx + 2] = [Shard(Range(a.range.start, b.range.end),
                                      list(a.nodes))]
        return shards
