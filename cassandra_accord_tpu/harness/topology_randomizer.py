"""Random topology mutations for the burn test.

Capability parity with ``accord.burn.TopologyRandomizer`` (TopologyRandomizer.java:1-524):
periodically mutate the cluster topology — move a replica between nodes, split a
shard's range, merge adjacent shards — driving live epoch adoption, bootstrap
(data fetch + exclusive sync point fencing) and epoch-sync machinery under load.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Iterable, List, Optional

from ..primitives.keys import Range, Ranges
from ..topology.topology import Shard, Topology
from ..utils.random import RandomSource

if TYPE_CHECKING:
    from .cluster import Cluster


class TopologyRandomizer:
    """``elastic=True`` grows the mutation mix with **join** (a node outside
    the current member set — spawned via ``Cluster.add_node`` from
    ``spawn_pool`` when no live non-member exists — takes a replica slot)
    and **leave** (a member hands off every shard it replicates to live
    peers in one epoch, ``Cluster.decommission``).  Every mutation respects
    the muted-quorum floor at range granularity (``_keeps_clean_quorum``)."""

    def __init__(self, cluster: "Cluster", rng: RandomSource,
                 candidate_nodes: Optional[List[int]] = None,
                 elastic: bool = False,
                 spawn_pool: Optional[List[int]] = None):
        self.cluster = cluster
        self.rng = rng
        self.candidates = sorted(candidate_nodes or cluster.nodes)
        self.elastic = elastic
        # node ids this randomizer may bring to life (never yet members)
        self.spawn_pool = sorted(spawn_pool or ())
        # member-count bounds for the churn-mix join/leave actions; set by
        # MembershipNemesis (its resolved membership_{min,max}_members) so
        # BOTH membership planes honor the configured bounds — None leaves
        # only the structural floors (rf, spawn-pool exhaustion)
        self.min_members: Optional[int] = None
        self.max_members: Optional[int] = None

    def _live_candidates(self) -> List[int]:
        """Move targets: the static candidate list, or — elastic — every
        live, non-drained process (joined nodes become move targets too)."""
        if self.elastic:
            return [n for n in sorted(self.cluster.nodes)
                    if n not in self.cluster.down
                    and n not in self.cluster.decommissioned]
        return self.candidates

    # -- the clean-replica floor ---------------------------------------------
    def _unreadable_at(self, node_id: int, rng_: Range) -> bool:
        """Is ``node_id``'s copy of ``rng_`` currently unreadable — the node
        muted (down/paused/journal-stalled), or the range overlapping its
        pending-bootstrap or stale marks?"""
        from .nemesis import muted_nodes
        cluster = self.cluster
        if node_id in muted_nodes(cluster):
            return True
        node = cluster.nodes.get(node_id)
        if node is None:
            return True
        probe = Ranges.of(rng_)
        for cs in node.command_stores.all_stores():
            if cs.pending_bootstrap and cs.pending_bootstrap.intersects(probe):
                return True
        stale = getattr(cluster.stores.get(node_id), "stale_ranges", None)
        if stale is not None and len(stale) and stale.intersects(probe):
            return True
        return False

    def _keeps_clean_quorum(self, shard: Shard,
                            joining: Iterable[int] = ()) -> bool:
        """Would ``shard`` (a candidate post-mutation shard) keep a READABLE
        slow-path quorum — replicas that are live, not muted, not mid-
        bootstrap/stale on the range, and not the about-to-bootstrap
        newcomers?  Stacking a second adoption (or join) onto a range whose
        previous adoption has not finished its fetch starves the range of
        clean readable copies: once every current-epoch owner of a slice is
        simultaneously re-fencing, no partial-read union can cover it and
        the range wedges against its own bootstrap fences (the seed-6
        shape).  The muted-quorum floor the nemeses share, extended to the
        churn plane (the reference gates churn globally on
        ``pendingTopologies() > 5``; this is the same idea at range
        granularity)."""
        joining = set(joining)
        clean = sum(1 for n in shard.nodes
                    if n not in joining and not self._unreadable_at(n, shard.range))
        return clean >= shard.slow_path_quorum_size

    def maybe_update_topology(self) -> Optional[Topology]:
        """Apply one random mutation; returns the new topology (or None if the
        chosen mutation was not applicable).

        Gated on outstanding bootstraps, matching the reference
        (TopologyRandomizer.java:434 ``pendingTopologies() > 5 -> skip``):
        un-gated churn outruns bootstrap completion and drives the cluster
        into a pending-bootstrap blanket — every replica's copy of most keys
        pending, reads unable to assemble coverage from any union, and the
        bootstrap fences those reads gate stuck behind them.  The reference
        never exercises that regime; neither should the harness."""
        # distinct pending ranges cluster-wide ~ topologies in flight (one
        # mutation bootstraps 1-2 distinct ranges across its replicas) —
        # counting per-store pieces over-gates by ~replication factor
        pending = {rng for node in self.cluster.nodes.values()
                   for cs in node.command_stores.all_stores()
                   for rng in (cs.pending_bootstrap or ())}
        if len(pending) > 5:
            return None
        current = self.cluster.topologies[-1]
        mutations = ["move", "move", "split", "merge"]
        if self.elastic:
            mutations += ["join", "leave"]
        mutation = self.rng.pick(mutations)
        shards = list(current.shards)
        if mutation == "move":
            new_shards = self._move(shards)
        elif mutation == "split":
            new_shards = self._split(shards)
        elif mutation == "join":
            new_shards = self._join(shards, current)
        elif mutation == "leave":
            new_shards = self._leave(shards, current)
        else:
            new_shards = self._merge(shards)
        if new_shards is None:
            return None
        topology = Topology(current.epoch + 1, new_shards)
        self.cluster.update_topology(topology)
        return topology

    # -- mutations -----------------------------------------------------------
    def _move(self, shards: List[Shard]) -> Optional[List[Shard]]:
        """Replace one replica of one shard with a node not currently in it."""
        idx = self.rng.next_int(len(shards))
        shard = shards[idx]
        outside = [n for n in self._live_candidates() if n not in shard.nodes]
        if not outside:
            return None
        newcomer = self.rng.pick(outside)
        leaver = self.rng.pick(list(shard.nodes))
        replicas = [newcomer if n == leaver else n for n in shard.nodes]
        new_shard = Shard(shard.range, replicas)
        if not self._keeps_clean_quorum(new_shard, joining=(newcomer,)):
            return None
        shards[idx] = new_shard
        return shards

    def _split(self, shards: List[Shard]) -> Optional[List[Shard]]:
        """Split one shard's range at an interior point."""
        idx = self.rng.next_int(len(shards))
        shard = shards[idx]
        start, end = shard.range.start, shard.range.end
        sv, ev = getattr(start, "value", None), getattr(end, "value", None)
        if not isinstance(sv, int) or not isinstance(ev, int) or ev - sv < 2:
            return None
        mid = sv + 1 + self.rng.next_int(ev - sv - 1)
        cls = type(start)
        prefix = getattr(start, "prefix", 0)
        mid_key = cls(mid, prefix)
        shards[idx: idx + 1] = [Shard(Range(start, mid_key), list(shard.nodes)),
                                Shard(Range(mid_key, end), list(shard.nodes))]
        return shards

    def _join(self, shards: List[Shard], current) -> Optional[List[Shard]]:
        """Bring a NON-MEMBER into the member set: a live node outside every
        shard (preferring an already-running non-member — e.g. a previously
        drained one — else a fresh process from ``spawn_pool`` via
        ``Cluster.add_node``) replaces one replica of one shard.  The
        newcomer bootstraps the range from live peers; the clean-quorum
        floor counts it unavailable until its fetch lands."""
        cluster = self.cluster
        members = current.nodes()
        if self.max_members is not None and len(members) >= self.max_members:
            return None
        live_outside = [n for n in sorted(cluster.nodes)
                        if n not in members and n not in cluster.down]
        spawnable = [n for n in self.spawn_pool if n not in cluster.nodes
                     and n not in cluster.down]
        if not live_outside and not spawnable:
            return None
        idx = self.rng.next_int(len(shards))
        shard = shards[idx]
        pool = live_outside if live_outside else spawnable
        newcomer = self.rng.pick(pool)
        leaver = self.rng.pick(list(shard.nodes))
        replicas = [newcomer if n == leaver else n for n in shard.nodes]
        new_shard = Shard(shard.range, replicas)
        # floor check BEFORE spawning (it only inspects existing members —
        # the newcomer is excluded via ``joining``): a refused join must not
        # leak a memberless fresh process into the cluster
        if not self._keeps_clean_quorum(new_shard, joining=(newcomer,)):
            return None
        if newcomer not in cluster.nodes:
            cluster.add_node(newcomer)   # counts node_joins itself
        else:
            # an already-running non-member (e.g. previously drained)
            # re-entering the member set is a join too — without this the
            # --json fault summary reports 0 joins for a rejoin-only run
            cluster._count("node_joins")
        cluster.decommissioned.discard(newcomer)   # a drained node can rejoin
        shards[idx] = new_shard
        return shards

    def _leave(self, shards: List[Shard], current) -> Optional[List[Shard]]:
        """A member hands off and leaves EVERY shard in one epoch (the
        ``Cluster.decommission`` shape, driven through the randomizer so the
        leave epoch interleaves with move/split/merge churn).  Replacements
        are live members; each affected shard must keep a clean readable
        quorum counting the (bootstrapping) replacement unavailable.  The
        leaver's process stays live serving prior epochs."""
        cluster = self.cluster
        members = sorted(current.nodes())
        if len(members) <= max(s.rf() for s in shards):
            return None   # nobody can be spared: every member is needed
        if self.min_members is not None and len(members) <= self.min_members:
            return None
        leaver = self.rng.pick(members)
        out = cluster.plan_handoff(
            shards, leaver,
            candidate_pool=[n for n in members
                            if n != leaver and n not in cluster.down
                            and n not in cluster.decommissioned],
            shard_ok=lambda new_shard, pick: self._keeps_clean_quorum(
                new_shard, joining=(pick,)))
        if out is None:
            return None
        cluster.decommissioned.add(leaver)
        cluster._count("node_decommissions")
        return out

    def _merge(self, shards: List[Shard]) -> Optional[List[Shard]]:
        """Merge two adjacent shards (the survivors' replicas bootstrap the
        merged range)."""
        if len(shards) < 2:
            return None
        idx = self.rng.next_int(len(shards) - 1)
        a, b = shards[idx], shards[idx + 1]
        if a.range.end != b.range.start or a.rf != b.rf:
            return None
        shards[idx: idx + 2] = [Shard(Range(a.range.start, b.range.end),
                                      list(a.nodes))]
        return shards
