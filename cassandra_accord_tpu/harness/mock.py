"""MockCluster: a controllable-reply unit harness for per-phase coordinator
tests.

Capability parity with ``accord.impl.mock.MockCluster`` /
``RecordingMessageSink`` / ``Network`` (impl/mock/MockCluster.java,
CoordinateTransactionTest.java:1-438): real Nodes on the simulated cluster,
with a delivery filter that lets a test HOLD matching requests in flight,
inspect them, and then for each one:

- ``release()`` — deliver normally (the replica processes and replies);
- ``reply(r)``  — swallow the request and deliver a hand-crafted reply to the
  sender's callback (preemptions, stale CheckStatusOk, nacks — states that
  are hard to reach organically);
- ``drop()``    — lose it silently (the sender's reply-timeout fires).

Interceptions are prefix-matched on message type name and optional from/to
node ids; each captures up to ``count`` requests then deactivates.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from ..primitives.keys import IntKey, Range, Ranges
from ..primitives.route import Route
from ..primitives.txn import Txn
from ..topology.topology import Shard, Topology
from ..utils import async_ as au
from .cluster import Cluster, ReplyContext


class Held:
    """One intercepted request, frozen mid-flight."""

    __slots__ = ("mock", "from_node", "to_node", "request", "msg_id",
                 "has_callback", "done")

    def __init__(self, mock: "MockCluster", from_node: int, to_node: int,
                 request, msg_id: int, has_callback: bool):
        self.mock = mock
        self.from_node = from_node
        self.to_node = to_node
        self.request = request
        self.msg_id = msg_id
        self.has_callback = has_callback
        self.done = False

    def _once(self) -> None:
        assert not self.done, "held request already resolved"
        self.done = True

    def release(self) -> None:
        """Deliver to the replica normally."""
        self._once()
        cluster = self.mock.cluster
        ctx = ReplyContext(self.from_node, self.msg_id)
        cluster.queue.add_after(0, lambda: cluster._deliver(
            self.to_node, self.request, self.from_node, ctx))

    def reply(self, reply) -> None:
        """Swallow the request; deliver ``reply`` to the sender's callback as
        if the replica had answered it."""
        self._once()
        cluster = self.mock.cluster
        cluster.queue.add_after(0, lambda: cluster.sinks[self.from_node]
                                .deliver_reply(self.to_node, self.msg_id, reply))

    def drop(self) -> None:
        """Lose the request; the sender's reply-timeout handles it."""
        self._once()

    def fail(self, exc: Optional[BaseException] = None) -> None:
        """Report a link failure to the sender's callback."""
        self._once()
        cluster = self.mock.cluster
        e = exc if exc is not None else ConnectionError(
            f"mock link {self.from_node}->{self.to_node}")
        cluster.queue.add_after(0, lambda: cluster.sinks[self.from_node]
                                .report_failure(self.msg_id, self.to_node, e))

    def __repr__(self):
        return (f"Held({type(self.request).__name__} "
                f"n{self.from_node}->n{self.to_node})")


class Interception:
    __slots__ = ("type_prefix", "from_node", "to_node", "remaining", "held")

    def __init__(self, type_prefix: str, from_node: Optional[int],
                 to_node: Optional[int], count: int):
        self.type_prefix = type_prefix
        self.from_node = from_node
        self.to_node = to_node
        self.remaining = count
        self.held: List[Held] = []

    def matches(self, from_node: int, to_node: int, request) -> bool:
        return (self.remaining > 0
                and type(request).__name__.startswith(self.type_prefix)
                and (self.from_node is None or from_node == self.from_node)
                and (self.to_node is None or to_node == self.to_node))


class MockCluster:
    """A small benign-network cluster with controllable delivery."""

    def __init__(self, rf: int = 3, seed: int = 1,
                 key_bound: int = 100, progress_log: bool = False):
        shards = [Shard(Range(IntKey(0), IntKey(key_bound)),
                        tuple(range(1, rf + 1)))]
        topology = Topology(1, shards)
        self.cluster = Cluster(topology, seed=seed, progress_log=progress_log)
        self.cluster.request_filter = self._filter
        self.interceptions: List[Interception] = []

    # -- interception ---------------------------------------------------------
    def intercept(self, type_prefix: str, from_node: Optional[int] = None,
                  to_node: Optional[int] = None, count: int = 1_000_000
                  ) -> Interception:
        """Hold up to ``count`` future requests whose type name starts with
        ``type_prefix`` (e.g. "Accept" also matches AcceptInvalidate — use
        "Accept(" semantics via exact names when that matters)."""
        ic = Interception(type_prefix, from_node, to_node, count)
        self.interceptions.append(ic)
        return ic

    def _filter(self, from_node: int, to_node: int, request, msg_id: int,
                has_callback: bool) -> bool:
        for ic in self.interceptions:
            if ic.matches(from_node, to_node, request):
                ic.remaining -= 1
                ic.held.append(Held(self, from_node, to_node, request,
                                    msg_id, has_callback))
                return True
        return False

    # -- driving --------------------------------------------------------------
    def node(self, node_id: int):
        return self.cluster.nodes[node_id]

    def coordinate(self, node_id: int, txn: Txn) -> au.AsyncResult:
        return self.cluster.nodes[node_id].coordinate(txn)

    def run_for(self, sim_seconds: float) -> None:
        self.cluster.run_for(sim_seconds)

    def run_until(self, cond: Callable[[], bool], sim_limit_s: float = 30.0
                  ) -> bool:
        deadline = self.cluster.queue.now_micros + int(sim_limit_s * 1e6)
        self.cluster.run_until(
            lambda: cond() or self.cluster.queue.now_micros > deadline)
        return cond()

    def await_held(self, ic: Interception, n: int = 1,
                   sim_limit_s: float = 10.0) -> List[Held]:
        """Run the sim until ``n`` requests are held (or the limit passes)."""
        ok = self.run_until(lambda: len(ic.held) >= n, sim_limit_s)
        assert ok, f"only {len(ic.held)}/{n} {ic.type_prefix} held"
        return ic.held[:n]

    # -- txn helpers ----------------------------------------------------------
    def write_txn(self, writes: dict) -> Txn:
        from ..impl.list_store import list_txn
        return list_txn([], writes)

    def read_txn(self, keys) -> Txn:
        from ..impl.list_store import list_txn
        return list_txn(list(keys), {})
