"""Strict-serializability verification for the list-append workload.

Capability parity with ``accord.verify.StrictSerializabilityVerifier``
(verify/StrictSerializabilityVerifier.java:40-894): client-visible observations
(what each txn read per key, what it appended, and its real-time submit/complete
window) are checked for the three properties that pin down strict serializability in
the unique-value list-append model:

1. **per-key linearizability**: every observed list for a key must be a prefix of a
   single total per-key order (the applied order);
2. **real-time order**: a txn that completed before another was submitted must be
   visible to it (reads include its writes; writes precede its writes);
3. **atomicity (no fractured reads)**: if any of txn W's writes is visible to reader
   R, every W write on a key R read must be visible to R.

Any violation raises ``HistoryViolation`` naming the offending txns, like the
reference's seed-stamped failures.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..primitives.keys import Key


class HistoryViolation(AssertionError):
    pass


class Observation:
    """One client txn's visible behavior.

    Outcomes mirror the reference burn's client accounting
    (BurnTest.java:426-447, ListRequest Outcome.Kind):

    - ``ok``: acknowledged with its reads/writes — fully constrained;
    - ``lost``: resolved, but unknown whether it applied (response lost and no
      replica evidence) — unconstrained, its writes MAY appear;
    - ``invalidated``: durably invalidated — its writes must NEVER appear;
    - ``failed``: unexpected failure (burns treat any as fatal).
    """

    __slots__ = ("op_id", "submit_time", "complete_time", "reads", "writes",
                 "outcome")

    def __init__(self, op_id: int, submit_time: int):
        self.op_id = op_id
        self.submit_time = submit_time
        self.complete_time: Optional[int] = None
        self.reads: Dict[Key, Tuple] = {}       # key -> observed list
        self.writes: Dict[Key, object] = {}     # key -> unique appended value
        self.outcome: Optional[str] = None

    def complete(self, complete_time: int, reads: Dict[Key, Tuple],
                 writes: Dict[Key, object]) -> None:
        self.complete_time = complete_time
        self.reads = reads
        self.writes = writes
        self.outcome = "ok"

    def fail(self, complete_time: int) -> None:
        self.complete_time = complete_time
        self.outcome = "failed"

    def lost(self, complete_time: int) -> None:
        self.complete_time = complete_time
        self.outcome = "lost"

    def invalidated(self, complete_time: int, writes: Dict[Key, object]) -> None:
        self.complete_time = complete_time
        self.writes = writes
        self.outcome = "invalidated"

    @property
    def failed(self) -> bool:
        return self.outcome == "failed"


class StrictSerializabilityVerifier:
    def __init__(self):
        self.observations: List[Observation] = []
        self._next_op = 0

    def begin(self, submit_time: int) -> Observation:
        obs = Observation(self._next_op, submit_time)
        self._next_op += 1
        self.observations.append(obs)
        return obs

    # ------------------------------------------------------------------
    def verify(self, final_state: Optional[Dict[Key, Tuple]] = None) -> None:
        done = [o for o in self.observations if o.outcome == "ok"]
        self._check_response_accounting()
        orders = self._check_prefix_consistency(done, final_state)
        # value -> position inverse index, shared by the three order-sensitive
        # checks so position semantics live in exactly one place
        pos = {key: {v: i for i, v in enumerate(order)}
               for key, order in orders.items()}
        self._check_real_time(done, pos)
        self._check_atomicity(done, pos)
        self._check_invalidated_never_applied(done, final_state)
        self._check_serialization_graph(done, pos)

    # -- 5: serialization-graph acyclicity (the Elle core) --------------------
    def _check_serialization_graph(self, done: List["Observation"],
                                   pos: Dict[Key, Dict[object, int]]) -> None:
        """Build the full dependency graph over acked ops and reject cycles
        (the reference pairs its verifier with Elle, verify/ElleVerifier.java;
        this is Elle's list-append core):

        - ww: per-key version order (the unique-value list positions);
        - wr: a read observing version v depends on v's writer;
        - rw (anti-dependency): a read observing length L precedes the writer
          of position L (it did not see that write);
        - rt: A completed before B was submitted => A precedes B.

        A cycle = the acked outcomes admit NO strict-serializable order, even
        when every per-key/per-pair check above passes."""
        writer_of: Dict[Tuple[Key, int], int] = {}
        op_index: Dict[int, Observation] = {o.op_id: o for o in done}
        for o in done:
            for key, value in o.writes.items():
                p = pos.get(key, {}).get(value)
                if p is not None:
                    writer_of[(key, p)] = o.op_id
        edges: Dict[int, set] = {o.op_id: set() for o in done}

        def add(a: int, b: int) -> None:
            if a != b and a in edges and b in op_index:
                edges[a].add(b)

        # ww: successive versions of a key
        for (key, p), writer in writer_of.items():
            nxt = writer_of.get((key, p + 1))
            if nxt is not None:
                add(writer, nxt)
        for o in done:
            for key, lst in o.reads.items():
                # wr: the last version this read observed precedes it
                if lst:
                    w = writer_of.get((key, len(lst) - 1))
                    if w is not None:
                        add(w, o.op_id)
                # rw: the first version it did NOT observe follows it
                w = writer_of.get((key, len(lst)))
                if w is not None:
                    add(o.op_id, w)
        # rt: real-time precedence in O(n) edges via a virtual submit chain:
        # v_j precedes op_j and v_{j+1}; a -> v_j for the first j submitted
        # after a's completion.  Paths a -> v_j -> ... -> op_k encode exactly
        # 'a completed before op_k was submitted' with no spurious op-op
        # constraints (the dense O(n^2) pair relation blew up verify time)
        from bisect import bisect_right
        ordered = sorted(done, key=lambda o: o.submit_time)
        submits = [o.submit_time for o in ordered]
        for j, o in enumerate(ordered):
            vj = ("rt", j)
            edges[vj] = set()
            edges[vj].add(o.op_id)
            if j + 1 < len(ordered):
                edges[vj].add(("rt", j + 1))
        for a in done:
            if a.complete_time is None:
                continue
            j = bisect_right(submits, a.complete_time)
            if j < len(ordered):
                edges[a.op_id].add(("rt", j))
        # cycle detection (iterative three-color DFS)
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {op: WHITE for op in edges}
        for root in edges:
            if color[root] != WHITE:
                continue
            stack = [(root, iter(edges[root]))]
            color[root] = GRAY
            path = [root]
            while stack:
                node, it = stack[-1]
                advanced = False
                for nxt in it:
                    if color[nxt] == GRAY:
                        i = path.index(nxt)
                        raise HistoryViolation(
                            f"serialization-graph cycle: {path[i:] + [nxt]} — "
                            f"acked outcomes admit no strict-serializable order")
                    if color[nxt] == WHITE:
                        color[nxt] = GRAY
                        stack.append((nxt, iter(edges[nxt])))
                        path.append(nxt)
                        advanced = True
                        break
                if not advanced:
                    color[node] = BLACK
                    stack.pop()
                    path.pop()

    # -- 0: every op resolved ------------------------------------------------
    def _check_response_accounting(self) -> None:
        unresolved = [o.op_id for o in self.observations if o.outcome is None]
        if unresolved:
            raise HistoryViolation(f"ops never resolved: {unresolved}")

    # -- 4: invalidated writes never visible ---------------------------------
    def _check_invalidated_never_applied(self, done: List["Observation"],
                                         final_state: Optional[Dict[Key, Tuple]]) -> None:
        visible = set()
        for o in done:
            for lst in o.reads.values():
                visible.update(lst)
        if final_state:
            for lst in final_state.values():
                visible.update(lst)
        for o in self.observations:
            if o.outcome != "invalidated":
                continue
            for key, value in o.writes.items():
                if value in visible:
                    raise HistoryViolation(
                        f"op {o.op_id} was durably invalidated but its write "
                        f"{value!r} to {key} is visible")

    # -- 1: per-key prefix order --------------------------------------------
    def _check_prefix_consistency(self, done: List[Observation],
                                  final_state: Optional[Dict[Key, Tuple]]
                                  ) -> Dict[Key, Tuple]:
        by_key: Dict[Key, List[Tuple[int, Tuple]]] = {}
        for o in done:
            for key, lst in o.reads.items():
                by_key.setdefault(key, []).append((o.op_id, lst))
        if final_state:
            for key, lst in final_state.items():
                by_key.setdefault(key, []).append((-1, lst))
        orders: Dict[Key, Tuple] = {}
        for key, views in by_key.items():
            views.sort(key=lambda v: len(v[1]))
            for (op_a, a), (op_b, b) in zip(views, views[1:]):
                if a != b[:len(a)]:
                    raise HistoryViolation(
                        f"key {key}: op {op_a} observed {a} which is not a prefix of "
                        f"op {op_b}'s {b}")
            orders[key] = views[-1][1] if views else ()
        return orders

    # -- 2: real-time --------------------------------------------------------
    def _check_real_time(self, done: List[Observation],
                         pos: Dict[Key, Dict[object, int]]) -> None:
        """O(n log n) sweep replacing the dense pair relation (the nested loop
        bounded burn scale before the protocol did).

        Ops are processed in submit order; ops completed at-or-before the
        current submit time are folded into per-key aggregates first.  Because
        prefix consistency has already been verified, ``b.reads[key]`` IS
        ``orders[key][:L]``, so "a's write visible to b" reduces to
        ``pos[a's value] < L`` — the aggregate only needs, per key, the max
        write position among completed ops (with its writer, for the error
        message) plus any completed writes never observed in the order at all
        (visible to no one — any later reader of the key violates)."""
        # per-key aggregates over completed ops:
        #   top2: the two highest-ordered completed writes BY DISTINCT OPS as
        #         (position, writer_op, value, complete_time) — two entries so
        #         a check for op b can exclude b itself (an op's own write may
        #         already be absorbed when submit/complete times tie), and the
        #         max over all OTHER ops is still exactly available;
        #   unordered: [(writer_op, value, complete_time)] completed writes
        #              absent from the observed order (visible to nobody).
        top2: Dict[Key, List[Tuple[int, int, object, int]]] = {}
        unordered: Dict[Key, List[Tuple[int, object, int]]] = {}

        def absorb(a: Observation) -> None:
            for key, value in a.writes.items():
                p = pos.get(key, {}).get(value)
                if p is None:
                    unordered.setdefault(key, []).append(
                        (a.op_id, value, a.complete_time))
                else:
                    entry = (p, a.op_id, value, a.complete_time)
                    best = top2.setdefault(key, [])
                    best.append(entry)
                    best.sort(reverse=True)
                    del best[2:]

        def max_excluding(key: Key, op_id: int):
            for entry in top2.get(key, ()):
                if entry[1] != op_id:
                    return entry
            return None

        by_submit = sorted(done, key=lambda o: o.submit_time)
        by_complete = sorted((o for o in done if o.complete_time is not None),
                             key=lambda o: o.complete_time)
        i = 0
        for b in by_submit:
            while i < len(by_complete) and \
                    by_complete[i].complete_time <= b.submit_time:
                absorb(by_complete[i])
                i += 1
            for key, lst in b.reads.items():
                ln = len(lst)
                agg = max_excluding(key, b.op_id)
                if agg is not None and agg[0] >= ln:
                    p, writer, value, ct = agg
                    raise HistoryViolation(
                        f"real-time violation: op {writer} wrote {value!r} to "
                        f"{key} and completed at {ct}, but op "
                        f"{b.op_id} (submitted {b.submit_time}) read {lst}")
                for writer, value, ct in unordered.get(key, ()):
                    if writer != b.op_id:
                        raise HistoryViolation(
                            f"real-time violation: op {writer} wrote {value!r} "
                            f"to {key} and completed at {ct}, but op "
                            f"{b.op_id} (submitted {b.submit_time}) read {lst}")
            for key, value in b.writes.items():
                pb = pos.get(key, {}).get(value)
                agg = max_excluding(key, b.op_id)
                if pb is not None and agg is not None and agg[0] > pb:
                    p, writer, wvalue, ct = agg
                    raise HistoryViolation(
                        f"real-time violation: op {writer}'s write {wvalue!r} "
                        f"ordered after op {b.op_id}'s {value!r} on {key} "
                        f"despite completing before it was submitted")

    # -- 3: atomicity --------------------------------------------------------
    def _check_atomicity(self, done: List[Observation],
                         pos: Dict[Key, Dict[object, int]]) -> None:
        """A fractured read needs a reader observing ≥2 of one writer's keys
        with mixed visibility, so only (key, key) pairs matter.  With prefix
        consistency already established, W's write at position p on key k is
        visible to a reader iff its read length on k exceeds p (never-ordered
        writes get an infinite position: visible to nobody).  Index every
        writer's key pairs as (p_i, p_j) points sorted by p_i with a running
        max of p_j; a reader pair (L_i, L_j) fractures iff some point has
        p_i < L_i (visible on k_i) and p_j >= L_j (invisible on k_j) — i.e.
        the prefix-max of p_j over p_i < L_i reaches L_j.  Replaces the
        reader×writers scan that went quadratic under contention."""
        INF = float("inf")
        # (k_i, k_j) -> [(p_i, p_j, writer_op)], both directions
        pairs: Dict[Tuple[Key, Key], List[Tuple[float, float, int]]] = {}
        for o in done:
            if len(o.writes) < 2:
                continue
            wkeys = sorted(o.writes, key=repr)
            wpos = {k: pos.get(k, {}).get(o.writes[k], INF) for k in wkeys}
            for idx, ki in enumerate(wkeys):
                for kj in wkeys[idx + 1:]:
                    pairs.setdefault((ki, kj), []).append(
                        (wpos[ki], wpos[kj], o.op_id))
                    pairs.setdefault((kj, ki), []).append(
                        (wpos[kj], wpos[ki], o.op_id))
        index: Dict[Tuple[Key, Key], Tuple[List[float], List[float]]] = {}
        for pk, pts in pairs.items():
            pts.sort()
            prefix_max: List[float] = []
            best = -1.0
            for _, pj, _ in pts:
                best = max(best, pj)
                prefix_max.append(best)
            index[pk] = ([pi for pi, _, _ in pts], prefix_max)
        from bisect import bisect_left
        for reader in done:
            if len(reader.reads) < 2:
                continue
            rkeys = list(reader.reads)
            for idx, ki in enumerate(rkeys):
                li = len(reader.reads[ki])
                for kj in rkeys[idx + 1:]:
                    lj = len(reader.reads[kj])
                    for (ka, la), (kb, lb) in (((ki, li), (kj, lj)),
                                               ((kj, lj), (ki, li))):
                        entry = index.get((ka, kb))
                        if entry is None:
                            continue
                        pis, pmax = entry
                        n = bisect_left(pis, la)  # points with p_i < L_a
                        if n == 0 or pmax[n - 1] < lb:
                            continue
                        # aggregate hit: enumerate culprits, excluding self
                        for pi, pj, writer in pairs[(ka, kb)]:
                            if writer != reader.op_id and pi < la and pj >= lb:
                                raise HistoryViolation(
                                    f"fractured read: op {reader.op_id} sees op "
                                    f"{writer}'s write on {ka} (read len {la} > "
                                    f"pos {pi}) but not on {kb} (read len {lb} "
                                    f"<= pos {pj})")
