"""Full message tracing + trace reconciliation.

Capability parity with ``accord.impl.basic.Trace`` and the burn's
``ReconcilingLogger`` (Cluster.java:237-264, burn/ReconcilingLogger.java):
every network event — SEND (with the link action taken: DELIVER / DROP /
FAILURE / DELIVER_WITH_FAILURE), reply routing (RPLY_*), and the actual
delivery (RECV / RECV_RPLY) — is recorded with a logical sequence number.
``reconcile`` then runs the same seed twice and diffs the COMPLETE traces,
not summary scalars: any nondeterminism in the simulation (iteration order,
uncontrolled randomness, wall-clock leakage) surfaces as the first
divergent event.
"""
from __future__ import annotations

from collections import deque
from typing import Optional, Tuple


def _brief(message) -> str:
    """A compact, deterministic description: class + primary txn id."""
    name = type(message).__name__
    tid = getattr(message, "txn_id", None)
    return f"{name}({tid})" if tid is not None else name


class Trace:
    """Recorder for one run; install via ``cluster.tracer = trace.hook`` —
    the cluster calls the hook for SEND/RPLY routing decisions and RECV
    deliveries.

    ``keep_last``: optional ring-buffer bound.  Reconciliation needs the FULL
    event list (both runs diff byte-for-byte), but a long burn that only
    wants the trace for forensics (the flight recorder's message timeline,
    stall postmortems) can cap memory at the last N events — a 1000-op
    hostile seed emits hundreds of thousands of events, and
    ``ACCORD_LONG_BURNS`` sweeps hold several runs' traces at once.  Dropped
    events are counted in ``dropped``; sequence numbers stay absolute, so a
    truncated trace is still diffable against the same-seed tail."""

    __slots__ = ("events", "_seq", "dropped", "_keep_last")

    def __init__(self, keep_last: Optional[int] = None):
        if keep_last is not None and keep_last < 0:
            raise ValueError(f"keep_last must be >= 0, got {keep_last}")
        # `is not None`: keep_last=0 means "count events, keep none", not
        # "unbounded" (deque(maxlen=0) implements exactly that)
        self.events = deque(maxlen=keep_last) if keep_last is not None else []
        self._keep_last = keep_last
        self._seq = 0
        self.dropped = 0

    def hook(self, event: str, frm: int, to: int, msg_id, message,
             now_micros: int) -> None:
        if self._keep_last is not None \
                and len(self.events) == self._keep_last:
            self.dropped += 1
        self.events.append((self._seq, now_micros, event, frm, to, msg_id,
                            _brief(message)))
        self._seq += 1

    def __len__(self):
        return len(self.events)


def diff_traces(a: Trace, b: Trace) -> Optional[str]:
    """None if identical; else a report of the first divergence with
    surrounding context.  Ring-bounded traces are normalised to lists first
    (a deque has no slicing); their absolute sequence numbers make truncated
    tails directly comparable."""
    ea, eb = list(a.events), list(b.events)
    n = min(len(ea), len(eb))
    for i in range(n):
        if ea[i] != eb[i]:
            lo = max(0, i - 3)
            ctx_a = "\n".join(f"  a[{j}]: {ea[j]}" for j in range(lo, min(i + 2, len(ea))))
            ctx_b = "\n".join(f"  b[{j}]: {eb[j]}" for j in range(lo, min(i + 2, len(eb))))
            return (f"traces diverge at event {i}:\n{ctx_a}\n  --- vs ---\n{ctx_b}")
    if len(ea) != len(eb):
        tail = (ea if len(ea) > n else eb)[n:n + 3]
        return (f"trace lengths differ: {len(ea)} vs {len(eb)}; "
                f"first extra events: {tail}")
    return None
