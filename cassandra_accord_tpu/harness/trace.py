"""Full message tracing + trace reconciliation.

Capability parity with ``accord.impl.basic.Trace`` and the burn's
``ReconcilingLogger`` (Cluster.java:237-264, burn/ReconcilingLogger.java):
every network event — SEND (with the link action taken: DELIVER / DROP /
FAILURE / DELIVER_WITH_FAILURE), reply routing (RPLY_*), and the actual
delivery (RECV / RECV_RPLY) — is recorded with a logical sequence number.
``reconcile`` then runs the same seed twice and diffs the COMPLETE traces,
not summary scalars: any nondeterminism in the simulation (iteration order,
uncontrolled randomness, wall-clock leakage) surfaces as the first
divergent event.
"""
from __future__ import annotations

from typing import List, Optional, Tuple


def _brief(message) -> str:
    """A compact, deterministic description: class + primary txn id."""
    name = type(message).__name__
    tid = getattr(message, "txn_id", None)
    return f"{name}({tid})" if tid is not None else name


class Trace:
    """Recorder for one run; install via ``cluster.tracer = trace.hook`` —
    the cluster calls the hook for SEND/RPLY routing decisions and RECV
    deliveries."""

    __slots__ = ("events", "_seq")

    def __init__(self):
        self.events: List[Tuple] = []
        self._seq = 0

    def hook(self, event: str, frm: int, to: int, msg_id, message,
             now_micros: int) -> None:
        self.events.append((self._seq, now_micros, event, frm, to, msg_id,
                            _brief(message)))
        self._seq += 1

    def __len__(self):
        return len(self.events)


def diff_traces(a: Trace, b: Trace) -> Optional[str]:
    """None if identical; else a report of the first divergence with
    surrounding context."""
    n = min(len(a.events), len(b.events))
    for i in range(n):
        if a.events[i] != b.events[i]:
            lo = max(0, i - 3)
            ctx_a = "\n".join(f"  a[{j}]: {a.events[j]}" for j in range(lo, min(i + 2, len(a.events))))
            ctx_b = "\n".join(f"  b[{j}]: {b.events[j]}" for j in range(lo, min(i + 2, len(b.events))))
            return (f"traces diverge at event {i}:\n{ctx_a}\n  --- vs ---\n{ctx_b}")
    if len(a.events) != len(b.events):
        i = n
        tail = (a if len(a.events) > n else b).events[n:n + 3]
        return (f"trace lengths differ: {len(a.events)} vs {len(b.events)}; "
                f"first extra events: {tail}")
    return None
