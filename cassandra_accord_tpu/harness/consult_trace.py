"""Consult-stream recording + scaled replay: the trace-driven data-plane bench.

The honest end-to-end protocol bench is Amdahl-capped by the Python control
plane, and at burn-scale index sizes the resolver cost model correctly keeps
every consult on the walk/host tiers — so the device tier never serves live
protocol traffic there (BENCH_r03 `resolver_device_consults: 0`).  This module
closes that gap with PROTOCOL-SEMANTICS traffic at data-plane scale:

1. **Record** — ``ConsultRecorder`` wraps every store's ``DepsResolver``
   during a real contended burn and captures the COMPLETE stream the protocol
   drove through it: registrations (witness/upgrade), prunes, durability-gate
   advances, delivery-window prefetches, and every query with its exact
   arguments.  This is the workload of ``SafeCommandStore.mapReduceActive`` /
   ``MaxConflicts`` (SafeCommandStore.java:292, cfk/CommandsForKey.java:925)
   as the protocol actually issued it — not a synthetic array shape.

2. **Replay at scale** — ``replay_stream`` re-drives N identity-rebased
   copies of that stream, interleaved event-by-event, into ONE fresh resolver
   (T multiplies by N: the store of a node serving N× the key universe at the
   recorded per-key contention).  Each copy's txn ids are hlc-offset and its
   keys value-offset, so copies stay disjoint and every per-copy answer keeps
   the recorded protocol semantics (elision gates, window coalescing,
   sequential exactness) — while the index grows to the regime the MXU join
   was built for (BASELINE configs 3-5).

3. **Tier comparison** — the same stream replays under each execution tier
   (``walk`` = the scalar cfk oracle, ``host`` = vectorized numpy,
   ``device`` = the fused consult through the PERSISTENT batched service
   (device_service/: incremental double-buffered index refresh + ragged
   batching windows — the r05 one-shot path re-uploaded the whole T×K index
   per consult and wedged at event 36), ``auto`` = the production cost
   model), yielding queries/s and commits-equivalent/s (total commits the
   recorded protocol achieved per consult workload, scaled by copies).  A
   sampled parity check asserts the tiers agree answer-for-answer.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from ..impl.resolver import DepsResolver, QuerySpec
from ..primitives.keys import IntKey, Range, RoutingKey
from ..primitives.timestamp import Domain, Timestamp, TxnId
from ..utils.invariants import check_state


class ConsultRecorder:
    """Captures one store's resolver stream (attach via ``wrap_store``)."""

    def __init__(self):
        self.streams: Dict[object, List[tuple]] = {}
        self.peak_live: Dict[object, int] = {}
        self.commits: Dict[object, int] = {}

    def wrap_store(self, store) -> None:
        store.resolver = _RecordingResolver(store.resolver, self, store)

    def unit_stream(self) -> List[tuple]:
        """The largest recorded per-store stream (the replay unit)."""
        check_state(bool(self.streams), "nothing recorded")
        key = max(self.streams, key=lambda k: len(self.streams[k]))
        return self.streams[key]

    def unit_peak_live(self) -> int:
        key = max(self.streams, key=lambda k: len(self.streams[k]))
        return max(1, self.peak_live.get(key, 1))

    def unit_commits(self) -> int:
        key = max(self.streams, key=lambda k: len(self.streams[k]))
        return self.commits.get(key, 0)


class _RecordingResolver(DepsResolver):
    """Delegating wrapper: records the full mutation+query stream, plus the
    store's durability-gate state whenever its generation advances (the
    elision soundness gate is part of the query semantics)."""

    def __init__(self, inner: DepsResolver, rec: ConsultRecorder, store):
        self.inner = inner
        self.rec = rec
        self.store = store
        self.events = rec.streams.setdefault(store, [])
        self._gen_seen = -1
        self._live = 0
        self._committed_seen = set()

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def _probe_durable(self) -> None:
        gen = getattr(self.store, "durable_gen", None)
        if gen is None or gen == self._gen_seen:
            return
        self._gen_seen = gen
        db = self.store.durable_before
        snap = {}
        for rk in self.store.cfks:
            e = db.entry(rk)
            if e is not None and e.majority_before is not None:
                snap[rk] = e.majority_before
        self.events.append(("durable", snap))

    # -- mutations -----------------------------------------------------------
    def register(self, txn_id, status, execute_at, keys) -> None:
        self._probe_durable()
        from ..local.cfk import InternalStatus as IS
        self.events.append(("reg", txn_id, int(status), execute_at, tuple(keys)))
        if int(status) >= int(IS.COMMITTED) and txn_id not in self._committed_seen:
            self._committed_seen.add(txn_id)
            self.rec.commits[self.store] = self.rec.commits.get(self.store, 0) + 1
        self.inner.register(txn_id, status, execute_at, keys)
        live = getattr(self.inner, "indexed_count", lambda: None)()
        if live is None:
            self._live += 1
            live = self._live
        self.rec.peak_live[self.store] = max(
            self.rec.peak_live.get(self.store, 0), live)

    def on_pruned(self, key, txn_ids) -> None:
        self._probe_durable()
        ids = tuple(txn_ids)
        if ids:
            self.events.append(("prune", key, ids))
        self.inner.on_pruned(key, ids)

    def mark_durable(self, txn_id) -> None:
        # the per-txn UNIVERSAL elision gate is part of the query semantics:
        # record it (base class defines this, so __getattr__ never forwards)
        self.events.append(("mark_durable", txn_id))
        self.inner.mark_durable(txn_id)

    # -- batching ------------------------------------------------------------
    def prefetch(self, specs) -> None:
        self._probe_durable()
        self.events.append(("prefetch", tuple(
            (s.op, s.by, tuple(s.keys), s.before) for s in specs)))
        self.inner.prefetch(specs)

    def end_batch(self) -> None:
        self.events.append(("end",))
        self.inner.end_batch()

    # -- frontier mirror (not replayed; passthrough) --------------------------
    def is_indexed(self, txn_id) -> bool:
        # explicit delegation: the base class defines this (returns False),
        # so __getattr__ would never forward it — frontier_exec under a
        # recorder would silently degrade to inline execution
        return self.inner.is_indexed(txn_id)

    def register_waiting(self, waiter, deps) -> None:
        self.inner.register_waiting(waiter, deps)

    def remove_waiting(self, waiter, dep) -> None:
        self.inner.remove_waiting(waiter, dep)

    def note_terminal(self, txn_id, invalidated: bool = False) -> None:
        self.inner.note_terminal(txn_id, invalidated=invalidated)

    # -- queries -------------------------------------------------------------
    def key_conflicts(self, by, keys, before):
        self._probe_durable()
        self.events.append(("kc", by, tuple(keys), before))
        return self.inner.key_conflicts(by, keys, before)

    def range_conflicts(self, by, rng, before):
        self._probe_durable()
        self.events.append(("rc", by, rng, before))
        return self.inner.range_conflicts(by, rng, before)

    def max_conflict_keys(self, keys):
        self._probe_durable()
        self.events.append(("mc", tuple(keys)))
        return self.inner.max_conflict_keys(keys)

    def max_conflict_range(self, rng):
        self._probe_durable()
        self.events.append(("mcr", rng))
        return self.inner.max_conflict_range(rng)


# ---------------------------------------------------------------------------
# replay
# ---------------------------------------------------------------------------

class _ReplayEntry:
    __slots__ = ("majority_before",)

    def __init__(self, bound):
        self.majority_before = bound


class _ReplayDurable:
    __slots__ = ("by_key",)

    def __init__(self):
        self.by_key: Dict[RoutingKey, object] = {}

    def entry(self, rk):
        b = self.by_key.get(rk)
        return None if b is None else _ReplayEntry(b)


class ReplayStore:
    """Minimal CommandStore stand-in: exactly the surface the resolvers read
    (cfk mirrors for the walk oracle, the durability gate, and nothing else)."""

    def __init__(self):
        self.cfks: Dict[RoutingKey, object] = {}
        self.durable_before = _ReplayDurable()
        self.durable_gen = 0

    def cfk(self, rk):
        from ..local.cfk import CommandsForKey
        c = self.cfks.get(rk)
        if c is None:
            c = self.cfks[rk] = CommandsForKey(rk)
        return c


class _Rebase:
    """Identity rebasing for one stream copy: txn ids shift by an hlc offset,
    IntKeys by a value stride — copies are disjoint in both spaces while every
    intra-copy order relation is preserved."""

    def __init__(self, copy: int, hlc_stride: int, key_stride: int):
        self.hlc_off = copy * hlc_stride
        self.key_off = copy * key_stride
        self._keys: Dict[RoutingKey, RoutingKey] = {}

    def txn(self, t: Optional[TxnId]):
        if t is None:
            return None
        return TxnId(t.epoch, t.hlc + self.hlc_off, t.node, kind=t.kind,
                     domain=t.domain, extra_flags=t.flags)

    def ts(self, t: Optional[Timestamp]):
        if t is None:
            return None
        if isinstance(t, TxnId):
            return self.txn(t)
        return Timestamp(t.epoch, t.hlc + self.hlc_off, t.node, t.flags)

    def key(self, rk: RoutingKey) -> RoutingKey:
        out = self._keys.get(rk)
        if out is None:
            if isinstance(rk, IntKey):
                out = type(rk)(rk.value + self.key_off, rk.prefix)
            else:
                out = rk    # sentinels: span every copy (still exact, wider)
            self._keys[rk] = out
        return out

    def rng(self, r: Range) -> Range:
        return Range(self.key(r.start), self.key(r.end))


def rebase_stream(events: List[tuple], copy: int, hlc_stride: int,
                  key_stride: int) -> List[tuple]:
    rb = _Rebase(copy, hlc_stride, key_stride)
    out: List[tuple] = []
    for ev in events:
        op = ev[0]
        if op == "reg":
            _, tid, st, ea, keys = ev
            out.append(("reg", rb.txn(tid), st, rb.ts(ea),
                        tuple(rb.key(k) for k in keys)))
        elif op == "prune":
            _, key, ids = ev
            out.append(("prune", rb.key(key), tuple(rb.txn(t) for t in ids)))
        elif op == "durable":
            out.append(("durable", {rb.key(k): rb.txn(b)
                                    for k, b in ev[1].items()}))
        elif op == "mark_durable":
            out.append(("mark_durable", rb.txn(ev[1])))
        elif op == "prefetch":
            out.append(("prefetch", tuple(
                (o, rb.txn(by), tuple(rb.key(k) for k in keys), rb.ts(before))
                for o, by, keys, before in ev[1])))
        elif op == "kc":
            _, by, keys, before = ev
            out.append(("kc", rb.txn(by), tuple(rb.key(k) for k in keys),
                        rb.ts(before)))
        elif op == "rc":
            _, by, r, before = ev
            out.append(("rc", rb.txn(by), rb.rng(r), rb.ts(before)))
        elif op == "mc":
            out.append(("mc", tuple(rb.key(k) for k in ev[1])))
        elif op == "mcr":
            out.append(("mcr", rb.rng(ev[1])))
        else:
            out.append(ev)
    return out


def interleave(streams: List[List[tuple]]) -> List[tuple]:
    """Window-aligned merge.  Copies advance in lockstep so the live index
    holds every copy's in-flight set simultaneously (T multiplies) — but at
    WINDOW granularity, not event granularity: a naive round-robin would let
    copy B's ``end_batch`` wipe copy A's prefetched window mid-flight.  The
    i-th delivery windows of all copies fuse into ONE window: their prefetch
    specs concatenate into a single batched consult (exactly the
    across-stores batching the MXU join wants — B multiplies with copies),
    their bodies run back to back, then one ``end``.  Inter-window events
    keep per-copy order and are concatenated per segment."""
    # split each stream into segments: [(pre, specs_or_None, body), ...]
    split: List[List[Tuple[list, Optional[tuple], list]]] = []
    for s in streams:
        segs = []
        pre: list = []
        specs = None
        body: list = []
        for ev in s:
            if ev[0] == "prefetch":
                if specs is not None:        # unterminated window: flush
                    segs.append((pre, specs, body))
                    pre, body = [], []
                specs = ev[1]
            elif ev[0] == "end":
                segs.append((pre, specs, body))
                pre, specs, body = [], None, []
            elif specs is None:
                pre.append(ev)
            else:
                body.append(ev)
        if pre or body or specs is not None:
            segs.append((pre, specs, body))
        split.append(segs)
    out: List[tuple] = []
    n = max(len(s) for s in split)
    for i in range(n):
        fused: list = []
        bodies: list = []
        for segs in split:
            if i >= len(segs):
                continue
            pre, specs, body = segs[i]
            out.extend(pre)
            if specs is not None:
                fused.extend(specs)
            bodies.extend(body)
        if fused:
            out.append(("prefetch", tuple(fused)))
        out.extend(bodies)
        if fused:
            out.append(("end",))
    return out


_QUERY_OPS = ("kc", "rc", "mc", "mcr", "prefetch", "end")


def replay_stream(events: List[tuple], tier: str,
                  txn_capacity: int, key_capacity: int,
                  parity_oracle: bool = False,
                  parity_sample: int = 0,
                  query_sample: int = 1,
                  max_seconds: Optional[float] = None) -> dict:
    """Drive one merged stream through a fresh resolver under ``tier``.

    Returns wall-clock split into mutation and query time, query count, and
    (with ``parity_sample`` > 0) asserts every Nth query against the cfk walk
    oracle built on the same shell store.

    ``query_sample`` > 1 answers only every Nth query (mutations still run in
    full) and extrapolates the reported rate — the budget valve for the
    scalar walk tier at data-plane scale, where a full replay of every query
    is hours of pure Python (VERDICT r04: the bench must never full-replay
    the walk at T>=4k).  Queries have no side effects on the index, so the
    skipped ones change nothing downstream; ``queries`` still counts them
    all and ``sampled_queries`` records how many actually ran."""
    from ..local.cfk import InternalStatus as IS
    from ..impl.resolver import CpuDepsResolver
    from ..impl.tpu_resolver import TpuDepsResolver

    store = ReplayStore()
    if tier == "walk":
        resolver: DepsResolver = CpuDepsResolver(store)
    else:
        resolver = TpuDepsResolver(store, txn_capacity=txn_capacity,
                                   key_capacity=key_capacity)
        resolver.tier = tier
    oracle = CpuDepsResolver(store) if parity_sample else None

    q_time = 0.0
    m_time = 0.0
    queries = 0
    answered = 0
    parity_checked = 0
    reg_keys: Dict[TxnId, tuple] = {}   # txn -> indexed keys (mark_durable)
    deadline = time.perf_counter() + max_seconds if max_seconds else None
    truncated_at = None
    for i, ev in enumerate(events):
        op = ev[0]
        if deadline is not None and time.perf_counter() > deadline:
            # budget valve (device tier over a tunnel: per-launch latency can
            # make a full replay hours): prefix replay, honest per-query
            # rates on what ran, labeled truncated
            truncated_at = i
            break
        if op in ("kc", "rc", "mc", "mcr"):
            queries += 1
            if query_sample > 1 and queries % query_sample != 0:
                continue
            answered += 1
        t0 = time.perf_counter()
        if op == "reg":
            _, tid, st, ea, keys = ev
            status = IS(st)
            indexed = tuple(k for k in keys if store.cfk(k).update(tid, status, ea))
            if indexed:
                reg_keys[tid] = indexed
                resolver.register(tid, status, ea, indexed)
            m_time += time.perf_counter() - t0
        elif op == "prune":
            _, key, ids = ev
            cfk = store.cfks.get(key)
            if cfk is not None:
                idset = set(ids)
                pruned = cfk._prune(lambda info: info.txn_id in idset)
                if pruned:
                    resolver.on_pruned(key, pruned)
            m_time += time.perf_counter() - t0
        elif op == "durable":
            store.durable_before.by_key.update(ev[1])
            store.durable_gen += 1
            m_time += time.perf_counter() - t0
        elif op == "mark_durable":
            tid = ev[1]
            for k in reg_keys.get(tid, ()):
                cfk = store.cfks.get(k)
                if cfk is not None:
                    cfk.mark_durable(tid)
            resolver.mark_durable(tid)
            if oracle is not None:
                oracle.mark_durable(tid)
            m_time += time.perf_counter() - t0
        elif op == "prefetch":
            specs = [QuerySpec(o, by, keys, before)
                     for o, by, keys, before in ev[1]]
            resolver.prefetch(specs)
            q_time += time.perf_counter() - t0
        elif op == "end":
            resolver.end_batch()
            q_time += time.perf_counter() - t0
        elif op == "kc":
            _, by, keys, before = ev
            ans = resolver.key_conflicts(by, list(keys), before)
            q_time += time.perf_counter() - t0
            if oracle is not None and answered % parity_sample == 0:
                expect = oracle.key_conflicts(by, list(keys), before)
                check_state(sorted(ans) == sorted(expect),
                            "replay parity violation (kc) at event %s", i)
                parity_checked += 1
        elif op == "rc":
            _, by, r, before = ev
            ans = resolver.range_conflicts(by, r, before)
            q_time += time.perf_counter() - t0
        elif op == "mc":
            ans = resolver.max_conflict_keys(list(ev[1]))
            q_time += time.perf_counter() - t0
            if oracle is not None and answered % parity_sample == 0:
                expect = oracle.max_conflict_keys(list(ev[1]))
                check_state(ans == expect,
                            "replay parity violation (mc) at event %s", i)
                parity_checked += 1
        elif op == "mcr":
            ans = resolver.max_conflict_range(ev[1])
            q_time += time.perf_counter() - t0

    out = {"tier": tier, "queries": queries,
           "query_seconds": round(q_time, 4),
           "mutation_seconds": round(m_time, 4),
           "queries_per_sec": round(answered / q_time, 1) if q_time else None,
           "parity_checked": parity_checked}
    if query_sample > 1:
        out["sampled_queries"] = answered
        out["query_sample"] = query_sample
    if truncated_at is not None:
        out["truncated_at_event"] = truncated_at
        out["events_total"] = len(events)
    for tele in ("walk_consults", "host_consults", "device_consults",
                 "prefetch_hits", "prefetch_patched", "prefetch_misses",
                 "service_submitted", "service_batches"):
        v = getattr(resolver, tele, None)
        if v:
            out[tele] = v
    svc = getattr(resolver, "_service_obj", None)
    if svc is not None:
        # the persistent-service health block: batching behavior, refresh
        # traffic, and the bounded-compilation ledger (jit_shapes) — the
        # replay used to wedge here on whole-index re-uploads (r05)
        out["service"] = svc.stats()
    idx = getattr(resolver, "indexed_count", None)
    if idx is not None:
        out["final_indexed"] = idx()
    return out


def record_burn(seed: int = 7, ops: int = 1200, **kw) -> ConsultRecorder:
    """Run a contended burn with every store's resolver wrapped; returns the
    recorder (bench entry point)."""
    from .burn import run_burn
    rec = ConsultRecorder()
    kw.setdefault("resolver", "tpu")
    run_burn(seed=seed, ops=ops, consult_recorder=rec, **kw)
    return rec


def max_hlc_and_key(events: List[tuple]) -> Tuple[int, int, int]:
    """(max hlc, max IntKey value, distinct key count) — rebasing strides and
    capacity sizing."""
    mh, mk = 0, 0
    distinct = set()

    def see_ts(t):
        nonlocal mh
        if t is not None:
            mh = max(mh, t.hlc)

    def see_key(k):
        nonlocal mk
        if isinstance(k, IntKey):
            mk = max(mk, k.value)
        distinct.add(k)

    for ev in events:
        op = ev[0]
        if op == "reg":
            see_ts(ev[1]); see_ts(ev[3])
            for k in ev[4]:
                see_key(k)
        elif op == "prune":
            see_key(ev[1])
            for t in ev[2]:
                see_ts(t)
        elif op == "durable":
            for k, b in ev[1].items():
                see_key(k); see_ts(b)
        elif op == "prefetch":
            for o, by, keys, before in ev[1]:
                see_ts(by); see_ts(before)
                for k in keys:
                    see_key(k)
        elif op == "kc":
            see_ts(ev[1]); see_ts(ev[3])
            for k in ev[2]:
                see_key(k)
        elif op == "rc":
            see_ts(ev[1]); see_ts(ev[3])
            see_key(ev[2].start); see_key(ev[2].end)
        elif op == "mc":
            for k in ev[1]:
                see_key(k)
        elif op == "mcr":
            see_key(ev[1].start); see_key(ev[1].end)
    return mh, mk, len(distinct)


def scaled_replay(rec: ConsultRecorder, t_target: int, tiers: List[str],
                  parity_sample: int = 0,
                  walk_query_sample: int = 1,
                  walk_sample_target: Optional[int] = None,
                  tier_max_seconds: Optional[dict] = None) -> dict:
    """Replay enough interleaved copies of the recorded unit stream to grow
    the live index to ~``t_target``, under each tier."""
    unit = rec.unit_stream()
    peak = rec.unit_peak_live()
    copies = max(1, (t_target + peak - 1) // peak)
    mh, mk, n_keys = max_hlc_and_key(unit)
    hlc_stride = mh + 1_000_000
    key_stride = mk + 1_000
    merged = interleave([
        rebase_stream(unit, c, hlc_stride, key_stride) for c in range(copies)])
    t_cap = 1 << max(6, (copies * peak - 1).bit_length())
    k_cap = 1 << max(6, (copies * (n_keys + 1) - 1).bit_length())
    out = {"t_target": t_target, "copies": copies, "unit_events": len(unit),
           "unit_peak_live": peak, "merged_events": len(merged),
           "txn_capacity": t_cap, "key_capacity": k_cap,
           "commits_replayed": rec.unit_commits() * copies, "tiers": {}}
    if walk_sample_target:
        total_q = sum(1 for ev in merged if ev[0] in ("kc", "rc", "mc", "mcr"))
        walk_query_sample = max(walk_query_sample, total_q // walk_sample_target)
    for tier in tiers:
        r = replay_stream(merged, tier, t_cap, k_cap,
                          parity_sample=parity_sample,
                          query_sample=walk_query_sample
                          if tier == "walk" else 1,
                          max_seconds=(tier_max_seconds or {}).get(tier))
        # extrapolate sampled query time to the FULL query count before
        # forming commits-equiv (sampling answers 1/N of the queries; the
        # commit count is for all of them)
        q_full = r["query_seconds"]
        if r.get("query_sample", 1) > 1 and r.get("sampled_queries"):
            q_full = r["query_seconds"] * r["queries"] / r["sampled_queries"]
        total = q_full + r["mutation_seconds"]
        commits = out["commits_replayed"]
        if "truncated_at_event" in r:
            commits = commits * r["truncated_at_event"] / max(1, r["events_total"])
        r["commits_equiv_per_sec"] = round(commits / total, 1) if total else None
        out["tiers"][tier] = r
    return out
