"""The burn test: randomized workloads on the deterministic cluster, verified for
strict serializability.

Capability parity with ``accord.burn.BurnTest`` (BurnTest.java:123-622): one seed
fully determines topology (rf, node count, key count), the randomized client workload
(read/write/read-write txns over 1-3 keys, zipf-or-uniform key choice), concurrency
window, link latencies and faults; every client op feeds the verifier; any violation
or unresolved op fails the run with its seed.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..impl.list_store import ListResult, list_txn, range_read_txn
from ..primitives.keys import IntKey, Range, Ranges
from ..topology.topology import Shard, Topology
from ..utils.random import RandomSource
from .cluster import Cluster, LinkConfig
from .verifier import HistoryViolation, Observation, StrictSerializabilityVerifier


class BurnResult:
    def __init__(self, seed: int):
        self.seed = seed
        self.ops_submitted = 0
        self.ops_ok = 0
        self.ops_failed = 0
        self.sim_micros = 0
        self.stats: Dict[str, int] = {}

    def __repr__(self):
        return (f"BurnResult(seed={self.seed}, ok={self.ops_ok}, "
                f"failed={self.ops_failed}, sim_ms={self.sim_micros // 1000})")


class SimulationException(Exception):
    """Wraps any failure with its seed so the run can be replayed
    (BurnTest.java:588)."""

    def __init__(self, seed: int, cause: BaseException):
        super().__init__(f"burn seed={seed} failed: {cause}")
        self.seed = seed
        self.cause = cause


def run_burn(seed: int, ops: int = 200, concurrency: int = 10,
             link_config: Optional[LinkConfig] = None,
             nodes: Optional[int] = None, rf: Optional[int] = None,
             key_count: Optional[int] = None, num_shards: int = 1,
             allow_failures: bool = False,
             topology_churn: bool = False,
             churn_interval_s: float = 1.0,
             delayed_stores: bool = False,
             clock_drift: bool = False,
             journal: bool = False,
             resolver: Optional[str] = None) -> BurnResult:
    """Run one seeded burn; raises SimulationException on any violation."""
    rng = RandomSource(seed)
    rf = rf if rf is not None else rng.pick([3, 3, 5])
    n_nodes = nodes if nodes is not None else rng.next_int(rf, 2 * rf)
    key_count = key_count if key_count is not None else rng.next_int(5, 21)
    node_ids = list(range(1, n_nodes + 1))

    # shard the key space into rf-replicated ranges over the nodes
    n_ranges = max(1, n_nodes // max(1, rf // 2))
    bound = 1000
    step = bound // n_ranges
    shards = []
    for i in range(n_ranges):
        start, end = i * step, bound if i == n_ranges - 1 else (i + 1) * step
        replicas = [node_ids[(i + j) % n_nodes] for j in range(rf)]
        shards.append(Shard(Range(IntKey(start), IntKey(end)), replicas))
    topology = Topology(1, shards)

    cluster = Cluster(topology, seed=rng.next_long(), num_shards=num_shards,
                      link_config=link_config, delayed_stores=delayed_stores,
                      clock_drift=clock_drift, journal=journal,
                      resolver=resolver)
    member_ids = sorted(cluster.nodes)  # nodes actually replicating some shard
    churn_task = None
    if topology_churn:
        # random topology mutations at a fixed sim-time cadence
        # (Cluster.java:461, TopologyRandomizer.maybeUpdateTopology)
        from .topology_randomizer import TopologyRandomizer
        randomizer = TopologyRandomizer(cluster, rng.fork())
        churn_task = cluster.scheduler.recurring(churn_interval_s,
                                                 randomizer.maybe_update_topology)
    verifier = StrictSerializabilityVerifier()
    result = BurnResult(seed)
    zipf = rng.next_boolean()

    def key_for(i: int) -> IntKey:
        idx = rng.next_zipf(key_count) if zipf else rng.next_int(key_count)
        return IntKey((idx * bound) // key_count)

    state = {"submitted": 0, "in_flight": 0}

    def submit_next() -> None:
        while state["in_flight"] < concurrency and state["submitted"] < ops:
            op_id = state["submitted"]
            state["submitted"] += 1
            state["in_flight"] += 1
            if rng.next_float() < 0.15:
                # range query: 1-2 ranges, uniform or zipf sized
                # (BurnTest.java:208-240)
                nranges = rng.next_int(1, 3)
                rngs = []
                for _ in range(nranges):
                    width = 1 + (rng.next_zipf(bound // 2) if zipf
                                 else rng.next_int(bound // 2))
                    start = rng.next_int(bound - 1)
                    rngs.append(Range(IntKey(start),
                                      IntKey(min(bound, start + width))))
                txn = range_read_txn(Ranges.of(*rngs))
                writes = {}
            else:
                nkeys = rng.next_int(1, 4)
                keys = sorted({key_for(i) for i in range(nkeys)})
                kind = rng.pick(["read", "write", "rw", "rw"])
                reads = keys if kind in ("read", "rw") else []
                writes = {key: f"v{op_id}.{ki}" for ki, key in enumerate(keys)} \
                    if kind in ("write", "rw") else {}
                txn = list_txn(reads, writes)
            coordinator = cluster.nodes[rng.pick(member_ids)]
            obs = verifier.begin(cluster.now_micros)

            def on_done(value, failure, obs=obs, writes=writes):
                state["in_flight"] -= 1
                if failure is not None or not isinstance(value, ListResult):
                    obs.fail(cluster.now_micros)
                    result.ops_failed += 1
                else:
                    obs.complete(cluster.now_micros,
                                 dict(value.reads), dict(writes))
                    result.ops_ok += 1
                submit_next()

            coordinator.coordinate(txn).add_listener(on_done)
    submit_next()

    try:
        cluster.run_until(lambda: result.ops_ok + result.ops_failed >= ops,
                          max_tasks=5_000_000)
        if churn_task is not None:
            churn_task.cancel()  # stop mutating so the cluster can quiesce
        cluster.run_until_idle(max_tasks=5_000_000)
        result.ops_submitted = state["submitted"]
        result.sim_micros = cluster.now_micros
        result.stats = dict(cluster.stats)
        if result.ops_ok + result.ops_failed < ops:
            raise HistoryViolation(
                f"only {result.ops_ok + result.ops_failed}/{ops} ops resolved "
                f"(liveness stall)")
        if not allow_failures and result.ops_failed:
            raise HistoryViolation(f"{result.ops_failed} ops failed under a benign network")
        # final replica state must agree per key across replicas covering it
        # (under churn, judge against the FINAL topology's replica sets)
        final: Dict[IntKey, tuple] = {}
        for shard in cluster.topologies[-1].shards:
            lists = {}
            for n in shard.nodes:
                store = cluster.stores[n]
                for key, entries in store.data.items():
                    if shard.range.contains(key):
                        lists.setdefault(key, set()).add(tuple(v for _, v in entries))
            for key, variants in lists.items():
                longest = max(variants, key=len)
                for v in variants:
                    if v != longest[:len(v)]:
                        raise HistoryViolation(
                            f"replica divergence on {key}: {sorted(variants)}")
                final[key] = longest
        verifier.verify(final)
        # persistence contract: the journal's diff log must reconstruct every
        # store's durable command state (Journal.java reconstruct)
        if cluster.journal is not None:
            for node in cluster.nodes.values():
                for store in node.command_stores.all_stores():
                    cluster.journal.verify_against(store)
    except BaseException as e:  # noqa: BLE001
        raise SimulationException(seed, e) from e
    return result


def reconcile(seed: int, **kwargs) -> None:
    """Run the same seed twice and assert identical observable behavior —
    catches nondeterminism itself (BurnTest.reconcile, ReconcilingLogger)."""
    a = run_burn(seed, **kwargs)
    b = run_burn(seed, **kwargs)
    assert (a.ops_ok, a.ops_failed, a.sim_micros) == \
           (b.ops_ok, b.ops_failed, b.sim_micros), \
        f"nondeterministic outcome for seed {seed}: {a} vs {b}"
    assert a.stats == b.stats, \
        f"nondeterministic message counts for seed {seed}: " \
        f"{ {k: (a.stats.get(k), b.stats.get(k)) for k in set(a.stats) | set(b.stats) if a.stats.get(k) != b.stats.get(k)} }"
