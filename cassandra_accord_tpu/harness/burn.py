"""The burn test: randomized workloads on the deterministic cluster, verified for
strict serializability.

Capability parity with ``accord.burn.BurnTest`` (BurnTest.java:123-622): one seed
fully determines topology (rf, node count, key count), the randomized client workload
(read/write/read-write txns over 1-3 keys, zipf-or-uniform key choice), concurrency
window, link latencies and faults; every client op feeds the verifier; any violation
or unresolved op fails the run with its seed.

Hostile mode (``chaos=True``) adds the reference's full fault model: per-link
behavior (drop / failure / latency spikes) and minority partitions re-randomized
every 5s of sim-time (impl/basic/Cluster.java:455-459), with the progress log
driving recovery and the client resolving lost responses through home-shard
CheckStatus probes classified Applied/Invalidated/Truncated/Lost
(impl/list/ListRequest.java:61-150).  Every op must still resolve; the verifier
constrains acked ops fully, requires invalidated writes to never surface, and
leaves lost ops unconstrained.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..coordinate.errors import CoordinationFailed, Invalidated, Overloaded
from ..impl.list_store import ListResult, list_txn, range_read_txn
from ..local.status import SaveStatus, Status
from ..primitives.keys import IntKey, Range, Ranges
from ..topology.topology import Shard, Topology
from ..utils.random import RandomSource
from .cluster import Cluster, LinkConfig
from .verifier import HistoryViolation, Observation, StrictSerializabilityVerifier


class BurnResult:
    def __init__(self, seed: int):
        self.seed = seed
        self.ops_submitted = 0
        self.ops_ok = 0          # acked with result
        self.ops_recovered = 0   # resolved Applied via client CheckStatus probe
        self.ops_nacked = 0      # durably invalidated
        self.ops_lost = 0        # resolved Lost/Truncated (outcome unknown)
        self.ops_failed = 0      # unexpected failure
        self.crashes = 0         # nemesis node kills
        self.restarts = 0        # journal-replay rebuilds
        self.pauses = 0          # stop-the-world process pauses
        self.disk_stalls = 0     # journal-append stalls
        self.joins = 0           # elastic membership: nodes joined mid-burn
        self.leaves = 0          # elastic membership: decommissions mid-burn
        # overload plane (PR-17): sheds count into ops_failed too (they ARE
        # client-visible fast failures) — this is the attribution split
        self.ops_shed = 0        # client-entry admission sheds (subset of failed)
        self.overload_nacks = 0  # replica-side Overloaded nacks sent
        self.budget_denied = 0   # retry-budget token denials
        self.paced_arrivals = 0  # open-loop arrivals drawn while AIMD-paced
        self.pace_downs = 0      # AIMD pace-down events
        self.sim_micros = 0
        self.stats: Dict[str, int] = {}
        self.audit: Optional[dict] = None   # InvariantAuditor verdict, if on
        self.history: Optional[dict] = None  # history-checker report, if on

    @property
    def resolved(self) -> int:
        return (self.ops_ok + self.ops_recovered + self.ops_nacked
                + self.ops_lost + self.ops_failed)

    def __repr__(self):
        restarts = f", restarts={self.restarts}" if self.restarts else ""
        pauses = f", pauses={self.pauses}" if self.pauses else ""
        stalls = f", disk_stalls={self.disk_stalls}" if self.disk_stalls else ""
        joins = f", joins={self.joins}" if self.joins else ""
        leaves = f", leaves={self.leaves}" if self.leaves else ""
        shed = f", shed={self.ops_shed}" if self.ops_shed else ""
        return (f"BurnResult(seed={self.seed}, ok={self.ops_ok}, "
                f"recovered={self.ops_recovered}, nacked={self.ops_nacked}, "
                f"lost={self.ops_lost}, failed={self.ops_failed}{shed}"
                f"{restarts}{pauses}{stalls}{joins}{leaves}, "
                f"sim_ms={self.sim_micros // 1000})")


class SimulationException(Exception):
    """Wraps any failure with its seed so the run can be replayed
    (BurnTest.java:588)."""

    def __init__(self, seed: int, cause: BaseException):
        super().__init__(f"burn seed={seed} failed: {cause}")
        self.seed = seed
        self.cause = cause
        self.audit = None   # InvariantAuditor verdict at failure, if audited


MAX_PROBE_ATTEMPTS = 1000   # ListRequest.java:204 "arbitrarily large limit"


def last_cluster():
    """The most recent run's Cluster while it is still alive (debug/forensics)."""
    ref = getattr(run_burn, "last_cluster_ref", None)
    return ref() if ref is not None else None


def verify_frontiers(cluster) -> int:
    """Frontier parity (SURVEY §7 stage 8): the kernel-computed execution
    frontier (kahn_frontier over the resolver's mirrored wait graph) must
    equal the event-driven WaitingOn state on every store.  Valid at
    quiescent points (between tasks, no deferred store executors).  Returns
    stores checked."""
    from ..impl.resolver import VerifyDepsResolver
    from ..local.cfk import InternalStatus
    from ..utils.invariants import check_state
    stable_i = int(InternalStatus.STABLE)
    checked = 0
    for node in cluster.nodes.values():
        for store in node.command_stores.all_stores():
            r = store.resolver
            if not isinstance(r, VerifyDepsResolver):
                continue
            tpu = r.tpu
            dev = tpu.frontier_ready()
            host = set()
            for tid, cmd in store.commands.items():
                m = tpu.txns.get(tid)
                if m is None or m.status != stable_i \
                        or cmd.save_status.is_truncated:
                    continue
                if cmd.waiting_on is not None and not cmd.waiting_on.is_waiting():
                    host.add(tid)
            check_state(dev == host,
                        "frontier parity violation on node %s store %s: "
                        "device-only=%s host-only=%s", node.id, store.id,
                        sorted(dev - host), sorted(host - dev))
            checked += 1
    return checked


def run_burn(seed: int, ops: int = 200, concurrency: int = 10,
             link_config: Optional[LinkConfig] = None,
             nodes: Optional[int] = None, rf: Optional[int] = None,
             key_count: Optional[int] = None, num_shards: int = 1,
             allow_failures: bool = False,
             topology_churn: bool = False,
             churn_interval_s: float = 1.0,
             elastic_membership: bool = False,
             delayed_stores: bool = False,
             clock_drift: bool = False,
             journal: bool = False,
             resolver: Optional[str] = None,
             chaos: bool = False,
             chaos_interval_s: float = 5.0,
             progress_log: Optional[bool] = None,
             progress_poll_s: float = 0.5,
             durability: bool = False,
             batch_window_us: int = 0,
             cache_miss: bool = False,
             frontier_exec: bool = False,
             restart_nodes: bool = False,
             pause_nodes: bool = False,
             disk_stall: bool = False,
             stall_watchdog_s: Optional[float] = None,
             columnar: Optional[str] = None,
             node_config=None,
             max_tasks: int = 20_000_000,
             tracer=None, on_submit=None, consult_recorder=None,
             observer=None,
             profiler=None,
             provenance=None,
             perturb=None,
             audit: str = "off",
             audit_slo_s: Optional[float] = None,
             check: str = "off",
             history_recorder=None,
             workload=None,
             rate_txn_s: float = 25.0,
             load_phases=None,
             control_timeout_s: float = 60.0,
             progress_every_s: Optional[float] = None,
             progress_label: str = "") -> BurnResult:
    """Run one seeded burn; raises SimulationException on any violation.

    ``chaos=True`` turns on the hostile network (randomized drops, failures,
    latency spikes, minority partitions) + client retry; the progress log is
    then mandatory for liveness and defaults on.

    ``restart_nodes=True`` adds the crash-restart nemesis (harness/nemesis.py):
    seeded node kills + journal-replay rebuilds, cadence/downtime/concurrency
    from LocalConfig (``node_config`` or env) — including crash-time journal
    damage injection (torn tails, bit flips) the restart replay must detect
    and absorb.  Requires ``journal=True``.

    ``elastic_membership=True`` adds the membership nemesis
    (harness/nemesis.py MembershipNemesis): seeded join (a fresh process
    spawned mid-run bootstraps its ranges from live peers) and decommission
    (hand-off: removed from every shard in one epoch; the drained process
    stays live for prior epochs) cycles, plus join/leave actions in the
    topology-churn mutation mix — all respecting the muted-quorum floor and
    the randomizer's per-range clean-readable-quorum floor.

    ``pause_nodes=True`` adds the pause nemesis: seeded stop-the-world
    process pauses; every frozen timer late-fires at resume.

    ``disk_stall=True`` adds the disk-stall nemesis: journal-append stalls
    (durability + outbound packets lag execution); a crash mid-stall loses
    the unsynced tail.  Requires ``journal=True``.

    ``stall_watchdog_s``: raise StallError with a full wait-graph dump after
    this much sim-time without a resolved op (None disables).

    ``observer``: an ``observe.FlightRecorder`` — records the metrics
    registry, per-txn lifecycle spans (submit/resolve, fast/slow path,
    recovery attribution, per-node status timelines) and message events for
    Chrome-trace export.  ZERO observer effect: a same-seed run with and
    without one yields byte-identical message traces (proven by
    tests/test_observe.py).

    ``profiler``: an ``observe.WallProfiler`` — the WALL-CLOCK plane
    (per-message-type handler CPU, event-loop occupancy + queue depth,
    device-service launch breakdown).  Explicitly outside the determinism
    contract (its numbers differ run to run) but equally forbidden from
    perturbing the sim: the recorder trace stays byte-identical with it on
    vs off (tests/test_profiler.py).

    ``progress_every_s``: heartbeat — print one progress line (ops resolved,
    in-flight, fast-path share) per this many SIM-seconds, so long seed
    sweeps aren't silent until the watchdog fires.

    ``audit``: ``"strict"`` / ``"warn"`` / ``"off"`` — run the online
    protocol-invariant auditor (observe/audit.py) over the same hooks the
    flight recorder uses.  ``strict`` raises AuditViolation (wrapped in
    SimulationException) at the first violated invariant; ``warn`` records
    violations into ``result.audit``.  Either way ``result.audit`` carries
    the per-run verdict (violations, SLO flags).  ``audit_slo_s`` overrides
    the unattended-txn liveness budget (sim-seconds).  The auditor IS a
    FlightRecorder, so ``observer`` must be left None (one is created) or
    already be an InvariantAuditor.

    ``check``: ``"history"`` records the client-visible operation history
    (observe/history.py — invoke/ok/fail/info per op, observed version lists
    per key) and runs the protocol-blind Elle-style checker
    (observe/checker.py) over it after final state is judged.  Any named
    anomaly (G0/G1c/G-single/G2/lost-update/non-repeatable-read/...) raises
    through SimulationException with the offending sub-history; a clean run
    stores the report on ``result.history``.  Composes with ``audit``
    (independent oracles).  Recording is a passive sink — zero observer
    effect, proven by tests/test_history_checker.py.

    ``workload``: None keeps the classic inline generator (byte-identical
    trajectories for every existing seed); a preset name
    (``multirange``/``zipf``/``openloop``) or a ``harness.workload.Workload``
    instance switches generation to that shape.  ``rate_txn_s`` sets the
    openloop Poisson arrival rate (sim txn/s); openloop ignores the
    ``concurrency`` window.  ``control_timeout_s``: barrier/sync-point ops
    (multirange) have no txn id the client could probe, so an unresolved
    control op resolves as lost after this much sim-time.

    ``load_phases``: open-loop offered-load schedule — a list of
    ``(start_sim_s, rate_mult)`` phases driven by the deterministic
    LoadSpikeNemesis (the overload ramp/burst presets).  Requires an
    open-loop workload.  Per-phase goodput lands in
    ``result.stats["load_phase{i}_ok"]``.

    ``provenance``: an ``observe.ProvenanceRecorder`` — records the per-run
    causal event DAG (observe/provenance.py) for divergence forensics and
    violation slicing.  Attached to the observer (one is created if needed);
    zero observer effect like every other attachment.

    ``perturb``: a callable ``(cluster) -> None`` invoked once after cluster
    construction — the mutation-test injection point (schedule an extra
    fault-in, delay a timer).  It must not consume cluster RNG at call time,
    so the trajectory stays byte-identical until the scheduled perturbation
    fires.
    """
    from ..config import LocalConfig
    if audit not in ("off", "strict", "warn"):
        raise ValueError(f"audit must be off/strict/warn, got {audit!r}")
    if check not in ("off", "history"):
        raise ValueError(f"check must be off/history, got {check!r}")
    history_rec = history_recorder
    if check == "history" and history_rec is None:
        from ..observe.history import HistoryRecorder
        history_rec = HistoryRecorder()
    if audit != "off":
        from ..observe.audit import InvariantAuditor
        if observer is None:
            observer = InvariantAuditor(mode=audit,
                                        slo_unattended_s=audit_slo_s,
                                        provenance=provenance)
        elif isinstance(observer, InvariantAuditor):
            observer.mode = audit
        else:
            raise ValueError("audit requires the observer to be an "
                             "InvariantAuditor (or None — one is created); "
                             "got a plain FlightRecorder")
    if provenance is not None:
        if observer is None:
            from ..observe import FlightRecorder
            observer = FlightRecorder(record_messages=False,
                                      provenance=provenance)
        else:
            # attach (idempotent for the auto-created auditor above): the
            # cluster reads observer.provenance at construction
            observer.provenance = provenance
    rng = RandomSource(seed)
    rf = rf if rf is not None else rng.pick([3, 3, 5])
    n_nodes = nodes if nodes is not None else rng.next_int(rf, 2 * rf)
    key_count = key_count if key_count is not None else rng.next_int(5, 21)
    node_ids = list(range(1, n_nodes + 1))
    if restart_nodes:
        assert journal, "restart_nodes requires journal=True (the restart " \
                        "store of record)"
        assert num_shards == 1, \
            "restart_nodes requires num_shards=1: restart replay keys " \
            "journal logs by store id, and multi-store range assignment " \
            "is not stable across a restart boundary"
    cfg = node_config if node_config is not None else LocalConfig.from_env()
    if progress_log is None:
        # recovery must be live whenever coordinators can die mid-flight —
        # and whenever admission control can NACK a PreAccept: the nack is a
        # partial failure (some replicas witnessed the txn), and only the
        # progress log settles the orphan the rest of the deps graph
        # blocks behind
        progress_log = chaos or restart_nodes or cfg.admission_enabled
    if columnar is not None:
        # the columnar protocol engine knob (protocol_batch/): auto|on|off.
        # By the exact-skip contract the knob NEVER changes a trajectory —
        # same-seed runs on-vs-off are byte-identical (tests/
        # test_protocol_batch.py) — so overriding it here is always safe
        from dataclasses import replace as _dc_replace
        cfg = _dc_replace(cfg, columnar=columnar)
        node_config = cfg

    # shard the key space into rf-replicated ranges over the nodes
    n_ranges = max(1, n_nodes // max(1, rf // 2))
    bound = 1000
    step = bound // n_ranges
    shards = []
    for i in range(n_ranges):
        start, end = i * step, bound if i == n_ranges - 1 else (i + 1) * step
        replicas = [node_ids[(i + j) % n_nodes] for j in range(rf)]
        shards.append(Shard(Range(IntKey(start), IntKey(end)), replicas))
    topology = Topology(1, shards)

    if chaos and link_config is None:
        from .chaos import RandomizedLinkConfig
        link_config = RandomizedLinkConfig(rng.fork(), rf,
                                           interval_s=chaos_interval_s)
    cluster = Cluster(topology, seed=rng.next_long(), num_shards=num_shards,
                      link_config=link_config, delayed_stores=delayed_stores,
                      clock_drift=clock_drift, journal=journal,
                      resolver=resolver, progress_log=progress_log,
                      progress_poll_s=progress_poll_s,
                      batch_window_us=batch_window_us,
                      node_config=node_config,
                      observer=observer, profiler=profiler)
    cluster.tracer = tracer
    if perturb is not None:
        # mutation-test injection: the callable may only SCHEDULE work (an
        # extra crash, a delayed timer) — the extra queue entry shifts later
        # seq numbers uniformly, so the trajectory is untouched until the
        # perturbation actually fires
        perturb(cluster)
    if consult_recorder is not None:
        # trace-driven data-plane bench (harness/consult_trace.py): wrap every
        # store's resolver so the full mutation+query stream is captured
        for node in cluster.nodes.values():
            for cs in node.command_stores.all_stores():
                consult_recorder.wrap_store(cs)
    # debugging handle (stall forensics): weak, so finished runs don't pin the
    # whole cluster graph in a module global
    import weakref
    run_burn.last_cluster_ref = weakref.ref(cluster)
    member_ids = sorted(cluster.nodes)  # nodes actually replicating some shard
    churn_task = None
    randomizer = None
    # elastic membership: node ids the run may SPAWN mid-burn (joins beyond
    # the initial member set); candidate set covers spawned nodes too
    spawn_pool = list(range(n_nodes + 1, n_nodes + 1 + max(2, n_nodes // 2))) \
        if elastic_membership else []
    if topology_churn or elastic_membership:
        # random topology mutations at a fixed sim-time cadence
        # (Cluster.java:461, TopologyRandomizer.maybeUpdateTopology); with
        # elastic membership the mutation mix grows join/leave actions
        from .topology_randomizer import TopologyRandomizer
        randomizer = TopologyRandomizer(cluster, rng.fork(),
                                        elastic=elastic_membership,
                                        spawn_pool=spawn_pool)
        if topology_churn:
            churn_task = cluster.scheduler.recurring(
                churn_interval_s, randomizer.maybe_update_topology)
    durability_scheduling: Dict[int, object] = {}
    if durability:
        # scheduled durability + truncation running DURING the burn, with
        # randomized cadences (Cluster.java:429-445)
        from ..impl.durability_scheduling import CoordinateDurabilityScheduling
        shard_cycle = float(rng.next_biased_int(5, 15, 45))
        global_cycle = float(rng.next_biased_int(10, 30, 90))

        def start_durability(node):
            sched = CoordinateDurabilityScheduling(
                node, shard_cycle_time_s=shard_cycle,
                global_cycle_time_s=global_cycle)
            sched.start()
            durability_scheduling[node.id] = sched

        for node in cluster.nodes.values():
            start_durability(node)
        # a restarted node gets a fresh scheduling instance (the old one's
        # timers died with its incarnation); a JOINED node gets one too
        cluster.on_restart_hooks.append(start_durability)
        cluster.on_add_hooks.append(start_durability)
    cache_miss_task = None
    if cache_miss:
        # cache-miss injection (DelayedCommandStores.java:138-195 capability):
        # keep evicting terminal commands so the protocol continuously runs
        # against state that is NOT memory-resident and must fault back in
        # from the journal (requires journal=True)
        assert journal, "cache_miss injection requires the journal"
        evict_rng = rng.fork()

        def evict_some():
            # eviction runs INSIDE each store's executor: a deferred store
            # task (delayed_stores) may still hold a direct reference to a
            # command — evicting from outside would let a later lookup fault
            # in a SECOND live instance of the same command, silently breaking
            # the single-instance invariant even when no mutation races
            for node in cluster.nodes.values():
                for cs in node.command_stores.all_stores():
                    def evict_in_store(safe, cs=cs):
                        for tid in list(cs.commands):
                            if evict_rng.next_float() < 0.3:
                                safe.evict(tid)
                    cs.execute(evict_in_store)
        cache_miss_task = cluster.scheduler.recurring(0.4, evict_some)

    frontier_task = None
    if resolver == "verify" and not chaos and not delayed_stores:
        # continuous frontier parity at (deterministic) quiescent task points
        frontier_task = cluster.scheduler.recurring(
            0.7, lambda: verify_frontiers(cluster))
    frontier_release_task = None
    if frontier_exec:
        # frontier-DRIVEN execution (SURVEY §7 stage 8): indexed STABLE txns
        # whose WaitingOn drained park in store.exec_deferred; only the device
        # kahn_frontier releases them into ReadyToExecute.  A frontier that
        # misses a ready txn stalls the burn — the parity failure is loud.
        assert resolver in ("verify", "tpu"), \
            "frontier_exec needs the device resolver's wait-graph mirror"
        from ..local import commands as C
        from ..local.status import SaveStatus as _SS
        for node in cluster.nodes.values():
            for cs in node.command_stores.all_stores():
                cs.frontier_exec = True

        def release_frontiers():
            for node in cluster.nodes.values():
                for cs in node.command_stores.all_stores():
                    if not cs.exec_deferred:
                        continue

                    def in_store(safe, cs=cs):
                        if not cs.exec_deferred:
                            return
                        r = getattr(cs.resolver, "tpu", cs.resolver)
                        ready = r.frontier_ready()
                        parked = list(cs.exec_deferred)
                        # columnar prefilter (exact-skip): resident rows the
                        # mirror PROVES moved past STABLE are discarded
                        # without the scalar visit; unknown rows (possible
                        # fault-in) always take it
                        known = stable = None
                        if cs.batch_engine is not None:
                            part = cs.batch_engine.exec_deferred_partition(
                                parked)
                            if part is not None:
                                known, stable = part
                        for i, tid in enumerate(parked):
                            if known is not None and known[i] \
                                    and not stable[i]:
                                cs.exec_deferred.discard(tid)
                                continue
                            cmd = safe.get_if_exists(tid)
                            if cmd is None \
                                    or cmd.save_status is not _SS.STABLE:
                                cs.exec_deferred.discard(tid)
                                continue
                            if tid in ready:
                                cs.exec_deferred.discard(tid)
                                cluster.stats["frontier_released"] = \
                                    cluster.stats.get("frontier_released", 0) + 1
                                C.maybe_execute(safe, cmd, True,
                                                from_frontier=True)
                    cs.execute(in_store)
        frontier_release_task = cluster.scheduler.recurring(
            0.05, release_frontiers)
    verifier = StrictSerializabilityVerifier()
    result = BurnResult(seed)
    zipf = rng.next_boolean()
    workload_obj = None
    if workload is not None:
        # the preset draws from its OWN fork of the seeded stream: a seed
        # still fully determines the workload, and the fork keeps the main
        # stream's draw sequence independent of per-op generation arity
        from .workload import make_workload
        workload_obj = make_workload(workload, rate_txn_s=rate_txn_s)
        workload_obj.bind(rng.fork(), key_count=key_count, bound=bound,
                          ops=ops)

    def key_for(i: int) -> IntKey:
        idx = rng.next_zipf(key_count) if zipf else rng.next_int(key_count)
        return IntKey((idx * bound) // key_count)

    state = {"submitted": 0, "in_flight": 0}
    # op_id -> client record; the crash-restart nemesis fails over any op
    # whose coordinator died mid-flight (the reference burn's external client
    # resolving a dead coordinator's silence through CheckStatus probes)
    inflight: Dict[int, dict] = {}
    # per-load-phase goodput buckets (overload burst recovery measurement);
    # load_nemesis is bound below, after the other nemeses
    load_nemesis = None
    phase_ok: Dict[int, int] = {}

    def pick_coordinator():
        # liveness precheck WITHOUT touching the rng (keeps seeded streams
        # stable): if every member is down at once (keep_quorum=False
        # experiments), the redial loop below would spin at HOST level —
        # sim time frozen, so not even the stall watchdog could fire
        if not any(m in cluster.nodes for m in member_ids):
            raise RuntimeError("no live member to coordinate: every member "
                               "node is down (restart_keep_quorum=False with "
                               "restart_max_down >= cluster size?)")
        node_id = rng.pick(member_ids)
        while node_id not in cluster.nodes:   # crashed: the client redials
            node_id = rng.pick(member_ids)
        return cluster.nodes[node_id]

    def live(node):
        """The client's connection: if this node object crashed, dial a
        currently-live node instead."""
        if cluster.nodes.get(node.id) is node:
            return node
        return pick_coordinator()

    def resolve(rec: dict, kind: str, reads=None,
                writes: Optional[dict] = None) -> None:
        if rec["settled"]:
            return   # e.g. probe failover and a late reply raced; first wins
        rec["settled"] = True
        inflight.pop(rec["op_id"], None)
        obs = rec["obs"]
        state["in_flight"] -= 1
        now = cluster.now_micros
        if observer is not None and rec["txn_id"] is not None:
            observer.on_resolve(rec["txn_id"], kind, now)
        if history_rec is not None and not rec.get("control"):
            history_rec.resolve(rec["op_id"], kind, now, reads, writes)
        if kind == "ok":
            obs.complete(now, reads or {}, writes or {})
            result.ops_ok += 1
        elif kind == "recovered":
            obs.complete(now, reads or {}, writes or {})
            result.ops_recovered += 1
        elif kind == "nacked":
            obs.invalidated(now, writes or {})
            result.ops_nacked += 1
        elif kind == "lost":
            obs.lost(now)
            result.ops_lost += 1
        else:
            obs.fail(now)
            result.ops_failed += 1
        if workload_obj is not None and workload_obj.open_loop:
            # client-side AIMD: a shed backs the offered rate off
            # multiplicatively, a success recovers it gradually — the
            # backpressure loop that keeps overload from going metastable
            if rec.get("shed"):
                workload_obj.on_shed()
            elif kind in ("ok", "recovered"):
                workload_obj.on_ok()
            if kind in ("ok", "recovered") and state["submitted"] < ops:
                # a commit landing while arrivals are still being offered:
                # the honest goodput numerator (drain-tail commits after the
                # last arrival are latency, not sustained throughput)
                state["window_ok"] = state.get("window_ok", 0) + 1
        if load_nemesis is not None and kind in ("ok", "recovered"):
            ph = load_nemesis.phase_of(now / 1e6)
            phase_ok[ph] = phase_ok.get(ph, 0) + 1
        submit_next()

    def probe(coordinator, rec: dict, attempt: int) -> None:
        """Client lost-response resolution: CheckStatus the cluster until the
        txn's fate is known (ListRequest.CheckOnResult, ListRequest.java:61-150)."""
        from ..coordinate.fetch_data import check_status_quorum
        if rec["settled"]:
            return
        coordinator = live(coordinator)
        # the prober now owns this op's resolution: if IT crashes mid-probe
        # (its sink teardown swallows the CheckStatus callbacks, so neither
        # reply nor failure ever fires), fail_over_orphans must match on the
        # CURRENT prober, not the original submitter, or the op hangs forever
        rec["coordinator"] = coordinator.id
        txn_id, route, writes = rec["txn_id"], rec["route"], rec["writes"]

        def retry():
            if rec["settled"]:
                return
            if attempt + 1 >= MAX_PROBE_ATTEMPTS:
                resolve(rec, "failed")
                return
            cluster.scheduler.once(0.5 + rng.next_float(),
                                   lambda: probe(coordinator, rec, attempt + 1))

        def on_checked(merged, failure):
            if failure is not None:
                retry()
                return
            ss = merged.save_status if merged is not None else SaveStatus.NOT_DEFINED
            if ss is SaveStatus.INVALIDATED:
                resolve(rec, "nacked", writes=writes)
            elif merged is not None and merged.invalid_if_undecided \
                    and not ss.has_been(Status.PRE_COMMITTED):
                # Infer (Infer.java IfUndecided with quorum): every quorum
                # member's majority-durability watermark passed txnId and none
                # saw a decision — the txn provably never committed and never
                # can (preaccept below the fence refuses): durably invalid
                resolve(rec, "nacked", writes=writes)
            elif ss.ordinal >= SaveStatus.APPLIED.ordinal and not ss.is_truncated:
                reads = dict(merged.result.reads) \
                    if isinstance(merged.result, ListResult) else {}
                resolve(rec, "recovered", reads=reads, writes=writes)
            elif ss.is_truncated:
                # durably decided and cleaned up; outcome unknowable → Lost-class
                resolve(rec, "lost")
            elif not ss.has_been(Status.PRE_ACCEPTED):
                # a quorum answered and nothing witnessed it
                resolve(rec, "lost")
            else:
                # in flight somewhere — but only SOME replica may have
                # witnessed it, and if the home shard never did, NOTHING
                # drives recovery (the progress log monitors only witnessed
                # txns): a minority-witnessed orphan then stays PRE_ACCEPTED
                # forever and the probe loops to its cap.  Tell the home
                # shard it exists (InformOfTxnId.java role; the reference's
                # ListRequest escalation) so MaybeRecover settles it —
                # typically by invalidation — and the next probe resolves.
                if attempt >= 2:
                    from ..messages.status_messages import InformOfTxn
                    topo = coordinator.config_service.current_topology()
                    shard = topo.for_key(route.home_key)
                    if shard is not None:
                        for to in shard.nodes:
                            coordinator.send(to, InformOfTxn(
                                txn_id, route.home_key_only(),
                                coordinator.epoch()))
                retry()  # recovery (now informed) settles it

        check_status_quorum(coordinator, txn_id, route, include_info=True) \
            .to_chain().begin(on_checked)

    def dispatch_txn(op_id: int, txn, read_keys, writes) -> None:
        """Submit one data txn: verifier observation, client record, history
        invoke, coordinate + resolution callback (shared by the classic
        generator and every workload preset)."""
        coordinator = pick_coordinator()
        adm = getattr(coordinator, "admission", None)
        if adm is not None and adm.overloaded():
            # client-entry shed: refused BEFORE a txn id exists, so the fast
            # client-visible failure is sound — the txn provably never
            # entered the system (the round-13 fresh-values rule lets the
            # history checker treat a `fail` as definitely-not-applied)
            adm.sheds += 1
            result.ops_shed += 1
            obs = verifier.begin(cluster.now_micros)
            rec = {"op_id": op_id, "obs": obs, "txn_id": None, "route": None,
                   "writes": {}, "coordinator": coordinator.id,
                   "settled": False, "shed": True}
            inflight[op_id] = rec
            if history_rec is not None:
                history_rec.invoke(op_id, None, cluster.now_micros,
                                   read_keys, writes)
            if observer is not None:
                observer.registry.counter("overload.shed",
                                          node=coordinator.id).inc()
            resolve(rec, "failed")
            return
        txn_id = coordinator.next_txn_id(txn.kind, txn.domain)
        route = txn.to_route()
        obs = verifier.begin(cluster.now_micros)
        rec = {"op_id": op_id, "obs": obs, "txn_id": txn_id, "route": route,
               "writes": dict(writes), "coordinator": coordinator.id,
               "settled": False}
        inflight[op_id] = rec
        if history_rec is not None:
            history_rec.invoke(op_id, txn_id, cluster.now_micros,
                               read_keys, writes)
        if observer is not None:
            observer.on_submit(op_id, txn_id, coordinator.id,
                               cluster.now_micros)
        if on_submit is not None:
            on_submit(op_id, txn_id, txn, coordinator.id)

        def on_done(value, failure, rec=rec, coordinator=coordinator):
            if isinstance(failure, Overloaded) and workload_obj is not None \
                    and workload_obj.open_loop:
                # a replica-side admission nack surfaced as the coordination
                # outcome: pace the open-loop client down before resolving
                # through the normal lost-response machinery
                workload_obj.on_shed()
            if failure is None and isinstance(value, ListResult):
                resolve(rec, "ok", reads=dict(value.reads),
                        writes=dict(rec["writes"]))
            elif isinstance(failure, Invalidated):
                resolve(rec, "nacked", writes=dict(rec["writes"]))
            elif chaos or restart_nodes \
                    or isinstance(failure, CoordinationFailed):
                # response lost in the chaos: resolve through the home shard
                probe(coordinator, rec, 0)
            else:
                resolve(rec, "failed")

        coordinator.coordinate(txn, txn_id=txn_id).add_listener(on_done)

    def dispatch_control(op_id: int, control) -> None:
        """Interactive op (barrier / sync point) through the coordinate
        surface.  No txn id exists before coordination allocates one, so the
        client cannot CheckStatus-probe it: an op whose callbacks died (e.g.
        its coordinator crashed) resolves as lost at a sim-time deadline."""
        coordinator = pick_coordinator()
        obs = verifier.begin(cluster.now_micros)
        rec = {"op_id": op_id, "obs": obs, "txn_id": None, "route": None,
               "writes": {}, "coordinator": coordinator.id,
               "settled": False, "control": True}
        inflight[op_id] = rec
        rec["deadline"] = cluster.scheduler.once(
            control_timeout_s, lambda rec=rec: resolve(rec, "lost"))

        def on_ctl_done(value, failure, rec=rec):
            timer = rec.pop("deadline", None)
            if timer is not None:
                timer.cancel()
            if rec["settled"]:
                return
            if failure is None:
                resolve(rec, "ok")
            elif isinstance(failure, Invalidated):
                resolve(rec, "nacked")
            elif chaos or restart_nodes \
                    or isinstance(failure, CoordinationFailed):
                resolve(rec, "lost")
            else:
                resolve(rec, "failed")

        if control[0] == "barrier":
            _kind, btype, seekables = control
            res = coordinator.barrier(seekables,
                                      min_epoch=coordinator.epoch(),
                                      barrier_type=btype)
        else:
            _kind, seekables = control
            res = coordinator.sync_point(seekables, blocking=False)
        res.add_listener(on_ctl_done)

    def submit_workload_op() -> None:
        op_id = state["submitted"]
        state["submitted"] += 1
        state["in_flight"] += 1
        wop = workload_obj.next_op(op_id)
        if wop.control is not None:
            dispatch_control(op_id, wop.control)
        else:
            dispatch_txn(op_id, wop.txn, wop.read_keys, wop.writes)

    def submit_next() -> None:
        if workload_obj is not None:
            if workload_obj.open_loop:
                return   # arrivals are timer-driven, not window-driven
            while state["in_flight"] < concurrency \
                    and state["submitted"] < ops:
                submit_workload_op()
            return
        while state["in_flight"] < concurrency and state["submitted"] < ops:
            op_id = state["submitted"]
            state["submitted"] += 1
            state["in_flight"] += 1
            if rng.next_float() < 0.15:
                # range query: 1-2 ranges, uniform or zipf sized
                # (BurnTest.java:208-240)
                nranges = rng.next_int(1, 3)
                rngs = []
                for _ in range(nranges):
                    width = 1 + (rng.next_zipf(bound // 2) if zipf
                                 else rng.next_int(bound // 2))
                    start = rng.next_int(bound - 1)
                    rngs.append(Range(IntKey(start),
                                      IntKey(min(bound, start + width))))
                txn = range_read_txn(Ranges.of(*rngs))
                reads = []
                writes = {}
            else:
                nkeys = rng.next_int(1, 4)
                keys = sorted({key_for(i) for i in range(nkeys)})
                kind = rng.pick(["read", "write", "rw", "rw"])
                reads = keys if kind in ("read", "rw") else []
                writes = {key: f"v{op_id}.{ki}" for ki, key in enumerate(keys)} \
                    if kind in ("write", "rw") else {}
                txn = list_txn(reads, writes)
            dispatch_txn(op_id, txn, tuple(reads), writes)

    def schedule_arrivals() -> None:
        """Open-loop: Poisson arrivals on the sim clock — submit at the drawn
        instants regardless of what is in flight."""
        def fire():
            if state["submitted"] >= ops:
                return
            state["last_arrival_us"] = cluster.now_micros
            submit_workload_op()
            arm()

        def arm():
            if state["submitted"] >= ops:
                return
            cluster.scheduler.once(workload_obj.next_arrival_s(), fire)

        arm()

    membership_nemesis = None
    if elastic_membership:
        from .nemesis import MembershipNemesis
        membership_nemesis = MembershipNemesis(
            cluster, rng.fork(), randomizer,
            interval_s=cfg.membership_interval_s,
            min_members=cfg.membership_min_members,
            max_members=cfg.membership_max_members)
        membership_nemesis.attach()
    nemesis = None
    if restart_nodes:
        from .nemesis import RestartNemesis

        def fail_over_orphans(victim: int) -> None:
            # every unsettled op this client had submitted THROUGH the dead
            # coordinator will never hear back (its callbacks died with the
            # process): resolve each through home-shard probes from a live
            # node, exactly like a lost response under chaos
            for rec in list(inflight.values()):
                if rec["coordinator"] == victim and not rec["settled"] \
                        and not rec.get("control"):
                    # control ops (barrier/sync point) have no txn id to
                    # probe; their sim-time deadline resolves them as lost
                    cluster.scheduler.once(
                        0.1 + rng.next_float(),
                        lambda rec=rec: probe(pick_coordinator(), rec, 0))

        nemesis = RestartNemesis(
            cluster, rng.fork(),
            interval_s=cfg.restart_interval_s,
            downtime_min_s=cfg.restart_downtime_min_s,
            downtime_max_s=cfg.restart_downtime_max_s,
            max_down=cfg.restart_max_down,
            keep_quorum=cfg.restart_keep_quorum,
            torn_tail_chance=cfg.journal_torn_tail_chance,
            corrupt_chance=cfg.journal_corrupt_chance,
            on_crash=fail_over_orphans)
        nemesis.attach()
    pause_nemesis = None
    if pause_nodes:
        from .nemesis import PauseNemesis
        pause_nemesis = PauseNemesis(
            cluster, rng.fork(),
            interval_s=cfg.pause_interval_s,
            pause_min_s=cfg.pause_min_s, pause_max_s=cfg.pause_max_s,
            max_paused=cfg.pause_max_paused,
            keep_quorum=cfg.pause_keep_quorum)
        pause_nemesis.attach()
    disk_nemesis = None
    if disk_stall:
        assert journal, "disk_stall requires journal=True (the stalled device)"
        from .nemesis import DiskStallNemesis
        disk_nemesis = DiskStallNemesis(
            cluster, rng.fork(),
            interval_s=cfg.disk_stall_interval_s,
            stall_min_s=cfg.disk_stall_min_s,
            stall_max_s=cfg.disk_stall_max_s)
        disk_nemesis.attach()
    if load_phases:
        assert workload_obj is not None and workload_obj.open_loop, \
            "load_phases requires an open-loop workload (the offered-load " \
            "multiplier scales arrival rates)"
        from .nemesis import LoadSpikeNemesis
        load_nemesis = LoadSpikeNemesis(cluster, workload_obj, load_phases)
        load_nemesis.attach()
    watchdog = None
    if stall_watchdog_s is not None:
        from .watchdog import StallWatchdog
        watchdog = StallWatchdog(cluster, lambda: result.resolved,
                                 stalled_after_s=stall_watchdog_s,
                                 interval_s=cfg.stall_watchdog_interval_s)
        watchdog.attach()
    heartbeat_task = None
    if progress_every_s:
        # one line per N sim-seconds so long seed sweeps aren't silent until
        # the watchdog fires.  NOTE: unlike the flight recorder this DOES
        # schedule (a recurring sim task) — it shifts queue sequence numbers,
        # so runs meant for trace reconciliation should leave it off.
        label = progress_label if progress_label else f"seed {seed}"

        def heartbeat():
            line = (f"[burn {label}] sim={cluster.now_micros / 1e6:.1f}s "
                    f"resolved={result.resolved}/{ops} "
                    f"in_flight={state['in_flight']}")
            if observer is not None:
                fast = observer.registry.counter("txn.path.fast").value
                slow = observer.registry.counter("txn.path.slow").value
                if fast + slow:
                    line += f" fast_path={100.0 * fast / (fast + slow):.0f}%"
            print(line, flush=True)
        heartbeat_task = cluster.scheduler.recurring(float(progress_every_s),
                                                     heartbeat)
    if workload_obj is not None and workload_obj.open_loop:
        schedule_arrivals()
    else:
        submit_next()

    try:
        cluster.run_until(lambda: result.resolved >= ops, max_tasks=max_tasks)
        # quiesce: stop chaos/churn/durability/nemesis so the cluster can
        # settle (the reference's noMoreWorkSignal, Cluster.java:470-475)
        if watchdog is not None:
            watchdog.cancel()   # resolved stops moving by design from here on
        if heartbeat_task is not None:
            heartbeat_task.cancel()
        if churn_task is not None:
            churn_task.cancel()
        if membership_nemesis is not None:
            # stop join/leave scheduling; drained nodes stay live (prior
            # epochs still need them; the agreement check judges the FINAL
            # topology's replica sets)
            membership_nemesis.stop()
        if pause_nemesis is not None:
            # resume every paused node BEFORE restarting downed ones: the
            # parked late-firing timers must drain into a full replica set
            pause_nemesis.stop_and_restore()
        if disk_nemesis is not None:
            # everything buffered becomes durable; held packets hit the wire
            disk_nemesis.stop_and_restore()
        if load_nemesis is not None:
            load_nemesis.stop()
        if nemesis is not None:
            # restore every down node BEFORE judging final state: the
            # agreement checks need the full replica set live and caught up
            nemesis.stop_and_restore()
        for sched in durability_scheduling.values():
            sched.stop()
        if hasattr(cluster.link, "heal"):
            cluster.link.heal()
        cluster.run_until_idle(max_tasks=max_tasks)
        if cache_miss_task is not None:
            cache_miss_task.cancel()
        if frontier_task is not None:
            frontier_task.cancel()
            verify_frontiers(cluster)   # final quiescent frontier parity
        elif resolver == "verify":
            # chaos / delayed-store runs: mid-run points are nondeterministic,
            # but FINAL quiescence must still agree (VERDICT r03 item 3)
            verify_frontiers(cluster)
        if frontier_release_task is not None:
            frontier_release_task.cancel()
            # txns parked AFTER the last release tick (run_until_idle stops
            # once only recurring tasks remain) are not frontier misses: keep
            # releasing while each round strictly shrinks the deferred set
            # (a parked dependency chain can be arbitrarily deep at quiesce),
            # and only judge once a round makes no progress
            def _deferred_count():
                return sum(len(cs.exec_deferred)
                           for n in cluster.nodes.values()
                           for cs in n.command_stores.all_stores())
            prev = _deferred_count()
            while prev:
                release_frontiers()
                cluster.run_until_idle(max_tasks=max_tasks)
                cur = _deferred_count()
                if cur >= prev:
                    break
                prev = cur
            leftover = [(n.id, cs.id, sorted(cs.exec_deferred))
                        for n in cluster.nodes.values()
                        for cs in n.command_stores.all_stores()
                        if cs.exec_deferred]
            if leftover:
                raise HistoryViolation(
                    f"frontier-driven execution left deferred txns: {leftover}")
        result.ops_submitted = state["submitted"]
        result.sim_micros = cluster.now_micros
        result.stats = dict(cluster.stats)
        result.crashes = cluster.stats.get("node_crashes", 0)
        result.restarts = cluster.stats.get("node_restarts", 0)
        result.pauses = cluster.stats.get("node_pauses", 0)
        result.disk_stalls = cluster.stats.get("journal_stalls", 0)
        result.joins = cluster.stats.get("node_joins", 0)
        result.leaves = cluster.stats.get("node_decommissions", 0)
        # overload plane: admission nacks + retry-budget denials, summed from
        # plain per-node counters (observer-free by design — the zero-
        # observer-effect contract extends to the overload.* series); current
        # incarnations only, like every other per-node end-of-run sum
        for node in cluster.nodes.values():
            oc = getattr(node, "overload_counters", None)
            if oc:
                result.overload_nacks += oc.get("nacks", 0)
                result.budget_denied += oc.get("budget_denied", 0)
        if workload_obj is not None and workload_obj.open_loop:
            result.paced_arrivals = workload_obj.paced_arrivals
            result.pace_downs = workload_obj.pace_downs
        for key, val in (("overload_nacks", result.overload_nacks),
                         ("overload_budget_denied", result.budget_denied),
                         ("ops_shed", result.ops_shed),
                         ("paced_arrivals", result.paced_arrivals)):
            if val:
                result.stats[key] = val
        for ph, n_ok in sorted(phase_ok.items()):
            result.stats[f"load_phase{ph}_ok"] = n_ok
        if state.get("last_arrival_us"):
            # the offered-load window (first to last open-loop arrival): the
            # overload oracles measure goodput against THIS, not total sim
            # time — the post-arrival drain tail is latency, not throughput
            result.stats["last_arrival_us"] = state["last_arrival_us"]
            result.stats["window_ok_commits"] = state.get("window_ok", 0)
        # per-key execution-register inversion diagnostic (TimestampsForKey):
        # surfaced in every burn's stats; MUST be 0 in benign runs (asserted
        # by test_timestamps_for_key) — growth under chaos pages the Agent
        # via on_inconsistent_timestamp escalation, not silence
        result.stats["tfk_inversions"] = sum(
            cs.tfk_inversions for node in cluster.nodes.values()
            for cs in node.command_stores.all_stores())
        # columnar-engine effectiveness counters (deterministic given the
        # trajectory — the engine never CHANGES the trajectory): how many
        # scalar visits the vectorized passes proved skippable
        col_stats: Dict[str, int] = {}
        for node in cluster.nodes.values():
            for cs in node.command_stores.all_stores():
                if cs.batch_engine is not None:
                    for k2, v in cs.batch_engine.stats.items():
                        col_stats[k2] = col_stats.get(k2, 0) + v
        if col_stats:
            result.stats.update({f"columnar_{k2}": v
                                 for k2, v in col_stats.items()})
        if cache_miss:
            result.stats["cache_miss_loads"] = sum(
                cs.cache_miss_loads for node in cluster.nodes.values()
                for cs in node.command_stores.all_stores())
        # data-plane telemetry (tpu/verify resolvers): batching + tier
        # choices, from the unified device-metrics source (observe.device —
        # the same counters the flight recorder and bench.py report)
        from ..observe.device import cluster_resolver_totals
        tel = cluster_resolver_totals(cluster)
        if any(tel.values()):
            result.stats.update({f"resolver_{k2}": v for k2, v in tel.items()})
        if profiler is not None:
            # pull the resolver-side wall counters (consult_wall_s totals)
            profiler.collect_cluster(cluster)
        if observer is not None:
            # end-of-run pull collection: simulator stats, per-store gauges,
            # resolver counters — one registry for burns AND bench reporting
            observer.collect_cluster(cluster)
            verdict = getattr(observer, "verdict", None)
            if verdict is not None:
                result.audit = verdict()
            if audit == "strict" and getattr(observer, "violations", None):
                # belt-and-braces: a violation raised inside a callback can
                # be swallowed by on_callback_failure plumbing — a strict
                # run must STILL fail on any recorded violation
                raise observer.violations[0]
        if result.resolved < ops:
            raise HistoryViolation(
                f"only {result.resolved}/{ops} ops resolved (liveness stall): "
                f"{result!r}")
        if not allow_failures and result.ops_failed:
            raise HistoryViolation(f"{result.ops_failed} ops failed unexpectedly")
        if not chaos and not restart_nodes and not cfg.admission_enabled \
                and (result.ops_lost or result.ops_recovered
                     or (not allow_failures and result.ops_nacked)):
            # (a crashed coordinator legitimately turns acks into
            # probe-recovered / lost resolutions even on a benign network —
            # and so does an admission nack: the shed PreAccept is a partial
            # failure the client resolves through probes)
            raise HistoryViolation(
                f"benign network must ack everything: {result!r}")
        # final replica state must agree per key across replicas covering it
        # (under churn, judge against the FINAL topology's replica sets)
        final: Dict[IntKey, tuple] = {}
        for shard in cluster.topologies[-1].shards:
            lists = {}
            for n in shard.nodes:
                store = cluster.stores[n]
                for key, entries in store.data.items():
                    if shard.range.contains(key):
                        lists.setdefault(key, set()).add(tuple(v for _, v in entries))
            for key, variants in lists.items():
                longest = max(variants, key=len)
                for v in variants:
                    if v != longest[:len(v)]:
                        raise HistoryViolation(
                            f"replica divergence on {key}: {sorted(variants)}")
                final[key] = longest
        verifier.verify(final)
        # persistence contract: the journal's diff log must reconstruct every
        # store's durable command state (Journal.java reconstruct)
        if cluster.journal is not None:
            for node in cluster.nodes.values():
                for store in node.command_stores.all_stores():
                    cluster.journal.verify_against(store)
        if check == "history":
            # the independent oracle: replays the CLIENT-VISIBLE history with
            # zero protocol knowledge; raises HistoryAnomaly on any cycle
            from ..observe.checker import check_history
            result.history = check_history(
                history_rec.ops, final_state=final,
                spans=getattr(observer, "spans", None),
                provenance=getattr(observer, "provenance", None))
    except BaseException as e:  # noqa: BLE001
        if profiler is not None:
            try:
                profiler.collect_cluster(cluster)
            except Exception:  # noqa: BLE001 — never mask the real failure
                pass
        if observer is not None:
            # the recording is most valuable on a FAILED seed: pull-collect
            # the cluster gauges so the artifacts written by the CLI's
            # failure path carry the final simulator/store state too
            try:
                observer.collect_cluster(cluster)
                verdict = getattr(observer, "verdict", None)
                if verdict is not None:
                    result.audit = verdict()
            except Exception:  # noqa: BLE001 — never mask the real failure
                pass
        wrapped = SimulationException(seed, e)
        wrapped.audit = result.audit   # the verdict survives the failure path
        raise wrapped from e
    return result


def reconcile(seed: int, **kwargs):
    """Run the same seed twice and assert identical observable behavior —
    the COMPLETE message traces (every SEND/DROP/RPLY/RECV with its logical
    sequence number), plus outcome counters and message stats.  Catches
    nondeterminism itself (BurnTest.reconcile, ReconcilingLogger).  Returns
    the two BurnResults (with ``audit=...`` each run constructs its own
    auditor; the caller reads the verdicts off the results)."""
    from .trace import Trace, diff_traces
    ta, tb = Trace(), Trace()
    a = run_burn(seed, tracer=ta.hook, **kwargs)
    b = run_burn(seed, tracer=tb.hook, **kwargs)
    divergence = diff_traces(ta, tb)
    assert divergence is None, \
        f"nondeterministic trace for seed {seed} " \
        f"({len(ta)} vs {len(tb)} events):\n{divergence}"
    assert (a.ops_ok, a.ops_recovered, a.ops_nacked, a.ops_lost, a.ops_failed,
            a.sim_micros) == \
           (b.ops_ok, b.ops_recovered, b.ops_nacked, b.ops_lost, b.ops_failed,
            b.sim_micros), \
        f"nondeterministic outcome for seed {seed}: {a} vs {b}"
    # tier-choice counters are cost-model (wall-clock) driven, not sim-driven:
    # exclude them from the determinism contract (answers are tier-invariant)
    tier_keys = ("resolver_host_consults", "resolver_native_consults",
                 "resolver_device_consults", "resolver_service_submitted",
                 "resolver_service_batches")
    sa = {k: v for k, v in a.stats.items() if k not in tier_keys}
    sb = {k: v for k, v in b.stats.items() if k not in tier_keys}
    assert sa == sb, \
        f"nondeterministic message counts for seed {seed}: " \
        f"{ {k: (sa.get(k), sb.get(k)) for k in set(sa) | set(sb) if sa.get(k) != sb.get(k)} }"
    return a, b


def build_slo_specs(latency_s=None, budget=None, windows=None):
    """CLI SloSpec tuning (``--slo-latency/--slo-budget/--slo-windows``).

    ``SloSpec`` is an immutable ``__slots__`` class, so overrides rebuild the
    DEFAULT_SLOS tuple with fresh instances.  Returns None when nothing is
    overridden (callers keep the shared defaults).  ``windows`` is
    ``"short:long"`` in sim-seconds; ``latency_s`` applies to latency-kind
    specs only (liveness has no latency threshold)."""
    if latency_s is None and budget is None and windows is None:
        return None
    from ..observe.burnrate import DEFAULT_SLOS, SloSpec
    short_s = long_s = None
    if windows is not None:
        s, sep, l = str(windows).partition(":")
        if not sep:
            raise ValueError(f"--slo-windows wants SHORT:LONG sim-seconds, "
                             f"got {windows!r}")
        short_s, long_s = float(s), float(l)
    specs = []
    for spec in DEFAULT_SLOS:
        specs.append(SloSpec(
            spec.name, spec.kind,
            budget=float(budget) if budget is not None else spec.budget,
            short_s=short_s if short_s is not None else spec.short_us / 1e6,
            long_s=long_s if long_s is not None else spec.long_us / 1e6,
            burn_threshold=spec.burn_threshold,
            min_bad=spec.min_bad,
            latency_slo_us=int(float(latency_s) * 1e6)
            if latency_s is not None and spec.kind == "latency"
            else spec.latency_slo_us))
    return tuple(specs)


def _overload_observer(slo_specs, provenance=None):
    """Fresh warn-mode auditor + burn-rate monitor pair for one overload
    point (each burn needs its own: the monitors are stateful)."""
    from ..observe import BurnRateMonitor, InvariantAuditor
    monitor = BurnRateMonitor(specs=slo_specs) if slo_specs \
        else BurnRateMonitor()
    return InvariantAuditor(mode="warn", burnrate=monitor,
                            provenance=provenance), monitor


def _goodput(result) -> float:
    """Committed client ops per sim-second of OFFERED-LOAD time: commits
    that landed while arrivals were still being offered, over the
    first-to-last-arrival window.  Drain-tail commits (after the last
    arrival) are excluded from BOTH numerator and denominator — they are
    latency, not sustained throughput; the latency SLO monitors are the
    oracle for "committed but far too slow"."""
    window_us = result.stats.get("last_arrival_us", result.sim_micros)
    ok = result.stats.get("window_ok_commits",
                          result.ops_ok + result.ops_recovered)
    return ok / max(window_us / 1e6, 1e-9)


def run_overload_ramp(seed: int, kw: dict, rate_txn_s: float,
                      mults=(0.5, 1.0, 2.0, 4.0), frac: float = 0.8,
                      slo_specs=None) -> dict:
    """The metastability ramp oracle: sequential open-loop burns at each
    offered-load multiple of the estimated capacity rate.  Pass iff goodput
    at every overload point (mult > 1) holds >= ``frac`` of the 1x
    capacity-goodput — a metastable collapse shows up as goodput CRATERING
    past saturation instead of plateauing (shed ops are fast client-visible
    failures, not goodput).  ``kw`` carries the fault matrix + an
    admission/budget-enabled node_config; each point gets a fresh warn-mode
    auditor so SLO flags ride the verdict.

    The ramp clients are deliberately UNCOOPERATIVE (AIMD pacing off): a
    metastability probe must hold the offered rate no matter what the
    cluster signals, so the floor it measures is the server-side defense
    alone (admission + budgets).  The burst oracle is the cooperative-client
    counterpart — there AIMD pacing is exactly what is being demonstrated."""
    from .workload import OpenLoopWorkload
    out = {"mode": "ramp", "rate_txn_s": rate_txn_s,
           "mults": [float(m) for m in mults], "frac": frac, "points": []}
    baseline = None
    base_ops = int(kw.get("ops") or 200)
    for mult in mults:
        kw2 = dict(kw)
        observer, monitor = _overload_observer(slo_specs)
        kw2["observer"] = observer
        kw2["workload"] = OpenLoopWorkload(
            rate_txn_s=rate_txn_s * float(mult), aimd=False)
        # hold the ARRIVAL WINDOW constant across points (ops scales with
        # the rate) so every goodput measurement spans the same sim-seconds
        kw2["ops"] = max(int(base_ops * float(mult)), 20)
        r = run_burn(seed, rate_txn_s=rate_txn_s * float(mult), **kw2)
        point = {"mult": float(mult),
                 "goodput_txn_s": round(_goodput(r), 3),
                 "ok": r.ops_ok, "recovered": r.ops_recovered,
                 "failed": r.ops_failed, "shed": r.ops_shed,
                 "nacks": r.overload_nacks,
                 "budget_denied": r.budget_denied,
                 "paced": r.paced_arrivals,
                 "sim_s": round(r.sim_micros / 1e6, 2),
                 "violations": (r.audit or {}).get("violations", 0),
                 "slo_burn_events": monitor.report()["slo_burn_events"]}
        out["points"].append(point)
        if float(mult) == 1.0:
            baseline = point["goodput_txn_s"]
    over = [p for p in out["points"] if p["mult"] > 1.0]
    clean = all(p["violations"] == 0 for p in out["points"])
    if baseline and over:
        worst = min(p["goodput_txn_s"] for p in over)
        out["capacity_goodput_txn_s"] = baseline
        out["goodput_floor_frac"] = round(worst / baseline, 3)
        out["passed"] = bool(worst >= frac * baseline and clean)
    else:
        out["passed"] = clean   # no goodput comparison — audit alone
    return out


def run_overload_burst(seed: int, kw: dict, rate_txn_s: float,
                       burst_mult: float = 4.0, pre_s: float = 30.0,
                       burst_s: float = 20.0, post_s: float = 40.0,
                       frac: float = 0.8, slo_specs=None,
                       provenance=None) -> dict:
    """The burst-then-recover oracle: one open-loop burn whose offered load
    steps 1x -> ``burst_mult`` -> 1x on the deterministic LoadSpikeNemesis
    schedule.  Pass iff post-burst goodput recovers to >= ``frac`` of
    pre-burst goodput within the bounded post window AND the run ends with
    zero open SLO flags/burns — the signature of a metastable failure is
    exactly a system that does NOT recover when the trigger is removed."""
    phases = [(0.0, 1.0), (pre_s, float(burst_mult)),
              (pre_s + burst_s, 1.0)]
    # size the op count to span the whole schedule (arrivals stop at `ops`)
    ops = max(int(rate_txn_s * (pre_s + burst_s * float(burst_mult)
                                + post_s)), 50)
    kw2 = dict(kw, ops=ops, load_phases=phases)
    kw2.setdefault("workload", "openloop")
    observer, monitor = _overload_observer(slo_specs, provenance=provenance)
    kw2["observer"] = observer
    if provenance is not None:
        kw2["provenance"] = provenance
    r = run_burn(seed, rate_txn_s=rate_txn_s, **kw2)
    sim_s = r.sim_micros / 1e6
    pre_ok = r.stats.get("load_phase0_ok", 0)
    burst_ok = r.stats.get("load_phase1_ok", 0)
    post_ok = r.stats.get("load_phase2_ok", 0)
    post_dur = max(sim_s - (pre_s + burst_s), 1e-9)
    pre_goodput = pre_ok / pre_s
    post_goodput = post_ok / post_dur
    rep = monitor.report()
    open_flags = (r.audit or {}).get("slo_flags_open", 0)
    recovered = pre_goodput == 0.0 or post_goodput >= frac * pre_goodput
    out = {"mode": "burst", "rate_txn_s": rate_txn_s,
           "burst_mult": float(burst_mult), "frac": frac, "ops": ops,
           "pre_goodput_txn_s": round(pre_goodput, 3),
           "burst_goodput_txn_s": round(burst_ok / burst_s, 3),
           "post_goodput_txn_s": round(post_goodput, 3),
           "recovery_sim_s": round(post_dur, 2),
           "shed": r.ops_shed, "nacks": r.overload_nacks,
           "budget_denied": r.budget_denied, "paced": r.paced_arrivals,
           "sim_s": round(sim_s, 2),
           "slo_burn_events": rep["slo_burn_events"],
           "open_slo_burns": len(rep["open_slo_burns"]),
           "slo_flags_open": open_flags,
           "violations": (r.audit or {}).get("violations", 0),
           "passed": bool(recovered and not rep["open_slo_burns"]
                          and open_flags == 0
                          and (r.audit or {}).get("violations", 0) == 0)}
    return out


def _append_trend(record: dict) -> None:
    """Ledger a record into BENCH_HISTORY.jsonl via tools/trend.py.
    Best-effort: the ledger must never be able to fail a burn."""
    try:
        import os as _os
        import sys as _sys
        root = _os.path.dirname(_os.path.dirname(_os.path.dirname(
            _os.path.abspath(__file__))))
        if root not in _sys.path:
            _sys.path.insert(0, root)
        from tools.trend import append_entry
        append_entry(record)
    except Exception:  # noqa: BLE001 — the ledger must never fail a burn
        pass


def _sweep_worker(seed: int, kw: dict) -> dict:
    """One seed of a ``--parallel-seeds`` sweep.  Module-level so the spawn
    pool can pickle it; observer-free (the sweep is a pass/fail matrix —
    replay a failed seed singly for artifacts).  Never raises: a failure
    becomes a status entry so the cohort always completes."""
    import time as _time
    t0 = _time.perf_counter()
    entry = {"seed": seed}
    try:
        result = run_burn(seed, **kw)
        entry.update(status="pass", resolved=result.resolved,
                     ok=result.ops_ok, recovered=result.ops_recovered,
                     nacked=result.ops_nacked, lost=result.ops_lost,
                     failed=result.ops_failed, shed=result.ops_shed,
                     paced=result.paced_arrivals,
                     budget_denied=result.budget_denied,
                     sim_ms=result.sim_micros // 1000)
        if result.history is not None:
            entry["history"] = {k: result.history[k]
                                for k in ("ops", "ok", "keys", "edges")}
        if getattr(result, "audit", None) is not None:
            entry["audit"] = result.audit
    except SimulationException as e:
        entry.update(status="fail", error=str(e.cause)[:2000])
    except Exception as e:  # noqa: BLE001 — report, don't kill the pool
        entry.update(status="fail", error=repr(e)[:2000])
    entry["wall_s"] = round(_time.perf_counter() - t0, 3)
    return entry


def main(argv=None) -> None:
    """Long-running burn entry point (the reference's BurnTest main:
    ``python -m cassandra_accord_tpu.harness.burn --seeds 0:100 --ops 1000``).
    Every seed runs the full hostile matrix by default; any violation raises
    SimulationException with the seed for replay."""
    import argparse
    import time as _time
    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("--seeds", default="0:10",
                   help="seed or lo:hi range (default 0:10)")
    p.add_argument("--ops", type=int, default=1000)
    p.add_argument("--concurrency", type=int, default=20)
    p.add_argument("--rf", type=int, default=None,
                   help="replication factor (default: seeded 2-9)")
    p.add_argument("--nodes", type=int, default=None)
    p.add_argument("--resolver", default=None,
                   choices=[None, "cpu", "tpu", "verify"])
    p.add_argument("--columnar", default=None,
                   choices=[None, "auto", "on", "off"],
                   help="columnar protocol engine (protocol_batch/): "
                        "struct-of-arrays txn batches + vectorized release/"
                        "frontier/progress scans.  Trajectory-neutral by "
                        "contract (same-seed on-vs-off burns are byte-"
                        "identical); default: LocalConfig/ACCORD_COLUMNAR "
                        "(auto = on)")
    p.add_argument("--benign", action="store_true",
                   help="disable the chaos network")
    p.add_argument("--no-churn", action="store_true",
                   help="disable topology churn (churn is part of the "
                        "default hostile matrix: the reference's hardest "
                        "regime mutates topology DURING partitions)")
    p.add_argument("--elastic", action="store_true",
                   help="elastic membership: seeded join (add_node + join "
                        "epoch) and decommission (hand-off + removal from "
                        "every shard) cycles under load, plus join/leave "
                        "actions in the churn mix — all respecting the "
                        "muted-quorum floor")
    p.add_argument("--matrix", default=None, choices=["big"],
                   help="'big' = the large-cluster elastic regime: 10-20 "
                        "nodes (seeded), rf 3/5, elastic membership + the "
                        "full gray-failure matrix.  Gated behind "
                        "ACCORD_LONG_BURNS=1 (hours-class wall clock)")
    p.add_argument("--no-cache-miss", action="store_true")
    p.add_argument("--no-restart", action="store_true",
                   help="disable the crash-restart nemesis (node kills + "
                        "journal-replay rebuilds are part of the default "
                        "hostile matrix)")
    p.add_argument("--no-pause", action="store_true",
                   help="disable the pause nemesis (stop-the-world process "
                        "pauses with late-firing timers are part of the "
                        "default hostile matrix)")
    p.add_argument("--no-disk-stall", action="store_true",
                   help="disable the disk-stall nemesis (journal-append "
                        "stalls; a crash mid-stall loses the unsynced tail)")
    p.add_argument("--no-corruption", action="store_true",
                   help="disable crash-time journal damage injection "
                        "(torn tail records, bit flips)")
    p.add_argument("--corruption-policy", default=None,
                   choices=["quarantine", "halt"],
                   help="restart-replay policy for a corrupt MID-LOG record "
                        "(default: LocalConfig/ACCORD_JOURNAL_CORRUPTION)")
    p.add_argument("--restart-interval", type=float, default=None,
                   help="mean sim-seconds between crash attempts "
                        "(default: LocalConfig/ACCORD_RESTART_INTERVAL)")
    p.add_argument("--audit", default="off",
                   choices=["strict", "warn", "off"],
                   help="online protocol-invariant auditor over the flight-"
                        "recorder stream (observe/audit.py): strict raises "
                        "at the first violated invariant with the txn's "
                        "full timeline; warn records violations into the "
                        "--json verdict; SLO liveness flags are recorded "
                        "either way")
    p.add_argument("--audit-slo", type=float, default=None, metavar="SIM_S",
                   help="auditor liveness budget: flag a txn undecided this "
                        "many sim-seconds with no recovery attempt "
                        "attributed (default 10)")
    p.add_argument("--check", default="off", choices=["off", "history"],
                   help="independent history oracle (observe/checker.py): "
                        "record the client-visible invoke/ok/fail/info "
                        "history and verify strict serializability over it "
                        "with ZERO protocol knowledge — version orders from "
                        "unique write values, wr/ww/rw + real-time edges, "
                        "any cycle named (G0/G1c/G-single/G2/-realtime, "
                        "lost-update, non-repeatable-read) with the "
                        "offending sub-history.  Composes with --audit")
    p.add_argument("--workload", default=None,
                   choices=["multirange", "zipf", "openloop"],
                   help="traffic shape preset (harness/workload.py): "
                        "multirange = cross-shard txns + interactive "
                        "barriers/sync points; zipf = hot-key skew with a "
                        "mid-burn hot-range migration; openloop = Poisson "
                        "arrivals at --rate txn/s of sim-time (pair with "
                        "--burnrate: zero slo.burn events = rate sustained). "
                        "Default: the classic uniform closed-loop mix")
    p.add_argument("--rate", type=float, default=25.0, metavar="TXN_S",
                   help="openloop arrival rate, txn per sim-second "
                        "(default 25)")
    p.add_argument("--overload", default=None, choices=["ramp", "burst"],
                   help="overload-robustness oracle (implies --workload "
                        "openloop, admission control + retry budgets ON): "
                        "ramp = sequential burns at --overload-mults x "
                        "--rate, pass iff goodput past saturation holds "
                        ">= --overload-frac of the 1x capacity-goodput; "
                        "burst = one burn whose offered load steps "
                        "1x -> 4x -> 1x, pass iff post-burst goodput "
                        "recovers and zero SLO flags stay open.  Exit code "
                        "4 on acceptance failure (2 stays the stall exit)")
    p.add_argument("--overload-mults", default="0.5,1,2,4", metavar="M,M,..",
                   help="ramp offered-load multipliers (default 0.5,1,2,4)")
    p.add_argument("--overload-frac", type=float, default=0.8,
                   metavar="FRAC",
                   help="acceptance floor: overload goodput >= FRAC x "
                        "capacity-goodput (default 0.8)")
    p.add_argument("--slo-latency", type=float, default=None, metavar="SIM_S",
                   help="commit-latency SLO threshold in sim-seconds "
                        "(default 5.0) for the burn-rate monitors")
    p.add_argument("--slo-budget", type=float, default=None, metavar="FRAC",
                   help="SLO error-budget fraction in (0,1) applied to "
                        "every monitor (defaults: latency 0.05, "
                        "liveness 0.02)")
    p.add_argument("--slo-windows", default=None, metavar="SHORT:LONG",
                   help="burn-rate window pair in sim-seconds "
                        "(default 5:30)")
    p.add_argument("--parallel-seeds", type=int, default=0, metavar="N",
                   help="run the seed range across N worker processes "
                        "(spawn pool; observers/artifacts stay off in "
                        "workers) and ledger one cohort record to "
                        "BENCH_HISTORY.jsonl")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="write a machine-readable per-seed summary "
                        "(pass/stall/divergence, wall-clock, ops resolved, "
                        "faults injected) after every seed — seed-range "
                        "matrix runs diff across PRs instead of eyeballing "
                        "logs")
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="write the flight recorder's metrics-registry "
                        "snapshot (stable JSON; per-seed suffix on seed "
                        "ranges) after every seed")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="write the flight recorder's Chrome trace-event "
                        "JSON (open in Perfetto / chrome://tracing; one "
                        "track per node/store) after every seed")
    p.add_argument("--provenance", default=None, metavar="PATH",
                   help="record the causal event DAG (observe/provenance.py: "
                        "every message/handler/timer/transition with its "
                        "execution + message-chain parents) and write the "
                        "dump after every seed (per-seed suffix on seed "
                        "ranges).  Zero observer effect: the message trace "
                        "stays byte-identical.  Audit violations, history "
                        "anomalies and watchdog stall dumps gain bounded "
                        "backward causal slices; --trace-out gains causal "
                        "flow arrows")
    p.add_argument("--explain-vs", default=None, metavar="PROV_JSON",
                   help="divergence forensics: after the run, align this "
                        "run's causal DAG against a reference --provenance "
                        "dump and report the causally-first divergent event "
                        "+ its ancestor cone (implies provenance recording; "
                        "single seed only)")
    p.add_argument("--timeline-out", default=None, metavar="PATH",
                   help="write the sim-time windowed-telemetry JSONL "
                        "(observe/timeline.py: per-window commits/s + "
                        "latency p50/p95/p99 + in-flight + message rates, "
                        "plus consult-service trajectory windows) after "
                        "every seed; also adds a per-window counter track "
                        "to --trace-out")
    p.add_argument("--timeline-window", type=float, default=1.0,
                   metavar="SIM_S",
                   help="timeline window width in sim-seconds (default 1.0)")
    p.add_argument("--burnrate", action="store_true",
                   help="multi-window SLO burn-rate monitors "
                        "(observe/burnrate.py) over commit latency and the "
                        "auditor's liveness-flag plane: deterministic "
                        "slo.burn events land in the --json audit verdict "
                        "and the watchdog stall dump — mid-run early "
                        "warning for soak burns (implies --audit=warn when "
                        "auditing is off: the liveness-flag plane feeds "
                        "the monitors)")
    p.add_argument("--profile", action="store_true",
                   help="two-plane performance profile per seed: the "
                        "sim-time critical-path latency budget (which "
                        "segment classes a commit's life is spent in — "
                        "observe/critical_path.py) and the wall-clock "
                        "profile (per-message-type handler CPU, event-loop "
                        "occupancy, device launch breakdown — "
                        "observe/profiler.py).  Rides the flight recorder; "
                        "zero effect on the recorded trace.  With --json "
                        "both reports land in the per-seed entry; with "
                        "--trace-out the wall handler tracks + txn flow "
                        "links are embedded in the Perfetto trace")
    p.add_argument("--progress", type=float, default=None, metavar="SIM_S",
                   help="heartbeat: one progress line (resolved, in-flight, "
                        "fast-path %%) per SIM_S sim-seconds")
    p.add_argument("--no-watchdog", action="store_true",
                   help="disable the stall watchdog (on stall it dumps the "
                        "wait graph + status frontier and exits nonzero)")
    p.add_argument("--watchdog-stall", type=float, default=None,
                   help="sim-seconds without a resolved op before the "
                        "watchdog fires (default: LocalConfig)")
    p.add_argument("--reconcile", action="store_true",
                   help="double-run each seed and diff full traces")
    args = p.parse_args(argv)
    from dataclasses import replace as _replace
    from ..config import LocalConfig
    from .watchdog import StallError
    cfg = LocalConfig.from_env()
    if args.restart_interval is not None:
        cfg = _replace(cfg, restart_interval_s=args.restart_interval)
    if args.no_corruption:
        cfg = _replace(cfg, journal_torn_tail_chance=0.0,
                       journal_corrupt_chance=0.0)
    if args.corruption_policy is not None:
        cfg = _replace(cfg, journal_corruption_policy=args.corruption_policy)
    watchdog_s = None
    if not args.no_watchdog:
        watchdog_s = args.watchdog_stall if args.watchdog_stall is not None \
            else cfg.stall_watchdog_after_s
    # --slo-* overrides rebuild the DEFAULT_SLOS tuple (None = defaults)
    slo_specs = build_slo_specs(args.slo_latency, args.slo_budget,
                                args.slo_windows)
    if args.matrix == "big":
        import os as _os
        if "ACCORD_LONG_BURNS" not in _os.environ:
            raise SystemExit("--matrix big is an hours-class run: set "
                             "ACCORD_LONG_BURNS=1 to confirm")
        args.elastic = True
    lo, _, hi = args.seeds.partition(":")
    seeds = range(int(lo), int(hi) + 1) if hi else [int(lo)]
    summaries: list = []

    def artifact_path(path: str, seed: int) -> str:
        """Per-seed artifact name on seed ranges; the exact path otherwise."""
        if len(seeds) == 1:
            return path
        import os.path as _p
        stem, ext = _p.splitext(path)
        return f"{stem}.seed{seed}{ext or '.json'}"

    if args.reconcile and (args.metrics_out or args.trace_out or args.profile
                           or args.timeline_out or args.burnrate
                           or args.provenance or args.explain_vs):
        # reconcile runs two bare runs per seed and diffs them; a flight
        # recorder would conflate both into one recording — say so up front
        # instead of silently never writing the files
        print("warning: --metrics-out/--trace-out/--profile/--timeline-out/"
              "--burnrate/--provenance/--explain-vs are ignored with "
              "--reconcile (no artifacts/profiles will be produced)",
              flush=True)

    if args.explain_vs and len(seeds) != 1 and not args.reconcile:
        raise SystemExit("--explain-vs compares ONE run against ONE "
                         f"reference dump (got --seeds {args.seeds})")

    if args.burnrate and args.audit == "off" and not args.reconcile:
        # the monitors' liveness plane burns on the auditor's SLO-flag
        # openings, and the --json burnrate report rides the audit verdict —
        # without the auditor a total wedge starves BOTH monitor streams and
        # nothing ever fires.  --burnrate therefore implies the warn plane.
        print("note: --burnrate implies --audit=warn (the liveness-flag "
              "plane feeds the monitors and carries their report)",
              flush=True)
        args.audit = "warn"

    def write_json() -> None:
        if args.json is None:
            return
        import json as _json
        doc = {"ops": args.ops, "concurrency": args.concurrency,
               "seeds": args.seeds, "benign": args.benign,
               "results": summaries}
        with open(args.json, "w") as f:
            _json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")

    _FAULT_KEYS = ("node_crashes", "node_restarts", "node_pauses",
                   "journal_stalls", "journal_unsynced_lost",
                   "journal_injected_tears", "journal_injected_bitflips",
                   "journal_torn_records", "journal_quarantined_txns",
                   "node_joins", "node_decommissions")
    def base_kw(seed: int):
        """Seeded per-seed run_burn kwargs — shared by the inline loop and
        the --parallel-seeds pool, so everything here must stay picklable."""
        if args.matrix == "big":
            # the large-cluster regime: 10-20 nodes, rf 3/5, seeded per seed
            srng = RandomSource(seed)
            rf = args.rf if args.rf is not None else srng.pick([3, 3, 5])
            if args.nodes is None:
                args_nodes = srng.next_int(10, 21)
            else:
                args_nodes = args.nodes
        else:
            rf = args.rf if args.rf is not None \
                else 2 + RandomSource(seed).next_int(8)
            args_nodes = args.nodes
        kw = dict(ops=args.ops, concurrency=args.concurrency, rf=rf,
                  nodes=args_nodes, resolver=args.resolver,
                  chaos=not args.benign, allow_failures=not args.benign,
                  topology_churn=not args.no_churn,
                  elastic_membership=bool(args.elastic),
                  durability=True, journal=True,
                  delayed_stores=not args.benign, clock_drift=not args.benign,
                  cache_miss=not args.no_cache_miss,
                  restart_nodes=not args.no_restart,
                  pause_nodes=not args.no_pause,
                  disk_stall=not args.no_disk_stall,
                  stall_watchdog_s=watchdog_s,
                  columnar=args.columnar,
                  check=args.check,
                  workload=args.workload,
                  rate_txn_s=args.rate,
                  node_config=cfg,
                  max_tasks=200_000_000)
        return rf, kw

    if args.overload:
        if args.workload not in (None, "openloop"):
            raise SystemExit("--overload drives the openloop workload "
                             f"(got --workload {args.workload})")
        if args.reconcile or args.parallel_seeds > 1:
            raise SystemExit("--overload does not compose with --reconcile/"
                             "--parallel-seeds (the oracle is itself a "
                             "multi-burn schedule)")
        # the defense under test: admission control + retry budgets ON
        ov_cfg = _replace(cfg, admission_enabled=True,
                          retry_budget_enabled=True)
        try:
            mults = tuple(float(m) for m in args.overload_mults.split(",")
                          if m.strip())
        except ValueError:
            raise SystemExit(f"--overload-mults wants comma-separated "
                             f"floats, got {args.overload_mults!r}")
        failures = 0
        for seed in seeds:
            _rf, kw = base_kw(seed)
            kw.update(workload="openloop", node_config=ov_cfg,
                      allow_failures=True)
            kw.pop("rate_txn_s", None)   # the oracle sets the rate per point
            if args.audit != "off":
                kw["audit"] = args.audit
                kw["audit_slo_s"] = args.audit_slo
            t0 = _time.perf_counter()
            entry = {"seed": seed, "overload": args.overload,
                     "rate_txn_s": args.rate}
            summaries.append(entry)
            prov = None
            if args.provenance and args.overload == "burst":
                # one recorder per burst burn; the ramp oracle runs several
                # burns per point and would conflate them into one DAG
                from ..observe import ProvenanceRecorder
                prov = ProvenanceRecorder()
            elif args.provenance and args.overload == "ramp":
                print("warning: --provenance is ignored with --overload "
                      "ramp (multi-burn schedule)", flush=True)
            try:
                if args.overload == "ramp":
                    out = run_overload_ramp(
                        seed, kw, args.rate, mults=mults,
                        frac=args.overload_frac, slo_specs=slo_specs)
                else:
                    out = run_overload_burst(
                        seed, kw, args.rate, frac=args.overload_frac,
                        slo_specs=slo_specs, provenance=prov)
            except SimulationException as e:
                entry.update(status="fail", error=str(e.cause)[:2000],
                             wall_s=round(_time.perf_counter() - t0, 3))
                if prov is not None:
                    # the DAG up to the failure point IS the forensic artifact
                    prov.save(artifact_path(args.provenance, seed))
                write_json()
                if isinstance(e.cause, StallError):
                    print(f"seed {seed}: STALL during --overload "
                          f"{args.overload}\n{e.cause.dump}")
                    raise SystemExit(2)
                raise
            entry.update(status="pass" if out["passed"] else
                         "overload_failed",
                         wall_s=round(_time.perf_counter() - t0, 3),
                         result=out)
            if prov is not None:
                prov.save(artifact_path(args.provenance, seed))
            if args.overload == "ramp":
                metric, value = ("goodput_floor_frac",
                                 out.get("goodput_floor_frac"))
            else:
                metric, value = ("recovery_sim_s", out.get("recovery_sim_s"))
            _append_trend({"kind": "overload", "metric": metric,
                           "value": value, "unit": "frac"
                           if args.overload == "ramp" else "s",
                           "mode": args.overload, "seeds": [seed],
                           "rate_txn_s": args.rate,
                           "capacity_goodput_txn_s":
                           out.get("capacity_goodput_txn_s",
                                   out.get("pre_goodput_txn_s")),
                           "shed": out.get("shed", sum(
                               p["shed"] for p in out.get("points", []))),
                           "budget_denied": out.get("budget_denied", sum(
                               p["budget_denied"]
                               for p in out.get("points", []))),
                           "paced": out.get("paced", sum(
                               p["paced"] for p in out.get("points", []))),
                           "passed": out["passed"]})
            print(f"seed {seed}: overload {args.overload} "
                  f"{'PASS' if out['passed'] else 'FAIL'} "
                  f"({_time.perf_counter() - t0:.1f}s) {out}", flush=True)
            if not out["passed"]:
                failures += 1
        write_json()
        if failures:
            # distinct exit code: the cluster survived (no stall, no
            # violation) but FAILED the overload acceptance bar
            raise SystemExit(4)
        return

    if args.parallel_seeds > 1:
        if args.reconcile:
            raise SystemExit("--parallel-seeds does not compose with "
                             "--reconcile (run the sweep, replay failed "
                             "seeds singly)")
        if (args.metrics_out or args.trace_out or args.profile
                or args.timeline_out or args.provenance or args.explain_vs):
            print("warning: per-seed artifacts are skipped under "
                  "--parallel-seeds (workers run observer-free)", flush=True)
        import multiprocessing as _mp
        t0 = _time.perf_counter()
        jobs = []
        for seed in seeds:
            _rf, kw = base_kw(seed)
            if args.audit != "off":
                # run_burn constructs its own auditor per worker; the mode
                # string is picklable where an InvariantAuditor is not
                kw["audit"] = args.audit
                kw["audit_slo_s"] = args.audit_slo
            jobs.append((seed, kw))
        ctx = _mp.get_context("spawn")   # no inherited simulator/device state
        with ctx.Pool(processes=args.parallel_seeds) as pool:
            results = pool.starmap(_sweep_worker, jobs)
        wall = round(_time.perf_counter() - t0, 3)
        summaries.extend(results)
        n_pass = sum(1 for r in results if r["status"] == "pass")
        _append_trend({"kind": "burn_sweep", "metric": "sweep_wall_s",
                       "value": wall, "unit": "s",
                       "seeds": [int(s) for s in seeds], "ops": args.ops,
                       "workers": args.parallel_seeds,
                       "workload": args.workload, "check": args.check,
                       "audit": args.audit, "benign": bool(args.benign),
                       "passed": n_pass,
                       "failed": len(results) - n_pass})
        write_json()
        for r in results:
            line = f"seed {r['seed']}: {r['status']} ({r['wall_s']}s)"
            if r["status"] != "pass":
                line += f" — {r.get('error', '')[:200]}"
            print(line, flush=True)
        print(f"sweep: {n_pass}/{len(results)} passed in {wall}s "
              f"({args.parallel_seeds} workers)", flush=True)
        if n_pass != len(results):
            raise SystemExit(1)
        return

    for seed in seeds:
        rf, kw = base_kw(seed)
        observer = None
        # per-seed trajectory planes: windowed sim-time telemetry
        # (--timeline-out) and the multi-window SLO burn-rate monitors
        # (--burnrate) — both ride whichever recorder/auditor is built below
        timeline = None
        if args.timeline_out and not args.reconcile:
            from ..observe import Timeline
            timeline = Timeline(window_us=int(args.timeline_window * 1e6))
        monitor = None
        if args.burnrate and not args.reconcile:
            from ..observe import BurnRateMonitor
            monitor = BurnRateMonitor(specs=slo_specs) if slo_specs \
                else BurnRateMonitor()
        prov = None
        if (args.provenance or args.explain_vs) and not args.reconcile:
            from ..observe import ProvenanceRecorder
            prov = ProvenanceRecorder()
            kw["provenance"] = prov
        if args.audit != "off" and not args.reconcile:
            # the auditor IS a FlightRecorder, so it also serves
            # --metrics-out/--trace-out (reconcile runs construct their own
            # auditor per run inside run_burn — audit composes with
            # --reconcile, artifacts do not)
            from ..observe import InvariantAuditor
            observer = InvariantAuditor(
                mode=args.audit, slo_unattended_s=args.audit_slo,
                record_messages=bool(args.trace_out or args.profile),
                timeline=timeline, burnrate=monitor, provenance=prov)
            kw["observer"] = observer
            kw["audit"] = args.audit
        elif args.audit != "off" and args.reconcile:
            kw["audit"] = args.audit
            kw["audit_slo_s"] = args.audit_slo
        elif (args.metrics_out or args.trace_out or args.profile
              or args.timeline_out or args.burnrate or prov is not None) \
                and not args.reconcile:
            # flight recorder (reconcile runs its own two bare runs: the
            # recorder would conflate them, so it stays off there — warned
            # once before the loop).  --profile keeps the message timeline:
            # the critical-path extractor uses PreAccept RECV events to
            # split network wait from replica queueing
            from ..observe import FlightRecorder
            observer = FlightRecorder(
                record_messages=bool(args.trace_out or args.profile),
                timeline=timeline, burnrate=monitor, provenance=prov)
            kw["observer"] = observer
        profiler = None
        if args.profile and not args.reconcile:
            from ..observe import WallProfiler
            profiler = WallProfiler()
            kw["profiler"] = profiler
        if args.progress:
            kw.update(progress_every_s=args.progress,
                      progress_label=f"seed {seed}")

        def write_artifacts(observer=observer, seed=seed, profiler=profiler,
                            prov=prov):
            if args.provenance and prov is not None:
                prov.save(artifact_path(args.provenance, seed))
            if observer is None:
                return
            import json as _json
            if args.metrics_out:
                with open(artifact_path(args.metrics_out, seed), "w") as f:
                    _json.dump(observer.metrics_snapshot(), f, indent=2,
                               sort_keys=True)
                    f.write("\n")
            if args.trace_out:
                # the wall handler tracks + sim→wall txn flow links ride
                # along whenever the profiler ran
                observer.write_trace(artifact_path(args.trace_out, seed),
                                     profiler=profiler)
            if args.timeline_out and getattr(observer, "timeline", None) \
                    is not None:
                observer.write_timeline(
                    artifact_path(args.timeline_out, seed))

        def profile_reports(entry, observer=observer, profiler=profiler,
                            seed=seed):
            """--profile: compute/print both planes, enrich the --json entry.
            Runs on success AND failure (the budget of a stalled seed is the
            forensic artifact)."""
            if profiler is None or observer is None:
                return
            from ..observe import format_budget, format_wall_profile
            budget = observer.latency_budget()
            wall = profiler.report()
            entry["latency_budget"] = budget
            entry["wall_profile"] = wall
            print(format_budget(budget, label=f"seed {seed}"), flush=True)
            print(format_wall_profile(wall, label=f"seed {seed}"), flush=True)

        def explain_report(entry, prov=prov, seed=seed):
            """--explain-vs: align this run's causal DAG against the
            reference --provenance dump.  Prints the human forensics report
            (causally-first divergent event + ancestor cone back to the
            originating decision) and embeds the machine-readable core in
            the --json entry.  Runs on success AND failure."""
            if args.explain_vs is None or prov is None:
                return
            from ..observe import ProvenanceRecorder, explain_divergence
            ref = ProvenanceRecorder.load(args.explain_vs)
            rep = explain_divergence(ref, prov)
            if rep is None:
                entry["explain"] = None
                print(f"seed {seed}: causal DAG identical to reference "
                      f"{args.explain_vs}", flush=True)
                return
            entry["explain"] = {k: v for k, v in rep.items() if k != "text"}
            print(rep["text"], flush=True)
        t0 = _time.perf_counter()
        entry = {"seed": seed, "rf": rf, "ops": args.ops}
        summaries.append(entry)
        try:
            if args.reconcile:
                ra, _rb = reconcile(seed, **kw)
                entry.update(status="pass", reconciled=True,
                             wall_s=round(_time.perf_counter() - t0, 3))
                if getattr(ra, "audit", None) is not None:
                    # warn-mode verdicts must not be silently dropped: the
                    # runs are trace-identical, so one verdict speaks for both
                    entry["audit"] = ra.audit
                write_json()
                print(f"seed {seed}: reconciled (rf={rf}, "
                      f"{_time.perf_counter() - t0:.1f}s)")
            else:
                result = run_burn(seed, **kw)
                entry.update(
                    status="pass", wall_s=round(_time.perf_counter() - t0, 3),
                    resolved=result.resolved, ok=result.ops_ok,
                    recovered=result.ops_recovered, nacked=result.ops_nacked,
                    lost=result.ops_lost, failed=result.ops_failed,
                    shed=getattr(result, "ops_shed", 0),
                    paced=getattr(result, "paced_arrivals", 0),
                    budget_denied=getattr(result, "budget_denied", 0),
                    sim_ms=result.sim_micros // 1000,
                    faults={k: result.stats[k] for k in _FAULT_KEYS
                            if result.stats.get(k)})
                if observer is not None:
                    # --json enrichment: the cluster-scope registry (outcome
                    # partition, path split, recovery/timeout counters)
                    entry["metrics"] = \
                        observer.metrics_snapshot().get("cluster", {})
                if getattr(result, "audit", None) is not None:
                    # per-seed audit verdict: violations + SLO flags
                    entry["audit"] = result.audit
                if getattr(result, "history", None) is not None:
                    # the oracle's clean-run summary (op/edge counts); any
                    # anomaly would have raised HistoryAnomaly instead
                    entry["history"] = {k: result.history[k]
                                        for k in ("ops", "ok", "keys",
                                                  "edges")}
                if args.workload == "openloop" and monitor is not None:
                    # the open-loop SLO preset's verdict: sustained = the
                    # arrival rate was held for the whole burn with zero
                    # slo.burn events — ledgered as the workload_slo series
                    rep = monitor.report()
                    events = rep.get("slo_burn_events", 0)
                    slo_rec = {"kind": "workload_slo",
                               "metric": "slo_burn_events", "value": events,
                               "slo_burn_events": events,
                               "unit": "events", "workload": "openloop",
                               "seeds": [seed], "ops": args.ops,
                               "rate_txn_s": args.rate,
                               "sim_minutes": round(
                                   result.sim_micros / 60e6, 2),
                               "sustained": events == 0}
                    _append_trend(slo_rec)
                    entry["workload_slo"] = slo_rec
                profile_reports(entry)
                explain_report(entry)
                write_artifacts()
                write_json()
                print(f"seed {seed}: {result!r} (rf={rf}, "
                      f"{_time.perf_counter() - t0:.1f}s)")
        except SimulationException as e:
            from ..observe.audit import AuditViolation
            from ..observe.checker import HistoryAnomaly
            if isinstance(e.cause, AuditViolation):
                status = "audit_violation"
            elif isinstance(e.cause, HistoryAnomaly):
                status = "history_anomaly"
            elif isinstance(e.cause, StallError):
                status = "stall"
            elif isinstance(e.cause, HistoryViolation) \
                    and "divergence" in str(e.cause):
                status = "divergence"
            else:
                status = "fail"
            entry.update(status=status,
                         wall_s=round(_time.perf_counter() - t0, 3),
                         error=str(e.cause)[:2000])
            if isinstance(e.cause, HistoryAnomaly):
                # the structured report (named anomalies + sub-histories +
                # flight-recorder timelines) for machine diffing
                entry["history"] = e.cause.report
            if e.audit is not None:
                entry["audit"] = e.audit
            # the flight recording is MOST valuable on a failed seed: write
            # whatever was captured up to the failure point
            try:
                profile_reports(entry)
            except Exception:  # noqa: BLE001 — never mask the real failure
                pass
            try:
                # forensics on the FAILED trajectory: where did this run
                # causally depart from the reference?
                explain_report(entry)
            except Exception:  # noqa: BLE001 — never mask the real failure
                pass
            write_artifacts()
            write_json()
            if isinstance(e.cause, StallError):
                # actionable stall artifact for CI / seed-range sweeps: the
                # wait-graph + status-frontier dump, then a nonzero exit —
                # never rely on an external `timeout` kill for this signal
                print(f"seed {seed}: STALL after "
                      f"{_time.perf_counter() - t0:.1f}s\n{e.cause.dump}")
                raise SystemExit(2)
            raise
    write_json()


if __name__ == "__main__":
    main()
