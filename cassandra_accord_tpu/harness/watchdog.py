"""Stall watchdog: turn silent liveness stalls into actionable artifacts.

When a burn stops resolving ops for ``stalled_after_s`` of sim-time, the
watchdog raises ``StallError`` carrying a full wait-state dump — per-node /
per-store status frontiers, every blocked txn with the dependency ids it is
waiting on, progress-log monitor sets, pending-bootstrap and stale ranges,
and the device execution frontier where a device resolver is attached.  This
is the diagnostic the PRE_APPLIED-backlog investigation (KNOWN_ISSUES) needs:
CI and seed-range sweeps get the wait graph instead of a bare ``timeout``
kill.
"""
from __future__ import annotations

from typing import Callable, List

from .cluster import Cluster

_MAX_BLOCKED_PER_STORE = 24   # dump bound; the stall root is always among the
                              # oldest blocked ids, listed first
_TIMELINE_DUMP_WINDOWS = 12   # last-N telemetry windows embedded in a stall
                              # dump: the trajectory INTO the stall


class StallError(Exception):
    """A burn stopped making progress; ``dump`` holds the wait-state report."""

    def __init__(self, message: str, dump: str):
        super().__init__(f"{message}\n{dump}")
        self.dump = dump


def dump_wait_state(cluster: Cluster) -> str:
    """Render the cluster's host/device wait graphs + per-node status
    frontier.  Names every blocked txn id and what it waits on."""
    from ..local.status import SaveStatus
    lines: List[str] = []
    stall_roots: List[tuple] = []   # (txn_id, node, store) slice anchors —
                                    # the oldest blocked txn per store
    stalled = sorted(n for n in cluster.nodes
                     if cluster.journal is not None
                     and cluster.journal.is_stalled(n))
    lines.append(f"sim_time_s={cluster.now_micros / 1e6:.3f} "
                 f"down_nodes={sorted(cluster.down)} "
                 f"paused_nodes={sorted(cluster.paused)} "
                 f"stalled_journals={stalled} "
                 f"epoch={cluster.topologies[-1].epoch}")
    for node_id in sorted(cluster.nodes):
        node = cluster.nodes[node_id]
        for store in node.command_stores.all_stores():
            counts: dict = {}
            blocked = []
            max_applied = None
            for txn_id, cmd in store.commands.items():
                counts[cmd.save_status.name] = counts.get(cmd.save_status.name, 0) + 1
                if cmd.save_status is SaveStatus.APPLIED and (
                        max_applied is None or txn_id > max_applied):
                    max_applied = txn_id
                if cmd.waiting_on is not None and cmd.waiting_on.is_waiting():
                    blocked.append((txn_id, cmd))
            lines.append(
                f"node {node_id} store {store.id}: frontier={counts} "
                f"max_applied={max_applied} cold={len(store.cold)} "
                f"pending_bootstrap={store.pending_bootstrap!r} "
                f"stale={cluster.stores[node_id].stale_ranges!r}")
            blocked.sort(key=lambda p: p[0])
            if blocked:
                stall_roots.append((blocked[0][0], node_id, store.id))
            for txn_id, cmd in blocked[:_MAX_BLOCKED_PER_STORE]:
                waits = sorted(cmd.waiting_on.waiting)
                lines.append(
                    f"  BLOCKED {txn_id} [{cmd.save_status.name}] "
                    f"waiting_on={waits[:12]}"
                    + (f" (+{len(waits) - 12} more)" if len(waits) > 12 else ""))
            if len(blocked) > _MAX_BLOCKED_PER_STORE:
                lines.append(f"  ... {len(blocked) - _MAX_BLOCKED_PER_STORE} "
                             f"more blocked txns")
            pl = store.progress_log
            if hasattr(pl, "coordinating"):
                lines.append(
                    f"  progress_log: coordinating={sorted(pl.coordinating)[:12]} "
                    f"blocking={sorted(pl.blocking)[:12]} "
                    f"non_home={len(pl.non_home)}")
            resolver = getattr(store.resolver, "tpu", store.resolver)
            frontier_ready = getattr(resolver, "frontier_ready", None)
            if frontier_ready is not None:
                try:
                    ready = sorted(frontier_ready())
                    lines.append(f"  device_frontier_ready={ready[:12]}"
                                 + (f" (+{len(ready) - 12} more)"
                                    if len(ready) > 12 else ""))
                except Exception as e:  # noqa: BLE001 — diagnostics must not mask the stall
                    lines.append(f"  device_frontier_ready=<error {e!r}>")
    observer = getattr(cluster, "observer", None)
    if observer is not None:
        # metrics snapshot section (flight recorder): the full registry —
        # message counts, lifecycle transitions, recovery attribution — in
        # the same artifact CI already captures for stalls
        try:
            lines.append("metrics: " + observer.registry_json(cluster))
        except Exception as e:  # noqa: BLE001 — diagnostics must not mask the stall
            lines.append(f"metrics: <error {e!r}>")
        # audit section (InvariantAuditor): the open liveness-SLO flags name
        # the exact txns a stall is stuck on — read this BEFORE the wait
        # graph; the flagged ids are usually the roots
        report = getattr(observer, "audit_report", None)
        if report is not None:
            try:
                lines.append("audit: " + report())
            except Exception as e:  # noqa: BLE001 — diagnostics must not mask the stall
                lines.append(f"audit: <error {e!r}>")
        # burn-rate section (observe/burnrate.py): a monitor that fired
        # mid-run DATED the degradation — its sim timestamps bound when the
        # wedge began, long before this dump's final state
        monitor = getattr(observer, "burnrate", None)
        if monitor is not None and monitor.events:
            import json as _json
            try:
                lines.append("slo_burn: " + _json.dumps(
                    monitor.events[-8:], sort_keys=True, default=str))
            except Exception as e:  # noqa: BLE001 — diagnostics must not mask the stall
                lines.append(f"slo_burn: <error {e!r}>")
        # timeline section (observe/timeline.py): the last-N telemetry
        # windows — windowed commits/s, latency percentiles, in-flight —
        # i.e. the TRAJECTORY into the stall, not just the end snapshot
        timeline = getattr(observer, "timeline", None)
        if timeline is not None:
            import json as _json
            try:
                recs = timeline.records(include_open=True)
                lines.append("timeline: " + _json.dumps(
                    recs[-_TIMELINE_DUMP_WINDOWS:], sort_keys=True,
                    default=str))
            except Exception as e:  # noqa: BLE001 — diagnostics must not mask the stall
                lines.append(f"timeline: <error {e!r}>")
        # provenance section (observe/provenance.py): the bounded backward
        # causal slice of each store's oldest blocked txn — how the wedge
        # was REACHED (handlers, timers, timeouts), not just what it waits on
        prov = getattr(observer, "provenance", None)
        if prov is not None:
            import json as _json
            try:
                slices = {}
                for txn_id, node_id, store_id in stall_roots[:4]:
                    sl = prov.slice_for(txn_id=txn_id, node=node_id,
                                        store=store_id)
                    if sl is not None:
                        slices[str(txn_id)] = sl
                lines.append("provenance: " + _json.dumps(
                    {"stall_root_slices": slices,
                     "tail": prov.tail_summary()}, sort_keys=True,
                    default=str))
            except Exception as e:  # noqa: BLE001 — diagnostics must not mask the stall
                lines.append(f"provenance: <error {e!r}>")
    return "\n".join(lines)


class StallWatchdog:
    """Recurring (sim-time) progress check over a monotonic counter."""

    def __init__(self, cluster: Cluster, progress_fn: Callable[[], int],
                 stalled_after_s: float = 120.0, interval_s: float = 5.0):
        self.cluster = cluster
        self.progress_fn = progress_fn
        self.stalled_after_s = stalled_after_s
        self.interval_s = interval_s
        self._last_progress = progress_fn()
        self._last_change_us = cluster.now_micros
        self._task = None

    def attach(self) -> None:
        self._task = self.cluster.scheduler.recurring(self.interval_s, self.check)

    def cancel(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    def check(self) -> None:
        progress = self.progress_fn()
        now = self.cluster.now_micros
        if progress != self._last_progress:
            self._last_progress = progress
            self._last_change_us = now
            return
        stalled_s = (now - self._last_change_us) / 1e6
        if stalled_s >= self.stalled_after_s:
            raise StallError(
                f"no progress for {stalled_s:.1f}s of sim-time "
                f"(progress counter stuck at {progress})",
                dump_wait_state(self.cluster))
