"""Workload shapes for the burn harness (ROADMAP item 4a-c).

The default burn workload is a closed-loop uniform/zipf single-range
read/write mix.  This module grows the axis the fault matrix was missing —
the traffic SHAPES production clusters actually generate — as pluggable
presets behind ``run_burn(workload=...)`` / ``burn --workload``:

- ``multirange``  multi-range transactions (keys spread across shards, 2-4
  range reads) plus INTERACTIVE operations driven through the coordinate
  surface: barriers (LOCAL / GLOBAL_ASYNC / GLOBAL_SYNC over keys and
  ranges) and inclusive sync points — under whatever fault matrix the burn
  runs (the elastic+hostile regime is the target).
- ``zipf``        Zipf-skewed key selection (theta=0.99: a hot head) with a
  MID-BURN HOT-RANGE MIGRATION: at the half-way op the hot ranks rotate to
  the far side of the keyspace, moving the contention point across shard
  boundaries while in-flight txns still target the old one.
- ``openloop``    open-loop Poisson arrivals at a target rate (txn/s of
  SIM-time): the client submits at the drawn instants no matter what is in
  flight — the regime where queueing collapses show up as latency-SLO burn
  (the PR-10 burn-rate monitors are the pass/fail oracle; zero ``slo.burn``
  events = the rate was sustained).

Determinism contract: every preset draws ONLY from the RandomSource the
harness hands it at bind time (a fork of the burn's seeded stream), so a
seed fully determines the workload; ``workload=None`` leaves the original
inline generation untouched (byte-identical trajectories for every existing
seed).
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

from ..api.interfaces import BarrierType
from ..impl.list_store import list_txn, range_read_txn
from ..primitives.keys import IntKey, Keys, Range, Ranges


class WorkloadOp:
    """One generated client operation.

    ``control`` is None for a data txn (``txn`` set), else a tuple:
    ``("barrier", barrier_type, seekables)`` or ``("sync_point", seekables)``
    — executed through the node's coordinate surface, with no data payload.
    """

    __slots__ = ("txn", "read_keys", "writes", "control")

    def __init__(self, txn=None, read_keys: Tuple = (),
                 writes: Optional[Dict] = None, control=None):
        self.txn = txn
        self.read_keys = tuple(read_keys)
        self.writes = dict(writes or {})
        self.control = control


class Workload:
    """Base preset: bind once per burn, then generate ops by id."""

    name = "workload"
    open_loop = False

    def __init__(self):
        self.counts: Dict[str, int] = {}
        self.rng = None
        self.key_count = 0
        self.bound = 1000
        self.ops = 0

    def bind(self, rng, key_count: int, bound: int, ops: int) -> None:
        self.rng = rng
        self.key_count = key_count
        self.bound = bound
        self.ops = ops

    def _count(self, kind: str) -> None:
        self.counts[kind] = self.counts.get(kind, 0) + 1

    def _key(self, idx: int) -> IntKey:
        return IntKey((idx * self.bound) // self.key_count)

    def _list_op(self, op_id: int, keys) -> WorkloadOp:
        keys = sorted(set(keys))
        kind = self.rng.pick(["read", "write", "rw", "rw"])
        reads = keys if kind in ("read", "rw") else []
        writes = {key: f"v{op_id}.{ki}" for ki, key in enumerate(keys)} \
            if kind in ("write", "rw") else {}
        return WorkloadOp(txn=list_txn(reads, writes),
                          read_keys=tuple(reads), writes=writes)

    def next_op(self, op_id: int) -> WorkloadOp:
        raise NotImplementedError


class MultiRangeWorkload(Workload):
    """Cross-shard txns + interactive barrier/sync-point traffic."""

    name = "multirange"

    def next_op(self, op_id: int) -> WorkloadOp:
        rng = self.rng
        u = rng.next_float()
        if u < 0.10:
            # interactive barrier: local or global, over a key or ranges
            btype = rng.pick([BarrierType.LOCAL, BarrierType.GLOBAL_ASYNC,
                              BarrierType.GLOBAL_SYNC])
            if rng.next_boolean():
                seekables = Keys.of([self._key(rng.next_int(self.key_count))])
            else:
                seekables = Ranges.of(*self._ranges(1 + rng.next_int(2)))
            self._count("barrier")
            return WorkloadOp(control=("barrier", btype, seekables))
        if u < 0.18:
            # inclusive sync point over ranges (non-blocking coordination)
            seekables = Ranges.of(*self._ranges(1 + rng.next_int(2)))
            self._count("sync_point")
            return WorkloadOp(control=("sync_point", seekables))
        if u < 0.40:
            # multi-range read: 2-4 ranges
            self._count("range_read")
            rngs = self._ranges(2 + rng.next_int(3))
            return WorkloadOp(txn=range_read_txn(Ranges.of(*rngs)))
        # cross-shard key txn: 2-5 keys strided across the keyspace so they
        # land in DIFFERENT shards whenever the topology has several
        self._count("multirange_txn")
        n = 2 + rng.next_int(4)
        base = rng.next_int(self.key_count)
        stride = max(1, self.key_count // n)
        keys = [self._key((base + j * stride) % self.key_count)
                for j in range(n)]
        return self._list_op(op_id, keys)

    def _ranges(self, n: int):
        out = []
        for _ in range(n):
            start = self.rng.next_int(self.bound - 1)
            width = 1 + self.rng.next_int(self.bound // 2)
            out.append(Range(IntKey(start),
                             IntKey(min(self.bound, start + width))))
        return out


class ZipfWorkload(Workload):
    """Zipf-skewed keys with a mid-burn hot-range migration."""

    name = "zipf"

    def __init__(self, theta: float = 0.99, migrate_at: float = 0.5):
        super().__init__()
        self.theta = theta
        self.migrate_at = migrate_at
        self.key_log = []   # (op_id, key_index) — migration forensics

    def _zipf_key_index(self, op_id: int) -> int:
        # rank 0 is the hottest key; before the migration point ranks map to
        # the LOW end of the keyspace (clustered in the first shard), after
        # it they rotate half the keyspace away — the hot range MOVES
        rank = self.rng.next_zipf(self.key_count, self.theta)
        if op_id >= int(self.ops * self.migrate_at):
            rank = (rank + self.key_count // 2) % self.key_count
            self._count("post_migration")
        idx = rank
        self.key_log.append((op_id, idx))
        return idx

    def next_op(self, op_id: int) -> WorkloadOp:
        rng = self.rng
        if rng.next_float() < 0.10:
            # skewed range read around the hot point
            self._count("range_read")
            center = (self._zipf_key_index(op_id) * self.bound) \
                // self.key_count
            width = 1 + rng.next_zipf(self.bound // 4)
            lo = max(0, center - width // 2)
            r = Range(IntKey(lo), IntKey(min(self.bound, lo + width)))
            return WorkloadOp(txn=range_read_txn(Ranges.of(r)))
        self._count("txn")
        n = 1 + rng.next_int(3)
        keys = [self._key(self._zipf_key_index(op_id)) for _ in range(n)]
        return self._list_op(op_id, keys)


class OpenLoopWorkload(Workload):
    """Poisson arrivals at ``rate_txn_s`` of sim-time, uniform key mix.

    Overload hooks (PR-17): ``rate_mult`` is the nemesis-driven offered-load
    multiplier (a ramp/burst phase setting 4.0 quadruples the arrival rate),
    and ``pace`` is the client-side AIMD backpressure state — ``on_shed()``
    multiplicatively stretches inter-arrival gaps when the cluster sheds,
    ``on_ok()`` additively-ish recovers toward pace 1.0 on success.  Both
    default to exactly 1.0, and ``x * 1.0`` is bitwise ``x`` in IEEE floats,
    so the un-overloaded arrival stream is byte-identical to pre-PR-17."""

    name = "openloop"
    open_loop = True

    def __init__(self, rate_txn_s: float = 25.0, aimd: bool = True,
                 aimd_backoff: float = 2.0, aimd_recover: float = 0.9,
                 pace_max: float = 8.0):
        super().__init__()
        assert rate_txn_s > 0, "openloop needs a positive --rate"
        self.rate_txn_s = float(rate_txn_s)
        self.rate_mult = 1.0         # nemesis-set offered-load multiplier
        self.pace = 1.0              # AIMD gap stretch (1.0 = full rate)
        self.aimd = aimd
        self.aimd_backoff = float(aimd_backoff)
        self.aimd_recover = float(aimd_recover)
        self.pace_max = float(pace_max)
        self.paced_arrivals = 0      # arrivals drawn while pace > 1.0
        self.pace_downs = 0          # on_shed() events that stretched pace

    def on_shed(self) -> None:
        """A shed/Overloaded nack: multiplicatively back the offered rate
        off (stretch the inter-arrival gap), capped at ``pace_max``."""
        if self.aimd:
            self.pace = min(self.pace_max, self.pace * self.aimd_backoff)
            self.pace_downs += 1

    def on_ok(self) -> None:
        """A success: recover pace geometrically toward 1.0."""
        if self.aimd and self.pace > 1.0:
            self.pace = max(1.0, self.pace * self.aimd_recover)

    def next_arrival_s(self) -> float:
        # inverse-CDF exponential inter-arrival; 1-u keeps the argument in
        # (0, 1] (next_float may return exactly 0.0)
        u = 1.0 - self.rng.next_float()
        if self.pace > 1.0:
            self.paced_arrivals += 1
        return -math.log(u) * self.pace / (self.rate_txn_s * self.rate_mult)

    def next_op(self, op_id: int) -> WorkloadOp:
        rng = self.rng
        if rng.next_float() < 0.10:
            self._count("range_read")
            start = rng.next_int(self.bound - 1)
            width = 1 + rng.next_int(self.bound // 2)
            r = Range(IntKey(start), IntKey(min(self.bound, start + width)))
            return WorkloadOp(txn=range_read_txn(Ranges.of(r)))
        self._count("txn")
        n = 1 + rng.next_int(3)
        keys = [self._key(rng.next_int(self.key_count)) for _ in range(n)]
        return self._list_op(op_id, keys)


PRESETS = {
    "multirange": MultiRangeWorkload,
    "zipf": ZipfWorkload,
    "openloop": OpenLoopWorkload,
}


def make_workload(spec, rate_txn_s: float = 25.0) -> Workload:
    """Resolve a preset name or pass a ``Workload`` instance through."""
    if isinstance(spec, Workload):
        return spec
    cls = PRESETS.get(spec)
    if cls is None:
        raise ValueError(f"unknown workload {spec!r}; presets: "
                         f"{sorted(PRESETS)} (or pass a Workload instance)")
    if cls is OpenLoopWorkload:
        return OpenLoopWorkload(rate_txn_s=rate_txn_s)
    return cls()
