"""Simulated persistence: per-command field-diff log + reconstruction.

Capability parity with ``accord.impl.basic.Journal`` (Journal.java:59-542,174,310):
every command state transition appends a field-level diff; ``reconstruct`` replays
the diffs into fresh state, and the burn harness asserts the reconstruction matches
the live store — proving the recorded (serializable) state is sufficient for
persistence/replay, the checkpoint/resume contract of SURVEY §5.

Fields are serialized through the maelstrom wire codec, so the journal also
continuously exercises full-state serializability.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..local.command import Command, WaitingOn
from ..local.status import Durability, SaveStatus
from ..maelstrom import codec
from ..primitives.timestamp import TxnId

_FIELDS = ("save_status", "durability", "route", "partial_txn", "partial_deps",
           "promised", "accepted_or_committed", "execute_at", "writes", "result",
           "applied_locally")
_MISSING = object()


def _encode_fields(command: Command) -> Dict[str, object]:
    return {f: codec.encode_value(getattr(command, f)) for f in _FIELDS}


class Journal:
    """One journal per cluster; keyed by (node_id, store_id)."""

    def __init__(self):
        # (node, store) -> txn_id -> list of diffs (field -> encoded value)
        self.logs: Dict[Tuple[int, int], Dict[TxnId, List[Dict[str, object]]]] = {}
        # last full encoded state per txn (for diffing)
        self._last: Dict[Tuple[int, int, TxnId], Dict[str, object]] = {}
        # decoded-route memo for peek_route (invalidated on save/erase)
        self._routes: Dict[Tuple[int, int, TxnId], object] = {}
        # last raw field objects per txn: a field whose object is IDENTICAL
        # (is) to the last-saved one cannot have changed (command fields are
        # assigned, never mutated in place) and skips re-encoding — without
        # this every transition re-encodes the full deps payload just to
        # discover it is unchanged (dominant cost in hostile burns);
        # verify_against still proves the recorded state sufficient
        self._raw: Dict[Tuple[int, int, TxnId], Dict[str, object]] = {}
        # global append order per (node, store): the write-ahead sequence a
        # drop_tail (unsynced-tail loss) truncation operates on
        self._order: Dict[Tuple[int, int], List[TxnId]] = {}
        # erased-entry count per (node, store): erase() leaves stale TxnIds in
        # _order; once they outnumber the live ones the list is compacted, so
        # a long GC-heavy burn doesn't pin one dead reference per save forever
        self._order_dead: Dict[Tuple[int, int], int] = {}
        self.records = 0

    def attach(self, store) -> None:
        """Install this journal as the store's on-save hook."""
        store.journal = self

    # -- recording -----------------------------------------------------------
    def save(self, store, command: Command) -> None:
        key3 = (store.node.id, store.id, command.txn_id)
        prev = self._last.get(key3)
        if prev is None:
            diff = _encode_fields(command)
            self._last[key3] = dict(diff)
            self._raw[key3] = {f: getattr(command, f) for f in _FIELDS}
        else:
            raw = self._raw.setdefault(key3, {})
            diff = {}
            for f in _FIELDS:
                v = getattr(command, f)
                if raw.get(f, _MISSING) is v:
                    continue
                raw[f] = v
                enc = codec.encode_value(v)
                if prev.get(f) != enc:
                    prev[f] = enc
                    diff[f] = enc
            if not diff:
                return
        if "route" in diff:
            self._routes.pop(key3, None)
        self.logs.setdefault(key3[:2], {}).setdefault(command.txn_id, []) \
            .append(diff)
        self._order.setdefault(key3[:2], []).append(command.txn_id)
        self.records += 1

    def erase(self, store, txn_id: TxnId) -> None:
        """GC erasure also erases the journal entry (tombstone drop)."""
        key = (store.node.id, store.id)
        logs = self.logs.get(key, {})
        diffs = logs.pop(txn_id, None)
        self._last.pop(key + (txn_id,), None)
        self._routes.pop(key + (txn_id,), None)
        self._raw.pop(key + (txn_id,), None)
        if diffs:
            dead = self._order_dead.get(key, 0) + len(diffs)
            order = self._order.get(key)
            if order is not None and dead * 2 > len(order):
                order[:] = [t for t in order if t in logs]
                dead = 0
            self._order_dead[key] = dead

    def on_evict(self, store, txn_id: TxnId) -> None:
        """The store evicted this command: drop the raw-identity memo so the
        journal does not pin the full field object graph of cold state (the
        encoded _last stays — it IS the fault-in source).  The next save after
        a fault-in re-encodes each field once and repopulates the memo."""
        self._raw.pop((store.node.id, store.id, txn_id), None)

    def peek_route(self, store, txn_id: TxnId):
        """Decode ONLY the journaled route of an evicted command — scans that
        merely need a footprint filter (recovery evidence) must not pay a full
        command decode per cold entry (the hostile churn matrix spent most of
        its wall-clock in exactly that)."""
        key3 = (store.node.id, store.id, txn_id)
        route = self._routes.get(key3)
        if route is None:
            full = self._last.get(key3)
            if full is None:
                return None
            enc = full.get("route")
            if enc is None:
                return None
            route = codec.decode_value(enc)
            self._routes[key3] = route
        return route

    # -- reconstruction (Journal.reconstruct) --------------------------------
    def reconstruct(self, node_id: int, store_id: int) -> Dict[TxnId, Command]:
        out: Dict[TxnId, Command] = {}
        for txn_id, diffs in self.logs.get((node_id, store_id), {}).items():
            command = Command(txn_id)
            for diff in diffs:
                for field, encoded in diff.items():
                    setattr(command, field, codec.decode_value(encoded))
            out[txn_id] = command
        return out

    def reconstruct_one(self, store, txn_id: TxnId) -> Optional[Command]:
        """Rebuild ONE command from its latest recorded state — the
        cache-miss reload path (SafeCommandStore._fault_in)."""
        full = self._last.get((store.node.id, store.id, txn_id))
        if full is None:
            return None
        command = Command(txn_id)
        for field, encoded in full.items():
            setattr(command, field, codec.decode_value(encoded))
        return command

    # -- restart (crash-restart nemesis) --------------------------------------
    def restart_commands(self, node_id: int, store_id: int) -> Dict[TxnId, Command]:
        """Reconstruct a crashed store's commands for restart: everything the
        journal recorded, with legitimately-volatile state collapsed to its
        durable tier (READY_TO_EXECUTE resumes from STABLE, APPLYING from
        PRE_APPLIED — the round-3 replay contract).  waiting_on / listeners
        are never journaled: the restart path re-derives them."""
        rebuilt = self.reconstruct(node_id, store_id)
        for command in rebuilt.values():
            command.save_status = self._durable_status(command.save_status)
        return rebuilt

    def drop_tail(self, node_id: int, store_id: int, count: int) -> int:
        """Drop the last ``count`` records of a store's log — simulated loss
        of an unsynced write-ahead tail at crash.  Returns records dropped.
        NOTE: losing promise/accept records is NOT sound for consensus (a
        real journal fsyncs before replying); this exists for targeted
        durability experiments, not the default hostile matrix."""
        key = (node_id, store_id)
        order = self._order.get(key, [])
        logs = self.logs.get(key, {})
        dropped = 0
        while dropped < count and order:
            txn_id = order.pop()
            diffs = logs.get(txn_id)
            if not diffs:
                continue   # erased since; its order entries are stale
            diffs.pop()
            dropped += 1
            key3 = key + (txn_id,)
            self._raw.pop(key3, None)
            self._routes.pop(key3, None)
            if not diffs:
                del logs[txn_id]
                self._last.pop(key3, None)
            else:
                # rebuild the latest-state snapshot from the surviving diffs
                full: Dict[str, object] = {}
                for diff in diffs:
                    full.update(diff)
                self._last[key3] = full
        self.records -= dropped
        return dropped

    # -- verification ---------------------------------------------------------
    @staticmethod
    def _durable_status(status: SaveStatus) -> SaveStatus:
        """Collapse transient LocalExecution sub-states to their durable tier
        (SaveStatus.java LocalExecution): READY_TO_EXECUTE and APPLYING are
        volatile — a restart legitimately resumes from STABLE / PRE_APPLIED."""
        if status is SaveStatus.READY_TO_EXECUTE:
            return SaveStatus.STABLE
        if status is SaveStatus.APPLYING:
            return SaveStatus.PRE_APPLIED
        return status

    def verify_against(self, store) -> None:
        """Reconstruction must match the live store's command state for every
        durable field (waiting_on/listeners are transient execution state)."""
        rebuilt = self.reconstruct(store.node.id, store.id)
        live = store.commands
        for txn_id, command in live.items():
            if command.save_status is SaveStatus.NOT_DEFINED:
                continue  # never reached a durable state
            copy = rebuilt.get(txn_id)
            assert copy is not None, \
                f"journal lost {txn_id} on node {store.node.id}/store {store.id}"
            a = self._durable_status(command.save_status)
            b = self._durable_status(copy.save_status)
            assert a is b, \
                f"journal mismatch {txn_id}.save_status: live={a!r} rebuilt={b!r}"
            for f in ("durability", "execute_at"):
                va, vb = getattr(command, f), getattr(copy, f)
                assert va == vb or (va is vb), \
                    f"journal mismatch {txn_id}.{f}: live={va!r} rebuilt={vb!r}"
            assert (command.writes is None) == (copy.writes is None), \
                f"journal writes mismatch for {txn_id}"
        cold = getattr(store, "cold", set())
        for txn_id in rebuilt:
            assert txn_id in live or txn_id in cold, \
                f"journal has {txn_id} the live store erased without journal.erase"


