"""Simulated persistence: per-command field-diff log + reconstruction.

Capability parity with ``accord.impl.basic.Journal`` (Journal.java:59-542,174,310):
every command state transition appends a field-level diff; ``reconstruct`` replays
the diffs into fresh state, and the burn harness asserts the reconstruction matches
the live store — proving the recorded (serializable) state is sufficient for
persistence/replay, the checkpoint/resume contract of SURVEY §5.

Fields are serialized through the maelstrom wire codec, so the journal also
continuously exercises full-state serializability.

Gray-failure extensions (round 7):

- every record is stored as its canonical JSON **bytes + CRC32** — the replay
  path re-verifies each record, so torn writes and bit rot are DETECTED, never
  silently replayed;
- ``stall``/``unstall``/``lose_unsynced`` model a stalled append path
  (durability lags execution): a crash mid-stall loses the whole unsynced
  tail, strictly more than ``drop_tail`` experiments ever did;
- ``corrupt_random_record``/``tear_tail_record`` inject crash-time damage, and
  ``restart_replay`` applies the corrupt-record policy: a damaged TAIL record
  truncates to the last whole record (normal WAL semantics); a damaged
  MID-LOG record either raises ``JournalCorruption`` (halt-loud) or
  quarantines the txn — records dropped, footprint reported so the restart
  re-enters the bootstrap catch-up ladder over it.
"""
from __future__ import annotations

import json
import zlib
from typing import Callable, Dict, List, Optional, Tuple

from ..local.command import Command, WaitingOn
from ..local.status import Durability, SaveStatus
from ..maelstrom import codec
from ..primitives.timestamp import TxnId

_FIELDS = ("save_status", "durability", "route", "partial_txn", "partial_deps",
           "promised", "accepted_or_committed", "execute_at", "writes", "result",
           "applied_locally", "elided_unapplied")
# NOTE: elided_unapplied rides the identity-diff (`is`) skip like every other
# field, so it is ASSIGN-ONLY on Command — mutating the set in place would
# silently skip re-encoding (local/commands.py _note_elided_unless_applied
# and the serve-time prune both reassign fresh sets)
_MISSING = object()


def _encode_fields(command: Command) -> Dict[str, object]:
    return {f: codec.encode_value(getattr(command, f)) for f in _FIELDS}


class JournalCorruption(Exception):
    """A journal record failed checksum/parse verification at replay and the
    corrupt-record policy is halt-loud."""


class Record:
    """One durable journal append: the field-diff's canonical JSON bytes plus
    the CRC32 computed at append time.  Damage injection mutates ``payload``
    only — the stored checksum then witnesses the corruption at replay
    (CRC32 catches every single-bit flip and, practically, every torn
    truncation)."""

    __slots__ = ("payload", "crc")

    def __init__(self, payload: bytes, crc: int):
        self.payload = payload
        self.crc = crc

    @classmethod
    def encode(cls, diff: Dict[str, object]) -> "Record":
        payload = json.dumps(diff, sort_keys=True,
                             separators=(",", ":")).encode()
        return cls(payload, zlib.crc32(payload))

    def try_diff(self) -> Optional[Dict[str, object]]:
        """The decoded field-diff, or None if the record is damaged."""
        if zlib.crc32(self.payload) != self.crc:
            return None
        try:
            return json.loads(self.payload.decode())
        except (UnicodeDecodeError, ValueError):
            return None

    def diff(self) -> Dict[str, object]:
        d = self.try_diff()
        if d is None:
            raise JournalCorruption("record failed checksum/parse verification")
        return d


class RestartReplay:
    """Result of a verified restart replay over one (node, store) log."""

    __slots__ = ("commands", "quarantined", "torn_tail_dropped",
                 "corrupt_records")

    def __init__(self, commands: Dict[TxnId, Command],
                 quarantined: Dict[TxnId, object],
                 torn_tail_dropped: int, corrupt_records: int):
        self.commands = commands
        # txn -> last-known Route (None when no intact record named one):
        # the caller scopes the bootstrap quarantine from these
        self.quarantined = quarantined
        self.torn_tail_dropped = torn_tail_dropped
        self.corrupt_records = corrupt_records


class Journal:
    """One journal per cluster; keyed by (node_id, store_id)."""

    def __init__(self):
        # (node, store) -> txn_id -> list of diffs (field -> encoded value)
        self.logs: Dict[Tuple[int, int], Dict[TxnId, List[Dict[str, object]]]] = {}
        # last full encoded state per txn (for diffing)
        self._last: Dict[Tuple[int, int, TxnId], Dict[str, object]] = {}
        # decoded-route memo for peek_route (invalidated on save/erase)
        self._routes: Dict[Tuple[int, int, TxnId], object] = {}
        # last raw field objects per txn: a field whose object is IDENTICAL
        # (is) to the last-saved one cannot have changed (command fields are
        # assigned, never mutated in place) and skips re-encoding — without
        # this every transition re-encodes the full deps payload just to
        # discover it is unchanged (dominant cost in hostile burns);
        # verify_against still proves the recorded state sufficient
        self._raw: Dict[Tuple[int, int, TxnId], Dict[str, object]] = {}
        # global append order per (node, store): the write-ahead sequence a
        # drop_tail (unsynced-tail loss) truncation operates on
        self._order: Dict[Tuple[int, int], List[TxnId]] = {}
        # erased-entry count per (node, store): erase() leaves stale TxnIds in
        # _order; once they outnumber the live ones the list is compacted, so
        # a long GC-heavy burn doesn't pin one dead reference per save forever
        self._order_dead: Dict[Tuple[int, int], int] = {}
        # node -> per-(node,store) live-record-count snapshot at stall time:
        # the durable watermark a mid-stall crash rewinds to
        self._stalled: Dict[int, Dict[Tuple[int, int], int]] = {}
        # sim-time supplier (installed by the owning Cluster) + last-append
        # times: the torn-write injector must only tear records no peer can
        # have acked yet (see tear_tail_record)
        self.now_us: Optional[Callable[[], int]] = None
        self._append_us: Dict[Tuple[int, int], int] = {}
        self.records = 0

    def attach(self, store) -> None:
        """Install this journal as the store's on-save hook."""
        store.journal = self

    # -- recording -----------------------------------------------------------
    def save(self, store, command: Command) -> None:
        key3 = (store.node.id, store.id, command.txn_id)
        prev = self._last.get(key3)
        if prev is None:
            diff = _encode_fields(command)
            self._last[key3] = dict(diff)
            self._raw[key3] = {f: getattr(command, f) for f in _FIELDS}
        else:
            raw = self._raw.setdefault(key3, {})
            diff = {}
            for f in _FIELDS:
                v = getattr(command, f)
                if raw.get(f, _MISSING) is v:
                    continue
                raw[f] = v
                enc = codec.encode_value(v)
                if prev.get(f) != enc:
                    prev[f] = enc
                    diff[f] = enc
            if not diff:
                return
        if "route" in diff:
            self._routes.pop(key3, None)
        self.logs.setdefault(key3[:2], {}).setdefault(command.txn_id, []) \
            .append(Record.encode(diff))
        self._order.setdefault(key3[:2], []).append(command.txn_id)
        if self.now_us is not None:
            self._append_us[key3[:2]] = self.now_us()
        self.records += 1

    def erase(self, store, txn_id: TxnId) -> None:
        """GC erasure also erases the journal entry (tombstone drop)."""
        self.erase_key(store.node.id, store.id, txn_id)

    def erase_key(self, node_id: int, store_id: int, txn_id: TxnId) -> None:
        """Store-object-free erase (restart-time quarantine runs before the
        rebuilt store exists)."""
        key = (node_id, store_id)
        logs = self.logs.get(key, {})
        recs = logs.pop(txn_id, None)
        self._last.pop(key + (txn_id,), None)
        self._routes.pop(key + (txn_id,), None)
        self._raw.pop(key + (txn_id,), None)
        if recs:
            dead = self._order_dead.get(key, 0) + len(recs)
            order = self._order.get(key)
            if order is not None and dead * 2 > len(order):
                order[:] = [t for t in order if t in logs]
                dead = 0
            self._order_dead[key] = dead

    def on_evict(self, store, txn_id: TxnId) -> None:
        """The store evicted this command: drop the raw-identity memo so the
        journal does not pin the full field object graph of cold state (the
        encoded _last stays — it IS the fault-in source).  The next save after
        a fault-in re-encodes each field once and repopulates the memo."""
        self._raw.pop((store.node.id, store.id, txn_id), None)

    def peek_route(self, store, txn_id: TxnId):
        """Decode ONLY the journaled route of an evicted command — scans that
        merely need a footprint filter (recovery evidence) must not pay a full
        command decode per cold entry (the hostile churn matrix spent most of
        its wall-clock in exactly that)."""
        return self._peek_route((store.node.id, store.id, txn_id))

    def _peek_route(self, key3):
        route = self._routes.get(key3)
        if route is None:
            full = self._last.get(key3)
            if full is None:
                return None
            enc = full.get("route")
            if enc is None:
                return None
            route = codec.decode_value(enc)
            self._routes[key3] = route
        return route

    # -- reconstruction (Journal.reconstruct) --------------------------------
    def reconstruct(self, node_id: int, store_id: int) -> Dict[TxnId, Command]:
        out: Dict[TxnId, Command] = {}
        for txn_id, recs in self.logs.get((node_id, store_id), {}).items():
            command = Command(txn_id)
            for rec in recs:
                for field, encoded in rec.diff().items():
                    setattr(command, field, codec.decode_value(encoded))
            out[txn_id] = command
        return out

    def reconstruct_one(self, store, txn_id: TxnId) -> Optional[Command]:
        """Rebuild ONE command from its latest recorded state — the
        cache-miss reload path (SafeCommandStore._fault_in)."""
        full = self._last.get((store.node.id, store.id, txn_id))
        if full is None:
            return None
        command = Command(txn_id)
        for field, encoded in full.items():
            setattr(command, field, codec.decode_value(encoded))
        return command

    # -- restart (crash-restart nemesis) --------------------------------------
    def restart_commands(self, node_id: int, store_id: int) -> Dict[TxnId, Command]:
        """Reconstruct a crashed store's commands for restart: everything the
        journal recorded, with legitimately-volatile state collapsed to its
        durable tier (READY_TO_EXECUTE resumes from STABLE, APPLYING from
        PRE_APPLIED — the round-3 replay contract).  waiting_on / listeners
        are never journaled: the restart path re-derives them.  Halt-loud on
        any damaged record; ``restart_replay`` is the policy-aware variant."""
        return self.restart_replay(node_id, store_id, policy="halt").commands

    def restart_replay(self, node_id: int, store_id: int,
                       policy: str = "quarantine") -> RestartReplay:
        """Verified restart reconstruction: every record is re-checked against
        its append-time CRC32.

        - A damaged record at the very TAIL of the log is a torn write (the
          crash interrupted the append): silently truncate to the last whole
          record, exactly like any write-ahead log.
        - A damaged MID-LOG record is corruption (bit rot, firmware lies):
          ``policy="halt"`` raises JournalCorruption; ``policy="quarantine"``
          drops every record of the affected txn and reports its last-known
          route so the caller can bootstrap-catch-up the footprint."""
        assert policy in ("halt", "quarantine"), policy
        key = (node_id, store_id)
        logs = self.logs.get(key, {})
        # 1. torn tail: truncate trailing damaged records (append order)
        torn = 0
        while True:
            tail_txn = self._tail_txn(key)
            if tail_txn is None:
                break
            recs = logs.get(tail_txn)
            if recs and recs[-1].try_diff() is None:
                self._drop_last_record(key)
                torn += 1
            else:
                break
        # 2. decode everything else; any remaining damage is mid-log corruption
        commands: Dict[TxnId, Command] = {}
        quarantined: Dict[TxnId, object] = {}
        corrupt = 0
        for txn_id in list(logs):
            diffs = []
            for rec in logs[txn_id]:
                d = rec.try_diff()
                if d is None:
                    diffs = None
                    break
                diffs.append(d)
            if diffs is None:
                corrupt += 1
                if policy == "halt":
                    raise JournalCorruption(
                        f"corrupt journal record for {txn_id} on node "
                        f"{node_id}/store {store_id} (policy=halt)")
                route = self._peek_route(key + (txn_id,))
                self.erase_key(node_id, store_id, txn_id)
                quarantined[txn_id] = route
                continue
            command = Command(txn_id)
            for diff in diffs:
                for field, encoded in diff.items():
                    setattr(command, field, codec.decode_value(encoded))
            command.save_status = self._durable_status(command.save_status)
            commands[txn_id] = command
        return RestartReplay(commands, quarantined, torn, corrupt)

    def drop_tail(self, node_id: int, store_id: int, count: int) -> int:
        """Drop the last ``count`` records of a store's log — simulated loss
        of an unsynced write-ahead tail at crash.  Returns records dropped.
        NOTE: losing promise/accept records is NOT sound for consensus (a
        real journal fsyncs before replying); this exists for targeted
        durability experiments.  The disk-stall nemesis gets the same effect
        soundly by ALSO holding the node's outbound replies for the stall
        (fsync-before-reply: no peer ever observes state that was lost)."""
        key = (node_id, store_id)
        dropped = 0
        while dropped < count and self._drop_last_record(key) is not None:
            dropped += 1
        return dropped

    def _tail_txn(self, key: Tuple[int, int]) -> Optional[TxnId]:
        """The txn owning the globally-LAST live record of a store's log."""
        order = self._order.get(key, [])
        logs = self.logs.get(key, {})
        for txn_id in reversed(order):
            if logs.get(txn_id):
                return txn_id
        return None

    def _drop_last_record(self, key: Tuple[int, int]) -> Optional[TxnId]:
        """Remove the newest record of a store's log, rewinding the
        latest-state snapshot to the surviving prefix.  Returns the owning
        txn, or None if the log is empty."""
        order = self._order.get(key, [])
        logs = self.logs.get(key, {})
        while order:
            txn_id = order.pop()
            recs = logs.get(txn_id)
            if not recs:
                # erased since; its order entries are stale — keep the dead
                # count exact or _live_count over-reports after a drop
                dead = self._order_dead.get(key, 0)
                if dead:
                    self._order_dead[key] = dead - 1
                continue
            recs.pop()
            key3 = key + (txn_id,)
            self._raw.pop(key3, None)
            self._routes.pop(key3, None)
            if not recs:
                del logs[txn_id]
                self._last.pop(key3, None)
            else:
                # rebuild the latest-state snapshot from the surviving records
                full: Dict[str, object] = {}
                for rec in recs:
                    d = rec.try_diff()
                    if d is not None:
                        full.update(d)
                self._last[key3] = full
            self.records -= 1
            return txn_id
        return None

    # -- journal-append stalls (disk-stall nemesis) ---------------------------
    def _live_count(self, key: Tuple[int, int]) -> int:
        return len(self._order.get(key, ())) - self._order_dead.get(key, 0)

    def stall(self, node_id: int) -> None:
        """Freeze the durable watermark: appends keep landing in memory but
        nothing past this point is fsynced until ``unstall``.  A crash while
        stalled (``lose_unsynced``) rewinds to the watermark."""
        if node_id in self._stalled:
            return
        snap = {key: self._live_count(key)
                for key in self._order if key[0] == node_id}
        self._stalled[node_id] = snap

    def unstall(self, node_id: int) -> None:
        """The append path caught up: everything buffered is now durable."""
        self._stalled.pop(node_id, None)

    def is_stalled(self, node_id: int) -> bool:
        return node_id in self._stalled

    def lose_unsynced(self, node_id: int) -> int:
        """Crash during a stall: every record appended after the stall began
        is gone.  Returns records lost.  (Erase interleavings make the
        positional rewind conservative: an erased pre-stall txn shrinks the
        live count, so at most FEWER post-stall records are dropped.)"""
        snap = self._stalled.pop(node_id, None)
        if snap is None:
            return 0
        lost = 0
        for key in list(self._order):
            if key[0] != node_id:
                continue
            excess = self._live_count(key) - snap.get(key, 0)
            if excess > 0:
                lost += self.drop_tail(key[0], key[1], excess)
        return lost

    # -- damage injection (the hostile matrix's corruption axis) --------------
    def corrupt_random_record(self, node_id: int, rng) -> Optional[Tuple]:
        """Flip one random bit in one random NON-TAIL record of ``node_id``'s
        logs (bit rot / firmware lies).  The stored CRC32 witnesses it at
        replay.  The global tail record is excluded: replay classifies a
        damaged tail as a torn write and silently truncates it — but this
        record may be long-acked, and rolling an acked promise/accept back is
        injection unsoundness, not a protocol bug (the torn-write injector
        has its own cannot-have-been-acked age gate).  Returns
        (key, txn_id, record_index) or None if the node has no eligible
        records."""
        entries = []
        for key, logs in self.logs.items():
            if key[0] != node_id:
                continue
            tail = self._tail_txn(key)
            for txn_id, recs in logs.items():
                last = len(recs) - (1 if txn_id == tail else 0)
                for i in range(last):
                    entries.append((key, txn_id, i))
        if not entries:
            return None
        key, txn_id, i = rng.pick(entries)
        rec = self.logs[key][txn_id][i]
        payload = bytearray(rec.payload)
        bit = rng.next_int(len(payload) * 8)
        payload[bit // 8] ^= 1 << (bit % 8)
        rec.payload = bytes(payload)
        return (key, txn_id, i)

    def tear_tail_record(self, node_id: int, rng,
                         max_age_us: Optional[int] = None) -> int:
        """Truncate the LAST record of each of ``node_id``'s store logs to a
        strict prefix — the partial append a crash tears.  Returns records
        torn; restart replay truncates them to the last whole record.

        ``max_age_us`` gates soundness: a record appended more than one
        minimum link latency before the crash may already have been ACKED to
        a peer (fsync-before-reply: synced, then replied), and tearing it
        would roll back a promise the protocol assumes stable.  With the
        gate, only appends the crash provably raced — no reply can have
        crossed the wire yet — are torn; older tails are left intact (the
        crash simply didn't interrupt a write)."""
        torn = 0
        now = self.now_us() if self.now_us is not None else None
        for key in list(self._order):
            if key[0] != node_id:
                continue
            if max_age_us is not None and now is not None \
                    and now - self._append_us.get(key, 0) > max_age_us:
                continue
            tail = self._tail_txn(key)
            if tail is None:
                continue
            rec = self.logs[key][tail][-1]
            if len(rec.payload) < 2:
                continue
            cut = 1 + rng.next_int(len(rec.payload) - 1)
            rec.payload = rec.payload[:cut]
            torn += 1
        return torn

    # -- verification ---------------------------------------------------------
    @staticmethod
    def _durable_status(status: SaveStatus) -> SaveStatus:
        """Collapse transient LocalExecution sub-states to their durable tier
        (SaveStatus.java LocalExecution): READY_TO_EXECUTE and APPLYING are
        volatile — a restart legitimately resumes from STABLE / PRE_APPLIED."""
        if status is SaveStatus.READY_TO_EXECUTE:
            return SaveStatus.STABLE
        if status is SaveStatus.APPLYING:
            return SaveStatus.PRE_APPLIED
        return status

    def verify_against(self, store) -> None:
        """Reconstruction must match the live store's command state for every
        durable field (waiting_on/listeners are transient execution state)."""
        rebuilt = self.reconstruct(store.node.id, store.id)
        live = store.commands
        for txn_id, command in live.items():
            if command.save_status is SaveStatus.NOT_DEFINED:
                continue  # never reached a durable state
            copy = rebuilt.get(txn_id)
            assert copy is not None, \
                f"journal lost {txn_id} on node {store.node.id}/store {store.id}"
            a = self._durable_status(command.save_status)
            b = self._durable_status(copy.save_status)
            assert a is b, \
                f"journal mismatch {txn_id}.save_status: live={a!r} rebuilt={b!r}"
            for f in ("durability", "execute_at"):
                va, vb = getattr(command, f), getattr(copy, f)
                assert va == vb or (va is vb), \
                    f"journal mismatch {txn_id}.{f}: live={va!r} rebuilt={vb!r}"
            assert (command.writes is None) == (copy.writes is None), \
                f"journal writes mismatch for {txn_id}"
        cold = getattr(store, "cold", set())
        for txn_id in rebuilt:
            assert txn_id in live or txn_id in cold, \
                f"journal has {txn_id} the live store erased without journal.erase"


