"""cassandra_accord_tpu — a TPU-native framework implementing the Accord consensus
protocol (leaderless, shard-per-key-range, strict-serializable multi-key/multi-range
ACID transactions; 1-RTT fast path, 2-RTT slow path).

Capability reference: bdeggleston/cassandra-accord (Java).  This is NOT a port: the
consensus/messaging control plane is a clean host-side implementation, and the
dependency-graph data plane (conflict indexes of in-flight transactions, the
PreAccept/Accept dependency computation, the execute-phase topological wait) is
device-resident JAX/XLA/Pallas behind a pluggable ``DepsResolver`` boundary.

Layout (mirrors the reference's layer map, SURVEY.md §1):

- ``utils``       zero-dependency substrate: sorted-array algebra, CSR multimaps,
                  interval maps, async chains, deterministic RNG, invariants
- ``primitives``  Timestamp/TxnId/Ballot, Keys/Ranges/Routes, Deps, Txn, Writes
- ``api``         the SPI the embedding system implements (Agent, DataStore,
                  MessageSink, ConfigurationService, ProgressLog, Scheduler, ...)
- ``topology``    epoch-versioned shard maps, fast-path electorates, quorum math
- ``local``       per-node per-shard replica state machine (Node, CommandStore,
                  Command lifecycle, CommandsForKey conflict index)
- ``messages``    wire-protocol request/reply types with replica-side handlers
- ``coordinate``  coordinator-side phase state machines + quorum trackers
- ``impl``        in-memory reference implementations of the SPI
- ``ops``         the TPU data plane: batched deps kernels (overlap join,
                  transitive closure, topo frontier) + DepsResolver impls
- ``parallel``    mesh/sharding utilities for multi-chip deps-graph state
- ``models``      flagship batched deps-graph engine (the jittable "model")
- ``harness``     deterministic simulation cluster + fault injection + verifiers
- ``maelstrom``   JSON-over-stdio node adapter for the Maelstrom workbench
"""

__version__ = "0.1.0"
