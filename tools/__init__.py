"""Repo tooling (perf regression gate, etc.) — importable as ``tools.*``
from the repo root, runnable as scripts."""
