"""explain — divergence forensics over two causal provenance dumps.

Takes two ``--provenance`` dumps (``observe/provenance.py`` ``save()``
format, version 1) from same-seed runs and reports WHERE the trajectories
causally departed: the causally-first divergent event over the full causal
stream (handlers, timers, crashes, transitions — causes that are invisible
in the byte-level message trace), the first message-trace divergence (the
byte-level symptom, for contrast), and the divergent event's bounded
ancestor cone back through execution-context and message-chain parents to
the originating decision.

Usage:
    python tools/explain.py ref-prov.json other-prov.json [--hops N]

Producing the inputs:
    python -m cassandra_accord_tpu.harness.burn --seeds 7 --ops 400 \
        --provenance ref-prov.json
    # ... the perturbed / suspect run writes other-prov.json ...

Stdout TAIL contract (same as bench.py / tools/trend.py, pinned by
tests/test_explain_smoke.py): the LAST stdout line is one compact
single-line JSON object (identical-or-not, divergence index + sim time,
both events' kind/what, cone size), sized to survive a bounded tail
capture.  Exit code: 0 = identical, 3 = divergent — never nonzero for a
mere divergence-shaped answer to the question being asked, but distinct
from 0 so scripts can branch.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from cassandra_accord_tpu.observe.provenance import (  # noqa: E402
    ProvenanceRecorder, explain_divergence)

_TAIL_WHAT_CHARS = 160   # per-event description budget in the JSON tail


def _tail_event(ev: dict) -> dict:
    """Compact one aligned event for the tail line (bounded description)."""
    if ev is None:
        return None
    return {"kind": ev.get("kind"), "sim_us": ev.get("sim_us"),
            "what": str(ev.get("what", ""))[:_TAIL_WHAT_CHARS]}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="explain",
        description="report the causally-first divergent event between two "
                    "provenance dumps, plus its ancestor cone")
    p.add_argument("reference", help="provenance dump of the reference run "
                                     "(run a)")
    p.add_argument("other", help="provenance dump of the suspect run (run b)")
    p.add_argument("--hops", type=int, default=10,
                   help="ancestor-cone depth in parent hops (default 10)")
    args = p.parse_args(argv)

    a = ProvenanceRecorder.load(args.reference)
    b = ProvenanceRecorder.load(args.other)
    rep = explain_divergence(a, b, hops=args.hops)

    tail = {"reference": os.path.basename(args.reference),
            "other": os.path.basename(args.other),
            "events_a": len(a["events"]), "events_b": len(b["events"]),
            "hops": args.hops}
    if rep is None:
        print("causal DAGs are identical "
              f"({len(a['events'])} events each)", flush=True)
        tail.update(identical=True)
        print(json.dumps(tail, sort_keys=True), flush=True)
        return 0
    print(rep["text"], flush=True)
    msg = rep.get("first_message_divergence")
    tail.update(
        identical=False, index=rep["index"], sim_us=rep["sim_us"],
        event_a=_tail_event(rep.get("event_a")),
        event_b=_tail_event(rep.get("event_b")),
        origin=_tail_event(rep.get("origin")),
        first_message_divergence_seq=msg.get("seq") if msg else None,
        cone_events=len(rep.get("cone") or []))
    line = json.dumps(tail, sort_keys=True)
    if len(line) >= 4096:   # tail contract: survive a bounded capture
        for k in ("origin", "event_a", "event_b"):
            if tail.get(k):
                tail[k] = {"kind": tail[k]["kind"]}
        line = json.dumps(tail, sort_keys=True)
    print(line, flush=True)
    return 3


if __name__ == "__main__":
    sys.exit(main())
