"""trend — the cross-run performance trend ledger.

Every bench / smoke / perf-gate run appends ONE JSON line to
``BENCH_HISTORY.jsonl`` (the durable perf trajectory the one-shot
``BENCH_r0*.json`` artifacts never gave us), and this tool renders the
metric deltas across runs: run N vs run N−1 and vs the oldest run in the
window, per metric, with the same deterministic sim-plane metrics the perf
gate compares (``commit_latency_mean_us`` / ``p95`` / ``sim_ms`` /
``messages``) plus each run's headline.

Writers:
- ``bench.py`` (all modes) appends its compact tail summary,
- ``tools/perfgate.py --smoke/--gate`` appends the smoke measurement and
  PRINTS the last-K trend next to its baseline delta,
so the ledger grows as a side effect of runs that already happen — no new
ritual.  ``ACCORD_BENCH_HISTORY`` overrides the ledger path (tests point it
at a tmp file); set it to ``0`` to disable appends entirely.

Stdout TAIL contract (same as bench.py, pinned by tests/test_trend.py): the
LAST stdout line of the CLI is one compact single-line JSON object
(run count + latest values + deltas), sized to survive a bounded tail
capture.

Usage:
    python tools/trend.py                 # render the last 8 runs
    python tools/trend.py --last 20
    python tools/trend.py --history PATH
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

HISTORY_NAME = "BENCH_HISTORY.jsonl"
DEFAULT_HISTORY_PATH = os.path.join(_REPO_ROOT, HISTORY_NAME)

# the sim-plane metrics rendered as trend columns (the perf gate's own
# deterministic set — tools/perfgate.py GATED_METRICS keys)
TREND_SIM_KEYS = ("commit_latency_mean_us", "commit_latency_p95_us",
                  "sim_ms", "messages")

# the protocol-throughput series (bench.py protocol_ramp): wall commits/s at
# the top concurrency level with the columnar engine on — the ledger line
# that shows the 43-commits/s wall breaking run-over-run.  Wall-clock, so
# machine-dependent: rendered as its own series, never gated.
RAMP_KEY = "protocol_commits_per_sec"


def history_path(path: Optional[str] = None) -> Optional[str]:
    """Resolve the ledger path: explicit arg > ACCORD_BENCH_HISTORY env >
    repo default.  Returns None when appends are disabled (env = 0/empty)."""
    if path is not None:
        return path
    env = os.environ.get("ACCORD_BENCH_HISTORY")
    if env is not None:
        if env in ("", "0", "off"):
            return None
        return env
    return DEFAULT_HISTORY_PATH


def append_entry(record: dict, path: Optional[str] = None) -> Optional[dict]:
    """Append one run record to the ledger (stamped with wall time — the
    ledger is CROSS-run bookkeeping, explicitly outside the sim determinism
    contract).  Never raises: the ledger must not be able to fail a bench
    or gate run.  Returns the stamped record, or None when disabled."""
    target = history_path(path)
    if target is None:
        return None
    entry = {"ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime()),
             **record}
    try:
        with open(target, "a") as f:
            f.write(json.dumps(entry, sort_keys=True) + "\n")
    except OSError:
        return None
    return entry


def load_history(path: Optional[str] = None) -> List[dict]:
    """Parse the ledger; unparseable lines are skipped (a torn tail from a
    killed run must not brick the trend report)."""
    target = history_path(path)
    if target is None:
        return []
    out: List[dict] = []
    try:
        with open(target) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                if isinstance(doc, dict):
                    out.append(doc)
    except OSError:
        pass
    return out


def _sim_value(entry: dict, key: str):
    sim = entry.get("sim")
    if isinstance(sim, dict) and key in sim:
        return sim[key]
    return None


def _cohort(entry: dict):
    """The comparability key for run-over-run deltas: a multi-seed median
    record and a single-seed record measure DIFFERENT things — a delta
    between them reads as a regression on an unchanged tree.  Records
    without a ``seeds`` field (legacy ledger lines) form their own cohort
    and only compare with each other."""
    seeds = entry.get("seeds")
    if isinstance(seeds, list) and seeds:
        return tuple(sorted(seeds))
    return None


def _fmt_delta(cur, prev) -> str:
    if cur is None or prev is None:
        return ""
    if prev == 0:
        return " (prev 0)"
    ratio = cur / prev
    sign = "+" if ratio >= 1 else ""
    return f" ({sign}{100.0 * (ratio - 1):.1f}%)"


def trend_lines(entries: List[dict], last_k: int = 8,
                sim_keys=TREND_SIM_KEYS) -> List[str]:
    """Human-readable last-K trend: one line per run, then per-metric delta
    series run-over-run."""
    window = entries[-last_k:]
    lines: List[str] = []
    if not window:
        lines.append(f"trend: no runs recorded yet ({HISTORY_NAME} empty "
                     f"or missing)")
        return lines
    lines.append(f"trend: last {len(window)} of {len(entries)} recorded runs")
    for i, e in enumerate(window):
        head = f"  [{i}] {e.get('ts', '?')} {e.get('kind', '?'):<8}"
        seeds = e.get("seeds")
        if isinstance(seeds, list) and seeds:
            head += " seeds=" + ",".join(str(s) for s in seeds)
        metric = e.get("metric")
        if metric and e.get("value") is not None:
            head += f" {metric}={e['value']}"
        if e.get(RAMP_KEY) is not None and metric != RAMP_KEY:
            head += f" {RAMP_KEY}={e[RAMP_KEY]}"
        ramp = e.get("ramp")
        if isinstance(ramp, dict) and ramp.get("wall"):
            head += (f"  ramp@{ramp.get('levels')}: "
                     f"wall={ramp['wall']} sim={ramp.get('sim')}")
        sims = [f"{k}={_sim_value(e, k)}" for k in sim_keys
                if _sim_value(e, k) is not None]
        if sims:
            head += "  sim: " + " ".join(sims)
        lines.append(head)
    for key in sim_keys:
        present = [(e, v) for e in window
                   if (v := _sim_value(e, key)) is not None]
        if len(present) < 2:
            continue
        # delta arrows only across SAME-cohort runs (same seed set): a
        # multi-seed median vs a single-seed run is not a regression
        cohort = _cohort(present[-1][0])
        same = [v for e, v in present if _cohort(e) == cohort]
        skipped = len(present) - len(same)
        if len(same) < 2:
            lines.append(f"  {key:<26} {same[-1]} (no prior same-seed run "
                         f"to compare; {skipped} other-seed run(s))")
            continue
        parts = []
        prev = None
        for v in same:
            parts.append(f"{v}{_fmt_delta(v, prev)}")
            prev = v
        tail = f"  [{skipped} other-seed run(s) omitted]" if skipped else ""
        lines.append(f"  {key:<26} " + " -> ".join(parts) + tail)
    # the deps-graph kernel series (bench.py deps_graph stage, round 12):
    # frontier-tier seconds per kernel at the largest recorded T, with the
    # old-vs-new speedup where the dense twin was measured.  Wall-clock,
    # never gated — rendered so the closure/SCC retirement holds visibly
    # run-over-run.
    dg_present = [(e, e["deps_graph"]) for e in window
                  if isinstance(e.get("deps_graph"), dict)]
    if dg_present:
        def _top_t(dg):
            ts = sorted((int(k[1:]) for k in dg if k.startswith("T")),
                        reverse=True)
            return ts[0] if ts else None
        top = _top_t(dg_present[-1][1])
        if top is not None:
            for key in ("closure_frontier_s", "scc_frontier_s",
                        "elide_frontier_s"):
                same = [dg[f"T{top}"].get(key) for _e, dg in dg_present
                        if _top_t(dg) == top
                        and dg.get(f"T{top}", {}).get(key) is not None]
                if not same:
                    continue
                if len(same) >= 2:
                    parts, prev = [], None
                    for v in same:
                        parts.append(f"{v}{_fmt_delta(v, prev)}")
                        prev = v
                    lines.append(f"  deps_graph.{key}@T{top}    "
                                 + " -> ".join(parts)
                                 + "  (wall-clock: never gated)")
                else:
                    lines.append(f"  deps_graph.{key}@T{top}    {same[-1]} "
                                 f"(no prior same-T run)")
            er = dg_present[-1][1].get("exec_commit_rate")
            if isinstance(er, dict) and er:
                lines.append("  deps_graph.exec_commit_rate  "
                             + " ".join(f"{k}={v}" for k, v in er.items()))
    # the workload_slo series (ISSUE-16 open-loop preset): did the run
    # sustain its arrival rate — rendered per (workload, rate) cohort so a
    # rate change never reads as a regression.  Sources: bench.py's
    # workload_slo stage records embed a dict; the burn CLI's openloop runs
    # append standalone kind=workload_slo records.
    def _wslo(e):
        if isinstance(e.get("workload_slo"), dict):
            return e["workload_slo"]
        if e.get("kind") == "workload_slo":
            return e
        return None
    ws_present = [(e, w) for e in window if (w := _wslo(e)) is not None]
    if ws_present:
        latest_w = ws_present[-1][1]
        rate_cohort = (latest_w.get("workload"), latest_w.get("rate_txn_s"))
        same = [w for _e, w in ws_present
                if (w.get("workload"), w.get("rate_txn_s")) == rate_cohort]
        parts = [f"{'sustained' if w.get('sustained') else 'BURNED'}"
                 f"({w.get('slo_burn_events', w.get('value'))} ev"
                 f"/{w.get('sim_minutes')}min)" for w in same]
        lines.append(f"  workload_slo@{rate_cohort[0]}:"
                     f"{rate_cohort[1]}txn/s     " + " -> ".join(parts))
    # the overload series (ISSUE-17 metastability oracles): goodput floor
    # fraction (ramp) or recovery window (burst) per (mode, rate) cohort —
    # a metastable regression shows as the floor cratering run-over-run.
    # Sources: bench.py overload-stage embeds; burn CLI kind=overload
    # records (--overload ramp|burst).
    def _ovl(e):
        if isinstance(e.get("overload"), dict):
            return e["overload"]
        if e.get("kind") == "overload":
            return e
        return None
    ov_present = [(e, o) for e in window if (o := _ovl(e)) is not None]
    if ov_present:
        latest_o = ov_present[-1][1]
        ov_cohort = (latest_o.get("mode"), latest_o.get("rate_txn_s"))
        same = [o for _e, o in ov_present
                if (o.get("mode"), o.get("rate_txn_s")) == ov_cohort]
        parts = []
        for o in same:
            metric = o.get("goodput_floor_frac",
                           o.get("recovery_sim_s", o.get("value")))
            cap = o.get("capacity_goodput_txn_s")
            parts.append(f"{'pass' if o.get('passed') else 'FAIL'}"
                         f"({metric}" + (f"@{cap}txn/s" if cap else "") + ")")
        lines.append(f"  overload@{ov_cohort[0]}:"
                     f"{ov_cohort[1]}txn/s      " + " -> ".join(parts))
    # the protocol-throughput series: delta arrows across runs recording the
    # same ramp levels (a different concurrency ceiling is a different
    # measurement, like a different seed cohort)
    ramp_present = [(e, e[RAMP_KEY]) for e in window
                    if e.get(RAMP_KEY) is not None]
    if len(ramp_present) >= 1:
        def _levels(e):
            ramp = e.get("ramp")
            lv = ramp.get("levels") if isinstance(ramp, dict) else None
            return tuple(lv) if isinstance(lv, list) else None
        cohort = _levels(ramp_present[-1][0])
        same = [v for e, v in ramp_present if _levels(e) == cohort]
        if len(same) >= 2:
            parts = []
            prev = None
            for v in same:
                parts.append(f"{v}{_fmt_delta(v, prev)}")
                prev = v
            lines.append(f"  {RAMP_KEY:<26} " + " -> ".join(parts)
                         + "  (wall-clock: never gated)")
        else:
            lines.append(f"  {RAMP_KEY:<26} {same[-1]} (no prior same-levels "
                         f"run to compare)")
    return lines


def latest_deltas(entries: List[dict],
                  sim_keys=TREND_SIM_KEYS) -> Dict[str, float]:
    """Per-metric current/previous ratio of the two most recent SAME-cohort
    runs that carry each metric (the tail-contract JSON payload).  Cohort =
    the record's seed set: comparing a multi-seed median against a
    single-seed run would report a spurious delta on an unchanged tree."""
    out: Dict[str, float] = {}
    for key in sim_keys:
        present = [(e, v) for e in entries
                   if (v := _sim_value(e, key)) is not None]
        if not present:
            continue
        cohort = _cohort(present[-1][0])
        series = [v for e, v in present if _cohort(e) == cohort]
        if len(series) >= 2 and series[-2]:
            out[key] = round(series[-1] / series[-2], 4)
    return out


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--last", type=int, default=8, metavar="K",
                   help="render the last K runs (default 8)")
    p.add_argument("--history", default=None, metavar="PATH",
                   help=f"ledger path (default: repo {HISTORY_NAME}, or "
                        f"ACCORD_BENCH_HISTORY)")
    args = p.parse_args(argv)
    entries = load_history(args.history)
    for line in trend_lines(entries, last_k=args.last):
        print(line, flush=True)
    window = entries[-args.last:]
    latest = window[-1] if window else None
    # stdout TAIL contract: the LAST line is one compact single-line JSON
    # object (the same bounded-tail-capture contract bench.py honors)
    summary = {
        "runs": len(entries),
        "window": len(window),
        "latest": None if latest is None else {
            "ts": latest.get("ts"), "kind": latest.get("kind"),
            "metric": latest.get("metric"), "value": latest.get("value"),
            "sim": {k: _sim_value(latest, k) for k in TREND_SIM_KEYS
                    if _sim_value(latest, k) is not None} or None,
            RAMP_KEY: latest.get(RAMP_KEY),
        },
        "deltas_vs_prev": latest_deltas(entries),
    }
    print(json.dumps(summary, sort_keys=True), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
