"""perfgate — the commit-latency regression gate.

Measures a small FIXED-SEED smoke workload through the full simulated
cluster with the flight recorder + critical-path extractor attached, then
compares the result against the recorded baseline (``BASELINE.json``'s
``gate`` block) and — informationally — against the latest ``BENCH_r0*.json``
headline.  Prints per-metric deltas; in ``--gate`` mode exits nonzero
(``EXIT_REGRESSION``) when any GATED metric regresses past its threshold.

What is gated vs merely printed:

- **Gated: sim-time metrics.**  Simulated commit latency (mean/p95), total
  sim duration, and the message volume of the fixed-seed workload are fully
  deterministic — same code, same numbers, on any machine.  A change here
  IS a protocol-behavior change (more round trips, longer dependency
  chains), which is exactly what the gate exists to catch, with zero CI
  flake risk.
- **Printed only: wall-clock metrics.**  commits/s, handler CPU, event-loop
  occupancy differ per machine; they are reported for the human reading the
  log (the tier-1 budget guard prints them every verify run) but never
  fail the gate.

Self-test hook: ``ACCORD_PERFGATE_INJECT_LATENCY=<float>`` multiplies the
measured sim latencies before comparison (``tests/test_perfgate.py`` uses
2.0 to prove the gate trips on a 2x regression without doctoring the tree).

Usage:
    python tools/perfgate.py --smoke            # measure + print deltas, rc 0
    python tools/perfgate.py --gate             # ... rc 3 past thresholds
    python tools/perfgate.py --write-baseline   # refresh BASELINE.json gate
    python bench.py --gate                      # same gate, bench entry point
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

BASELINE_PATH = os.path.join(_REPO_ROOT, "BASELINE.json")

EXIT_REGRESSION = 3

# the fixed smoke workload: deterministic, seconds-class, contended enough
# that commit latency moves when the protocol's round structure changes
SMOKE_SEED = 7
SMOKE_KW = dict(ops=120, concurrency=16, nodes=3, rf=3, key_count=6,
                durability=True, journal=True)

# gated sim-time metrics: (key in summary["sim"], regression threshold as a
# current/baseline ratio).  Latency thresholds are deliberately loose (1.5x)
# — the gate is for "someone made commits take another round trip", not for
# one-bucket jitter; sim metrics have NO run-to-run noise, so anything past
# the threshold is a real behavior change.
GATED_METRICS = (
    ("commit_latency_mean_us", 1.5),
    ("commit_latency_p95_us", 1.5),
    ("sim_ms", 1.5),
    ("messages", 1.5),
    # round 12: the phase share the frontier/CSR work batters down — gated
    # so the deps_execute_wait win is HELD, not just measured once.  A sim
    # share (deterministic, dimensionless): 1.5x means "the execute-wait
    # share grew by half", i.e. someone re-serialized the execution plane.
    ("deps_execute_wait_share", 1.5),
)


def inject_factor() -> float:
    """The ``ACCORD_PERFGATE_INJECT_LATENCY`` self-test multiplier (1.0 =
    off; a malformed value raises — a doctored run must never pass for
    clean).  Single source of truth for every consumer of the hook: the
    measurement rescale below, the ledger-append guards here and in
    bench.py, and ``write_baseline``'s refusal."""
    return float(os.environ.get("ACCORD_PERFGATE_INJECT_LATENCY", "1.0"))


def inject_active() -> bool:
    """True when the self-test hook is doctoring measured latencies — such
    runs must never reach the trend ledger or the baseline."""
    return inject_factor() != 1.0


def measure_smoke(seed: int = SMOKE_SEED) -> dict:
    """Run the smoke workload; returns the gate summary (sim plane + wall
    plane + the latency budget's class shares).  ``seed`` parameterizes the
    multi-seed mode — same workload shape, different trajectory."""
    from cassandra_accord_tpu.harness.burn import run_burn
    from cassandra_accord_tpu.observe import FlightRecorder, WallProfiler
    rec = FlightRecorder()
    prof = WallProfiler()
    t0 = time.perf_counter()
    res = run_burn(seed, observer=rec, profiler=prof, **SMOKE_KW)
    wall_s = time.perf_counter() - t0
    budget = rec.latency_budget()
    cluster_metrics = rec.metrics_snapshot()["cluster"]
    messages = sum(v for k, v in cluster_metrics.items()
                   if k.startswith("link.") and isinstance(v, int))
    wall = prof.report()
    inject = inject_factor()
    return {
        "workload": dict(seed=seed, **SMOKE_KW),
        "sim": {
            "commit_latency_mean_us":
                round(budget["mean_commit_latency_us"] * inject, 1),
            "commit_latency_p95_us": round(budget["p95_us"] * inject, 1),
            "sim_ms": res.sim_micros // 1000,
            "messages": messages,
            "commits": res.ops_ok,
            # the round-12 gated phase share (deps_execute_wait /
            # deps_commit_wait split the old deps wait by pending plane)
            "deps_execute_wait_share": round(
                (budget.get("phases", {}).get("deps_execute_wait") or {})
                .get("share", 0.0), 4),
        },
        "budget_shares": {c: v["share"] for c, v in budget["classes"].items()},
        "dominating_class": budget["dominating_class"],
        "dominating_share": budget["dominating_share"],
        "attributed_share": budget["attributed_share"],
        "wall": {
            "wall_s": round(wall_s, 3),
            "commits_per_sec": round(res.ops_ok / wall_s, 1) if wall_s else None,
            "handler_cpu_s": wall["handler_total_s"],
            "loop_occupancy": wall["scheduler"]["occupancy"],
        },
    }


def load_baseline(path: str = BASELINE_PATH) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f).get("gate")
    except (OSError, ValueError):
        return None


def latest_bench(root: str = _REPO_ROOT) -> Optional[Tuple[str, dict]]:
    """The newest BENCH_r0*.json artifact's parsed content, if any parses."""
    names = sorted(n for n in os.listdir(root)
                   if n.startswith("BENCH_r") and n.endswith(".json"))
    for name in reversed(names):
        try:
            with open(os.path.join(root, name)) as f:
                doc = json.load(f)
            if doc:
                return name, doc
        except (OSError, ValueError):
            continue
    return None


def compare(current: dict, baseline: Optional[dict]) \
        -> Tuple[List[str], List[str]]:
    """Per-metric delta lines + the list of gated failures."""
    lines: List[str] = []
    failures: List[str] = []
    if baseline is None:
        lines.append("perfgate: no baseline recorded (BASELINE.json has no "
                     "'gate' block) — deltas unavailable, nothing gated")
        cur = current["sim"]
        for key, _thresh in GATED_METRICS:
            lines.append(f"  {key:<26} {cur.get(key)}")
        return lines, failures
    base_sim = baseline.get("sim", {})
    cur_sim = current["sim"]
    lines.append(f"perfgate deltas vs baseline "
                 f"(recorded {baseline.get('recorded', '?')}, sim plane is "
                 f"deterministic):")
    for key, thresh in GATED_METRICS:
        cur, base = cur_sim.get(key), base_sim.get(key)
        if cur is None or base is None:
            lines.append(f"  {key:<26} {cur} (baseline {base}: not comparable)")
            continue
        if base == 0:
            # a zero baseline cannot ratio: any nonzero current is a loud
            # regression rather than a silent skip
            if cur > 0:
                failures.append(f"{key}: 0 -> {cur} (baseline is zero)")
                lines.append(f"  {key:<26} 0 -> {cur}  ** REGRESSION "
                             f"(zero baseline)")
            else:
                lines.append(f"  {key:<26} 0 -> 0  (1.000x)")
            continue
        ratio = cur / base
        mark = ""
        if ratio > thresh:
            mark = f"  ** REGRESSION (> {thresh:.2f}x)"
            failures.append(f"{key}: {base} -> {cur} ({ratio:.2f}x, "
                            f"threshold {thresh:.2f}x)")
        elif ratio < 1.0 / thresh:
            mark = "  (improvement)"
        lines.append(f"  {key:<26} {base} -> {cur}  ({ratio:.3f}x){mark}")
    dom = current.get("dominating_class")
    if dom:
        lines.append(f"  commit budget: {dom} dominates at "
                     f"{100.0 * current['dominating_share']:.1f}% "
                     f"({100.0 * current['attributed_share']:.1f}% attributed)"
                     + (f"; baseline {baseline.get('dominating_class')} at "
                        f"{100.0 * baseline.get('dominating_share', 0):.1f}%"
                        if baseline.get("dominating_class") else ""))
    base_wall = baseline.get("wall", {})
    cur_wall = current.get("wall", {})
    if cur_wall.get("commits_per_sec"):
        line = f"  wall (printed, never gated): " \
               f"{cur_wall['commits_per_sec']} commits/s, " \
               f"{cur_wall['handler_cpu_s']}s handler CPU, " \
               f"occupancy {cur_wall['loop_occupancy']}"
        if base_wall.get("commits_per_sec"):
            line += f"  (baseline {base_wall['commits_per_sec']} commits/s)"
        lines.append(line)
    bench = latest_bench()
    if bench is not None:
        name, doc = bench
        value = doc.get("value") or (doc.get("detail") or {}).get("value")
        if value:
            lines.append(f"  latest bench artifact {name}: "
                         f"{doc.get('metric')} = {value}")
    return lines, failures


def baseline_sim_for(baseline: Optional[dict], seed: int) -> Optional[dict]:
    """The baseline's sim block for one seed: the per-seed ``seeds`` table
    when recorded (``--write-baseline --seeds``), else the default block for
    the default smoke seed."""
    if baseline is None:
        return None
    per_seed = baseline.get("seeds") or {}
    if str(seed) in per_seed:
        return per_seed[str(seed)].get("sim")
    base_seed = (baseline.get("workload") or {}).get("seed", SMOKE_SEED)
    if seed == base_seed:
        return baseline.get("sim")
    return None


def compare_multi(per_seed: Dict[int, dict], baseline: Optional[dict]) \
        -> Tuple[List[str], List[str]]:
    """Multi-seed gating, per the KNOWN_ISSUES "trajectory sensitivity"
    note: single-seed hostile trajectories are knife-edge chaotic, so the
    gate judges the MEDIAN of the per-seed current/baseline ratios — one
    chaotic seed cannot trip (or mask) a regression alone."""
    import statistics
    lines: List[str] = []
    failures: List[str] = []
    seeds = sorted(per_seed)
    lines.append(f"perfgate multi-seed deltas (seeds {seeds}, gating on the "
                 f"MEDIAN per-metric ratio):")
    if baseline is None:
        lines.append("  no baseline recorded — nothing gated")
        return lines, failures
    for key, thresh in GATED_METRICS:
        ratios = []
        per_seed_bits = []
        for seed in seeds:
            cur = per_seed[seed]["sim"].get(key)
            base_sim = baseline_sim_for(baseline, seed) or {}
            base = base_sim.get(key)
            if cur is None or base is None or base == 0:
                per_seed_bits.append(f"s{seed}:{cur}/{base}?")
                continue
            ratios.append(cur / base)
            per_seed_bits.append(f"s{seed}:{cur / base:.3f}x")
        if not ratios:
            lines.append(f"  {key:<26} not comparable "
                         f"({' '.join(per_seed_bits)}) — record per-seed "
                         f"baselines with --write-baseline --seeds")
            continue
        med = statistics.median(ratios)
        mark = ""
        if med > thresh:
            mark = f"  ** REGRESSION (median > {thresh:.2f}x)"
            failures.append(f"{key}: median {med:.2f}x over "
                            f"{len(ratios)} seeds (threshold {thresh:.2f}x)")
        elif med < 1.0 / thresh:
            mark = "  (improvement)"
        lines.append(f"  {key:<26} median {med:.3f}x "
                     f"({' '.join(per_seed_bits)}){mark}")
    return lines, failures


def _median_sim(per_seed: Dict[int, dict]) -> dict:
    """Per-metric median of the sim planes (the trend-ledger record for a
    multi-seed run)."""
    import statistics
    out = {}
    for key, _thresh in GATED_METRICS:
        vals = [s["sim"][key] for s in per_seed.values()
                if s["sim"].get(key) is not None]
        if vals:
            out[key] = statistics.median(vals)
    return out


def _print_trend(out) -> None:
    """The cross-run ledger context (tools/trend.py): the last-K recorded
    runs' sim-metric trajectory, printed next to the baseline delta."""
    try:
        from tools.trend import load_history, trend_lines
        entries = load_history()
        for line in trend_lines(entries, last_k=5):
            print(line, file=out, flush=True)
    except Exception as e:  # noqa: BLE001 — trend context must not fail the gate
        print(f"trend: <unavailable: {e!r}>", file=out, flush=True)


def run(gate: bool, baseline_path: str = BASELINE_PATH,
        current: Optional[dict] = None, out=None,
        seeds: Optional[List[int]] = None) -> int:
    """Measure (unless ``current`` given), print deltas + the cross-run
    trend, return the exit code (0, or EXIT_REGRESSION when ``gate`` and a
    threshold tripped).  ``seeds`` switches to per-seed measurement with
    median gating (a single listed seed is measured AS THAT SEED — never
    silently replaced by the default smoke seed) and is mutually exclusive
    with ``current`` (an artifact carries one seed's measurement; re-running
    live would gate the wrong tree state).  A measurement taken here is
    appended to the trend ledger (BENCH_HISTORY.jsonl)."""
    out = out or sys.stdout
    if seeds and current is not None:
        raise ValueError("--current and --seeds are mutually exclusive: a "
                         "saved artifact holds one seed's measurement; "
                         "gate it with plain --current")
    measured_here = current is None
    history_record = None
    if seeds:
        per_seed = {}
        for seed in seeds:
            per_seed[seed] = measure_smoke(seed)
            sim = per_seed[seed]["sim"]
            print(f"perfgate seed {seed}: " + " ".join(
                f"{k}={sim.get(k)}" for k, _t in GATED_METRICS),
                file=out, flush=True)
        lines, failures = compare_multi(per_seed, load_baseline(baseline_path))
        history_record = {"kind": "perfgate", "seeds": sorted(per_seed),
                          "sim": _median_sim(per_seed)}
    else:
        if current is None:
            current = measure_smoke()
        lines, failures = compare(current, load_baseline(baseline_path))
        if measured_here:
            history_record = {"kind": "perfgate",
                              "seeds": [current["workload"]["seed"]],
                              "sim": dict(current["sim"])}
    for line in lines:
        print(line, file=out, flush=True)
    if inject_active():
        # the documented self-test hook doctors the measured latencies — a
        # ledger record of it would read as a real 2x regression in every
        # later trend report
        history_record = None
    if history_record is not None:
        # the ledger grows as a side effect of runs that already happen
        try:
            from tools.trend import append_entry
            append_entry(history_record)
        except Exception:  # noqa: BLE001 — the ledger must not fail the gate
            pass
    _print_trend(out)
    if failures:
        verdict = "perfgate: " + ("FAIL — " if gate else "regressions "
                                  "detected (print-only mode) — ") \
            + "; ".join(failures)
        print(verdict, file=out, flush=True)
        return EXIT_REGRESSION if gate else 0
    print("perfgate: PASS (no gated metric past threshold)", file=out,
          flush=True)
    return 0


def write_baseline(path: str = BASELINE_PATH,
                   seeds: Optional[List[int]] = None) -> dict:
    """Measure and record the gate baseline into BASELINE.json['gate'];
    ``seeds`` additionally records a per-seed ``seeds`` table (the sim
    planes the multi-seed median gate compares against)."""
    import datetime
    if inject_active():
        # a doctored baseline would make every future REAL regression gate
        # clean — refuse loudly rather than record it
        raise RuntimeError(
            "refusing --write-baseline with ACCORD_PERFGATE_INJECT_LATENCY "
            "set: the doctored latencies would become the baseline and "
            "silently defeat the gate")
    summary = measure_smoke()
    summary["recorded"] = datetime.date.today().isoformat()
    if seeds:
        summary["seeds"] = {
            str(seed): {"sim": (summary["sim"] if seed == SMOKE_SEED
                                else measure_smoke(seed)["sim"])}
            for seed in seeds}
    with open(path) as f:
        doc = json.load(f)
    doc["gate"] = summary
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return summary


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    mode = p.add_mutually_exclusive_group(required=True)
    mode.add_argument("--smoke", action="store_true",
                      help="measure + print deltas vs baseline; ALWAYS exit "
                           "0 (the tier-1 budget guard's per-verify report)")
    mode.add_argument("--gate", action="store_true",
                      help=f"measure + compare; exit {EXIT_REGRESSION} when "
                           f"a gated sim metric regresses past threshold")
    mode.add_argument("--write-baseline", action="store_true",
                      help="measure and record the result as the new "
                           "BASELINE.json gate block")
    p.add_argument("--baseline", default=BASELINE_PATH,
                   help="baseline JSON path (default: repo BASELINE.json)")
    p.add_argument("--current", default=None, metavar="PATH",
                   help="compare a saved measure_smoke() summary instead of "
                        "measuring (offline gating of an artifact)")
    p.add_argument("--seeds", default=None, metavar="A,B,C",
                   help="multi-seed mode: measure every listed seed and "
                        "gate on the MEDIAN per-metric ratio (per the "
                        "KNOWN_ISSUES trajectory-sensitivity note that "
                        "single-seed regressions are knife-edge chaotic); "
                        "with --write-baseline, records the per-seed "
                        "baseline table")
    args = p.parse_args(argv)
    seeds = None
    if args.seeds:
        seeds = [int(s) for s in args.seeds.split(",") if s.strip()]
    if args.write_baseline:
        summary = write_baseline(args.baseline, seeds=seeds)
        print(json.dumps(summary["sim"], sort_keys=True))
        print(f"perfgate: baseline written to {args.baseline}"
              + (f" (per-seed table for {seeds})" if seeds else ""))
        return 0
    current = None
    if args.current:
        if seeds:
            p.error("--current and --seeds are mutually exclusive (a saved "
                    "artifact is one seed's measurement)")
        with open(args.current) as f:
            current = json.load(f)
    return run(gate=args.gate, baseline_path=args.baseline, current=current,
               seeds=seeds)


if __name__ == "__main__":
    raise SystemExit(main())
