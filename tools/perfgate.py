"""perfgate — the commit-latency regression gate.

Measures a small FIXED-SEED smoke workload through the full simulated
cluster with the flight recorder + critical-path extractor attached, then
compares the result against the recorded baseline (``BASELINE.json``'s
``gate`` block) and — informationally — against the latest ``BENCH_r0*.json``
headline.  Prints per-metric deltas; in ``--gate`` mode exits nonzero
(``EXIT_REGRESSION``) when any GATED metric regresses past its threshold.

What is gated vs merely printed:

- **Gated: sim-time metrics.**  Simulated commit latency (mean/p95), total
  sim duration, and the message volume of the fixed-seed workload are fully
  deterministic — same code, same numbers, on any machine.  A change here
  IS a protocol-behavior change (more round trips, longer dependency
  chains), which is exactly what the gate exists to catch, with zero CI
  flake risk.
- **Printed only: wall-clock metrics.**  commits/s, handler CPU, event-loop
  occupancy differ per machine; they are reported for the human reading the
  log (the tier-1 budget guard prints them every verify run) but never
  fail the gate.

Self-test hook: ``ACCORD_PERFGATE_INJECT_LATENCY=<float>`` multiplies the
measured sim latencies before comparison (``tests/test_perfgate.py`` uses
2.0 to prove the gate trips on a 2x regression without doctoring the tree).

Usage:
    python tools/perfgate.py --smoke            # measure + print deltas, rc 0
    python tools/perfgate.py --gate             # ... rc 3 past thresholds
    python tools/perfgate.py --write-baseline   # refresh BASELINE.json gate
    python bench.py --gate                      # same gate, bench entry point
"""
from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

BASELINE_PATH = os.path.join(_REPO_ROOT, "BASELINE.json")

EXIT_REGRESSION = 3

# the fixed smoke workload: deterministic, seconds-class, contended enough
# that commit latency moves when the protocol's round structure changes
SMOKE_SEED = 7
SMOKE_KW = dict(ops=120, concurrency=16, nodes=3, rf=3, key_count=6,
                durability=True, journal=True)

# gated sim-time metrics: (key in summary["sim"], regression threshold as a
# current/baseline ratio).  Latency thresholds are deliberately loose (1.5x)
# — the gate is for "someone made commits take another round trip", not for
# one-bucket jitter; sim metrics have NO run-to-run noise, so anything past
# the threshold is a real behavior change.
GATED_METRICS = (
    ("commit_latency_mean_us", 1.5),
    ("commit_latency_p95_us", 1.5),
    ("sim_ms", 1.5),
    ("messages", 1.5),
)


def measure_smoke() -> dict:
    """Run the smoke workload; returns the gate summary (sim plane + wall
    plane + the latency budget's class shares)."""
    from cassandra_accord_tpu.harness.burn import run_burn
    from cassandra_accord_tpu.observe import FlightRecorder, WallProfiler
    rec = FlightRecorder()
    prof = WallProfiler()
    t0 = time.perf_counter()
    res = run_burn(SMOKE_SEED, observer=rec, profiler=prof, **SMOKE_KW)
    wall_s = time.perf_counter() - t0
    budget = rec.latency_budget()
    cluster_metrics = rec.metrics_snapshot()["cluster"]
    messages = sum(v for k, v in cluster_metrics.items()
                   if k.startswith("link.") and isinstance(v, int))
    wall = prof.report()
    inject = float(os.environ.get("ACCORD_PERFGATE_INJECT_LATENCY", "1.0"))
    return {
        "workload": dict(seed=SMOKE_SEED, **SMOKE_KW),
        "sim": {
            "commit_latency_mean_us":
                round(budget["mean_commit_latency_us"] * inject, 1),
            "commit_latency_p95_us": round(budget["p95_us"] * inject, 1),
            "sim_ms": res.sim_micros // 1000,
            "messages": messages,
            "commits": res.ops_ok,
        },
        "budget_shares": {c: v["share"] for c, v in budget["classes"].items()},
        "dominating_class": budget["dominating_class"],
        "dominating_share": budget["dominating_share"],
        "attributed_share": budget["attributed_share"],
        "wall": {
            "wall_s": round(wall_s, 3),
            "commits_per_sec": round(res.ops_ok / wall_s, 1) if wall_s else None,
            "handler_cpu_s": wall["handler_total_s"],
            "loop_occupancy": wall["scheduler"]["occupancy"],
        },
    }


def load_baseline(path: str = BASELINE_PATH) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f).get("gate")
    except (OSError, ValueError):
        return None


def latest_bench(root: str = _REPO_ROOT) -> Optional[Tuple[str, dict]]:
    """The newest BENCH_r0*.json artifact's parsed content, if any parses."""
    names = sorted(n for n in os.listdir(root)
                   if n.startswith("BENCH_r") and n.endswith(".json"))
    for name in reversed(names):
        try:
            with open(os.path.join(root, name)) as f:
                doc = json.load(f)
            if doc:
                return name, doc
        except (OSError, ValueError):
            continue
    return None


def compare(current: dict, baseline: Optional[dict]) \
        -> Tuple[List[str], List[str]]:
    """Per-metric delta lines + the list of gated failures."""
    lines: List[str] = []
    failures: List[str] = []
    if baseline is None:
        lines.append("perfgate: no baseline recorded (BASELINE.json has no "
                     "'gate' block) — deltas unavailable, nothing gated")
        cur = current["sim"]
        for key, _thresh in GATED_METRICS:
            lines.append(f"  {key:<26} {cur.get(key)}")
        return lines, failures
    base_sim = baseline.get("sim", {})
    cur_sim = current["sim"]
    lines.append(f"perfgate deltas vs baseline "
                 f"(recorded {baseline.get('recorded', '?')}, sim plane is "
                 f"deterministic):")
    for key, thresh in GATED_METRICS:
        cur, base = cur_sim.get(key), base_sim.get(key)
        if cur is None or base is None:
            lines.append(f"  {key:<26} {cur} (baseline {base}: not comparable)")
            continue
        if base == 0:
            # a zero baseline cannot ratio: any nonzero current is a loud
            # regression rather than a silent skip
            if cur > 0:
                failures.append(f"{key}: 0 -> {cur} (baseline is zero)")
                lines.append(f"  {key:<26} 0 -> {cur}  ** REGRESSION "
                             f"(zero baseline)")
            else:
                lines.append(f"  {key:<26} 0 -> 0  (1.000x)")
            continue
        ratio = cur / base
        mark = ""
        if ratio > thresh:
            mark = f"  ** REGRESSION (> {thresh:.2f}x)"
            failures.append(f"{key}: {base} -> {cur} ({ratio:.2f}x, "
                            f"threshold {thresh:.2f}x)")
        elif ratio < 1.0 / thresh:
            mark = "  (improvement)"
        lines.append(f"  {key:<26} {base} -> {cur}  ({ratio:.3f}x){mark}")
    dom = current.get("dominating_class")
    if dom:
        lines.append(f"  commit budget: {dom} dominates at "
                     f"{100.0 * current['dominating_share']:.1f}% "
                     f"({100.0 * current['attributed_share']:.1f}% attributed)"
                     + (f"; baseline {baseline.get('dominating_class')} at "
                        f"{100.0 * baseline.get('dominating_share', 0):.1f}%"
                        if baseline.get("dominating_class") else ""))
    base_wall = baseline.get("wall", {})
    cur_wall = current.get("wall", {})
    if cur_wall.get("commits_per_sec"):
        line = f"  wall (printed, never gated): " \
               f"{cur_wall['commits_per_sec']} commits/s, " \
               f"{cur_wall['handler_cpu_s']}s handler CPU, " \
               f"occupancy {cur_wall['loop_occupancy']}"
        if base_wall.get("commits_per_sec"):
            line += f"  (baseline {base_wall['commits_per_sec']} commits/s)"
        lines.append(line)
    bench = latest_bench()
    if bench is not None:
        name, doc = bench
        value = doc.get("value") or (doc.get("detail") or {}).get("value")
        if value:
            lines.append(f"  latest bench artifact {name}: "
                         f"{doc.get('metric')} = {value}")
    return lines, failures


def run(gate: bool, baseline_path: str = BASELINE_PATH,
        current: Optional[dict] = None, out=None) -> int:
    """Measure (unless ``current`` given), print deltas, return the exit
    code (0, or EXIT_REGRESSION when ``gate`` and a threshold tripped)."""
    out = out or sys.stdout
    if current is None:
        current = measure_smoke()
    lines, failures = compare(current, load_baseline(baseline_path))
    for line in lines:
        print(line, file=out, flush=True)
    if failures:
        verdict = "perfgate: " + ("FAIL — " if gate else "regressions "
                                  "detected (print-only mode) — ") \
            + "; ".join(failures)
        print(verdict, file=out, flush=True)
        return EXIT_REGRESSION if gate else 0
    print("perfgate: PASS (no gated metric past threshold)", file=out,
          flush=True)
    return 0


def write_baseline(path: str = BASELINE_PATH) -> dict:
    """Measure and record the gate baseline into BASELINE.json['gate']."""
    import datetime
    summary = measure_smoke()
    summary["recorded"] = datetime.date.today().isoformat()
    with open(path) as f:
        doc = json.load(f)
    doc["gate"] = summary
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return summary


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    mode = p.add_mutually_exclusive_group(required=True)
    mode.add_argument("--smoke", action="store_true",
                      help="measure + print deltas vs baseline; ALWAYS exit "
                           "0 (the tier-1 budget guard's per-verify report)")
    mode.add_argument("--gate", action="store_true",
                      help=f"measure + compare; exit {EXIT_REGRESSION} when "
                           f"a gated sim metric regresses past threshold")
    mode.add_argument("--write-baseline", action="store_true",
                      help="measure and record the result as the new "
                           "BASELINE.json gate block")
    p.add_argument("--baseline", default=BASELINE_PATH,
                   help="baseline JSON path (default: repo BASELINE.json)")
    p.add_argument("--current", default=None, metavar="PATH",
                   help="compare a saved measure_smoke() summary instead of "
                        "measuring (offline gating of an artifact)")
    args = p.parse_args(argv)
    if args.write_baseline:
        summary = write_baseline(args.baseline)
        print(json.dumps(summary["sim"], sort_keys=True))
        print(f"perfgate: baseline written to {args.baseline}")
        return 0
    current = None
    if args.current:
        with open(args.current) as f:
            current = json.load(f)
    return run(gate=args.gate, baseline_path=args.baseline, current=current)


if __name__ == "__main__":
    raise SystemExit(main())
