"""Ad-hoc chaos-burn debugger: trace messages about specific ops."""
import sys

from cassandra_accord_tpu.harness.burn import run_burn, SimulationException

SEED = int(sys.argv[1]) if len(sys.argv) > 1 else 4
OPS = int(sys.argv[2]) if len(sys.argv) > 2 else 60
WATCH_OPS = [int(x) for x in sys.argv[3].split(",")] if len(sys.argv) > 3 else [10, 25]

op_txn = {}          # op_id -> txn_id
txn_op = {}          # txn_id -> op_id
events = []

def on_submit(op_id, txn_id, txn, coord):
    op_txn[op_id] = txn_id
    txn_op[txn_id] = op_id
    events.append((None, f"SUBMIT op{op_id} {txn_id} kind={txn.kind.name} "
                   f"keys={txn.keys} coord=n{coord}"))

def tracer(event, frm, to, msg_id, message, now):
    tid = getattr(message, "txn_id", None)
    if tid is None or tid not in txn_op:
        return
    op = txn_op[tid]
    if op not in WATCH_OPS:
        return
    desc = f"{type(message).__name__}"
    for attr in ("deps", "partial_deps"):
        d = getattr(message, attr, None)
        if d is not None:
            try:
                ids = sorted({txn_op.get(t, t) for t in d.txn_ids()})
                desc += f" deps={ids}"
            except Exception:
                pass
    ss = getattr(message, "save_status", None)
    if ss is not None:
        desc += f" ss={ss.name}"
    ea = getattr(message, "execute_at", None)
    if ea is not None:
        desc += f" ea={ea}"
    events.append((now, f"{now/1e6:9.3f} {event:18s} n{frm}->n{to} #{msg_id} op{op} {desc}"))

try:
    r = run_burn(SEED, ops=OPS, concurrency=10, chaos=True, allow_failures=True,
                 tracer=tracer, on_submit=on_submit)
    print("OK", r)
except SimulationException as e:
    print("FAIL", str(e.cause)[:200])
print(f"--- {len(events)} events for ops {WATCH_OPS} ---")
for _, line in events:
    print(line)
