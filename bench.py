"""Benchmark: conflicting-txn dependency-resolution throughput on the device
data plane (the BASELINE.md contention metric).

Workload: batches of B txns against a T-slot in-flight conflict graph with
50% key contention (half of each batch hits an 8-key hot set, half uniform
over K key slots), driven through the full fused step
(overlap-join -> conflict-max -> insert -> stabilise -> execution frontier)
= models.conflict_graph.txn_step, with slot recycling.

Baseline: the same dependency resolution executed the scalar way (per-txn
Python/numpy loop over the in-flight index — the shape of the reference's
per-key CommandsForKey.mapReduceActive scans, cfk/CommandsForKey.java:925),
measured on a sample and extrapolated.  ``vs_baseline`` is the speedup.

Prints ONE JSON line.
"""
import json
import time

import numpy as np


T, K, B = 4096, 512, 256
HOT_KEYS = 8
ITERS = 50
EPOCH = 1


def _make_batches(rng, n_batches):
    """Pre-built numpy batches: 50% of txns on the hot key set."""
    batches = []
    hlc = 1000
    for bi in range(n_batches):
        key_inc = np.zeros((B, K), dtype=np.int8)
        hot = rng.random(B) < 0.5
        for i in range(B):
            if hot[i]:
                keys = rng.choice(HOT_KEYS, 2, replace=False)
            else:
                keys = HOT_KEYS + rng.choice(K - HOT_KEYS, 2, replace=False)
            key_inc[i, keys] = 1
        lanes = np.zeros((B, 5), dtype=np.int32)
        lanes[:, 0] = EPOCH
        lanes[:, 2] = hlc + np.arange(B)            # hlc_lo (hlc < 2^31)
        lanes[:, 4] = rng.integers(1, 16, B)        # node
        hlc += B
        kinds = rng.choice([0, 1], B).astype(np.int8)  # reads + writes
        slots = (np.arange(B, dtype=np.int32) + bi * B) % T
        batches.append((slots, key_inc, lanes, kinds))
    return batches


def bench_device(batches):
    import jax
    import jax.numpy as jnp
    from cassandra_accord_tpu import ops
    from cassandra_accord_tpu.models import TxnBatch

    from cassandra_accord_tpu.models import txn_step_scan

    state = ops.init_state(T, K)
    n = len(batches)
    stacked = TxnBatch(
        slots=jnp.asarray(np.stack([b[0] for b in batches])),
        key_inc=jnp.asarray(np.stack([b[1] for b in batches])),
        txn_id=jnp.asarray(np.stack([b[2] for b in batches])),
        kind=jnp.asarray(np.stack([b[3] for b in batches])),
        valid=jnp.ones((n, B), dtype=jnp.bool_))
    # warmup/compile on a copy
    warm_state, counts = txn_step_scan(ops.init_state(T, K), stacked)
    jax.block_until_ready(counts)
    t0 = time.perf_counter()
    state, counts = txn_step_scan(state, stacked)
    jax.block_until_ready(counts)
    dt = time.perf_counter() - t0
    return n * B / dt


def bench_host_scalar(batches, sample_txns=64):
    """Scalar per-txn resolver over the same index shapes (baseline stand-in
    for the reference's per-key scans)."""
    key_inc = np.zeros((T, K), dtype=np.int8)
    lanes = np.zeros((T, 5), dtype=np.int64)
    active = np.zeros(T, dtype=bool)
    # fill the index to steady state occupancy
    rng = np.random.default_rng(1)
    occ = rng.integers(0, len(batches), T)
    for s, k, l, kd in batches[:4]:
        key_inc[s] = k
        lanes[s] = l
        active[s] = True
    done = 0
    t0 = time.perf_counter()
    for s, k, l, kd in batches:
        for i in range(B):
            if done >= sample_txns:
                break
            # per-txn scan: key overlap + started-before over whole index
            overlap = (key_inc & k[i]).any(axis=1) & active
            tid = tuple(l[i])
            for t in np.nonzero(overlap)[0]:
                _ = tuple(lanes[t]) < tid
            # max-conflict
            if overlap.any():
                _ = lanes[overlap].max(axis=0)
            done += 1
        if done >= sample_txns:
            break
    dt = time.perf_counter() - t0
    return done / dt


def main():
    rng = np.random.default_rng(42)
    batches = _make_batches(rng, ITERS)
    device_tps = bench_device(batches)
    host_tps = bench_host_scalar(batches)
    print(json.dumps({
        "metric": "contended_deps_txn_per_sec",
        "value": round(device_tps, 1),
        "unit": "txn/s",
        "vs_baseline": round(device_tps / host_tps, 2),
    }))


if __name__ == "__main__":
    main()
