"""Benchmarks: protocol-level end-to-end throughput + device-kernel scaling.

PRIMARY METRIC (protocol level, the BASELINE.md contention metric): commits/s
of the FULL simulated Accord cluster — coordinators, replicas, quorums, the
works — on a contended workload, comparing the two deps-resolver data planes
behind the same protocol code (impl/resolver.py boundary):

- resolver=cpu : the host reference data plane (per-key CommandsForKey walks,
                 the shape of cfk/CommandsForKey.java:925-1000).
- resolver=tpu : the device data plane (impl/tpu_resolver.py) with delivery-
                 window batching (harness/cluster.py batch_window_us): each
                 window's PreAccept/Accept consults are answered by ONE fused
                 MXU launch (ops.deps_kernels.consult).

Output: the full-detail RESULT object, then — as the LAST stdout line — a
compact single-line JSON summary (metric/value/unit/vs_baseline + per-stage
health) sized to survive the harness's bounded tail capture.

``vs_baseline`` is tpu/cpu on identical seed+workload — an honest end-to-end
comparison, not a strawman.  NOTE the cpu baseline here is this repo's Python
host walk, not the reference JVM (stated per VERDICT r02 task #2).

SECONDARY (kernel level): fused-consult throughput at T in {4096, 65536}
in-flight txns vs a numpy-VECTORIZED host baseline (the strongest host
implementation of the same join — labeled host_numpy; the old pure-Python
scalar walk is reported as host_python_scalar, measured on a sample).

Prints ONE JSON line.
"""
import json
import os
import time
from typing import Optional

import numpy as np

os.environ.setdefault("ACCORD_TPU_TXN_SLOTS", "1024")
os.environ.setdefault("ACCORD_TPU_KEY_SLOTS", "64")
os.environ.setdefault("ACCORD_TPU_WALK_MAX", "512")   # tuned: cost-ladder knee
TPU_WINDOW_US = 5_000                                  # tuned delivery window


# ---------------------------------------------------------------------------
# protocol-level: same seed + workload through both resolver data planes
# ---------------------------------------------------------------------------

PROTO_SEED = 7
# deep-contention config (the BASELINE.md config-3 shape: few keys, deep deps
# chains): per-key histories grow into the thousands, where the
# reference-shaped per-key walk scans O(history) per query and the array
# consult (one vectorized pass / one MXU launch per delivery window) is flat
PROTO_OPS = 1200
PROTO_CONC = 64
# durability=True: scheduled durability rounds advance the majority
# watermarks that GATE transitive elision (the soundness gate) — without
# them deps grow O(history) and the bench measures an unrealistic regime
# (real deployments always run durability; GC depends on it)
PROTO_KW = dict(nodes=3, rf=3, key_count=6, num_shards=1, durability=True)


def bench_protocol(resolver: str, batch_window_us: int, ops: int = PROTO_OPS,
                   reps: int = 2):
    from cassandra_accord_tpu.harness.burn import run_burn
    best, res = 0.0, None
    for _ in range(reps):
        t0 = time.perf_counter()
        res = run_burn(seed=PROTO_SEED, ops=ops, concurrency=PROTO_CONC,
                       resolver=resolver, batch_window_us=batch_window_us,
                       **PROTO_KW)
        dt = time.perf_counter() - t0
        best = max(best, res.ops_ok / dt)
    return best, res


# ---------------------------------------------------------------------------
# protocol ramp: commits/s vs in-flight concurrency, columnar on vs off
# ---------------------------------------------------------------------------

RAMP_LEVELS = (8, 32, 128)
RAMP_OPS = 400


def bench_protocol_ramp(levels=RAMP_LEVELS, ops: int = RAMP_OPS):
    """The ROADMAP item-1 oracle: ``protocol_commits_per_sec`` must SCALE
    with in-flight concurrency instead of flatlining.  Runs the fixed-seed
    contended workload at each concurrency level with the columnar protocol
    engine on and off.  Two rates per run:

    - ``sim``: commits per SIM second — deterministic, identical on-vs-off
      by the engine's byte-identity contract; this is the protocol-level
      scaling curve (the round-10 timeline ramp oracle);
    - ``wall``: commits per WALL second — the machine-dependent number the
      43-commits/s wall was measured in; columnar on-vs-off deltas here are
      the engine's whole point.
    """
    from cassandra_accord_tpu.harness.burn import run_burn
    out = {"levels": list(levels), "ops": ops, "seed": PROTO_SEED,
           "workload": dict(ops=ops, seed=PROTO_SEED, **PROTO_KW)}
    # warm the process (imports, allocator) so the first measured mode
    # doesn't eat the cold start, and INTERLEAVE modes per level — a
    # mode-major order systematically biases against whichever runs first
    run_burn(seed=PROTO_SEED, ops=40, concurrency=levels[0], **PROTO_KW)
    rates = {"on": {"wall": [], "sim": []}, "off": {"wall": [], "sim": []}}
    for conc in levels:
        for mode in ("on", "off"):
            t0 = time.perf_counter()
            res = run_burn(seed=PROTO_SEED, ops=ops, concurrency=conc,
                           columnar=mode, **PROTO_KW)
            dt = time.perf_counter() - t0
            rates[mode]["wall"].append(round(res.ops_ok / dt, 1)
                                       if dt else None)
            rates[mode]["sim"].append(
                round(res.ops_ok / (res.sim_micros / 1e6), 1)
                if res.sim_micros else None)
            if mode == "on":
                out["columnar_stats"] = {
                    k: v for k, v in res.stats.items()
                    if k.startswith("columnar_")}
    for mode in ("on", "off"):
        out[f"columnar_{mode}"] = {
            "commits_per_sec_wall": rates[mode]["wall"],
            "commits_per_sec_sim": rates[mode]["sim"]}
    on = out["columnar_on"]
    sim = on["commits_per_sec_sim"]
    wall = on["commits_per_sec_wall"]
    out["protocol_commits_per_sec"] = wall[-1]
    out["sim_ramp_scaling"] = round(sim[-1] / sim[0], 3) \
        if sim[0] and sim[-1] else None
    off_wall = out["columnar_off"]["commits_per_sec_wall"]
    out["columnar_wall_speedup"] = [
        round(a / b, 3) if a and b else None for a, b in zip(wall, off_wall)]
    return out


# ---------------------------------------------------------------------------
# kernel-level: fused consult vs vectorized-numpy host at scale
# ---------------------------------------------------------------------------



def _make_index(rng, t, k, hot=8, keys_per_txn=2):
    """A contended in-flight index: 50% of txns on the hot key set (wide
    range-join shapes — keys_per_txn > hot — draw uniformly instead)."""
    key_inc = np.zeros((t, k), dtype=np.int8)
    hot_mask = rng.random(t) < 0.5
    wide = keys_per_txn > hot
    for i in range(t):
        if wide:
            key_inc[i, rng.choice(k, keys_per_txn, replace=False)] = 1
            continue
        pool = hot if hot_mask[i] else k - hot
        off = 0 if hot_mask[i] else hot
        key_inc[i, off + rng.choice(pool, keys_per_txn, replace=False)] = 1
    lanes = np.zeros((t, 5), dtype=np.int32)
    lanes[:, 0] = 1
    lanes[:, 2] = 1000 + rng.permutation(t)
    lanes[:, 4] = rng.integers(1, 16, t)
    kind = rng.choice([0, 1], t).astype(np.int8)
    status = rng.choice([1, 2, 3, 4], t).astype(np.int8)
    active = np.ones(t, dtype=bool)
    return key_inc, lanes, kind, status, active


def _make_queries(rng, b, k, t, hot=8, keys_per_txn=2):
    q = np.zeros((b, k), dtype=np.int8)
    hot_mask = rng.random(b) < 0.5
    wide = keys_per_txn > hot
    for i in range(b):
        if wide:
            q[i, rng.choice(k, keys_per_txn, replace=False)] = 1
            continue
        pool = hot if hot_mask[i] else k - hot
        off = 0 if hot_mask[i] else hot
        q[i, off + rng.choice(pool, keys_per_txn, replace=False)] = 1
    before = np.zeros((b, 5), dtype=np.int32)
    before[:, 0] = 1
    before[:, 2] = 1000 + t + rng.integers(0, t, b)
    before[:, 4] = rng.integers(1, 16, b)
    kind = rng.choice([0, 1], b).astype(np.int8)
    return q, before, kind


def make_host_tier(key_inc, ts, txn_id, kind, status, active):
    """The host tier of the SAME fused consult — the resolver's own
    vectorized-numpy implementation (impl.tpu_resolver._consult_host), driven
    directly so the baseline cannot drift from the shipped semantics."""
    from cassandra_accord_tpu.impl.tpu_resolver import TpuDepsResolver
    r = TpuDepsResolver.__new__(TpuDepsResolver)   # host tier needs only _h
    r.host_consults = 0
    r._host_engine = "numpy"   # bare instance: skip the native-engine probe
    # no covered bits in the synthetic index: live == full incidence
    r._h = {"key_inc": key_inc, "key_inc_f32": key_inc.T.astype(np.float32),
            "live_f32": key_inc.T.astype(np.float32),
            "ts": ts, "txn_id": txn_id, "kind": kind, "status": status,
            "active": active}
    return lambda q, before, qkind: r._consult_host(q, before, qkind)


def host_python_scalar(key_inc, txn_id, active, q, before, sample=32):
    """The reference-shaped per-txn scalar walk, on a sample (extrapolated)."""
    done = 0
    t0 = time.perf_counter()
    for i in range(min(sample, q.shape[0])):
        overlap = (key_inc & q[i]).any(axis=1) & active
        bound = tuple(before[i])
        for s in np.nonzero(overlap)[0]:
            _ = tuple(txn_id[s]) < bound
        if overlap.any():
            _ = txn_id[overlap].max(axis=0)
        done += 1
    return done / (time.perf_counter() - t0)


def bench_kernel(t, k=512, b=256, iters=20, keys_per_txn=2, packed=False):
    import jax
    import jax.numpy as jnp
    from cassandra_accord_tpu.ops import deps_kernels as dk
    rng = np.random.default_rng(42)
    key_inc, lanes, kind, status, active = _make_index(rng, t, k,
                                                       keys_per_txn=keys_per_txn)
    q, before, qkind = _make_queries(rng, b, k, t, keys_per_txn=keys_per_txn)
    index_dev = [jnp.asarray(x) for x in
                 (key_inc, key_inc, lanes, lanes, kind, status, active)]
    # DISTINCT query batch per iteration: identical repeated computations can
    # be served from caches (driver/tunnel level) and would overstate rates
    batches = []
    for _ in range(iters):
        qi, bi, ki = _make_queries(rng, b, k, t, keys_per_txn=keys_per_txn)
        batches.append((jnp.asarray(qi), jnp.asarray(bi), jnp.asarray(ki)))
    kernel = dk.consult_packed if packed else dk.consult
    # warmup/compile
    jax.block_until_ready(kernel(*index_dev, jnp.asarray(q),
                                 jnp.asarray(before), jnp.asarray(qkind)))
    t0 = time.perf_counter()
    outs = [kernel(*index_dev, *bt) for bt in batches]
    jax.block_until_ready(outs)
    dev_qps = iters * b / (time.perf_counter() - t0)
    # numpy-vectorized host baseline: the resolver's own host tier
    host_tier = make_host_tier(key_inc, lanes, lanes, kind, status, active)
    t0 = time.perf_counter()
    for _ in range(3):
        host_tier(q, before, qkind)
    np_qps = 3 * b / (time.perf_counter() - t0)
    # native C++ host engine (native/consult.cpp) on the same state/queries
    native_qps = None
    from cassandra_accord_tpu import native
    if native.available():
        from cassandra_accord_tpu.ops.graph_state import INVALIDATED
        h = {"key_inc": key_inc, "live_inc": key_inc, "ts": lanes,
             "txn_id": lanes, "kind": kind, "status": status, "active": active}
        qcols = [np.nonzero(row)[0] for row in q]
        native.consult_batch(h, qcols, before, qkind, INVALIDATED)  # warm
        t0 = time.perf_counter()
        for _ in range(3):
            native.consult_batch(h, qcols, before, qkind, INVALIDATED)
        native_qps = 3 * b / (time.perf_counter() - t0)
    py_qps = host_python_scalar(key_inc, lanes, active, q, before)
    # roofline block (index bytes, join TFLOP/s, MFU) from the unified
    # device-metrics source — same formulas the flight recorder reports
    from cassandra_accord_tpu.observe.device import kernel_consult_metrics
    out = {"T": t, "K": k, "B": b, "keys_per_txn": keys_per_txn,
           "packed_result": packed,
           "device_queries_per_sec": round(dev_qps, 1),
           "host_numpy_queries_per_sec": round(np_qps, 1),
           "host_native_queries_per_sec":
               round(native_qps, 1) if native_qps else None,
           "host_python_scalar_queries_per_sec": round(py_qps, 1),
           "device_vs_host_numpy": round(dev_qps / np_qps, 2)}
    out.update(kernel_consult_metrics(t, k, b, dev_qps))
    return out


def _bare_service_resolver(key_inc, lanes, kind, status, active):
    """A TpuDepsResolver shell carrying a pre-built synthetic index — just
    the surface the consult service reads (host arrays, dirty-row ledger,
    occupancy watermarks, the host fallback tier)."""
    from cassandra_accord_tpu.config import LocalConfig
    from cassandra_accord_tpu.impl.tpu_resolver import TpuDepsResolver
    t, k = key_inc.shape
    r = TpuDepsResolver.__new__(TpuDepsResolver)
    r.host_consults = 0
    r.native_consults = 0
    r.device_consults = 0
    r._host_engine = "numpy"
    r._h = {"key_inc": key_inc, "live_inc": key_inc,
            "key_inc_f32": key_inc.T.astype(np.float32),
            "live_f32": key_inc.T.astype(np.float32),
            "ts": lanes, "txn_id": lanes, "kind": kind, "status": status,
            "active": active, "durable": np.zeros(t, dtype=np.bool_)}
    r._dirty_rows = set()
    r._max_slot = t - 1
    r._max_key_slot = k - 1
    r.store = None
    r.config = LocalConfig.from_env(tpu_service="on",
                                    tpu_service_backend="jax")
    r.host_index = lambda: r._h            # bare shell: no _flush machinery

    def take_dirty():
        d = r._dirty_rows
        r._dirty_rows = set()
        return d
    r.take_dirty_rows = take_dirty
    return r


def bench_service(t, k=512, b=64, keys_per_txn=2, dirty_rows_per_window=8):
    """The consult_service section: batched windows through the persistent
    service vs one-shot dispatch vs host-native, at the same T — with the
    measured batch-size distribution and honest MFU.  Between windows a few
    rows go dirty (the protocol's mutation interleave), so the numbers carry
    the incremental-refresh cost the one-shot path pays as full re-uploads."""
    from cassandra_accord_tpu.device_service.service import DeviceConsultService
    from cassandra_accord_tpu.observe.device import kernel_consult_metrics
    windows = 12 if t <= 8192 else (8 if t <= 32768 else 4)
    rng = np.random.default_rng(7)
    key_inc, lanes, kind, status, active = _make_index(rng, t, k,
                                                       keys_per_txn=keys_per_txn)
    qs = []
    for _ in range(windows):
        qs.append(_make_queries(rng, b, k, t, keys_per_txn=keys_per_txn))
    # -- batched windows through the service (futures path) ------------------
    r = _bare_service_resolver(key_inc, lanes, kind, status, active)
    svc = DeviceConsultService(r, config=r.config)
    svc.begin_window()                     # warm: buffers + first compile
    f = svc.submit(np.nonzero(qs[0][0][0])[0].tolist(),
                   tuple(int(v) for v in qs[0][1][0]), int(qs[0][2][0]))
    f.result()
    svc.end_window()
    t0 = time.perf_counter()
    for q, before, qkind in qs:
        svc.begin_window()
        futs = [svc.submit(np.nonzero(q[i])[0].tolist(),
                           tuple(int(v) for v in before[i]), int(qkind[i]))
                for i in range(b)]
        futs[0].result()                   # one launch answers the window
        svc.end_window()
        r._dirty_rows.update(int(x) for x in
                             rng.integers(0, t, dirty_rows_per_window))
    batched_qps = windows * b / (time.perf_counter() - t0)
    stats = svc.stats()
    # -- one-shot dispatch (window of 1: unamortized launch RTT) -------------
    r1 = _bare_service_resolver(key_inc, lanes, kind, status, active)
    svc1 = DeviceConsultService(r1, config=r1.config)
    q, before, qkind = qs[0]
    svc1.consult_rows(q[:1], before[:1], qkind[:1])      # warm
    n_oneshot = min(2 * b, 64)
    t0 = time.perf_counter()
    for i in range(n_oneshot):
        svc1.consult_rows(q[i:i + 1], before[i:i + 1], qkind[i:i + 1])
        r1._dirty_rows.update(int(x) for x in
                              rng.integers(0, t, 1))     # mutation interleave
    oneshot_qps = n_oneshot / (time.perf_counter() - t0)
    # -- host-native: the resolver's own vectorized host tier ----------------
    host_tier = make_host_tier(key_inc, lanes, lanes, kind, status, active)
    t0 = time.perf_counter()
    for q, before, qkind in qs[:3]:
        host_tier(q, before, qkind)
    host_qps = 3 * b / (time.perf_counter() - t0)
    out = {"T": t, "K": k, "B": b, "windows": windows,
           "batched_queries_per_sec": round(batched_qps, 1),
           "oneshot_queries_per_sec": round(oneshot_qps, 1),
           "host_native_queries_per_sec": round(host_qps, 1),
           "batched_vs_host": round(batched_qps / host_qps, 2),
           "batched_vs_oneshot": round(batched_qps / max(oneshot_qps, 1e-9), 2),
           "batch_size_hist": stats["batch_size_hist"],
           "window_occupancy": stats["window_occupancy"],
           "dispatch_mean_s": stats["dispatch_mean_s"],
           "index_incremental_refreshes": stats["index_incremental_refreshes"],
           "index_full_uploads": stats["index_full_uploads"],
           "jit_shapes": stats["jit_shapes"]}
    # honest MFU: the service joins over the OCCUPANCY VIEW (== T here; the
    # synthetic index is fully occupied), denominated against the bf16 peak
    # even on backends that cannot reach it
    out.update(kernel_consult_metrics(t, k, b, batched_qps))
    return out


def bench_graph(t=8192, iters=3):
    """BASELINE config-5 shape: cycle-heavy adversarial dependency graph —
    transitive closure, SCC condensation (cycle handling), and the Kahn
    frontier, all as matmul kernels.  Dense [T, T] int8 adjacency: the stated
    memory budget is T^2 bytes (64 MB at 8k; dense caps ~64k on one chip —
    beyond that the index shards over the mesh, parallel/mesh.py)."""
    import jax
    import jax.numpy as jnp
    from cassandra_accord_tpu.ops import deps_kernels as dk
    rng = np.random.default_rng(9)
    adj = (rng.random((t, t)) < (8.0 / t)).astype(np.int8)   # ~8 deps/txn
    np.fill_diagonal(adj, 0)
    status = np.full((t,), 4, dtype=np.int8)                 # STABLE
    active = np.ones((t,), dtype=bool)
    a = jnp.asarray(adj)
    s, act = jnp.asarray(status), jnp.asarray(active)
    out = {"T": t, "adjacency_bytes": t * t,
           "deps_per_txn": float(adj.sum() / t)}
    closure_flops = 2.0 * t * t * t * max(1, int(t - 1).bit_length())
    for name, fn, args, flops in (
            ("closure", dk.transitive_closure, (a,), closure_flops),
            ("scc_condense", dk.scc_condense, (a, act), closure_flops),
            ("kahn_frontier", dk.kahn_frontier, (a, s, act), 2.0 * t * t)):
        jax.block_until_ready(fn(*args))                     # compile
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn(*args)
        jax.block_until_ready(r)
        dt = (time.perf_counter() - t0) / iters
        out[name] = {"seconds": round(dt, 4),
                     "tflops": round(flops / dt / 1e12, 2)}
    return out


def bench_deps_graph(ts=(1024, 8192), dense_max=None):
    """ISSUE-13 ``deps_graph`` stage: the O(T^3)-shaped dense kernels vs
    their frontier/CSR replacements (ops.frontier_kernels) on the BASELINE
    config-5 graph shape (~8 deps/txn, cycle-heavy), at T in {1k, 8k}.

    The dense twins run at every T up to ``dense_max`` (default: 1024, env
    ACCORD_BENCH_DENSE_MAX overrides) — at 8k they are the 45.5 s / 41.8 s
    kernels this stage exists to retire, so by default their 8k cost is
    reported as null and the speedup at 1k stands in; set
    ACCORD_BENCH_DENSE_MAX=8192 for the full old-vs-new measurement.
    Bit-identity old-vs-new is asserted in tier-1 (test_ops_kernels); this
    stage measures rates only."""
    import jax
    import jax.numpy as jnp
    from cassandra_accord_tpu.ops import deps_kernels as dk
    from cassandra_accord_tpu.ops import frontier_kernels as fk
    if dense_max is None:
        dense_max = int(os.environ.get("ACCORD_BENCH_DENSE_MAX", "1024"))
    out = {}
    for t in ts:
        rng = np.random.default_rng(9)
        adj = (rng.random((t, t)) < (8.0 / t)).astype(np.int8)
        np.fill_diagonal(adj, 0)
        status = np.full((t,), 4, dtype=np.int8)                 # STABLE
        active = np.ones((t,), dtype=bool)
        row = {"T": t, "edges": int(adj.sum())}

        def timed(fn, warm=True):
            if warm:
                fn()
            t0 = time.perf_counter()
            r = fn()
            jax.block_until_ready(r) if hasattr(r, "block_until_ready") \
                else r
            return round(time.perf_counter() - t0, 4)

        row["closure_frontier_s"] = timed(
            lambda: fk.closure_condensed(adj))
        row["elide_frontier_s"] = timed(lambda: fk.elide_csr(adj))
        row["scc_frontier_s"] = timed(
            lambda: fk.scc_condense_csr(adj, active))
        row["kahn_frontier_s"] = timed(
            lambda: fk.kahn_frontier_csr(adj, status, active))
        if t <= dense_max:
            a = jnp.asarray(adj)
            act = jnp.asarray(active)
            st_j = jnp.asarray(status)
            row["closure_dense_s"] = timed(
                lambda: jax.block_until_ready(dk.transitive_closure(a)))
            row["elide_dense_s"] = timed(
                lambda: jax.block_until_ready(dk.elide(a)))
            row["scc_dense_s"] = timed(
                lambda: jax.block_until_ready(dk.scc_condense(a, act)[0]))
            row["kahn_dense_s"] = timed(
                lambda: jax.block_until_ready(dk.kahn_frontier(a, st_j, act)))
            for k2 in ("closure", "elide", "scc", "kahn"):
                new, old = row[f"{k2}_frontier_s"], row[f"{k2}_dense_s"]
                row[f"{k2}_speedup"] = round(old / new, 2) if new else None
        else:
            row["dense_skipped"] = f"T > dense_max={dense_max} " \
                                   f"(ACCORD_BENCH_DENSE_MAX overrides)"
        out[f"T{t}"] = row
    # frontier-DRIVEN vs event-driven end-to-end commit rate, same workload
    from cassandra_accord_tpu.harness.burn import run_burn
    rates = {}
    for label, fx in (("event_driven", False), ("frontier_driven", True)):
        t0 = time.perf_counter()
        res = run_burn(seed=PROTO_SEED, ops=300, concurrency=PROTO_CONC,
                       resolver="tpu", batch_window_us=TPU_WINDOW_US,
                       frontier_exec=fx, **PROTO_KW)
        dt = time.perf_counter() - t0
        rates[label] = {"commits_per_sec_wall": round(res.ops_ok / dt, 1),
                        "sim_ms": round(res.sim_micros / 1000.0, 1),
                        "frontier_released":
                            res.stats.get("frontier_released", 0)}
    out["exec_commit_rate"] = rates
    # the KNOWN_ISSUES round-6 repro config, profiled: the deps_execute_wait
    # phase share the 72.8% figure was measured on (round 12 split it into
    # commit-plane vs execute-plane waits; the ledger holds the series)
    from cassandra_accord_tpu.observe import FlightRecorder
    rec = FlightRecorder()
    res = run_burn(0, ops=100, concurrency=20, resolver="verify",
                   frontier_exec=True, chaos=True, allow_failures=True,
                   topology_churn=True, durability=True, journal=True,
                   delayed_stores=True, clock_drift=True, cache_miss=True,
                   observer=rec, max_tasks=200_000_000)
    b = rec.latency_budget()
    out["frontier_profile"] = {
        "workload": "round-6 repro (seed 0, 100 ops, full hostile matrix, "
                    "frontier_exec)",
        "ops": res.resolved,
        "mean_commit_ms": round(b["mean_commit_latency_us"] / 1000.0, 1),
        "deps_execute_wait_share":
            round((b["phases"].get("deps_execute_wait") or {})
                  .get("share", 0.0), 4),
        "deps_commit_wait_share":
            round((b["phases"].get("deps_commit_wait") or {})
                  .get("share", 0.0), 4),
        "attributed_share": b["attributed_share"],
    }
    return out


def probe_device(timeout_s: int = 120) -> bool:
    """Check the TPU is actually reachable — in a SUBPROCESS, because a wedged
    axon tunnel blocks inside native code at jax import (uninterruptible
    in-process).  First compile over the tunnel takes 20-40s; allow slack."""
    import subprocess
    import sys
    code = ("import jax, jax.numpy as jnp; "
            "y = (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready(); "
            "print('device-ok', jax.devices()[0].platform)")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout_s,
                           capture_output=True, text=True)
        return r.returncode == 0 and "device-ok" in r.stdout
    except Exception:  # noqa: BLE001 — timeout or spawn failure: no device
        return False


def _strip_axon_and_go_cpu():
    """Re-exec with the axon site stripped so NOTHING can touch the wedged
    tunnel (even `import jax` hangs while its plugin dials the dead relay)."""
    if os.environ.get("ACCORD_BENCH_CPU") == "1":
        return
    os.environ["ACCORD_BENCH_CPU"] = "1"
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PYTHONPATH"] = os.pathsep.join(
        p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p) or os.path.dirname(os.path.abspath(__file__))
    import sys
    os.execve(sys.executable,
              [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
              os.environ)


# ---------------------------------------------------------------------------
# fail-open staging: the bench NEVER exits without printing its JSON line.
# Every completed stage lands in RESULT immediately; SIGTERM/SIGALRM (the
# driver's timeout) triggers the emit of everything finished so far
# (VERDICT r04: a single print at the end turned a timeout into an empty
# artifact — rc 124, no numbers at all).
# ---------------------------------------------------------------------------

import signal

RESULT = {
    "metric": "consult_replay_commits_equiv_per_sec_T32k",
    "value": None,
    "unit": "commits-equiv/s",
    "vs_baseline": None,
    "detail": {"stages": {}, "incomplete": True},
}
_EMITTED = False
DEADLINE = time.monotonic() + float(os.environ.get("ACCORD_BENCH_DEADLINE_S",
                                                   "1500"))


def _finalize_headline():
    """Compute the headline from whatever replay stages completed: the
    fastest engaged tier vs the scalar cfk walk at the LARGEST completed T."""
    d = RESULT["detail"]
    replay = d.get("trace_replay") or {}
    for key in sorted(replay, key=lambda k: -int(k[1:])):
        tiers = replay[key].get("tiers") or {}
        walk = (tiers.get("walk") or {}).get("commits_equiv_per_sec")
        rates = {t: v.get("commits_equiv_per_sec") for t, v in tiers.items()
                 if v.get("commits_equiv_per_sec")}
        if not rates:
            continue
        # headline = the PRODUCTION tier choice: auto (the shipped cost
        # model) when measured, else the fastest tier that ran
        best_tier = "auto" if rates.get("auto") else max(rates, key=rates.get)
        RESULT["value"] = rates[best_tier]
        RESULT["metric"] = f"consult_replay_commits_equiv_per_sec_{key}"
        d["headline_tier"] = best_tier
        d["headline_T"] = key
        if walk:
            RESULT["vs_baseline"] = round(rates[best_tier] / walk, 3)
        return
    # no replay completed: fall back to the end-to-end protocol ratio
    proto = d.get("protocol_end_to_end")
    if proto and proto.get("commits_per_sec_tpu_dataplane"):
        RESULT["metric"] = "protocol_commits_per_sec"
        RESULT["unit"] = "commits/s"
        RESULT["value"] = proto["commits_per_sec_tpu_dataplane"]
        RESULT["vs_baseline"] = proto.get("ratio")


def emit_and_exit(code=0):
    global _EMITTED
    if _EMITTED:
        os._exit(code)
    _EMITTED = True
    _finalize_headline()
    # cross-run trend ledger (tools/trend.py): every bench run appends its
    # headline (+ the deterministic smoke sim plane when measured) to
    # BENCH_HISTORY.jsonl — the durable perf trajectory across PRs.  Guarded:
    # the ledger must never be able to kill the emit.
    try:
        from tools.trend import append_entry
        from tools.perfgate import inject_active
        smoke = (RESULT["detail"].get("smoke") or {})
        # the ACCORD_PERFGATE_INJECT_LATENCY self-test doctors the measured
        # latencies — they must never enter the ledger as a real run
        if not inject_active():
            record = {
                "kind": "bench",
                "metric": RESULT["metric"],
                "value": RESULT["value"],
                "unit": RESULT["unit"],
                "vs_baseline": RESULT["vs_baseline"],
                "incomplete": RESULT["detail"].get("incomplete", True),
                "sim": smoke.get("sim"),
            }
            dg = RESULT["detail"].get("deps_graph")
            if dg:
                # the kernel series tools/trend.py renders: frontier-tier
                # seconds per kernel per T + the old-vs-new speedups where
                # the dense twin was measured, plus the execution-mode rates
                deps_graph = {}
                for tkey, row in dg.items():
                    if not tkey.startswith("T"):
                        continue
                    deps_graph[tkey] = {
                        k2: row.get(k2) for k2 in
                        ("closure_frontier_s", "elide_frontier_s",
                         "scc_frontier_s", "kahn_frontier_s",
                         "closure_speedup", "elide_speedup", "scc_speedup",
                         "kahn_speedup")
                        if row.get(k2) is not None}
                exec_rate = dg.get("exec_commit_rate") or {}
                if exec_rate:
                    deps_graph["exec_commit_rate"] = {
                        label: (v or {}).get("commits_per_sec_wall")
                        for label, v in exec_rate.items()}
                prof = dg.get("frontier_profile") or {}
                if prof:
                    deps_graph["frontier_deps_execute_wait_share"] = \
                        prof.get("deps_execute_wait_share")
                    deps_graph["frontier_deps_commit_wait_share"] = \
                        prof.get("deps_commit_wait_share")
                record["deps_graph"] = deps_graph
            ramp = RESULT["detail"].get("protocol_ramp")
            if ramp:
                # the ledger's protocol_commits_per_sec series
                # (tools/trend.py renders it run-over-run): wall rate at the
                # top concurrency level with the columnar engine on, plus
                # the full ramp curve for the record
                record["protocol_commits_per_sec"] = \
                    ramp.get("protocol_commits_per_sec")
                record["ramp"] = {
                    "levels": ramp.get("levels"),
                    "wall": (ramp.get("columnar_on") or {})
                    .get("commits_per_sec_wall"),
                    "sim": (ramp.get("columnar_on") or {})
                    .get("commits_per_sec_sim"),
                }
            wslo = RESULT["detail"].get("workload_slo")
            if wslo:
                # the workload_slo series tools/trend.py renders: did the
                # open-loop preset sustain its arrival rate this run
                record["workload_slo"] = {
                    "workload": wslo.get("workload"),
                    "rate_txn_s": wslo.get("rate_txn_s"),
                    "sim_minutes": wslo.get("sim_minutes"),
                    "slo_burn_events": wslo.get("slo_burn_events"),
                    "sustained": wslo.get("sustained"),
                }
            ov = RESULT["detail"].get("overload")
            if ov:
                # the overload series tools/trend.py renders: goodput floor
                # under the admission-controlled metastability ramp
                record["overload"] = {
                    "mode": ov.get("mode"),
                    "rate_txn_s": ov.get("rate_txn_s"),
                    "capacity_goodput_txn_s":
                        ov.get("capacity_goodput_txn_s"),
                    "goodput_floor_frac": ov.get("goodput_floor_frac"),
                    "shed": sum(p.get("shed", 0)
                                for p in ov.get("points", [])),
                    "passed": ov.get("passed"),
                }
            # the seed cohort keys run-over-run comparability in
            # tools/trend.py — a bench smoke record and a perfgate record
            # of the same seed are the same measurement
            seed = (smoke.get("workload") or {}).get("seed")
            if seed is not None:
                record["seeds"] = [seed]
            append_entry(record)
    except Exception:  # noqa: BLE001 — the ledger must not break the bench
        pass
    print(json.dumps(RESULT), flush=True)
    # the harness captures only a bounded TAIL of stdout and parses its last
    # line: the full-detail object above routinely exceeds that window and
    # parsed as null in every BENCH_r0*.json — so the LAST line is a compact
    # single-line summary that always fits (headline + stage health only)
    summary = {
        "metric": RESULT["metric"],
        "value": RESULT["value"],
        "unit": RESULT["unit"],
        "vs_baseline": RESULT["vs_baseline"],
        "incomplete": RESULT["detail"].get("incomplete", True),
        "headline_tier": RESULT["detail"].get("headline_tier"),
        "device_present": RESULT["detail"].get("device_present"),
        "stages": {name: ("error" if "error" in st
                          else "skipped" if "skipped" in st else "ok")
                   for name, st in RESULT["detail"].get("stages", {}).items()},
    }
    print(json.dumps(summary), flush=True)
    os._exit(code)


def _on_term(signum, frame):
    RESULT["detail"]["killed_by"] = signal.Signals(signum).name
    emit_and_exit(0)


def stage(name: str, fn):
    """Run one bench stage; record wall/errors; never raise.  Skips (with a
    reason) once the global deadline leaves no room."""
    stages = RESULT["detail"]["stages"]
    left = DEADLINE - time.monotonic()
    if left <= 30:
        stages[name] = {"skipped": f"deadline ({left:.0f}s left)"}
        return None
    t0 = time.monotonic()
    try:
        out = fn()
        stages[name] = {"seconds": round(time.monotonic() - t0, 1)}
        return out
    except Exception as e:  # noqa: BLE001 — a failed stage must not kill the rest
        stages[name] = {"seconds": round(time.monotonic() - t0, 1),
                        "error": f"{type(e).__name__}: {e}"[:300]}
        return None


def smoke_main():
    """``bench.py --smoke``: the seconds-class fixed-seed measurement the
    perf gate runs — full protocol burn + critical-path latency budget +
    wall profile — honoring the same fail-open staging and stdout TAIL
    contract as the full bench (the LAST stdout line is one compact
    single-line JSON object; tests/test_bench_smoke.py pins this)."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGALRM, _on_term)
    signal.alarm(max(60, int(DEADLINE - time.monotonic()) - 30))
    d = RESULT["detail"]

    def smoke():
        from tools.perfgate import measure_smoke
        summary = measure_smoke()
        d["smoke"] = summary
        RESULT["metric"] = "smoke_commit_latency_mean_us"
        RESULT["unit"] = "sim_us"
        RESULT["value"] = summary["sim"]["commit_latency_mean_us"]
        d["headline_tier"] = summary["dominating_class"]
    stage("smoke", smoke)
    d["incomplete"] = "smoke" not in d
    emit_and_exit(0)


def ramp_main():
    """``bench.py --ramp``: just the protocol_ramp stage (minutes-class),
    same fail-open staging + single-line-JSON stdout tail contract."""
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGALRM, _on_term)
    signal.alarm(max(60, int(DEADLINE - time.monotonic()) - 30))
    d = RESULT["detail"]

    def ramp():
        out = bench_protocol_ramp()
        d["protocol_ramp"] = out
        RESULT["metric"] = "protocol_commits_per_sec"
        RESULT["unit"] = "commits/s"
        RESULT["value"] = out["protocol_commits_per_sec"]
        speedups = [s for s in out["columnar_wall_speedup"] if s]
        if speedups:
            RESULT["vs_baseline"] = speedups[-1]   # columnar on/off, top level
    stage("protocol_ramp", ramp)
    d["incomplete"] = "protocol_ramp" not in d
    emit_and_exit(0)


def gate_main():
    """``bench.py --gate``: run the smoke measurement and compare against
    BASELINE.json's gate block (tools/perfgate.py) — per-metric deltas on
    stdout, exit nonzero past thresholds.  Only deterministic SIM-time
    metrics gate; wall-clock numbers are printed for the log."""
    from tools.perfgate import run
    raise SystemExit(run(gate=True))


def main():
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGALRM, _on_term)
    # hard backstop 60s before any external timeout budget we were given
    signal.alarm(max(60, int(DEADLINE - time.monotonic()) - 60))
    d = RESULT["detail"]
    d["north_star"] = (
        "BASELINE.md targets 10x conflicting-txn commit throughput at deps "
        "parity.  Where it stands: the round-5 flat-cost redesign (per-txn "
        "universal-durability elision + hot/cold demotion) bounds EVERY "
        "tier's per-query work at O(concurrency) — including the reference-"
        "shaped scalar walk, which therefore now wins at protocol index "
        "scales; the production cost model (tier=auto) routes accordingly, "
        "and the per-op protocol cost is flat with history "
        "(per_op_cost_flatness below, the prerequisite no tier could buy "
        "while deps grew O(history)).  The MXU device tier's domain is the "
        "regimes the host cannot touch: batched wide-key range joins and "
        "huge live indexes (kernel_scaling: fused consult at T=65k, "
        "1k-key joins; graph_kernels: closure/SCC/frontier at T=8k) — and "
        "it now also serves live protocol-semantics streams "
        "(trace_replay tier=device, engaged for the first time this round), "
        "where per-launch tunnel latency at small windows is the measured "
        "cost to amortize.")

    device = probe_device()
    if not device:
        _strip_axon_and_go_cpu()
    d["device_present"] = device
    # protocol stages never touch the chip in-process: a wedged axon tunnel
    # blocks inside native code un-interruptibly (BENCH_r04 post-mortem)
    os.environ.setdefault("ACCORD_TPU_DISPATCH_ELEMS", "1e13")

    def proto():
        # one warm rep (jit caches), then ONE timed rep per data plane
        bench_protocol("tpu", batch_window_us=TPU_WINDOW_US, ops=40, reps=1)
        tpu_cps, tpu_res = bench_protocol("tpu", batch_window_us=TPU_WINDOW_US,
                                          reps=1)
        cpu_cps, cpu_res = bench_protocol("cpu", batch_window_us=0, reps=1)
        tel = {k: v for k, v in tpu_res.stats.items()
               if k.startswith("resolver_")}
        mismatch = tpu_res.ops_ok != cpu_res.ops_ok
        # flat-cost check (VERDICT r05 item 2): commits/s at 200 vs 1200 ops
        short_cps, _ = bench_protocol("cpu", batch_window_us=0, ops=200,
                                      reps=1)
        d["protocol_end_to_end"] = {
            "commits_per_sec_tpu_dataplane": round(tpu_cps, 1),
            "commits_per_sec_cpu_resolver": round(cpu_cps, 1),
            "ratio": None if mismatch else round(tpu_cps / cpu_cps, 3),
            "workload_mismatch": {"tpu_ops_ok": tpu_res.ops_ok,
                                  "cpu_ops_ok": cpu_res.ops_ok}
            if mismatch else None,
            "commits_per_sec_cpu_at_200_ops": round(short_cps, 1),
            "per_op_cost_flatness_1200_vs_200":
                round(cpu_cps / short_cps, 3) if short_cps else None,
            "workload": {"ops": PROTO_OPS, "concurrency": PROTO_CONC,
                         **PROTO_KW, "seed": PROTO_SEED,
                         "tpu_batch_window_us": TPU_WINDOW_US},
            "tpu_resolver_telemetry": tel,
        }
    stage("protocol", proto)

    def ramp():
        # the ROADMAP item-1 ramp oracle: commits/s vs in-flight, columnar
        # engine on vs off (the sim curve must SCALE, the wall delta is the
        # engine's earnings)
        return bench_protocol_ramp()

    rp = stage("protocol_ramp", ramp)
    if rp is not None:
        d["protocol_ramp"] = rp

    def protocol_slo():
        # latency-SLO workload judged by the flight-recorder/auditor plane
        # (ROADMAP item 5): p50/p95/p99 commit latency from the recorder's
        # sim-time histogram plus liveness-SLO flag counts, at 5 and 15
        # nodes under the ELASTIC matrix (join/decommission under load).
        # Sim-time latencies: deterministic, workload-intrinsic — wall clock
        # never enters the percentile math.
        from dataclasses import replace as _replace
        from cassandra_accord_tpu.config import LocalConfig
        from cassandra_accord_tpu.harness.burn import run_burn
        from cassandra_accord_tpu.observe import InvariantAuditor
        from cassandra_accord_tpu.observe import schema as _schema
        from cassandra_accord_tpu.observe.registry import Histogram

        # percentile estimate from a fixed-bound histogram snapshot: the
        # registry's conservative bucket-upper-bound formula
        pct = Histogram.snapshot_percentile

        out = {}
        cfg = _replace(LocalConfig(), membership_interval_s=6.0)
        for n_nodes in (5, 15):
            auditor = InvariantAuditor(mode="warn")
            t0 = time.perf_counter()
            res = run_burn(seed=PROTO_SEED, ops=200, concurrency=PROTO_CONC,
                           nodes=n_nodes, rf=5 if n_nodes >= 5 else 3,
                           chaos=True, allow_failures=True,
                           topology_churn=True, elastic_membership=True,
                           durability=True, journal=True, node_config=cfg,
                           observer=auditor, audit="warn",
                           stall_watchdog_s=300.0, max_tasks=80_000_000)
            dt = time.perf_counter() - t0
            hist = auditor.registry.histogram(
                _schema.LATENCY_METRIC).to_snapshot()
            verdict = res.audit or {}
            out[f"nodes_{n_nodes}"] = {
                "ops": res.resolved,
                "joins": res.joins, "leaves": res.leaves,
                "commits_per_sec_wall": round(res.resolved / dt, 1)
                if dt else None,
                "commit_latency_us": {
                    "p50": pct(hist, 0.50), "p95": pct(hist, 0.95),
                    "p99": pct(hist, 0.99), "count": hist["count"],
                    "mean": round(hist["total"] / hist["count"])
                    if hist["count"] else None},
                "slo_flags": {
                    "raised": verdict.get("slo_flags_raised"),
                    "open_at_quiesce": verdict.get("slo_flags_open")},
                "violations": verdict.get("violations"),
            }
        return out

    ps = stage("protocol_slo", protocol_slo)
    if ps is not None:
        d["protocol_slo"] = ps

    def workload_slo():
        # open-loop arrival-rate SLO preset (ISSUE-16): sustain a target
        # txn/s of SIM-time under the hostile matrix with the burn-rate
        # monitors as the oracle — zero slo.burn events = sustained.  The
        # independent history oracle rides along (check="history": any
        # strict-serializability anomaly in the client-visible history
        # raises).  Ledgered as the workload_slo series in BENCH_HISTORY.
        from cassandra_accord_tpu.harness.burn import run_burn
        from cassandra_accord_tpu.observe import BurnRateMonitor, InvariantAuditor

        rate = 30.0
        monitor = BurnRateMonitor()
        auditor = InvariantAuditor(mode="warn", burnrate=monitor)
        t0 = time.perf_counter()
        res = run_burn(seed=PROTO_SEED, ops=240, concurrency=PROTO_CONC,
                       chaos=True, allow_failures=True, durability=True,
                       journal=True, delayed_stores=True, clock_drift=True,
                       workload="openloop", rate_txn_s=rate, check="history",
                       observer=auditor, audit="warn",
                       stall_watchdog_s=300.0, max_tasks=80_000_000)
        dt = time.perf_counter() - t0
        rep = monitor.report()
        events = rep.get("slo_burn_events", 0)
        return {
            "workload": "openloop", "rate_txn_s": rate,
            "ops": res.resolved,
            "sim_minutes": round(res.sim_micros / 60e6, 2),
            "slo_burn_events": events,
            "sustained": events == 0,
            "history": {k: res.history[k] for k in ("ops", "ok", "keys")}
            if res.history else None,
            "wall_s": round(dt, 2),
        }

    ws = stage("workload_slo", workload_slo)
    if ws is not None:
        d["workload_slo"] = ws

    def overload():
        # overload-robustness cohort (ISSUE-17): a small metastability ramp
        # (0.5x/1x/2x of the target rate, admission control + retry budgets
        # on) under the hostile matrix — the bench ledgers the goodput floor
        # fraction and capacity estimate run-over-run so a metastable
        # regression (goodput cratering past saturation) shows in trend.py
        from dataclasses import replace
        from cassandra_accord_tpu.config import LocalConfig
        from cassandra_accord_tpu.harness.burn import run_overload_ramp

        rate = 30.0
        cfg = replace(LocalConfig.from_env(), admission_enabled=True,
                      retry_budget_enabled=True)
        kw = dict(ops=120, concurrency=PROTO_CONC, chaos=True,
                  allow_failures=True, durability=True, journal=True,
                  delayed_stores=True, clock_drift=True, workload="openloop",
                  node_config=cfg, check="history", audit="warn",
                  stall_watchdog_s=300.0, max_tasks=80_000_000)
        t0 = time.perf_counter()
        out = run_overload_ramp(PROTO_SEED, kw, rate, mults=(0.5, 1.0, 2.0))
        out["wall_s"] = round(time.perf_counter() - t0, 2)
        return out

    ov = stage("overload", overload)
    if ov is not None:
        d["overload"] = ov

    def frontier():
        # frontier-driven execution in the flagship configuration
        from cassandra_accord_tpu.harness.burn import run_burn
        t0 = time.perf_counter()
        res = run_burn(seed=PROTO_SEED, ops=400, concurrency=PROTO_CONC,
                       resolver="tpu", batch_window_us=TPU_WINDOW_US,
                       frontier_exec=True, **PROTO_KW)
        dt = time.perf_counter() - t0
        d["frontier_exec"] = {
            "commits_per_sec": round(res.ops_ok / dt, 1),
            "ops": 400,
            "frontier_stats": {k: v for k, v in res.stats.items()
                               if "frontier" in k or "exec" in k},
        }
    stage("frontier_exec", frontier)

    dg = stage("deps_graph", bench_deps_graph)   # ISSUE-13 kernel series
    if dg is not None:
        d["deps_graph"] = dg

    def record():
        from cassandra_accord_tpu.harness.consult_trace import record_burn
        os.environ["ACCORD_TPU_F32_MAX"] = str(1 << 20)
        return record_burn(seed=PROTO_SEED, ops=PROTO_OPS,
                           concurrency=PROTO_CONC,
                           batch_window_us=TPU_WINDOW_US, **PROTO_KW)
    rec = stage("record_burn", record)

    if rec is not None:
        from cassandra_accord_tpu.harness.consult_trace import scaled_replay
        d["trace_replay"] = {}
        for t_target in (4096, 32768):
            # re-probe: the tunnel can wedge mid-run; skip rather than hang
            dev_now = device and probe_device(timeout_s=60)
            tiers = ["walk", "host", "auto"] + (["device"] if dev_now else [])

            def replay(t_target=t_target, tiers=tiers):
                # walk tier: ~300 sampled queries, extrapolated; device tier:
                # through the PERSISTENT consult service (incremental
                # double-buffered refresh — the r05 one-shot path re-uploaded
                # the whole index per consult and wedged at event 36), with a
                # budget valve for honesty on slow links.  Neither may blow
                # the budget (VERDICT r04 item 1b).
                return scaled_replay(rec, t_target, tiers, parity_sample=500,
                                     walk_sample_target=300,
                                     tier_max_seconds={"device": 180.0,
                                                       "host": 240.0,
                                                       "auto": 240.0})
            r = stage(f"replay_T{t_target}", replay)
            if r is not None:
                d["trace_replay"][f"T{t_target}"] = r
                _finalize_headline()   # refresh headline after every stage

    def consult_service_stage():
        # the persistent batched device service ON the protocol path: a burn
        # with the device tier forced through the service (acceptance: the
        # protocol tier reports resolver_device_consults > 0 — no more
        # zero-consult device tier, BENCH_r03), then batched-vs-oneshot-vs-
        # host scaling with the measured batch-size distribution
        import jax
        from cassandra_accord_tpu.config import LocalConfig
        from cassandra_accord_tpu.harness.burn import run_burn
        out = {"platform": jax.default_backend()}
        cfg = LocalConfig.from_env(resolver_kind="tpu", tpu_tier="device",
                                   tpu_walk_max=0, tpu_walk_width=0,
                                   tpu_service="on",
                                   tpu_service_backend="jax")
        t0 = time.perf_counter()
        res = run_burn(seed=PROTO_SEED, ops=300, concurrency=PROTO_CONC,
                       resolver="tpu", batch_window_us=TPU_WINDOW_US,
                       node_config=cfg, **PROTO_KW)
        dt = time.perf_counter() - t0
        out["protocol_burn_via_service"] = {
            "ops": 300,
            "commits_per_sec": round(res.ops_ok / dt, 1),
            "resolver_device_consults":
                res.stats.get("resolver_device_consults", 0),
            "resolver_service_submitted":
                res.stats.get("resolver_service_submitted", 0),
            "resolver_service_batches":
                res.stats.get("resolver_service_batches", 0),
        }
        out["scaling"] = [bench_service(8192), bench_service(32768),
                          bench_service(65536)]
        return out

    # in-process jax is safe here: either the axon site was stripped (pure
    # CPU backend) or the device just answered a subprocess probe
    if not device or probe_device(timeout_s=60):
        cs = stage("consult_service", consult_service_stage)
        if cs is not None:
            d["consult_service"] = cs

    def kernels():
        # each entry carries the roofline block (join TFLOP/s, MFU vs the
        # chip's bf16 peak) from observe.device.kernel_consult_metrics
        return [bench_kernel(4096), bench_kernel(65536),
                bench_kernel(65536, packed=True),
                # BASELINE config 4: range txns, 1k keys/txn wide join
                bench_kernel(65536, k=2048, b=64, keys_per_txn=1024,
                             packed=True)]

    if device and probe_device(timeout_s=60):
        k = stage("kernel_scaling", kernels)
        if k is not None:
            d["kernel_scaling"] = k
        g = stage("graph_kernels", bench_graph)   # BASELINE config 5
        if g is not None:
            d["graph_kernels"] = g

    d["incomplete"] = False
    emit_and_exit(0)


if __name__ == "__main__":
    import argparse
    _p = argparse.ArgumentParser(description=__doc__)
    _p.add_argument("--smoke", action="store_true",
                    help="seconds-class fixed-seed smoke measurement "
                         "(protocol burn + latency budget); same last-line "
                         "single-JSON tail contract as the full bench")
    _p.add_argument("--gate", action="store_true",
                    help="smoke measurement + regression gate vs "
                         "BASELINE.json (tools/perfgate.py): prints "
                         "per-metric deltas, exits nonzero past thresholds")
    _p.add_argument("--ramp", action="store_true",
                    help="just the protocol_ramp stage: commits/s at "
                         f"concurrency {RAMP_LEVELS}, columnar engine on "
                         "vs off; appends the protocol_commits_per_sec "
                         "series to BENCH_HISTORY.jsonl")
    _args = _p.parse_args()
    if _args.gate:
        gate_main()
    elif _args.ramp:
        ramp_main()
    elif _args.smoke:
        smoke_main()
    else:
        main()
