"""tools/explain.py stdout TAIL contract (tier-1).

Same harness contract as bench.py / tools/trend.py (the bounded tail
capture parses the LAST stdout line as one compact JSON object): pinned
here on canned provenance dumps so the smoke stays sub-second — no burn
runs in-process; the dumps are synthesized with the recorder API.
"""
import json
import os
import subprocess
import sys

import pytest

from cassandra_accord_tpu.observe import ProvenanceRecorder

EXPLAIN = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools", "explain.py")


def _dump(path, crash_at=None):
    """A small synthetic run: send/recv/handler/transition chain, with an
    optional injected crash event (the divergence under test)."""
    prov = ProvenanceRecorder()
    for i in range(8):
        us = 100 * (i + 1)
        if crash_at == i:
            prov.on_crash(2, us)
        prov.on_message_event("SEND", 1, 2, i, None, us)
        prov.on_message_event("RECV", 1, 2, i, None, us + 10)
        prov.begin_handler(2, "PreAccept", f"t{i}", us + 10)
        prov.on_transition(2, 0, f"t{i}", "PRE_ACCEPTED", us + 10)
        prov.end()
    prov.save(str(path))
    return prov


@pytest.fixture()
def dumps(tmp_path):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    _dump(a)
    _dump(b, crash_at=4)
    return str(a), str(b)


def _run(*argv):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable, EXPLAIN, *argv],
                          capture_output=True, text=True, timeout=120,
                          env=env, cwd=os.path.dirname(os.path.dirname(EXPLAIN)))


def test_divergent_tail_is_single_json_object(dumps):
    a, b = dumps
    proc = _run(a, b)
    assert proc.returncode == 3, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert lines, "explain printed nothing"
    tail = json.loads(lines[-1])          # the harness's parse, exactly
    assert isinstance(tail, dict)
    assert tail["identical"] is False
    assert tail["event_b"]["kind"] == "crash"
    assert isinstance(tail["index"], int)
    assert tail["cone_events"] >= 1
    # sized to survive a bounded tail capture
    assert len(lines[-1]) < 4096
    # the human report precedes the tail
    assert any("causal divergence" in l for l in lines[:-1])


def test_identical_tail_and_exit_zero(dumps):
    a, _b = dumps
    proc = _run(a, a)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    tail = json.loads(lines[-1])
    assert tail["identical"] is True
    assert tail["events_a"] == tail["events_b"]
    assert len(lines[-1]) < 4096
