"""Progress log liveness: automatic recovery of orphaned txns and resolution of
blocked dependencies via CheckStatus / FetchData / Propagate.

Parity target: accord.impl.SimpleProgressLog behavior — the home shard notices a
txn making no progress and drives MaybeRecover; replicas blocked on a missing
dependency fetch its outcome from peers and apply it locally.
"""
import pytest

from cassandra_accord_tpu.harness.cluster import Cluster, LinkConfig
from cassandra_accord_tpu.impl.list_store import list_txn
from cassandra_accord_tpu.local.status import SaveStatus, Status
from cassandra_accord_tpu.primitives.keys import IntKey, Range
from cassandra_accord_tpu.topology.topology import Shard, Topology
from cassandra_accord_tpu.utils.random import RandomSource


def k(v):
    return IntKey(v)


class Deadable(LinkConfig):
    """Once `dead` is set, that node sends nothing (requests or replies)."""

    def __init__(self, rng):
        super().__init__(rng)
        self.dead = None

    def action(self, from_node, to_node, message=None):
        if self.dead is not None and from_node == self.dead:
            return LinkConfig.DROP
        return LinkConfig.DELIVER


def make_cluster(seed=1, nodes=(1, 2, 3)):
    shards = [Shard(Range(k(0), k(1000)), list(nodes))]
    link = Deadable(RandomSource(seed * 13 + 5))
    cluster = Cluster(Topology(1, shards), seed=seed, link_config=link,
                      progress_log=True)
    return cluster, link


def statuses(cluster, txn_id, nodes):
    out = {}
    for n in nodes:
        for store in cluster.nodes[n].command_stores.all_stores():
            cmd = store.commands.get(txn_id)
            if cmd is not None:
                out[n] = cmd.save_status
    return out


def witnessed_txn_id(cluster, node_id):
    ids = set()
    for store in cluster.nodes[node_id].command_stores.all_stores():
        ids.update(store.commands.keys())
    return next(iter(ids)) if len(ids) == 1 else None


def test_progress_log_settles_orphaned_preaccept():
    """Coordinator dies right after PreAccept: surviving home-shard replicas must
    settle the txn autonomously (invalidate or complete) — no client calls."""
    cluster, link = make_cluster()
    # let the preaccepts out, then the coordinator goes dark
    txn = list_txn([], {k(5): "a"})
    res = cluster.nodes[1].coordinate(txn)
    cluster.run_until(lambda: witnessed_txn_id(cluster, 2) is not None,
                      max_tasks=10_000)
    txn_id = witnessed_txn_id(cluster, 2)
    assert txn_id is not None
    link.dead = 1

    cluster.run_for(20.0)
    st = statuses(cluster, txn_id, (2, 3))
    assert st, "txn vanished"
    terminal = {SaveStatus.APPLIED, SaveStatus.INVALIDATED}
    assert all(s in terminal for s in st.values()), st
    assert len(set(st.values())) == 1, f"replicas disagree: {st}"
    # and the data converged with the decision
    vals = {cluster.stores[n].get(k(5)) for n in (2, 3)}
    assert len(vals) == 1


def test_progress_log_completes_stable_txn():
    """Coordinator dies after Stable reached replicas: progress log must finish
    execution (the txn is durably decided, so it MUST apply, not invalidate)."""
    class DropApply(LinkConfig):
        armed = False

        def action(self, from_node, to_node, message=None):
            if self.armed and from_node == 1:
                return LinkConfig.DROP
            if from_node == 1 and type(message).__name__ == "Apply":
                return LinkConfig.DROP
            return LinkConfig.DELIVER

    shards = [Shard(Range(k(0), k(1000)), [1, 2, 3])]
    link = DropApply(RandomSource(77))
    cluster = Cluster(Topology(1, shards), seed=3, link_config=link,
                      progress_log=True)
    txn = list_txn([], {k(7): "x"})
    res = cluster.nodes[1].coordinate(txn)

    def stable_on_replicas():
        tid = witnessed_txn_id(cluster, 2)
        if tid is None:
            return False
        st = statuses(cluster, tid, (2, 3))
        return len(st) == 2 and all(s.has_been(Status.STABLE) for s in st.values())

    cluster.run_until(stable_on_replicas, max_tasks=100_000)
    assert stable_on_replicas()
    txn_id = witnessed_txn_id(cluster, 2)
    link.armed = True  # node 1 goes fully dark

    cluster.run_for(20.0)
    st = statuses(cluster, txn_id, (2, 3))
    assert all(s is SaveStatus.APPLIED for s in st.values()), st
    for n in (2, 3):
        assert cluster.stores[n].get(k(7)) == ("x",)


def test_blocked_dependency_fetched_and_applied():
    """Apply of txn A never reaches node 3; a later conflicting txn B leaves node 3
    blocked on A.  The blocking machinery must fetch A's outcome and unblock B."""
    class DropApplyTo3(LinkConfig):
        active = True

        def action(self, from_node, to_node, message=None):
            if self.active and to_node == 3 and type(message).__name__ == "Apply":
                return LinkConfig.DROP
            return LinkConfig.DELIVER

    shards = [Shard(Range(k(0), k(1000)), [1, 2, 3])]
    link = DropApplyTo3(RandomSource(31))
    cluster = Cluster(Topology(1, shards), seed=9, link_config=link,
                      progress_log=True)

    ra = cluster.nodes[1].coordinate(list_txn([], {k(4): "A"}))
    assert cluster.run_until(ra.is_done)
    cluster.run_until_idle(max_tasks=50_000)
    assert cluster.stores[3].get(k(4)) == ()  # apply dropped

    link.active = False  # subsequent txns deliver everywhere
    rb = cluster.nodes[2].coordinate(list_txn([], {k(4): "B"}))
    assert cluster.run_until(rb.is_done)
    cluster.run_for(20.0)
    # node 3 must have resolved A through fetch/propagate and applied both
    assert cluster.stores[3].get(k(4)) == ("A", "B")


def test_progress_log_quiescent_on_healthy_cluster():
    """No faults: the progress log must not interfere (no recoveries, data exact)."""
    cluster, _link = make_cluster(seed=11)
    results = [cluster.nodes[1 + (i % 3)].coordinate(list_txn([], {k(2): i}))
               for i in range(6)]
    assert cluster.run_until(lambda: all(r.is_done() for r in results))
    cluster.run_for(10.0)
    lists = [cluster.stores[n].get(k(2)) for n in cluster.nodes]
    assert len(set(lists)) == 1
    assert sorted(lists[0]) == list(range(6))
    assert cluster.stats.get("BeginRecovery", 0) == 0, cluster.stats


def test_undecided_blocking_dependency_gets_settled():
    """Txn A's coordinator dies before reaching a quorum: A is pre-accepted on a
    minority only.  A later txn B witnesses A as a dep and blocks on it on nodes
    that never saw A.  The blocking machinery must drive A to a decision
    (complete or invalidate) so B executes everywhere."""
    class DropFromOne(LinkConfig):
        active = False

        def action(self, from_node, to_node, message=None):
            if self.active and from_node == 1:
                return LinkConfig.DROP
            return LinkConfig.DELIVER

    shards = [Shard(Range(k(0), k(1000)), [1, 2, 3])]
    link = DropFromOne(RandomSource(17))
    cluster = Cluster(Topology(1, shards), seed=21, link_config=link,
                      progress_log=True)

    # A pre-accepts ONLY on node 1 (its own store) — every outbound dropped
    link.active = True
    ra = cluster.nodes[1].coordinate(list_txn([], {k(6): "A"}))
    cluster.run_until(lambda: any(
        store.commands for store in cluster.nodes[1].command_stores.all_stores()),
        max_tasks=10_000)
    cluster.run_for(0.1)
    link.active = False

    # B from node 2: node 1's PreAccept reply includes A as a dependency
    rb = cluster.nodes[2].coordinate(list_txn([], {k(6): "B"}))
    assert cluster.run_until(rb.is_done, max_tasks=500_000)
    cluster.run_for(30.0)

    # every replica must converge: B applied everywhere; A either applied
    # everywhere-or-invalidated everywhere
    lists = {n: cluster.stores[n].get(k(6)) for n in cluster.nodes}
    assert len(set(lists.values())) == 1, f"diverged: {lists}"
    assert "B" in lists[1], lists
