"""Timestamp/TxnId/Ballot ordering, flags, packing and the witness matrix.

Parity targets: reference TxnIdTest / Timestamp semantics
(accord-core/src/test/java/accord/primitives/TxnIdTest.java, Timestamp.java:27-118).
"""
import pytest

from cassandra_accord_tpu.primitives.timestamp import (
    Ballot, Domain, REJECTED_FLAG, Timestamp, TxnId, TxnKind,
)


def test_total_order():
    a = Timestamp(1, 10, 1)
    b = Timestamp(1, 10, 2)
    c = Timestamp(1, 11, 1)
    d = Timestamp(2, 0, 0)
    assert a < b < c < d
    assert sorted([d, c, b, a]) == [a, b, c, d]
    assert a == Timestamp(1, 10, 1)
    assert hash(a) == hash(Timestamp(1, 10, 1))


def test_epoch_bounds():
    lo = Timestamp.min_for_epoch(5)
    hi = Timestamp.max_for_epoch(5)
    mid = Timestamp(5, 123, 7)
    assert lo <= mid <= hi
    assert hi < Timestamp.min_for_epoch(6)


def test_merge_max_retains_rejected_flag():
    a = Timestamp(1, 10, 1).with_rejected()
    b = Timestamp(1, 20, 1)
    m = a.merge_max(b)
    assert m.epoch == 1 and m.hlc == 20
    assert m.is_rejected  # MERGE_FLAGS retained from the smaller operand
    m2 = b.merge_max(a)
    assert m2.is_rejected


def test_pack_unpack_roundtrip():
    t = Timestamp(123456, (1 << 50) + 17, 42, 0x1E)
    msb, lsb = t.pack64()
    assert Timestamp.unpack64(msb, lsb, 42) == t
    # packed ordering agrees with logical ordering
    u = Timestamp(123456, (1 << 50) + 18, 42)
    assert t.pack64() < u.pack64()


def test_txnid_kind_domain_roundtrip():
    for kind in TxnKind:
        for domain in Domain:
            t = TxnId(3, 99, 5, kind, domain)
            assert t.kind is kind
            assert t.domain is domain
            assert t.epoch == 3 and t.hlc == 99 and t.node == 5


def test_txnid_ordering_consistent_with_timestamp():
    t1 = TxnId(1, 5, 1, TxnKind.READ)
    t2 = TxnId(1, 5, 1, TxnKind.WRITE)
    # different kinds differ in flags => not equal, but both between neighbors
    assert t1 != t2
    lo, hi = Timestamp(1, 4, 9), Timestamp(1, 6, 0)
    assert lo < t1 < hi and lo < t2 < hi


def test_witness_matrix():
    R, W, E = TxnKind.READ, TxnKind.WRITE, TxnKind.EPHEMERAL_READ
    S, X, L = TxnKind.SYNC_POINT, TxnKind.EXCLUSIVE_SYNC_POINT, TxnKind.LOCAL_ONLY
    # Read/EphemeralRead witness only writes (Txn.java: Ws)
    for r in (R, E):
        assert r.witnesses(W)
        assert not r.witnesses(R) and not r.witnesses(E)
        assert not r.witnesses(S) and not r.witnesses(X)
    # Write/SyncPoint witness reads+writes (RsOrWs) — not ephemeral reads
    for w in (W, S):
        assert w.witnesses(R) and w.witnesses(W)
        assert not w.witnesses(E) and not w.witnesses(X)
    # ExclusiveSyncPoint witnesses any globally visible
    assert X.witnesses(R) and X.witnesses(W) and X.witnesses(S) and X.witnesses(X)
    assert not X.witnesses(E) and not X.witnesses(L)
    # witnessed_by is the inverse of witnesses for globally-visible pairs
    for a in TxnKind:
        for b in TxnKind:
            if a.is_globally_visible and b.is_globally_visible:
                assert a.witnessed_by(b) == b.witnesses(a), (a, b)
    # EphemeralRead is witnessed by nothing
    for k in TxnKind:
        assert not E.witnessed_by(k)


def test_ballot():
    b = Ballot(1, 2, 3)
    assert Ballot.ZERO < b < Ballot.MAX
    assert isinstance(b.merge_max(Ballot(1, 5, 0)), Timestamp)


def test_awaits_only_deps():
    assert TxnKind.EXCLUSIVE_SYNC_POINT.awaits_only_deps
    assert TxnKind.EPHEMERAL_READ.awaits_only_deps
    assert not TxnKind.WRITE.awaits_only_deps
