"""Range-domain transactions end-to-end on the simulated cluster.

Parity target: the reference's range queries (BurnTest.java:208-240 range reads;
RangeDeps through PreAccept/Accept; range txns ordered against key writes).
"""
from cassandra_accord_tpu.harness.cluster import Cluster
from cassandra_accord_tpu.impl.list_store import ListResult, list_txn, range_read_txn
from cassandra_accord_tpu.primitives.keys import IntKey, Range, Ranges
from cassandra_accord_tpu.primitives.timestamp import Domain
from cassandra_accord_tpu.topology.topology import Shard, Topology


def k(v):
    return IntKey(v)


def make_cluster(seed=1, nodes=(1, 2, 3), shards=None, **kw):
    if shards is None:
        shards = [Shard(Range(k(0), k(1000)), list(nodes))]
    return Cluster(Topology(1, shards), seed=seed, **kw)


def submit_write(cluster, node_id, appends):
    txn = list_txn([], {k(key): v for key, v in appends.items()})
    return cluster.nodes[node_id].coordinate(txn)


def submit_range_read(cluster, node_id, lo, hi):
    txn = range_read_txn(Ranges.of(Range(k(lo), k(hi))))
    assert txn.domain is Domain.RANGE
    return cluster.nodes[node_id].coordinate(txn)


def test_range_read_sees_prior_writes():
    cluster = make_cluster()
    w = submit_write(cluster, 1, {5: "a", 50: "b", 500: "c"})
    assert cluster.run_until(w.is_done)
    r = submit_range_read(cluster, 2, 0, 100)
    assert cluster.run_until(r.is_done)
    assert isinstance(r.value, ListResult)
    assert r.value.reads[k(5)] == ("a",)
    assert r.value.reads[k(50)] == ("b",)
    assert k(500) not in r.value.reads  # outside the range


def test_range_read_across_shards():
    shards = [Shard(Range(k(0), k(100)), [1, 2, 3]),
              Shard(Range(k(100), k(200)), [1, 2, 3])]
    cluster = make_cluster(shards=shards)
    w = submit_write(cluster, 1, {50: "x", 150: "y"})
    assert cluster.run_until(w.is_done)
    r = submit_range_read(cluster, 3, 0, 200)
    assert cluster.run_until(r.is_done)
    assert r.value.reads[k(50)] == ("x",)
    assert r.value.reads[k(150)] == ("y",)


def test_range_read_atomic_under_concurrent_writes():
    """A range read must observe an atomic snapshot: for a multi-key txn's writes,
    either all keys inside the range show it, or none do."""
    cluster = make_cluster(seed=11)
    results = []
    for i in range(8):
        results.append(submit_write(cluster, 1 + (i % 3), {10: f"a{i}", 20: f"b{i}"}))
    reads = [submit_range_read(cluster, 1 + (i % 3), 0, 100) for i in range(6)]
    assert cluster.run_until(
        lambda: all(r.is_done() for r in results + reads))
    cluster.run_until_idle()
    for r in reads:
        obs = r.value.reads
        a = obs.get(k(10), ())
        b = obs.get(k(20), ())
        # writes are paired a{i}/b{i}: observed prefixes must have equal length
        assert len(a) == len(b), f"non-atomic range snapshot: {a} vs {b}"
        for va, vb in zip(a, b):
            assert va[1:] == vb[1:], f"order divergence: {a} vs {b}"


def test_range_reads_are_serialized_with_writes_per_key():
    """Successive range reads observe monotonically growing prefixes."""
    cluster = make_cluster(seed=3)
    prefixes = []
    for i in range(5):
        w = submit_write(cluster, 1 + (i % 3), {42: f"v{i}"})
        assert cluster.run_until(w.is_done)
        r = submit_range_read(cluster, 1 + ((i + 1) % 3), 0, 1000)
        assert cluster.run_until(r.is_done)
        prefixes.append(r.value.reads.get(k(42), ()))
    for earlier, later in zip(prefixes, prefixes[1:]):
        assert later[: len(earlier)] == earlier, prefixes
    assert prefixes[-1] == tuple(f"v{i}" for i in range(5))
