"""Overload robustness (ISSUE-17): admission control, retry budgets, and the
metastable-failure oracles.

Three planes under test:

- primitives (``local/overload.py`` + ``backoff_timeout_us``): deterministic
  hash jitter, token buckets, watermark hysteresis — property-pinned so the
  EXACT arithmetic (including post-cap jitter) can never drift silently;
- the burn harness with admission + budgets ON: open-loop hostile burns must
  shed fast, resolve everything, check clean against the history oracle, and
  stay deterministic with ZERO observer effect on the ``overload.*`` events;
- the acceptance oracles (``run_overload_ramp`` / ``run_overload_burst`` and
  the ``--overload`` CLI with its distinct exit code 4), small-scale in
  tier-1 with the full-scale sweeps gated behind ACCORD_LONG_BURNS.
"""
import json
import os

import pytest

from cassandra_accord_tpu.config import LocalConfig
from cassandra_accord_tpu.harness.burn import (build_slo_specs,
                                               main as burn_main,
                                               run_burn,
                                               run_overload_burst,
                                               run_overload_ramp)
from cassandra_accord_tpu.harness.cluster import backoff_timeout_us
from cassandra_accord_tpu.local.overload import (AdmissionController,
                                                 TokenBucket, hash_jitter)

from dataclasses import replace

HOSTILE = dict(chaos=True, allow_failures=True, durability=True,
               journal=True, delayed_stores=True, clock_drift=True,
               max_tasks=20_000_000)

ADMISSION_CFG = replace(LocalConfig(), admission_enabled=True,
                        retry_budget_enabled=True)


# ---------------------------------------------------------------- primitives

def test_backoff_timeout_us_properties():
    # satellite 1: pin the ACTUAL backoff arithmetic.  Jitter is applied
    # AFTER the max_s cap, so post-cap timeouts keep jittering upward in
    # [cap, cap*(1+jitter_frac)) — that is load-bearing (capped re-arms
    # across nodes must not phase-lock) and must not be "fixed".
    base_s, factor, max_s, jf = 0.25, 2.0, 4.0, 0.2
    for salt in (0, 1, 7, 12345, 2**63):
        for attempt in range(12):
            t = backoff_timeout_us(base_s, attempt, factor, max_s, jf, salt)
            # deterministic: a pure function of its arguments
            assert t == backoff_timeout_us(base_s, attempt, factor, max_s,
                                           jf, salt)
            capped = min(base_s * factor ** attempt, max_s)
            assert t >= int(capped * 1e6)
            assert t <= int(capped * (1.0 + jf) * 1e6)

    # jitter_frac=0: exactly the capped exponential, monotone nondecreasing
    prev = -1
    for attempt in range(12):
        t = backoff_timeout_us(base_s, attempt, factor, max_s, 0.0, 99)
        assert t == int(min(base_s * factor ** attempt, max_s) * 1e6)
        assert t >= prev
        prev = t
    assert prev == int(max_s * 1e6)   # the cap binds

    # post-cap jitter: attempts past the cap still vary (per attempt AND per
    # salt), so capped retries never phase-lock into a herd
    capped_attempts = [backoff_timeout_us(base_s, a, factor, max_s, jf, 42)
                      for a in range(8, 12)]
    assert len(set(capped_attempts)) > 1
    across_salts = [backoff_timeout_us(base_s, 10, factor, max_s, jf, s)
                    for s in range(16)]
    assert len(set(across_salts)) > 1


def test_hash_jitter_bounded_and_deterministic():
    vals = [hash_jitter(salt, n, 0.25)
            for salt in (0, 3, 2**40) for n in range(64)]
    assert all(-0.25 <= v < 0.25 for v in vals)
    assert len(set(vals)) > 100          # actually spreads
    assert hash_jitter(7, 3, 0.25) == hash_jitter(7, 3, 0.25)


def test_token_bucket_grants_burst_then_denies_then_refills():
    tb = TokenBucket(rate_per_s=2.0, burst=4.0, jitter_frac=0.0, salt=1)
    assert all(tb.try_acquire(0.0) for _ in range(4))   # starts full
    assert not tb.try_acquire(0.0)                      # empty -> denied
    assert tb.denied == 1 and tb.granted == 4
    assert tb.try_acquire(1.0)                          # 1s * 2/s = 2 tokens
    assert tb.try_acquire(1.0)
    assert not tb.try_acquire(1.0)
    # refill never exceeds burst
    assert tb.try_acquire(100.0)
    assert tb.tokens <= tb.burst
    # deterministic: a twin bucket fed the same calls agrees exactly
    a = TokenBucket(rate_per_s=3.0, burst=5.0, jitter_frac=0.25, salt=9)
    b = TokenBucket(rate_per_s=3.0, burst=5.0, jitter_frac=0.25, salt=9)
    calls = [0.0, 0.1, 0.1, 0.5, 2.0, 2.0, 2.0, 9.0]
    assert [a.try_acquire(t) for t in calls] == \
        [b.try_acquire(t) for t in calls]
    assert a.tokens == b.tokens


class _StubStores:
    def __init__(self):
        self.stores = []

    def all_stores(self):
        return self.stores


class _StubNode:
    """Just enough node surface for AdmissionController: config, a sink with
    ``callbacks``, command stores, and sim-time."""

    def __init__(self, cfg):
        self.config = cfg
        self.message_sink = type("S", (), {"callbacks": {}})()
        self.command_stores = _StubStores()
        self._now = 0

    def now_micros(self):
        return self._now

    def tick(self, callbacks: int):
        # advance past the 100ms recompute bucket so load() re-reads
        self._now += 200_000
        self.message_sink.callbacks = {i: None for i in range(callbacks)}


def test_admission_hysteresis():
    cfg = replace(LocalConfig(), admission_enabled=True, admission_hi=10,
                  admission_lo=4)
    node = _StubNode(cfg)
    adm = AdmissionController(node)
    node.tick(9)
    assert not adm.overloaded()          # below hi: admitting
    node.tick(10)
    assert adm.overloaded()              # at hi: starts shedding
    node.tick(7)
    assert adm.overloaded()              # between lo and hi: KEEPS shedding
    node.tick(5)
    assert adm.overloaded()              # still above lo
    node.tick(4)
    assert not adm.overloaded()          # at lo: readmits
    node.tick(9)
    assert not adm.overloaded()          # below hi again: no flap


def test_admission_load_cached_within_bucket():
    cfg = replace(LocalConfig(), admission_enabled=True)
    node = _StubNode(cfg)
    adm = AdmissionController(node)
    node.tick(3)
    assert adm.load() == 3
    # mutate WITHOUT advancing sim-time: the 100ms cache holds
    node.message_sink.callbacks = {i: None for i in range(50)}
    assert adm.load() == 3
    node.tick(50)
    assert adm.load() == 50


def test_overload_knobs_default_off():
    cfg = LocalConfig()
    assert cfg.admission_enabled is False
    assert cfg.retry_budget_enabled is False
    # a default-config burn builds no admission plane and counts nothing
    res = run_burn(5, ops=30, concurrency=6, workload="openloop",
                   rate_txn_s=40.0, **HOSTILE)
    assert res.ops_shed == 0 and res.overload_nacks == 0
    assert res.budget_denied == 0
    assert "overload_nacks" not in res.stats
    assert "ops_shed" not in res.stats


# ------------------------------------------------- admission-enabled burns

def test_admission_burn_sheds_and_checks_clean():
    # the hostile matrix with admission + budgets ON at an overdriven rate:
    # every op resolves (shed = fast client-visible FAILURE, sound because
    # the txn is refused before a txn id exists), the history checks clean,
    # and the shed/nack counters actually populate
    res = run_burn(1, ops=120, concurrency=10, workload="openloop",
                   rate_txn_s=60.0, node_config=ADMISSION_CFG,
                   check="history", **HOSTILE)
    assert res.resolved == 120
    assert res.history is not None and res.history["anomalies"] == []
    assert res.ops_shed + res.overload_nacks > 0
    assert res.ops_failed >= res.ops_shed    # sheds surface as failed


def test_admission_burn_is_deterministic():
    from cassandra_accord_tpu.harness.trace import Trace, diff_traces
    kw = dict(ops=60, concurrency=8, workload="openloop", rate_txn_s=60.0,
              node_config=ADMISSION_CFG, **HOSTILE)
    ta, tb = Trace(), Trace()
    a = run_burn(2, tracer=ta.hook, **kw)
    b = run_burn(2, tracer=tb.hook, **kw)
    assert diff_traces(ta, tb) is None
    assert (a.ops_shed, a.overload_nacks, a.budget_denied) == \
        (b.ops_shed, b.overload_nacks, b.budget_denied)


def test_overload_events_have_zero_observer_effect():
    # the PR-10 contract extended to overload.*: attaching a full recorder
    # must not move a single event in an admission-enabled trajectory
    from cassandra_accord_tpu.harness.trace import Trace, diff_traces
    from cassandra_accord_tpu.observe import FlightRecorder
    kw = dict(ops=120, concurrency=10, workload="openloop", rate_txn_s=60.0,
              node_config=ADMISSION_CFG, **HOSTILE)
    ta, tb = Trace(), Trace()
    run_burn(1, tracer=ta.hook, **kw)
    rec = FlightRecorder()
    run_burn(1, tracer=tb.hook, observer=rec, **kw)
    assert diff_traces(ta, tb) is None
    # and the observer actually SAW the overload plane
    snap = rec.registry.snapshot()
    assert any(name.startswith("overload.") and value > 0
               for metrics in snap.values()
               for name, value in metrics.items()
               if not isinstance(value, dict))


# ------------------------------------------------------- acceptance oracles

def _oracle_kw(ops):
    return dict(ops=ops, concurrency=10, node_config=ADMISSION_CFG,
                check="history", **HOSTILE)


def test_overload_ramp_small_scale_passes():
    # tier-1 scale metastability ramp: 1x and 2x of a modest rate must hold
    # the goodput floor with admission + budgets on (the full 0.5x..4x
    # sweep is the ACCORD_LONG_BURNS soak below)
    out = run_overload_ramp(1, _oracle_kw(60), 30.0, mults=(1.0, 2.0),
                            frac=0.8)
    assert out["passed"], out
    assert out["capacity_goodput_txn_s"] > 0
    assert out["goodput_floor_frac"] >= 0.8
    assert [p["mult"] for p in out["points"]] == [1.0, 2.0]
    # overload points actually exercised the defense
    assert out["points"][1]["shed"] + out["points"][1]["nacks"] > 0


def test_overload_burst_small_scale_recovers():
    # burst-then-recover at tier-1 scale: post-burst goodput back to >= 80%
    # of pre-burst, zero open SLO flags/burns at quiesce
    out = run_overload_burst(1, _oracle_kw(200), 10.0, burst_mult=3.0,
                             pre_s=6.0, burst_s=4.0, post_s=8.0, frac=0.8)
    assert out["passed"], out
    assert out["pre_goodput_txn_s"] > 0
    assert out["post_goodput_txn_s"] >= 0.8 * out["pre_goodput_txn_s"]
    assert out["slo_flags_open"] == 0 and out["open_slo_burns"] == 0


def test_build_slo_specs():
    # satellite 2: None when nothing is overridden (callers keep defaults)
    assert build_slo_specs(None, None, None) is None
    from cassandra_accord_tpu.observe.burnrate import DEFAULT_SLOS
    specs = build_slo_specs(0.5, 0.1, "5:50")
    assert specs is not None
    defaults = {s.name: s for s in DEFAULT_SLOS}
    for s in specs:
        assert s.budget == 0.1
        assert s.short_us == 5_000_000 and s.long_us == 50_000_000
        if s.kind == "latency":
            assert s.latency_slo_us == 500_000
        else:
            # non-latency specs keep their default threshold untouched
            assert s.latency_slo_us == defaults[s.name].latency_slo_us
    # latency override alone leaves liveness budget untouched
    from cassandra_accord_tpu.observe.burnrate import DEFAULT_SLOS
    only_lat = build_slo_specs(1.0, None, None)
    assert {s.name: s.budget for s in only_lat} == \
        {s.name: s.budget for s in DEFAULT_SLOS}
    with pytest.raises(ValueError):
        build_slo_specs(None, None, "nocolon")


def test_overload_cli_ramp_pass_and_exit4_on_failure(tmp_path, monkeypatch):
    # satellite 3: the --overload CLI ledgers a kind=overload record, emits
    # shed/paced/budget-denied in --json, and distinguishes "survived but
    # failed the acceptance bar" with exit code 4 (stalls stay exit 2)
    ledger = tmp_path / "history.jsonl"
    out_json = tmp_path / "overload.json"
    monkeypatch.setenv("ACCORD_BENCH_HISTORY", str(ledger))
    burn_main(["--seeds", "1", "--ops", "60", "--rate", "30",
               "--overload", "ramp", "--overload-mults", "1,2",
               "--check", "history", "--json", str(out_json)])
    doc = json.loads(out_json.read_text())
    (entry,) = doc["results"]
    assert entry["status"] == "pass"
    result = entry["result"]
    assert result["passed"] is True
    for point in result["points"]:
        assert {"shed", "paced", "budget_denied"} <= set(point)
    records = [json.loads(l) for l in ledger.read_text().splitlines()]
    (rec,) = [r for r in records if r["kind"] == "overload"]
    assert rec["metric"] == "goodput_floor_frac" and rec["passed"] is True
    assert rec["capacity_goodput_txn_s"] > 0

    # an impossible floor fraction: the cluster survives (no stall) but the
    # acceptance bar fails -> exit code 4, status overload_failed
    with pytest.raises(SystemExit) as exc:
        burn_main(["--seeds", "1", "--ops", "60", "--rate", "30",
                   "--overload", "ramp", "--overload-mults", "1,2",
                   "--overload-frac", "5.0", "--check", "history",
                   "--json", str(out_json)])
    assert exc.value.code == 4
    doc = json.loads(out_json.read_text())
    assert doc["results"][0]["status"] == "overload_failed"


def test_overload_cli_rejects_bad_combos():
    with pytest.raises(SystemExit):
        burn_main(["--seeds", "0", "--overload", "ramp",
                   "--workload", "zipf"])
    with pytest.raises(SystemExit):
        burn_main(["--seeds", "0", "--overload", "ramp", "--reconcile"])
    with pytest.raises(SystemExit):
        burn_main(["--seeds", "0:2", "--overload", "ramp",
                   "--parallel-seeds", "2"])


# ------------------------------------------------------------------- soaks

@pytest.mark.slow
@pytest.mark.skipif("ACCORD_LONG_BURNS" not in os.environ,
                    reason="hours-class: full overload sweeps")
def test_overload_ramp_full_sweep():
    out = run_overload_ramp(1, _oracle_kw(150), 30.0,
                            mults=(0.5, 1.0, 2.0, 4.0), frac=0.8)
    assert out["passed"], out


@pytest.mark.slow
@pytest.mark.skipif("ACCORD_LONG_BURNS" not in os.environ,
                    reason="hours-class: full overload sweeps")
@pytest.mark.xfail(strict=False,
                   reason="open find (KNOWN_ISSUES round 15): on the "
                          "committed tree the PR-17 invalidate_conflict "
                          "claim does NOT reproduce (0 violations); the "
                          "soak instead fails the 0.8 recovery bar — "
                          "post-burst goodput 0.147x of pre over a ~615 "
                          "sim-s CheckStatus probe-storm drain tail — "
                          "flips to XPASS when root-caused")
def test_overload_burst_soak():
    out = run_overload_burst(1, _oracle_kw(4500), 30.0, burst_mult=4.0,
                             pre_s=30.0, burst_s=20.0, post_s=40.0, frac=0.8)
    assert out["passed"], out
