"""The cross-run trend ledger (tools/trend.py + BENCH_HISTORY.jsonl).

Contracts: append/load round-trip (with the ACCORD_BENCH_HISTORY override
and kill switch), torn-tail tolerance, delta rendering, the CLI's stdout
TAIL contract (last line = one compact single-line JSON object, same as
bench.py), and the perfgate integration (trend context printed; offline
compares never append)."""
import io
import json
import os
import subprocess
import sys

from tools import trend

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _entry(i, mean):
    return {"kind": "bench", "metric": "m", "value": mean,
            "sim": {"commit_latency_mean_us": mean,
                    "commit_latency_p95_us": mean * 2,
                    "sim_ms": 1000 + i, "messages": 4000 + i}}


def test_append_and_load_roundtrip(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    for i in range(3):
        stamped = trend.append_entry(_entry(i, 100.0 + i), path=path)
        assert stamped is not None and "ts" in stamped
    entries = trend.load_history(path)
    assert len(entries) == 3
    assert entries[-1]["sim"]["commit_latency_mean_us"] == 102.0
    assert all("ts" in e for e in entries)


def test_env_override_and_kill_switch(tmp_path, monkeypatch):
    target = str(tmp_path / "env.jsonl")
    monkeypatch.setenv("ACCORD_BENCH_HISTORY", target)
    assert trend.history_path() == target
    trend.append_entry(_entry(0, 1.0))
    assert len(trend.load_history()) == 1
    monkeypatch.setenv("ACCORD_BENCH_HISTORY", "0")
    assert trend.history_path() is None
    assert trend.append_entry(_entry(1, 2.0)) is None   # disabled, no raise
    assert trend.load_history() == []


def test_torn_tail_lines_are_skipped(tmp_path):
    path = tmp_path / "torn.jsonl"
    path.write_text(json.dumps(_entry(0, 50.0)) + "\n"
                    + '{"kind": "bench", "tru')       # killed mid-append
    entries = trend.load_history(str(path))
    assert len(entries) == 1


def test_trend_lines_render_deltas(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    trend.append_entry(_entry(0, 100.0), path=path)
    trend.append_entry(_entry(1, 150.0), path=path)
    lines = trend.trend_lines(trend.load_history(path))
    text = "\n".join(lines)
    assert "last 2 of 2 recorded runs" in text
    assert "commit_latency_mean_us" in text
    assert "(+50.0%)" in text
    deltas = trend.latest_deltas(trend.load_history(path))
    assert deltas["commit_latency_mean_us"] == 1.5


def test_empty_history_renders_gracefully():
    lines = trend.trend_lines([])
    assert any("no runs recorded" in l for l in lines)
    assert trend.latest_deltas([]) == {}


def test_cli_stdout_tail_contract(tmp_path):
    """The LAST stdout line of tools/trend.py is one compact single-line
    JSON object (the bounded-tail-capture contract bench.py honors)."""
    path = str(tmp_path / "hist.jsonl")
    trend.append_entry(_entry(0, 100.0), path=path)
    trend.append_entry(_entry(1, 110.0), path=path)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trend.py"),
         "--history", path],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-1000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    tail = json.loads(lines[-1])               # the harness's parse, exactly
    assert tail["runs"] == 2 and tail["window"] == 2
    assert tail["latest"]["sim"]["commit_latency_mean_us"] == 110.0
    assert tail["deltas_vs_prev"]["commit_latency_mean_us"] == 1.1
    assert len(lines[-1]) < 4096
    # human-readable trend lines precede the tail
    assert any("commit_latency_mean_us" in l for l in lines[:-1])


def test_perfgate_prints_trend_and_offline_compare_never_appends(
        tmp_path, monkeypatch):
    """perfgate.run with a saved measurement (offline gating) must print the
    trend context but NOT append to the ledger — only real measurements
    grow the trajectory."""
    from tools import perfgate
    path = str(tmp_path / "hist.jsonl")
    monkeypatch.setenv("ACCORD_BENCH_HISTORY", path)
    trend.append_entry(_entry(0, 100.0))
    current = {"sim": {k: 1000.0 for k, _t in perfgate.GATED_METRICS},
               "wall": {}, "workload": {"seed": 7}}
    out = io.StringIO()
    rc = perfgate.run(gate=False, current=current, out=out)
    assert rc == 0
    text = out.getvalue()
    assert "trend: last 1 of 1 recorded runs" in text
    assert len(trend.load_history()) == 1, \
        "offline compare appended to the ledger"


def test_repo_ledger_exists_with_runs():
    """The acceptance artifact: the repo's BENCH_HISTORY.jsonl carries at
    least two appended runs and tools/trend.py renders their deltas."""
    entries = trend.load_history(trend.DEFAULT_HISTORY_PATH)
    assert len(entries) >= 2, \
        "BENCH_HISTORY.jsonl missing or under-populated — run " \
        "`python tools/perfgate.py --smoke` twice"
    lines = trend.trend_lines(entries)
    assert any("commit_latency_mean_us" in l for l in lines)
