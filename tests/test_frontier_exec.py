"""Frontier-DRIVEN execution: the device kahn_frontier releases STABLE txns
into ReadyToExecute instead of the event-driven WaitingOn drain firing them
inline (SURVEY §7 stage 8 'execute-phase topological wait on device';
VERDICT r03 item 3).  The event path still does all bookkeeping, so a
frontier that misses a ready txn stalls the run loudly.

Round 12 promoted the mode into the FULL hostile matrix: the one-sided
device mirror leak (KNOWN_ISSUES rounds 6-11) is fixed — terminal SaveStatus
transitions now reach the resolver mirror through ``note_terminal`` at the
transition choke point instead of riding the cfk-gated witness path — and
the old ACCORD_LONG_BURNS xfail repro is the tier-1 regression test below."""
import os

import pytest

from cassandra_accord_tpu.harness.burn import run_burn


def test_benign_burn_frontier_driven(monkeypatch):
    monkeypatch.setenv("ACCORD_TPU_WALK_MAX", "0")
    result = run_burn(seed=301, ops=60, concurrency=8, resolver="verify",
                      frontier_exec=True)
    assert result.ops_ok == 60


def test_frontier_driven_actually_defers(monkeypatch):
    """The mode must actually route executions through the frontier: with a
    contended single key every later write waits on earlier ones, so some
    must park in exec_deferred before the device frontier releases them."""
    monkeypatch.setenv("ACCORD_TPU_WALK_MAX", "0")
    released = {"n": 0}
    from cassandra_accord_tpu.local import commands as C
    orig = C.maybe_execute

    def counting(safe_store, command, always_notify_listeners,
                 from_frontier=False):
        if from_frontier:
            released["n"] += 1
        return orig(safe_store, command, always_notify_listeners,
                    from_frontier=from_frontier)
    monkeypatch.setattr(C, "maybe_execute", counting)
    result = run_burn(seed=302, ops=50, concurrency=10, key_count=2,
                      resolver="verify", frontier_exec=True)
    assert result.ops_ok == 50
    assert released["n"] > 0, \
        "no execution was ever released by the device frontier"


def test_hostile_burn_frontier_driven(monkeypatch):
    """The verdict's done-criterion: hostile burn green with frontier-driven
    execution under resolver=verify (chaos + durability + journal +
    delayed stores)."""
    monkeypatch.setenv("ACCORD_TPU_WALK_MAX", "0")
    result = run_burn(seed=303, ops=40, concurrency=8, chaos=True,
                      allow_failures=True, durability=True, journal=True,
                      delayed_stores=True, resolver="verify",
                      frontier_exec=True, max_tasks=4_000_000)
    assert result.resolved == 40


def test_hostile_burn_frontier_driven_with_churn(monkeypatch):
    monkeypatch.setenv("ACCORD_TPU_WALK_MAX", "0")
    result = run_burn(seed=304, ops=40, concurrency=8, chaos=True,
                      allow_failures=True, durability=True, journal=True,
                      topology_churn=True, resolver="verify",
                      frontier_exec=True, max_tasks=6_000_000)
    assert result.resolved == 40


# ---------------------------------------------------------------------------
# Round 12: the mirror-leak regression suite (KNOWN_ISSUES rounds 6-11 fix)
# ---------------------------------------------------------------------------

def test_terminal_transition_reaches_device_mirror():
    """The pinned mirror-leak shape: a terminal transition on the last
    in-flight dependency must propagate to the device wait-graph mirror
    before quiescence EVEN WHEN the cfk witness path refuses the update
    (demoted-cold/pruned entry, churn-dropped key, truncation/GC-erase that
    never calls register_witness).  ``note_terminal`` is that propagation:
    without it the dep's mirror row stayed STABLE and the kernel frontier
    reported it ready forever (device-only=7 / host-only=[] at final
    quiescence on the round-6 repro)."""
    from cassandra_accord_tpu.local.cfk import InternalStatus
    from cassandra_accord_tpu.primitives.timestamp import Timestamp
    from tests.test_resolver import make_pair, register_both, rk, tid

    store, verify = make_pair()
    tpu = verify.tpu
    w, d = tid(10), tid(20)
    for t, ks in ((w, [rk(0)]), (d, [rk(0)])):
        register_both(store, verify, t, InternalStatus.PREACCEPTED, None, ks)
        register_both(store, verify, t, InternalStatus.STABLE,
                      Timestamp(1, t.hlc + 1, 0, 1), ks)
    tpu.register_waiting(w, {d})
    tpu.register_waiting(d, set())
    assert tpu.frontier_ready() == {d}          # w blocked on d
    # d reaches APPLIED on the host but the cfk refuses the witness update
    # (the leak shape): ONLY note_terminal carries it to the mirror — the
    # waiting edge then points at a done slot and w becomes ready, with NO
    # remove_waiting ever mirrored
    verify.note_terminal(d)
    ready = tpu.frontier_ready()
    assert d not in ready, "terminal dep still reported execution-ready"
    assert ready == {w}, f"waiter not released by terminal dep: {ready}"
    # terminal waiter leaves the frontier and drops its own edges
    verify.note_terminal(w)
    assert tpu.frontier_ready() == set()
    assert w not in tpu.edges


def test_note_terminal_invalidated_guard():
    """The invalidated path honors cfk.update's committed-never-invalidated
    rule: a committed-or-later mirror row ignores an invalidation signal
    (same guard as ``register``), a pre-committed row takes it."""
    from cassandra_accord_tpu.local.cfk import InternalStatus
    from cassandra_accord_tpu.primitives.timestamp import Timestamp
    from tests.test_resolver import make_pair, register_both, rk, tid

    store, verify = make_pair()
    tpu = verify.tpu
    a, b = tid(10), tid(20)
    register_both(store, verify, a, InternalStatus.PREACCEPTED, None, [rk(0)])
    register_both(store, verify, b, InternalStatus.STABLE,
                  Timestamp(1, b.hlc + 1, 0, 1), [rk(0)])
    inv = int(InternalStatus.INVALIDATED)
    verify.note_terminal(a, invalidated=True)
    assert tpu.txns[a].status == inv
    verify.note_terminal(b, invalidated=True)   # committed+: must refuse
    assert tpu.txns[b].status != inv


def test_frontier_exec_full_hostile_matrix_parity(monkeypatch):
    """THE promoted round-6 repro, verbatim config, now expected clean: seed
    0, 100 ops, full hostile matrix (chaos + churn + durability + journal +
    delayed stores + clock drift + cache-miss eviction) under frontier-driven
    execution and strict audit.  The final-quiescence verify_frontiers pass
    inside run_burn is the oracle that used to throw device-only=7."""
    result = run_burn(0, ops=100, concurrency=20, resolver="verify",
                      frontier_exec=True, chaos=True, allow_failures=True,
                      topology_churn=True, durability=True, journal=True,
                      delayed_stores=True, clock_drift=True, cache_miss=True,
                      audit="strict", max_tasks=200_000_000)
    assert result.resolved == 100
    assert result.stats.get("frontier_released", 0) > 0, \
        "frontier mode never released anything — the mode did not engage"


def test_frontier_exec_gray_elastic_strict():
    """Frontier execution composed with the gray-failure plane (pause +
    disk-stall nemeses) and elastic membership under strict audit — the
    promotion's widest tier-1 compose."""
    result = run_burn(3, ops=80, concurrency=16, resolver="verify",
                      frontier_exec=True, chaos=True, allow_failures=True,
                      durability=True, journal=True, pause_nodes=True,
                      disk_stall=True, elastic_membership=True,
                      topology_churn=True, audit="strict",
                      max_tasks=200_000_000)
    assert result.resolved == 80


@pytest.mark.skipif("ACCORD_LONG_BURNS" not in os.environ,
                    reason="seed-range frontier matrix; run with ACCORD_LONG_BURNS=1")
def test_frontier_exec_hostile_matrix_seed_range():
    """ISSUE 13 acceptance: frontier_exec=True strict-clean across seeds 0-9
    under the full hostile matrix (zero violations — the in-run audit and
    the final verify_frontiers parity pass both gate)."""
    for seed in range(10):
        result = run_burn(seed, ops=100, concurrency=20, resolver="verify",
                          frontier_exec=True, chaos=True, allow_failures=True,
                          topology_churn=True, durability=True, journal=True,
                          delayed_stores=True, clock_drift=True,
                          cache_miss=True, audit="strict",
                          max_tasks=200_000_000)
        assert result.resolved == 100, f"seed {seed}: {result}"
