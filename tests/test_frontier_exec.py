"""Frontier-DRIVEN execution: the device kahn_frontier releases STABLE txns
into ReadyToExecute instead of the event-driven WaitingOn drain firing them
inline (SURVEY §7 stage 8 'execute-phase topological wait on device';
VERDICT r03 item 3).  The event path still does all bookkeeping, so a
frontier that misses a ready txn stalls the run loudly."""
import pytest

from cassandra_accord_tpu.harness.burn import run_burn


def test_benign_burn_frontier_driven(monkeypatch):
    monkeypatch.setenv("ACCORD_TPU_WALK_MAX", "0")
    result = run_burn(seed=301, ops=60, concurrency=8, resolver="verify",
                      frontier_exec=True)
    assert result.ops_ok == 60


def test_frontier_driven_actually_defers(monkeypatch):
    """The mode must actually route executions through the frontier: with a
    contended single key every later write waits on earlier ones, so some
    must park in exec_deferred before the device frontier releases them."""
    monkeypatch.setenv("ACCORD_TPU_WALK_MAX", "0")
    released = {"n": 0}
    from cassandra_accord_tpu.local import commands as C
    orig = C.maybe_execute

    def counting(safe_store, command, always_notify_listeners,
                 from_frontier=False):
        if from_frontier:
            released["n"] += 1
        return orig(safe_store, command, always_notify_listeners,
                    from_frontier=from_frontier)
    monkeypatch.setattr(C, "maybe_execute", counting)
    result = run_burn(seed=302, ops=50, concurrency=10, key_count=2,
                      resolver="verify", frontier_exec=True)
    assert result.ops_ok == 50
    assert released["n"] > 0, \
        "no execution was ever released by the device frontier"


def test_hostile_burn_frontier_driven(monkeypatch):
    """The verdict's done-criterion: hostile burn green with frontier-driven
    execution under resolver=verify (chaos + durability + journal +
    delayed stores)."""
    monkeypatch.setenv("ACCORD_TPU_WALK_MAX", "0")
    result = run_burn(seed=303, ops=40, concurrency=8, chaos=True,
                      allow_failures=True, durability=True, journal=True,
                      delayed_stores=True, resolver="verify",
                      frontier_exec=True, max_tasks=4_000_000)
    assert result.resolved == 40


def test_hostile_burn_frontier_driven_with_churn(monkeypatch):
    monkeypatch.setenv("ACCORD_TPU_WALK_MAX", "0")
    result = run_burn(seed=304, ops=40, concurrency=8, chaos=True,
                      allow_failures=True, durability=True, journal=True,
                      topology_churn=True, resolver="verify",
                      frontier_exec=True, max_tasks=6_000_000)
    assert result.resolved == 40
