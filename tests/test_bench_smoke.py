"""bench.py stdout TAIL contract (tier-1).

The driver harness captures only a bounded tail of bench stdout and parses
its LAST line as JSON.  PR 4 fixed the overflow that nulled every
BENCH_r0*.json but left the contract untested — this is the regression
test, pinned on the fast ``--smoke`` mode so tier-1 stays seconds-class.
"""
import json
import os
import subprocess
import sys

import pytest

BENCH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "bench.py")


@pytest.fixture(scope="module")
def smoke_ledger(tmp_path_factory):
    return str(tmp_path_factory.mktemp("trend") / "hist.jsonl")


@pytest.fixture(scope="module")
def smoke_run(smoke_ledger):
    env = dict(os.environ, JAX_PLATFORMS="cpu", ACCORD_BENCH_DEADLINE_S="150",
               ACCORD_BENCH_HISTORY=smoke_ledger)
    proc = subprocess.run([sys.executable, BENCH, "--smoke"],
                          capture_output=True, text=True, timeout=200,
                          env=env, cwd=os.path.dirname(BENCH))
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc


def test_smoke_last_stdout_line_is_single_json_object(smoke_run):
    lines = [l for l in smoke_run.stdout.splitlines() if l.strip()]
    assert lines, "bench --smoke printed nothing"
    tail = json.loads(lines[-1])          # the harness's parse, exactly
    assert isinstance(tail, dict)
    # the compact summary carries the headline + per-stage health
    assert tail["metric"] == "smoke_commit_latency_mean_us"
    assert isinstance(tail["value"], (int, float)) and tail["value"] > 0
    assert tail["stages"].get("smoke") == "ok"
    assert tail["incomplete"] is False
    # sized to survive a bounded tail capture (the full-detail object that
    # overflowed r01-r04 was tens of KB)
    assert len(lines[-1]) < 4096


def test_smoke_emits_full_detail_object_before_tail(smoke_run):
    lines = [l for l in smoke_run.stdout.splitlines() if l.strip()]
    assert len(lines) >= 2
    full = json.loads(lines[-2])
    smoke = full["detail"]["smoke"]
    # the measurement is the perfgate one: sim plane + budget + wall plane
    assert smoke["sim"]["commits"] == smoke["workload"]["ops"]
    assert smoke["attributed_share"] >= 0.95
    assert smoke["dominating_class"]


def test_smoke_appends_one_trend_ledger_record(smoke_run, smoke_ledger):
    """Every bench run appends its summary to the trend ledger
    (BENCH_HISTORY.jsonl via ACCORD_BENCH_HISTORY) — the durable perf
    trajectory tools/trend.py renders."""
    records = [json.loads(l)
               for l in open(smoke_ledger).read().splitlines() if l.strip()]
    assert len(records) == 1
    assert records[0]["kind"] == "bench"
    assert records[0]["sim"]["commit_latency_mean_us"] > 0


def test_inject_self_test_bench_run_skips_the_ledger(tmp_path):
    """ACCORD_PERFGATE_INJECT_LATENCY doctors the measured latencies — a
    bench run under it must NOT append to the trend ledger (where it would
    read as a real 2x regression); the gate must still trip (exit 3)."""
    ledger = tmp_path / "hist.jsonl"
    env = dict(os.environ, JAX_PLATFORMS="cpu", ACCORD_BENCH_DEADLINE_S="150",
               ACCORD_BENCH_HISTORY=str(ledger),
               ACCORD_PERFGATE_INJECT_LATENCY="2.0")
    proc = subprocess.run([sys.executable, BENCH, "--gate"],
                          capture_output=True, text=True, timeout=200,
                          env=env, cwd=os.path.dirname(BENCH))
    assert proc.returncode == 3, (proc.stdout[-800:], proc.stderr[-800:])
    assert not ledger.exists(), "doctored run leaked into the trend ledger"
