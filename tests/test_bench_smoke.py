"""bench.py stdout TAIL contract (tier-1).

The driver harness captures only a bounded tail of bench stdout and parses
its LAST line as JSON.  PR 4 fixed the overflow that nulled every
BENCH_r0*.json but left the contract untested — this is the regression
test, pinned on the fast ``--smoke`` mode so tier-1 stays seconds-class.
"""
import json
import os
import subprocess
import sys

import pytest

BENCH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "bench.py")


@pytest.fixture(scope="module")
def smoke_run():
    env = dict(os.environ, JAX_PLATFORMS="cpu", ACCORD_BENCH_DEADLINE_S="150")
    proc = subprocess.run([sys.executable, BENCH, "--smoke"],
                          capture_output=True, text=True, timeout=200,
                          env=env, cwd=os.path.dirname(BENCH))
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc


def test_smoke_last_stdout_line_is_single_json_object(smoke_run):
    lines = [l for l in smoke_run.stdout.splitlines() if l.strip()]
    assert lines, "bench --smoke printed nothing"
    tail = json.loads(lines[-1])          # the harness's parse, exactly
    assert isinstance(tail, dict)
    # the compact summary carries the headline + per-stage health
    assert tail["metric"] == "smoke_commit_latency_mean_us"
    assert isinstance(tail["value"], (int, float)) and tail["value"] > 0
    assert tail["stages"].get("smoke") == "ok"
    assert tail["incomplete"] is False
    # sized to survive a bounded tail capture (the full-detail object that
    # overflowed r01-r04 was tens of KB)
    assert len(lines[-1]) < 4096


def test_smoke_emits_full_detail_object_before_tail(smoke_run):
    lines = [l for l in smoke_run.stdout.splitlines() if l.strip()]
    assert len(lines) >= 2
    full = json.loads(lines[-2])
    smoke = full["detail"]["smoke"]
    # the measurement is the perfgate one: sim plane + budget + wall plane
    assert smoke["sim"]["commits"] == smoke["workload"]["ops"]
    assert smoke["attributed_share"] >= 0.95
    assert smoke["dominating_class"]
