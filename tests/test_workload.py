"""Workload presets (ISSUE-16): multi-range/interactive, Zipf-with-migration,
open-loop Poisson — plus the --parallel-seeds sweep runner.

Every preset runs under the hostile matrix with the history oracle on: the
new traffic shapes must not just execute, they must check clean against a
protocol-blind second opinion.  Heavy presets (10k-op Zipf, open-loop soak)
are gated behind ACCORD_LONG_BURNS.
"""
import json
import os

import pytest

from cassandra_accord_tpu.harness.burn import main as burn_main
from cassandra_accord_tpu.harness.burn import run_burn
from cassandra_accord_tpu.harness.workload import (MultiRangeWorkload,
                                                   OpenLoopWorkload,
                                                   ZipfWorkload,
                                                   make_workload)

HOSTILE = dict(chaos=True, allow_failures=True, durability=True,
               journal=True, delayed_stores=True, clock_drift=True,
               max_tasks=20_000_000)


def test_make_workload_rejects_unknown():
    with pytest.raises(ValueError):
        make_workload("bogus")
    w = OpenLoopWorkload(rate_txn_s=10.0)
    assert make_workload(w) is w   # instances pass through


def test_multirange_hostile_with_interactive_ops():
    # cross-shard txns + barriers + sync points through the coordinate
    # surface, under chaos + churn + elastic membership, history-checked
    w = MultiRangeWorkload()
    res = run_burn(1, ops=80, concurrency=10, topology_churn=True,
                   elastic_membership=True, check="history", workload=w,
                   **HOSTILE)
    assert res.resolved == 80
    assert res.history is not None and res.history["anomalies"] == []
    # the preset actually generated every op class it advertises
    assert w.counts.get("multirange_txn", 0) > 0
    assert w.counts.get("range_read", 0) > 0
    assert w.counts.get("barrier", 0) + w.counts.get("sync_point", 0) > 0


def test_zipf_migration_moves_the_hot_range():
    w = ZipfWorkload()
    res = run_burn(2, ops=120, concurrency=10, check="history", workload=w,
                   **HOSTILE)
    assert res.resolved == 120
    assert res.history is not None and res.history["anomalies"] == []
    assert w.counts.get("post_migration", 0) > 0
    # forensics: the modal hot index must MOVE at the migration point
    cut = int(120 * w.migrate_at)
    pre = [idx for op_id, idx in w.key_log if op_id < cut]
    post = [idx for op_id, idx in w.key_log if op_id >= cut]
    assert pre and post
    mode = lambda xs: max(set(xs), key=xs.count)   # noqa: E731
    assert mode(pre) != mode(post)


def test_openloop_sustains_rate_with_zero_slo_burn():
    # the PR-10 burn-rate monitors as the pass/fail oracle: at a modest
    # arrival rate the hostile matrix must hold the SLO with zero burns
    from cassandra_accord_tpu.observe import BurnRateMonitor, InvariantAuditor
    monitor = BurnRateMonitor()
    auditor = InvariantAuditor(mode="warn", burnrate=monitor)
    res = run_burn(3, ops=100, concurrency=8, workload="openloop",
                   rate_txn_s=30.0, check="history", observer=auditor,
                   audit="warn", **HOSTILE)
    assert res.resolved == 100
    assert res.history is not None and res.history["anomalies"] == []
    assert monitor.report()["slo_burn_events"] == 0


def test_openloop_is_deterministic():
    kw = dict(ops=60, concurrency=8, workload="openloop", rate_txn_s=40.0,
              **HOSTILE)
    a = run_burn(4, **kw)
    b = run_burn(4, **kw)
    assert a.sim_micros == b.sim_micros
    assert (a.ops_ok, a.ops_recovered, a.ops_nacked, a.ops_lost,
            a.ops_failed) == (b.ops_ok, b.ops_recovered, b.ops_nacked,
                              b.ops_lost, b.ops_failed)


def test_workload_off_stays_byte_identical():
    # workload=None leaves the classic generator untouched: the new hooks
    # must not perturb a single RNG draw on existing seeds
    from cassandra_accord_tpu.harness.trace import Trace, diff_traces
    kw = dict(ops=30, concurrency=6, chaos=True, allow_failures=True,
              durability=True, journal=True, max_tasks=3_000_000)
    ta, tb = Trace(), Trace()
    run_burn(11, tracer=ta.hook, **kw)
    run_burn(11, tracer=tb.hook, workload=None, **kw)
    assert diff_traces(ta, tb) is None


def test_parallel_seeds_cli_sweep(tmp_path, monkeypatch):
    # the process-pool sweep: 3 seeds across 2 spawn workers, one cohort
    # record in the ledger, per-seed entries in --json
    ledger = tmp_path / "history.jsonl"
    out = tmp_path / "sweep.json"
    monkeypatch.setenv("ACCORD_BENCH_HISTORY", str(ledger))
    burn_main(["--seeds", "0:2", "--ops", "20", "--concurrency", "6",
               "--parallel-seeds", "2", "--check", "history",
               "--json", str(out)])
    doc = json.loads(out.read_text())
    assert len(doc["results"]) == 3
    assert all(r["status"] == "pass" for r in doc["results"])
    assert all(r["history"]["ops"] >= 1 for r in doc["results"])
    records = [json.loads(l) for l in ledger.read_text().splitlines()]
    cohort = [r for r in records if r["kind"] == "burn_sweep"]
    assert len(cohort) == 1
    assert cohort[0]["seeds"] == [0, 1, 2]
    assert cohort[0]["passed"] == 3 and cohort[0]["failed"] == 0
    assert cohort[0]["workers"] == 2


def test_openloop_cli_ledgers_workload_slo(tmp_path, monkeypatch):
    ledger = tmp_path / "history.jsonl"
    monkeypatch.setenv("ACCORD_BENCH_HISTORY", str(ledger))
    burn_main(["--seeds", "0", "--ops", "40", "--workload", "openloop",
               "--rate", "30", "--burnrate", "--check", "history"])
    records = [json.loads(l) for l in ledger.read_text().splitlines()]
    slo = [r for r in records if r["kind"] == "workload_slo"]
    assert len(slo) == 1
    assert slo[0]["workload"] == "openloop"
    assert slo[0]["rate_txn_s"] == 30.0
    assert slo[0]["sustained"] is True
    assert slo[0]["slo_burn_events"] == 0


def test_barrier_to_overloaded_coordinator_resolves_lost():
    # ISSUE-17 satellite: an interactive barrier submitted while its
    # coordinator is overloaded must RESOLVE — either a fast Overloaded
    # CoordinationFailed or the control deadline — as ``lost``, never hang
    # the burn.  Config pins every node permanently over the high watermark
    # (hi=0, lo=-1: load >= 0 always, load <= -1 never), so every barrier
    # in the multirange mix meets an overloaded coordinator.
    from dataclasses import replace
    from cassandra_accord_tpu.config import LocalConfig
    cfg = replace(LocalConfig(), admission_enabled=True, admission_hi=0,
                  admission_lo=-1)
    w = MultiRangeWorkload()
    res = run_burn(6, ops=60, concurrency=8, workload=w, node_config=cfg,
                   **HOSTILE)
    assert res.resolved == 60                # nothing hangs
    assert w.counts.get("barrier", 0) > 0    # barriers were actually issued
    # an always-shedding cluster cannot commit barriers: they land as lost
    # (deadline or fast CoordinationFailed), and the run still quiesces
    assert res.ops_lost + res.ops_failed + res.ops_shed > 0


@pytest.mark.slow
@pytest.mark.skipif("ACCORD_LONG_BURNS" not in os.environ,
                    reason="hours-class: soak presets")
def test_zipf_soak_10k_ops():
    w = ZipfWorkload()
    res = run_burn(0, ops=10_000, concurrency=24, topology_churn=True,
                   elastic_membership=True, check="history", workload=w,
                   chaos=True, allow_failures=True, durability=True,
                   journal=True, delayed_stores=True, clock_drift=True,
                   restart_nodes=True, pause_nodes=True, disk_stall=True,
                   max_tasks=500_000_000)
    assert res.resolved == 10_000
    assert res.history is not None and res.history["anomalies"] == []


@pytest.mark.slow
@pytest.mark.skipif("ACCORD_LONG_BURNS" not in os.environ,
                    reason="hours-class: soak presets")
def test_openloop_soak_sustained():
    from cassandra_accord_tpu.observe import BurnRateMonitor, InvariantAuditor
    monitor = BurnRateMonitor()
    auditor = InvariantAuditor(mode="warn", burnrate=monitor)
    res = run_burn(1, ops=5_000, concurrency=24, workload="openloop",
                   rate_txn_s=40.0, check="history", observer=auditor,
                   audit="warn", chaos=True, allow_failures=True,
                   durability=True, journal=True, delayed_stores=True,
                   clock_drift=True, max_tasks=500_000_000)
    assert res.resolved == 5_000
    assert monitor.report()["slo_burn_events"] == 0
