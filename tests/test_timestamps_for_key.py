"""TimestampsForKey register semantics (impl/TimestampsForKey.java parity)."""
import pytest

from cassandra_accord_tpu.local.timestamps_for_key import (TimestampsForKey,
                                                           TimestampsForKeys)
from cassandra_accord_tpu.primitives.timestamp import (Domain, Timestamp,
                                                       TxnId, TxnKind)


def ts(hlc, node=1, epoch=1):
    return Timestamp(epoch=epoch, hlc=hlc, node=node)


class TestRegisters:
    def test_write_advances_all(self):
        tfk = TimestampsForKey("k")
        assert tfk.record_execution(ts(10), True) is False
        assert tfk.last_write == ts(10)
        assert tfk.last_executed == ts(10)
        assert tfk.last_executed_hlc == 10

    def test_read_advances_executed_not_write(self):
        tfk = TimestampsForKey("k")
        tfk.record_execution(ts(10), True)
        assert tfk.record_execution(ts(20), False) is False
        assert tfk.last_write == ts(10)
        assert tfk.last_executed == ts(20)

    def test_write_below_last_write_counts_inversion(self):
        # local apply-order inversion: absorbed by the MVCC store, recorded
        # as a diagnostic (module doc rationale)
        tfk = TimestampsForKey("k")
        tfk.record_execution(ts(10), True)
        assert tfk.record_execution(ts(5), True) is True
        assert tfk.last_write == ts(10)   # no regression

    def test_read_below_registers_is_legal(self):
        tfk = TimestampsForKey("k")
        tfk.record_execution(ts(10), True)
        assert tfk.record_execution(ts(5), False) is False
        assert tfk.last_executed == ts(10)

    def test_equal_execute_at_is_idempotent(self):
        tfk = TimestampsForKey("k")
        tfk.record_execution(ts(10), True)
        hlc = tfk.last_executed_hlc
        assert tfk.record_execution(ts(10), True) is False
        assert tfk.last_executed_hlc == hlc

    def test_hlc_strictly_monotonic_on_ties(self):
        # two executions whose executeAt HLCs tie (different node ids) must
        # still produce strictly increasing register HLCs
        tfk = TimestampsForKey("k")
        tfk.record_execution(ts(10, node=1), True)
        tfk.record_execution(ts(10, node=2), True)
        assert tfk.last_executed_hlc == 11

    def test_ephemeral_fence(self):
        tfk = TimestampsForKey("k")
        tfk.record_ephemeral_read(ts(15))
        assert tfk.last_ephemeral_read == ts(15)
        assert tfk.last_executed == ts(15)
        # a write below the served snapshot missed it: the enforced invariant
        assert tfk.violates_ephemeral_fence(ts(10), True)
        assert not tfk.violates_ephemeral_fence(ts(20), True)
        assert not tfk.violates_ephemeral_fence(ts(10), False)

    def test_without_redundant(self):
        tfk = TimestampsForKey("k")
        tfk.record_execution(ts(10), True)
        tfk.record_ephemeral_read(ts(12))
        assert not tfk.without_redundant(ts(5))
        assert tfk.last_write == ts(10)
        assert tfk.without_redundant(ts(50))
        assert tfk.last_write is None and tfk.last_executed is None
        assert tfk.last_ephemeral_read is None


class TestRegistry:
    def test_get_or_create_and_gc(self):
        reg = TimestampsForKeys()
        reg.merge_applied_write("a", ts(10))
        reg.merge_applied_write("b", ts(100))
        assert len(reg) == 2
        reg.remove_redundant(ts(50))
        assert len(reg) == 1
        assert reg.get_if_present("a") is None
        assert reg.get_if_present("b").last_write == ts(100)


class TestClusterConsistency:
    """The registers on a live cluster: after quiescence every key's
    last_write equals the max executeAt among writes applied to it, and an
    ephemeral read advances last_executed but not last_write."""

    def _cluster(self):
        from cassandra_accord_tpu.harness.cluster import Cluster
        from cassandra_accord_tpu.primitives.keys import IntKey, Range
        from cassandra_accord_tpu.topology.topology import Shard, Topology
        return Cluster(Topology(
            1, [Shard(Range(IntKey(0), IntKey(1000)), [1, 2, 3])]), seed=5)

    def test_registers_match_data_plane(self):
        from cassandra_accord_tpu.impl.list_store import list_txn
        from cassandra_accord_tpu.primitives.keys import IntKey
        cluster = self._cluster()
        results = [cluster.nodes[1 + (i % 3)].coordinate(
            list_txn([IntKey(7)], {IntKey(7): f"v{i}"})) for i in range(6)]
        assert cluster.run_until(lambda: all(r.is_done() for r in results))
        cluster.run_until_idle()
        for n, node in cluster.nodes.items():
            entries = node.data_store.data.get(IntKey(7), ())
            assert entries
            max_ts = max(e[0] for e in entries)
            for cs in node.command_stores.all_stores():
                tfk = cs.timestamps_for_key.get_if_present(IntKey(7))
                if tfk is not None and tfk.last_write is not None:
                    assert tfk.last_write == max_ts, \
                        f"node {n}: register {tfk.last_write} != data {max_ts}"

    def test_ephemeral_read_advances_registers(self):
        from cassandra_accord_tpu.impl.list_store import (ephemeral_read_txn,
                                                          list_txn)
        from cassandra_accord_tpu.primitives.keys import IntKey
        cluster = self._cluster()
        w = cluster.nodes[1].coordinate(list_txn([], {IntKey(5): "a"}))
        assert cluster.run_until(w.is_done)
        cluster.run_until_idle()
        r = cluster.nodes[2].coordinate(ephemeral_read_txn([IntKey(5)]))
        assert cluster.run_until(r.is_done)
        cluster.run_until_idle()
        advanced = False
        for node in cluster.nodes.values():
            for cs in node.command_stores.all_stores():
                tfk = cs.timestamps_for_key.get_if_present(IntKey(5))
                if tfk is None or tfk.last_executed is None:
                    continue
                assert tfk.last_write is None or \
                    tfk.last_executed >= tfk.last_write
                if tfk.last_write is not None \
                        and tfk.last_executed > tfk.last_write:
                    advanced = True   # the read moved last_executed past it
        assert advanced


def test_tfk_inversions_zero_and_surfaced_in_benign_burns():
    """The MVCC-inversion diagnostic (store.tfk_inversions) is surfaced in
    every BurnResult's stats and must be exactly 0 under benign runs
    (VERDICT r04 weak-item 7: the counter was write-only)."""
    from cassandra_accord_tpu.harness.burn import run_burn
    for seed in (3, 17):
        res = run_burn(seed=seed, ops=80, concurrency=8, durability=True,
                       journal=True)
        assert "tfk_inversions" in res.stats
        assert res.stats["tfk_inversions"] == 0, \
            f"benign burn seed={seed} recorded MVCC inversions"
