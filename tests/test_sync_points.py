"""Sync points and barriers on the simulated cluster.

Parity targets: CoordinateSyncPoint inclusive/exclusive (CoordinateSyncPoint.java:58-140),
Barrier local/global (Barrier.java:56-313), WaitUntilApplied.
"""
from cassandra_accord_tpu.api.interfaces import BarrierType
from cassandra_accord_tpu.harness.cluster import Cluster
from cassandra_accord_tpu.impl.list_store import list_txn
from cassandra_accord_tpu.local.status import SaveStatus
from cassandra_accord_tpu.primitives.keys import IntKey, Keys, Range, Ranges
from cassandra_accord_tpu.primitives.sync_point import SyncPoint
from cassandra_accord_tpu.primitives.timestamp import TxnKind
from cassandra_accord_tpu.topology.topology import Shard, Topology


def k(v):
    return IntKey(v)


def make_cluster(seed=1, nodes=(1, 2, 3), shards=None, **kw):
    if shards is None:
        shards = [Shard(Range(k(0), k(1000)), list(nodes))]
    return Cluster(Topology(1, shards), seed=seed, **kw)


def submit_write(cluster, node_id, appends):
    txn = list_txn([], {k(key): v for key, v in appends.items()})
    return cluster.nodes[node_id].coordinate(txn)


def all_ranges():
    return Ranges.of(Range(k(0), k(1000)))


def test_inclusive_sync_point_blocking_waits_for_deps():
    cluster = make_cluster()
    w = submit_write(cluster, 1, {5: "a"})
    res = cluster.nodes[2].sync_point(all_ranges(), blocking=True)
    assert cluster.run_until(res.is_done)
    sp = res.value
    assert isinstance(sp, SyncPoint)
    assert sp.txn_id.kind is TxnKind.SYNC_POINT
    # the write it syncs over must have been applied at a quorum: on this
    # cluster the write is also resolved
    assert w.is_done()
    cluster.run_until_idle()
    # the sync point itself applied on replicas
    for n in cluster.nodes:
        node = cluster.nodes[n]
        found = False
        for store in node.command_stores.all_stores():
            cmd = store.commands.get(sp.txn_id)
            if cmd is not None and cmd.save_status.ordinal >= SaveStatus.APPLIED.ordinal:
                found = True
        assert found, f"sync point not applied on node {n}"


def test_inclusive_sync_point_witnesses_prior_write():
    cluster = make_cluster(seed=5)
    w = submit_write(cluster, 1, {7: "x"})
    assert cluster.run_until(w.is_done)
    res = cluster.nodes[3].sync_point(all_ranges(), blocking=True)
    assert cluster.run_until(res.is_done)
    # deps of the sync point must include the applied write's txn id (it is a
    # conflicting earlier txn on a covered key)
    dep_ids = set(res.value.deps.txn_ids())
    assert any(t.kind is TxnKind.WRITE for t in dep_ids), dep_ids


def test_exclusive_sync_point():
    cluster = make_cluster(seed=9)
    submit_write(cluster, 1, {3: "z"})
    fired = []
    cluster.nodes[2].add_exclusive_sync_point_listener(
        lambda txn_id, ranges: fired.append((txn_id, ranges)))
    res = cluster.nodes[2].sync_point(all_ranges(), exclusive=True)
    assert cluster.run_until(res.is_done)
    assert res.value.txn_id.kind is TxnKind.EXCLUSIVE_SYNC_POINT
    cluster.run_until_idle()
    assert fired and fired[0][0] == res.value.txn_id


def test_exclusive_sync_point_witnesses_all_earlier_txns():
    """Witness-matrix parity (Txn.java:221-262): ExclusiveSyncPoint witnesses
    AnyGloballyVisible — both earlier reads and earlier writes appear in its
    deps.  (A later Write does NOT witness the XSP: Write witnesses RsOrWs.)"""
    cluster = make_cluster(seed=13)
    w = submit_write(cluster, 1, {500: "pre"})
    assert cluster.run_until(w.is_done)
    r = cluster.nodes[2].coordinate(list_txn([k(600)], {}))
    assert cluster.run_until(r.is_done)
    cluster.run_until_idle()
    res = cluster.nodes[1].sync_point(all_ranges(), exclusive=True)
    assert cluster.run_until(res.is_done)
    dep_kinds = {t.kind for t in res.value.deps.txn_ids()}
    assert TxnKind.WRITE in dep_kinds, res.value.deps
    assert TxnKind.READ in dep_kinds, res.value.deps


def test_global_sync_barrier():
    cluster = make_cluster(seed=17)
    submit_write(cluster, 1, {9: "b"})
    res = cluster.nodes[2].barrier(all_ranges(), barrier_type=BarrierType.GLOBAL_SYNC)
    assert cluster.run_until(res.is_done)
    assert isinstance(res.value, SyncPoint)


def test_global_async_barrier_resolves_before_applies_finish():
    cluster = make_cluster(seed=19)
    res = cluster.nodes[1].barrier(all_ranges(), barrier_type=BarrierType.GLOBAL_ASYNC)
    assert cluster.run_until(res.is_done)
    assert isinstance(res.value, SyncPoint)
    cluster.run_until_idle()


def test_local_barrier_fast_path_uses_existing_applied_txn():
    cluster = make_cluster(seed=23)
    w = submit_write(cluster, 1, {11: "c"})
    assert cluster.run_until(w.is_done)
    cluster.run_until_idle()
    # barrier over just the written key: the applied write covers it
    res = cluster.nodes[1].barrier(Keys.of([k(11)]), min_epoch=1,
                                   barrier_type=BarrierType.LOCAL)
    assert cluster.run_until(res.is_done)
    assert res.value is not None


def test_local_barrier_slow_path_coordinates_sync_point():
    cluster = make_cluster(seed=29)
    res = cluster.nodes[2].barrier(Keys.of([k(77)]), min_epoch=1,
                                   barrier_type=BarrierType.LOCAL)
    assert cluster.run_until(res.is_done)
    assert isinstance(res.value, SyncPoint)


def test_wait_until_applied_message():
    from cassandra_accord_tpu.messages.txn_messages import (ApplyOk,
                                                            WaitUntilApplied)
    cluster = make_cluster(seed=31)
    w = submit_write(cluster, 1, {13: "d"})
    assert cluster.run_until(w.is_done)
    cluster.run_until_idle()
    # find the applied write's id + route on node 2
    node = cluster.nodes[2]
    target = None
    for store in node.command_stores.all_stores():
        for txn_id, cmd in store.commands.items():
            if txn_id.kind is TxnKind.WRITE and cmd.route is not None:
                target = (txn_id, cmd.route)
    assert target is not None
    txn_id, route = target
    replies = []

    class _Cb:
        def on_success(self, from_node, reply):
            replies.append(reply)

        def on_failure(self, from_node, failure):
            replies.append(failure)

    cluster.nodes[1].send(2, WaitUntilApplied(txn_id, route, 1), _Cb())
    assert cluster.run_until(lambda: bool(replies))
    assert isinstance(replies[0], ApplyOk), replies
