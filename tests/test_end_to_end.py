"""End-to-end transaction pipeline on the simulated cluster.

Parity target: the reference's minimum slice (SURVEY.md §7): PreAccept -> fast/slow
path -> Stable -> Execute -> Apply on a 3-node cluster with the list-append model.
"""
import pytest

from cassandra_accord_tpu.harness.cluster import Cluster, LinkConfig
from cassandra_accord_tpu.impl.list_store import ListResult, list_txn
from cassandra_accord_tpu.primitives.keys import IntKey, Range
from cassandra_accord_tpu.topology.topology import Shard, Topology


def k(v):
    return IntKey(v)


def make_cluster(seed=1, nodes=(1, 2, 3), shards=None, **kw):
    if shards is None:
        shards = [Shard(Range(k(0), k(1000)), list(nodes))]
    return Cluster(Topology(1, shards), seed=seed, **kw)


def submit(cluster, node_id, reads, appends):
    """Coordinate a txn; returns the settable result."""
    txn = list_txn([k(x) for x in reads], {k(key): v for key, v in appends.items()})
    return cluster.nodes[node_id].coordinate(txn)


def test_single_write_txn_commits():
    cluster = make_cluster()
    res = submit(cluster, 1, [], {5: "a"})
    assert cluster.run_until(res.is_done)
    assert isinstance(res.value, ListResult)
    cluster.run_until_idle()
    # writes applied on every replica
    for n in cluster.nodes:
        assert cluster.stores[n].get(k(5)) == ("a",)


def test_read_sees_prior_write():
    cluster = make_cluster()
    r1 = submit(cluster, 1, [], {5: "a"})
    assert cluster.run_until(r1.is_done)
    r2 = submit(cluster, 2, [5], {})
    assert cluster.run_until(r2.is_done)
    assert r2.value.reads[k(5)] == ("a",)


def test_writes_to_same_key_are_ordered():
    cluster = make_cluster()
    results = [submit(cluster, 1 + (i % 3), [], {7: f"v{i}"}) for i in range(9)]
    assert cluster.run_until(lambda: all(r.is_done() for r in results))
    cluster.run_until_idle()
    lists = [cluster.stores[n].get(k(7)) for n in cluster.nodes]
    # all replicas converge to the same order containing all 9 values
    assert all(sorted(l) == sorted([f"v{i}" for i in range(9)]) for l in lists), lists
    assert len({l for l in lists}) == 1, f"replicas diverged: {lists}"


def test_concurrent_conflicting_writers_from_all_nodes():
    cluster = make_cluster(seed=7)
    results = []
    for i in range(12):
        results.append(submit(cluster, 1 + (i % 3), [3] if i % 2 else [], {3: i}))
    assert cluster.run_until(lambda: all(r.is_done() for r in results))
    cluster.run_until_idle()
    lists = [cluster.stores[n].get(k(3)) for n in cluster.nodes]
    assert len({l for l in lists}) == 1, f"replicas diverged: {lists}"
    assert sorted(lists[0]) == sorted(range(12))


def test_multi_key_txn_across_shards():
    shards = [Shard(Range(k(0), k(100)), [1, 2, 3]),
              Shard(Range(k(100), k(200)), [1, 2, 3])]
    cluster = make_cluster(shards=shards)
    res = submit(cluster, 1, [], {50: "x", 150: "y"})
    assert cluster.run_until(res.is_done)
    cluster.run_until_idle()
    for n in cluster.nodes:
        assert cluster.stores[n].get(k(50)) == ("x",)
        assert cluster.stores[n].get(k(150)) == ("y",)


def test_read_your_writes_across_coordinators():
    cluster = make_cluster(seed=3)
    for i in range(5):
        r = submit(cluster, 1 + (i % 3), [], {9: i})
        assert cluster.run_until(r.is_done)
    r = submit(cluster, 3, [9], {})
    assert cluster.run_until(r.is_done)
    assert sorted(r.value.reads[k(9)]) == [0, 1, 2, 3, 4]
    # order of the read list equals the replicas' applied order
    cluster.run_until_idle()
    assert r.value.reads[k(9)] == cluster.stores[1].get(k(9))


def test_message_stats_recorded():
    cluster = make_cluster()
    res = submit(cluster, 1, [], {5: "a"})
    cluster.run_until(res.is_done)
    cluster.run_until_idle()
    assert cluster.stats.get("PreAccept", 0) >= 3
    assert cluster.stats.get("Commit", 0) >= 3
    assert cluster.stats.get("Apply", 0) >= 3


def test_determinism_same_seed_same_stats():
    def run(seed):
        cluster = make_cluster(seed=seed)
        results = [submit(cluster, 1 + (i % 3), [2], {2: i}) for i in range(6)]
        cluster.run_until(lambda: all(r.is_done() for r in results))
        cluster.run_until_idle()
        return (dict(cluster.stats), cluster.now_micros,
                tuple(cluster.stores[1].get(k(2))))

    a, b = run(42), run(42)
    assert a == b
    c = run(43)
    assert a[1] != c[1] or a[0] != c[0]  # different seed -> different schedule


def test_txn_on_disjoint_shard_topology_does_not_hang():
    """Regression: trackers must only track shards intersecting the route."""
    shards = [Shard(Range(k(0), k(100)), [1, 2, 3]),
              Shard(Range(k(100), k(200)), [4, 5, 6])]
    cluster = make_cluster(nodes=(1, 2, 3, 4, 5, 6), shards=shards)
    res = submit(cluster, 1, [], {5: "a"})  # touches only shard A
    assert cluster.run_until(res.is_done)
    assert isinstance(res.value, ListResult)
    cluster.run_until_idle()
    for n in (1, 2, 3):
        assert cluster.stores[n].get(k(5)) == ("a",)
    for n in (4, 5, 6):
        assert cluster.stores[n].get(k(5)) == ()
