"""Test configuration: force JAX onto a virtual 8-device CPU platform so
multi-chip sharding tests run without TPU hardware (the driver separately
dry-run-compiles the multi-chip path via __graft_entry__.dryrun_multichip)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# Under the axon TPU tunnel the env var is pre-empted (jax_platforms is forced
# to "axon,cpu"); the config update below reliably pins tests to the virtual
# 8-device CPU platform regardless.
jax.config.update("jax_platforms", "cpu")

import time  # noqa: E402

import pytest  # noqa: E402


def pytest_configure(config):
    # session start stamp for the tier-1 wall-clock budget guard
    # (tests/test_zz_budget_guard.py): the verify pipeline runs the default
    # selection under a hard `timeout 870`; the guard test — collected LAST
    # under -p no:randomly (alphabetical file order) — asserts the suite
    # finished with margin, so a creeping selection fails LOUDLY as a test
    # instead of silently as a timeout kill.  Stored on the pytest config:
    # importing conftest as a module from a test binds a SECOND module
    # instance (tests/ is not a package) with its own stamp.
    config._accord_session_t0 = time.monotonic()
    # the tier-1 selection runs `-m 'not slow'`: hours-class burns (the
    # ACCORD_LONG_BURNS acceptance matrices, soak presets) carry this mark
    config.addinivalue_line(
        "markers", "slow: hours-class burns excluded from the tier-1 run")


@pytest.fixture
def rng():
    from cassandra_accord_tpu.utils.random import RandomSource
    return RandomSource(12345)
