"""Test configuration: force JAX onto a virtual 8-device CPU platform so
multi-chip sharding tests run without TPU hardware (the driver separately
dry-run-compiles the multi-chip path via __graft_entry__.dryrun_multichip)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture
def rng():
    from cassandra_accord_tpu.utils.random import RandomSource
    return RandomSource(12345)
