"""Content coverage for ``harness/watchdog.dump_wait_state``: the dump names
every blocked txn id (up to the per-store bound), respects
``_MAX_BLOCKED_PER_STORE``, and — with a flight recorder attached — includes
the metrics-registry snapshot section."""
import json

from cassandra_accord_tpu.harness.cluster import Cluster, LinkConfig
from cassandra_accord_tpu.harness.watchdog import (_MAX_BLOCKED_PER_STORE,
                                                   dump_wait_state)
from cassandra_accord_tpu.impl.list_store import list_txn
from cassandra_accord_tpu.observe import FlightRecorder
from cassandra_accord_tpu.primitives.keys import IntKey, Range
from cassandra_accord_tpu.topology.topology import Shard, Topology
from cassandra_accord_tpu.utils.random import RandomSource


class _DropApplyTo(LinkConfig):
    """Swallow every Apply addressed to ``victim``: its replicas never apply,
    so later same-key txns pile up STABLE/PRE_APPLIED waiting on them."""

    def __init__(self, rng, victim):
        super().__init__(rng)
        self.victim = victim

    def action(self, from_node, to_node, message=None):
        if to_node == self.victim and type(message).__name__ == "Apply":
            return LinkConfig.DROP
        return LinkConfig.DELIVER


def _backlogged_cluster(n_txns, observer=None):
    shards = [Shard(Range(IntKey(0), IntKey(1000)), [1, 2, 3])]
    cluster = Cluster(Topology(1, shards), seed=6,
                      link_config=_DropApplyTo(RandomSource(13), 3),
                      journal=True, progress_log=False, observer=observer)
    for i in range(n_txns):
        r = cluster.nodes[1].coordinate(list_txn([], {IntKey(7): f"v{i}"}))
        assert cluster.run_until(r.is_done)
    cluster.run_until_idle()
    blocked = [
        (txn_id, cmd)
        for store in cluster.nodes[3].command_stores.all_stores()
        for txn_id, cmd in store.commands.items()
        if cmd.waiting_on is not None and cmd.waiting_on.is_waiting()]
    assert blocked, "fixture failed to produce blocked txns on node 3"
    return cluster, blocked


def test_dump_names_blocked_ids_and_their_deps():
    cluster, blocked = _backlogged_cluster(4)
    dump = dump_wait_state(cluster)
    assert "BLOCKED" in dump
    for txn_id, cmd in blocked:
        assert str(txn_id) in dump
        for dep in cmd.waiting_on.waiting:
            assert str(dep) in dump
    assert "frontier=" in dump


def test_dump_respects_max_blocked_per_store_bound():
    """More blocked txns than the bound: exactly _MAX_BLOCKED_PER_STORE
    BLOCKED lines for that store (oldest first) plus a '... N more' line
    accounting for the rest."""
    n = _MAX_BLOCKED_PER_STORE + 6
    cluster, blocked = _backlogged_cluster(n + 1)   # txn 1 is the unblocked root
    assert len(blocked) > _MAX_BLOCKED_PER_STORE
    dump = dump_wait_state(cluster)
    blocked_lines = [l for l in dump.splitlines()
                     if l.lstrip().startswith("BLOCKED")]
    assert len(blocked_lines) == _MAX_BLOCKED_PER_STORE
    overflow = len(blocked) - _MAX_BLOCKED_PER_STORE
    assert f"... {overflow} more blocked txns" in dump
    # the listed ids are the OLDEST blocked (the stall root end of the graph)
    oldest = sorted(txn_id for txn_id, _cmd in blocked)[:_MAX_BLOCKED_PER_STORE]
    for txn_id in oldest:
        assert str(txn_id) in dump


def test_dump_includes_metrics_snapshot_with_flight_recorder():
    rec = FlightRecorder()
    cluster, blocked = _backlogged_cluster(4, observer=rec)
    dump = dump_wait_state(cluster)
    metrics_lines = [l for l in dump.splitlines() if l.startswith("metrics: ")]
    assert len(metrics_lines) == 1, "metrics snapshot section missing"
    snap = json.loads(metrics_lines[0][len("metrics: "):])
    # the registry really rode along: lifecycle counters + pulled store gauges
    assert snap["cluster"]["txn.save_status.pre_accepted"] >= 4
    assert any(scope.startswith("store/") for scope in snap)


def test_dump_has_no_metrics_section_without_recorder():
    cluster, _blocked = _backlogged_cluster(3)
    dump = dump_wait_state(cluster)
    assert "metrics: " not in dump
