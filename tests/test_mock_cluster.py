"""Per-phase coordinator tests on the controllable-reply MockCluster.

Parity target: accord/coordinate/CoordinateTransactionTest.java:1-438 with
impl/mock/MockCluster — hand-crafted reply sequences driving the coordinator
into states that are hard to reach organically (preemption mid-phase, lost
rounds, stale status evidence).
"""
import pytest

from cassandra_accord_tpu.coordinate.errors import (CoordinationFailed,
                                                    Exhausted, Preempted,
                                                    Timeout as CoordTimeout)
from cassandra_accord_tpu.harness.mock import MockCluster
from cassandra_accord_tpu.impl.list_store import ListResult
from cassandra_accord_tpu.messages.txn_messages import (AcceptNack,
                                                        PreAcceptOk)
from cassandra_accord_tpu.primitives.keys import IntKey
from cassandra_accord_tpu.primitives.timestamp import Ballot, Timestamp


def _result(res):
    out = {}
    res.add_listener(lambda v, f: out.update(v=v, f=f))
    return out


def test_mock_happy_path():
    mc = MockCluster()
    out = _result(mc.coordinate(1, mc.write_txn({IntKey(5): "a"})))
    assert mc.run_until(lambda: out)
    assert out["f"] is None and isinstance(out["v"], ListResult)


def test_mock_release_delivers_normally():
    mc = MockCluster()
    ic = mc.intercept("PreAccept", count=1)
    out = _result(mc.coordinate(1, mc.write_txn({IntKey(5): "a"})))
    held = mc.await_held(ic, 1)
    assert held[0].to_node in (1, 2, 3)
    held[0].release()
    assert mc.run_until(lambda: out)
    assert out["f"] is None


def test_slow_path_preempted_mid_accept():
    """A crafted PreAcceptOk with a LATER witnessed timestamp forces the slow
    path; an AcceptNack naming a higher ballot then preempts the Accept round
    (CoordinateTransactionTest preemption coverage)."""
    mc = MockCluster()
    pre_ic = mc.intercept("PreAcceptOk", count=0)  # placeholder (requests only)
    ic = mc.intercept("PreAccept", to_node=2, count=1)
    out = _result(mc.coordinate(1, mc.write_txn({IntKey(5): "a"})))
    held = mc.await_held(ic, 1)
    req = held[0].request
    # conflict evidence: witnessed at a later timestamp than txnId
    later = Timestamp(req.txn_id.epoch, req.txn_id.hlc + 999, 2)
    from cassandra_accord_tpu.primitives.deps import Deps
    held[0].reply(PreAcceptOk(req.txn_id, later, Deps.NONE))
    # slow path now runs Accept: nack it with a higher ballot
    acc_ic = mc.intercept("Accept", to_node=3, count=1)
    acc = mc.await_held(acc_ic, 1)
    high = Ballot(req.txn_id.epoch, req.txn_id.hlc + 10_000, 9)
    acc[0].reply(AcceptNack(req.txn_id, high))
    assert mc.run_until(lambda: out)
    assert isinstance(out["f"], Preempted)


def test_lost_stable_round_exhausts():
    """Dropping every Stable/Commit request starves the stabilise quorum; the
    coordinator reports the coordination failed rather than hanging (the
    reply-timeout plane drives it)."""
    mc = MockCluster()
    ic = mc.intercept("Commit", count=1_000_000)
    out = _result(mc.coordinate(1, mc.write_txn({IntKey(5): "a"})))
    # hold (and drop) every commit; reply-timeouts fire at ~2s sim
    mc.run_until(lambda: len(ic.held) >= 3)
    for h in list(ic.held):
        if not h.done:
            h.drop()
    assert mc.run_until(lambda: out, sim_limit_s=30.0)
    assert isinstance(out["f"], CoordinationFailed)


def test_routeless_blocked_txn_discovers_route_and_settles():
    """A node that learns a txnId WITHOUT its route (InformOfTxnId-class
    knowledge) discovers the route via FindSomeRoute and drives the txn
    terminal (RecoverWithSomeRoute capability, RecoverWithRoute.java:1-242)."""
    from cassandra_accord_tpu.local.status import SaveStatus, Status

    mc = MockCluster(progress_log=True)
    # a txn that reaches PreAccepted on SOME nodes but whose coordinator dies
    # (every Accept/Commit swallowed -> no progress); key 5's replicas all know
    # the route, the blocked observer does not
    ic_acc = mc.intercept("Accept", count=10**6)
    ic_cmt = mc.intercept("Commit", count=10**6)
    out = _result(mc.coordinate(1, mc.write_txn({IntKey(5): "x"})))
    mc.run_for(0.2)
    # find the txn id that got preaccepted
    node2 = mc.node(2)
    store2 = node2.command_stores.all_stores()[0]
    pre = [tid for tid, cmd in store2.commands.items()
           if cmd.route is not None]
    assert pre, "txn never preaccepted anywhere"
    tid = pre[0]
    # node 3 learns the id ONLY (no route): blocked-dependency monitoring
    node3 = mc.node(3)
    store3 = node3.command_stores.all_stores()[0]
    store3.progress_log.waiting(tid, None, None, None)
    # the dead coordinator stays dead, but recovery's own rounds must flow
    ic_acc.remaining = 0
    ic_cmt.remaining = 0
    # discovery + escalation drive it to a terminal state cluster-wide
    def terminal():
        cmd = store3.lookup(tid)
        return cmd is not None and (
            cmd.save_status.ordinal >= SaveStatus.APPLIED.ordinal
            or cmd.save_status is SaveStatus.INVALIDATED
            or cmd.save_status.is_truncated)
    assert mc.run_until(terminal, sim_limit_s=60.0), \
        f"blocked routeless txn never settled: {store3.lookup(tid)!r}"


def test_stale_check_status_escalates_to_invalidation():
    """A txn witnessed nowhere: maybe_recover's CheckStatus probes get empty
    (stale) evidence from a quorum, the definition is unrecoverable, and the
    blocked txn is invalidated so nothing waits on it forever."""
    from cassandra_accord_tpu.coordinate.maybe_recover import (ProgressToken,
                                                               maybe_recover)
    from cassandra_accord_tpu.primitives.route import Route
    from cassandra_accord_tpu.primitives.timestamp import Domain, TxnId, TxnKind

    from cassandra_accord_tpu.primitives.keys import RoutingKeys
    mc = MockCluster()
    node = mc.node(1)
    ghost = node.next_txn_id(TxnKind.WRITE, Domain.KEY)
    rk = IntKey(5).to_routing() if hasattr(IntKey(5), "to_routing") else IntKey(5)
    route = Route.for_keys(rk, RoutingKeys.of([rk]))
    # a zero prev-token: the first probe's identical evidence is NOT progress,
    # so the probe escalates immediately instead of standing down one cycle
    out = _result(maybe_recover(node, ghost, route, ProgressToken()))
    assert mc.run_until(lambda: out, sim_limit_s=30.0)
    # durably invalidated: settled, nothing can block on it
    assert out["f"] is None
    assert out["v"].settled


def test_slow_read_speculates_second_replica():
    """Slow-replica read speculation (ReadTracker.java; VERDICT r04 item 3):
    holding the Commit+read at the preferred replica (slow, NOT failed) must
    trigger a speculative read to another replica within the slow threshold,
    and the txn completes without the held reply ever arriving."""
    mc = MockCluster()
    # the coordinator fuses the data read with the Commit(Stable) to ONE
    # preferred replica (coordinator-local: node 1); hold that request
    ic = mc.intercept("Commit", to_node=1, count=1)
    out = _result(mc.coordinate(1, mc.write_txn({IntKey(5): "a"})))
    held = mc.await_held(ic, 1)
    assert held[0].request.read, "expected the fused Stable+Read"
    # observe the speculative read (a fresh Commit+read) reaching a
    # DIFFERENT replica — the initial broadcast already delivered, so any
    # further Commit carrying a read is the speculation
    spec = mc.intercept("Commit", count=100)
    assert mc.run_until(
        lambda: any(h.request.read and h.to_node != 1 for h in spec.held),
        sim_limit_s=5.0), "no speculative second read within the slow threshold"
    for h in list(spec.held):
        if not h.done:
            h.release()
    # txn completes off the speculative read; the held copy stays held
    assert mc.run_until(lambda: out, sim_limit_s=10.0)
    assert out["f"] is None and isinstance(out["v"], ListResult)
