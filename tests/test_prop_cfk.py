"""Property suite for CommandsForKey, modeled on the reference's
CommandsForKeyTest (CommandsForKeyTest.java:1-1103): randomized lifecycle
sequences checked against a NAIVE re-implementation of the query semantics —
including transitive elision — plus prune-guard invariants.
"""
from cassandra_accord_tpu.local.cfk import CommandsForKey, InternalStatus
from cassandra_accord_tpu.primitives.keys import IntKey
from cassandra_accord_tpu.primitives.timestamp import (Domain, Timestamp,
                                                       TxnId, TxnKind)
from cassandra_accord_tpu.utils import property as prop
from cassandra_accord_tpu.utils import accord_gens as gens

_DECIDED = (InternalStatus.COMMITTED, InternalStatus.STABLE,
            InternalStatus.APPLIED)

# one lifecycle event: (hlc, node, kind, final_status, ea_delta)
_EVENTS = prop.lists(
    prop.tuples(prop.ints(0, 200), prop.ints(1, 4),
                prop.pick([TxnKind.WRITE, TxnKind.WRITE, TxnKind.READ]),
                prop.pick([InternalStatus.PREACCEPTED, InternalStatus.ACCEPTED,
                           InternalStatus.COMMITTED, InternalStatus.STABLE,
                           InternalStatus.APPLIED, InternalStatus.INVALIDATED]),
                prop.ints(0, 30)),
    max_size=24)


def _play(events):
    """Drive a cfk through the lifecycle events; return (cfk, model) where
    model is [(txn_id, status, execute_at)] of indexed entries."""
    cfk = CommandsForKey(IntKey(0).to_routing())
    model = {}
    for hlc, node, kind, status, ea_delta in events:
        tid = TxnId(1, hlc, node, kind, Domain.KEY)
        ea = Timestamp(1, hlc + ea_delta, node) if ea_delta else None
        # walk the lattice up to `status` the way the protocol would
        path = [s for s in (InternalStatus.PREACCEPTED, InternalStatus.ACCEPTED,
                            InternalStatus.COMMITTED, InternalStatus.STABLE,
                            InternalStatus.APPLIED)
                if s <= status] if status is not InternalStatus.INVALIDATED \
            else [InternalStatus.PREACCEPTED, InternalStatus.INVALIDATED]
        for s in path:
            got_ea = ea if s >= InternalStatus.ACCEPTED else None
            if cfk.update(tid, s, got_ea):
                info = cfk.get(tid)
                model[tid] = (info.status, info.execute_at)
    return cfk, model


def _naive_active(model, before, by_kind, durable_majority=None):
    """The reference mapReduceActive semantics recomputed from scratch:
    witness filter, invalidated/TK skip, and transitive elision below BOTH
    the max committed WRITE executing before the bound
    (CommandsForKey.java:925-986) AND the majority-durable watermark (the
    soundness gate, cfk.map_reduce_active doc)."""
    maxcw = None
    for tid, (status, ea) in model.items():
        if status in _DECIDED and tid.is_write and ea < before:
            if maxcw is None or ea > maxcw:
                maxcw = ea
    out = set()
    for tid, (status, ea) in model.items():
        if not tid < before:
            continue
        if status in (InternalStatus.INVALIDATED,
                      InternalStatus.TRANSITIVELY_KNOWN):
            continue
        if not by_kind.witnesses(tid.kind):
            continue
        if maxcw is not None and status in _DECIDED \
                and durable_majority is not None and tid < durable_majority \
                and ea < maxcw and TxnKind.WRITE.witnesses(tid.kind):
            continue
        out.add(tid)
    return out


@prop.for_all(_EVENTS, prop.ints(0, 250),
              prop.pick([TxnKind.WRITE, TxnKind.READ]),
              prop.ints(0, 300), tries=3000)
def test_map_reduce_active_matches_naive(events, before_hlc, by_kind, dur_hlc):
    cfk, model = _play(events)
    before = Timestamp(1, before_hlc, 5)
    by = TxnId(1, before_hlc, 5, by_kind, Domain.KEY)
    # durability gate: absent for a third of cases, else a generated bound
    bound = None if dur_hlc % 3 == 0 else TxnId(1, dur_hlc, 9)
    got = set()
    cfk.map_reduce_active(before, by.witnesses, got.add,
                          durable_majority=bound)
    assert got == _naive_active(model, before, by_kind, bound)


@prop.for_all(_EVENTS, tries=3000)
def test_max_timestamp_matches_naive(events):
    cfk, model = _play(events)
    expect = None
    for tid, (_status, ea) in model.items():
        c = ea if ea > tid else tid
        if expect is None or c > expect:
            expect = c
    assert cfk.max_timestamp() == expect


@prop.for_all(_EVENTS, prop.ints(0, 250), tries=3000)
def test_prune_guard_and_requery(events, bound_hlc):
    """After a bound prune: pruned ids refuse resurrection (update returns
    False), survivors still answer queries per the naive semantics."""
    cfk, model = _play(events)
    bound = TxnId(1, bound_hlc, 9)
    pruned = set(cfk.prune_applied_before(bound))
    for tid in pruned:
        assert model[tid][0] in (InternalStatus.APPLIED,
                                 InternalStatus.INVALIDATED)
        assert tid < bound
        assert not cfk.update(tid, InternalStatus.PREACCEPTED, None), \
            "pruned entry must not resurrect"
        del model[tid]
    before = Timestamp(1, 300, 9)
    by = TxnId(1, 300, 9, TxnKind.WRITE, Domain.KEY)
    bound = TxnId(1, 280, 9)
    got = set()
    cfk.map_reduce_active(before, by.witnesses, got.add,
                          durable_majority=bound)
    assert got == _naive_active(model, before, by.kind, bound)


@prop.for_all(_EVENTS, tries=2000)
def test_status_monotone_and_execute_at_final(events):
    """Status never regresses; executeAt is immutable from COMMITTED on."""
    cfk = CommandsForKey(IntKey(0).to_routing())
    seen = {}
    for hlc, node, kind, status, ea_delta in events:
        tid = TxnId(1, hlc, node, kind, Domain.KEY)
        ea = Timestamp(1, hlc + ea_delta, node) if ea_delta else None
        cfk.update(tid, status, ea)
        info = cfk.get(tid)
        if info is None:
            continue
        prev = seen.get(tid)
        if prev is not None:
            assert info.status >= prev[0], "status regressed"
            if prev[0] >= InternalStatus.COMMITTED:
                assert info.execute_at == prev[1], "executeAt moved post-commit"
        seen[tid] = (info.status, info.execute_at)
