"""The online protocol-invariant auditor (observe/audit.py + rules.py).

Contracts proven here:

1. ZERO OBSERVER EFFECT extends to the auditor: a same-seed hostile burn
   with ``audit="strict"`` vs no observer yields byte-identical message
   traces and identical outcomes.
2. MUTATION CHECK: deliberately-injected violations — an illegal SaveStatus
   edge, a deps mismatch between two replicas' same-ballot commits, a ballot
   regression — are each caught AT THE INJECTING EVENT.
3. LEGAL-EDGE LINT: the edge table agrees two-way with the SaveStatus enum
   (every member a source and a target of at least one legal edge).
4. The strict matrix smoke: benign and hostile burns run clean under
   ``--audit=strict`` (zero violations), and the CLI carries per-seed audit
   verdicts in ``--json``.
"""
import json

import pytest

from cassandra_accord_tpu.harness.burn import run_burn
from cassandra_accord_tpu.harness.trace import Trace, diff_traces
from cassandra_accord_tpu.local.command import Command
from cassandra_accord_tpu.local.durability import DurableBefore, RedundantBefore
from cassandra_accord_tpu.observe import AuditViolation, InvariantAuditor
from cassandra_accord_tpu.observe import rules
from cassandra_accord_tpu.primitives.deps import Deps, KeyDeps
from cassandra_accord_tpu.primitives.keys import IntKey, Range, Ranges
from cassandra_accord_tpu.primitives.timestamp import (Ballot, Domain,
                                                       Timestamp, TxnId,
                                                       TxnKind)

HOSTILE = dict(ops=40, concurrency=8, chaos=True, allow_failures=True,
               durability=True, journal=True, delayed_stores=True,
               clock_drift=True, max_tasks=3_000_000)


def tid(hlc: int, node: int = 1, kind=TxnKind.WRITE) -> TxnId:
    return TxnId(epoch=1, hlc=hlc, node=node, kind=kind, domain=Domain.KEY)


class _FakeNode:
    def __init__(self, node_id):
        self.id = node_id


class _FakeStore:
    """The slice of CommandStore the auditor reads (reads only)."""

    def __init__(self, node_id: int, store_id: int, ranges: Ranges):
        self.node = _FakeNode(node_id)
        self.id = store_id
        self._ranges = ranges
        self.commands = {}
        self.cold = set()
        self.tfk_inversions = 0
        self.durable_gen = 0
        self.redundant_before = RedundantBefore.EMPTY
        self.durable_before = DurableBefore.EMPTY

    def all_ranges(self):
        return self._ranges

    def ranges_at(self, _epoch):
        return self._ranges


# ---------------------------------------------------------------------------
# legal-edge table lint (the CI satellite)
# ---------------------------------------------------------------------------

def test_legal_edge_table_lints_two_way():
    assert rules.lint_legal_edges() == []


def test_legal_edge_lint_catches_gaps(monkeypatch):
    # removing a source row OR a member's only target edge must be caught
    broken = {k: v for k, v in rules.LEGAL_EDGES.items() if k != "APPLYING"}
    monkeypatch.setattr(rules, "LEGAL_EDGES", broken)
    problems = rules.lint_legal_edges()
    assert any("APPLYING" in p and "source" in p for p in problems)
    broken2 = dict(rules.LEGAL_EDGES)
    broken2["PRE_APPLIED"] = frozenset({"TRUNCATED_APPLY", "ERASED"})
    monkeypatch.setattr(rules, "LEGAL_EDGES", broken2)
    problems = rules.lint_legal_edges()
    assert any("APPLYING" in p and "target" in p for p in problems)


def test_edge_predicate():
    assert rules.is_legal_edge("NOT_DEFINED", "PRE_ACCEPTED")
    assert rules.is_legal_edge("STABLE", "READY_TO_EXECUTE")
    assert not rules.is_legal_edge("APPLIED", "PRE_ACCEPTED")
    assert not rules.is_legal_edge("INVALIDATED", "COMMITTED")


# ---------------------------------------------------------------------------
# mutation checks: injected violations caught at the injecting event
# ---------------------------------------------------------------------------

def test_mutation_illegal_edge_raises_at_event():
    auditor = InvariantAuditor(mode="strict")
    t = tid(100)
    auditor.on_transition(1, 0, t, "STABLE", 10)
    auditor.on_transition(1, 0, t, "READY_TO_EXECUTE", 20)
    with pytest.raises(AuditViolation) as exc:
        # regression to an earlier phase: never legal
        auditor.on_transition(1, 0, t, "PRE_ACCEPTED", 30)
    v = exc.value
    assert v.rule == rules.RULE_ILLEGAL_EDGE
    assert "READY_TO_EXECUTE -> PRE_ACCEPTED" in v.detail
    assert v.node == 1 and v.store == 0 and v.now_us == 30
    # the violation carries the txn's full flight-recorder timeline
    assert v.timeline is not None
    assert v.timeline["transitions"]["1/0"] == [
        ["STABLE", 10], ["READY_TO_EXECUTE", 20], ["PRE_ACCEPTED", 30]] or \
        v.timeline["transitions"]["1/0"] == [
        ("STABLE", 10), ("READY_TO_EXECUTE", 20), ("PRE_ACCEPTED", 30)]
    assert v.registry is not None
    # warn mode records instead of raising
    warn = InvariantAuditor(mode="warn")
    warn.on_transition(1, 0, t, "PRE_APPLIED", 10)
    warn.on_transition(1, 0, t, "PRE_ACCEPTED", 20)
    assert len(warn.violations) == 1
    assert warn.verdict()["violations"] == 1
    assert warn.verdict()["rules_violated"] == [rules.RULE_ILLEGAL_EDGE]


def test_mutation_deps_mismatch_between_replica_commits():
    """Two replicas commit the same txn at the same ballot with different
    deps over commonly-owned ranges, the differing dep live: caught at the
    second replica's commit event."""
    ranges = Ranges.of(Range(IntKey(0), IntKey(100)))
    store_a = _FakeStore(1, 0, ranges)
    store_b = _FakeStore(2, 0, ranges)
    t = tid(500)
    rk = IntKey(10).to_routing()
    dep_live = tid(400, node=2)
    deps_a = Deps(key_deps=KeyDeps.of({rk: [dep_live]}))
    deps_b = Deps(key_deps=KeyDeps.of({rk: []}))

    def committed(store, deps):
        cmd = Command(t)
        cmd.execute_at = Timestamp(1, 600, 1)
        cmd.partial_deps = deps
        cmd.accepted_or_committed = Ballot.ZERO
        return cmd

    auditor = InvariantAuditor(mode="strict")
    auditor.on_transition(1, 0, t, "COMMITTED", 10,
                          command=committed(store_a, deps_a),
                          command_store=store_a)
    with pytest.raises(AuditViolation) as exc:
        auditor.on_transition(2, 0, t, "COMMITTED", 20,
                              command=committed(store_b, deps_b),
                              command_store=store_b)
    v = exc.value
    assert v.rule == rules.RULE_DEPS_MISMATCH
    assert str(dep_live) in v.detail
    assert v.now_us == 20


def test_deps_difference_of_settled_entries_is_elision_legal():
    """The SAME mismatch is legal when the differing dep is settled (applied)
    at the store that lacks it — the universal-durability elision class."""
    from cassandra_accord_tpu.local.status import SaveStatus
    ranges = Ranges.of(Range(IntKey(0), IntKey(100)))
    store_a = _FakeStore(1, 0, ranges)
    store_b = _FakeStore(2, 0, ranges)
    t = tid(500)
    rk = IntKey(10).to_routing()
    dep = tid(400, node=2)
    # the lacking store (b) has the dep APPLIED: eliding it cannot reorder
    settled = Command(dep)
    settled.save_status = SaveStatus.APPLIED
    store_b.commands[dep] = settled
    deps_a = Deps(key_deps=KeyDeps.of({rk: [dep]}))
    deps_b = Deps(key_deps=KeyDeps.of({rk: []}))
    auditor = InvariantAuditor(mode="strict")
    for node, store, deps in ((1, store_a, deps_a), (2, store_b, deps_b)):
        cmd = Command(t)
        cmd.execute_at = Timestamp(1, 600, 1)
        cmd.partial_deps = deps
        cmd.accepted_or_committed = Ballot.ZERO
        auditor.on_transition(node, 0, t, "COMMITTED", 10, command=cmd,
                              command_store=store)
    assert auditor.violations == []
    assert auditor.registry.counter("audit.deps_elision_diffs").value == 1


def test_mutation_ballot_regression():
    auditor = InvariantAuditor(mode="strict")
    t = tid(700)
    store = _FakeStore(3, 0, Ranges.of(Range(IntKey(0), IntKey(100))))
    cmd = Command(t)
    cmd.promised = Ballot(1, 50, 3)
    auditor.on_transition(3, 0, t, "PRE_ACCEPTED", 10, command=cmd,
                          command_store=store)
    cmd2 = Command(t)
    cmd2.promised = Ballot(1, 20, 3)   # regressed below the promise
    with pytest.raises(AuditViolation) as exc:
        auditor.on_transition(3, 0, t, "ACCEPTED", 20, command=cmd2,
                              command_store=store)
    assert exc.value.rule == rules.RULE_BALLOT_REGRESSION
    assert "promised" in exc.value.detail


def test_execute_at_mismatch_and_invalidate_conflict():
    auditor = InvariantAuditor(mode="warn")
    store_a = _FakeStore(1, 0, Ranges.of(Range(IntKey(0), IntKey(100))))
    store_b = _FakeStore(2, 0, Ranges.of(Range(IntKey(0), IntKey(100))))
    t = tid(900)
    c1 = Command(t)
    c1.execute_at = Timestamp(1, 950, 1)
    auditor.on_transition(1, 0, t, "PRE_COMMITTED", 10, command=c1,
                          command_store=store_a)
    c2 = Command(t)
    c2.execute_at = Timestamp(1, 960, 1)   # different decided executeAt
    auditor.on_transition(2, 0, t, "PRE_COMMITTED", 20, command=c2,
                          command_store=store_b)
    assert [v.rule for v in auditor.violations] == \
        [rules.RULE_EXECUTE_AT_MISMATCH]
    # a decided txn observed INVALIDATED anywhere: the quarantine-bug shape
    auditor2 = InvariantAuditor(mode="warn")
    auditor2.on_transition(1, 0, t, "PRE_COMMITTED", 10, command=c1,
                           command_store=store_a)
    c3 = Command(t)
    auditor2.on_transition(2, 0, t, "INVALIDATED", 20, command=c3,
                           command_store=store_b)
    assert [v.rule for v in auditor2.violations] == \
        [rules.RULE_COMMIT_INVALIDATE_CONFLICT]


def test_execute_at_uniqueness():
    auditor = InvariantAuditor(mode="warn")
    store = _FakeStore(1, 0, Ranges.of(Range(IntKey(0), IntKey(100))))
    shared = Timestamp(1, 1000, 1)
    for i, t in enumerate((tid(900), tid(901, node=2))):
        cmd = Command(t)
        cmd.execute_at = shared
        auditor.on_transition(1, 0, t, "PRE_COMMITTED", 10 + i, command=cmd,
                              command_store=store)
    assert [v.rule for v in auditor.violations] == \
        [rules.RULE_EXECUTE_AT_DUPLICATE]


def test_crash_rebaselines_lifecycle_state():
    """A journal replay re-observes commands at their durable tier: after
    on_crash the first re-observation per txn is a baseline, not an edge."""
    auditor = InvariantAuditor(mode="strict")
    t = tid(1100)
    auditor.on_transition(4, 0, t, "PRE_APPLIED", 8)
    auditor.on_transition(4, 0, t, "APPLYING", 9)
    auditor.on_transition(4, 0, t, "APPLIED", 10)
    auditor.on_crash(4)
    # replay re-observes at a LOWER tier — legal during the replay window
    auditor.on_transition(4, 0, t, "STABLE", 20)
    auditor.on_restart(4)
    auditor.on_transition(4, 0, t, "READY_TO_EXECUTE", 30)   # live edge again
    assert auditor.violations == []
    # but an illegal live edge after restart still raises
    with pytest.raises(AuditViolation):
        auditor.on_transition(4, 0, t, "COMMITTED", 40)


def test_crash_drops_deps_records_with_volatile_state():
    """A post-restart recovery may re-stabilize with a different (legal)
    cover: the pre-crash stable-deps record must not trip deps_mutated."""
    from cassandra_accord_tpu.local.status import SaveStatus  # noqa: F401
    ranges = Ranges.of(Range(IntKey(0), IntKey(100)))
    store = _FakeStore(4, 0, ranges)
    t = tid(1200)
    rk = IntKey(10).to_routing()

    def stable_cmd(deps):
        cmd = Command(t)
        cmd.execute_at = Timestamp(1, 1250, 1)
        cmd.partial_deps = deps
        cmd.accepted_or_committed = Ballot.ZERO
        return cmd

    auditor = InvariantAuditor(mode="strict")
    auditor.on_transition(4, 0, t, "STABLE", 10,
                          command=stable_cmd(
                              Deps(key_deps=KeyDeps.of({rk: [tid(1100)]}))),
                          command_store=store)
    auditor.on_crash(4)
    # replay re-baselines; recovery then re-stabilizes with a DIFFERENT cover
    auditor.on_transition(4, 0, t, "STABLE", 20,
                          command=stable_cmd(Deps(key_deps=KeyDeps.of({rk: []}))),
                          command_store=store)
    auditor.on_restart(4)
    cmd = stable_cmd(Deps(key_deps=KeyDeps.of({rk: []})))
    auditor.on_transition(4, 0, t, "PRE_APPLIED", 30, command=cmd,
                          command_store=store)
    assert auditor.violations == []


def test_slo_unapplied_rearms_after_dormancy():
    """The SLO scan must not stay dormant past a late decision: a txn that
    decides after every pre-decision deadline passed still gets its
    unapplied deadline scheduled and flagged."""
    auditor = InvariantAuditor(mode="warn", slo_unattended_s=1.0,
                               slo_undecided_s=2.0, slo_unapplied_s=3.0)
    store = _FakeStore(1, 0, Ranges.of(Range(IntKey(0), IntKey(100))))
    t = tid(1400)
    auditor.on_submit(0, t, 1, 0)
    auditor.on_recovery(1, t, Ballot(1, 1, 1), 100)   # attempt attributed
    # sim time passes BOTH pre-decision deadlines: undecided flag opens and
    # the scan has no future deadline left (dormant)
    auditor.on_message_event("DELIVER", 1, 2, 1, object(), 2_500_000)
    assert {f["kind"] for f in auditor.open_slo_flags()} == \
        {rules.SLO_UNDECIDED}
    # the txn NOW decides: the unapplied deadline must be re-armed
    cmd = Command(t)
    cmd.execute_at = Timestamp(1, 1500, 1)
    auditor.on_transition(1, 0, t, "PRE_COMMITTED", 3_000_000, command=cmd,
                          command_store=store)
    auditor.on_message_event("DELIVER", 1, 2, 2, object(), 6_500_000)
    assert {f["kind"] for f in auditor.open_slo_flags()} == \
        {rules.SLO_UNAPPLIED}


# ---------------------------------------------------------------------------
# liveness SLO flags
# ---------------------------------------------------------------------------

def test_slo_unattended_flag_opens_and_closes():
    auditor = InvariantAuditor(mode="strict", slo_unattended_s=1.0,
                               slo_undecided_s=100.0, slo_unapplied_s=100.0)
    t = tid(1300)
    auditor.on_submit(0, t, 1, 0)
    # sim time passes the budget with no attempt: flag opens (never raises)
    auditor.on_message_event("DELIVER", 1, 2, 1, object(), 2_000_000)
    flags = auditor.open_slo_flags()
    assert len(flags) == 1 and flags[0]["kind"] == rules.SLO_UNATTENDED
    assert flags[0]["txn_id"] == str(t)
    # a recovery attempt attributed to the txn closes it
    auditor.on_recovery(2, t, Ballot(1, 1, 2), 2_500_000)
    assert auditor.open_slo_flags() == []
    hist = auditor.slo_flag_history()
    assert hist[0]["closed_because"] == "recovery attempt attributed"
    assert auditor.verdict()["slo_flags_raised"] == 1
    assert auditor.verdict()["slo_flags_open"] == 0


# ---------------------------------------------------------------------------
# the tentpole invariant: zero observer effect under strict audit
# ---------------------------------------------------------------------------

def test_zero_observer_effect_strict_audit_hostile():
    """Same-seed hostile burn, --audit=strict vs no observer: identical full
    message traces and outcomes — the auditor's checks never perturb the
    simulation."""
    ta, tb = Trace(), Trace()
    bare = run_burn(9, tracer=ta.hook, **HOSTILE)
    audited = run_burn(9, tracer=tb.hook, audit="strict", **HOSTILE)
    divergence = diff_traces(ta, tb)
    assert divergence is None, \
        f"the auditor perturbed the simulation:\n{divergence}"
    assert (bare.ops_ok, bare.ops_recovered, bare.ops_nacked, bare.ops_lost,
            bare.ops_failed, bare.sim_micros) == \
           (audited.ops_ok, audited.ops_recovered, audited.ops_nacked,
            audited.ops_lost, audited.ops_failed, audited.sim_micros)
    assert audited.audit is not None
    assert audited.audit["violations"] == 0
    assert audited.audit["events_audited"] > 0


def test_benign_burn_strict_audit_clean():
    r = run_burn(11, ops=30, concurrency=6, audit="strict")
    assert r.audit["violations"] == 0
    assert r.audit["mode"] == "strict"
    assert r.audit["slo_flags_open"] == 0


def test_audit_rejects_plain_flight_recorder():
    from cassandra_accord_tpu.observe import FlightRecorder
    with pytest.raises(ValueError, match="InvariantAuditor"):
        run_burn(11, ops=5, audit="strict", observer=FlightRecorder())
    with pytest.raises(ValueError, match="off/strict/warn"):
        run_burn(11, ops=5, audit="bogus")


# ---------------------------------------------------------------------------
# burn CLI: --audit smoke (the tier-1 CI satellite) + watchdog integration
# ---------------------------------------------------------------------------

def test_burn_cli_audit_strict_smoke(tmp_path):
    """One short burn seed under --audit=strict: passes, and the --json
    summary carries the per-seed audit verdict."""
    from cassandra_accord_tpu.harness import burn as burn_cli
    j = tmp_path / "j.json"
    burn_cli.main(["--seeds", "1", "--ops", "20", "--no-cache-miss",
                   "--audit", "strict", "--json", str(j)])
    entry = json.loads(j.read_text())["results"][0]
    assert entry["status"] == "pass"
    assert entry["audit"]["mode"] == "strict"
    assert entry["audit"]["violations"] == 0
    assert "slo_flags_open" in entry["audit"]
    json.dumps(entry["audit"])   # the verdict is JSON-clean end to end


def test_watchdog_dump_includes_audit_section():
    from cassandra_accord_tpu.harness.burn import last_cluster
    from cassandra_accord_tpu.harness.watchdog import dump_wait_state
    auditor = InvariantAuditor(mode="warn", slo_unattended_s=0.001)
    run_burn(11, ops=10, concurrency=4, observer=auditor, audit="warn")
    cluster = last_cluster()
    assert cluster is not None   # pinned by auditor.attach_cluster
    dump = dump_wait_state(cluster)
    assert "audit: " in dump
    assert "slo_flags_raised" in dump
