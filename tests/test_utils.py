"""Async chains, interval maps, RNG determinism, invariants.

Parity targets: AsyncChainsTest (:1-365), ReducingRangeMapTest, RandomTest.
"""
import pytest

from cassandra_accord_tpu.utils import async_ as au
from cassandra_accord_tpu.utils.interval_map import ReducingIntervalMap
from cassandra_accord_tpu.utils.invariants import InvariantViolation, Invariants, Paranoia
from cassandra_accord_tpu.utils.random import RandomSource


# -- async ------------------------------------------------------------------

def test_chain_map_flatmap():
    out = []
    au.done(2).map(lambda x: x * 10).flat_map(lambda x: au.done(x + 1)) \
        .begin(lambda v, f: out.append((v, f)))
    assert out == [(21, None)]


def test_chain_failure_propagates_and_recovers():
    boom = RuntimeError("boom")
    out = []
    au.failure(boom).map(lambda x: x).begin(lambda v, f: out.append(f))
    assert out == [boom]
    out2 = []
    au.failure(boom).recover(lambda e: 99).begin(lambda v, f: out2.append((v, f)))
    assert out2 == [(99, None)]


def test_chain_single_begin():
    c = au.done(1)
    c.begin(lambda v, f: None)
    with pytest.raises(RuntimeError):
        c.begin(lambda v, f: None)


def test_map_raising_fails_chain():
    out = []
    au.done(1).map(lambda x: 1 // 0).begin(lambda v, f: out.append(type(f)))
    assert out == [ZeroDivisionError]


def test_settable_result_listeners():
    s = au.settable()
    seen = []
    s.add_listener(lambda v, f: seen.append(v))
    assert not s.is_done()
    assert s.set_success(5)
    assert not s.set_success(6)  # only first completion wins
    assert seen == [5]
    # late listener fires immediately
    s.add_listener(lambda v, f: seen.append(v * 2))
    assert seen == [5, 10]
    assert s.value == 5


def test_all_of():
    out = []
    au.all_of([au.done(1), au.done(2), au.done(3)]).begin(lambda v, f: out.append(v))
    assert out == [[1, 2, 3]]
    out2 = []
    au.all_of([au.done(1), au.failure(ValueError("x"))]).begin(lambda v, f: out2.append(type(f)))
    assert out2 == [ValueError]


def test_begin_result_multi_listener():
    r = au.done(7).begin_result()
    assert r.is_success() and r.value == 7


# -- interval map -----------------------------------------------------------

def test_interval_map_lookup():
    m = ReducingIntervalMap.of_range(10, 20, "a")
    assert m.get(9) is None
    assert m.get(10) == "a"
    assert m.get(19) == "a"
    assert m.get(20) is None


def test_interval_map_merge_reduce():
    a = ReducingIntervalMap.of_range(0, 10, 1)
    b = ReducingIntervalMap.of_range(5, 15, 2)
    m = a.merge(b, max)
    assert m.get(3) == 1
    assert m.get(7) == 2
    assert m.get(12) == 2
    assert m.get(16) is None


def test_interval_map_merge_against_oracle():
    rng = RandomSource(9)
    for _ in range(60):
        def rand_map():
            m = ReducingIntervalMap.constant(None)
            for _ in range(rng.next_int(1, 5)):
                lo = rng.next_int(0, 40)
                hi = rng.next_int(lo + 1, 50)
                m = m.merge(ReducingIntervalMap.of_range(lo, hi, rng.next_int(1, 100)), max)
            return m
        a, b = rand_map(), rand_map()
        merged = a.merge(b, max)
        for probe in range(-1, 51):
            va, vb = a.get(probe), b.get(probe)
            expect = max((v for v in (va, vb) if v is not None), default=None)
            assert merged.get(probe) == expect, probe


def test_interval_map_of_ranges_adjacent():
    m = ReducingIntervalMap.of_ranges([(0, 5), (5, 10), (20, 30)], "x")
    assert m.get(4) == "x" and m.get(5) == "x" and m.get(9) == "x"
    assert m.get(10) is None and m.get(25) == "x"


# -- rng --------------------------------------------------------------------

def test_rng_determinism_and_fork():
    a, b = RandomSource(1), RandomSource(1)
    assert [a.next_int(100) for _ in range(20)] == [b.next_int(100) for _ in range(20)]
    fa, fb = a.fork(), b.fork()
    assert [fa.next_long() for _ in range(5)] == [fb.next_long() for _ in range(5)]


def test_rng_biased_and_zipf():
    rng = RandomSource(2)
    for _ in range(100):
        v = rng.next_biased_int(0, 10, 100)
        assert 0 <= v < 100
    counts = [0] * 5
    for _ in range(500):
        counts[rng.next_zipf(5)] += 1
    assert counts[0] > counts[4]  # zipf skew


# -- invariants -------------------------------------------------------------

def test_invariants():
    Invariants.check_state(True)
    with pytest.raises(InvariantViolation):
        Invariants.check_state(False, "bad %s", "state")
    with pytest.raises(ValueError):
        Invariants.check_argument(False)
    old = Invariants.paranoia
    try:
        Invariants.set_paranoia(Paranoia.NONE)
        Invariants.paranoid(lambda: False)  # not evaluated at NONE
        Invariants.set_paranoia(Paranoia.SUPERLINEAR)
        with pytest.raises(InvariantViolation):
            Invariants.paranoid(lambda: False)
    finally:
        Invariants.set_paranoia(old)
