"""Independent history oracle (ISSUE-16 tentpole): the Elle-style checker.

Three planes:
1. MUTATION tests — hand-injected anomalous histories (stale read /
   real-time violation, lost update, G1c, G0, fractured read, aborted read,
   incompatible order) must each be caught and NAMED; a checker that only
   ever says "clean" is not an oracle.
2. CLEAN-matrix — full hostile burns under ``check="history"`` (composable
   with ``audit="strict"``) pass with zero anomalies; seeds 0-9 x 250 ops
   behind ACCORD_LONG_BURNS.
3. ZERO OBSERVER EFFECT — same-seed hostile burn with history recording on
   vs off is byte-identical (full trace diff + audit verdict + outcomes),
   the same proof pattern as the PR 3/10/12 observability planes.
"""
import os

import pytest

from cassandra_accord_tpu.harness.burn import run_burn
from cassandra_accord_tpu.harness.trace import Trace, diff_traces
from cassandra_accord_tpu.observe.checker import (HistoryAnomaly,
                                                  check_history,
                                                  format_report)
from cassandra_accord_tpu.observe.history import HistoryRecorder

HOSTILE = dict(ops=40, concurrency=8, chaos=True, allow_failures=True,
               durability=True, journal=True, delayed_stores=True,
               clock_drift=True, max_tasks=3_000_000)


# ---------------------------------------------------------------------------
# mutation tests: injected anomalies must be caught and named
# ---------------------------------------------------------------------------

def _anomaly(rec, final_state=None):
    with pytest.raises(HistoryAnomaly) as exc:
        check_history(rec.ops, final_state=final_state)
    return exc.value.report["anomalies"][0]


def test_stale_read_is_a_realtime_violation():
    # op2 is invoked strictly AFTER op1's write completed, yet observes an
    # empty list: serializable (op2 before op1) but not STRICTLY so
    rec = HistoryRecorder()
    rec.invoke(1, "t1", 0, (), {"k": "a"})
    rec.resolve(1, "ok", 100, writes={"k": "a"})
    rec.invoke(2, "t2", 200, ("k",))
    rec.resolve(2, "ok", 300, reads={"k": ()})
    a = _anomaly(rec, final_state={"k": ("a",)})
    assert a["name"] == "G-single-realtime"
    assert "stale read" in a["detail"]
    kinds = {e["kind"] for e in a["edges"]}
    assert kinds == {"rw", "rt"}


def test_stale_read_caught_without_final_state():
    # the hardest stale-read shape: the committed write's value never
    # surfaces in ANY observation and no final state pins its position —
    # but a read returns the ENTIRE list, so an acked append absent from a
    # later read's list is an rw edge regardless of position knowledge.
    # (Found by probing the package boundary: the positional rw table
    # alone cannot see writers the version order never named.)
    rec = HistoryRecorder()
    rec.invoke(1, "t1", 0, (), {"k": "a"})
    rec.resolve(1, "ok", 100, writes={"k": "a"})
    rec.invoke(2, "t2", 200, ("k",))
    rec.resolve(2, "ok", 300, reads={"k": ()})
    a = _anomaly(rec)   # NO final_state
    assert a["name"] == "G-single-realtime"
    assert {e["kind"] for e in a["edges"]} == {"rw", "rt"}


def test_lost_update_caught():
    # an acked write whose value never made the authoritative final order
    rec = HistoryRecorder()
    rec.invoke(1, "t1", 0, (), {"k": "a"})
    rec.resolve(1, "ok", 100, writes={"k": "a"})
    a = _anomaly(rec, final_state={"k": ("b",)})
    assert a["name"] == "lost-update"
    assert "missing from final order" in a["detail"]
    # key entirely absent from the final state is the same anomaly
    rec2 = HistoryRecorder()
    rec2.invoke(1, "t1", 0, (), {"k": "a"})
    rec2.resolve(1, "ok", 100, writes={"k": "a"})
    assert _anomaly(rec2, final_state={})["name"] == "lost-update"


def test_g1c_circular_information_flow():
    # op1 writes x and observes op2's y; op2 writes y and observes op1's x —
    # each read the other's write: no serial order exists.  Overlapping
    # intervals, so the cycle closes WITHOUT real-time edges.
    rec = HistoryRecorder()
    rec.invoke(1, "t1", 0, ("y",), {"x": "a"})
    rec.invoke(2, "t2", 0, ("x",), {"y": "b"})
    rec.resolve(1, "ok", 1000, reads={"y": ("b",)}, writes={"x": "a"})
    rec.resolve(2, "ok", 1000, reads={"x": ("a",)}, writes={"y": "b"})
    a = _anomaly(rec)
    assert a["name"] == "G1c"
    assert {e["kind"] for e in a["edges"]} == {"wr"}


def test_g0_write_cycle():
    # ww-only cycle: the version orders interleave the two writers' keys in
    # opposite orders
    rec = HistoryRecorder()
    rec.invoke(1, "t1", 0, (), {"x": "a1", "y": "b2"})
    rec.invoke(2, "t2", 0, (), {"x": "a2", "y": "b1"})
    rec.resolve(1, "ok", 1000, writes={"x": "a1", "y": "b2"})
    rec.resolve(2, "ok", 1000, writes={"x": "a2", "y": "b1"})
    a = _anomaly(rec, final_state={"x": ("a1", "a2"), "y": ("b1", "b2")})
    assert a["name"] == "G0"
    assert {e["kind"] for e in a["edges"]} == {"ww"}


def test_fractured_read_named_non_repeatable():
    # op2 observes HALF of op1's atomic two-key write
    rec = HistoryRecorder()
    rec.invoke(1, "t1", 0, (), {"x": "a", "y": "b"})
    rec.invoke(2, "t2", 0, ("x", "y"))
    rec.resolve(1, "ok", 1000, writes={"x": "a", "y": "b"})
    rec.resolve(2, "ok", 1000, reads={"x": ("a",), "y": ()})
    a = _anomaly(rec, final_state={"x": ("a",), "y": ("b",)})
    assert a["name"] == "non-repeatable-read"
    assert "fractured read" in a["detail"]


def test_aborted_read_g1a():
    # an op the cluster durably NACKED must never surface to a reader
    rec = HistoryRecorder()
    rec.invoke(1, "t1", 0, (), {"x": "a"})
    rec.resolve(1, "nacked", 100, writes={"x": "a"})
    rec.invoke(2, "t2", 200, ("x",))
    rec.resolve(2, "ok", 300, reads={"x": ("a",)})
    a = _anomaly(rec)
    assert a["name"] == "G1a-aborted-read"


def test_incompatible_order():
    # list-append reads must be prefixes of one another
    rec = HistoryRecorder()
    rec.invoke(1, "t1", 0, ("x",))
    rec.resolve(1, "ok", 100, reads={"x": ("a", "b")})
    rec.invoke(2, "t2", 0, ("x",))
    rec.resolve(2, "ok", 100, reads={"x": ("a", "c")})
    a = _anomaly(rec)
    assert a["name"] == "incompatible-order"


def test_info_op_writes_may_surface_cleanly():
    # a lost op's writes MAY apply: surfacing is not an anomaly, and the
    # writer joins the graph for attribution
    rec = HistoryRecorder()
    rec.invoke(1, "t1", 0, (), {"x": "a"})
    rec.resolve(1, "lost", 100)
    rec.invoke(2, "t2", 200, ("x",))
    rec.resolve(2, "ok", 300, reads={"x": ("a",)})
    report = check_history(rec.ops, final_state={"x": ("a",)})
    assert report["anomalies"] == []
    assert report["edges"]["wr"] == 1


def test_clean_history_reports_clean():
    rec = HistoryRecorder()
    rec.invoke(1, "t1", 0, (), {"x": "a"})
    rec.resolve(1, "ok", 100, writes={"x": "a"})
    rec.invoke(2, "t2", 200, ("x",))
    rec.resolve(2, "ok", 300, reads={"x": ("a",)})
    report = check_history(rec.ops, final_state={"x": ("a",)})
    assert report["anomalies"] == []
    assert report["ok"] == 2 and report["keys"] == 1


# ---------------------------------------------------------------------------
# report content: sub-history, edges, flight-recorder timelines
# ---------------------------------------------------------------------------

def test_report_carries_sub_history_and_timelines():
    class _Span:
        def to_dict(self):
            return {"events": ["PreAccept", "Commit"]}

    rec = HistoryRecorder()
    rec.invoke(1, "t1", 0, (), {"k": "a"})
    rec.resolve(1, "ok", 100, writes={"k": "a"})
    rec.invoke(2, "t2", 200, ("k",))
    rec.resolve(2, "ok", 300, reads={"k": ()})
    with pytest.raises(HistoryAnomaly) as exc:
        check_history(rec.ops, final_state={"k": ("a",)},
                      spans={"t1": _Span(), "t2": _Span()})
    a = exc.value.report["anomalies"][0]
    ids = {r["op_id"] for r in a["sub_history"]}
    assert ids == {1, 2}
    assert set(a["timelines"]) == {"t1", "t2"}
    text = format_report(exc.value.report)
    assert "G-single-realtime" in text and "op 1" in text
    assert "timelines attached" in text


# ---------------------------------------------------------------------------
# burn integration: hostile matrix clean under check="history"
# ---------------------------------------------------------------------------

def test_hostile_burn_checks_clean():
    res = run_burn(5, check="history", **HOSTILE)
    assert res.history is not None
    assert res.history["anomalies"] == []
    assert res.history["ops"] >= res.ops_ok


def test_history_composes_with_strict_audit():
    # both oracles at once: the protocol-aware auditor AND the protocol-
    # blind checker over the identical trajectory
    res = run_burn(7, check="history", audit="strict", **HOSTILE)
    assert res.history is not None and res.history["anomalies"] == []
    assert res.audit is not None and not res.audit.get("violations")


def test_zero_observer_effect_history_recording():
    # the recorder is a passive sink: same-seed hostile burns with history
    # recording on vs off are byte-identical in the FULL message trace, the
    # audit verdict, and the outcome partition
    ta, tb = Trace(), Trace()
    bare = run_burn(9, tracer=ta.hook, audit="warn", **HOSTILE)
    checked = run_burn(9, tracer=tb.hook, audit="warn", check="history",
                       **HOSTILE)
    assert diff_traces(ta, tb) is None
    assert (bare.ops_ok, bare.ops_recovered, bare.ops_nacked,
            bare.ops_lost, bare.ops_failed) == \
           (checked.ops_ok, checked.ops_recovered, checked.ops_nacked,
            checked.ops_lost, checked.ops_failed)
    assert bare.audit == checked.audit
    assert checked.history is not None


@pytest.mark.slow
@pytest.mark.skipif("ACCORD_LONG_BURNS" not in os.environ,
                    reason="hours-class: the full acceptance matrix")
def test_full_matrix_seeds_0_9_clean():
    # the ISSUE-16 acceptance matrix: hostile + churn + elastic, seeds 0-9 x
    # 250 ops, BOTH oracles on — zero violations, zero anomalies
    for seed in range(10):
        res = run_burn(seed, ops=250, concurrency=16, chaos=True,
                       allow_failures=True, durability=True, journal=True,
                       delayed_stores=True, clock_drift=True,
                       topology_churn=True, elastic_membership=True,
                       restart_nodes=True, pause_nodes=True, disk_stall=True,
                       check="history", audit="strict",
                       max_tasks=100_000_000)
        assert res.history is not None and res.history["anomalies"] == []
