"""Ephemeral reads: 1-round, non-durable, ordered after witnessed writes.

Parity targets: CoordinateEphemeralRead.java:57-150, GetEphemeralReadDeps.java,
ReadEphemeralTxnData.java; witness matrix — EphemeralRead is witnessed by Nothing.
"""
from cassandra_accord_tpu.harness.cluster import Cluster
from cassandra_accord_tpu.impl.list_store import (ListResult, ephemeral_read_txn,
                                                  list_txn)
from cassandra_accord_tpu.primitives.keys import IntKey, Range
from cassandra_accord_tpu.primitives.timestamp import TxnKind
from cassandra_accord_tpu.topology.topology import Shard, Topology


def k(v):
    return IntKey(v)


def make_cluster(seed=1, nodes=(1, 2, 3), shards=None, **kw):
    if shards is None:
        shards = [Shard(Range(k(0), k(1000)), list(nodes))]
    return Cluster(Topology(1, shards), seed=seed, **kw)


def submit_write(cluster, node_id, appends):
    return cluster.nodes[node_id].coordinate(
        list_txn([], {k(key): v for key, v in appends.items()}))


def test_ephemeral_read_sees_prior_writes():
    cluster = make_cluster()
    w = submit_write(cluster, 1, {5: "a"})
    assert cluster.run_until(w.is_done)
    cluster.run_until_idle()
    r = cluster.nodes[2].coordinate(ephemeral_read_txn([k(5)]))
    assert cluster.run_until(r.is_done)
    assert isinstance(r.value, ListResult)
    assert r.value.reads[k(5)] == ("a",)


def test_ephemeral_read_leaves_no_durable_state():
    cluster = make_cluster(seed=3)
    w = submit_write(cluster, 1, {9: "x"})
    assert cluster.run_until(w.is_done)
    r = cluster.nodes[3].coordinate(ephemeral_read_txn([k(9)]))
    assert cluster.run_until(r.is_done)
    cluster.run_until_idle()
    for n in cluster.nodes:
        for store in cluster.nodes[n].command_stores.all_stores():
            for txn_id in store.commands:
                assert txn_id.kind is not TxnKind.EPHEMERAL_READ, \
                    f"ephemeral read left command state on node {n}"
            for cfk in store.cfks.values():
                for info in cfk.by_id:
                    assert info.txn_id.kind is not TxnKind.EPHEMERAL_READ


def test_ephemeral_read_waits_for_concurrent_write():
    """An ephemeral read that witnesses an in-flight write's deps must observe
    it once the write resolves (ordered-after semantics)."""
    cluster = make_cluster(seed=7)
    # seed some history so deps exist
    w0 = submit_write(cluster, 1, {21: "base"})
    assert cluster.run_until(w0.is_done)
    w1 = submit_write(cluster, 2, {21: "mid"})
    r = cluster.nodes[3].coordinate(ephemeral_read_txn([k(21)]))
    assert cluster.run_until(lambda: w1.is_done() and r.is_done())
    got = r.value.reads[k(21)]
    assert got[0] == "base", got
    # must be a prefix of the final list
    cluster.run_until_idle()
    final = cluster.stores[1].get(k(21))
    assert got == final[: len(got)], (got, final)


def test_ephemeral_read_multiple_keys_across_shards():
    shards = [Shard(Range(k(0), k(100)), [1, 2, 3]),
              Shard(Range(k(100), k(200)), [1, 2, 3])]
    cluster = make_cluster(shards=shards, seed=11)
    w = submit_write(cluster, 1, {50: "l", 150: "r"})
    assert cluster.run_until(w.is_done)
    cluster.run_until_idle()
    r = cluster.nodes[2].coordinate(ephemeral_read_txn([k(50), k(150)]))
    assert cluster.run_until(r.is_done)
    assert r.value.reads[k(50)] == ("l",)
    assert r.value.reads[k(150)] == ("r",)
