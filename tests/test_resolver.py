"""DepsResolver boundary: CPU/TPU parity, slot lifecycle, burn-level parity.

Parity targets: SafeCommandStore.mapReduceActive (SafeCommandStore.java:292),
cfk/CommandsForKey.java:925-1000 (the hot deps query), MaxConflicts.java:32.
The TPU resolver (impl/tpu_resolver.py) must answer every query bit-identically
to the CPU reference walk — VerifyDepsResolver asserts this on every call.
"""
import pytest

from cassandra_accord_tpu.harness.burn import run_burn
from cassandra_accord_tpu.harness.cluster import Cluster
from cassandra_accord_tpu.impl.list_store import list_txn
from cassandra_accord_tpu.impl.resolver import (CpuDepsResolver,
                                                VerifyDepsResolver)
from cassandra_accord_tpu.impl.tpu_resolver import TpuDepsResolver
from cassandra_accord_tpu.local.cfk import InternalStatus
from cassandra_accord_tpu.primitives.keys import IntKey, Range, Ranges
from cassandra_accord_tpu.primitives.timestamp import (Domain, Timestamp, TxnId,
                                                       TxnKind)
from cassandra_accord_tpu.topology.topology import Shard, Topology
from cassandra_accord_tpu.utils.random import RandomSource


def k(v):
    return IntKey(v)


def rk(v):
    return IntKey(v).to_routing()


def tid(hlc, node=1, kind=TxnKind.WRITE):
    return TxnId(epoch=1, hlc=hlc, node=node, kind=kind, domain=Domain.KEY)


class _FakeStore:
    """Minimal stand-in exposing .cfks for the CPU resolver."""

    def __init__(self):
        self.cfks = {}

    def cfk(self, key):
        from cassandra_accord_tpu.local.cfk import CommandsForKey
        c = self.cfks.get(key)
        if c is None:
            c = CommandsForKey(key)
            self.cfks[key] = c
        return c


def make_pair():
    store = _FakeStore()
    cpu = CpuDepsResolver(store)
    tpu = TpuDepsResolver(store, txn_capacity=4, key_capacity=4)  # force growth
    return store, VerifyDepsResolver(cpu, tpu)


def register_both(store, verify, txn_id, status, execute_at, keys):
    """Mirror SafeCommandStore.register_witness: cfk update + resolver feed
    (only keys the cfk actually indexed — its prune guard may refuse)."""
    indexed = tuple(key for key in keys
                    if store.cfk(key).update(txn_id, status, execute_at))
    if indexed:
        verify.register(txn_id, status, execute_at, indexed)


def test_parity_random_workload():
    """10k randomized register/update/prune/query ops: every query must agree
    bit-for-bit between the cfk walk and the device join."""
    rng = RandomSource(1234)
    store, verify = make_pair()
    keys = [rk(i * 10) for i in range(12)]
    live = []
    hlc = 0
    for _ in range(600):
        roll = rng.next_float()
        if roll < 0.35 or not live:
            hlc += rng.next_int(1, 5)
            kind = rng.pick([TxnKind.WRITE, TxnKind.READ, TxnKind.WRITE])
            t = tid(hlc, node=1 + rng.next_int(3), kind=kind)
            ks = sorted({rng.pick(keys) for _ in range(rng.next_int(1, 4))})
            register_both(store, verify, t, InternalStatus.PREACCEPTED, None, ks)
            live.append((t, ks))
        elif roll < 0.55:
            t, ks = rng.pick(live)
            status = rng.pick([InternalStatus.ACCEPTED, InternalStatus.COMMITTED,
                               InternalStatus.STABLE, InternalStatus.APPLIED,
                               InternalStatus.INVALIDATED])
            ea = Timestamp(1, hlc + rng.next_int(10), 0, t.node) \
                if status in (InternalStatus.ACCEPTED, InternalStatus.COMMITTED,
                              InternalStatus.STABLE) else None
            register_both(store, verify, t, status, ea, ks)
        elif roll < 0.65:
            # bound-prune one key (GC): both planes must evict identically
            key = rng.pick(keys)
            cfk = store.cfks.get(key)
            if cfk is not None:
                bound = tid(hlc + 1)
                verify.on_pruned(key, cfk.prune_applied_before(bound))
        else:
            hlc += 1
            q = tid(hlc, kind=rng.pick([TxnKind.WRITE, TxnKind.READ]))
            qk = sorted({rng.pick(keys) for _ in range(rng.next_int(1, 5))})
            before = q.as_timestamp() if rng.next_boolean() else Timestamp.MAX
            verify.key_conflicts(q, qk, before)
            verify.max_conflict_keys(qk)
            if rng.next_boolean():
                rng_lo = rng.next_int(0, 100)
                r = Range(k(rng_lo), k(rng_lo + rng.next_int(10, 60)))
                verify.range_conflicts(q, r, before)
                verify.max_conflict_range(r)
    assert verify.queries > 100


def test_slot_recycling_and_growth():
    """Slots free when a txn is pruned from all keys; capacity growth rebuilds
    losslessly (start capacity 4, insert dozens)."""
    store, verify = make_pair()
    tpu = verify.tpu
    all_ids = []
    for i in range(40):
        t = tid(10 + i)
        register_both(store, verify, t, InternalStatus.PREACCEPTED, None,
                      [rk(i % 6 * 10)])
        all_ids.append(t)
    assert tpu.indexed_count() == 40
    # apply + prune the first 30 from their keys
    for i, t in enumerate(all_ids[:30]):
        register_both(store, verify, t, InternalStatus.APPLIED, None,
                      [rk(i % 6 * 10)])
    for key in list(store.cfks):
        verify.on_pruned(key, store.cfks[key].prune_applied_before(tid(40)))
    assert tpu.indexed_count() == 10
    # queries over the survivors still agree
    q = tid(1000)
    got = verify.key_conflicts(q, [rk(i * 10) for i in range(6)],
                               q.as_timestamp())
    assert {t for _, t in got} == set(all_ids[30:])
    # recycled slots are reused
    for i in range(20):
        register_both(store, verify, tid(2000 + i), InternalStatus.PREACCEPTED,
                      None, [rk(0)])
    verify.key_conflicts(tid(3000), [rk(0)], tid(3000).as_timestamp())


def test_multi_key_partial_prune():
    """A txn pruned from one key must stay visible via its other keys."""
    store, verify = make_pair()
    t = tid(10)
    register_both(store, verify, t, InternalStatus.APPLIED, None,
                  [rk(0), rk(10)])
    verify.on_pruned(rk(0), store.cfks[rk(0)].prune_applied_before(tid(50)))
    q = tid(100)
    got = verify.key_conflicts(q, [rk(0), rk(10)], q.as_timestamp())
    assert got == [(rk(10), t)]
    assert verify.tpu.indexed_count() == 1
    # now prune the second key: slot recycles
    verify.on_pruned(rk(10), store.cfks[rk(10)].prune_applied_before(tid(50)))
    assert verify.key_conflicts(q, [rk(0), rk(10)], q.as_timestamp()) == []
    assert verify.tpu.indexed_count() == 0


def test_witness_matrix_parity():
    """Reads witness writes but not reads; writes witness both (Txn.java:221-262)."""
    store, verify = make_pair()
    w = tid(10, kind=TxnKind.WRITE)
    r = tid(20, kind=TxnKind.READ)
    register_both(store, verify, w, InternalStatus.PREACCEPTED, None, [rk(0)])
    register_both(store, verify, r, InternalStatus.PREACCEPTED, None, [rk(0)])
    read_q = tid(30, kind=TxnKind.READ)
    write_q = tid(30, kind=TxnKind.WRITE)
    got_r = verify.key_conflicts(read_q, [rk(0)], read_q.as_timestamp())
    got_w = verify.key_conflicts(write_q, [rk(0)], write_q.as_timestamp())
    assert {t for _, t in got_r} == {w}
    assert {t for _, t in got_w} == {w, r}


def test_cluster_end_to_end_verify_resolver():
    """A full simulated-cluster run with the parity-asserting resolver."""
    shards = [Shard(Range(k(0), k(1000)), [1, 2, 3])]
    cluster = Cluster(Topology(1, shards), seed=77, resolver="verify")
    results = []
    for i in range(12):
        txn = list_txn([k(5)] if i % 4 == 0 else [],
                       {k(5): f"v{i}", k(600): f"w{i}"})
        results.append(cluster.nodes[1 + i % 3].coordinate(txn))
    assert cluster.run_until(lambda: all(r.is_done() for r in results))
    cluster.run_until_idle()
    assert all(r.failure is None for r in results)
    lists = {cluster.stores[n].get(k(5)) for n in cluster.nodes}
    assert len(lists) == 1
    # parity checks actually ran
    total = 0
    for n in cluster.nodes:
        for store in cluster.nodes[n].command_stores.all_stores():
            assert isinstance(store.resolver, VerifyDepsResolver)
            total += store.resolver.queries
    assert total > 50, f"only {total} parity-checked queries"


def test_burn_with_verify_resolver():
    """Seeded burn (topology churn + journal) under continuous deps parity."""
    result = run_burn(seed=424242, ops=80, concurrency=8, topology_churn=True,
                      journal=True, resolver="verify")
    assert result.ops_ok > 0
