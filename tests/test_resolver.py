"""DepsResolver boundary: CPU/TPU parity, slot lifecycle, burn-level parity.

Parity targets: SafeCommandStore.mapReduceActive (SafeCommandStore.java:292),
cfk/CommandsForKey.java:925-1000 (the hot deps query), MaxConflicts.java:32.
The TPU resolver (impl/tpu_resolver.py) must answer every query bit-identically
to the CPU reference walk — VerifyDepsResolver asserts this on every call.
"""
import pytest

from cassandra_accord_tpu.harness.burn import run_burn
from cassandra_accord_tpu.harness.cluster import Cluster
from cassandra_accord_tpu.impl.list_store import list_txn
from cassandra_accord_tpu.impl.resolver import (CpuDepsResolver,
                                                VerifyDepsResolver)
from cassandra_accord_tpu.impl.tpu_resolver import TpuDepsResolver
from cassandra_accord_tpu.local.cfk import InternalStatus
from cassandra_accord_tpu.primitives.keys import IntKey, Range, Ranges
from cassandra_accord_tpu.primitives.timestamp import (Domain, Timestamp, TxnId,
                                                       TxnKind)
from cassandra_accord_tpu.topology.topology import Shard, Topology
from cassandra_accord_tpu.utils.random import RandomSource


def k(v):
    return IntKey(v)


def rk(v):
    return IntKey(v).to_routing()


def tid(hlc, node=1, kind=TxnKind.WRITE):
    return TxnId(epoch=1, hlc=hlc, node=node, kind=kind, domain=Domain.KEY)


class _FakeStore:
    """Minimal stand-in exposing .cfks (+ durability watermarks: the elision
    soundness gate) for the CPU resolver."""

    def __init__(self):
        from cassandra_accord_tpu.local.durability import DurableBefore
        from cassandra_accord_tpu.primitives.keys import Ranges as _Rs
        self.cfks = {}
        # a high majority watermark over the whole keyspace: these unit tests
        # exercise elision mechanics, not the durability protocol
        self.durable_before = DurableBefore.of(
            _Rs.of(Range(k(0), k(100000))),
            majority_before=tid(1 << 40), universal_before=None)
        self.durable_gen = 0

    def cfk(self, key):
        from cassandra_accord_tpu.local.cfk import CommandsForKey
        c = self.cfks.get(key)
        if c is None:
            c = CommandsForKey(key)
            self.cfks[key] = c
        return c


def make_pair():
    store = _FakeStore()
    cpu = CpuDepsResolver(store)
    tpu = TpuDepsResolver(store, txn_capacity=4, key_capacity=4)  # force growth
    tpu._walk_max = 0    # keep the vector tiers under test (not the walk rung)
    tpu._walk_width = 0  # and disable the narrow-query walk routing too
    return store, VerifyDepsResolver(cpu, tpu)


def register_both(store, verify, txn_id, status, execute_at, keys):
    """Mirror SafeCommandStore.register_witness: cfk update + resolver feed
    (only keys the cfk actually indexed — its prune guard may refuse)."""
    indexed = tuple(key for key in keys
                    if store.cfk(key).update(txn_id, status, execute_at))
    if indexed:
        verify.register(txn_id, status, execute_at, indexed)


def test_parity_random_workload():
    """10k randomized register/update/prune/query ops: every query must agree
    bit-for-bit between the cfk walk and the device join."""
    rng = RandomSource(1234)
    store, verify = make_pair()
    keys = [rk(i * 10) for i in range(12)]
    live = []
    hlc = 0
    for _ in range(600):
        roll = rng.next_float()
        if roll < 0.35 or not live:
            hlc += rng.next_int(1, 5)
            kind = rng.pick([TxnKind.WRITE, TxnKind.READ, TxnKind.WRITE])
            t = tid(hlc, node=1 + rng.next_int(3), kind=kind)
            ks = sorted({rng.pick(keys) for _ in range(rng.next_int(1, 4))})
            register_both(store, verify, t, InternalStatus.PREACCEPTED, None, ks)
            live.append((t, ks))
        elif roll < 0.55:
            t, ks = rng.pick(live)
            status = rng.pick([InternalStatus.ACCEPTED, InternalStatus.COMMITTED,
                               InternalStatus.STABLE, InternalStatus.APPLIED,
                               InternalStatus.INVALIDATED])
            ea = Timestamp(1, hlc + rng.next_int(10), 0, t.node) \
                if status in (InternalStatus.ACCEPTED, InternalStatus.COMMITTED,
                              InternalStatus.STABLE) else None
            register_both(store, verify, t, status, ea, ks)
        elif roll < 0.65:
            # bound-prune one key (GC): both planes must evict identically
            key = rng.pick(keys)
            cfk = store.cfks.get(key)
            if cfk is not None:
                bound = tid(hlc + 1)
                verify.on_pruned(key, cfk.prune_applied_before(bound))
        else:
            hlc += 1
            q = tid(hlc, kind=rng.pick([TxnKind.WRITE, TxnKind.READ]))
            qk = sorted({rng.pick(keys) for _ in range(rng.next_int(1, 5))})
            before = q.as_timestamp() if rng.next_boolean() else Timestamp.MAX
            verify.key_conflicts(q, qk, before)
            verify.max_conflict_keys(qk)
            if rng.next_boolean():
                rng_lo = rng.next_int(0, 100)
                r = Range(k(rng_lo), k(rng_lo + rng.next_int(10, 60)))
                verify.range_conflicts(q, r, before)
                verify.max_conflict_range(r)
    assert verify.queries > 100


def test_slot_recycling_and_growth():
    """Slots free when a txn is pruned from all keys; capacity growth rebuilds
    losslessly (start capacity 4, insert dozens)."""
    store, verify = make_pair()
    tpu = verify.tpu
    all_ids = []
    for i in range(40):
        t = tid(10 + i)
        register_both(store, verify, t, InternalStatus.PREACCEPTED, None,
                      [rk(i % 6 * 10)])
        all_ids.append(t)
    assert tpu.indexed_count() == 40
    # apply + prune the first 30 from their keys
    for i, t in enumerate(all_ids[:30]):
        register_both(store, verify, t, InternalStatus.APPLIED, None,
                      [rk(i % 6 * 10)])
    for key in list(store.cfks):
        verify.on_pruned(key, store.cfks[key].prune_applied_before(tid(40)))
    assert tpu.indexed_count() == 10
    # queries over the survivors still agree
    q = tid(1000)
    got = verify.key_conflicts(q, [rk(i * 10) for i in range(6)],
                               q.as_timestamp())
    assert {t for _, t in got} == set(all_ids[30:])
    # recycled slots are reused
    for i in range(20):
        register_both(store, verify, tid(2000 + i), InternalStatus.PREACCEPTED,
                      None, [rk(0)])
    verify.key_conflicts(tid(3000), [rk(0)], tid(3000).as_timestamp())


def test_multi_key_partial_prune():
    """A txn pruned from one key must stay visible via its other keys."""
    store, verify = make_pair()
    t = tid(10)
    register_both(store, verify, t, InternalStatus.APPLIED, None,
                  [rk(0), rk(10)])
    verify.on_pruned(rk(0), store.cfks[rk(0)].prune_applied_before(tid(50)))
    q = tid(100)
    got = verify.key_conflicts(q, [rk(0), rk(10)], q.as_timestamp())
    assert got == [(rk(10), t)]
    assert verify.tpu.indexed_count() == 1
    # now prune the second key: slot recycles
    verify.on_pruned(rk(10), store.cfks[rk(10)].prune_applied_before(tid(50)))
    assert verify.key_conflicts(q, [rk(0), rk(10)], q.as_timestamp()) == []
    assert verify.tpu.indexed_count() == 0


def test_witness_matrix_parity():
    """Reads witness writes but not reads; writes witness both (Txn.java:221-262)."""
    store, verify = make_pair()
    w = tid(10, kind=TxnKind.WRITE)
    r = tid(20, kind=TxnKind.READ)
    register_both(store, verify, w, InternalStatus.PREACCEPTED, None, [rk(0)])
    register_both(store, verify, r, InternalStatus.PREACCEPTED, None, [rk(0)])
    read_q = tid(30, kind=TxnKind.READ)
    write_q = tid(30, kind=TxnKind.WRITE)
    got_r = verify.key_conflicts(read_q, [rk(0)], read_q.as_timestamp())
    got_w = verify.key_conflicts(write_q, [rk(0)], write_q.as_timestamp())
    assert {t for _, t in got_r} == {w}
    assert {t for _, t in got_w} == {w, r}


def test_cluster_end_to_end_verify_resolver(monkeypatch):
    """A full simulated-cluster run with the parity-asserting resolver."""
    monkeypatch.setenv("ACCORD_TPU_WALK_MAX", "0")
    monkeypatch.setenv("ACCORD_TPU_WALK_WIDTH", "0")   # exercise vector tiers
    shards = [Shard(Range(k(0), k(1000)), [1, 2, 3])]
    cluster = Cluster(Topology(1, shards), seed=77, resolver="verify")
    results = []
    for i in range(12):
        txn = list_txn([k(5)] if i % 4 == 0 else [],
                       {k(5): f"v{i}", k(600): f"w{i}"})
        results.append(cluster.nodes[1 + i % 3].coordinate(txn))
    assert cluster.run_until(lambda: all(r.is_done() for r in results))
    cluster.run_until_idle()
    assert all(r.failure is None for r in results)
    lists = {cluster.stores[n].get(k(5)) for n in cluster.nodes}
    assert len(lists) == 1
    # parity checks actually ran
    total = 0
    for n in cluster.nodes:
        for store in cluster.nodes[n].command_stores.all_stores():
            assert isinstance(store.resolver, VerifyDepsResolver)
            total += store.resolver.queries
    assert total > 50, f"only {total} parity-checked queries"


def test_burn_with_verify_resolver(monkeypatch):
    """Seeded burn (topology churn + journal) under continuous deps parity."""
    monkeypatch.setenv("ACCORD_TPU_WALK_MAX", "0")
    monkeypatch.setenv("ACCORD_TPU_WALK_WIDTH", "0")   # exercise vector tiers
    result = run_burn(seed=424242, ops=80, concurrency=8, topology_churn=True,
                      journal=True, resolver="verify")
    assert result.ops_ok > 0


def test_parity_device_tier(monkeypatch):
    """Force every consult onto the DEVICE tier (ops.deps_kernels.consult):
    the MXU join must agree bit-for-bit with the cfk walk, like the host
    tier does (the two tiers of impl/tpu_resolver._consult)."""
    monkeypatch.setenv("ACCORD_TPU_TIER", "device")
    rng = RandomSource(777)
    store, verify = make_pair()
    assert verify.tpu.tier == "device"
    keys = [rk(i * 10) for i in range(8)]
    hlc = 0
    for _ in range(120):
        roll = rng.next_float()
        if roll < 0.5:
            hlc += rng.next_int(1, 5)
            t = tid(hlc, node=1 + rng.next_int(3),
                    kind=rng.pick([TxnKind.WRITE, TxnKind.READ]))
            ks = sorted({rng.pick(keys) for _ in range(rng.next_int(1, 4))})
            register_both(store, verify, t, InternalStatus.PREACCEPTED, None, ks)
        else:
            hlc += 1
            q = tid(hlc, kind=rng.pick([TxnKind.WRITE, TxnKind.READ]))
            qk = sorted({rng.pick(keys) for _ in range(rng.next_int(1, 5))})
            verify.key_conflicts(q, qk, q.as_timestamp())
            verify.max_conflict_keys(qk)
    assert verify.tpu.device_consults > 20
    assert verify.tpu.host_consults == 0


def test_prefetch_exact_and_interference():
    """Prefetched answers serve only when provably equal to a live query:
    self-registration is exempt; any other same-key mutation forces fallback."""
    from cassandra_accord_tpu.impl.resolver import QuerySpec
    store, verify = make_pair()
    tpu = verify.tpu
    a, b = tid(10), tid(20, node=2)
    register_both(store, verify, a, InternalStatus.PREACCEPTED, None, [rk(0)])

    # window with two upcoming preaccept consults: b on key 0 (interferes with
    # c's registration below), c on key 10 (clean)
    c = tid(30, node=3)
    verify.prefetch([QuerySpec("mc", None, [rk(0)], None),
                     QuerySpec("kc", b, [rk(0)], b.as_timestamp()),
                     QuerySpec("mc", None, [rk(10)], None),
                     QuerySpec("kc", c, [rk(10)], c.as_timestamp())])
    h0 = tpu.prefetch_hits

    # message 1: preaccept(b) on key 0 — mc hits clean; then register; the kc
    # is served patched (b itself is the only delta, and b.txnId < b.txnId is
    # false, so the patch adds nothing — sequential semantics preserved)
    assert verify.max_conflict_keys([rk(0)]) is not None
    register_both(store, verify, b, InternalStatus.PREACCEPTED, None, [rk(0)])
    assert {t for _, t in verify.key_conflicts(b, [rk(0)], b.as_timestamp())} == {a}
    assert tpu.prefetch_hits == h0 + 1
    assert tpu.prefetch_patched >= 1

    # message 2: preaccept(c), but on key 0 instead of the declared key 10 —
    # b's registration dirtied key 0: the stale cached answer must not be
    # served as-is; b (new since prefetch) is PATCHED in from the mirrors
    h1 = tpu.prefetch_hits
    got = verify.key_conflicts(c, [rk(0)], c.as_timestamp())
    assert {t for _, t in got} == {a, b}   # sequential semantics: sees b
    assert tpu.prefetch_hits == h1        # not a clean hit: patched or fallback
    verify.end_batch()
    assert tpu._cache is None


def test_prefetch_accept_on_fresh_replica():
    """An Accept-style walk (before = executeAt > txnId) on a replica that
    never witnessed the txn: the prefetched answer lacks the txn, the handler
    registers it, and the cfk oracle DOES report it (txnId < before) — the
    self-exemption must not serve the stale answer; the patch must add it."""
    from cassandra_accord_tpu.impl.resolver import QuerySpec
    store, verify = make_pair()
    a = tid(10)
    register_both(store, verify, a, InternalStatus.PREACCEPTED, None, [rk(0)])
    b = tid(20, node=2)
    exec_at = Timestamp(1, 90, 0, 2)     # executeAt > b's txnId
    verify.prefetch([QuerySpec("kc", b, [rk(0)], exec_at)])
    # the Accept handler registers b (fresh here), THEN walks deps at exec_at
    register_both(store, verify, b, InternalStatus.ACCEPTED, exec_at, [rk(0)])
    got = verify.key_conflicts(b, [rk(0)], exec_at)
    assert {t for _, t in got} == {a, b}   # parity-asserted vs the cfk walk


def test_live_ops_not_replayed_on_recycled_slot():
    """Buffered cover/uncover ops must die with their slot: a new occupant of
    a recycled slot must not inherit a stale covered bit (which would drop it
    from deps answers — a missing-dependency serializability hazard)."""
    store, verify = make_pair()
    w1, a = tid(10), tid(20)
    register_both(store, verify, w1, InternalStatus.PREACCEPTED, None, [rk(0)])
    register_both(store, verify, w1, InternalStatus.COMMITTED,
                  Timestamp(1, 100, 0, 1), [rk(0)])
    # a commits below the covering bound -> covered (live op buffered, no
    # query in between so nothing flushes it)
    register_both(store, verify, a, InternalStatus.PREACCEPTED, None, [rk(0)])
    register_both(store, verify, a, InternalStatus.COMMITTED,
                  Timestamp(1, 50, 0, 1), [rk(0)])
    register_both(store, verify, a, InternalStatus.APPLIED, None, [rk(0)])
    verify.on_pruned(rk(0), store.cfks[rk(0)].prune_applied_before(tid(25)))
    # b recycles a's slot on the same key
    b = tid(30, node=2)
    register_both(store, verify, b, InternalStatus.PREACCEPTED, None, [rk(0)])
    q = tid(40)
    got = verify.key_conflicts(q, [rk(0)], q.as_timestamp())
    assert {t for _, t in got} == {w1, b}   # parity-asserted; b must survive


def test_elision_bounds_deps_under_contention():
    """Deep committed history on one key must NOT inflate deps answers: the
    covering write stands in for everything it orders (elision), so the
    answer stays O(uncommitted + 1) while the index holds hundreds."""
    store, verify = make_pair()
    for i in range(300):
        t = tid(10 + 2 * i)
        register_both(store, verify, t, InternalStatus.PREACCEPTED, None, [rk(0)])
        register_both(store, verify, t, InternalStatus.COMMITTED,
                      Timestamp(1, 11 + 2 * i, 0, 1), [rk(0)])
    # a couple of in-flight (uncommitted) txns remain visible
    u1, u2 = tid(1000, node=2), tid(1001, node=3)
    register_both(store, verify, u1, InternalStatus.PREACCEPTED, None, [rk(0)])
    register_both(store, verify, u2, InternalStatus.ACCEPTED,
                  Timestamp(1, 1002, 0, 3), [rk(0)])
    q = tid(2000)
    got = verify.key_conflicts(q, [rk(0)], q.as_timestamp())
    deps = {t for _, t in got}
    assert u1 in deps and u2 in deps
    assert tid(10 + 2 * 299) in deps          # the covering write itself
    assert len(deps) == 3, f"elision failed to bound deps: {len(deps)}"
    # and the timestamp proposal still sees the full history's max
    assert verify.max_conflict_keys([rk(0)]) is not None


def test_frontier_ready_kernel():
    """The kernel-computed execution frontier (kahn_frontier over the wait
    mirror): STABLE txns become ready exactly when their edges drain or point
    at applied slots; external (unindexed) deps block conservatively."""
    store, verify = make_pair()
    tpu = verify.tpu
    a, b, c = tid(10), tid(20), tid(30)
    ext = tid(99, node=7)                       # never indexed here
    for t, ks in ((a, [rk(0)]), (b, [rk(0)]), (c, [rk(10)])):
        register_both(store, verify, t, InternalStatus.PREACCEPTED, None, ks)
        register_both(store, verify, t, InternalStatus.STABLE,
                      Timestamp(1, t.hlc + 1, 0, 1), ks)
    tpu.register_waiting(a, set())
    tpu.register_waiting(b, {a})
    tpu.register_waiting(c, {ext})
    assert tpu.frontier_ready() == {a}          # b blocked by a, c by external
    register_both(store, verify, a, InternalStatus.APPLIED, None, [rk(0)])
    tpu.remove_waiting(b, a)
    assert tpu.frontier_ready() == {b}          # a no longer STABLE; c external
    tpu.remove_waiting(c, ext)
    assert tpu.frontier_ready() == {b, c}


def test_burn_frontier_parity_runs():
    """The verify-resolver burn continuously asserts kernel-frontier ==
    event-driven WaitingOn; make sure the check actually covers stores."""
    from cassandra_accord_tpu.harness.burn import run_burn, verify_frontiers, last_cluster
    result = run_burn(seed=987, ops=60, concurrency=8, resolver="verify")
    assert result.ops_ok == 60
    cluster = last_cluster()
    assert cluster is not None and verify_frontiers(cluster) > 0


def test_txnid_rebuild_keeps_kind():
    """TxnId flag-rebuild paths (merge_max, with_rejected) must preserve the
    kind cache."""
    a = tid(10, kind=TxnKind.READ)
    b = tid(10, kind=TxnKind.READ)
    merged = a.merge_max(b.with_rejected())
    assert merged.kind is TxnKind.READ
    assert merged.is_rejected
    assert a.with_rejected().kind is TxnKind.READ


def test_cluster_batch_window_parity(monkeypatch):
    """Delivery-window coalescing under the parity-asserting resolver: the
    batched/prefetched fast path must agree with the cfk walk on every query,
    and actually hit."""
    monkeypatch.setenv("ACCORD_TPU_WALK_MAX", "0")
    monkeypatch.setenv("ACCORD_TPU_WALK_WIDTH", "0")   # exercise vector tiers
    shards = [Shard(Range(k(0), k(1000)), [1, 2, 3])]
    cluster = Cluster(Topology(1, shards), seed=99, resolver="verify",
                      batch_window_us=2_000)
    results = []
    for i in range(24):
        # heavy same-key contention => intra-window interference paths run too
        txn = list_txn([k(5)] if i % 3 == 0 else [],
                       {k(5): f"v{i}", k(600 + (i % 4)): f"w{i}"})
        results.append(cluster.nodes[1 + i % 3].coordinate(txn))
    assert cluster.run_until(lambda: all(r.is_done() for r in results))
    cluster.run_until_idle()
    assert all(r.failure is None for r in results)
    lists = {cluster.stores[n].get(k(5)) for n in cluster.nodes}
    assert len(lists) == 1
    hits = misses = 0
    for n in cluster.nodes:
        for store in cluster.nodes[n].command_stores.all_stores():
            hits += store.resolver.tpu.prefetch_hits
            misses += store.resolver.tpu.prefetch_misses
    assert hits > 20, f"prefetch never hit (hits={hits}, misses={misses})"
