"""Topology change, bootstrap, and epoch machinery on the simulated cluster.

Parity targets: CommandStores.updateTopology (CommandStores.java:402-482),
Bootstrap.java:83-494 (exclusive sync point fence + DataStore.fetch +
bootstrappedAt), TopologyManager epoch sync, TopologyRandomizer.java.
"""
from cassandra_accord_tpu.harness.cluster import Cluster
from cassandra_accord_tpu.harness.topology_randomizer import TopologyRandomizer
from cassandra_accord_tpu.impl.list_store import list_txn
from cassandra_accord_tpu.primitives.keys import IntKey, Range
from cassandra_accord_tpu.topology.topology import Shard, Topology
from cassandra_accord_tpu.utils.random import RandomSource


def k(v):
    return IntKey(v)


def submit_write(cluster, node_id, appends):
    return cluster.nodes[node_id].coordinate(
        list_txn([], {k(key): v for key, v in appends.items()}))


def test_replica_move_bootstraps_data():
    """Node 4 takes over node 3's replica: it must fetch existing data and then
    serve coordinated reads that include it."""
    topo1 = Topology(1, [Shard(Range(k(0), k(1000)), [1, 2, 3])])
    cluster = Cluster(topo1, seed=5, extra_nodes=[4])
    w = submit_write(cluster, 1, {10: "old1", 700: "old2"})
    assert cluster.run_until(w.is_done)
    cluster.run_until_idle()

    topo2 = Topology(2, [Shard(Range(k(0), k(1000)), [1, 2, 4])])
    cluster.update_topology(topo2)
    cluster.run_until_idle()

    # node 4 bootstrapped: fetched pre-existing data
    assert cluster.stores[4].get(k(10)) == ("old1",), cluster.stores[4].data
    assert cluster.stores[4].get(k(700)) == ("old2",)
    # bootstrapped_at recorded
    store4 = cluster.nodes[4].command_stores.all_stores()[0]
    e = store4.redundant_before.entry(k(10).to_routing())
    assert e is not None and e.bootstrapped_at is not None
    assert not store4.pending_bootstrap

    # writes + reads keep working across the new topology
    w2 = submit_write(cluster, 4, {10: "new1"})
    assert cluster.run_until(w2.is_done)
    r = cluster.nodes[2].coordinate(list_txn([k(10)], {}))
    assert cluster.run_until(r.is_done)
    assert r.value.reads[k(10)] == ("old1", "new1")
    cluster.run_until_idle()
    assert cluster.stores[4].get(k(10)) == ("old1", "new1")


def test_writes_during_topology_change_not_lost():
    topo1 = Topology(1, [Shard(Range(k(0), k(1000)), [1, 2, 3])])
    cluster = Cluster(topo1, seed=9, extra_nodes=[4, 5])
    results = [submit_write(cluster, 1 + (i % 3), {5: f"a{i}"}) for i in range(4)]
    # change topology while writes are in flight
    cluster.update_topology(Topology(2, [Shard(Range(k(0), k(1000)), [1, 4, 5])]))
    results += [submit_write(cluster, 1 + (i % 3), {5: f"b{i}"}) for i in range(4)]
    assert cluster.run_until(lambda: all(r.is_done() for r in results),
                             max_tasks=2_000_000)
    cluster.run_until_idle()
    # replicas of the NEW topology agree and contain all 8 values
    lists = {cluster.stores[n].get(k(5)) for n in (1, 4, 5)}
    assert len(lists) == 1, lists
    final = lists.pop()
    assert sorted(final) == sorted([f"a{i}" for i in range(4)] + [f"b{i}" for i in range(4)]), final


def test_split_and_merge_ranges():
    topo1 = Topology(1, [Shard(Range(k(0), k(1000)), [1, 2, 3])])
    cluster = Cluster(topo1, seed=13)
    w = submit_write(cluster, 1, {100: "x", 900: "y"})
    assert cluster.run_until(w.is_done)
    # split
    cluster.update_topology(Topology(2, [
        Shard(Range(k(0), k(500)), [1, 2, 3]),
        Shard(Range(k(500), k(1000)), [1, 2, 3])]))
    cluster.run_until_idle()
    w2 = submit_write(cluster, 2, {100: "x2", 900: "y2"})
    assert cluster.run_until(w2.is_done)
    # merge back
    cluster.update_topology(Topology(3, [Shard(Range(k(0), k(1000)), [1, 2, 3])]))
    cluster.run_until_idle()
    r = cluster.nodes[3].coordinate(list_txn([k(100), k(900)], {}))
    assert cluster.run_until(r.is_done)
    assert r.value.reads[k(100)] == ("x", "x2")
    assert r.value.reads[k(900)] == ("y", "y2")


def test_epoch_sync_tracked():
    topo1 = Topology(1, [Shard(Range(k(0), k(1000)), [1, 2, 3])])
    cluster = Cluster(topo1, seed=17)
    cluster.run_until_idle()
    cluster.update_topology(Topology(2, [Shard(Range(k(0), k(1000)), [1, 2, 3])]))
    cluster.run_until_idle()
    for n in cluster.nodes:
        tm = cluster.nodes[n].topology
        assert tm.current_epoch == 2
        assert tm.is_sync_complete(2), f"node {n} epoch 2 not synced"


def test_randomized_topology_churn_with_traffic():
    """Burn-style: continuous writes while the randomizer mutates topology;
    every write must survive into the final replica sets, consistently."""
    topo1 = Topology(1, [Shard(Range(k(0), k(1000)), [1, 2, 3])])
    cluster = Cluster(topo1, seed=21, extra_nodes=[4, 5])
    randomizer = TopologyRandomizer(cluster, RandomSource(7))
    results = []
    state = {"i": 0}

    def submit_some():
        for _ in range(3):
            i = state["i"]
            state["i"] += 1
            results.append(submit_write(cluster, 1 + (i % 3), {(i * 53) % 997: f"v{i}"}))

    for round_ in range(6):
        submit_some()
        deadline = cluster.now_micros + 400_000
        cluster.run_until(lambda: cluster.now_micros >= deadline, max_tasks=300_000)
        randomizer.maybe_update_topology()
    assert cluster.run_until(lambda: all(r.is_done() for r in results),
                             max_tasks=3_000_000)
    cluster.run_until_idle(max_tasks=3_000_000)

    final_topo = cluster.topologies[-1]
    for i in range(state["i"]):
        key = k((i * 53) % 997)
        shard = next(s for s in final_topo.shards if s.range.contains(key.to_routing()))
        variants = {cluster.stores[n].get(key) for n in shard.nodes}
        assert len(variants) == 1, f"divergence on {key}: {variants}"
        assert f"v{i}" in variants.pop(), f"write v{i} lost on {key}"


def test_burn_with_topology_churn():
    from cassandra_accord_tpu.harness.burn import run_burn
    for seed in (2, 5):
        res = run_burn(seed, ops=100, concurrency=8, topology_churn=True,
                       churn_interval_s=0.3)
        assert res.ops_ok == 100, res


def test_epoch_fetch_watchdog_fails_unobtainable_epoch():
    """An unreachable/never-advancing configuration service must not stall
    epoch-gated work forever: the fetch watchdog retries, then fails the
    waiters (TopologyManager fetch-watchdog capability)."""
    from cassandra_accord_tpu.coordinate.errors import Timeout as AccordTimeout
    shards = [Shard(Range(IntKey(0), IntKey(1000)), [1, 2, 3])]
    cluster = Cluster(Topology(1, shards), seed=5)
    node = cluster.nodes[1]
    got = {}
    node.with_epoch(99).begin(lambda v, f: got.setdefault("f", f))
    assert cluster.run_until(lambda: "f" in got)
    assert isinstance(got["f"], AccordTimeout)
