"""Bootstrap-grade staleness catch-up (Bootstrap.java:83-494 rerun for stale
ranges): a replica whose data plane was stale-marked under a sustained TOTAL
partition must, once peers return, re-enter the bootstrap fetch ladder —
fence, stream, advance bootstrapped_at — instead of retrying the paced
peer-snapshot heal forever (the KNOWN_ISSUES open item)."""
from cassandra_accord_tpu.harness.cluster import Cluster, LinkConfig
from cassandra_accord_tpu.impl.list_store import list_txn
from cassandra_accord_tpu.primitives.keys import IntKey, Range, Ranges
from cassandra_accord_tpu.topology.topology import Shard, Topology
from cassandra_accord_tpu.utils.random import RandomSource


def k(v):
    return IntKey(v)


class SwitchableLinks(LinkConfig):
    """Total partition of one node, switchable at runtime."""

    def __init__(self, rng, isolated: int):
        super().__init__(rng)
        self.isolated = isolated
        self.partitioned = False

    def action(self, from_node: int, to_node: int, message=None) -> str:
        if self.partitioned and self.isolated in (from_node, to_node):
            return LinkConfig.DROP
        return LinkConfig.DELIVER


def test_total_partition_heal_escalates_to_bootstrap_ladder():
    links = SwitchableLinks(RandomSource(7), isolated=3)
    topo = Topology(1, [Shard(Range(k(0), k(1000)), [1, 2, 3])])
    cluster = Cluster(topo, seed=42, link_config=links)

    # committed data everywhere
    writes = [cluster.nodes[1].coordinate(list_txn([], {k(5): f"v{i}"}))
              for i in range(3)]
    assert cluster.run_until(lambda: all(w.is_done() for w in writes))
    cluster.run_until_idle()

    # isolate node 3 and open a data gap on it (the truncated-outcome
    # adoption scenario): stale-mark via the heal entry point
    links.partitioned = True
    node3 = cluster.nodes[3]
    gap = Ranges.of(Range(k(0), k(1000)))
    store3 = node3.command_stores.all_stores()[0]

    def trigger(safe_store):
        from cassandra_accord_tpu.messages.status_messages import \
            _heal_store_gaps
        _heal_store_gaps(node3, safe_store, gap)

    store3.execute(trigger)
    assert cluster.run_until(
        lambda: len(node3.data_store.stale_ranges) > 0, max_tasks=200_000)

    # paced heal rounds exhaust against the partition; the escalation enters
    # the bootstrap ladder (pending_bootstrap marks the footprint)
    assert cluster.run_until(
        lambda: len(store3.pending_bootstrap) > 0, max_tasks=2_000_000), \
        "heal never escalated to the bootstrap ladder"
    # while partitioned, the ladder retries without completing
    assert len(node3.data_store.stale_ranges) > 0

    # partition heals -> the ladder completes: fence coordinated, data
    # streamed from fence-epoch peers, stale + pending marks cleared
    links.partitioned = False
    assert cluster.run_until(
        lambda: len(node3.data_store.stale_ranges) == 0
        and len(store3.pending_bootstrap) == 0, max_tasks=4_000_000), \
        "catch-up never completed after the partition healed"
    # bootstrapped_at advanced over the footprint (the fence fences the past)
    e = store3.redundant_before.entry(k(5).to_routing())
    assert e is not None and e.bootstrapped_at is not None
    # and the data plane is whole again: every committed write present
    assert set(node3.data_store.get(k(5))) == {"v0", "v1", "v2"}


def test_catch_up_fetch_refuses_without_sources():
    """catch_up=True must never report 'trivially complete' when no peer is
    reachable in the plan (the data exists; we lost it)."""
    topo = Topology(1, [Shard(Range(k(0), k(1000)), [1])])
    cluster = Cluster(topo, seed=3)
    node = cluster.nodes[1]
    store = node.command_stores.all_stores()[0]

    failures = []

    class FR:
        def fetched(self, ranges):
            failures.append(("fetched", ranges))

        def fail(self, failure):
            failures.append(("fail", failure))

    class FakeSyncPoint:
        from cassandra_accord_tpu.primitives.timestamp import (Domain, TxnId,
                                                               TxnKind)
        txn_id = TxnId(epoch=1, hlc=99, node=1,
                       kind=TxnKind.EXCLUSIVE_SYNC_POINT, domain=Domain.RANGE)

    def run(safe_store):
        node.data_store.fetch(node, safe_store,
                              Ranges.of(Range(k(0), k(1000))),
                              FakeSyncPoint(), FR(), catch_up=True)

    store.execute(run)
    cluster.run_until(lambda: len(failures) > 0, max_tasks=100_000)
    assert failures and failures[0][0] == "fail"
