"""Plane-2 wall-clock profiler (observe/profiler.py).

Contract 1 — the PR-3 byte-identity proof EXTENDED: a same-seed hostile
burn with the profiler on vs off leaves the flight-recorder trace
byte-identical (the profiler reads wall clocks but may never perturb the
sim).  Contract 2 — the three measurement planes (handler CPU, event-loop
occupancy/queue depth, device launches) actually measure.
"""
import json

from cassandra_accord_tpu.harness.burn import run_burn
from cassandra_accord_tpu.harness.trace import Trace, diff_traces
from cassandra_accord_tpu.observe import (FlightRecorder, WallProfiler,
                                          format_wall_profile,
                                          validate_chrome_trace)
from cassandra_accord_tpu.observe.export import WALL_PID

HOSTILE = dict(ops=40, concurrency=8, chaos=True, allow_failures=True,
               durability=True, journal=True, delayed_stores=True,
               clock_drift=True, max_tasks=3_000_000)


def test_profiler_zero_observer_effect():
    """Recorder byte-identity with the profiler on vs off (same-seed hostile
    burn): the wall plane must not perturb the deterministic plane."""
    ta, tb = Trace(), Trace()
    bare = run_burn(9, tracer=ta.hook, **HOSTILE)
    rec = FlightRecorder()
    prof = WallProfiler()
    profiled = run_burn(9, tracer=tb.hook, observer=rec, profiler=prof,
                        **HOSTILE)
    divergence = diff_traces(ta, tb)
    assert divergence is None, \
        f"wall profiler perturbed the simulation:\n{divergence}"
    assert (bare.ops_ok, bare.ops_recovered, bare.ops_nacked, bare.ops_lost,
            bare.ops_failed, bare.sim_micros) == \
           (profiled.ops_ok, profiled.ops_recovered, profiled.ops_nacked,
            profiled.ops_lost, profiled.ops_failed, profiled.sim_micros)
    # and the profiler DID measure while staying invisible
    assert prof.tasks > 0 and prof.busy_s > 0
    assert prof.handlers, "no handler timings recorded"


def test_handler_timings_and_scheduler_occupancy():
    rec = FlightRecorder()
    prof = WallProfiler()
    result = run_burn(11, ops=30, concurrency=6, observer=rec, profiler=prof)
    assert result.ops_ok == 30
    report = prof.report()
    json.dumps(report)
    assert report["time_plane"] == "wall_s"
    # per-message-type handler CPU: the protocol's core verbs all appear
    names = set(prof.handlers)
    assert {"PreAccept", "Commit", "Apply"} <= names, names
    for row in report["handlers"].values():
        assert row["count"] > 0
    sch = report["scheduler"]
    assert sch["tasks"] > 0
    assert 0.0 < sch["occupancy"] <= 1.0
    assert sch["queue_depth"]["samples"] > 0
    assert sch["queue_depth"]["max"] >= sch["queue_depth"]["p50"]
    # handler CPU is a subset of loop busy time
    assert report["handler_total_s"] <= sch["busy_s"] * 1.05
    # resolver wall counters were pulled (cpu resolver has none: 0.0 is fine)
    assert report["device"]["consult_wall_s"] >= 0.0
    text = format_wall_profile(report, label="t")
    assert "occupancy" in text and "PreAccept" in text


def test_wall_tracks_and_flow_events_in_trace():
    """The Perfetto export grows wall-clock handler tracks (pid WALL_PID)
    and per-txn flow events linking sim spans to the host slices that
    served them — all schema-valid."""
    rec = FlightRecorder()
    prof = WallProfiler()
    run_burn(11, ops=30, concurrency=6, observer=rec, profiler=prof)
    doc = rec.chrome_trace(profiler=prof)
    assert validate_chrome_trace(doc) == []
    events = doc["traceEvents"]
    wall = [e for e in events if e.get("cat") == "wall_handler"]
    assert wall and all(e["pid"] == WALL_PID and e["ph"] == "X"
                        for e in wall)
    flows = [e for e in events if e["ph"] in ("s", "t", "f")]
    assert flows, "no flow events linking sim spans to wall slices"
    by_id = {}
    for e in flows:
        assert e["id"]
        by_id.setdefault(e["id"], []).append(e["ph"])
    for phases in by_id.values():
        # every flow has exactly one start (on the sim plane) and one finish
        # (on the wall plane); document order is globally ts-sorted across
        # the two time bases, so only the multiset is asserted
        assert phases.count("s") == 1 and phases.count("f") == 1
    starts = [e for e in flows if e["ph"] == "s"]
    assert all(e["pid"] != WALL_PID for e in starts)
    # the wall process is named in metadata
    assert any(e["ph"] == "M" and e["pid"] == WALL_PID
               and "wall" in e["args"]["name"] for e in events)
    # without a profiler the trace is unchanged-shape and still valid
    assert validate_chrome_trace(rec.chrome_trace()) == []


def test_validate_rejects_flow_event_without_id():
    bad = {"name": "serves", "cat": "txnflow", "ph": "s", "ts": 1,
           "pid": 1, "tid": 0}
    assert validate_chrome_trace({"traceEvents": [bad]})
    ok = dict(bad, id="flow-1")
    assert validate_chrome_trace({"traceEvents": [ok]}) == []


def test_device_launch_breakdown():
    """The device-service launch hooks: per-launch RTT, transfer bytes, and
    compile events (new jit shapes) reach the profiler when the owning node
    carries one."""
    import numpy as np
    from types import SimpleNamespace
    from bench import _bare_service_resolver
    from cassandra_accord_tpu.device_service.service import DeviceConsultService
    t, k = 256, 32
    rng = np.random.default_rng(3)
    key_inc = np.zeros((t, k), dtype=np.int8)
    for i in range(t):
        key_inc[i, rng.choice(k, 2, replace=False)] = 1
    lanes = np.zeros((t, 5), dtype=np.int32)
    lanes[:, 0] = 1
    lanes[:, 2] = 1000 + np.arange(t)
    kind = np.zeros(t, dtype=np.int8)
    status = np.full(t, 2, dtype=np.int8)
    active = np.ones(t, dtype=bool)
    r = _bare_service_resolver(key_inc, lanes, kind, status, active)
    prof = WallProfiler()
    r.store = SimpleNamespace(node=SimpleNamespace(profiler=prof,
                                                   now_micros=lambda: 0))
    svc = DeviceConsultService(r, config=r.config)
    svc.begin_window()
    fut = svc.submit([0, 1], (1, 0, 5000, 0, 1), 0)
    fut.result()
    svc.end_window()
    assert prof.launches >= 1
    assert prof.launch_wall_s > 0
    assert prof.h2d_bytes > 0 and prof.d2h_bytes > 0
    assert prof.compile_events >= 1       # first launch compiled its shape
    report = prof.report()["device"]
    assert report["dispatch_mean_ms"] > 0
    assert report["kernel_ms_p50"] is not None
    assert report["launch_mfu_vs_275tflops"] >= 0
    json.dumps(report)
