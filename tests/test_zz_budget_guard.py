"""Tier-1 wall-clock budget guard.

The verify pipeline runs the default test selection under a hard
``timeout -k 10 870`` (ROADMAP "Tier-1 verify").  A selection that creeps
past the budget dies as an opaque timeout kill — mid-file, with no signal
about WHICH additions ate the margin.  This file sorts LAST in the default
alphabetical collection order (``-p no:randomly``), so by the time it runs
every other tier-1 test has finished: asserting on the elapsed session
wall-clock here turns budget creep into a loud, attributable test failure
while there is still margin to act on.

The threshold leaves headroom below the 870s ceiling for collection,
interpreter startup, and machine variance; the measured post-round-9
baseline is ~230-260s (seed baseline 207s + the seed-6 regression burn and
the membership suite).  Round-13 headroom re-check: the history-checker +
workload + maelstrom-cross-check additions cost ~35s (mutation tests are
milliseconds; the hostile-burn integration tests and the spawn-pool sweep
dominate), with the soak presets (10k-op Zipf, open-loop soak, the seeds
0-9 acceptance matrix) gated behind ACCORD_LONG_BURNS + ``-m 'not slow'``.
"""
import os
import time

# 870s hard ceiling minus margin for startup/teardown/variance.  If this
# fires: profile `--durations=20`, then either speed up the new tests or
# gate the heavyweight ones behind ACCORD_LONG_BURNS.
TIER1_BUDGET_S = 870
GUARD_THRESHOLD_S = 700


def test_zz_perfgate_smoke_report(capsys, monkeypatch, tmp_path):
    """Every verify run PRINTS (never gates) the commit-latency budget
    deltas vs BASELINE.json — tools/perfgate.py --smoke wired into the
    tier-1 tail.  The gated mode (bench.py --gate, exit-nonzero semantics)
    is covered by tests/test_perfgate.py; here a regression only shows up
    in the log, so budget creep is visible on every verify without making
    tier-1 flaky.  The trend-ledger append goes to a tmp path: a test run
    must not dirty the checked-in BENCH_HISTORY.jsonl (real bench/gate
    runs, not pytest invocations, grow the repo ledger)."""
    monkeypatch.setenv("ACCORD_BENCH_HISTORY", str(tmp_path / "h.jsonl"))
    from tools import perfgate
    with capsys.disabled():   # the report IS the point: keep it in the log
        print()
        rc = perfgate.run(gate=False)
    assert rc == 0   # print-only mode never fails the build


def test_tier1_selection_within_wall_clock_budget(request):
    if os.environ.get("ACCORD_LONG_BURNS"):
        # the gated long-burn selection is hours-class by design
        return
    t0 = getattr(request.config, "_accord_session_t0", None)
    if t0 is None:
        # collected without the repo conftest (exotic invocation): no stamp
        return
    elapsed = time.monotonic() - t0
    assert elapsed < GUARD_THRESHOLD_S, (
        f"tier-1 selection took {elapsed:.0f}s before the budget guard ran — "
        f"within {TIER1_BUDGET_S - elapsed:.0f}s of the verify pipeline's "
        f"{TIER1_BUDGET_S}s hard timeout.  Profile with --durations=20 and "
        f"trim or gate (ACCORD_LONG_BURNS) the heavyweight additions.")
