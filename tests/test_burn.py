"""Burn tests: seeded randomized workloads checked for strict serializability.

Parity target: accord/burn/BurnTest.java run at reduced scale for CI speed; the
verifier itself is exercised against hand-built violating histories.
"""
import pytest

from cassandra_accord_tpu.harness.burn import SimulationException, run_burn
from cassandra_accord_tpu.harness.verifier import (
    HistoryViolation, StrictSerializabilityVerifier,
)
from cassandra_accord_tpu.primitives.keys import IntKey


def k(v):
    return IntKey(v)


# -- verifier unit checks ---------------------------------------------------

def test_verifier_accepts_clean_history():
    v = StrictSerializabilityVerifier()
    a = v.begin(0)
    a.complete(10, {}, {k(1): "x"})
    b = v.begin(20)
    b.complete(30, {k(1): ("x",)}, {k(1): "y"})
    c = v.begin(40)
    c.complete(50, {k(1): ("x", "y")}, {})
    v.verify()


def test_verifier_rejects_prefix_divergence():
    v = StrictSerializabilityVerifier()
    a = v.begin(0)
    a.complete(10, {k(1): ("x", "y")}, {})
    b = v.begin(0)
    b.complete(10, {k(1): ("y", "x", "z")}, {})
    with pytest.raises(HistoryViolation, match="prefix"):
        v.verify()


def test_verifier_rejects_real_time_violation():
    v = StrictSerializabilityVerifier()
    a = v.begin(0)
    a.complete(10, {}, {k(1): "x"})     # completed at 10
    b = v.begin(20)                      # submitted after a completed
    b.complete(30, {k(1): ()}, {})       # ...but does not see x
    with pytest.raises(HistoryViolation, match="real-time"):
        v.verify()


def test_verifier_rejects_fractured_read():
    v = StrictSerializabilityVerifier()
    w = v.begin(0)
    w.complete(100, {}, {k(1): "x", k(2): "y"})
    r = v.begin(0)
    r.complete(100, {k(1): ("x",), k(2): ()}, {})
    with pytest.raises(HistoryViolation, match="fractured"):
        v.verify()


def test_verifier_rejects_unresolved_ops():
    v = StrictSerializabilityVerifier()
    v.begin(0)
    with pytest.raises(HistoryViolation, match="never resolved"):
        v.verify()


def test_verifier_rejects_real_time_write_write_reorder():
    # a's write ordered AFTER b's despite a completing before b was submitted;
    # exercises the write-vs-write branch of the sweep aggregate.
    v = StrictSerializabilityVerifier()
    a = v.begin(0)
    a.complete(10, {}, {k(1): "late"})
    b = v.begin(20)
    b.complete(30, {}, {k(1): "early"})
    c = v.begin(40)
    c.complete(50, {k(1): ("early", "late")}, {})
    with pytest.raises(HistoryViolation, match="real-time"):
        v.verify()


def test_verifier_rejects_unordered_completed_write():
    # a's acked write never appears in any observed order; any later reader of
    # the key is a violation (the 'unordered' aggregate path).
    v = StrictSerializabilityVerifier()
    a = v.begin(0)
    a.complete(10, {}, {k(1): "ghost"})
    b = v.begin(20)
    b.complete(30, {k(1): ("other",)}, {})
    c = v.begin(0)
    c.complete(5, {}, {k(1): "other"})
    with pytest.raises(HistoryViolation, match="real-time"):
        v.verify()


def test_verifier_tied_timestamps_not_self_violating():
    # an op whose complete_time ties another op's submit_time must never be
    # counted against itself by the real-time sweep (zero-duration ops under
    # tied simulated clocks).
    v = StrictSerializabilityVerifier()
    a = v.begin(10)
    a.complete(11, {}, {})
    b = v.begin(10)
    b.complete(10, {k(1): ()}, {k(1): "x"})
    c = v.begin(20)
    c.complete(21, {k(1): ("x",)}, {})
    v.verify()


def test_verifier_self_pair_not_fractured():
    # an op that writes two keys and reads both (not seeing its own writes)
    # must not be flagged against itself by the pair index.
    v = StrictSerializabilityVerifier()
    a = v.begin(0)
    a.complete(10, {k(1): (), k(2): ()}, {k(1): "x", k(2): "y"})
    b = v.begin(20)
    b.complete(30, {k(1): ("x",), k(2): ("y",)}, {})
    v.verify()


def test_verifier_scales_to_5k_ops():
    # regression: the real-time and atomicity checks were O(n^2) pair scans;
    # 5k sequential ops must verify in seconds, not minutes.
    import random as _random
    import time as _time
    rng = _random.Random(7)
    keys = [k(i) for i in range(8)]
    v = StrictSerializabilityVerifier()
    state = {key: [] for key in keys}
    t = 0
    for op in range(5000):
        t += 1
        obs = v.begin(t)
        ks = rng.sample(keys, rng.randint(1, 3))
        reads = {key: tuple(state[key]) for key in ks}
        writes = {}
        for key in ks:
            if rng.random() < 0.5:
                val = (op, key.value)
                state[key].append(val)
                writes[key] = val
        t += 1
        obs.complete(t, reads, writes)
    t0 = _time.time()
    v.verify({key: tuple(s) for key, s in state.items()})
    assert _time.time() - t0 < 10.0


# -- burn runs --------------------------------------------------------------

@pytest.mark.parametrize("seed", [1, 2, 3])
def test_burn_benign_network(seed):
    result = run_burn(seed, ops=60, concurrency=8)
    assert result.ops_ok == 60
    assert result.ops_failed == 0


def test_burn_multi_store(seed=11):
    result = run_burn(seed, ops=40, concurrency=6, num_shards=2)
    assert result.ops_ok == 40


def test_burn_determinism():
    a = run_burn(77, ops=40, concurrency=6)
    b = run_burn(77, ops=40, concurrency=6)
    assert a.sim_micros == b.sim_micros
    assert a.stats == b.stats
