"""Burn tests: seeded randomized workloads checked for strict serializability.

Parity target: accord/burn/BurnTest.java run at reduced scale for CI speed; the
verifier itself is exercised against hand-built violating histories.
"""
import pytest

from cassandra_accord_tpu.harness.burn import SimulationException, run_burn
from cassandra_accord_tpu.harness.verifier import (
    HistoryViolation, StrictSerializabilityVerifier,
)
from cassandra_accord_tpu.primitives.keys import IntKey


def k(v):
    return IntKey(v)


# -- verifier unit checks ---------------------------------------------------

def test_verifier_accepts_clean_history():
    v = StrictSerializabilityVerifier()
    a = v.begin(0)
    a.complete(10, {}, {k(1): "x"})
    b = v.begin(20)
    b.complete(30, {k(1): ("x",)}, {k(1): "y"})
    c = v.begin(40)
    c.complete(50, {k(1): ("x", "y")}, {})
    v.verify()


def test_verifier_rejects_prefix_divergence():
    v = StrictSerializabilityVerifier()
    a = v.begin(0)
    a.complete(10, {k(1): ("x", "y")}, {})
    b = v.begin(0)
    b.complete(10, {k(1): ("y", "x", "z")}, {})
    with pytest.raises(HistoryViolation, match="prefix"):
        v.verify()


def test_verifier_rejects_real_time_violation():
    v = StrictSerializabilityVerifier()
    a = v.begin(0)
    a.complete(10, {}, {k(1): "x"})     # completed at 10
    b = v.begin(20)                      # submitted after a completed
    b.complete(30, {k(1): ()}, {})       # ...but does not see x
    with pytest.raises(HistoryViolation, match="real-time"):
        v.verify()


def test_verifier_rejects_fractured_read():
    v = StrictSerializabilityVerifier()
    w = v.begin(0)
    w.complete(100, {}, {k(1): "x", k(2): "y"})
    r = v.begin(0)
    r.complete(100, {k(1): ("x",), k(2): ()}, {})
    with pytest.raises(HistoryViolation, match="fractured"):
        v.verify()


def test_verifier_rejects_unresolved_ops():
    v = StrictSerializabilityVerifier()
    v.begin(0)
    with pytest.raises(HistoryViolation, match="never resolved"):
        v.verify()


# -- burn runs --------------------------------------------------------------

@pytest.mark.parametrize("seed", [1, 2, 3])
def test_burn_benign_network(seed):
    result = run_burn(seed, ops=60, concurrency=8)
    assert result.ops_ok == 60
    assert result.ops_failed == 0


def test_burn_multi_store(seed=11):
    result = run_burn(seed, ops=40, concurrency=6, num_shards=2)
    assert result.ops_ok == 40


def test_burn_determinism():
    a = run_burn(77, ops=40, concurrency=6)
    b = run_burn(77, ops=40, concurrency=6)
    assert a.sim_micros == b.sim_micros
    assert a.stats == b.stats
