"""The perf regression gate (tools/perfgate.py + bench.py --gate).

Acceptance contract: exit 0 on an unchanged tree, nonzero on an injected
2x latency regression.  Only deterministic SIM-time metrics gate (zero CI
flake); wall-clock numbers are print-only.
"""
import copy
import io
import json

import pytest

from tools import perfgate


@pytest.fixture(scope="module")
def measured():
    """One real smoke measurement shared by the gate tests (seconds-class;
    sim metrics are seed-deterministic)."""
    return perfgate.measure_smoke()


def test_baseline_gate_block_recorded():
    base = perfgate.load_baseline()
    assert base is not None, "BASELINE.json has no 'gate' block"
    for key, _thresh in perfgate.GATED_METRICS:
        assert base["sim"].get(key), f"baseline gate block missing {key}"


def test_gate_passes_on_unchanged_tree(measured):
    """The measured sim metrics of the fixed-seed smoke workload equal the
    recorded baseline on an unchanged tree — the gate MUST exit 0."""
    base = perfgate.load_baseline()
    assert measured["sim"] == base["sim"], \
        "smoke sim metrics drifted from BASELINE.json gate block — either " \
        "a real protocol-behavior change (update the PR description and " \
        "re-run tools/perfgate.py --write-baseline) or lost determinism"
    out = io.StringIO()
    rc = perfgate.run(gate=True, current=measured, out=out)
    assert rc == 0, out.getvalue()
    assert "PASS" in out.getvalue()


def test_gate_fails_on_2x_latency_regression(measured):
    doctored = copy.deepcopy(measured)
    for key in ("commit_latency_mean_us", "commit_latency_p95_us"):
        doctored["sim"][key] = round(doctored["sim"][key] * 2, 1)
    out = io.StringIO()
    rc = perfgate.run(gate=True, current=doctored, out=out)
    assert rc == perfgate.EXIT_REGRESSION
    text = out.getvalue()
    assert "REGRESSION" in text and "commit_latency_mean_us" in text
    # print-only mode reports the same regression but never fails the build
    rc = perfgate.run(gate=False, current=doctored, out=io.StringIO())
    assert rc == 0


def test_compare_handles_missing_baseline(measured):
    lines, failures = perfgate.compare(measured, None)
    assert failures == []
    assert any("no baseline" in l for l in lines)


def test_compare_flags_each_gated_metric():
    base = {"sim": {k: 1000.0 for k, _t in perfgate.GATED_METRICS},
            "recorded": "t"}
    cur = {"sim": {k: 1000.0 for k, _t in perfgate.GATED_METRICS},
           "wall": {}}
    for key, thresh in perfgate.GATED_METRICS:
        doctored = copy.deepcopy(cur)
        doctored["sim"][key] = 1000.0 * thresh * 1.01
        _lines, failures = perfgate.compare(doctored, base)
        assert len(failures) == 1 and key in failures[0]
        # just under threshold: clean
        doctored["sim"][key] = 1000.0 * thresh * 0.99
        _lines, failures = perfgate.compare(doctored, base)
        assert failures == []


def test_compare_zero_baseline_is_loud():
    """A zero baseline (or a metric collapsing to 0) must never be a silent
    'not comparable' skip — zero is data, not absence."""
    base = {"sim": {k: 0 for k, _t in perfgate.GATED_METRICS},
            "recorded": "t"}
    cur = {"sim": {k: 5 for k, _t in perfgate.GATED_METRICS}, "wall": {}}
    _lines, failures = perfgate.compare(cur, base)
    assert len(failures) == len(perfgate.GATED_METRICS)
    cur0 = {"sim": {k: 0 for k, _t in perfgate.GATED_METRICS}, "wall": {}}
    _lines, failures = perfgate.compare(cur0, base)
    assert failures == []
    # only a truly absent metric is 'not comparable'
    lines, failures = perfgate.compare({"sim": {}, "wall": {}}, base)
    assert failures == [] and any("not comparable" in l for l in lines)


def test_inject_hook_scales_latency(monkeypatch, measured):
    """ACCORD_PERFGATE_INJECT_LATENCY is the documented self-test hook:
    bench.py --gate under inject=2.0 must exit nonzero (proven end-to-end
    in-process here; tests/test_bench_smoke.py covers the subprocess
    plumbing)."""
    monkeypatch.setenv("ACCORD_PERFGATE_INJECT_LATENCY", "2.0")
    # reuse the recorded measurement, rescaled exactly as measure_smoke would
    doctored = copy.deepcopy(measured)
    inject = 2.0
    for key in ("commit_latency_mean_us", "commit_latency_p95_us"):
        doctored["sim"][key] = round(doctored["sim"][key] * inject, 1)
    rc = perfgate.run(gate=True, current=doctored, out=io.StringIO())
    assert rc == perfgate.EXIT_REGRESSION


def test_summary_is_stable_json(measured):
    doc = json.loads(json.dumps(measured, sort_keys=True))
    assert doc["sim"]["commits"] == perfgate.SMOKE_KW["ops"]
    assert doc["attributed_share"] >= 0.95
