"""The perf regression gate (tools/perfgate.py + bench.py --gate).

Acceptance contract: exit 0 on an unchanged tree, nonzero on an injected
2x latency regression.  Only deterministic SIM-time metrics gate (zero CI
flake); wall-clock numbers are print-only.
"""
import copy
import io
import json

import pytest

from tools import perfgate


@pytest.fixture(scope="module")
def measured():
    """One real smoke measurement shared by the gate tests (seconds-class;
    sim metrics are seed-deterministic)."""
    return perfgate.measure_smoke()


def test_baseline_gate_block_recorded():
    base = perfgate.load_baseline()
    assert base is not None, "BASELINE.json has no 'gate' block"
    for key, _thresh in perfgate.GATED_METRICS:
        assert base["sim"].get(key), f"baseline gate block missing {key}"


def test_gate_passes_on_unchanged_tree(measured):
    """The measured sim metrics of the fixed-seed smoke workload equal the
    recorded baseline on an unchanged tree — the gate MUST exit 0."""
    base = perfgate.load_baseline()
    assert measured["sim"] == base["sim"], \
        "smoke sim metrics drifted from BASELINE.json gate block — either " \
        "a real protocol-behavior change (update the PR description and " \
        "re-run tools/perfgate.py --write-baseline) or lost determinism"
    out = io.StringIO()
    rc = perfgate.run(gate=True, current=measured, out=out)
    assert rc == 0, out.getvalue()
    assert "PASS" in out.getvalue()


def test_gate_fails_on_2x_latency_regression(measured):
    doctored = copy.deepcopy(measured)
    for key in ("commit_latency_mean_us", "commit_latency_p95_us"):
        doctored["sim"][key] = round(doctored["sim"][key] * 2, 1)
    out = io.StringIO()
    rc = perfgate.run(gate=True, current=doctored, out=out)
    assert rc == perfgate.EXIT_REGRESSION
    text = out.getvalue()
    assert "REGRESSION" in text and "commit_latency_mean_us" in text
    # print-only mode reports the same regression but never fails the build
    rc = perfgate.run(gate=False, current=doctored, out=io.StringIO())
    assert rc == 0


def test_compare_handles_missing_baseline(measured):
    lines, failures = perfgate.compare(measured, None)
    assert failures == []
    assert any("no baseline" in l for l in lines)


def test_compare_flags_each_gated_metric():
    base = {"sim": {k: 1000.0 for k, _t in perfgate.GATED_METRICS},
            "recorded": "t"}
    cur = {"sim": {k: 1000.0 for k, _t in perfgate.GATED_METRICS},
           "wall": {}}
    for key, thresh in perfgate.GATED_METRICS:
        doctored = copy.deepcopy(cur)
        doctored["sim"][key] = 1000.0 * thresh * 1.01
        _lines, failures = perfgate.compare(doctored, base)
        assert len(failures) == 1 and key in failures[0]
        # just under threshold: clean
        doctored["sim"][key] = 1000.0 * thresh * 0.99
        _lines, failures = perfgate.compare(doctored, base)
        assert failures == []


def test_compare_zero_baseline_is_loud():
    """A zero baseline (or a metric collapsing to 0) must never be a silent
    'not comparable' skip — zero is data, not absence."""
    base = {"sim": {k: 0 for k, _t in perfgate.GATED_METRICS},
            "recorded": "t"}
    cur = {"sim": {k: 5 for k, _t in perfgate.GATED_METRICS}, "wall": {}}
    _lines, failures = perfgate.compare(cur, base)
    assert len(failures) == len(perfgate.GATED_METRICS)
    cur0 = {"sim": {k: 0 for k, _t in perfgate.GATED_METRICS}, "wall": {}}
    _lines, failures = perfgate.compare(cur0, base)
    assert failures == []
    # only a truly absent metric is 'not comparable'
    lines, failures = perfgate.compare({"sim": {}, "wall": {}}, base)
    assert failures == [] and any("not comparable" in l for l in lines)


def test_inject_hook_scales_latency(monkeypatch, measured):
    """ACCORD_PERFGATE_INJECT_LATENCY is the documented self-test hook:
    bench.py --gate under inject=2.0 must exit nonzero (proven end-to-end
    in-process here; tests/test_bench_smoke.py covers the subprocess
    plumbing)."""
    monkeypatch.setenv("ACCORD_PERFGATE_INJECT_LATENCY", "2.0")
    # reuse the recorded measurement, rescaled exactly as measure_smoke would
    doctored = copy.deepcopy(measured)
    inject = 2.0
    for key in ("commit_latency_mean_us", "commit_latency_p95_us"):
        doctored["sim"][key] = round(doctored["sim"][key] * inject, 1)
    rc = perfgate.run(gate=True, current=doctored, out=io.StringIO())
    assert rc == perfgate.EXIT_REGRESSION


def test_summary_is_stable_json(measured):
    doc = json.loads(json.dumps(measured, sort_keys=True))
    assert doc["sim"]["commits"] == perfgate.SMOKE_KW["ops"]
    assert doc["attributed_share"] >= 0.95


# ---------------------------------------------------------------------------
# multi-seed median gating (--seeds), per the KNOWN_ISSUES trajectory-
# sensitivity note: single-seed regressions are knife-edge chaotic, so the
# gate judges the MEDIAN per-seed current/baseline ratio
# ---------------------------------------------------------------------------

def _synth(seed, scale=1.0):
    return {"workload": {"seed": seed},
            "sim": {k: round(1000.0 * scale, 1)
                    for k, _t in perfgate.GATED_METRICS}}


def _synth_baseline(seeds):
    return {"workload": {"seed": perfgate.SMOKE_SEED},
            "sim": {k: 1000.0 for k, _t in perfgate.GATED_METRICS},
            "recorded": "t",
            "seeds": {str(s): {"sim": {k: 1000.0 for k, _t
                                       in perfgate.GATED_METRICS}}
                      for s in seeds}}


def test_multi_seed_one_chaotic_seed_cannot_trip():
    """One knife-edge seed regressing 3x does NOT trip the gate while the
    median of three seeds stays flat — the whole point of --seeds."""
    base = _synth_baseline([1, 2, 3])
    per_seed = {1: _synth(1), 2: _synth(2), 3: _synth(3, scale=3.0)}
    lines, failures = perfgate.compare_multi(per_seed, base)
    assert failures == [], "\n".join(lines)
    assert any("median 1.000x" in l for l in lines)


def test_multi_seed_median_regression_trips():
    """Two of three seeds regressed past threshold: the median trips, and
    the failure names the metric + seed count."""
    base = _synth_baseline([1, 2, 3])
    per_seed = {1: _synth(1, 2.0), 2: _synth(2, 2.0), 3: _synth(3)}
    _lines, failures = perfgate.compare_multi(per_seed, base)
    assert failures and all("median 2.00x" in f for f in failures)
    assert len(failures) == len(perfgate.GATED_METRICS)


def test_multi_seed_missing_per_seed_baseline_is_not_comparable():
    """A seed with no recorded baseline row is reported loudly as not
    comparable (with the --write-baseline --seeds fix), never silently
    passed; the default smoke seed falls back to the default sim block."""
    base = _synth_baseline([])          # no per-seed table at all
    per_seed = {perfgate.SMOKE_SEED: _synth(perfgate.SMOKE_SEED, 2.0),
                99: _synth(99, 2.0)}
    lines, failures = perfgate.compare_multi(per_seed, base)
    # the default seed compares via the fallback; 99 is flagged uncomparable
    assert failures, "default-seed fallback lost the regression"
    assert any("s99:" in l and "?" in l for l in lines)
    assert perfgate.baseline_sim_for(base, 99) is None
    assert perfgate.baseline_sim_for(base, perfgate.SMOKE_SEED) == base["sim"]


def test_multi_seed_run_measures_each_seed_and_appends_median(
        tmp_path, monkeypatch):
    """run(seeds=[...]) measures every listed seed, gates on the median,
    and appends ONE ledger record carrying the per-metric median sim."""
    ledger = tmp_path / "hist.jsonl"
    monkeypatch.setenv("ACCORD_BENCH_HISTORY", str(ledger))
    measured_seeds = []

    def fake_smoke(seed):
        measured_seeds.append(seed)
        return _synth(seed, scale={1: 0.9, 2: 1.0, 3: 1.1}[seed])
    monkeypatch.setattr(perfgate, "measure_smoke", fake_smoke)
    out = io.StringIO()
    rc = perfgate.run(gate=True, current=None, out=out, seeds=[1, 2, 3])
    assert rc == 0 and measured_seeds == [1, 2, 3]
    assert "gating on the MEDIAN" in out.getvalue()
    entries = [json.loads(l) for l in ledger.read_text().splitlines()]
    assert len(entries) == 1
    assert entries[0]["kind"] == "perfgate" and entries[0]["seeds"] == [1, 2, 3]
    for key, _t in perfgate.GATED_METRICS:
        assert entries[0]["sim"][key] == 1000.0   # the median (scale 1.0)


def test_single_listed_seed_is_measured_as_that_seed(tmp_path, monkeypatch):
    """--seeds with ONE seed measures THAT seed — never silently replaced
    by the default smoke seed (a seed-specific regression must not be
    gated against the wrong trajectory).  Baseline is synthetic so the
    assertion is about seed ROUTING, not the real tree's values."""
    monkeypatch.setenv("ACCORD_BENCH_HISTORY", str(tmp_path / "h.jsonl"))
    monkeypatch.setattr(perfgate, "load_baseline",
                        lambda path=perfgate.BASELINE_PATH:
                        _synth_baseline([23]))
    measured_seeds = []

    def fake_smoke(seed):
        measured_seeds.append(seed)
        return _synth(seed)
    monkeypatch.setattr(perfgate, "measure_smoke", fake_smoke)
    rc = perfgate.run(gate=True, current=None, out=io.StringIO(), seeds=[23])
    assert rc == 0 and measured_seeds == [23]


def test_inject_self_test_never_poisons_the_ledger(tmp_path, monkeypatch):
    """The ACCORD_PERFGATE_INJECT_LATENCY self-test doctors the measured
    latencies — its run must NOT append to BENCH_HISTORY.jsonl, where it
    would read as a real 2x regression in every later trend report."""
    ledger = tmp_path / "h.jsonl"
    monkeypatch.setenv("ACCORD_BENCH_HISTORY", str(ledger))
    monkeypatch.setenv("ACCORD_PERFGATE_INJECT_LATENCY", "2.0")
    monkeypatch.setattr(perfgate, "measure_smoke", lambda seed=7: _synth(seed))
    perfgate.run(gate=True, current=None, out=io.StringIO())
    perfgate.run(gate=True, current=None, out=io.StringIO(), seeds=[1])
    assert not ledger.exists(), "inject run leaked into the trend ledger"
    # and a clean run still appends
    monkeypatch.setenv("ACCORD_PERFGATE_INJECT_LATENCY", "1.0")
    perfgate.run(gate=True, current=None, out=io.StringIO())
    assert len(ledger.read_text().splitlines()) == 1


def test_write_baseline_refuses_under_inject(monkeypatch, tmp_path):
    """--write-baseline under the inject hook would record doctored
    latencies as the baseline and silently defeat the gate forever —
    it must refuse loudly."""
    monkeypatch.setenv("ACCORD_PERFGATE_INJECT_LATENCY", "2.0")
    with pytest.raises(RuntimeError, match="refusing --write-baseline"):
        perfgate.write_baseline(str(tmp_path / "b.json"))


def test_current_and_seeds_are_mutually_exclusive(measured):
    """A saved --current artifact is one seed's measurement; combining it
    with --seeds must fail loudly instead of silently re-measuring live."""
    with pytest.raises(ValueError, match="mutually exclusive"):
        perfgate.run(gate=True, current=measured, out=io.StringIO(),
                     seeds=[1, 2])
    with pytest.raises(SystemExit):
        perfgate.main(["--current", "x.json", "--seeds", "1,2"])
