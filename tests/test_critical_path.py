"""Plane-1 critical-path latency attribution (observe/critical_path.py).

The core contract: every committed txn's [submit, resolve] window is
partitioned EXACTLY into segments, each attributed to one of the closed
class set, and the hand-built synthetic trace below has a known dominating
chain whose segment classes and durations the extractor must reproduce to
the microsecond.
"""
import json

from cassandra_accord_tpu.harness.burn import run_burn
from cassandra_accord_tpu.observe import (FlightRecorder, SEGMENT_CLASSES,
                                          extract_critical_paths,
                                          format_budget, latency_budget)
from cassandra_accord_tpu.observe.critical_path import extract_txn_path


class PreAccept:
    """Stand-in whose class NAME is what the message timeline records
    (harness.trace._brief -> "PreAccept(<txn_id>)")."""

    def __init__(self, txn_id):
        self.txn_id = txn_id


def _synthetic_recorder():
    """One txn with a hand-built causal chain:

    0      submit (coordinator 1)
    3000   PreAccept delivered at node 2          -> fan-out message wait
    5000   first PRE_ACCEPTED (node 2)            -> replica queue wait
    7000   last  PRE_ACCEPTED (node 3)            -> quorum gather
    12000  COMMITTED + STABLE (node 2)            -> decision wait
    30000  READY_TO_EXECUTE (node 2)              -> deps/execute wait
    31000  APPLIED (node 2)                       -> apply (handler compute)
    33000  resolve                                -> ack
    """
    rec = FlightRecorder()
    t = "tx-1"
    rec.on_submit(0, t, coordinator=1, now_us=0)
    rec.on_message_event("RECV", 1, 2, 77, PreAccept(t), 3000)
    rec.on_transition(2, 0, t, "PRE_ACCEPTED", 5000)
    rec.on_transition(3, 0, t, "PRE_ACCEPTED", 7000)
    rec.on_transition(2, 0, t, "COMMITTED", 12000)
    rec.on_transition(2, 0, t, "STABLE", 12000)
    rec.on_transition(2, 0, t, "READY_TO_EXECUTE", 30000)
    rec.on_transition(2, 0, t, "APPLYING", 30000)
    rec.on_transition(2, 0, t, "APPLIED", 31000)
    rec.on_path(t, "fast")
    rec.on_resolve(t, "ok", 33000)
    return rec


def test_synthetic_chain_exact_segments():
    rec = _synthetic_recorder()
    paths = extract_critical_paths(rec)
    assert len(paths) == 1
    path = paths[0]
    assert path.total_us == 33000
    got = [(s.phase, s.cls, s.start_us, s.dur_us) for s in path.segments]
    assert got == [
        ("preaccept_fanout", "message_wait", 0, 3000),
        ("preaccept_queue", "replica_queue_wait", 3000, 2000),
        ("preaccept_quorum_gather", "message_wait", 5000, 2000),
        ("decision_wait", "message_wait", 7000, 5000),
        ("deps_execute_wait", "deps_wait", 12000, 18000),
        ("apply", "handler_compute", 30000, 1000),
        ("ack", "message_wait", 31000, 2000),
    ]
    # the partition is exact: segments tile [submit, resolve] with no gaps
    assert sum(s.dur_us for s in path.segments) == path.total_us
    by_class = path.by_class()
    assert by_class["deps_wait"] == 18000          # the dominating class
    assert by_class["message_wait"] == 3000 + 2000 + 5000 + 2000
    assert by_class["replica_queue_wait"] == 2000
    assert by_class["handler_compute"] == 1000
    assert "unattributed" not in by_class


def test_synthetic_budget_report():
    rec = _synthetic_recorder()
    report = latency_budget(rec)
    assert report["txns"] == 1
    assert report["mean_commit_latency_us"] == 33000
    assert report["attributed_share"] == 1.0
    assert report["dominating_class"] == "deps_wait"
    assert report["dominating_share"] == round(18000 / 33000, 4)
    assert report["top"][0]["class"] == "deps_wait"
    # classes use the closed vocabulary; JSON-serializable end to end
    assert set(report["classes"]) <= set(SEGMENT_CLASSES)
    json.dumps(report)
    text = format_budget(report, label="synthetic")
    assert "deps_wait" in text and "100.0% attributed" in text


def test_no_message_timeline_folds_queue_into_fanout():
    """Without the PreAccept RECV event the fan-out leg absorbs the replica
    queue wait — total attribution unchanged."""
    rec = _synthetic_recorder()
    rec._message_trace.events.clear()
    paths = extract_critical_paths(rec)
    segs = {s.phase: s for s in paths[0].segments}
    assert "preaccept_queue" not in segs
    assert segs["preaccept_fanout"].dur_us == 5000
    assert sum(s.dur_us for s in paths[0].segments) == 33000


def test_bootstrap_landing_classified_fence_wait():
    """A store that never pre-accepted the txn (first observation already
    decided: bootstrap/fetch landing) and applies LAST makes the execute
    wait fence/bootstrap-class."""
    rec = FlightRecorder()
    t = "tx-boot"
    rec.on_submit(0, t, coordinator=1, now_us=0)
    rec.on_transition(2, 0, t, "PRE_ACCEPTED", 1000)
    rec.on_transition(2, 0, t, "STABLE", 2000)
    rec.on_transition(2, 0, t, "APPLIED", 3000)
    # node 3 learned it decided (no PRE_ACCEPTED) and applied much later
    rec.on_transition(3, 0, t, "STABLE", 2000)
    rec.on_transition(3, 0, t, "APPLIED", 50000)
    rec.on_path(t, "slow")
    rec.on_resolve(t, "ok", 51000)
    path = extract_critical_paths(rec)[0]
    by_class = path.by_class()
    assert by_class.get("fence_bootstrap_wait", 0) == 48000
    assert sum(s.dur_us for s in path.segments) == 51000


def test_recovery_classification():
    """Recovery-attributed txns charge the decision phase (and a recovered
    outcome the probe ack) to the recovery class."""
    rec = FlightRecorder()
    t = "tx-rec"
    rec.on_submit(0, t, coordinator=1, now_us=0)
    rec.on_transition(2, 0, t, "PRE_ACCEPTED", 1000)
    rec.on_recovery(2, t, now_us=5000)
    rec.on_transition(2, 0, t, "COMMITTED", 20000)
    rec.on_transition(2, 0, t, "STABLE", 20000)
    rec.on_transition(2, 0, t, "APPLIED", 21000)
    rec.on_resolve(t, "recovered", 40000)
    path = extract_critical_paths(rec)[0]
    by_class = path.by_class()
    # decision (1000->20000) and the probe ack (21000->40000) are recovery
    assert by_class["recovery"] == 19000 + 19000
    assert sum(s.dur_us for s in path.segments) == 40000


def test_span_with_no_replica_evidence():
    rec = FlightRecorder()
    rec.on_submit(0, "tx-ghost", coordinator=1, now_us=0)
    rec.on_resolve("tx-ghost", "recovered", 9000)
    path = extract_critical_paths(rec)[0]
    assert [(s.phase, s.cls) for s in path.segments] == [("opaque", "recovery")]
    # a non-commit outcome contributes nothing to the budget
    rec.on_submit(1, "tx-lost", coordinator=1, now_us=0)
    rec.on_resolve("tx-lost", "lost", 5000)
    assert len(extract_critical_paths(rec)) == 1


def test_unresolved_span_excluded():
    rec = _synthetic_recorder()
    rec.on_submit(1, "tx-open", coordinator=1, now_us=100)
    assert extract_txn_path(rec.spans.spans["tx-open"]) is None
    assert latency_budget(rec)["txns"] == 1


def test_real_burn_budget_attributes_95_percent():
    """The acceptance bar on a real (benign) burn: >=95% of mean commit
    latency lands in named classes, the partition is exact per txn, and the
    report is stable JSON."""
    rec = FlightRecorder()
    result = run_burn(11, ops=30, concurrency=6, delayed_stores=True,
                      observer=rec)
    report = latency_budget(rec)
    assert report["txns"] == result.ops_ok == 30
    assert report["attributed_share"] >= 0.95
    assert report["dominating_class"] in SEGMENT_CLASSES
    for path in extract_critical_paths(rec):
        assert sum(s.dur_us for s in path.segments) == path.total_us
    json.dumps(report)
    # delayed stores inject executor queueing: the replica-queue class must
    # actually receive attribution on this configuration
    assert report["classes"].get("replica_queue_wait", {"total_us": 0})[
        "total_us"] > 0


def test_hostile_burn_budget_attributes_95_percent():
    """Same bar under the hostile matrix (recoveries, probes, retries)."""
    rec = FlightRecorder()
    run_burn(9, ops=40, concurrency=8, chaos=True, allow_failures=True,
             durability=True, journal=True, delayed_stores=True,
             clock_drift=True, max_tasks=3_000_000, observer=rec)
    report = latency_budget(rec)
    assert report["txns"] > 0
    assert report["attributed_share"] >= 0.95
    json.dumps(report)
