"""Registry parity: every MessageType is produced by a real message class,
and the new standalone rounds (GetMaxConflict, InformHomeDurable, Propagate)
work end to end.
"""
import importlib
import inspect

from cassandra_accord_tpu.harness.cluster import Cluster
from cassandra_accord_tpu.impl.list_store import list_txn
from cassandra_accord_tpu.messages import base
from cassandra_accord_tpu.primitives.keys import IntKey, Range, RoutingKeys
from cassandra_accord_tpu.primitives.route import Route
from cassandra_accord_tpu.primitives.timestamp import Domain, TxnKind
from cassandra_accord_tpu.topology.topology import Shard, Topology

_MODULES = ["base", "txn_messages", "recovery_messages", "status_messages",
            "durability_messages", "ephemeral_messages", "fetch_messages",
            "deps_messages"]


def _covered_types():
    covered = set()
    for name in _MODULES:
        mod = importlib.import_module(f"cassandra_accord_tpu.messages.{name}")
        for cls in vars(mod).values():
            if not (inspect.isclass(cls) and issubclass(cls, base.Message)
                    and cls.__module__ == mod.__name__):
                continue
            multi = getattr(cls, "MESSAGE_TYPES", None)
            if multi:
                covered.update(multi)
                continue
            prop = inspect.getattr_static(cls, "type", None)
            if isinstance(prop, property):
                try:
                    t = prop.fget(object.__new__(cls))
                    if isinstance(t, base.MessageType):
                        covered.add(t)
                except Exception:  # noqa: BLE001 — instance-dependent type
                    pass
    return covered


# message classes whose .type depends on instance state declare MESSAGE_TYPES;
# these are the remaining instance-dependent ones, enumerated here so a NEW
# enum member without an implementation fails the test
_DYNAMIC = {
    "Commit": ["COMMIT_SLOW_PATH_REQ", "COMMIT_MAXIMAL_REQ",
               "STABLE_FAST_PATH_REQ", "STABLE_SLOW_PATH_REQ",
               "STABLE_MAXIMAL_REQ"],
    "Apply": ["APPLY_MINIMAL_REQ", "APPLY_MAXIMAL_REQ"],
    "AcceptInvalidate": ["BEGIN_INVALIDATE_REQ"],
    "WaitOnCommit": ["RECOVER_AWAIT_REQ"],
}


def test_every_message_type_is_implemented():
    covered = {t.name for t in _covered_types()}
    for names in _DYNAMIC.values():
        covered.update(names)
    missing = [t.name for t in base.MessageType if t.name not in covered]
    assert not missing, f"MessageTypes with no implementing class: {missing}"


def _cluster():
    shards = [Shard(Range(IntKey(0), IntKey(1000)), [1, 2, 3])]
    cluster = Cluster(Topology(1, shards), seed=31)
    results = [cluster.nodes[1].coordinate(
        list_txn([IntKey(5)], {IntKey(5): f"v{i}"})) for i in range(4)]
    assert cluster.run_until(lambda: all(r.is_done() for r in results))
    cluster.run_until_idle()
    return cluster


def test_fetch_max_conflict_round():
    from cassandra_accord_tpu.coordinate.collect_deps import fetch_max_conflict
    cluster = _cluster()
    node = cluster.nodes[2]
    rk = IntKey(5).to_routing()
    probe = node.next_txn_id(TxnKind.WRITE, Domain.KEY)
    route = Route.for_keys(rk, RoutingKeys.of([rk]))
    got = fetch_max_conflict(node, probe, route, [IntKey(5)])
    assert cluster.run_until(lambda: got.is_done())
    assert got.failure is None and got.value is not None
    # a fresh key conflicts with nothing
    rk2 = IntKey(900).to_routing()
    got2 = fetch_max_conflict(node, probe, Route.for_keys(
        rk2, RoutingKeys.of([rk2])), [IntKey(900)])
    assert cluster.run_until(lambda: got2.is_done())
    assert got2.failure is None and got2.value is None


def test_inform_home_durable_stats():
    cluster = _cluster()
    # the persist path broadcasts InformHomeDurable to the home shard
    assert cluster.stats.get("InformHomeDurable", 0) > 0


def test_propagate_is_a_first_class_request():
    """fetch_data applies fetched knowledge via a Propagate request: a typed,
    wire-serializable message (applied synchronously on self-delivery), whose
    PROPAGATE_* type reflects the knowledge tier it carries."""
    from cassandra_accord_tpu.coordinate.fetch_data import fetch_data
    from cassandra_accord_tpu.maelstrom import codec
    from cassandra_accord_tpu.messages.status_messages import (CheckStatusOk,
                                                               Propagate)
    cluster = _cluster()
    node = cluster.nodes[3]
    # pick an applied txn id from node 1's store
    store = next(iter(cluster.nodes[1].command_stores.all_stores()))
    txn_id = next(iter(store.commands))
    cmd = store.commands[txn_id]
    got = fetch_data(node, txn_id, cmd.route)
    assert cluster.run_until(lambda: got.is_done())
    assert got.failure is None
    # typed + serializable round trip
    prop = Propagate(txn_id, CheckStatusOk.of(txn_id, cmd))
    assert prop.type is base.MessageType.PROPAGATE_APPLY_MSG
    rt = codec.loads(codec.dumps(prop))
    assert isinstance(rt, Propagate) and rt.type is prop.type
    assert rt.merged.save_status is prop.merged.save_status
