"""Maelstrom adapter: codec round-trips, in-process simulator, stdio binary.

Parity targets: accord-maelstrom Json.java (full wire codec), Main.java serve loop,
maelstrom/Cluster.java (random delays + partitions), Runner/SimpleRandomTest.
"""
import json
import os
import subprocess
import sys

import pytest

from cassandra_accord_tpu.maelstrom import codec
from cassandra_accord_tpu.maelstrom.node import TopologyFactory, parse_txn
from cassandra_accord_tpu.maelstrom.runner import MaelstromCluster, run_workload
from cassandra_accord_tpu.impl.list_store import list_txn
from cassandra_accord_tpu.primitives.deps import DepsBuilder
from cassandra_accord_tpu.primitives.keys import IntKey, Range, Ranges
from cassandra_accord_tpu.primitives.timestamp import (Ballot, Domain, Timestamp,
                                                       TxnId, TxnKind)


def tid(hlc, node=1, kind=TxnKind.WRITE):
    return TxnId(1, hlc, node, kind, domain=Domain.KEY)


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------

def test_codec_primitives_round_trip():
    for obj in (tid(42), Ballot(1, 7, 3), Timestamp(2, 9, 1),
                IntKey(5), Range(IntKey(0), IntKey(10)),
                Ranges.of(Range(IntKey(0), IntKey(10)), Range(IntKey(20), IntKey(30)))):
        back = codec.loads(codec.dumps(obj))
        assert repr(back) == repr(obj)
        assert type(back) is type(obj)


def test_codec_deps_round_trip():
    b = DepsBuilder()
    b.add(IntKey(5).to_routing(), tid(1))
    b.add(IntKey(5).to_routing(), tid(2, kind=TxnKind.READ))
    b.add(Range(IntKey(0), IntKey(100)),
          TxnId(1, 3, 2, TxnKind.EXCLUSIVE_SYNC_POINT, Domain.RANGE))
    deps = b.build()
    back = codec.loads(codec.dumps(deps))
    assert sorted(map(repr, back.txn_ids())) == sorted(map(repr, deps.txn_ids()))


def test_codec_every_txn_pipeline_message():
    from cassandra_accord_tpu.messages.txn_messages import (
        Accept, Apply, Commit, PreAccept, PreAcceptOk, ReadOk, WaitUntilApplied)
    from cassandra_accord_tpu.local.status import SaveStatus
    from cassandra_accord_tpu.impl.list_store import ListData

    txn = list_txn([IntKey(5)], {IntKey(7): "x"})
    route = txn.to_route()
    full = Ranges.of(Range(IntKey(0), IntKey(1000)))
    partial = txn.slice(full, True)
    t = tid(11)
    b = DepsBuilder()
    b.add(IntKey(5).to_routing(), tid(1))
    deps = b.build()
    writes = partial.execute(t, t.as_timestamp(), None)
    messages = [
        PreAccept(t, route, 1, partial, 1, route=route),
        Accept(t, route, 1, Ballot.ZERO, t.as_timestamp(), partial.keys, deps,
               route=route),
        Commit(t, route, 1, SaveStatus.STABLE, t.as_timestamp(), partial, deps,
               read=True, route=route),
        Apply(t, route, 1, Apply.MINIMAL, t.as_timestamp(), deps, partial,
              writes, None, route=route),
        WaitUntilApplied(t, route, 1),
        PreAcceptOk(t, t.as_timestamp(), deps),
        ReadOk(ListData({IntKey(5): ("a", "b")})),
    ]
    for m in messages:
        s = codec.dumps(m)
        back = codec.loads(s)
        assert type(back) is type(m), (type(back), type(m))
        if hasattr(m, "txn_id"):
            assert back.txn_id == m.txn_id


def test_codec_recovery_and_status_messages():
    from cassandra_accord_tpu.messages.recovery_messages import BeginRecovery
    from cassandra_accord_tpu.messages.status_messages import (CheckStatus,
                                                               CheckStatusOk)
    from cassandra_accord_tpu.local.command import Command
    txn = list_txn([IntKey(5)], {})
    route = txn.to_route()
    t = tid(13)
    partial = txn.slice(Ranges.of(Range(IntKey(0), IntKey(1000))), True)
    m = BeginRecovery(t, route, 1, partial, Ballot(1, 5, 2), route=route)
    back = codec.loads(codec.dumps(m))
    assert back.txn_id == t and back.ballot == m.ballot
    cs = CheckStatus(t, route, 1)
    back2 = codec.loads(codec.dumps(cs))
    assert back2.txn_id == t
    ok = CheckStatusOk.of(t, Command(t), Ranges.EMPTY)
    back3 = codec.loads(codec.dumps(ok))
    assert back3.save_status is ok.save_status


# ---------------------------------------------------------------------------
# topology factory + txn parsing
# ---------------------------------------------------------------------------

def test_topology_factory():
    topo = TopologyFactory.build(["n1", "n2", "n3"])
    assert topo.size == 3
    assert topo.nodes() == frozenset({1, 2, 3})
    for shard in topo.shards:
        assert len(shard.nodes) == 3
    # keys anywhere in the int space land in exactly one shard
    for v in (0, 1, 17, 10**5):
        assert sum(1 for s in topo.shards if s.range.contains(IntKey(v).to_routing())) == 1


def test_parse_txn_multi_append():
    txn, ops = parse_txn([["r", 1, None], ["append", 1, "a"], ["append", 1, "b"]])
    assert txn.is_write()
    from cassandra_accord_tpu.maelstrom.node import MULTI, flatten
    appends = txn.update.appends
    assert flatten(tuple(appends.values())) == ["a", "b"]


# ---------------------------------------------------------------------------
# in-process simulator
# ---------------------------------------------------------------------------

def test_runner_benign_network():
    out = run_workload(1, n_nodes=3, ops=40, partition_interval_s=None)
    assert out["ok"] == 40
    # the Elle-style cross-check ran end-to-end over the adapter's history
    # (every attempt recorded + final-state read-back); an anomaly raises
    assert out["history"]["ops"] == out["history_ops"]
    assert out["final_keys"] >= 1
    assert out["history"]["edges"]["ww"] + out["history"]["edges"]["wr"] > 0


def test_runner_with_partitions():
    for seed in (2, 9):
        out = run_workload(seed, n_nodes=5, ops=40, partition_interval_s=1.5)
        assert out["ok"] == 40
        assert out["history"]["ops"] >= 40   # retries add info ops


# ---------------------------------------------------------------------------
# stdio binary
# ---------------------------------------------------------------------------

def test_stdio_single_node():
    lines = [
        {"src": "c1", "dest": "n1",
         "body": {"type": "init", "msg_id": 1, "node_id": "n1", "node_ids": ["n1"]}},
        {"src": "c1", "dest": "n1",
         "body": {"type": "txn", "msg_id": 2,
                  "txn": [["append", 5, 1], ["r", 5, None]]}},
        {"src": "c1", "dest": "n1",
         "body": {"type": "txn", "msg_id": 3,
                  "txn": [["append", 5, 2], ["r", 5, None]]}},
    ]
    env = dict(os.environ, ACCORD_RESOLVER="cpu")  # no jax cold-start in subprocess
    proc = subprocess.run(
        [sys.executable, "-m", "cassandra_accord_tpu.maelstrom"],
        input="\n".join(json.dumps(l) for l in lines) + "\n",
        capture_output=True, text=True, timeout=60, env=env)
    replies = [json.loads(l) for l in proc.stdout.splitlines()
               if '"dest":"c1"' in l or '"dest": "c1"' in l]
    by_reply = {r["body"].get("in_reply_to"): r["body"] for r in replies}
    assert by_reply[1]["type"] == "init_ok"
    assert by_reply[2]["type"] == "txn_ok"
    assert by_reply[2]["txn"][1] == ["r", 5, []]
    assert by_reply[3]["type"] == "txn_ok"
    assert by_reply[3]["txn"][1] == ["r", 5, [1]]
