"""Property suites for the deps primitives, modeled on the reference's
KeyDepsTest/RangeDepsTest (KeyDepsTest.java:1-619): thousands of generated
cases per invariant, checked against a naive dict model, with shrinking.
"""
from collections import defaultdict

from cassandra_accord_tpu.primitives.deps import Deps, KeyDeps, RangeDeps
from cassandra_accord_tpu.primitives.keys import IntKey, Range, Ranges
from cassandra_accord_tpu.utils import accord_gens as gens
from cassandra_accord_tpu.utils import property as prop


def model_of(pairs):
    m = defaultdict(set)
    for rk, tid in pairs:
        m[rk].add(tid)
    return m


@prop.for_all(gens.key_deps_pairs(), tries=2500)
def test_key_deps_matches_model(pairs):
    """Build + lookup: keys, per-key txn lists (sorted, deduped), contains,
    participants — all equal the naive model."""
    kd = gens.key_deps_from(pairs)
    model = model_of(pairs)
    assert set(kd.keys) == set(model)
    all_ids = set()
    for rk, ids in model.items():
        assert kd.txn_ids_for(rk) == sorted(ids), rk
        all_ids |= ids
    for tid in all_ids:
        assert kd.contains(tid)
        expect = sorted(rk for rk, ids in model.items() if tid in ids)
        assert sorted(kd.participants(tid)) == expect


@prop.for_all(gens.key_deps_pairs(), gens.ranges(), tries=2500)
def test_key_deps_slice_matches_model(pairs, rngs):
    kd = gens.key_deps_from(pairs)
    sliced = kd.slice(rngs)
    model = {rk: ids for rk, ids in model_of(pairs).items()
             if rngs.contains(rk)}
    assert set(sliced.keys) == set(model)
    for rk, ids in model.items():
        assert sliced.txn_ids_for(rk) == sorted(ids)


@prop.for_all(gens.key_deps_pairs(), gens.key_deps_pairs(), tries=2500)
def test_key_deps_merge_matches_model(pairs_a, pairs_b):
    merged = gens.key_deps_from(pairs_a).with_merged(
        gens.key_deps_from(pairs_b))
    model = model_of(pairs_a + pairs_b)
    assert set(merged.keys) == set(model)
    for rk, ids in model.items():
        assert merged.txn_ids_for(rk) == sorted(ids)


@prop.for_all(gens.key_deps_pairs(), gens.txn_ids(), tries=2500)
def test_key_deps_without_matches_model(pairs, bound):
    kd = gens.key_deps_from(pairs).without(lambda t: t < bound)
    model = {rk: {t for t in ids if not t < bound}
             for rk, ids in model_of(pairs).items()}
    model = {rk: ids for rk, ids in model.items() if ids}
    assert set(kd.keys) == set(model)
    for rk, ids in model.items():
        assert kd.txn_ids_for(rk) == sorted(ids)


@prop.for_all(gens.range_deps_pairs(), gens.routing_keys(), tries=2500)
def test_range_deps_stabbing_matches_model(pairs, probe):
    """intersecting txn ids for a key == naive scan (the stabbing query the
    reference backs with CheckpointIntervalArray, RangeDeps.java:74-85)."""
    rd = gens.range_deps_from(pairs)
    expect = set()
    for (start, width), tid in pairs:
        if Range(IntKey(start), IntKey(min(gens.KEY_SPACE, start + width))) \
                .contains(probe):
            expect.add(tid)
    got = set()
    rd.for_each_intersecting_key(probe, got.add)
    assert got == expect


@prop.for_all(gens.key_deps_pairs(), gens.ranges(), gens.ranges(), tries=1500)
def test_key_deps_slice_compose(pairs, r1, r2):
    """slice(a).slice(b) == slice on keys in both (composition law)."""
    kd = gens.key_deps_from(pairs)
    twice = kd.slice(r1).slice(r2)
    model = {rk: ids for rk, ids in model_of(pairs).items()
             if r1.contains(rk) and r2.contains(rk)}
    assert set(twice.keys) == set(model)
    for rk, ids in model.items():
        assert twice.txn_ids_for(rk) == sorted(ids)


def test_property_shrinking_reports_minimal_case():
    """The DSL itself: a failing property shrinks toward a minimal case and
    reports the seed."""
    try:
        @prop.for_all(prop.lists(prop.ints(0, 100), max_size=30), tries=200)
        def prop_no_big(xs):
            assert sum(xs) < 150
        prop_no_big()
    except prop.PropertyFailure as f:
        assert sum(f.shrunk_args[0]) >= 150
        assert len(f.shrunk_args[0]) <= len(f.args[0])
        assert f.seed is not None
    else:
        raise AssertionError("property should have failed")
