"""Crash-restart nemesis: node death mid-protocol, journal-replay rebuild,
peer recovery of a dead coordinator's in-flight txns, and the stall watchdog.

Parity targets: the reference burn's node-restart axis (BurnTest's
journal-backed restarts) — a node's in-memory state is discarded and
reconstructed from its journal (volatile execution state collapses to its
durable tier), then bootstrap/staleness catch-up and peer recovery heal what
the journal predates.  Covers the satellite checklist of ISSUE 1:
journal round-trip per status, PendingQueue idle-accounting hardening,
deterministic coordinator-crash recovery, the watchdog's wait-graph dump,
the restart smoke burn (tier-1) and the gated restart x hostile matrix.
"""
import os
from dataclasses import replace
from types import SimpleNamespace

import pytest

from cassandra_accord_tpu.config import LocalConfig
from cassandra_accord_tpu.harness.burn import SimulationException, run_burn
from cassandra_accord_tpu.harness.cluster import Cluster, LinkConfig, PendingQueue
from cassandra_accord_tpu.harness.journal import _FIELDS, Journal
from cassandra_accord_tpu.harness.watchdog import StallError, StallWatchdog, dump_wait_state
from cassandra_accord_tpu.impl.list_store import list_txn
from cassandra_accord_tpu.local.command import Command, WaitingOn
from cassandra_accord_tpu.local.status import SaveStatus
from cassandra_accord_tpu.maelstrom import codec
from cassandra_accord_tpu.primitives.keys import IntKey, Range
from cassandra_accord_tpu.primitives.timestamp import TxnId
from cassandra_accord_tpu.topology.topology import Shard, Topology
from cassandra_accord_tpu.utils.random import RandomSource


def k(v):
    return IntKey(v)


def make_cluster(seed=1, nodes=(1, 2, 3), link=None, progress_poll_s=0.2):
    shards = [Shard(Range(k(0), k(1000)), list(nodes))]
    cluster = Cluster(Topology(1, shards), seed=seed, link_config=link,
                      journal=True, progress_log=True,
                      progress_poll_s=progress_poll_s)
    return cluster


def find_command(cluster, node_id, txn_id):
    for store in cluster.nodes[node_id].command_stores.all_stores():
        cmd = store.commands.get(txn_id)
        if cmd is not None:
            return cmd
    return None


def restart_config(**overrides):
    return replace(LocalConfig(), **overrides)


# ---------------------------------------------------------------------------
# Satellite 1: journal round-trip per command status
# ---------------------------------------------------------------------------

# restart resumes from the durable tier: transient LocalExecution sub-states
# collapse (round-3 replay contract); everything else survives unchanged
_EXPECTED_COLLAPSE = {
    SaveStatus.READY_TO_EXECUTE: SaveStatus.STABLE,
    SaveStatus.APPLYING: SaveStatus.PRE_APPLIED,
}


def _applied_template():
    """A real APPLIED command (route, definition, deps, writes, result all
    populated by the live protocol) to clone per-status."""
    cluster = make_cluster(seed=9)
    res = cluster.nodes[1].coordinate(list_txn([k(5)], {k(5): "tpl"}))
    assert cluster.run_until(res.is_done)
    cluster.run_until_idle()
    for store in cluster.nodes[1].command_stores.all_stores():
        for cmd in store.commands.values():
            if cmd.save_status is SaveStatus.APPLIED:
                return cmd
    raise AssertionError("no applied command produced")


def _clone_with_status(template, status):
    copy = Command(template.txn_id)
    for f in _FIELDS:
        setattr(copy, f, codec.decode_value(codec.encode_value(getattr(template, f))))
    copy.save_status = status
    # volatile execution state the crash must destroy
    copy.waiting_on = WaitingOn({TxnId(1, 1, 1)})
    copy.listeners = {TxnId(1, 2, 1)}
    return copy


@pytest.mark.parametrize("status", list(SaveStatus), ids=lambda s: s.name)
def test_journal_restart_roundtrip_per_status(status):
    """`restart_commands` after a simulated crash, for every SaveStatus:
    volatile fields (waiting_on, listeners, transient sub-states) are
    dropped; durable fields survive byte-for-byte."""
    template = _applied_template()
    command = _clone_with_status(template, status)
    journal = Journal()
    store = SimpleNamespace(node=SimpleNamespace(id=7), id=0)
    journal.save(store, command)

    rebuilt = journal.restart_commands(7, 0)
    assert set(rebuilt) == {command.txn_id}
    copy = rebuilt[command.txn_id]
    assert copy.save_status is _EXPECTED_COLLAPSE.get(status, status)
    # never journaled: the restart path re-derives the execution frontier
    assert copy.waiting_on is None
    assert copy.listeners == set()
    for f in _FIELDS:
        if f == "save_status":
            continue
        assert codec.encode_value(getattr(copy, f)) \
            == codec.encode_value(getattr(command, f)), \
            f"{status.name}: durable field {f} did not survive byte-for-byte"


def test_journal_restart_roundtrip_after_burn():
    """After a whole benign burn, every store's journal rebuilds the full
    command set at the durable tier (the live burn's verify_against, but
    through the restart entry point)."""
    result = run_burn(11, ops=30, journal=True)
    assert result.ops_ok == 30
    from cassandra_accord_tpu.harness.burn import last_cluster
    cluster = last_cluster()
    checked = 0
    for node in cluster.nodes.values():
        for store in node.command_stores.all_stores():
            rebuilt = cluster.journal.restart_commands(node.id, store.id)
            for txn_id, cmd in store.commands.items():
                if cmd.save_status is SaveStatus.NOT_DEFINED:
                    continue
                copy = rebuilt[txn_id]
                assert copy.save_status is Journal._durable_status(cmd.save_status)
                assert copy.waiting_on is None
                checked += 1
    assert checked > 0


def test_journal_drop_tail_rewinds_latest_state():
    """Unsynced-tail loss: drop_tail removes the newest records and rewinds
    the latest-state snapshot to the surviving prefix."""
    template = _applied_template()
    journal = Journal()
    store = SimpleNamespace(node=SimpleNamespace(id=3), id=0)
    pre = _clone_with_status(template, SaveStatus.STABLE)
    journal.save(store, pre)
    post = _clone_with_status(template, SaveStatus.APPLIED)
    journal.save(store, post)
    assert journal.restart_commands(3, 0)[template.txn_id].save_status \
        is SaveStatus.APPLIED

    dropped = journal.drop_tail(3, 0, 1)
    assert dropped == 1
    assert journal.restart_commands(3, 0)[template.txn_id].save_status \
        is SaveStatus.STABLE
    # dropping the remaining record erases the txn entirely
    assert journal.drop_tail(3, 0, 5) == 1
    assert journal.restart_commands(3, 0) == {}


# ---------------------------------------------------------------------------
# Satellite 2: PendingQueue idle-accounting hardening
# ---------------------------------------------------------------------------

def _exact_live(queue):
    return sum(1 for e in queue._heap if not e.cancelled and not e.recurring)


def test_pending_queue_cancel_after_pop_is_noop():
    """The round-4 idle-accounting bug class: cancelling an entry that was
    already popped+executed must not double-decrement `_live_nonrecurring`."""
    q = PendingQueue()
    fired = []
    entry = q.add_after(10, lambda: fired.append(1))
    other = q.add_after(20, lambda: fired.append(2))
    assert q.has_nonrecurring()
    q.pop()()
    assert fired == [1]
    entry.cancel()          # already popped: must be a no-op
    entry.cancel()          # idempotent
    assert q._live_nonrecurring == _exact_live(q) == 1
    assert q.has_nonrecurring()
    other.cancel()
    assert q._live_nonrecurring == _exact_live(q) == 0
    assert not q.has_nonrecurring()
    other.cancel()          # cancel-after-cancel: also a no-op
    assert q._live_nonrecurring == 0


def test_pending_queue_counter_never_negative():
    """The invariant assertion fires on any double decrement instead of the
    queue silently claiming idle while real timeouts still pend."""
    q = PendingQueue()
    entry = q.add_after(5, lambda: None)
    entry.cancel()
    assert q._live_nonrecurring == 0
    # forcing a second decrement must trip the assertion, not go negative
    entry.cancelled = False
    entry.popped = False
    with pytest.raises(AssertionError):
        entry.cancel()


def test_pending_queue_exact_after_crash_teardown():
    """Cluster.crash cancels a node's timers/callbacks; the queue's live
    non-recurring accounting must stay exact (not pinned, not negative)."""
    cluster = make_cluster(seed=4)
    res = cluster.nodes[1].coordinate(list_txn([], {k(5): "a"}))
    # crash node 3 mid-flight with its timers/callbacks live
    cluster.run_until(lambda: len(cluster.queue) > 0)
    cluster.crash(3)
    assert cluster.queue._live_nonrecurring == _exact_live(cluster.queue)
    assert cluster.run_until(res.is_done, max_tasks=200_000)
    cluster.run_until_idle()
    assert cluster.queue._live_nonrecurring == _exact_live(cluster.queue)
    cluster.restart(3)
    cluster.run_until_idle()
    assert cluster.queue._live_nonrecurring == _exact_live(cluster.queue)


# ---------------------------------------------------------------------------
# Acceptance: a crashed coordinator's in-flight txn is settled by peers
# ---------------------------------------------------------------------------

class _HoldAfterPreAccept(LinkConfig):
    """Drops the coordinator's post-preaccept traffic (simulates dying with
    the decision not yet announced)."""

    def __init__(self, rng, coordinator):
        super().__init__(rng)
        self.coordinator = coordinator
        self.holding = True

    def action(self, from_node, to_node, message=None):
        if self.holding and from_node == self.coordinator \
                and type(message).__name__ in ("Accept", "Commit", "Apply"):
            return LinkConfig.DROP
        return LinkConfig.DELIVER


def test_crashed_coordinator_superseded_by_peer_recovery():
    """A node crashes while COORDINATING an in-flight txn (peers saw only
    PreAccept): the peers' progress logs must settle the txn to a terminal
    state — committed or invalidated — without the coordinator.  After the
    node restarts from its journal it converges to the same outcome."""
    link = _HoldAfterPreAccept(RandomSource(8), 1)
    cluster = make_cluster(seed=2, link=link)
    txn = list_txn([], {k(5): "orphan"})
    cluster.nodes[1].coordinate(txn)

    def witnessed_at_peers():
        return any(store.commands
                   for store in cluster.nodes[2].command_stores.all_stores())
    assert cluster.run_until(witnessed_at_peers, max_tasks=100_000)
    txn_id = next(iter(
        cluster.nodes[2].command_stores.all_stores()[0].commands))
    cluster.crash(1)
    link.holding = False   # the drops modeled the dead coordinator

    def settled_at_peers():
        return all(
            find_command(cluster, n, txn_id) is not None
            and find_command(cluster, n, txn_id).save_status.is_terminal
            for n in (2, 3))
    cluster.run_for(90)
    assert settled_at_peers(), \
        f"peers never settled the orphan: " \
        f"{[find_command(cluster, n, txn_id).save_status for n in (2, 3)]}"
    statuses = {find_command(cluster, n, txn_id).save_status for n in (2, 3)}
    assert statuses <= {SaveStatus.APPLIED, SaveStatus.INVALIDATED,
                        SaveStatus.TRUNCATED_APPLY, SaveStatus.ERASED}

    # the restarted coordinator replays its journal and converges
    cluster.restart(1)
    cluster.run_for(60)
    datas = {n: cluster.stores[n].get(k(5)) for n in cluster.nodes}
    assert len(set(datas.values())) == 1, f"divergent after restart: {datas}"


def test_restarted_replica_catches_up_through_deps():
    """A replica that was down while writes committed rebuilds from its
    journal and catches up through the dependency chain of later txns."""
    cluster = make_cluster(seed=3)
    for value, down in (("a", False), ("b", True), ("c", False)):
        if value == "b":
            cluster.crash(3)
        elif value == "c":
            cluster.restart(3)
        res = cluster.nodes[1].coordinate(list_txn([], {k(5): value}))
        assert cluster.run_until(res.is_done, max_tasks=500_000), value
        assert res.is_success(), res.failure
    cluster.run_for(60)
    assert cluster.stores[3].get(k(5)) == ("a", "b", "c")
    for n in (1, 2):
        assert cluster.stores[n].get(k(5)) == ("a", "b", "c")


# ---------------------------------------------------------------------------
# Stall watchdog: wait-graph dump names the blocked txn ids
# ---------------------------------------------------------------------------

class _DropApplyTo(LinkConfig):
    def __init__(self, rng, victim):
        super().__init__(rng)
        self.victim = victim

    def action(self, from_node, to_node, message=None):
        if to_node == self.victim and type(message).__name__ == "Apply":
            return LinkConfig.DROP
        return LinkConfig.DELIVER


def _stalled_cluster():
    """Deterministic stall fixture: txn A's Apply never reaches node 3, so a
    later same-key txn B sits PRE_APPLIED on node 3 waiting on A forever
    (progress log disabled: nothing heals it)."""
    shards = [Shard(Range(k(0), k(1000)), [1, 2, 3])]
    cluster = Cluster(Topology(1, shards), seed=6,
                      link_config=_DropApplyTo(RandomSource(13), 3),
                      journal=True, progress_log=False)
    ra = cluster.nodes[1].coordinate(list_txn([], {k(7): "first"}))
    assert cluster.run_until(ra.is_done)
    rb = cluster.nodes[1].coordinate(list_txn([], {k(7): "second"}))
    assert cluster.run_until(rb.is_done)
    cluster.run_until_idle()
    blocked = [
        (txn_id, cmd)
        for store in cluster.nodes[3].command_stores.all_stores()
        for txn_id, cmd in store.commands.items()
        if cmd.waiting_on is not None and cmd.waiting_on.is_waiting()]
    assert blocked, "fixture failed to produce a blocked txn on node 3"
    return cluster, blocked


def test_wait_state_dump_names_blocked_txns():
    cluster, blocked = _stalled_cluster()
    dump = dump_wait_state(cluster)
    assert "BLOCKED" in dump
    for txn_id, cmd in blocked:
        assert str(txn_id) in dump, f"dump does not name blocked {txn_id}"
        for dep in cmd.waiting_on.waiting:
            assert str(dep) in dump, f"dump does not name dependency {dep}"
    # the per-node status frontier is part of the report
    assert "frontier=" in dump and "node 3" in dump


def test_stall_watchdog_fires_with_dump():
    """On a deliberately-induced stall the watchdog raises StallError whose
    dump carries the wait graph (the artifact CI gets instead of a bare
    `timeout` kill)."""
    cluster, blocked = _stalled_cluster()
    watchdog = StallWatchdog(cluster, lambda: 0,
                             stalled_after_s=5.0, interval_s=1.0)
    watchdog.attach()
    with pytest.raises(StallError) as exc:
        cluster.run_for(30)
    assert str(blocked[0][0]) in exc.value.dump
    assert "no progress for" in str(exc.value)


def test_stall_watchdog_quiet_while_progressing():
    """A moving progress counter never trips the watchdog."""
    cluster = make_cluster(seed=5)
    ticks = []
    cluster.scheduler.recurring(1.0, lambda: ticks.append(1))
    watchdog = StallWatchdog(cluster, lambda: len(ticks),
                             stalled_after_s=3.0, interval_s=0.5)
    watchdog.attach()
    cluster.run_for(30)   # must not raise
    watchdog.cancel()


def test_burn_cli_stall_exits_nonzero(monkeypatch, capsys):
    """The burn CLI turns a watchdog stall into exit code 2 + the wait-graph
    dump on stdout — CI artifacts instead of an external timeout kill."""
    from cassandra_accord_tpu.harness import burn as burn_mod

    def fake_run_burn(seed, **kw):
        raise SimulationException(
            seed, StallError("no progress for 120.0s of sim-time",
                             "node 1 store 0: frontier={}\n"
                             "  BLOCKED [1,42,1]Wk [STABLE] waiting_on=[[1,7,2]Wk]"))
    monkeypatch.setattr(burn_mod, "run_burn", fake_run_burn)
    with pytest.raises(SystemExit) as exc:
        burn_mod.main(["--seeds", "0", "--ops", "5"])
    assert exc.value.code == 2
    out = capsys.readouterr().out
    assert "STALL" in out and "BLOCKED [1,42,1]Wk" in out


# ---------------------------------------------------------------------------
# Satellite 6: tier-1 restart smoke + the gated restart x hostile matrix
# ---------------------------------------------------------------------------

def test_restart_smoke_burn():
    """Fast tier-1 smoke: a benign-network burn with the crash-restart
    nemesis actually crashing and rebuilding nodes (>=1 full cycle), every
    op resolving and the final states agreeing."""
    cfg = restart_config(restart_interval_s=0.3, restart_downtime_min_s=0.2,
                         restart_downtime_max_s=0.5)
    result = run_burn(3, ops=40, concurrency=8, journal=True,
                      restart_nodes=True, node_config=cfg,
                      max_tasks=5_000_000)
    assert result.resolved == 40
    assert result.ops_failed == 0
    assert result.restarts >= 1, \
        f"nemesis never completed a crash-restart cycle: {result!r}"
    assert result.crashes == result.restarts


def test_restart_burn_is_deterministic():
    """Same seed, same crash schedule, same outcome (the nemesis draws from
    the seeded rng tree like every other fault axis)."""
    cfg = restart_config(restart_interval_s=0.3, restart_downtime_min_s=0.2,
                         restart_downtime_max_s=0.5)
    kw = dict(ops=40, concurrency=8, journal=True, restart_nodes=True,
              node_config=cfg, max_tasks=5_000_000)
    a = run_burn(3, **kw)
    b = run_burn(3, **kw)
    assert (a.ops_ok, a.ops_recovered, a.ops_nacked, a.ops_lost, a.crashes,
            a.restarts, a.sim_micros) \
        == (b.ops_ok, b.ops_recovered, b.ops_nacked, b.ops_lost, b.crashes,
            b.restarts, b.sim_micros)


def test_restart_with_chaos_burn():
    """One hostile-network seed with restarts in tier-1 (the full matrix is
    gated behind ACCORD_LONG_BURNS): crash-restart under message loss,
    recovery resolving orphaned client ops."""
    cfg = restart_config(restart_interval_s=3.0, restart_downtime_min_s=1.0,
                         restart_downtime_max_s=3.0)
    result = run_burn(1, ops=60, concurrency=10, chaos=True,
                      allow_failures=True, durability=True, journal=True,
                      restart_nodes=True, node_config=cfg,
                      max_tasks=20_000_000)
    assert result.resolved == 60
    assert result.restarts >= 1


@pytest.mark.skipif("ACCORD_LONG_BURNS" not in os.environ,
                    reason="seed-range restart x hostile matrix; run with ACCORD_LONG_BURNS=1")
def test_restart_hostile_matrix_seed_range():
    """ISSUE 1 acceptance: >=8 seeds x 200 ops with crash-restart alongside
    the full hostile matrix (chaos + churn + durability + truncation + clock
    drift + delayed stores + cache-miss + journal faults), averaging >=1
    restart per seed, no divergence, no stalls."""
    cfg = restart_config(restart_interval_s=5.0)
    total_restarts = 0
    # no seed carve-outs: the seed-6 range-read vs bootstrap-refencing
    # wedge is FIXED (round 9 — grandfathered coverage + MVCC read-dep rule
    # + re-fencing backoff)
    for seed in (0, 1, 2, 3, 4, 5, 6, 7, 8):
        rf = 2 + RandomSource(seed).next_int(8)
        result = run_burn(seed, ops=200, concurrency=20, rf=rf, chaos=True,
                          allow_failures=True, topology_churn=True,
                          durability=True, journal=True, delayed_stores=True,
                          clock_drift=True, cache_miss=True,
                          restart_nodes=True, node_config=cfg,
                          stall_watchdog_s=300.0, max_tasks=200_000_000)
        assert result.resolved == 200, result
        total_restarts += result.restarts
    assert total_restarts >= 8, \
        f"averaged <1 restart/seed across the range: {total_restarts}"


# ---------------------------------------------------------------------------
# Frontier-parity: FIXED round 12 (the round-6 open repro is now the tier-1
# regression test tests/test_frontier_exec.py::
# test_frontier_exec_full_hostile_matrix_parity; the seed-range promotion
# matrix lives beside it behind ACCORD_LONG_BURNS).  Root cause: terminal
# SaveStatus transitions never reached the device mirror when cfk refused the
# witness update (demoted-cold/pruned entries, churn-dropped keys) or when
# truncation/GC-erase bypassed register_witness entirely — the stale
# mirror-STABLE slot then sat in the kernel frontier as ready forever.
# Fixed by resolver.note_terminal at the _observe_transition choke point.
# ---------------------------------------------------------------------------
